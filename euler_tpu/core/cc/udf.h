// Registerable value-UDF framework (capability parity with the
// reference's euler/core/framework/udf.h:33-68: named UDFs resolved by
// the feature op, with a process-wide cache; built-ins
// min/max/mean like min_udf.cc / max_udf.cc / mean_udf.cc).
//
// Redesign for the TPU build: a UDF is a std::function transforming one
// ragged float column in place (offsets + values), optionally
// parameterized — the GQL attr "udf:name:p1:p2" carries numeric params
// (the reference's ParamsVec). The registry accepts C-ABI callbacks so
// Python can register custom UDFs through ctypes without recompiling.
#ifndef EULER_TPU_UDF_H_
#define EULER_TPU_UDF_H_

#include <algorithm>
#include <cstdint>
#include <functional>
#include <list>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"

namespace et {

// Transforms a ragged float column in place. offs has n_rows+1 entries;
// vals has offs.back() entries. Implementations may change both row
// lengths and values, but must keep offs/vals consistent.
using ValueUdf = std::function<Status(const std::vector<double>& params,
                                      std::vector<uint64_t>* offs,
                                      std::vector<float>* vals)>;

class UdfRegistry {
 public:
  static UdfRegistry& Instance();

  // Last registration wins (lets tests/users override built-ins).
  void Register(const std::string& name, ValueUdf fn);
  // Returns a COPY under the lock (a pointer into the map would race
  // with concurrent re-registration); empty function when unknown.
  // When generation is non-null it receives the registry generation
  // ATOMICALLY with the lookup — cache keys must use this value, not a
  // later Generation() read, or a concurrent re-registration could
  // cache the OLD function's result under the NEW generation.
  ValueUdf Find(const std::string& name,
                uint64_t* generation = nullptr) const;
  std::vector<std::string> Names() const;
  // Bumped on every Register(). Part of the result-cache key, so
  // re-registering a UDF (new behavior under an old name) implicitly
  // invalidates every cached result.
  uint64_t Generation() const;

 private:
  mutable std::mutex mu_;
  uint64_t generation_ = 0;
  std::unordered_map<std::string, ValueUdf> fns_;
};

// Parse "udf:name:p1:p2" (after the "udf:" prefix) → (name, params).
Status ParseUdfSpec(const std::string& spec, std::string* name,
                    std::vector<double>* params);

// One cached UDF-transformed column, with its FULL key stored alongside
// the result so a 64-bit hash collision verifies as a miss instead of
// serving another query's data. Immutable once published (shared_ptr
// handed out under the lock; readers never copy the vectors).
struct CachedColumn {
  uint64_t graph_uid = 0;
  uint64_t generation = 0;      // UdfRegistry generation at compute time
  std::string spec;             // full "udf:name:p1:p2" attr
  int fid = 0;
  std::vector<uint64_t> ids;    // the queried ids (key verification)
  std::vector<uint64_t> offs;   // the transformed ragged column
  std::vector<float> vals;

  bool KeyEquals(uint64_t uid, uint64_t gen, const std::string& s, int f,
                 const uint64_t* q_ids, size_t n) const {
    return graph_uid == uid && generation == gen && fid == f && spec == s &&
           ids.size() == n &&
           std::equal(ids.begin(), ids.end(), q_ids);
  }
};

// Result cache for UDF-transformed feature columns (reference UdfCache,
// euler/core/framework/udf.h:33-68 — there it caches Udf instances to
// skip re-construction; here the expensive repeated work is the
// transform itself, so the cache holds the transformed ragged columns).
//
// Invalidation story: finalized graphs are IMMUTABLE, and the key
// includes the graph's process-unique uid (Graph::uid), the UdfRegistry
// generation (bumped by every Register(), so re-registering a UDF
// orphans old entries), the full udf spec (name + params), the feature
// id, and the queried ids — so an entry can never go stale; it can only
// be evicted. Eviction is size-bounded LRU (default 64MB,
// SetCapacityBytes to change; capacity 0 disables caching). Clear()
// drops everything (tests / memory pressure).
//
// Purity contract: cached UDFs must be pure functions of
// (params, offsets, values) — see register_udf's documentation; a
// deliberately stateful UDF should disable the cache (capacity 0).
class UdfResultCache {
 public:
  static UdfResultCache& Instance();

  // Hit → the cached column (full-key verified); miss/collision →
  // nullptr. Counts hits/misses. The returned column is immutable and
  // safe to read without the lock.
  std::shared_ptr<const CachedColumn> Get(uint64_t key, uint64_t graph_uid,
                                          uint64_t generation,
                                          const std::string& spec, int fid,
                                          const uint64_t* ids, size_t n);
  void Put(uint64_t key, std::shared_ptr<const CachedColumn> col);
  void Clear();
  void Stats(uint64_t* hits, uint64_t* misses, uint64_t* entries,
             uint64_t* bytes) const;
  void SetCapacityBytes(size_t cap);

  // Epoch-bump invalidation (streaming deltas): a delta apply swaps in
  // a NEW Graph snapshot (new uid), so entries keyed on the old uid can
  // never be served again — drop exactly those (entries for other
  // graphs are retained) and count them, instead of letting dead
  // entries squat in the LRU until capacity pressure. Returns the
  // number evicted; the cumulative count is EpochEvictions()
  // (udf_cache_epoch_evictions_total on the Python obs registry).
  size_t EvictGraph(uint64_t graph_uid);
  uint64_t EpochEvictions() const;

 private:
  struct Entry {
    std::shared_ptr<const CachedColumn> col;
    std::list<uint64_t>::iterator lru_it;
  };
  static size_t EntryBytes(const Entry& e) {
    return (e.col->offs.size() + e.col->ids.size()) * sizeof(uint64_t) +
           e.col->vals.size() * sizeof(float) + e.col->spec.size();
  }
  mutable std::mutex mu_;
  std::list<uint64_t> lru_;  // front = most recent
  std::unordered_map<uint64_t, Entry> map_;
  size_t bytes_ = 0;
  size_t cap_bytes_ = 64u << 20;
  uint64_t hits_ = 0, misses_ = 0;
  uint64_t epoch_evictions_ = 0;
};

// FNV-1a over (graph uid, registry generation, udf spec, fid, ids),
// each component length-prefixed so concatenations cannot alias. The
// hash only buckets — CachedColumn::KeyEquals decides a true hit.
uint64_t UdfCacheKey(uint64_t graph_uid, uint64_t generation,
                     const std::string& spec, int fid, const uint64_t* ids,
                     size_t n);

}  // namespace et

#endif  // EULER_TPU_UDF_H_
