// Registerable value-UDF framework (capability parity with the
// reference's euler/core/framework/udf.h:33-68: named UDFs resolved by
// the feature op, with a process-wide cache; built-ins
// min/max/mean like min_udf.cc / max_udf.cc / mean_udf.cc).
//
// Redesign for the TPU build: a UDF is a std::function transforming one
// ragged float column in place (offsets + values), optionally
// parameterized — the GQL attr "udf:name:p1:p2" carries numeric params
// (the reference's ParamsVec). The registry accepts C-ABI callbacks so
// Python can register custom UDFs through ctypes without recompiling.
#ifndef EULER_TPU_UDF_H_
#define EULER_TPU_UDF_H_

#include <cstdint>
#include <functional>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"

namespace et {

// Transforms a ragged float column in place. offs has n_rows+1 entries;
// vals has offs.back() entries. Implementations may change both row
// lengths and values, but must keep offs/vals consistent.
using ValueUdf = std::function<Status(const std::vector<double>& params,
                                      std::vector<uint64_t>* offs,
                                      std::vector<float>* vals)>;

class UdfRegistry {
 public:
  static UdfRegistry& Instance();

  // Last registration wins (lets tests/users override built-ins).
  void Register(const std::string& name, ValueUdf fn);
  // Returns a COPY under the lock (a pointer into the map would race
  // with concurrent re-registration); empty function when unknown.
  ValueUdf Find(const std::string& name) const;
  std::vector<std::string> Names() const;

 private:
  mutable std::mutex mu_;
  std::unordered_map<std::string, ValueUdf> fns_;
};

// Parse "udf:name:p1:p2" (after the "udf:" prefix) → (name, params).
Status ParseUdfSpec(const std::string& spec, std::string* name,
                    std::vector<double>* params);

}  // namespace et

#endif  // EULER_TPU_UDF_H_
