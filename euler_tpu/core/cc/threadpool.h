// Fixed-size work-stealing-free thread pool.
//
// Capability parity with the reference's euler/common/env.h:39 ThreadPool
// (Schedule(fn) onto N posix threads). Redesigned as a single
// mutex+condvar task queue — the executor schedules coarse batch kernels
// (thousands of rows each), so queue contention is negligible and
// simplicity wins.
#ifndef EULER_TPU_THREADPOOL_H_
#define EULER_TPU_THREADPOOL_H_

#include <condition_variable>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace et {

class ThreadPool {
 public:
  // Dispatch lanes. kHigh is the default and serves the user read path
  // (query execution); kLow carries maintenance traffic — delta
  // applies, anti-entropy catch-up, snapshot compaction — so a burst
  // of background work can never queue ahead of a user read. Weak
  // priority, not strict: worker 0 prefers the LOW lane while every
  // other worker prefers HIGH, so neither lane can be starved forever
  // by a saturating flood of the other (the executor's "tasks must not
  // block on same-pool tasks" invariant needs every lane to make
  // progress).
  enum Lane { kHigh = 0, kLow = 1 };

  explicit ThreadPool(size_t num_threads);
  ~ThreadPool();

  ThreadPool(const ThreadPool&) = delete;
  ThreadPool& operator=(const ThreadPool&) = delete;

  // Enqueue fn for execution on some pool thread. Never blocks.
  void Schedule(std::function<void()> fn, Lane lane = kHigh);

  size_t num_threads() const { return threads_.size(); }

 private:
  void WorkerLoop(size_t worker_idx);

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::function<void()>> queue_;      // kHigh lane
  std::deque<std::function<void()>> low_queue_;  // kLow lane
  bool shutdown_ = false;
  std::vector<std::thread> threads_;
};

// Process-wide shared pool for query execution (lazily constructed,
// hardware_concurrency threads).
// Invariant: tasks on this pool must never block on other tasks of the
// same pool (the executor relies on it — a blocked compute thread can
// starve the DAG and deadlock). Blocking RPC I/O goes on ClientThreadPool.
ThreadPool* GlobalThreadPool();

// Partition [0, n) into chunks of >= grain and run fn(begin, end, chunk)
// on the pool, blocking the CALLER until all chunks finish. For use from
// host entry points (ctypes C API) only — never from a kernel running on
// GlobalThreadPool itself (a pool task blocking on pool tasks can
// deadlock; see the invariant above).
void ParallelFor(ThreadPool* pool, int64_t n, int64_t grain,
                 const std::function<void(int64_t, int64_t, int)>& fn);

// Dedicated pool for blocking client RPC calls (socket send/recv while a
// remote shard executes). Kept separate from GlobalThreadPool so in-flight
// remote calls can never starve local kernel execution — in single-process
// multi-shard setups both sides share GlobalThreadPool and mixing them
// deadlocks once every thread is parked in a blocking call.
ThreadPool* ClientThreadPool();

}  // namespace et

#endif  // EULER_TPU_THREADPOOL_H_
