#include "gql.h"

#include <algorithm>
#include <cctype>
#include <cstdlib>
#include <sstream>
#include <unordered_set>

namespace et {

// ---------------------------------------------------------------------------
// Lexer + parser
// ---------------------------------------------------------------------------
// Tokens: '.', '(', ')', ',' and words (identifiers/numbers/'*'/':'-lists).
// A chain is call ('.' call)*; call is name '(' arg (',' arg)* ')'; an arg
// is one or more whitespace-separated words (conditions keep their and/or
// words: has(price gt 3 and label eq A)).
Status ParseGql(const std::string& q, std::vector<GqlCall>* calls) {
  calls->clear();
  size_t i = 0;
  auto skip_ws = [&] {
    while (i < q.size() && std::isspace(static_cast<unsigned char>(q[i]))) ++i;
  };
  auto word = [&]() -> std::string {
    size_t b = i;
    while (i < q.size() && (std::isalnum(static_cast<unsigned char>(q[i])) ||
                            q[i] == '_' || q[i] == '*' || q[i] == ':' ||
                            q[i] == '-' || q[i] == '+'))
      ++i;
    return q.substr(b, i - b);
  };
  skip_ws();
  while (i < q.size()) {
    GqlCall call;
    skip_ws();
    call.name = word();
    if (call.name.empty())
      return Status::InvalidArgument("expected call name at pos " +
                                     std::to_string(i) + " in: " + q);
    skip_ws();
    if (i >= q.size() || q[i] != '(')
      return Status::InvalidArgument("expected ( after " + call.name);
    ++i;  // consume (
    std::vector<std::string> arg;
    for (;;) {
      skip_ws();
      if (i >= q.size())
        return Status::InvalidArgument("unterminated ( in: " + q);
      if (q[i] == ')') {
        ++i;
        if (!arg.empty()) call.args.push_back(std::move(arg));
        break;
      }
      if (q[i] == ',') {
        ++i;
        if (arg.empty())
          return Status::InvalidArgument("empty argument in " + call.name);
        call.args.push_back(std::move(arg));
        arg.clear();
        continue;
      }
      std::string w = word();
      if (w.empty())
        return Status::InvalidArgument("bad character '" +
                                       std::string(1, q[i]) + "' in: " + q);
      arg.push_back(std::move(w));
    }
    calls->push_back(std::move(call));
    skip_ws();
    if (i < q.size()) {
      if (q[i] != '.')
        return Status::InvalidArgument("expected . between calls in: " + q);
      ++i;
    }
  }
  if (calls->empty()) return Status::InvalidArgument("empty query");
  return Status::OK();
}

// ---------------------------------------------------------------------------
// Translator
// ---------------------------------------------------------------------------
namespace {

std::string JoinWords(const std::vector<std::string>& ws) {
  std::string out;
  for (size_t i = 0; i < ws.size(); ++i) {
    if (i) out += " ";
    out += ws[i];
  }
  return out;
}

// Words like {price, gt, 3, and, a, eq, b, or, x, lt, 2} → DNF
// {{"price gt 3","a eq b"},{"x lt 2"}}.
Status WordsToDnf(const std::vector<std::string>& ws,
                  std::vector<std::vector<std::string>>* dnf) {
  std::vector<std::vector<std::string>> disj;
  std::vector<std::string> conj;
  std::vector<std::string> term;
  auto flush_term = [&]() -> Status {
    if (term.size() != 3)
      return Status::InvalidArgument("condition term must be 'attr op value'"
                                     ", got: " + JoinWords(term));
    conj.push_back(term[0] + " " + term[1] + " " + term[2]);
    term.clear();
    return Status::OK();
  };
  for (const auto& w : ws) {
    if (w == "and") {
      ET_RETURN_IF_ERROR(flush_term());
    } else if (w == "or") {
      ET_RETURN_IF_ERROR(flush_term());
      disj.push_back(std::move(conj));
      conj.clear();
    } else {
      term.push_back(w);
    }
  }
  ET_RETURN_IF_ERROR(flush_term());
  disj.push_back(std::move(conj));
  *dnf = std::move(disj);
  return Status::OK();
}

// AND-combine two DNFs (cross product of conjunctions).
std::vector<std::vector<std::string>> AndDnf(
    const std::vector<std::vector<std::string>>& a,
    const std::vector<std::vector<std::string>>& b) {
  if (a.empty()) return b;
  if (b.empty()) return a;
  std::vector<std::vector<std::string>> out;
  for (const auto& ca : a)
    for (const auto& cb : b) {
      std::vector<std::string> c = ca;
      c.insert(c.end(), cb.begin(), cb.end());
      out.push_back(std::move(c));
    }
  return out;
}

struct TransState {
  DAGDef* dag;
  // current node-id set tensor (empty if none)
  std::string cur_ids;
  // current edge triple (src, dst, type) tensor names (empty if none)
  std::vector<std::string> cur_edge;
  // current whole-graph label set tensor (empty if none)
  std::string cur_labels;
  // last emitted node + its output tensor names
  std::string last_node;
  std::vector<std::string> last_outputs;
  // last ragged quad outputs (idx, ids, w, t) for post-process/filter
  std::vector<std::string> last_quad;

  NodeDef* Emit(const std::string& op, std::vector<std::string> inputs,
                std::vector<std::string> attrs, int n_outputs) {
    NodeDef n;
    n.name = dag->UniqueName(op);
    n.op = op;
    n.inputs = std::move(inputs);
    n.attrs = std::move(attrs);
    dag->nodes.push_back(std::move(n));
    NodeDef* p = &dag->nodes.back();
    last_node = p->name;
    last_outputs.clear();
    for (int i = 0; i < n_outputs; ++i)
      last_outputs.push_back(p->OutName(i));
    return p;
  }

  // The semantic producer of the current results: AS nodes are
  // transparent aliases, so orderBy/limit/has after as() must look
  // through them to the op that made the data.
  NodeDef* Producer() {
    if (last_node.empty()) return nullptr;
    NodeDef* t = dag->Find(last_node);
    while (t != nullptr && t->op == "AS" && !t->inputs.empty()) {
      const std::string& in = t->inputs[0];
      auto colon = in.rfind(':');
      t = dag->Find(colon == std::string::npos ? in : in.substr(0, colon));
    }
    return t;
  }
};

}  // namespace

Status TranslateGql(const std::vector<GqlCall>& calls, TranslateResult* out) {
  out->dag = DAGDef();
  out->aliases.clear();
  TransState st;
  st.dag = &out->dag;

  for (size_t ci = 0; ci < calls.size(); ++ci) {
    const GqlCall& c = calls[ci];
    auto arg = [&](size_t i) -> std::string {
      return i < c.args.size() ? JoinWords(c.args[i]) : std::string();
    };
    auto argw = [&](size_t i, const std::string& dflt) {
      std::string v = arg(i);
      return v.empty() ? dflt : v;
    };

    if (c.name == "v") {
      // v(roots) — external u64 id input
      if (c.args.empty())
        return Status::InvalidArgument("v() needs an input tensor name");
      st.cur_ids = arg(0);
      st.cur_edge.clear();
      st.last_node.clear();
      st.last_outputs = {st.cur_ids};
      st.last_quad.clear();
    } else if (c.name == "e") {
      // e(batch) — external (batch:0, batch:1, batch:2) = src, dst, type
      if (c.args.empty())
        return Status::InvalidArgument("e() needs an input tensor name");
      std::string b = arg(0);
      st.cur_edge = {b + ":0", b + ":1", b + ":2"};
      st.cur_ids.clear();
      st.last_node.clear();
      st.last_outputs = st.cur_edge;
      st.last_quad.clear();
    } else if (c.name == "sampleN") {
      // sampleN(type, count)
      st.Emit("API_SAMPLE_NODE", {},
              {argw(1, "0"), argw(0, "-1")}, 1);
      st.cur_ids = st.last_outputs[0];
      st.cur_edge.clear();
      st.last_quad.clear();
    } else if (c.name == "sampleE") {
      NodeDef* n = st.Emit("API_SAMPLE_EDGE", {},
                           {argw(1, "0"), argw(0, "-1")}, 3);
      st.cur_edge = {n->OutName(0), n->OutName(1), n->OutName(2)};
      st.cur_ids.clear();
      st.last_quad.clear();
    } else if (c.name == "sampleNWithTypes") {
      if (c.args.empty())
        return Status::InvalidArgument("sampleNWithTypes needs a types input");
      st.Emit("API_SAMPLE_N_WITH_TYPES", {arg(0)}, {}, 1);
      st.cur_ids = st.last_outputs[0];
      st.cur_edge.clear();
      st.last_quad.clear();
    } else if (c.name == "sampleGL") {
      // sampleGL(count) — whole-graph labels (graph classification roots)
      st.Emit("API_SAMPLE_GRAPH_LABEL", {}, {argw(0, "1")}, 1);
      st.cur_labels = st.last_outputs[0];
      st.cur_ids.clear();
      st.cur_edge.clear();
      st.last_quad.clear();
    } else if (c.name == "graphNodes") {
      // graphNodes() — nodes of each labeled graph; needs a label set
      // (sampleGL or gl(input)). out: pos, idx, node ids.
      if (st.cur_labels.empty())
        return Status::InvalidArgument("graphNodes without a label set");
      st.Emit("API_GET_GRAPH_BY_LABEL", {st.cur_labels}, {"all"}, 3);
      st.cur_ids = st.last_outputs[2];
      st.cur_labels.clear();
      st.last_quad.clear();
    } else if (c.name == "gl") {
      // gl(labels) — bind an input tensor as the current label set
      if (c.args.empty())
        return Status::InvalidArgument("gl needs a labels input");
      st.cur_labels = arg(0);
      st.cur_ids.clear();
      st.cur_edge.clear();
      st.last_quad.clear();
    } else if (c.name == "sampleNB") {
      // sampleNB(edge_types, count, default_id)
      if (st.cur_ids.empty())
        return Status::InvalidArgument("sampleNB without a node set");
      st.Emit("API_SAMPLE_NB", {st.cur_ids},
              {argw(0, "*"), argw(1, "1"), argw(2, "0")}, 4);
      st.last_quad = st.last_outputs;
      st.cur_ids = st.last_outputs[1];
      st.cur_edge.clear();
    } else if (c.name == "sampleLNB") {
      // sampleLNB(edge_types, layer_sizes m0:m1:..., default_id
      //           [, weight_func]) — weight_func "sqrt" dampens the
      // accumulated candidate mass (reference GeneralSampleLayer,
      // local_sample_layer_op.cc:94); default identity.
      if (st.cur_ids.empty())
        return Status::InvalidArgument("sampleLNB without a node set");
      std::string sizes = argw(1, "1");
      int n_layers = 1 + static_cast<int>(std::count(sizes.begin(),
                                                     sizes.end(), ':'));
      std::string wf = argw(3, "");
      if (!wf.empty() && wf != "sqrt")
        return Status::InvalidArgument(
            "sampleLNB weight_func must be 'sqrt' (or omitted), got " + wf);
      st.Emit("API_SAMPLE_L", {st.cur_ids},
              {argw(0, "*"), sizes, argw(2, "0"), wf}, n_layers);
      st.cur_ids = st.last_outputs.back();
      st.last_quad.clear();
      st.cur_edge.clear();
    } else if (c.name == "outV" || c.name == "getNB") {
      if (st.cur_ids.empty())
        return Status::InvalidArgument(c.name + " without a node set");
      st.Emit("API_GET_NB_NODE", {st.cur_ids}, {argw(0, "*")}, 4);
      st.last_quad = st.last_outputs;
      st.cur_ids = st.last_outputs[1];
      st.cur_edge.clear();
    } else if (c.name == "getSortedNB") {
      if (st.cur_ids.empty())
        return Status::InvalidArgument("getSortedNB without a node set");
      st.Emit("API_GET_SORTED_NB_NODE", {st.cur_ids}, {argw(0, "*")}, 4);
      st.last_quad = st.last_outputs;
      st.cur_ids = st.last_outputs[1];
      st.cur_edge.clear();
    } else if (c.name == "inV" || c.name == "getRNB") {
      if (st.cur_ids.empty())
        return Status::InvalidArgument(c.name + " without a node set");
      st.Emit("API_GET_RNB_NODE", {st.cur_ids}, {argw(0, "*")}, 4);
      st.last_quad = st.last_outputs;
      st.cur_ids = st.last_outputs[1];
      st.cur_edge.clear();
    } else if (c.name == "getTopKNB") {
      if (st.cur_ids.empty())
        return Status::InvalidArgument("getTopKNB without a node set");
      st.Emit("API_GET_TOPK_NB", {st.cur_ids},
              {argw(0, "*"), argw(1, "1")}, 4);
      st.last_quad = st.last_outputs;
      st.cur_ids = st.last_outputs[1];
      st.cur_edge.clear();
    } else if (c.name == "outE" || c.name == "getNBEdge") {
      // outE(edge_types) — the *edges* to each root's out-neighbors
      // (reference gremlin.l:21 out_e → API_GET_NB_EDGE). Leaves the
      // edge triple current so values() chains edge features, and the
      // neighbor ids current so traversal can continue.
      if (st.cur_ids.empty())
        return Status::InvalidArgument(c.name + " without a node set");
      NodeDef* n = st.Emit("API_GET_NB_EDGE", {st.cur_ids},
                           {argw(0, "*")}, 5);
      st.cur_edge = {n->OutName(1), n->OutName(2), n->OutName(3)};
      st.cur_ids = n->OutName(2);
      st.last_quad.clear();
    } else if (c.name == "values" || c.name == "udf") {
      std::vector<std::string> attrs;
      size_t a0 = 0;
      if (c.name == "udf") {
        attrs.push_back("udf:" + arg(0));
        a0 = 1;
      }
      for (size_t i = a0; i < c.args.size(); ++i) attrs.push_back(arg(i));
      int nf = static_cast<int>(attrs.size() - a0);
      if (!st.cur_edge.empty()) {
        st.Emit("API_GET_EDGE_P", st.cur_edge, attrs, 2 * nf);
      } else if (!st.cur_ids.empty()) {
        st.Emit("API_GET_P", {st.cur_ids}, attrs, 2 * nf);
      } else {
        return Status::InvalidArgument("values() without a node/edge set");
      }
      st.last_quad.clear();
    } else if (c.name == "label") {
      if (st.cur_ids.empty())
        return Status::InvalidArgument("label() without a node set");
      st.Emit("API_GET_NODE_T", {st.cur_ids}, {}, 1);
      st.last_quad.clear();
    } else if (c.name == "has" || c.name == "hasLabel" ||
               c.name == "hasKey" || c.name == "hasId") {
      std::vector<std::vector<std::string>> dnf;
      if (c.name == "has") {
        if (c.args.empty())
          return Status::InvalidArgument("empty has()");
        // args joined by commas are AND-ed conjunctions
        std::vector<std::string> words;
        for (size_t i = 0; i < c.args.size(); ++i) {
          if (i) words.push_back("and");
          words.insert(words.end(), c.args[i].begin(), c.args[i].end());
        }
        ET_RETURN_IF_ERROR(WordsToDnf(words, &dnf));
      } else if (c.name == "hasLabel") {
        dnf = {{"node_type eq " + arg(0)}};
      } else if (c.name == "hasKey") {
        dnf = {{arg(0) + " hk _"}};
      } else {  // hasId(x) — membership in an id list "a:b:c"
        dnf = {{"id in " + arg(0)}};
      }
      // Attach to the producing node (condition pushdown): sampling roots
      // take the dnf directly; a bare v() input gets an API_GET_NODE
      // filter; a quad gets API_GET_NB_FILTER on the neighbors. The
      // lookup is deliberately NOT through as(): an earlier alias must
      // keep its unfiltered data, so after as() the fallback paths
      // (NB_FILTER / GET_NODE) apply a separate filter node instead.
      NodeDef* target =
          st.last_node.empty() ? nullptr : st.dag->Find(st.last_node);
      if (target != nullptr && target->op == "API_GET_NB_EDGE" &&
          !target->post_process.empty()) {
        // the kernel filters before sort/limit; a has() written after
        // orderBy/limit would silently run in the wrong order
        return Status::InvalidArgument(
            "outE: put has() before orderBy()/limit()");
      }
      if (target != nullptr && (target->op == "API_SAMPLE_NODE" ||
                                target->op == "API_GET_NODE" ||
                                target->op == "API_GET_NB_EDGE")) {
        target->dnf = AndDnf(target->dnf, dnf);
      } else if (!st.last_quad.empty()) {
        std::vector<std::string> quad = st.last_quad;
        NodeDef* f = st.Emit("API_GET_NB_FILTER", quad, {}, 4);
        f->dnf = dnf;
        st.last_quad = st.last_outputs;
        st.cur_ids = st.last_outputs[1];
      } else if (!st.cur_ids.empty()) {
        NodeDef* f = st.Emit("API_GET_NODE", {st.cur_ids}, {}, 2);
        f->dnf = dnf;
        st.cur_ids = st.last_outputs[0];
      } else {
        return Status::InvalidArgument(c.name + " with nothing to filter");
      }
    } else if (c.name == "orderBy" || c.name == "order_by") {
      NodeDef* direct =
          st.last_node.empty() ? nullptr : st.dag->Find(st.last_node);
      if (direct != nullptr && direct->op == "API_GET_NB_EDGE") {
        // edge results post-process inside the op (reference
        // get_neighbor_edge_op.cc applies order_by/limit in-kernel)
        direct->post_process.push_back(
            "order_by " + argw(0, "weight") + " " + argw(1, "asc"));
        continue;
      }
      NodeDef* prod = st.Producer();
      if (prod != nullptr && prod->op == "API_GET_NB_EDGE") {
        // mutating the op here would retroactively change data already
        // bound by the alias (the reference grammar attaches edge
        // post-process before AS, gremlin.y:162-165)
        return Status::InvalidArgument(
            "outE: put orderBy() before as()");
      }
      if (st.last_quad.empty())
        return Status::InvalidArgument("orderBy needs neighbor results");
      NodeDef* target = st.dag->Find(st.last_node);
      if (target != nullptr && target->op == "POST_PROCESS") {
        target->post_process.push_back("order_by " + argw(0, "weight") + " " +
                                       argw(1, "asc"));
      } else {
        std::vector<std::string> quad = st.last_quad;
        NodeDef* pp = st.Emit("POST_PROCESS", quad, {}, 4);
        pp->post_process.push_back("order_by " + argw(0, "weight") + " " +
                                   argw(1, "asc"));
        st.last_quad = st.last_outputs;
        st.cur_ids = st.last_outputs[1];
      }
    } else if (c.name == "limit") {
      NodeDef* direct =
          st.last_node.empty() ? nullptr : st.dag->Find(st.last_node);
      if (direct != nullptr && direct->op == "API_GET_NB_EDGE") {
        direct->post_process.push_back("limit " + argw(0, "0"));
        continue;
      }
      NodeDef* prod = st.Producer();
      if (prod != nullptr && prod->op == "API_GET_NB_EDGE") {
        return Status::InvalidArgument("outE: put limit() before as()");
      }
      if (st.last_quad.empty())
        return Status::InvalidArgument("limit needs neighbor results");
      NodeDef* target = st.dag->Find(st.last_node);
      if (target != nullptr && target->op == "POST_PROCESS") {
        target->post_process.push_back("limit " + argw(0, "0"));
      } else {
        std::vector<std::string> quad = st.last_quad;
        NodeDef* pp = st.Emit("POST_PROCESS", quad, {}, 4);
        pp->post_process.push_back("limit " + argw(0, "0"));
        st.last_quad = st.last_outputs;
        st.cur_ids = st.last_outputs[1];
      }
    } else if (c.name == "as") {
      if (c.args.empty()) return Status::InvalidArgument("as() needs a name");
      std::vector<std::string> ins = st.last_outputs;
      NodeDef* n = st.Emit("AS", ins, {arg(0)},
                           static_cast<int>(ins.size()));
      (void)n;
      out->aliases.push_back(arg(0));
      // as() is transparent: keep cur/last pointing at the aliased data
      if (!st.last_quad.empty() && ins == st.last_quad) {
        // keep quad as-is
      }
      st.last_outputs = ins;
    } else {
      return Status::InvalidArgument("unknown GQL call: " + c.name);
    }
  }
  out->last_outputs = st.last_outputs;
  return Status::OK();
}

// ---------------------------------------------------------------------------
// Optimizer
// ---------------------------------------------------------------------------
namespace {

// Deterministic ops are CSE-safe; sampling ops are not.
const std::unordered_set<std::string>& DeterministicOps() {
  static auto* s = new std::unordered_set<std::string>{
      "API_GET_NODE", "API_GET_NB_NODE", "API_GET_SORTED_NB_NODE",
      "API_GET_RNB_NODE", "API_GET_TOPK_NB", "API_GET_NB_EDGE", "API_GET_P",
      "API_GET_EDGE_P", "API_GET_NODE_T", "ID_UNIQUE", "POST_PROCESS",
      "API_GET_NB_FILTER"};
  return *s;
}

std::string NodeKey(const NodeDef& n) {
  std::ostringstream os;
  os << n.op << "|";
  for (auto& i : n.inputs) os << i << ",";
  os << "|";
  for (auto& a : n.attrs) os << a << ",";
  os << "|";
  for (auto& c : n.dnf) {
    for (auto& t : c) os << t << "&";
    os << ";";
  }
  os << "|";
  for (auto& p : n.post_process) os << p << ",";
  return os.str();
}

void RenameInputs(DAGDef* dag, const std::string& from_node,
                  const std::string& to_node) {
  std::string prefix = from_node + ":";
  for (auto& n : dag->nodes) {
    for (auto& in : n.inputs) {
      if (in.rfind(prefix, 0) == 0)
        in = to_node + in.substr(from_node.size());
    }
  }
}

// protect: node names that must survive (their outputs are fetched by
// name — requested plan outputs / aliases). A protected duplicate is
// kept; an unprotected duplicate of a protected original still folds.
// Returns the number of removed nodes.
int CsePassProtected(DAGDef* dag,
                     const std::unordered_set<std::string>& protect) {
  std::unordered_map<std::string, std::string> seen;  // key → node name
  std::vector<NodeDef> kept;
  int removed = 0;
  for (auto& n : dag->nodes) {
    if (DeterministicOps().count(n.op) == 0) {
      kept.push_back(std::move(n));
      continue;
    }
    std::string key = NodeKey(n);
    auto it = seen.find(key);
    if (it == seen.end() || protect.count(n.name) > 0) {
      if (it == seen.end()) seen.emplace(std::move(key), n.name);
      kept.push_back(std::move(n));
    } else {
      // later duplicate → retarget all readers, drop the node
      RenameInputs(dag, n.name, it->second);
      // inputs already renamed in remaining `dag->nodes`; also fix kept
      std::string prefix = n.name + ":";
      for (auto& k : kept)
        for (auto& in : k.inputs)
          if (in.rfind(prefix, 0) == 0)
            in = it->second + in.substr(n.name.size());
      ++removed;
    }
  }
  dag->nodes = std::move(kept);
  return removed;
}

void CsePass(DAGDef* dag) { CsePassProtected(dag, {}); }

// The graph-touching ops that must run on the shard owning the data.
bool IsGraphOp(const std::string& op) {
  static auto* s = new std::unordered_set<std::string>{
      "API_SAMPLE_NODE", "API_SAMPLE_EDGE", "API_SAMPLE_N_WITH_TYPES",
      "API_GET_NODE", "API_SAMPLE_NB", "API_GET_NB_NODE",
      "API_GET_SORTED_NB_NODE", "API_GET_RNB_NODE", "API_GET_TOPK_NB",
      "API_GET_NB_EDGE", "API_GET_P", "API_GET_EDGE_P", "API_GET_NODE_T",
      "API_SAMPLE_L", "API_GET_NB_FILTER"};
  return s->count(op) > 0;
}

// NOTE: `out` reallocates on every Add, so Add returns the node NAME (a
// copy) — never hold NodeDef pointers across Adds.
struct Rewriter {
  const CompileOptions& opts;
  DAGDef* dag;           // source (for unique naming)
  std::vector<NodeDef> out;

  std::string Fresh(const std::string& op) { return dag->UniqueName(op); }

  std::string Add(const std::string& name, const std::string& op,
                  std::vector<std::string> inputs,
                  std::vector<std::string> attrs) {
    NodeDef n;
    n.name = name;
    n.op = op;
    n.inputs = std::move(inputs);
    n.attrs = std::move(attrs);
    out.push_back(std::move(n));
    return name;
  }

  std::string AddRemote(int shard, NodeDef inner,
                        std::vector<std::string> ship_inputs, int n_outs) {
    NodeDef r;
    r.name = Fresh("REMOTE");
    r.op = "REMOTE";
    r.shard_idx = shard;
    r.inputs = std::move(ship_inputs);
    for (int o = 0; o < n_outs; ++o) r.attrs.push_back(inner.OutName(o));
    r.inner.push_back(std::move(inner));
    std::string name = r.name;
    out.push_back(std::move(r));
    return name;
  }
};


// ---------------------------------------------------------------------------
// graph_partition rewrite (reference optimizer graph_partition mode +
// GP_* merge kernels, end2end_gp_test.cc): shards own whole graphs, so id
// placement is by OWNERSHIP, not hash. Every graph op is broadcast to all
// shards; each shard first filters the inputs it owns (API_GET_NODE, whose
// :1 output is the global input positions), runs the op on the owned
// subset, and returns (positions, outputs); the client reassembles with
// GP_* merges keyed on the returned positions.
// ---------------------------------------------------------------------------
Status GpRewrite(const CompileOptions& opts, DAGDef* dag) {
  const int S = opts.shard_num;
  Rewriter rw{opts, dag, {}};

  std::vector<NodeDef> nodes = std::move(dag->nodes);
  for (auto& n : nodes) {
    bool graph_op = IsGraphOp(n.op) || n.op == "API_SAMPLE_GRAPH_LABEL" ||
                    n.op == "API_GET_GRAPH_BY_LABEL";
    if (!graph_op) {
      rw.out.push_back(std::move(n));
      continue;
    }
    if (n.op == "API_SAMPLE_L" || n.op == "API_GET_EDGE_P" ||
        n.op == "API_GET_NB_FILTER") {
      return Status::InvalidArgument(
          n.op + " is not supported in graph_partition mode");
    }
    const std::string orig = n.name;

    // --- root sampling: count split proportional to shard weight ---
    if (n.op == "API_SAMPLE_NODE" || n.op == "API_SAMPLE_EDGE" ||
        n.op == "API_SAMPLE_GRAPH_LABEL") {
      bool edge = n.op == "API_SAMPLE_EDGE";
      bool glabel = n.op == "API_SAMPLE_GRAPH_LABEL";
      std::string kind = glabel ? "glabel" : (edge ? "edge" : "node");
      std::string split = rw.Add(
          rw.Fresh("SAMPLE_SPLIT"), "SAMPLE_SPLIT", n.inputs,
          {kind, n.attrs.size() > 0 ? n.attrs[0] : "0",
           n.attrs.size() > 1 && !glabel ? n.attrs[1] : "-1"});
      int n_outs = edge ? 3 : 1;
      std::vector<std::string> remotes;
      for (int s = 0; s < S; ++s) {
        NodeDef inner = n;
        inner.name = orig + "_sh" + std::to_string(s);
        inner.inputs = {split + ":" + std::to_string(s)};
        if (inner.attrs.empty()) inner.attrs.push_back("0");
        inner.attrs[0] = "0";  // count from the input scalar
        remotes.push_back(rw.AddRemote(s, std::move(inner),
                                       {split + ":" + std::to_string(s)},
                                       n_outs));
      }
      std::vector<std::string> collect;
      for (int o = 0; o < n_outs; ++o) {
        std::vector<std::string> ins;
        for (int s = 0; s < S; ++s)
          ins.push_back(remotes[s] + ":" + std::to_string(o));
        std::string m =
            rw.Add(rw.Fresh("APPEND_MERGE"), "APPEND_MERGE", ins, {});
        collect.push_back(m + ":0");
      }
      rw.Add(orig, "COLLECT", collect, {});
      continue;
    }

    if (n.op == "API_SAMPLE_N_WITH_TYPES") {
      return Status::InvalidArgument(
          "API_SAMPLE_N_WITH_TYPES is not supported in graph_partition "
          "mode");
    }

    // --- labels → graph nodes: broadcast, shards answer for owned labels ---
    if (n.op == "API_GET_GRAPH_BY_LABEL") {
      std::vector<std::string> remotes;
      for (int s = 0; s < S; ++s) {
        NodeDef inner = n;
        inner.name = orig + "_sh" + std::to_string(s);
        inner.attrs = {"owned"};
        remotes.push_back(rw.AddRemote(s, std::move(inner), n.inputs, 3));
      }
      std::vector<std::string> ins{n.inputs[0]};
      for (int s = 0; s < S; ++s) {
        ins.push_back(remotes[s] + ":0");  // pos
        ins.push_back(remotes[s] + ":1");  // idx
        ins.push_back(remotes[s] + ":2");  // ids
      }
      std::string m =
          rw.Add(rw.Fresh("GP_RAGGED_MERGE"), "GP_RAGGED_MERGE", ins, {"1"});
      rw.Add(orig, "COLLECT", {m + ":0", m + ":1", m + ":2"}, {});
      continue;
    }

    // --- id-keyed ops: broadcast + shard-side ownership filter ---
    std::string ids_in = n.inputs[0];

    if (n.op == "API_GET_NODE") {
      // the op IS the ownership filter; union the per-shard survivors
      std::vector<std::string> remotes;
      for (int s = 0; s < S; ++s) {
        NodeDef inner = n;
        inner.name = orig + "_sh" + std::to_string(s);
        remotes.push_back(rw.AddRemote(s, std::move(inner), {ids_in}, 2));
      }
      std::vector<std::string> ins;
      for (int s = 0; s < S; ++s) {
        ins.push_back(remotes[s] + ":0");
        ins.push_back(remotes[s] + ":1");
      }
      std::string m =
          rw.Add(rw.Fresh("GP_FILTER_MERGE"), "GP_FILTER_MERGE", ins, {});
      rw.Add(orig, "COLLECT", {m + ":0", m + ":1"}, {});
      continue;
    }

    // generic: inner = own-filter (GET_NODE) → op on owned subset
    int n_outs;
    if (n.op == "API_GET_P") {
      int nf = 0;
      for (auto& a : n.attrs)
        if (a.rfind("udf:", 0) != 0) nf++;
      n_outs = 2 * nf;
    } else if (n.op == "API_GET_NODE_T") {
      n_outs = 1;
    } else if (n.op == "API_GET_NB_EDGE") {
      n_outs = 5;  // idx + (src, dst, type, weight)
    } else {
      n_outs = 4;  // quad ops
    }

    std::vector<std::string> remotes;
    std::string own_base = orig + "_own_sh";
    for (int s = 0; s < S; ++s) {
      NodeDef own;
      own.name = own_base + std::to_string(s);
      own.op = "API_GET_NODE";
      own.inputs = {ids_in};
      NodeDef inner = n;
      inner.name = orig + "_sh" + std::to_string(s);
      inner.inputs[0] = own.OutName(0);
      // REMOTE with a 2-node inner plan; outputs = own positions + op outs
      NodeDef r;
      r.name = rw.Fresh("REMOTE");
      r.op = "REMOTE";
      r.shard_idx = s;
      r.inputs = {ids_in};
      r.attrs.push_back(own.OutName(1));
      for (int o = 0; o < n_outs; ++o) r.attrs.push_back(inner.OutName(o));
      r.inner.push_back(std::move(own));
      r.inner.push_back(std::move(inner));
      remotes.push_back(r.name);
      rw.out.push_back(std::move(r));
    }

    if (n.op == "API_GET_NODE_T") {
      std::vector<std::string> ins{ids_in};
      for (int s = 0; s < S; ++s) {
        ins.push_back(remotes[s] + ":0");  // pos
        ins.push_back(remotes[s] + ":1");  // types
      }
      std::string m = rw.Add(rw.Fresh("GP_SCATTER_MERGE"),
                             "GP_SCATTER_MERGE", ins, {});
      rw.Add(orig, "COLLECT", {m + ":0"}, {});
      continue;
    }

    if (n.op == "API_GET_P") {
      std::vector<std::string> collect;
      int nf = n_outs / 2;
      for (int f = 0; f < nf; ++f) {
        std::vector<std::string> ins{ids_in};
        for (int s = 0; s < S; ++s) {
          ins.push_back(remotes[s] + ":0");  // pos
          ins.push_back(remotes[s] + ":" + std::to_string(1 + 2 * f));
          ins.push_back(remotes[s] + ":" + std::to_string(2 + 2 * f));
        }
        std::string m = rw.Add(rw.Fresh("GP_RAGGED_MERGE"),
                               "GP_RAGGED_MERGE", ins, {"1"});
        collect.push_back(m + ":1");
        collect.push_back(m + ":2");
      }
      rw.Add(orig, "COLLECT", collect, {});
      continue;
    }

    if (n.op == "API_GET_NB_EDGE") {
      std::vector<std::string> ins{ids_in};
      for (int s = 0; s < S; ++s) {
        ins.push_back(remotes[s] + ":0");  // pos
        for (int o = 1; o <= 5; ++o)
          ins.push_back(remotes[s] + ":" + std::to_string(o));
      }
      std::string m = rw.Add(rw.Fresh("GP_RAGGED_MERGE"), "GP_RAGGED_MERGE",
                             ins, {"4"});
      rw.Add(orig, "COLLECT",
             {m + ":1", m + ":2", m + ":3", m + ":4", m + ":5"}, {});
      continue;
    }

    // quad ops: fixed-count sampling pads uncovered rows like local mode
    std::vector<std::string> attrs{"3"};
    if (n.op == "API_SAMPLE_NB") {
      std::string k = n.attrs.size() > 1 ? n.attrs[1] : "1";
      std::string def = n.attrs.size() > 2 ? n.attrs[2] : "0";
      attrs.push_back("pad:" + k + ":" + def);
    }
    std::vector<std::string> ins{ids_in};
    for (int s = 0; s < S; ++s) {
      ins.push_back(remotes[s] + ":0");  // pos
      for (int o = 1; o <= 4; ++o)
        ins.push_back(remotes[s] + ":" + std::to_string(o));
    }
    std::string m = rw.Add(rw.Fresh("GP_RAGGED_MERGE"), "GP_RAGGED_MERGE",
                           ins, attrs);
    rw.Add(orig, "COLLECT", {m + ":1", m + ":2", m + ":3", m + ":4"}, {});
  }
  dag->nodes = std::move(rw.out);
  return Status::OK();
}

}  // namespace

// Local fusion (reference analog: the optimizer's subgraph-iso fusion,
// optimizer.h:96 — here a direct collapse, no pattern matching needed):
// wrap the whole plan in one FUSED node whose kernel runs the original
// nodes inline in topological order. All local kernels are synchronous,
// so this removes the per-op executor scheduling (atomic dep counters +
// thread-pool handoff per node) from the hot sampling path; tensors keep
// their original names via also_produces, and seeded RNG streams hash the
// original node names, so fused and unfused plans sample identically.
int FuseLocalPass(DAGDef* dag) {
  if (dag->nodes.size() < 2) return 0;
  for (const auto& n : dag->nodes)
    if (n.op == "REMOTE" || LookupKernel(n.op) == nullptr) return 0;
  std::vector<int> order;
  if (!TopologicSort(*dag, &order)) return 0;  // cycle → executor reports
  NodeDef fused;
  fused.name = dag->UniqueName("FUSED");
  fused.op = "FUSED";
  std::unordered_set<std::string> inner_names;
  for (const auto& n : dag->nodes) inner_names.insert(n.name);
  std::unordered_set<std::string> seen_inputs;
  for (int idx : order) {
    const NodeDef& n = dag->nodes[idx];
    fused.also_produces.push_back(n.name);
    for (const auto& in : n.inputs) {
      auto pos = in.rfind(':');
      std::string producer =
          pos == std::string::npos ? in : in.substr(0, pos);
      if (inner_names.count(producer) == 0 && seen_inputs.insert(in).second)
        fused.inputs.push_back(in);  // external query input → dep edge
    }
  }
  std::vector<NodeDef> inner;
  inner.reserve(order.size());
  for (int idx : order) inner.push_back(std::move(dag->nodes[idx]));
  fused.inner = std::move(inner);
  dag->nodes.clear();
  dag->nodes.push_back(std::move(fused));
  return static_cast<int>(order.size());
}

bool IsDeterministicOp(const std::string& op) {
  return DeterministicOps().count(op) > 0;
}

bool DagIsDeterministic(const DAGDef& dag) {
  // AS / COLLECT / FUSED are pure plumbing (alias, passthrough, inline
  // group) — deterministic iff what they wrap is.
  std::function<bool(const std::vector<NodeDef>&)> det =
      [&](const std::vector<NodeDef>& nodes) {
        for (const auto& n : nodes) {
          if (n.op == "AS" || n.op == "COLLECT") continue;
          if (n.op == "FUSED") {
            if (!det(n.inner)) return false;
            continue;
          }
          if (DeterministicOps().count(n.op) == 0) return false;
        }
        return true;
      };
  return det(dag.nodes);
}

namespace {

// Filter / post-process pushdown over a registered plan: an adjacent
// sole-consumer chain of the same shaping op collapses into one node —
// the CHILD absorbs its producer (the child's name may be a requested
// output; the producer's never is, guarded below). Patterns:
//   API_GET_NODE(dnf2) ∘ API_GET_NODE(dnf1)  →  API_GET_NODE(dnf1∧dnf2)
//   POST_PROCESS(pp2)  ∘ POST_PROCESS(pp1)   →  POST_PROCESS(pp1;pp2)
//   ID_UNIQUE          ∘ ID_UNIQUE           →  ID_UNIQUE
// Legal only when the producer's outputs feed NOTHING but the child
// (GET_NODE:1 / chained positions change meaning otherwise) and the
// producer's name is not a requested output. For GET_NODE / ID_UNIQUE
// additionally nothing may consume the CHILD's :1+ outputs (positions /
// inverse index — relative to the producer's output before the merge,
// to the original input after; `consumed` carries the plan's requested
// output strings so a fetched child:1 also blocks). Returns removed.
int PushdownPass(DAGDef* dag, const std::unordered_set<std::string>& protect,
                 const std::unordered_set<std::string>& consumed) {
  int removed = 0;
  bool changed = true;
  while (changed) {
    changed = false;
    for (size_t ci = 0; ci < dag->nodes.size() && !changed; ++ci) {
      NodeDef& child = dag->nodes[ci];
      if (child.op != "API_GET_NODE" && child.op != "POST_PROCESS" &&
          child.op != "ID_UNIQUE")
        continue;
      if (child.inputs.empty()) continue;
      auto pos = child.inputs[0].rfind(':');
      if (pos == std::string::npos) continue;
      const std::string pname = child.inputs[0].substr(0, pos);
      NodeDef* prod = dag->Find(pname);
      if (prod == nullptr || prod->op != child.op) continue;
      if (protect.count(pname) > 0) continue;
      // the child must consume the producer positionally: input i is
      // exactly producer:i (a shuffled wiring is not a simple chain)
      bool chained = true;
      for (size_t i = 0; i < child.inputs.size() && chained; ++i)
        chained = child.inputs[i] == prod->OutName(static_cast<int>(i));
      if (!chained) continue;
      // sole consumer: no OTHER node reads any producer output
      const std::string prefix = pname + ":";
      bool sole = true;
      for (const auto& other : dag->nodes) {
        if (&other == &child) continue;
        for (const auto& in : other.inputs)
          if (in.rfind(prefix, 0) == 0) sole = false;
      }
      if (!sole) continue;
      // GET_NODE / ID_UNIQUE: the child's :1+ outputs index into what
      // the child CONSUMED — the merge rebases them onto the original
      // input, so any consumer of them blocks the rewrite
      if (child.op != "POST_PROCESS") {
        bool aux_read = false;
        for (int slot = 1; slot < 8 && !aux_read; ++slot) {
          const std::string out = child.OutName(slot);
          if (consumed.count(out) > 0) aux_read = true;
          for (const auto& other : dag->nodes)
            for (const auto& in : other.inputs)
              if (in == out) aux_read = true;
        }
        if (aux_read) continue;
      }
      if (child.op == "API_GET_NODE") {
        // dnf1 ∧ dnf2: survivors of both filters, positions now
        // relative to the PRODUCER's input — legal because nothing else
        // read the intermediate positions (sole-consumer guard)
        child.dnf = AndDnf(prod->dnf, child.dnf);
      } else if (child.op == "POST_PROCESS") {
        std::vector<std::string> pp = prod->post_process;
        pp.insert(pp.end(), child.post_process.begin(),
                  child.post_process.end());
        child.post_process = std::move(pp);
      }  // ID_UNIQUE ∘ ID_UNIQUE: idempotent, nothing to merge
      child.inputs = prod->inputs;
      for (size_t i = 0; i < dag->nodes.size(); ++i) {
        if (dag->nodes[i].name == pname) {
          dag->nodes.erase(dag->nodes.begin() + i);
          break;
        }
      }
      ++removed;
      changed = true;
    }
  }
  return removed;
}

}  // namespace

Status OptimizePreparedPlan(DAGDef* dag,
                            const std::vector<std::string>& outputs,
                            PlanOptStats* stats) {
  PlanOptStats local;
  PlanOptStats* st = stats != nullptr ? stats : &local;
  // producers of requested outputs must keep their names: the reply is
  // assembled by ctx lookup of these exact strings
  std::unordered_set<std::string> protect;
  std::unordered_set<std::string> consumed(outputs.begin(), outputs.end());
  for (const auto& out : outputs) {
    auto pos = out.rfind(':');
    protect.insert(pos == std::string::npos ? out : out.substr(0, pos));
  }
  st->dedup += CsePassProtected(dag, protect);
  st->pushdown += PushdownPass(dag, protect, consumed);
  st->fuse += FuseLocalPass(dag);
  std::vector<int> order;
  if (!TopologicSort(*dag, &order))
    return Status::Internal("optimized plan has a cycle");
  return Status::OK();
}

Status OptimizeDag(const CompileOptions& opts, DAGDef* dag) {
  CsePass(dag);
  if (opts.mode == "local" && opts.fuse_local &&
      std::getenv("EULER_TPU_NO_FUSE") == nullptr) {
    FuseLocalPass(dag);
    return Status::OK();
  }
  if (opts.mode == "graph_partition") return GpRewrite(opts, dag);
  // shard_num == 1 still needs the rewrite in distribute mode: the client
  // has no local graph, so graph ops must ship to the (single) remote
  // shard — the generic split/REMOTE/merge path degenerates correctly
  if (opts.mode != "distribute") return Status::OK();

  const int S = opts.shard_num;
  std::string pn = std::to_string(opts.partition_num);
  std::string sn = std::to_string(S);
  Rewriter rw{opts, dag, {}};

  std::vector<NodeDef> nodes = std::move(dag->nodes);
  for (auto& n : nodes) {
    // Whole-graph label ops also need shipping in hash-distribute mode: a
    // graph's nodes scatter across shards, so sampleGL splits by per-shard
    // label weight and graphNodes broadcasts + concat-merges the per-shard
    // member lists (a label may span several shards here, unlike gp mode).
    if (n.op == "API_SAMPLE_GRAPH_LABEL") {
      const std::string orig_gl = n.name;
      std::string split = rw.Add(
          rw.Fresh("SAMPLE_SPLIT"), "SAMPLE_SPLIT", n.inputs,
          {"glabel", n.attrs.size() > 0 ? n.attrs[0] : "0", "-1", "owned"});
      std::vector<std::string> remotes;
      for (int s = 0; s < S; ++s) {
        NodeDef inner = n;
        inner.name = orig_gl + "_sh" + std::to_string(s);
        inner.inputs = {split + ":" + std::to_string(s)};
        // owned form: shard draws only labels with label % S == s
        inner.attrs = {"0", "owned", std::to_string(s), sn};
        remotes.push_back(rw.AddRemote(s, std::move(inner),
                                       {split + ":" + std::to_string(s)},
                                       1));
      }
      std::vector<std::string> ins;
      for (int s = 0; s < S; ++s) ins.push_back(remotes[s] + ":0");
      std::string m =
          rw.Add(rw.Fresh("APPEND_MERGE"), "APPEND_MERGE", ins, {});
      rw.Add(orig_gl, "COLLECT", {m + ":0"}, {});
      continue;
    }
    if (n.op == "API_GET_GRAPH_BY_LABEL") {
      const std::string orig_gl = n.name;
      std::vector<std::string> remotes;
      for (int s = 0; s < S; ++s) {
        NodeDef inner = n;
        inner.name = orig_gl + "_sh" + std::to_string(s);
        inner.attrs = {"owned"};
        remotes.push_back(rw.AddRemote(s, std::move(inner), n.inputs, 3));
      }
      std::vector<std::string> ins{n.inputs[0]};
      for (int s = 0; s < S; ++s) {
        ins.push_back(remotes[s] + ":0");
        ins.push_back(remotes[s] + ":1");
        ins.push_back(remotes[s] + ":2");
      }
      std::string m = rw.Add(rw.Fresh("GP_RAGGED_MERGE"), "GP_RAGGED_MERGE",
                             ins, {"1", "concat_sort"});
      rw.Add(orig_gl, "COLLECT", {m + ":0", m + ":1", m + ":2"}, {});
      continue;
    }
    if (!IsGraphOp(n.op)) {
      rw.out.push_back(std::move(n));
      continue;
    }
    const std::string orig = n.name;

    if (n.op == "API_SAMPLE_NODE" || n.op == "API_SAMPLE_EDGE") {
      bool edge = n.op == "API_SAMPLE_EDGE";
      // SAMPLE_SPLIT -> per-shard count scalars :s
      std::string split = rw.Add(
          rw.Fresh("SAMPLE_SPLIT"), "SAMPLE_SPLIT", n.inputs,
          {edge ? "edge" : "node", n.attrs.size() > 0 ? n.attrs[0] : "0",
           n.attrs.size() > 1 ? n.attrs[1] : "-1"});
      int n_outs = edge ? 3 : 1;
      std::vector<std::string> remotes;
      for (int s = 0; s < S; ++s) {
        NodeDef inner = n;
        inner.name = orig + "_sh" + std::to_string(s);
        inner.inputs = {split + ":" + std::to_string(s)};
        inner.attrs[0] = "0";  // count comes from the input scalar
        remotes.push_back(rw.AddRemote(s, std::move(inner),
                                       {split + ":" + std::to_string(s)},
                                       n_outs));
      }
      std::vector<std::string> collect_ins;
      for (int o = 0; o < n_outs; ++o) {
        std::vector<std::string> ins;
        for (auto& r : remotes) ins.push_back(r + ":" + std::to_string(o));
        std::string m =
            rw.Add(rw.Fresh("APPEND_MERGE"), "APPEND_MERGE", ins, {});
        collect_ins.push_back(m + ":0");
      }
      rw.Add(orig, "COLLECT", collect_ins, {});
      continue;
    }

    if (n.op == "API_SAMPLE_N_WITH_TYPES") {
      std::string split = rw.Add(rw.Fresh("TYPES_SPLIT"), "TYPES_SPLIT",
                                 {n.inputs[0]}, {sn});
      std::vector<std::string> remotes;
      for (int s = 0; s < S; ++s) {
        NodeDef inner = n;
        inner.name = orig + "_sh" + std::to_string(s);
        inner.inputs = {split + ":" + std::to_string(2 * s)};
        remotes.push_back(rw.AddRemote(s, std::move(inner),
                                       {split + ":" + std::to_string(2 * s)},
                                       1));
      }
      std::vector<std::string> ins;
      for (int s = 0; s < S; ++s) {
        ins.push_back(split + ":" + std::to_string(2 * s + 1));  // pos
        ins.push_back(remotes[s] + ":0");                        // data
      }
      std::string m =
          rw.Add(rw.Fresh("REGULAR_MERGE"), "REGULAR_MERGE", ins, {"1"});
      rw.Add(orig, "COLLECT", {m + ":0"}, {});
      continue;
    }

    if (n.op == "API_SAMPLE_L") {
      // Per-LAYER split/remote/merge: layer l's pool is sampled by the
      // shards OWNING the layer-(l-1) nodes (edges are partitioned by
      // src, so only the owner sees a node's out-neighbors). A one-shot
      // broadcast once produced all-pad layer-2 pools: a shard's local
      // layer-1 nodes mostly live on other shards.
      std::vector<std::string> sizes;
      {
        std::stringstream ss(n.attrs[1]);
        std::string it;
        while (std::getline(ss, it, ':')) sizes.push_back(it);
      }
      std::string pool = n.inputs[0];
      std::vector<std::string> collect_ins;
      for (size_t l = 0; l < sizes.size(); ++l) {
        std::string split =
            rw.Add(rw.Fresh("ID_SPLIT"), "ID_SPLIT", {pool}, {pn, sn});
        std::vector<std::string> ins;
        for (int s = 0; s < S; ++s) {
          NodeDef inner = n;
          inner.name =
              orig + "_l" + std::to_string(l) + "_sh" + std::to_string(s);
          inner.inputs = {split + ":" + std::to_string(2 * s)};
          inner.attrs[1] = sizes[l];  // single-layer sample on the shard
          // each shard also reports its candidate weight mass so
          // POOL_MERGE can weigh shards (a mass-blind merge skewed the
          // pool toward low-weight shards and their pad entries)
          inner.attrs.resize(4);  // [ets, m, default, weight_func]
          inner.attrs.push_back("emit_wsum");
          std::string r = rw.AddRemote(
              s, std::move(inner),
              {split + ":" + std::to_string(2 * s)}, 2);
          ins.push_back(r + ":0");   // pool ids
          ins.push_back(r + ":1");   // candidate mass
        }
        std::string m =
            rw.Add(rw.Fresh("POOL_MERGE"), "POOL_MERGE", ins,
                   {sizes[l], n.attrs.size() > 2 ? n.attrs[2] : "0"});
        collect_ins.push_back(m + ":0");
        pool = m + ":0";
      }
      rw.Add(orig, "COLLECT", collect_ins, {});
      continue;
    }

    if (n.op == "API_GET_NB_FILTER") {
      // Filter a quad by a dnf evaluated on the shards owning the ids:
      // unique flat ids -> split -> remote API_GET_NODE(dnf) -> append
      // surviving ids -> apply membership to the quad.
      std::string uniq =
          rw.Add(rw.Fresh("ID_UNIQUE"), "ID_UNIQUE", {n.inputs[1]}, {});
      std::string split = rw.Add(rw.Fresh("ID_SPLIT"), "ID_SPLIT",
                                 {uniq + ":0"}, {pn, sn});
      std::vector<std::string> ins;
      for (int s = 0; s < S; ++s) {
        NodeDef inner;
        inner.name = orig + "_sh" + std::to_string(s);
        inner.op = "API_GET_NODE";
        inner.inputs = {split + ":" + std::to_string(2 * s)};
        inner.dnf = n.dnf;
        std::string r = rw.AddRemote(s, std::move(inner),
                                     {split + ":" + std::to_string(2 * s)},
                                     1);
        ins.push_back(r + ":0");
      }
      std::string m =
          rw.Add(rw.Fresh("APPEND_MERGE"), "APPEND_MERGE", ins, {});
      rw.Add(orig, "QUAD_FILTER_APPLY",
             {n.inputs[0], n.inputs[1], n.inputs[2], n.inputs[3], m + ":0"},
             {});
      continue;
    }

    if (n.op == "API_GET_EDGE_P") {
      std::string split = rw.Add(rw.Fresh("TRIPLE_SPLIT"), "TRIPLE_SPLIT",
                                 {n.inputs[0], n.inputs[1], n.inputs[2]},
                                 {pn, sn});
      int nf = 0;
      for (auto& a : n.attrs)
        if (a.rfind("udf:", 0) != 0) nf++;
      std::vector<std::string> remotes;
      for (int s = 0; s < S; ++s) {
        NodeDef inner = n;
        inner.name = orig + "_sh" + std::to_string(s);
        inner.inputs = {split + ":" + std::to_string(4 * s),
                        split + ":" + std::to_string(4 * s + 1),
                        split + ":" + std::to_string(4 * s + 2)};
        std::vector<std::string> ship = inner.inputs;
        remotes.push_back(
            rw.AddRemote(s, std::move(inner), std::move(ship), 2 * nf));
      }
      std::vector<std::string> collect_ins;
      for (int f = 0; f < nf; ++f) {
        std::vector<std::string> ins;
        for (int s = 0; s < S; ++s) {
          ins.push_back(split + ":" + std::to_string(4 * s + 3));
          ins.push_back(remotes[s] + ":" + std::to_string(2 * f));
          ins.push_back(remotes[s] + ":" + std::to_string(2 * f + 1));
        }
        std::string m =
            rw.Add(rw.Fresh("RAGGED_MERGE"), "RAGGED_MERGE", ins, {"1"});
        collect_ins.push_back(m + ":0");
        collect_ins.push_back(m + ":1");
      }
      rw.Add(orig, "COLLECT", collect_ins, {});
      continue;
    }

    // --- id-keyed node ops ---
    // unique+gather for GET ops. Exceptions: API_SAMPLE_NB draws per
    // input row (dedup would change the sample), and API_GET_NODE's
    // outputs are input-position-keyed with duplicates preserved —
    // deduping would emit unique-space positions, diverging from local
    // mode (FILTER_MERGE composes split positions, which must be
    // input-space).
    bool dedup = n.op != "API_SAMPLE_NB" && n.op != "API_GET_NODE";
    std::string ids_in = n.inputs[0];
    std::string uniq;
    if (dedup) {
      uniq = rw.Add(rw.Fresh("ID_UNIQUE"), "ID_UNIQUE", {ids_in}, {});
      ids_in = uniq + ":0";
    }
    std::string split =
        rw.Add(rw.Fresh("ID_SPLIT"), "ID_SPLIT", {ids_in}, {pn, sn});

    int n_outs;
    if (n.op == "API_GET_P") {
      int nf = 0;
      for (auto& a : n.attrs)
        if (a.rfind("udf:", 0) != 0) nf++;
      n_outs = 2 * nf;
    } else if (n.op == "API_GET_NODE_T") {
      n_outs = 1;
    } else if (n.op == "API_GET_NODE") {
      n_outs = 2;
    } else if (n.op == "API_GET_NB_EDGE") {
      n_outs = 5;  // idx + (src, dst, type, weight)
    } else {
      n_outs = 4;  // quad ops
    }

    std::vector<std::string> remotes;
    for (int s = 0; s < S; ++s) {
      NodeDef inner = n;
      inner.name = orig + "_sh" + std::to_string(s);
      inner.inputs[0] = split + ":" + std::to_string(2 * s);
      remotes.push_back(rw.AddRemote(s, std::move(inner),
                                     {split + ":" + std::to_string(2 * s)},
                                     n_outs));
    }

    if (n.op == "API_GET_NODE") {
      std::vector<std::string> ins;
      for (int s = 0; s < S; ++s) {
        ins.push_back(split + ":" + std::to_string(2 * s + 1));  // pos
        ins.push_back(remotes[s] + ":0");  // surviving ids
        ins.push_back(remotes[s] + ":1");  // local positions
      }
      // FILTER_MERGE emits (ids, input-space positions) ordered by
      // position — same contract as the local GetNodeOp.
      std::string m =
          rw.Add(rw.Fresh("FILTER_MERGE"), "FILTER_MERGE", ins, {});
      rw.Add(orig, "COLLECT", {m + ":0", m + ":1"}, {});
      continue;
    }

    if (n.op == "API_GET_NODE_T") {
      std::vector<std::string> ins;
      for (int s = 0; s < S; ++s) {
        ins.push_back(split + ":" + std::to_string(2 * s + 1));
        ins.push_back(remotes[s] + ":0");
      }
      std::string m =
          rw.Add(rw.Fresh("REGULAR_MERGE"), "REGULAR_MERGE", ins, {"1"});
      std::string g = rw.Add(rw.Fresh("REGULAR_GATHER"), "REGULAR_GATHER",
                             {uniq + ":1", m + ":0"}, {"1"});
      rw.Add(orig, "COLLECT", {g + ":0"}, {});
      continue;
    }

    if (n.op == "API_GET_P") {
      std::vector<std::string> collect_ins;
      int nf = n_outs / 2;
      for (int f = 0; f < nf; ++f) {
        std::vector<std::string> ins;
        for (int s = 0; s < S; ++s) {
          ins.push_back(split + ":" + std::to_string(2 * s + 1));
          ins.push_back(remotes[s] + ":" + std::to_string(2 * f));
          ins.push_back(remotes[s] + ":" + std::to_string(2 * f + 1));
        }
        std::string m =
            rw.Add(rw.Fresh("RAGGED_MERGE"), "RAGGED_MERGE", ins, {"1"});
        std::string g =
            rw.Add(rw.Fresh("RAGGED_GATHER"), "RAGGED_GATHER",
                   {uniq + ":1", m + ":0", m + ":1"}, {"1"});
        collect_ins.push_back(g + ":0");
        collect_ins.push_back(g + ":1");
      }
      rw.Add(orig, "COLLECT", collect_ins, {});
      continue;
    }

    if (n.op == "API_GET_NB_EDGE") {
      // same ragged merge/gather as quads, one more payload column
      std::vector<std::string> ins;
      for (int s = 0; s < S; ++s) {
        ins.push_back(split + ":" + std::to_string(2 * s + 1));
        for (int o = 0; o < 5; ++o)
          ins.push_back(remotes[s] + ":" + std::to_string(o));
      }
      std::string m =
          rw.Add(rw.Fresh("RAGGED_MERGE"), "RAGGED_MERGE", ins, {"4"});
      std::string g = rw.Add(
          rw.Fresh("RAGGED_GATHER"), "RAGGED_GATHER",
          {uniq + ":1", m + ":0", m + ":1", m + ":2", m + ":3", m + ":4"},
          {"4"});
      rw.Add(orig, "COLLECT",
             {g + ":0", g + ":1", g + ":2", g + ":3", g + ":4"}, {});
      continue;
    }

    // quad ops
    {
      std::vector<std::string> ins;
      for (int s = 0; s < S; ++s) {
        ins.push_back(split + ":" + std::to_string(2 * s + 1));
        for (int o = 0; o < 4; ++o)
          ins.push_back(remotes[s] + ":" + std::to_string(o));
      }
      std::string m =
          rw.Add(rw.Fresh("RAGGED_MERGE"), "RAGGED_MERGE", ins, {"3"});
      if (dedup) {
        std::string g = rw.Add(
            rw.Fresh("RAGGED_GATHER"), "RAGGED_GATHER",
            {uniq + ":1", m + ":0", m + ":1", m + ":2", m + ":3"}, {"3"});
        rw.Add(orig, "COLLECT", {g + ":0", g + ":1", g + ":2", g + ":3"},
               {});
      } else {
        rw.Add(orig, "COLLECT", {m + ":0", m + ":1", m + ":2", m + ":3"},
               {});
      }
      continue;
    }
  }
  dag->nodes = std::move(rw.out);
  return Status::OK();
}

// ---------------------------------------------------------------------------
// Compiler with cache
// ---------------------------------------------------------------------------
Status GqlCompiler::Compile(const std::string& query,
                            std::shared_ptr<const TranslateResult>* out) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = cache_.find(query);
    if (it != cache_.end()) {
      lru_.splice(lru_.begin(), lru_, it->second.second);
      *out = it->second.first;
      return Status::OK();
    }
  }
  std::vector<GqlCall> calls;
  ET_RETURN_IF_ERROR(ParseGql(query, &calls));
  auto result = std::make_shared<TranslateResult>();
  ET_RETURN_IF_ERROR(TranslateGql(calls, result.get()));
  ET_RETURN_IF_ERROR(OptimizeDag(opts_, &result->dag));
  std::vector<int> order;
  if (!TopologicSort(result->dag, &order))
    return Status::Internal("compiled DAG has a cycle: " + query);
  {
    // bounded LRU (kCacheCap): a proxy fed an unbounded stream of
    // distinct query strings stays flat; an evicted entry recompiles
    std::lock_guard<std::mutex> lk(mu_);
    auto it = cache_.find(query);
    if (it != cache_.end()) {
      lru_.splice(lru_.begin(), lru_, it->second.second);
      it->second.first = result;
    } else {
      lru_.push_front(query);
      cache_[query] = {result, lru_.begin()};
      while (cache_.size() > kCacheCap) {
        cache_.erase(lru_.back());
        lru_.pop_back();
      }
    }
  }
  *out = result;
  return Status::OK();
}

size_t GqlCompiler::cache_size() const {
  std::lock_guard<std::mutex> lk(mu_);
  return cache_.size();
}

std::string DagToString(const DAGDef& dag) {
  std::ostringstream os;
  std::function<void(const std::vector<NodeDef>&, int)> dump =
      [&](const std::vector<NodeDef>& nodes, int depth) {
        std::string ind(depth * 2, ' ');
        for (const auto& n : nodes) {
          os << ind << n.name << " = " << n.op << "(";
          for (size_t i = 0; i < n.inputs.size(); ++i)
            os << (i ? ", " : "") << n.inputs[i];
          os << ")";
          if (!n.attrs.empty()) {
            os << " attrs[";
            for (size_t i = 0; i < n.attrs.size(); ++i)
              os << (i ? ", " : "") << n.attrs[i];
            os << "]";
          }
          if (!n.dnf.empty()) {
            os << " dnf[";
            for (size_t i = 0; i < n.dnf.size(); ++i) {
              if (i) os << " | ";
              for (size_t j = 0; j < n.dnf[i].size(); ++j)
                os << (j ? " & " : "") << n.dnf[i][j];
            }
            os << "]";
          }
          if (!n.post_process.empty()) {
            os << " pp[";
            for (size_t i = 0; i < n.post_process.size(); ++i)
              os << (i ? "; " : "") << n.post_process[i];
            os << "]";
          }
          if (n.shard_idx >= 0) os << " shard=" << n.shard_idx;
          os << "\n";
          if (!n.inner.empty()) dump(n.inner, depth + 1);
        }
      };
  dump(dag.nodes, 0);
  return os.str();
}

}  // namespace et
