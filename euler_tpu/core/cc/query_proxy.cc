#include "query_proxy.h"

#include "threadpool.h"

#include <chrono>

namespace et {

Status QueryProxy::NewLocal(std::shared_ptr<const Graph> graph,
                            const std::string& index_spec, uint64_t seed,
                            std::unique_ptr<QueryProxy>* out) {
  auto qp = std::unique_ptr<QueryProxy>(new QueryProxy());
  qp->graph_ = std::move(graph);
  qp->seed_ = seed;
  if (!index_spec.empty()) {
    qp->index_ = std::make_shared<IndexManager>();
    ET_RETURN_IF_ERROR(qp->index_->BuildFromSpec(*qp->graph_, index_spec));
  }
  CompileOptions opts;
  opts.mode = "local";
  opts.shard_num = 1;
  qp->compiler_ = std::make_unique<GqlCompiler>(opts);
  *out = std::move(qp);
  return Status::OK();
}

Status QueryProxy::NewRemote(const std::string& endpoints, uint64_t seed,
                             const std::string& mode,
                             std::unique_ptr<QueryProxy>* out) {
  if (mode != "distribute" && mode != "graph_partition")
    return Status::InvalidArgument("remote mode must be distribute or "
                                   "graph_partition, got " + mode);
  ShardEndpoints eps;
  std::string watch_spec;
  if (endpoints.rfind("hosts:", 0) == 0) {
    ET_RETURN_IF_ERROR(DiscoverFromSpec(endpoints.substr(6), &eps));
  } else if (endpoints.rfind("dir:", 0) == 0) {
    watch_spec = endpoints.substr(4);
    ET_RETURN_IF_ERROR(DiscoverFromRegistryAuto(watch_spec, &eps));
  } else if (endpoints.rfind("tcp:", 0) == 0) {
    // TCP registry server — cross-machine discovery without a shared
    // filesystem (the reference's ZK role)
    watch_spec = endpoints;
    ET_RETURN_IF_ERROR(DiscoverFromRegistryAuto(watch_spec, &eps));
  } else {
    return Status::InvalidArgument(
        "endpoints must be 'hosts:h:p,...', 'dir:/path', or "
        "'tcp:host:port' (registry server)");
  }
  auto qp = std::unique_ptr<QueryProxy>(new QueryProxy());
  qp->seed_ = seed;
  qp->client_ = std::make_unique<ClientManager>();
  ET_RETURN_IF_ERROR(qp->client_->Init(eps));
  // registry mode gets live membership: restarted shards are picked up
  // without re-initializing the proxy (ZK watch parity)
  if (!watch_spec.empty()) qp->client_->WatchRegistry(watch_spec);
  CompileOptions opts;
  opts.mode = mode;
  opts.shard_num = qp->client_->shard_num();
  opts.partition_num = qp->client_->partition_num();
  qp->compiler_ = std::make_unique<GqlCompiler>(opts);
  *out = std::move(qp);
  return Status::OK();
}

const GraphMeta& QueryProxy::graph_meta() const {
  static GraphMeta empty;
  if (graph_) return graph_->meta();
  if (client_) return client_->graph_meta();
  return empty;
}

Status QueryProxy::RunGremlin(const std::string& query,
                              const std::map<std::string, Tensor>& inputs,
                              std::map<std::string, Tensor>* outputs) {
  auto t0 = std::chrono::steady_clock::now();
  Status st = RunGremlinTimed(query, inputs, outputs);
  uint64_t us = std::chrono::duration_cast<std::chrono::microseconds>(
                    std::chrono::steady_clock::now() - t0)
                    .count();
  queries_.fetch_add(1);
  if (!st.ok()) errors_.fetch_add(1);
  total_us_.fetch_add(us);
  last_us_.store(us);
  return st;
}

Status QueryProxy::RunGremlinTimed(const std::string& query,
                                   const std::map<std::string, Tensor>& inputs,
                                   std::map<std::string, Tensor>* outputs) {
  std::shared_ptr<const TranslateResult> plan;
  ET_RETURN_IF_ERROR(compiler_->Compile(query, &plan));
  OpKernelContext ctx;
  for (const auto& kv : inputs) ctx.Put(kv.first, kv.second);
  QueryEnv env;
  env.graph = graph_.get();
  env.index = index_.get();
  env.client = client_.get();
  env.pool = GlobalThreadPool();
  env.seed = seed_;
  env.nonce = run_counter_.fetch_add(1);
  Executor exec(&plan->dag, env, &ctx);
  ET_RETURN_IF_ERROR(exec.RunSync());
  outputs->clear();
  for (const auto& alias : plan->aliases) {
    for (int i = 0;; ++i) {
      std::string name = alias + ":" + std::to_string(i);
      Tensor t;
      if (!ctx.Get(name, &t)) break;
      (*outputs)[name] = std::move(t);
    }
  }
  for (const auto& name : plan->last_outputs) {
    Tensor t;
    if (ctx.Get(name, &t)) (*outputs)[name] = std::move(t);
  }
  return Status::OK();
}

}  // namespace et
