#include "query_proxy.h"

#include "threadpool.h"
#include "udf.h"

#include <chrono>

namespace et {

Status QueryProxy::NewLocal(std::shared_ptr<const Graph> graph,
                            const std::string& index_spec, uint64_t seed,
                            std::unique_ptr<QueryProxy>* out) {
  return NewLocal(std::make_shared<GraphRef>(std::move(graph)), index_spec,
                  seed, out);
}

Status QueryProxy::NewLocal(std::shared_ptr<GraphRef> graph_ref,
                            const std::string& index_spec, uint64_t seed,
                            std::unique_ptr<QueryProxy>* out) {
  auto qp = std::unique_ptr<QueryProxy>(new QueryProxy());
  qp->graph_ref_ = std::move(graph_ref);
  qp->seed_ = seed;
  qp->index_spec_ = index_spec;
  if (!index_spec.empty()) {
    auto g = qp->graph_ref_->get();
    qp->index_ = std::make_shared<IndexManager>();
    ET_RETURN_IF_ERROR(qp->index_->BuildFromSpec(*g, index_spec));
    qp->index_epoch_ = g->epoch();
  }
  CompileOptions opts;
  opts.mode = "local";
  opts.shard_num = 1;
  qp->compiler_ = std::make_unique<GqlCompiler>(opts);
  *out = std::move(qp);
  return Status::OK();
}

Status QueryProxy::NewRemote(const std::string& endpoints, uint64_t seed,
                             const std::string& mode,
                             std::unique_ptr<QueryProxy>* out) {
  if (mode != "distribute" && mode != "graph_partition")
    return Status::InvalidArgument("remote mode must be distribute or "
                                   "graph_partition, got " + mode);
  ShardEndpoints eps;
  std::string watch_spec;
  if (endpoints.rfind("hosts:", 0) == 0) {
    ET_RETURN_IF_ERROR(DiscoverFromSpec(endpoints.substr(6), &eps));
  } else if (endpoints.rfind("dir:", 0) == 0) {
    watch_spec = endpoints.substr(4);
    ET_RETURN_IF_ERROR(DiscoverFromRegistryAuto(watch_spec, &eps));
  } else if (endpoints.rfind("tcp:", 0) == 0) {
    // TCP registry server — cross-machine discovery without a shared
    // filesystem (the reference's ZK role)
    watch_spec = endpoints;
    ET_RETURN_IF_ERROR(DiscoverFromRegistryAuto(watch_spec, &eps));
  } else {
    return Status::InvalidArgument(
        "endpoints must be 'hosts:h:p,...', 'dir:/path', or "
        "'tcp:host:port' (registry server)");
  }
  auto qp = std::unique_ptr<QueryProxy>(new QueryProxy());
  qp->seed_ = seed;
  qp->client_ = std::make_unique<ClientManager>();
  ET_RETURN_IF_ERROR(qp->client_->Init(eps));
  // registry mode gets live membership: restarted shards are picked up
  // without re-initializing the proxy (ZK watch parity)
  if (!watch_spec.empty()) qp->client_->WatchRegistry(watch_spec);
  CompileOptions opts;
  opts.mode = mode;
  opts.shard_num = qp->client_->shard_num();
  opts.partition_num = qp->client_->partition_num();
  qp->compiler_ = std::make_unique<GqlCompiler>(opts);
  *out = std::move(qp);
  return Status::OK();
}

const GraphMeta& QueryProxy::graph_meta() const {
  static GraphMeta empty;
  if (graph_ref_) {
    // copy out of the pinned snapshot: returning a reference into the
    // Graph itself would dangle if a delta swap dropped the snapshot
    // between this return and the caller's read
    thread_local GraphMeta snap;
    snap = graph_ref_->get()->meta();
    return snap;
  }
  if (client_) return client_->graph_meta();
  return empty;
}

uint64_t QueryProxy::ObservedEpoch() const {
  if (graph_ref_) return graph_ref_->epoch();
  if (client_) return client_->ObservedEpoch();
  return 0;
}

Status QueryProxy::ApplyDelta(const NodeId* node_ids,
                              const int32_t* node_types,
                              const float* node_weights, size_t n_nodes,
                              const NodeId* edge_src, const NodeId* edge_dst,
                              const int32_t* edge_types,
                              const float* edge_weights, size_t n_edges,
                              uint64_t* new_epoch) {
  if (client_) {
    return client_->ApplyDelta(node_ids, node_types, node_weights, n_nodes,
                               edge_src, edge_dst, edge_types, edge_weights,
                               n_edges, new_epoch);
  }
  if (!graph_ref_) return Status::Internal("proxy has no graph");
  // per-ref apply lock: serialized with applies through ANY surface
  // sharing this ref (the capi handle, other proxies, a server)
  std::lock_guard<std::mutex> lk(graph_ref_->apply_mutex());
  auto base = graph_ref_->get();
  std::unique_ptr<Graph> next;
  std::vector<NodeId> dirty;
  ET_RETURN_IF_ERROR(ApplyGraphDelta(
      *base, node_ids, node_types, node_weights, n_nodes, edge_src, edge_dst,
      edge_types, edge_weights, n_edges, /*shard_idx=*/0, /*shard_num=*/1,
      &next, &dirty));
  uint64_t epoch = next->epoch();
  if (!graph_ref_->SwapFrom(base,
                            std::shared_ptr<const Graph>(std::move(next)),
                            std::move(dirty)))
    return Status::Internal("concurrent delta apply on this graph; retry");
  UdfResultCache::Instance().EvictGraph(base->uid());
  if (new_epoch != nullptr) *new_epoch = epoch;
  return Status::OK();
}

Status QueryProxy::SetOwnership(const std::string& spec) {
  if (!client_)
    return Status::InvalidArgument(
        "ownership maps apply to distribute-mode proxies only");
  auto m = std::make_shared<OwnershipMap>();
  ET_RETURN_IF_ERROR(OwnershipMap::Decode(spec, m.get()));
  return client_->SetOwnership(std::move(m));
}

Status QueryProxy::DeltaSince(uint64_t from, uint64_t* epoch, bool* covered,
                              std::vector<NodeId>* ids) {
  if (client_) return client_->DeltaSince(from, epoch, covered, ids);
  if (!graph_ref_) return Status::Internal("proxy has no graph");
  *covered = graph_ref_->DirtySince(from, ids, epoch);
  if (!*covered) ids->clear();
  return Status::OK();
}

Status QueryProxy::RunGremlin(const std::string& query,
                              const std::map<std::string, Tensor>& inputs,
                              std::map<std::string, Tensor>* outputs) {
  auto t0 = std::chrono::steady_clock::now();
  Status st = RunGremlinTimed(query, inputs, outputs);
  uint64_t us = std::chrono::duration_cast<std::chrono::microseconds>(
                    std::chrono::steady_clock::now() - t0)
                    .count();
  queries_.fetch_add(1);
  if (!st.ok()) errors_.fetch_add(1);
  total_us_.fetch_add(us);
  last_us_.store(us);
  return st;
}

Status QueryProxy::RunGremlinTimed(const std::string& query,
                                   const std::map<std::string, Tensor>& inputs,
                                   std::map<std::string, Tensor>* outputs) {
  std::shared_ptr<const TranslateResult> plan;
  ET_RETURN_IF_ERROR(compiler_->Compile(query, &plan));
  // pin this run's snapshot (local mode): a concurrent delta swap must
  // not free the graph mid-execution, and has() filters must see an
  // index coherent with the graph they run against
  std::shared_ptr<const Graph> g;
  std::shared_ptr<IndexManager> idx;
  if (graph_ref_) {
    g = graph_ref_->get();
    if (index_ != nullptr || !index_spec_.empty()) {
      std::lock_guard<std::mutex> lk(index_mu_);
      if (g->epoch() != index_epoch_ && !index_spec_.empty()) {
        // lazy rebuild on epoch bump — a delta applied through the
        // shared GraphRef (capi etg_apply_delta) reaches this proxy here
        auto fresh = std::make_shared<IndexManager>();
        ET_RETURN_IF_ERROR(fresh->BuildFromSpec(*g, index_spec_));
        index_ = std::move(fresh);
        index_epoch_ = g->epoch();
      }
      idx = index_;
    }
  }
  OpKernelContext ctx;
  for (const auto& kv : inputs) ctx.Put(kv.first, kv.second);
  QueryEnv env;
  env.graph = g.get();
  env.index = idx.get();
  env.client = client_.get();
  env.pool = GlobalThreadPool();
  env.seed = seed_;
  env.nonce = run_counter_.fetch_add(1);
  // per-call deadline handoff (rpc.h): set by the capi on this thread
  // just before the run; REMOTE sub-calls stamp the remaining budget
  // into their v2 request frames. Consumed (read-and-cleared) so a
  // later deadline-less run on this thread never inherits it.
  env.deadline_us = TakeCallDeadlineUs();
  // ownership-map epoch captured ONCE per run (see QueryEnv.map_epoch:
  // a live read at frame-write time could stamp a newer epoch than the
  // map the split actually routed with)
  env.map_epoch = client_ ? client_->map_epoch() : 0;
  // wire trace context (rpc.h SetCallTrace): same handoff pattern as
  // the deadline — consumed so a later untraced run never inherits it
  WireTrace wt = TakeCallTrace();
  env.trace_id = wt.id;
  env.trace_parent = wt.parent;
  Executor exec(&plan->dag, env, &ctx);
  ET_RETURN_IF_ERROR(exec.RunSync());
  outputs->clear();
  for (const auto& alias : plan->aliases) {
    for (int i = 0;; ++i) {
      std::string name = alias + ":" + std::to_string(i);
      Tensor t;
      if (!ctx.Get(name, &t)) break;
      (*outputs)[name] = std::move(t);
    }
  }
  for (const auto& name : plan->last_outputs) {
    Tensor t;
    if (ctx.Get(name, &t)) (*outputs)[name] = std::move(t);
  }
  return Status::OK();
}

}  // namespace et
