// GQL: the Gremlin-style graph query language — lexer, parser, translator,
// optimizer, compile cache.
//
// Capability parity with the reference's euler/parser/ (SURVEY.md §2.1):
// the flex/bison grammar (gremlin.l/gremlin.y) is a hand-rolled lexer +
// recursive-descent parser here (same token set: v, e, sampleN, sampleE,
// sampleNWithTypes, outV, inV, sampleNB, sampleLNB, values, label, udf,
// has, hasKey, hasLabel, limit, orderBy, as, and/or, gt/ge/lt/le/eq/ne);
// Translator::Translate → translation to a DAGDef of API_* nodes with DNF
// conditions; Optimizer::Optimize → CSE, local fusion (FuseLocalPass —
// the reference's subgraph-iso fusion, optimizer.h:96, as a direct
// whole-plan collapse), and the distribute rewrite (split → per-shard
// REMOTE → merge, with unique/gather dedup — reference optimizer.h:51-121);
// Compiler::Compile → cached compilation keyed by query text (compiler.h:112).
//
// Query chains reference externally supplied input tensors by name:
//   v(roots).sampleNB(0, 10, -1).as(nb)         — roots: u64 ids input
//   sampleN(0, 128).values(f_dense).as(feat)
//   e(batch).values(price).as(p)                — batch:0/1/2 = src/dst/type
#ifndef EULER_TPU_GQL_H_
#define EULER_TPU_GQL_H_

#include <list>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "dag.h"

namespace et {

// One parsed call in a query chain: name + comma-separated args, each arg a
// list of whitespace-separated words (conditions keep and/or structure).
struct GqlCall {
  std::string name;
  std::vector<std::vector<std::string>> args;
};

Status ParseGql(const std::string& query, std::vector<GqlCall>* calls);

// Translate a parsed chain into an executable DAGDef (local form — no
// split/REMOTE/merge). Also reports the "as" aliases and the terminal
// output names so callers know what to fetch.
struct TranslateResult {
  DAGDef dag;
  std::vector<std::string> aliases;       // as() names, in order
  std::vector<std::string> last_outputs;  // terminal op's output tensors
};
Status TranslateGql(const std::vector<GqlCall>& calls, TranslateResult* out);

struct CompileOptions {
  int shard_num = 1;      // >1 + mode=distribute → shard rewrite
  int partition_num = 1;  // graph partition count (placement modulus)
  std::string mode = "local";  // "local" | "distribute"
  // Local-mode fusion: collapse the whole (sync-op) plan into one FUSED
  // node executed inline — removes per-op executor scheduling from the
  // hot sampling path. Env override: EULER_TPU_NO_FUSE=1 disables.
  bool fuse_local = true;
};

// Node shard placement. Data prep assigns partition p = id % P and shard k
// of n loads partitions p % n == k (euler_tpu/tools/generate_data.py,
// io.cc LoadShard) — so the owner of id is (id % P) % n.
inline int ShardOf(uint64_t id, int partition_num, int shard_num) {
  if (partition_num < shard_num) partition_num = shard_num;
  return static_cast<int>((id % static_cast<uint64_t>(partition_num)) %
                          static_cast<uint64_t>(shard_num));
}

// Optimizer passes over a translated DAG (in place):
//  - CommonSubexpressionElimination: dedup deterministic nodes
//  - DistributeRewrite: wrap graph-touching ops in split/REMOTE/merge
Status OptimizeDag(const CompileOptions& opts, DAGDef* dag);

// True for ops whose output is a pure function of (inputs, graph
// snapshot) — CSE-safe and result-reuse-safe. Sampling verbs are not.
bool IsDeterministicOp(const std::string& op);

// True when every node of the plan (FUSED groups included) is
// deterministic — the gate for the server-side result-reuse window and
// cross-request execute coalescing (rpc.h RpcConfig::reuse_window /
// coalesce_window_us): only a plan whose bytes-in fully determine its
// bytes-out may ever be answered from a cached or shared execution.
bool DagIsDeterministic(const DAGDef& dag);

// Per-pass rewrite counts from one OptimizePreparedPlan run — surfaced
// through RpcCounters::plan_rewrites_* so every rewrite is countable.
struct PlanOptStats {
  int fuse = 0;      // nodes collapsed into a FUSED group
  int pushdown = 0;  // filter / post-process nodes absorbed downstream
  int dedup = 0;     // duplicate deterministic sub-plans removed
};

// Prepare-time plan optimizer (the server side of kPrepare, rpc.cc):
// rewrites a REGISTERED execute plan in place, once per registration,
// so every later prepared kExecute runs the optimized form. Passes, in
// order: sub-plan dedup (CSE, protecting requested output names),
// filter/post-process pushdown (adjacent sole-consumer GET_NODE dnf
// chains, POST_PROCESS chains and ID_UNIQUE chains absorb their
// producer), and whole-plan fusion into one FUSED node (sample→gather
// hops execute inline — no per-op executor scheduling). Result parity:
// tensors keep their original names (also_produces) and seeded RNG
// streams hash node names, so optimized and verbatim plans produce
// identical bytes for identical feeds. `outputs` are the plan's
// requested output tensor names — their producers are never removed.
Status OptimizePreparedPlan(DAGDef* dag,
                            const std::vector<std::string>& outputs,
                            PlanOptStats* stats);

class GqlCompiler {
 public:
  explicit GqlCompiler(CompileOptions opts) : opts_(std::move(opts)) {}

  // Parse + translate + optimize, with a bounded LRU query-text cache
  // (same discipline as the server plan cache, rpc.h plan_cache: a
  // long-lived proxy fed an unbounded stream of distinct query strings
  // must not grow without limit; an evicted entry just recompiles).
  Status Compile(const std::string& query,
                 std::shared_ptr<const TranslateResult>* out);

  const CompileOptions& options() const { return opts_; }

  size_t cache_size() const;

  // Compiled-plan cache bound. Training loops cycle a handful of query
  // strings; 256 keeps every realistic working set resident.
  static constexpr size_t kCacheCap = 256;

 private:
  CompileOptions opts_;
  mutable std::mutex mu_;
  std::list<std::string> lru_;  // front = most recently used
  std::unordered_map<std::string,
                     std::pair<std::shared_ptr<const TranslateResult>,
                               std::list<std::string>::iterator>>
      cache_;
};

// Debug: render a DAG as indented text (op name, inputs, attrs, dnf, inner)
// — used by golden structure tests (reference compiler_test.cc style).
std::string DagToString(const DAGDef& dag);

}  // namespace et

#endif  // EULER_TPU_GQL_H_
