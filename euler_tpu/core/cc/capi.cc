// extern "C" surface of the graph engine, consumed via ctypes from
// euler_tpu.core.lib.
//
// Capability parity with the reference's ctypes entry points
// (euler/service/python_api.cc StartService, tf_euler/utils/
// init_query_proxy.cc) plus the per-op C++ kernels the TF custom ops used
// (SURVEY.md §2.2) — collapsed into one direct batch API: Python builds or
// loads a graph, then issues bulk numpy-backed calls. Fixed-shape ops write
// caller-allocated buffers; variable-shape ops fill an EtResult handle the
// caller copies out of and frees.
//
// Convention: functions return 0 on success, nonzero on error;
// etg_last_error() returns a thread-local message.
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>

#include "capi_internal.h"
#include "common.h"
#include "graph.h"
#include "io.h"
#include "ops.h"
#include "rpc.h"
#include "store.h"
#include "threadpool.h"
#include "udf.h"

namespace {

thread_local std::string g_last_error;

int Fail(const std::string& msg) {
  g_last_error = msg;
  return 1;
}

struct Registry {
  std::mutex mu;
  int64_t next = 1;
  std::unordered_map<int64_t, std::shared_ptr<et::GraphBuilder>> builders;
  // handle → swappable snapshot holder: etg_apply_delta swaps a new
  // immutable Graph in behind the same handle (streaming deltas), and
  // every proxy bound to the handle observes the swap
  std::unordered_map<int64_t, std::shared_ptr<et::GraphRef>> graphs;
};

Registry& Reg() {
  static Registry* r = new Registry();
  return *r;
}

// shared_ptr copies keep the object alive for the duration of a call even
// if another thread concurrently etg_free()s the handle (each Graph
// SNAPSHOT is immutable, so concurrent readers are safe by design; a
// delta apply publishes a new snapshot instead of mutating).
std::shared_ptr<et::GraphBuilder> GetBuilder(int64_t h) {
  auto& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  auto it = r.builders.find(h);
  return it == r.builders.end() ? nullptr : it->second;
}

std::shared_ptr<et::GraphRef> GetGraphRef(int64_t h) {
  auto& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  auto it = r.graphs.find(h);
  return it == r.graphs.end() ? nullptr : it->second;
}

std::shared_ptr<et::Graph> GetGraph(int64_t h) {
  auto ref = GetGraphRef(h);
  // const_cast is sound: every capi call on a finalized graph is const
  // (the builder API is the only mutating surface, and it has its own
  // handle space) — the cast just spares 60 call sites a type change
  return ref ? std::const_pointer_cast<et::Graph>(ref->get()) : nullptr;
}

int64_t RegisterGraph(std::shared_ptr<const et::Graph> g) {
  auto& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  int64_t h = r.next++;
  r.graphs[h] = std::make_shared<et::GraphRef>(std::move(g));
  return h;
}

}  // namespace

namespace et {
namespace capi {
// Shared with capi_query.cc: resolve a Python-held graph handle.
std::shared_ptr<Graph> GraphFromHandle(int64_t h) { return GetGraph(h); }
std::shared_ptr<GraphRef> GraphRefFromHandle(int64_t h) {
  return GetGraphRef(h);
}
int FailWith(const std::string& msg) { return Fail(msg); }
}  // namespace capi
}  // namespace et

extern "C" {

const char* etg_last_error() { return g_last_error.c_str(); }

void etg_seed(uint64_t seed) { et::SeedGlobalRng(seed); }

void etg_set_log_level(int level) { et::MinLogLevel() = level; }

// ---- builder ----
int64_t etg_builder_new() {
  auto& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  int64_t h = r.next++;
  r.builders[h] = std::make_shared<et::GraphBuilder>();
  return h;
}

int etg_builder_set_feature(int64_t b, int is_edge, int fid, int kind,
                            int64_t dim, const char* name) {
  auto builder = GetBuilder(b);
  if (!builder) return Fail("bad builder handle");
  if (fid < 0 || fid > 65535) return Fail("feature id out of range");
  auto* meta = builder->mutable_meta();
  auto& feats = is_edge ? meta->edge_features : meta->node_features;
  if (static_cast<size_t>(fid) >= feats.size()) feats.resize(fid + 1);
  feats[fid].name = name ? name : "";
  feats[fid].kind = static_cast<et::FeatureKind>(kind);
  feats[fid].dim = dim;
  return 0;
}

int etg_builder_set_num_types(int64_t b, int num_node_types,
                              int num_edge_types) {
  auto builder = GetBuilder(b);
  if (!builder) return Fail("bad builder handle");
  builder->mutable_meta()->num_node_types = num_node_types;
  builder->mutable_meta()->num_edge_types = num_edge_types;
  return 0;
}

// Named types (reference type_ops get_node_type_id/get_edge_type_id:
// data-prep declares type NAMES, training code refers to them by name).
int etg_builder_set_type_name(int64_t b, int edge, int type_id,
                              const char* name) {
  auto builder = GetBuilder(b);
  if (!builder) return Fail("bad builder handle");
  if (type_id < 0) return Fail("type_id must be >= 0");
  auto* names = edge ? &builder->mutable_meta()->edge_type_names
                     : &builder->mutable_meta()->node_type_names;
  if (static_cast<size_t>(type_id) >= names->size())
    names->resize(type_id + 1);
  (*names)[type_id] = name;
  return 0;
}

// name → type id; -1 when unknown (numeric strings resolve to their
// value like the reference's int passthrough).
int etg_type_id(int64_t h, int edge, const char* name) {
  auto g = GetGraph(h);
  if (!g) {
    Fail("bad graph handle");
    return -1;
  }
  const auto& names =
      edge ? g->meta().edge_type_names : g->meta().node_type_names;
  std::string want = name;
  for (size_t i = 0; i < names.size(); ++i)
    if (names[i] == want) return static_cast<int>(i);
  char* end = nullptr;
  long v = std::strtol(name, &end, 10);
  // bounds-checked numeric passthrough: an out-of-int-range string must
  // surface as unknown (-1 → Python KeyError), not wrap to a valid id
  if (end != name && *end == '\0' && v >= 0 && v <= INT32_MAX)
    return static_cast<int>(v);
  return -1;
}

int etg_type_name(int64_t h, int edge, int type_id, char* buf, int64_t cap) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  const auto& names =
      edge ? g->meta().edge_type_names : g->meta().node_type_names;
  std::string out = type_id >= 0 && static_cast<size_t>(type_id) < names.size()
                        ? names[type_id]
                        : std::to_string(type_id);
  std::snprintf(buf, static_cast<size_t>(cap), "%s", out.c_str());
  return 0;
}

int etg_builder_add_nodes(int64_t b, int64_t n, const uint64_t* ids,
                          const int32_t* types, const float* weights) {
  auto builder = GetBuilder(b);
  if (!builder) return Fail("bad builder handle");
  builder->AddNodes(ids, types, weights, static_cast<size_t>(n));
  return 0;
}

int etg_builder_add_edges(int64_t b, int64_t n, const uint64_t* src,
                          const uint64_t* dst, const int32_t* types,
                          const float* weights) {
  auto builder = GetBuilder(b);
  if (!builder) return Fail("bad builder handle");
  builder->AddEdges(src, dst, types, weights, static_cast<size_t>(n));
  return 0;
}

int etg_builder_set_node_dense(int64_t b, const uint64_t* ids, int64_t n,
                               int fid, int64_t dim, const float* values) {
  auto builder = GetBuilder(b);
  if (!builder) return Fail("bad builder handle");
  builder->SetNodeDenseBulk(ids, static_cast<size_t>(n), fid, dim, values);
  return 0;
}

int etg_builder_set_node_sparse(int64_t b, const uint64_t* ids, int64_t n,
                                int fid, const uint64_t* offsets,
                                const uint64_t* values) {
  auto builder = GetBuilder(b);
  if (!builder) return Fail("bad builder handle");
  builder->SetNodeSparseBulk(ids, static_cast<size_t>(n), fid, offsets,
                             values);
  return 0;
}

int etg_builder_set_node_binary(int64_t b, uint64_t id, int fid,
                                const char* data, int64_t len) {
  auto builder = GetBuilder(b);
  if (!builder) return Fail("bad builder handle");
  builder->SetNodeBinary(id, fid, data, len);
  return 0;
}

int etg_builder_set_edge_dense(int64_t b, const uint64_t* src,
                               const uint64_t* dst, const int32_t* types,
                               int64_t n, int fid, int64_t dim,
                               const float* values) {
  auto builder = GetBuilder(b);
  if (!builder) return Fail("bad builder handle");
  builder->SetEdgeDenseBulk(src, dst, types, static_cast<size_t>(n), fid, dim,
                            values);
  return 0;
}

int etg_builder_set_edge_sparse(int64_t b, uint64_t src, uint64_t dst,
                                int32_t type, int fid, const uint64_t* values,
                                int64_t len) {
  auto builder = GetBuilder(b);
  if (!builder) return Fail("bad builder handle");
  builder->SetEdgeSparse(src, dst, type, fid, values, len);
  return 0;
}

int etg_builder_set_edge_binary(int64_t b, uint64_t src, uint64_t dst,
                                int32_t type, int fid, const char* data,
                                int64_t len) {
  auto builder = GetBuilder(b);
  if (!builder) return Fail("bad builder handle");
  builder->SetEdgeBinary(src, dst, type, fid, data, len);
  return 0;
}

int64_t etg_builder_finalize(int64_t b, int build_in_adjacency) {
  auto& r = Reg();
  std::shared_ptr<et::GraphBuilder> builder;
  {
    std::lock_guard<std::mutex> lk(r.mu);
    auto it = r.builders.find(b);
    if (it == r.builders.end()) {
      Fail("bad builder handle");
      return -1;
    }
    builder = std::move(it->second);
    r.builders.erase(it);
  }
  std::shared_ptr<const et::Graph> g = builder->Finalize(
      build_in_adjacency != 0);
  return RegisterGraph(std::move(g));
}

// ---- load/dump ----
int64_t etg_load(const char* dir, int shard_idx, int shard_num, int data_type,
                 int build_in_adjacency) {
  std::unique_ptr<et::Graph> g;
  et::Status s = et::LoadShard(dir, shard_idx, shard_num, data_type,
                               build_in_adjacency != 0, &g);
  if (!s.ok()) {
    Fail(s.message());
    return -1;
  }
  return RegisterGraph(std::shared_ptr<const et::Graph>(std::move(g)));
}

int etg_dump(int64_t h, const char* dir, int num_partitions, int by_graph) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  et::Status s = et::DumpGraphPartitioned(*g, dir, num_partitions,
                                          by_graph != 0);
  return s.ok() ? 0 : Fail(s.message());
}

// ---- out-of-core columnar store (store.h) ----
// Serialize handle h's CURRENT snapshot into a columnar store file at
// `path` (atomic tmp+rename). The file is byte-parity with the graph's
// in-memory arrays — attaching it reproduces every sampler draw.
int etg_store_write(int64_t h, const char* path) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  et::Status s = et::WriteColumnarStore(*g, path ? path : "");
  return s.ok() ? 0 : Fail(s.message());
}

// mmap a columnar store and register the attached graph as a handle.
// hot_bytes = hub-pinned hot-set budget (0 = accounting only, nothing
// pinned). -1 on error.
int64_t etg_store_open(const char* path, int64_t hot_bytes) {
  std::unique_ptr<et::Graph> g;
  et::Status s = et::LoadGraphFromStore(path ? path : "", hot_bytes, &g);
  if (!s.ok()) {
    Fail(s.message());
    return -1;
  }
  return RegisterGraph(std::shared_ptr<const et::Graph>(std::move(g)));
}

// Process-global out-of-core counters (store.h slot order):
// 0 hot_hits | 1 cold_reads | 2 page_in | 3 page_out | 4 resident_bytes
// | 5 mapped_bytes | 6 hot_pinned_bytes | 7 attaches | 8 cold_n
// | 9 cold_sum_us | 10..34 cold-read log2-µs bucket counts (1µs..2^23µs
// + overflow, the trace-hist convention). Polls mincore residency.
void etg_store_stats(uint64_t* out) { et::StoreStatsSnapshot(out); }

int etg_free(int64_t h) {
  auto& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  r.graphs.erase(h);
  r.builders.erase(h);
  return 0;
}

// ---- introspection ----
int64_t etg_node_count(int64_t h) {
  auto g = GetGraph(h);
  return g ? static_cast<int64_t>(g->node_count()) : -1;
}
int64_t etg_edge_count(int64_t h) {
  auto g = GetGraph(h);
  return g ? static_cast<int64_t>(g->edge_count()) : -1;
}
int etg_num_node_types(int64_t h) {
  auto g = GetGraph(h);
  return g ? g->num_node_types() : -1;
}
int etg_num_edge_types(int64_t h) {
  auto g = GetGraph(h);
  return g ? g->num_edge_types() : -1;
}
int etg_num_node_features(int64_t h) {
  auto g = GetGraph(h);
  return g ? static_cast<int>(g->meta().node_features.size()) : -1;
}
int etg_num_edge_features(int64_t h) {
  auto g = GetGraph(h);
  return g ? static_cast<int>(g->meta().edge_features.size()) : -1;
}
// kind/dim of feature fid; returns 0 on success.
int etg_feature_info(int64_t h, int is_edge, int fid, int32_t* kind,
                     int64_t* dim, char* name_buf, int64_t name_cap) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  const auto& feats =
      is_edge ? g->meta().edge_features : g->meta().node_features;
  if (fid < 0 || static_cast<size_t>(fid) >= feats.size()) {
    return Fail("bad feature id");
  }
  *kind = static_cast<int32_t>(feats[fid].kind);
  *dim = feats[fid].dim;
  if (name_buf && name_cap > 0) {
    std::strncpy(name_buf, feats[fid].name.c_str(), name_cap - 1);
    name_buf[name_cap - 1] = '\0';
  }
  return 0;
}

int etg_all_node_ids(int64_t h, uint64_t* out) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  for (size_t i = 0; i < g->node_count(); ++i) {
    out[i] = g->node_id(static_cast<uint32_t>(i));
  }
  return 0;
}

// Batch id → engine row (int32). Unknown ids (incl. the default pad id)
// map to `missing` — callers indexing a device feature table pass the
// index of a dedicated zero pad row so padded neighbor slots contribute
// zeros, matching GetDenseFeature's unknown-id behavior. Row-native
// feeding skips the host-side id translation entirely — the hot path for
// DeviceFeatureStore training input.
int etg_node_rows(int64_t h, const uint64_t* ids, int64_t n, int32_t missing,
                  int32_t* out) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  et::ParallelFor(et::GlobalThreadPool(), n, 8192,
                  [&](int64_t b, int64_t e, int) {
                    for (int64_t i = b; i < e; ++i) {
                      uint32_t row = g->NodeIndex(ids[i]);
                      out[i] = row == et::kInvalidIndex
                                   ? missing
                                   : static_cast<int32_t>(row);
                    }
                  });
  return 0;
}

int etg_all_node_weights(int64_t h, float* out) {
  // engine-row order (matches etg_all_node_ids) — backs device-resident
  // weighted global sampling (DeviceNodeSampler)
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  for (size_t i = 0; i < g->node_count(); ++i) {
    out[i] = g->node_weight(static_cast<uint32_t>(i));
  }
  return 0;
}

int etg_node_weight_sums(int64_t h, float* out) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  const auto& v = g->node_type_weight_sums();
  std::memcpy(out, v.data(), v.size() * sizeof(float));
  return 0;
}

int etg_edge_weight_sums(int64_t h, float* out) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  const auto& v = g->edge_type_weight_sums();
  std::memcpy(out, v.data(), v.size() * sizeof(float));
  return 0;
}

// ---- sampling ----
int etg_sample_node(int64_t h, int type, int64_t count, uint64_t* out) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  g->SampleNode(type, static_cast<size_t>(count), &et::ThreadLocalRng(), out);
  return 0;
}

int etg_sample_node_with_types(int64_t h, const int32_t* types, int64_t count,
                               uint64_t* out) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  g->SampleNodeWithTypes(types, static_cast<size_t>(count),
                         &et::ThreadLocalRng(), out);
  return 0;
}

int etg_sample_edge(int64_t h, int type, int64_t count, uint64_t* out_src,
                    uint64_t* out_dst, int32_t* out_type) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  g->SampleEdge(type, static_cast<size_t>(count), &et::ThreadLocalRng(),
                out_src, out_dst, out_type);
  return 0;
}

int etg_get_node_type(int64_t h, const uint64_t* ids, int64_t n,
                      int32_t* out) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  for (int64_t i = 0; i < n; ++i) {
    uint32_t idx = g->NodeIndex(ids[i]);
    out[i] = idx == et::kInvalidIndex ? -1 : g->node_type(idx);
  }
  return 0;
}

int etg_sample_neighbor(int64_t h, const uint64_t* ids, int64_t n,
                        const int32_t* edge_types, int64_t n_et, int64_t count,
                        uint64_t default_id, uint64_t* out_ids, float* out_w,
                        int32_t* out_t) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  auto& rng = et::ThreadLocalRng();
  size_t k = static_cast<size_t>(count);
  for (int64_t i = 0; i < n; ++i) {
    g->SampleNeighbor(ids[i], edge_types, static_cast<size_t>(n_et), k,
                      default_id, &rng, out_ids + i * k,
                      out_w ? out_w + i * k : nullptr,
                      out_t ? out_t + i * k : nullptr);
  }
  return 0;
}

int etg_sample_in_neighbor(int64_t h, const uint64_t* ids, int64_t n,
                           const int32_t* edge_types, int64_t n_et,
                           int64_t count, uint64_t default_id,
                           uint64_t* out_ids, float* out_w, int32_t* out_t) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  auto& rng = et::ThreadLocalRng();
  size_t k = static_cast<size_t>(count);
  for (int64_t i = 0; i < n; ++i) {
    g->SampleInNeighbor(ids[i], edge_types, static_cast<size_t>(n_et), k,
                        default_id, &rng, out_ids + i * k,
                        out_w ? out_w + i * k : nullptr,
                        out_t ? out_t + i * k : nullptr);
  }
  return 0;
}

int etg_get_top_k_neighbor(int64_t h, const uint64_t* ids, int64_t n,
                           const int32_t* edge_types, int64_t n_et, int64_t k,
                           uint64_t default_id, uint64_t* out_ids,
                           float* out_w, int32_t* out_t) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  size_t kk = static_cast<size_t>(k);
  for (int64_t i = 0; i < n; ++i) {
    g->GetTopKNeighbor(ids[i], edge_types, static_cast<size_t>(n_et), kk,
                       default_id, out_ids + i * kk, out_w + i * kk,
                       out_t + i * kk);
  }
  return 0;
}

int etg_sample_fanout(int64_t h, const uint64_t* roots, int64_t n_roots,
                      const int32_t* counts, int64_t n_hops,
                      const int32_t* edge_types, const int64_t* et_offsets,
                      uint64_t default_id, uint64_t** out_ids, float** out_w,
                      int32_t** out_t) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  std::vector<et::NodeId*> ids(n_hops);
  std::vector<float*> ws(n_hops);
  std::vector<int32_t*> ts(n_hops);
  for (int64_t i = 0; i < n_hops; ++i) {
    ids[i] = out_ids[i];
    ws[i] = out_w[i];
    ts[i] = out_t[i];
  }
  et::SampleFanout(*g, roots, static_cast<size_t>(n_roots), counts,
                   static_cast<size_t>(n_hops), edge_types, et_offsets,
                   default_id, &et::ThreadLocalRng(), ids, ws, ts);
  return 0;
}

int etg_random_walk(int64_t h, const uint64_t* roots, int64_t n, int64_t len,
                    float p, float q, uint64_t default_id,
                    const int32_t* edge_types, int64_t n_et, uint64_t* out) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  et::RandomWalk(*g, roots, static_cast<size_t>(n), static_cast<size_t>(len),
                 p, q, default_id, edge_types, static_cast<size_t>(n_et),
                 &et::ThreadLocalRng(), out);
  return 0;
}

int etg_sample_layerwise(int64_t h, const uint64_t* roots, int64_t n_roots,
                         const int32_t* layer_sizes, int64_t n_layers,
                         const int32_t* edge_types, int64_t n_et,
                         uint64_t default_id, int weight_func,
                         uint64_t** out_layers) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  if (weight_func < 0 || weight_func > 1)
    return Fail("weight_func must be 0 (identity) or 1 (sqrt)");
  std::vector<et::NodeId*> layers(n_layers);
  for (int64_t i = 0; i < n_layers; ++i) layers[i] = out_layers[i];
  et::SampleLayerwise(*g, roots, static_cast<size_t>(n_roots), layer_sizes,
                      static_cast<size_t>(n_layers), edge_types,
                      static_cast<size_t>(n_et), default_id,
                      &et::ThreadLocalRng(), layers,
                      static_cast<et::LayerWeightFunc>(weight_func));
  return 0;
}

// ---- features ----
int etg_get_dense_feature(int64_t h, const uint64_t* ids, int64_t n, int fid,
                          int64_t dim, float* out) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  g->GetDenseFeature(ids, static_cast<size_t>(n), fid, dim, out);
  return 0;
}

int etg_get_edge_dense_feature(int64_t h, const uint64_t* src,
                               const uint64_t* dst, const int32_t* types,
                               int64_t n, int fid, int64_t dim, float* out) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  g->GetEdgeDenseFeature(src, dst, types, static_cast<size_t>(n), fid, dim,
                         out);
  return 0;
}

// ---- variable-size results ----
EtResult* etres_new() { return new EtResult(); }
void etres_free(EtResult* r) { delete r; }
int64_t etres_offsets_len(EtResult* r) {
  return static_cast<int64_t>(r->offsets.size());
}
const uint64_t* etres_offsets(EtResult* r) { return r->offsets.data(); }
int64_t etres_u64_len(EtResult* r) { return static_cast<int64_t>(r->u64.size()); }
const uint64_t* etres_u64(EtResult* r) { return r->u64.data(); }
int64_t etres_f32_len(EtResult* r) { return static_cast<int64_t>(r->f32.size()); }
const float* etres_f32(EtResult* r) { return r->f32.data(); }
int64_t etres_i32_len(EtResult* r) { return static_cast<int64_t>(r->i32.size()); }
const int32_t* etres_i32(EtResult* r) { return r->i32.data(); }
int64_t etres_bytes_len(EtResult* r) {
  return static_cast<int64_t>(r->bytes.size());
}
const char* etres_bytes(EtResult* r) { return r->bytes.data(); }

// ---- whole-graph labels (graph classification; reference
// sample_graph_label_op / get_graph_by_label_op) ----
int etg_builder_set_graph_labels(int64_t h, const uint64_t* ids,
                                 const uint64_t* labels, int64_t n) {
  auto b = GetBuilder(h);
  if (!b) return Fail("bad builder handle");
  b->SetGraphLabels(ids, labels, static_cast<size_t>(n));
  return 0;
}

int64_t etg_graph_label_count(int64_t h) {
  auto g = GetGraph(h);
  if (!g) return -1;
  return static_cast<int64_t>(g->graph_label_count());
}

int etg_sample_graph_label(int64_t h, int64_t count, uint64_t* out) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  g->SampleGraphLabel(static_cast<size_t>(count), &et::ThreadLocalRng(), out);
  return 0;
}

// Ragged: per input label, the node ids of that graph (empty if unknown).
int etg_get_graph_by_label(int64_t h, const uint64_t* labels, int64_t n,
                           EtResult* res) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  res->offsets.assign(1, 0);
  res->u64.clear();
  for (int64_t i = 0; i < n; ++i) {
    const std::vector<uint32_t>* rows = g->GraphNodes(labels[i]);
    if (rows != nullptr)
      for (uint32_t r : *rows) res->u64.push_back(g->node_id(r));
    res->offsets.push_back(res->u64.size());
  }
  return 0;
}

int etg_get_full_neighbor(int64_t h, const uint64_t* ids, int64_t n,
                          const int32_t* edge_types, int64_t n_et,
                          int sorted_by_id, int in_edges, EtResult* res) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  res->offsets.assign(1, 0);
  res->u64.clear();
  res->f32.clear();
  res->i32.clear();
  std::vector<et::NodeId> ids_v;
  std::vector<float> ws_v;
  std::vector<int32_t> ts_v;
  for (int64_t i = 0; i < n; ++i) {
    ids_v.clear();
    ws_v.clear();
    ts_v.clear();
    if (in_edges) {
      g->GetFullInNeighbor(ids[i], edge_types, static_cast<size_t>(n_et),
                           &ids_v, &ws_v, &ts_v);
    } else {
      g->GetFullNeighbor(ids[i], edge_types, static_cast<size_t>(n_et), &ids_v,
                         &ws_v, &ts_v, sorted_by_id != 0);
    }
    res->u64.insert(res->u64.end(), ids_v.begin(), ids_v.end());
    res->f32.insert(res->f32.end(), ws_v.begin(), ws_v.end());
    res->i32.insert(res->i32.end(), ts_v.begin(), ts_v.end());
    res->offsets.push_back(res->u64.size());
  }
  return 0;
}

int etg_get_sparse_feature(int64_t h, const uint64_t* ids, int64_t n, int fid,
                           EtResult* res) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  res->offsets.clear();
  res->u64.clear();
  g->GetSparseFeature(ids, static_cast<size_t>(n), fid, &res->offsets,
                      &res->u64);
  return 0;
}

int etg_get_binary_feature(int64_t h, const uint64_t* ids, int64_t n, int fid,
                           EtResult* res) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  res->offsets.clear();
  res->bytes.clear();
  g->GetBinaryFeature(ids, static_cast<size_t>(n), fid, &res->offsets,
                      &res->bytes);
  return 0;
}

int etg_get_edge_sparse_feature(int64_t h, const uint64_t* src,
                                const uint64_t* dst, const int32_t* types,
                                int64_t n, int fid, EtResult* res) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  res->offsets.clear();
  res->u64.clear();
  g->GetEdgeSparseFeature(src, dst, types, static_cast<size_t>(n), fid,
                          &res->offsets, &res->u64);
  return 0;
}

int etg_get_edge_binary_feature(int64_t h, const uint64_t* src,
                                const uint64_t* dst, const int32_t* types,
                                int64_t n, int fid, EtResult* res) {
  auto g = GetGraph(h);
  if (!g) return Fail("bad graph handle");
  res->offsets.clear();
  res->bytes.clear();
  g->GetEdgeBinaryFeature(src, dst, types, static_cast<size_t>(n), fid,
                          &res->offsets, &res->bytes);
  return 0;
}

// ---- RPC transport config / counters (protocol v2 mux) ----
// Process-global transport knobs; applies to graph-service channels
// created afterwards (engines built after the call). Negative values
// leave the corresponding knob unchanged.
void etg_rpc_config(int mux, int mux_connections, int64_t compress_threshold,
                    int max_inflight, int64_t hedge_delay_us, int p2c,
                    int hedge_replicas, int prepared, int plan_cache,
                    int deflate_reuse, int plan_optimize,
                    int64_t coalesce_window_us, int reuse_window) {
  auto& c = et::GlobalRpcConfig();
  if (mux >= 0) c.mux = mux != 0;
  if (mux_connections > 0) c.mux_connections = mux_connections;
  if (compress_threshold >= 0) c.compress_threshold = compress_threshold;
  if (max_inflight > 0) c.max_inflight = max_inflight;
  if (hedge_delay_us >= 0) c.hedge_delay_us = hedge_delay_us;
  if (p2c >= 0) c.p2c = p2c != 0;
  if (hedge_replicas >= 0) c.hedge_replicas = hedge_replicas != 0;
  // wire path (prepared query plans + reply/deflate reuse knobs)
  if (prepared >= 0) c.prepared = prepared != 0;
  if (plan_cache > 0) c.plan_cache = plan_cache;
  if (deflate_reuse >= 0) c.deflate_reuse = deflate_reuse != 0;
  // plan optimizer + deterministic fast paths (server side)
  if (plan_optimize >= 0) c.plan_optimize = plan_optimize != 0;
  if (coalesce_window_us >= 0) c.coalesce_window_us = coalesce_window_us;
  if (reuse_window >= 0) c.reuse_window = reuse_window;
}

// Per-thread deadline handoff for the NEXT query run on this thread
// (remaining budget in ms; <= 0 clears). Set just before etq_exec_run;
// QueryProxy consumes it into the run's QueryEnv so REMOTE sub-calls
// stamp the remaining budget into their v2 request frames.
void etg_set_call_deadline_ms(double remaining_ms) {
  et::SetCallDeadlineUs(
      remaining_ms > 0
          ? et::SteadyNowUs() + static_cast<int64_t>(remaining_ms * 1000.0)
          : 0);
}

// out[27]: round_trips, bytes_sent, bytes_received, bytes_sent_raw,
// bytes_received_raw, connections_opened, compressed_frames_sent,
// compressed_frames_received, mux_calls, v1_calls, hello_fallbacks,
// inflight (gauge), deadline_propagated, deadline_shed (server edge),
// hedge_fired, hedge_won, hedge_wasted, stale_map_shed (server edge),
// replica_hedge_fired, replica_hedge_won, replica_hedge_wasted,
// trace_propagated, prepared_registered, prepared_hits,
// prepared_misses, prepared_invalidated (all four server edge),
// prepared_fallbacks (client edge), plan_optimized, plan_rewrites_fuse,
// plan_rewrites_pushdown, plan_rewrites_dedup, plan_rewrites_epoch,
// coalesced_requests, coalesce_batches, reuse_hits, reuse_misses,
// reuse_invalidated (the last ten all server edge — plan optimizer +
// deterministic fast paths). out is 37 slots.
// Client-edge accounting except the *_shed pair, the prepared plan
// cache counters, and the optimizer/fast-path block (see RpcCounters).
void etg_rpc_stats(uint64_t* out) {
  auto& c = et::GlobalRpcCounters();
  out[0] = c.round_trips.load();
  out[1] = c.bytes_sent.load();
  out[2] = c.bytes_received.load();
  out[3] = c.bytes_sent_raw.load();
  out[4] = c.bytes_received_raw.load();
  out[5] = c.connections_opened.load();
  out[6] = c.compressed_frames_sent.load();
  out[7] = c.compressed_frames_received.load();
  out[8] = c.mux_calls.load();
  out[9] = c.v1_calls.load();
  out[10] = c.hello_fallbacks.load();
  out[11] = static_cast<uint64_t>(std::max<int64_t>(c.inflight.load(), 0));
  out[12] = c.deadline_propagated.load();
  out[13] = c.deadline_shed.load();
  out[14] = c.hedge_fired.load();
  out[15] = c.hedge_won.load();
  out[16] = c.hedge_wasted.load();
  out[17] = c.stale_map_shed.load();
  out[18] = c.replica_hedge_fired.load();
  out[19] = c.replica_hedge_won.load();
  out[20] = c.replica_hedge_wasted.load();
  out[21] = c.trace_propagated.load();
  out[22] = c.prepared_registered.load();
  out[23] = c.prepared_hits.load();
  out[24] = c.prepared_misses.load();
  out[25] = c.prepared_invalidated.load();
  out[26] = c.prepared_fallbacks.load();
  out[27] = c.plan_optimized.load();
  out[28] = c.plan_rewrites_fuse.load();
  out[29] = c.plan_rewrites_pushdown.load();
  out[30] = c.plan_rewrites_dedup.load();
  out[31] = c.plan_rewrites_epoch.load();
  out[32] = c.coalesced_requests.load();
  out[33] = c.coalesce_batches.load();
  out[34] = c.reuse_hits.load();
  out[35] = c.reuse_misses.load();
  out[36] = c.reuse_invalidated.load();
}

// Per-thread wire-trace handoff for the NEXT query run on this thread
// (trace_id 0 clears). Set just before etq_exec_run; QueryProxy
// consumes it into the run's QueryEnv so every REMOTE sub-call stamps
// the context into its v2 request frame (hello-negotiated kFeatTrace).
void etg_set_call_trace(uint64_t trace_id, uint64_t parent_span) {
  et::SetCallTrace(trace_id, parent_span);
}

// Server-side per-request timing histograms (ServerTraceStats, always
// on). verb slot: 0 execute, 1 apply_delta, 2 get_delta,
// 3 get_delta_log, 4 set_ownership, 5 meta. phase: 0 queue-wait,
// 1 decode, 2 execute, 3 serialize (non-execute verbs record queue +
// execute only). out[27] = n, sum_us, counts[25] over log2-µs bounds
// 1µs..2^23µs + overflow (le-inclusive, the obs bucket convention).
int etg_server_trace_hist(int verb, int phase, uint64_t* out) {
  if (!et::GlobalServerTraceStats().HistSnapshot(verb, phase, &out[0],
                                                 &out[1], out + 2))
    return Fail("bad verb/phase index");
  return 0;
}

// Drain the bounded server span ring (requests that carried a wire
// trace id): res->u64 holds stride-10 records
// [trace_id, parent_span, span_id, verb, flags, start_unix_us,
//  queue_us, decode_us, exec_us, serialize_us]. Read-and-clear — the
// harness dumps once per run; flags: bit0 deadline-shed, bit1
// stale-map-shed, bit2 non-OK status.
int etg_server_trace_dump(EtResult* res) {
  std::vector<et::ServerTraceRecord> recs;
  et::GlobalServerTraceStats().Drain(&recs);
  res->offsets.clear();
  res->f32.clear();
  res->i32.clear();
  res->bytes.clear();
  res->u64.clear();
  res->u64.reserve(recs.size() * 10);
  for (const auto& r : recs) {
    res->u64.push_back(r.trace_id);
    res->u64.push_back(r.parent_span);
    res->u64.push_back(r.span_id);
    res->u64.push_back(r.verb);
    res->u64.push_back(r.flags);
    res->u64.push_back(static_cast<uint64_t>(r.start_unix_us));
    res->u64.push_back(r.queue_us);
    res->u64.push_back(r.decode_us);
    res->u64.push_back(r.exec_us);
    res->u64.push_back(r.serialize_us);
  }
  return 0;
}

// Push an ownership-map spec to one graph server over the admin verb
// (kSetOwnership) — the elastic driver's per-shard flip. Returns 0 and
// writes the installed epoch to *out_epoch on success.
int etg_push_ownership(const char* host, int port, const char* spec,
                       int64_t* out_epoch) {
  uint64_t e = 0;
  et::Status s = et::PushOwnership(host ? host : "", port,
                                   spec ? spec : "", &e);
  if (!s.ok()) return Fail(s.message());
  if (out_epoch != nullptr) *out_epoch = static_cast<int64_t>(e);
  return 0;
}

// out[8]: wal appends, fsyncs, replayed_records, compactions,
// catchup_deltas, refused, torn_records, degraded (gauge: the NUMBER
// of degraded wal instances in this process). Process-global
// durability counters (wal.h WalCounters) — the obs registry mirrors
// them as wal_*_total gauges (euler_tpu.gql wal_stats()).
void etg_wal_stats(uint64_t* out) {
  auto& c = et::GlobalWalCounters();
  out[0] = c.appends.load();
  out[1] = c.fsyncs.load();
  out[2] = c.replayed_records.load();
  out[3] = c.compactions.load();
  out[4] = c.catchup_deltas.load();
  out[5] = c.refused.load();
  out[6] = c.torn_records.load();
  out[7] = static_cast<uint64_t>(std::max<int64_t>(c.degraded.load(), 0));
}

// ---- streaming deltas (graph epoch + O(delta) maintenance) ----
// Current epoch of the handle's snapshot (0 = as-finalized; each
// etg_apply_delta bumps it). -1 on a bad handle.
int64_t etg_graph_epoch(int64_t h) {
  auto ref = GetGraphRef(h);
  if (!ref) {
    Fail("bad graph handle");
    return -1;
  }
  return static_cast<int64_t>(ref->epoch());
}

// Batched delta apply on an embedded graph handle: add/update nodes and
// edges through the builder machinery, rebuild an immutable snapshot
// off-path, swap it in behind the handle (queries bound to the handle
// see it; in-flight executions finish on the old snapshot), record the
// per-epoch dirty set, and orphan the old snapshot's UDF-cache entries.
// out_epoch gets the new epoch.
int etg_apply_delta(int64_t h, int64_t n_nodes, const uint64_t* node_ids,
                    const int32_t* node_types, const float* node_weights,
                    int64_t n_edges, const uint64_t* edge_src,
                    const uint64_t* edge_dst, const int32_t* edge_types,
                    const float* edge_weights, int64_t* out_epoch) {
  auto ref = GetGraphRef(h);
  if (!ref) return Fail("bad graph handle");
  // per-ref apply serialization: queues concurrent applies on THIS
  // graph (through any surface sharing the ref) without blocking
  // applies on unrelated graph handles
  std::lock_guard<std::mutex> lk(ref->apply_mutex());
  auto base = ref->get();
  std::unique_ptr<et::Graph> next;
  std::vector<et::NodeId> dirty;
  et::Status s = et::ApplyGraphDelta(
      *base, node_ids, node_types, node_weights,
      static_cast<size_t>(n_nodes), edge_src, edge_dst, edge_types,
      edge_weights, static_cast<size_t>(n_edges), /*shard_idx=*/0,
      /*shard_num=*/1, &next, &dirty);
  if (!s.ok()) return Fail(s.message());
  if (out_epoch != nullptr)
    *out_epoch = static_cast<int64_t>(next->epoch());
  if (!ref->SwapFrom(base, std::shared_ptr<const et::Graph>(std::move(next)),
                     std::move(dirty)))
    return Fail("concurrent delta apply on this graph; retry");
  et::UdfResultCache::Instance().EvictGraph(base->uid());
  return 0;
}

// Dirty-node union for epochs > from_epoch on an embedded handle.
// res->u64 gets the sorted unique ids; *out_epoch the covered-up-to
// epoch; *out_covered 0 when the bounded history no longer reaches
// from_epoch (treat everything as dirty).
int etg_delta_since(int64_t h, int64_t from_epoch, EtResult* res,
                    int64_t* out_epoch, int32_t* out_covered) {
  auto ref = GetGraphRef(h);
  if (!ref) return Fail("bad graph handle");
  std::vector<et::NodeId> ids;
  uint64_t epoch = 0;
  bool covered =
      ref->DirtySince(static_cast<uint64_t>(from_epoch), &ids, &epoch);
  res->u64.assign(ids.begin(), ids.end());
  res->offsets.clear();
  res->f32.clear();
  res->i32.clear();
  res->bytes.clear();
  if (out_epoch != nullptr) *out_epoch = static_cast<int64_t>(epoch);
  if (out_covered != nullptr) *out_covered = covered ? 1 : 0;
  return 0;
}

// Cumulative UDF result-cache entries dropped by epoch bumps (the
// udf_cache_epoch_evictions_total obs counter reads this).
uint64_t etg_udf_cache_epoch_evictions() {
  return et::UdfResultCache::Instance().EpochEvictions();
}

// 64-bit string hash for Python data-prep id mapping (parity:
// euler/util/python_api.cc py_hash64 — tools hash string node ids into
// u64). FNV-1a: stable across platforms/runs, unlike Python's hash().
uint64_t etg_hash64(const char* data, uint64_t size) {
  uint64_t h = 1469598103934665603ULL;
  for (uint64_t i = 0; i < size; ++i)
    h = (h ^ static_cast<unsigned char>(data[i])) * 1099511628211ULL;
  return h;
}

}  // extern "C"
