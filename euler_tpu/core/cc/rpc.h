// Distributed graph service: framed-TCP RPC server, retrying client,
// pluggable discovery, per-shard client manager.
//
// Capability parity with the reference's euler/service/ (gRPC async server,
// grpc_worker.cc:40-96 ExecuteAsync) + euler/client/ (RpcClient with retry
// kRpcRetryCount=10, RpcManager round-robin channel bookkeeping,
// ClientManager per-shard table — SURVEY.md §2.1) + ZooKeeper discovery
// (zk_server_monitor/register). Redesigned without external deps: the
// transport is length-prefixed frames over TCP (the payloads are the serde
// wire format), the server runs one acceptor + per-connection reader
// threads that execute requests on the shared executor thread pool, and
// discovery is a shared-filesystem registry directory (each server writes
// an ephemeral-ish "shard_<i>__<host>_<port>" file; clients list the
// directory) with a static "hosts=" fallback — ZooKeeper semantics on
// plain files, fitting one-host tests and multi-host NFS deployments.
//
// Frame v1: u32 'ETFR' | u32 msg_type | u64 body_len | body
// Frame v2: u32 'ETF2' | u32 msg_type | u32 flags | u64 request_id
//         | u64 body_len | body        (flags bit 0: body zlib-deflated,
//           laid out as u64 raw_len | deflate stream; flags bit 1:
//           reply body prefixed with the serving graph's u64 epoch —
//           hello-negotiated, applied before compression; flags bit 2:
//           REQUEST body prefixed with the caller's remaining deadline
//           as u64 µs — hello-negotiated (kFeatDeadline), applied
//           before compression; the server sheds a kExecute whose
//           deadline expired before dispatch pickup; flags bit 4:
//           REQUEST body prefixed with the caller's wire trace context
//           as u64 trace_id | u64 parent_span — hello-negotiated
//           (kFeatTrace), after the deadline and map-epoch prefixes;
//           the server's per-request timing breakdown records it so a
//           merged chrome trace stitches shard time under the client
//           span)
// msg types: 0 = Execute, 1 = ShardMeta, 2 = Ping, 6 = Hello (v2 only),
//            7 = ApplyDelta, 8 = GetDelta (streaming graph deltas),
//            9 = GetDeltaLog (raw retained delta records — the
//                anti-entropy catch-up source for recovering shards),
//            11 = Prepare (v2 only: register a content-hashed execute
//                 plan in the connection's bounded plan LRU; flags bit
//                 5 then marks a kExecute REQUEST whose body is a u64
//                 plan id + feed tensors only — hello-negotiated
//                 kFeatPrepared, the read-hot-path decode/bytes saver).
//
// v2 is negotiated per connection: a v2 client opens with a Hello frame
// carrying (version, feature bits, compress threshold); a v2 server
// answers Hello and from then on serves that connection PIPELINED —
// requests dispatch to the executor and replies return out-of-order,
// correlated by request_id, under a per-connection write lock. A v1
// server closes on the unknown magic, which the client takes as "speak
// v1" and falls back to the classic one-frame-per-connection-at-a-time
// path; v1 clients ('ETFR' frames) are served byte-for-byte as before.
#ifndef EULER_TPU_RPC_H_
#define EULER_TPU_RPC_H_

#include <atomic>
#include <condition_variable>
#include <deque>
#include <functional>
#include <list>
#include <map>
#include <unordered_map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common.h"
#include "dag.h"
#include "graph.h"
#include "index.h"
#include "serde.h"
#include "wal.h"

namespace et {

// ---------------------------------------------------------------------------
// Transport config + counters (protocol v2 mux / adaptive compression).
// ---------------------------------------------------------------------------
// Process-global transport knobs. Applies to GRAPH-SERVICE channels
// created AFTER a change (ClientManager::Init / registry re-resolution);
// registry channels always speak v1 (tiny frames, nothing to win).
// Fields are atomic: etg_rpc_config may run while live server readers
// (per-request dispatch cap) and channel builders read them.
struct RpcConfig {
  // Multiplex graph channels: one v2 connection carries many in-flight
  // requests (CallAsync + demux reader) instead of one blocking fd per
  // concurrent call.
  std::atomic<bool> mux{false};
  // Mux connections per channel endpoint (in-flight calls round-robin
  // over them). Wire fd count per shard == this, regardless of depth.
  std::atomic<int> mux_connections{1};
  // > 0: zlib level-1 deflate frame bodies >= this many bytes (both
  // directions, negotiated in the hello; a frame that doesn't shrink is
  // sent raw — the flag bit says which). 0 disables.
  std::atomic<int64_t> compress_threshold{0};
  // Per-mux-connection in-flight cap: callers block before writing the
  // next request past this depth (server mirrors it as a dispatch
  // bound), so a runaway feeder cannot queue unbounded server work.
  std::atomic<int> max_inflight{256};
  // > 0: a sync mux kExecute call whose reply has not arrived after
  // this delay fires a HEDGE — the same request on a DIFFERENT mux
  // connection of the channel; the first reply wins and the loser is
  // abandoned by request_id (its late reply is discarded at the demux
  // reader). Needs mux_connections >= 2 to have a second wire path.
  // 0 (default) disables — the data path is byte-identical to pre-
  // hedging builds. The adaptive delay is computed on the Python side
  // from the obs latency histograms (remote.py) and pushed here.
  std::atomic<int64_t> hedge_delay_us{0};
  // Power-of-two-choices mux connection selection: pick two random
  // slots and use the one with the lower (inflight, EWMA latency)
  // score instead of blind round-robin — a stalled connection stops
  // attracting new calls. Default off (rotation, the pre-p2c path).
  std::atomic<bool> p2c{false};
  // Hedge straggling kExecute calls across graph-shard REPLICAS: when
  // the ownership map lists another shard whose owned partitions cover
  // the target's (a replicated hot partition / a full replica),
  // ClientManager::Execute races the same request against it past
  // hedge_delay_us without a reply — first reply wins, the loser's
  // blocking leg finishes on its own thread and is discarded (counted
  // replica_hedge_wasted). Needs an installed OwnershipMap with a
  // covering alternative owner and hedge_delay_us > 0. Default off.
  std::atomic<bool> hedge_replicas{false};
  // Prepared-plan execution (hello feature kFeatPrepared): register
  // each distinct kExecute plan (inner DAG + output names) once per
  // connection via kPrepare, keyed by its content hash, then stamp
  // subsequent kExecute frames with the plan id and ship ONLY the feed
  // tensors. A server that does not know the id answers an explicit
  // counted miss status and the client re-prepares (or falls back to
  // the classic full-plan frame) — never a silent wrong-plan execute.
  // Default off: the wire is byte-identical to pre-prepared builds.
  std::atomic<bool> prepared{false};
  // Server-side bound on the per-connection LRU of decoded plans. An
  // evicted plan is a counted miss on its next use; the client
  // re-prepares and converges.
  std::atomic<int> plan_cache{64};
  // Reuse one zlib deflate state per connection writer (deflateReset
  // between frames) instead of a full per-frame init. Identical output
  // bytes (same level/window/strategy); off restores the per-frame
  // compress2 path for A/B.
  std::atomic<bool> deflate_reuse{true};
  // ---- plan optimizer / execute coalescing / result reuse ----
  // Run the prepare-time plan optimizer (gql.h OptimizePreparedPlan) on
  // every kPrepare registration: CSE sub-plan dedup, filter/post-process
  // pushdown, whole-plan fusion. Pure server-side — the wire and the
  // reply bytes are identical with it on or off (optimized plans keep
  // tensor names via also_produces, and RNG streams hash node names).
  std::atomic<bool> plan_optimize{true};
  // > 0: cross-request execute coalescing — a prepared kExecute of a
  // DETERMINISTIC plan holds for up to this many µs collecting other
  // requests with the same (plan id, graph epoch, feed bytes) — across
  // connections, via the shared plan store — then executes ONCE and
  // answers every coalesced request from that single run (each gets its
  // own reply frame). The MicroBatcher pattern (serving/batcher.py)
  // applied to the graph tier. 0 (default) disables: per-request
  // execution, byte-identical to pre-coalescing builds.
  std::atomic<int64_t> coalesce_window_us{0};
  // > 0: bounded server-side result-reuse window (entry count, LRU) for
  // DETERMINISTIC prepared plans, keyed (plan hash, graph epoch, feed
  // bytes) with exact feed-byte compare — a hash collision can never
  // serve foreign results. Every graph-epoch or ownership-map bump
  // purges the window (counted reuse_invalidated): a stale sample is
  // never served silently. 0 (default) disables.
  std::atomic<int> reuse_window{0};

  RpcConfig() = default;
  RpcConfig(const RpcConfig& o) { *this = o; }
  RpcConfig& operator=(const RpcConfig& o) {
    mux.store(o.mux.load());
    mux_connections.store(o.mux_connections.load());
    compress_threshold.store(o.compress_threshold.load());
    max_inflight.store(o.max_inflight.load());
    hedge_delay_us.store(o.hedge_delay_us.load());
    p2c.store(o.p2c.load());
    hedge_replicas.store(o.hedge_replicas.load());
    prepared.store(o.prepared.load());
    plan_cache.store(o.plan_cache.load());
    deflate_reuse.store(o.deflate_reuse.load());
    plan_optimize.store(o.plan_optimize.load());
    coalesce_window_us.store(o.coalesce_window_us.load());
    reuse_window.store(o.reuse_window.load());
    return *this;
  }
};
RpcConfig& GlobalRpcConfig();

// Client-side transport counters (process-global, monotonic; inflight is
// a gauge). Counted at the CLIENT edge only — loopback tests run client
// and server in one process and the A/B must read client traffic.
struct RpcCounters {
  std::atomic<uint64_t> round_trips{0};      // completed request/reply pairs
  std::atomic<uint64_t> bytes_sent{0};       // wire bytes incl. headers
  std::atomic<uint64_t> bytes_received{0};   // wire bytes incl. headers
  std::atomic<uint64_t> bytes_sent_raw{0};   // pre-compression payload view
  std::atomic<uint64_t> bytes_received_raw{0};
  std::atomic<uint64_t> connections_opened{0};
  std::atomic<uint64_t> compressed_frames_sent{0};
  std::atomic<uint64_t> compressed_frames_received{0};
  std::atomic<uint64_t> mux_calls{0};        // calls over v2 mux conns
  std::atomic<uint64_t> v1_calls{0};         // calls over the classic path
  std::atomic<uint64_t> hello_fallbacks{0};  // v2 hello refused → v1
  std::atomic<int64_t> inflight{0};          // mux calls on the wire now
  // ---- tail-latency machinery (deadline propagation + hedging) ----
  // requests stamped with a propagated deadline (client edge)
  std::atomic<uint64_t> deadline_propagated{0};
  // kExecute requests a SERVER dropped unexecuted because their
  // propagated deadline had already expired at dispatch pickup —
  // answered with an explicit "deadline shed" status, never silently.
  // Server-edge (loopback tests see both edges in one process).
  std::atomic<uint64_t> deadline_shed{0};
  std::atomic<uint64_t> hedge_fired{0};   // hedge legs submitted
  std::atomic<uint64_t> hedge_won{0};     // hedge leg answered first
  // legs abandoned after the other leg won: cancelled by request_id at
  // the demux reader, their replies discarded. Counted exactly once
  // per abandoned leg, at abandonment.
  std::atomic<uint64_t> hedge_wasted{0};
  // ---- elastic fleet (epoch-versioned ownership maps) ----
  // kExecute requests a SERVER refused because they were routed on an
  // OLDER ownership-map epoch than the shard's — answered with an
  // explicit "stale ownership map" status (the client refreshes the
  // registry-published map and retries; never a silent misroute).
  // Server-edge, like deadline_shed.
  std::atomic<uint64_t> stale_map_shed{0};
  // Replica-level hedging (ClientManager::Execute across shards that
  // own the same partitions — RpcConfig::hedge_replicas).
  std::atomic<uint64_t> replica_hedge_fired{0};
  std::atomic<uint64_t> replica_hedge_won{0};
  std::atomic<uint64_t> replica_hedge_wasted{0};
  // ---- cross-process tracing (hello feature kFeatTrace) ----
  // kExecute requests stamped with a wire trace context (client edge).
  // Zero whenever the feature is off, no trace is set, or the peer
  // predates it — the wire-identity tests pin exactly that.
  std::atomic<uint64_t> trace_propagated{0};
  // ---- prepared plans (hello feature kFeatPrepared) ----
  // SERVER-edge (loopback tests see both edges in one process):
  // registered = plans installed via kPrepare; hits = prepared
  // kExecutes served from the per-connection plan cache; misses =
  // prepared kExecutes whose id the server did not know (evicted /
  // never registered on this connection) — answered with an explicit
  // miss status; invalidated = cache entries rejected because an
  // ownership-map flip superseded the routing baked into client plans.
  std::atomic<uint64_t> prepared_registered{0};
  std::atomic<uint64_t> prepared_hits{0};
  std::atomic<uint64_t> prepared_misses{0};
  std::atomic<uint64_t> prepared_invalidated{0};
  // CLIENT-edge: prepared execution requested but the call went out as
  // a classic full-plan frame (peer lacks the feature / v1 fallback /
  // persistent miss) — the correctness fallback, counted never silent.
  std::atomic<uint64_t> prepared_fallbacks{0};
  // ---- prepare-time plan optimizer (RpcConfig::plan_optimize) ----
  // SERVER-edge, like the prepared_* cache counters.
  // registrations that ran the optimizer (whether or not any pass fired)
  std::atomic<uint64_t> plan_optimized{0};
  // per-pass rewrite counts (gql.h PlanOptStats): nodes collapsed into
  // FUSED groups / filter+post-process nodes absorbed / CSE duplicates
  // removed, summed over registrations
  std::atomic<uint64_t> plan_rewrites_fuse{0};
  std::atomic<uint64_t> plan_rewrites_pushdown{0};
  std::atomic<uint64_t> plan_rewrites_dedup{0};
  // re-registrations after a plan-generation bump (ownership-map flip):
  // the optimized form was re-derived for the new epoch — PR 14's
  // invalidation machinery driving per-epoch recompute, counted
  std::atomic<uint64_t> plan_rewrites_epoch{0};
  // ---- cross-request execute coalescing (coalesce_window_us) ----
  // requests answered from ANOTHER request's execution (the followers
  // of a coalesced batch; the leader's run is not counted)
  std::atomic<uint64_t> coalesced_requests{0};
  // leader executions that served more than one request
  std::atomic<uint64_t> coalesce_batches{0};
  // ---- deterministic result-reuse window (reuse_window) ----
  std::atomic<uint64_t> reuse_hits{0};
  std::atomic<uint64_t> reuse_misses{0};
  // entries purged by a graph-epoch / ownership-map bump — every bump
  // counts every dropped entry, so "stale but silently served" is
  // structurally impossible to miss in the A/B accounting
  std::atomic<uint64_t> reuse_invalidated{0};
};
RpcCounters& GlobalRpcCounters();

// ---------------------------------------------------------------------------
// Wire-level trace propagation (protocol v2, hello feature kFeatTrace).
// ---------------------------------------------------------------------------
// A client-generated trace context riding a kExecute request frame:
// `id` correlates every hop of one logical client call (hedged legs and
// stale-map retries share it), `parent` is the CLIENT span the server-
// side breakdown nests under in a merged chrome trace. id == 0 means
// "untraced" and stamps nothing — the wire stays byte-identical.
struct WireTrace {
  uint64_t id = 0;
  uint64_t parent = 0;
};

// Per-thread trace handoff, the SetCallDeadlineUs pattern: the capi
// sets it just before etq_exec_run on the query's calling thread;
// QueryProxy::RunGremlinTimed consumes it into the run's QueryEnv and
// every REMOTE sub-call stamps it into its v2 request frame (each wire
// attempt — retries, hedge legs — carries the same context; the server
// mints a distinct span id per request).
void SetCallTrace(uint64_t trace_id, uint64_t parent_span);
WireTrace TakeCallTrace();

// Unix wall-clock now in microseconds (server span timestamps must be
// comparable ACROSS processes, which steady_clock is not).
int64_t WallNowUs();

// Server-side per-request timing breakdown — the cross-process half of
// the observability subsystem. Two sinks:
//   * always-on native histograms, per verb and per phase (queue-wait /
//     decode / execute / serialize; non-kExecute verbs record queue +
//     execute only), log2-µs buckets — one /metrics scrape of a shard
//     shows queue-wait and execute quantiles with no Python in the
//     measurement path;
//   * a bounded ring of finished server spans for requests that carried
//     a wire trace context (kFeatTrace), drained by etg_server_trace_dump
//     and stitched under the client span in a merged chrome trace.
struct ServerTraceRecord {
  uint64_t trace_id = 0;
  uint64_t parent_span = 0;  // the client span the request rode under
  uint64_t span_id = 0;      // server-minted, unique per process
  uint32_t verb = 0;         // wire msg_type
  // bit 0: deadline-shed, bit 1: stale-map-shed, bit 2: non-OK status
  uint32_t flags = 0;
  int64_t start_unix_us = 0;  // wall clock at request arrival
  uint32_t queue_us = 0;      // arrival → dispatch pickup
  uint32_t decode_us = 0;
  uint32_t exec_us = 0;
  uint32_t serialize_us = 0;  // encode + reply write
};

// One lock-free log2-µs latency histogram — THE bucket convention every
// native latency surface shares (server verb/phase timings here, the
// storage tier's cold-read penalty in store.h): 24 le-inclusive bounds
// (1µs, 2µs, ... 2^23µs ≈ 8.4s) + one overflow bucket, plus n/sum_us so
// a scraper can derive both quantiles and the mean from one snapshot.
struct LatencyHist {
  static constexpr int kBuckets = 24;
  std::atomic<uint64_t> n{0};
  std::atomic<uint64_t> sum_us{0};
  std::atomic<uint64_t> counts[kBuckets + 1] = {};

  void Observe(uint64_t us);
  // counts must hold kBuckets+1 slots.
  void Snapshot(uint64_t* n_out, uint64_t* sum_us_out,
                uint64_t* counts_out) const;
};

class ServerTraceStats {
 public:
  // Histogram axes. Verb slots index the hist matrix; phases follow the
  // request's wire lifecycle. kTraceBuckets log2-µs bounds (1µs, 2µs,
  // ... 2^23µs ≈ 8.4s) + one overflow bucket.
  static constexpr int kTraceVerbs = 6;    // execute, apply_delta,
                                           // get_delta, get_delta_log,
                                           // set_ownership, meta
  static constexpr int kTracePhases = 4;   // queue, decode, exec, ser
  static constexpr int kTraceBuckets = LatencyHist::kBuckets;
  static constexpr size_t kRingCap = 8192;

  // msg_type → verb slot, -1 for untracked verbs (ping, hello, ...).
  static int VerbSlot(uint32_t msg_type);

  void Observe(int verb_slot, int phase, uint64_t us);
  // Ring append (only requests that carried a trace id land here).
  void Record(const ServerTraceRecord& rec);
  // Read-and-clear the span ring (the harness dumps once per run).
  void Drain(std::vector<ServerTraceRecord>* out);
  // Copy one (verb, phase) histogram: *n, *sum_us, counts[kTraceBuckets+1].
  bool HistSnapshot(int verb_slot, int phase, uint64_t* n,
                    uint64_t* sum_us, uint64_t* counts) const;
  uint64_t NextSpanId() { return next_span_.fetch_add(1); }

 private:
  LatencyHist hist_[kTraceVerbs][kTracePhases];
  std::atomic<uint64_t> next_span_{1};
  mutable std::mutex ring_mu_;
  std::deque<ServerTraceRecord> ring_;
};
ServerTraceStats& GlobalServerTraceStats();

// ---------------------------------------------------------------------------
// Per-call deadline propagation (protocol v2, hello feature kFeatDeadline).
// ---------------------------------------------------------------------------
// Monotonic (steady_clock) now, in microseconds.
int64_t SteadyNowUs();
// Set/clear the CALLING THREAD's deadline for the next query run
// (absolute steady-clock µs; 0 clears). The capi sets it just before
// etq_exec_run on the same thread; QueryProxy::RunGremlinTimed consumes
// it into the run's QueryEnv, and every REMOTE sub-call stamps its v2
// request frame with the remaining budget so a shard can shed work that
// can no longer make it. v1 peers (and calls with no deadline set) are
// byte-unchanged.
void SetCallDeadlineUs(int64_t abs_steady_us);
// Read-and-clear the calling thread's deadline (0 = none set).
int64_t TakeCallDeadlineUs();

// ---------------------------------------------------------------------------
// Shard metadata exchanged at client init (reference query_proxy.cc:62-105:
// graph meta + per-shard weight matrices for proportional sampling).
// ---------------------------------------------------------------------------
struct ShardMeta {
  int shard_idx = 0;
  int shard_num = 1;
  int partition_num = 1;
  std::vector<float> node_type_wsum;  // per node type
  std::vector<float> edge_type_wsum;  // per edge type
  uint64_t graph_label_count = 0;     // whole-graph labels on this shard
  // Labels this shard OWNS under the hash convention (label % shard_num
  // == shard_idx). Drives sampleGL count splitting in hash-distribute
  // mode, where a label present on several shards must still be drawn
  // from exactly one.
  uint64_t owned_graph_label_count = 0;
  GraphMeta graph_meta;
};

void EncodeShardMeta(const ShardMeta& m, ByteWriter* w);
Status DecodeShardMeta(ByteReader* r, ShardMeta* m);

// ---------------------------------------------------------------------------
// Server
// ---------------------------------------------------------------------------
struct PreparedPlan;  // a decoded, registered execute plan (rpc.cc)

class GraphServer {
 public:
  // Serves the given graph shard (+ optional index) on port (0 → ephemeral).
  GraphServer(std::shared_ptr<const Graph> graph,
              std::shared_ptr<IndexManager> index, int shard_idx,
              int shard_num, int partition_num);
  // Streaming form: the server serves whatever snapshot the ref holds —
  // kApplyDelta swaps a new one in while in-flight requests finish on
  // the old (each execution pins its snapshot shared_ptr).
  GraphServer(std::shared_ptr<GraphRef> graph_ref,
              std::shared_ptr<IndexManager> index, int shard_idx,
              int shard_num, int partition_num);
  ~GraphServer();

  // Index spec to rebuild attribute indexes from after a delta apply
  // ("" = no index). Set before Start.
  void set_index_spec(std::string spec) { index_spec_ = std::move(spec); }

  // This shard's swappable graph holder (tests / embedded callers).
  const std::shared_ptr<GraphRef>& graph_ref() const { return graph_ref_; }

  // Durable deltas (wal.h): every accepted kApplyDelta appends its raw
  // broadcast body (stamped with the epoch it produces) to the log
  // BEFORE the snapshot swap, and compaction re-dumps past the log
  // threshold. A failed append refuses the delta with an explicit
  // status (counted, wal_degraded gauge) so the in-memory graph never
  // runs ahead of its log. degraded=true marks "wal requested but
  // unopenable": reads serve normally, every delta is refused.
  void set_wal(std::shared_ptr<DeltaWal> wal, bool degraded = false) {
    wal_ = std::move(wal);
    wal_degraded_ = degraded;
    // an unopenable wal contributes to the degraded-instance gauge for
    // this server's lifetime (Stop releases it)
    if (degraded) GlobalWalCounters().degraded.fetch_add(1);
    if (storage_mode_ == 1 && wal_ != nullptr)
      wal_->set_columnar_sidecar(true);
  }

  // Out-of-core storage (store.h): mode 1 = mmap columnar tier. The
  // server's WAL compactions write the columnar sidecar, and after each
  // successful compaction the shard RE-ATTACHES the fresh generation —
  // swapping the heap snapshot (the RAM overlay deltas build on) for
  // its byte-identical mmap twin at the same epoch, so the heap copy is
  // only ever as old as one compaction interval. hot_bytes is the
  // hub-pinned hot-set budget per attach. Order-independent with
  // set_wal; set both before Start.
  void set_storage(int mode, int64_t hot_bytes) {
    storage_mode_ = mode;
    storage_hot_bytes_ = hot_bytes;
    if (storage_mode_ == 1 && wal_ != nullptr)
      wal_->set_columnar_sidecar(true);
  }
  int storage_mode() const { return storage_mode_; }

  // Pre-populate the retained anti-entropy delta log (kGetDeltaLog)
  // with records recovered from this shard's own WAL, so a freshly
  // recovered shard can serve catch-up to peers recovering after it.
  void SeedDeltaLog(const std::vector<WalRecord>& recs);

  // Mark this shard's epoch numbering untrusted for anti-entropy:
  // recovery left a known unclosed gap (replay stopped early, or the
  // registry catch-up failed), so local epochs may alias different
  // fleet deltas. kGetDeltaLog then always answers covered=0.
  void MarkDeltaLogGap() { dlog_authoritative_.store(false); }

  // Install an epoch-versioned ownership map (kSetOwnership / admin):
  // from then on (1) kExecute requests stamped with an OLDER map epoch
  // are refused with an explicit "stale ownership map" status (counted
  // stale_map_shed) — the flip is what makes a superseded routing map
  // unable to read partitions whose deltas now land elsewhere; (2)
  // delta applies filter by the map's owner lists instead of the hash
  // convention; (3) the spec is persisted beside the WAL (when one is
  // attached) so crash-recovery replay re-filters identically. A map
  // older than the installed one is refused.
  Status SetOwnership(std::shared_ptr<const OwnershipMap> m);
  std::shared_ptr<const OwnershipMap> ownership() const {
    std::lock_guard<std::mutex> lk(omap_mu_);
    return omap_;
  }
  uint64_t map_epoch() const { return map_epoch_.load(); }

  uint64_t epoch() const { return graph_ref_->epoch(); }

  // Anti-entropy catch-up (restart rejoin): pull the raw delta records
  // this shard missed (epoch > ours) from a peer's retained delta log
  // (kGetDeltaLog) and apply them through the normal apply path — WAL
  // append included, so caught-up epochs survive the NEXT crash too.
  // Run between Start and Register: the shard rejoins at the fleet
  // epoch before discovery routes traffic to it.
  Status CatchUpFromPeer(const std::string& host, int port);
  // Scan the registry for OTHER shards' endpoints and catch up from the
  // first that answers covered. Non-fatal: an uncoverable gap logs a
  // warning and serves at the reached epoch (clients fall back to the
  // epoch-regression full flush). OK no-op when no peer is registered.
  Status CatchUpFromRegistry(const std::string& registry);

  Status Start(int port);
  void Stop();
  int port() const { return port_; }

  // Register under the registry (a shared directory OR a
  // "tcp:<host>:<port>" RegistryServer) as shard_<i>__<host>_<port> and
  // start a heartbeat thread that re-puts the entry every heartbeat_ms —
  // the ephemeral-node semantics of the reference's ZK registration
  // (zk_server_register.cc): a crashed server's entry goes stale and
  // monitors mark the shard down. heartbeat_ms <= 0 disables (tests).
  Status Register(const std::string& registry, const std::string& host,
                  int heartbeat_ms = 2000);

  // Introspection probe (capi ets_plan_debug): one block per plan in
  // the shared store — id, generation, deterministic flag, per-pass
  // rewrite counts, and the INSTALLED (optimized) DagToString, with the
  // verbatim registered form when the optimizer changed it.
  std::string DebugPlans() const;

 private:
  struct Conn {
    std::thread thread;
    std::shared_ptr<std::atomic<bool>> finished;
  };
  struct ConnState;  // per-connection v2 state (rpc.cc)

  void AcceptLoop();
  void ReapFinishedLocked();  // join + drop exited connection threads
  void HandleConnection(int fd);
  void HandleExecute(ByteReader* r, ByteWriter* w);
  // v2 path: dispatch one decoded frame; false → close the connection.
  bool HandleV2Frame(const std::shared_ptr<ConnState>& conn,
                     uint32_t msg_type, uint64_t request_id,
                     uint32_t flags, std::vector<char> body);
  void BuildMeta(ByteWriter* w) const;
  // Streaming delta verbs (shared by the v1 and v2 frame paths).
  void HandleApplyDelta(ByteReader* r, ByteWriter* w);
  void HandleGetDelta(ByteReader* r, ByteWriter* w);
  void HandleGetDeltaLog(ByteReader* r, ByteWriter* w);
  // kSetOwnership: body = ownership spec → decode + SetOwnership.
  void HandleSetOwnership(ByteReader* r, ByteWriter* w);
  // Shared apply path (wire kApplyDelta AND peer catch-up): decode →
  // WAL append → rebuild → swap → retained log → compaction. Writes the
  // wire reply (u32 code | u64 epoch, or u32 1 | str error) into w.
  void ApplyDeltaBody(const char* body, size_t len, ByteWriter* w);
  // Current-snapshot pair for one request (graph pinned, index coherent
  // with it — index_ swaps under state_mu_ on delta apply).
  void SnapshotState(std::shared_ptr<const Graph>* g,
                     std::shared_ptr<IndexManager>* idx) const;
  // Purge the result-reuse window, counting every dropped entry into
  // reuse_invalidated. Called on EVERY epoch bump — graph delta apply
  // and ownership-map install — so a stale sample is never served.
  void InvalidateReuse();

  std::shared_ptr<GraphRef> graph_ref_;
  std::shared_ptr<IndexManager> index_;
  mutable std::mutex state_mu_;  // index_ swap vs request snapshots
  std::string index_spec_;
  // elastic fleet: installed ownership map (delta filtering + the
  // stale-map request check). map_epoch_ mirrors omap_->map_epoch so
  // the per-request check is one atomic load.
  mutable std::mutex omap_mu_;
  std::shared_ptr<const OwnershipMap> omap_;
  std::atomic<uint64_t> map_epoch_{0};
  // prepared-plan cache generation: bumped on every ownership-map
  // install — the distribute rewrite bakes shard routing into client
  // plans, so a flip invalidates every cached plan on this server
  // (entries from an older generation answer the counted miss status
  // and the client re-prepares against the new map)
  std::atomic<uint64_t> plan_gen_{1};
  // Shared per-process plan store (kPrepare): ONE bounded LRU of
  // decoded plans per server, shared by every connection — a plan
  // registered on one connection hits from any other, and registrations
  // survive reconnects (the store outlives connection state). Entries
  // are immutable once installed (dag.h read-only contract); plan_mu_
  // covers the map/LRU structure only.
  mutable std::mutex plan_mu_;
  std::list<uint64_t> plan_lru_;  // front = most recently used
  std::unordered_map<uint64_t,
                     std::pair<std::shared_ptr<const PreparedPlan>,
                               std::list<uint64_t>::iterator>>
      plans_;
  // Bounded deterministic result-reuse window (RpcConfig::reuse_window):
  // LRU of completed execute results keyed by a 64-bit mix of
  // (plan id, graph snapshot uid, feed-byte hash); entries carry the
  // exact feed bytes for a full compare on hit.
  struct ReuseEntry;
  mutable std::mutex reuse_mu_;
  std::list<uint64_t> reuse_lru_;
  std::unordered_map<uint64_t,
                     std::pair<std::shared_ptr<const ReuseEntry>,
                               std::list<uint64_t>::iterator>>
      reuse_;
  // Cross-request execute coalescing (RpcConfig::coalesce_window_us):
  // open batches keyed like the reuse window; a request that finds an
  // open bucket parks its reply continuation and the bucket leader
  // answers it from the single shared execution.
  struct CoalesceBucket;
  std::mutex coalesce_mu_;
  std::unordered_map<uint64_t, std::shared_ptr<CoalesceBucket>> coalesce_;
  std::shared_ptr<DeltaWal> wal_;
  bool wal_degraded_ = false;  // wal requested but unopenable: refuse deltas
  int storage_mode_ = 0;       // 0 heap, 1 mmap out-of-core (store.h)
  int64_t storage_hot_bytes_ = 0;
  // Post-compaction mmap re-attach (rpc.cc; caller holds apply_mutex).
  void ReattachFromSidecar(DeltaWal* wal);
  // off-path compaction accounting: Stop() drains in-flight tasks
  // before releasing the wal, so a successor reopening the same
  // wal_dir can never race a still-running dump
  std::mutex compact_mu_;
  std::condition_variable compact_cv_;
  int compact_inflight_ = 0;
  // false when this shard's own recovery left a known unclosed epoch
  // gap: its locally-stamped epochs may alias different fleet deltas,
  // so kGetDeltaLog must answer covered=0 (peers fall back to the
  // client-driven convergence path) instead of serving aliased bodies
  std::atomic<bool> dlog_authoritative_{true};
  // bounded retained raw delta bodies (epoch, kApplyDelta wire body)
  // served to recovering peers via kGetDeltaLog — the anti-entropy
  // source. Consecutive epochs by construction (each apply bumps by 1).
  mutable std::mutex dlog_mu_;
  std::deque<std::pair<uint64_t, std::vector<char>>> dlog_;
  size_t dlog_bytes_ = 0;
  static constexpr size_t kMaxDlogRecords = 256;
  static constexpr size_t kMaxDlogBytes = 64u << 20;
  int shard_idx_, shard_num_, partition_num_;
  bool v1_only_ = false;  // EULER_TPU_RPC_SERVER_V1: emulate a pre-v2
                          // binary exactly (interop tests)
  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> stopping_{false};
  std::thread acceptor_;
  std::mutex conn_mu_;
  std::vector<Conn> conns_;
  std::vector<int> conn_fds_;  // open connection sockets (for Stop)
  std::string reg_spec_, reg_name_;  // registry spec + entry name
  std::thread heartbeat_;
  std::mutex hb_mu_;
  std::condition_variable hb_cv_;
  // periodic connection-thread reaper: without it an idle server only
  // reaps finished handler threads at the NEXT accept, so a burst of
  // short-lived clients leaves joinable threads parked until then
  std::thread reaper_;
};

// ---------------------------------------------------------------------------
// Client
// ---------------------------------------------------------------------------
// One logical endpoint ("host:port") with a pool of pooled blocking
// sockets; Call() is thread-safe, retries up to kRetryCount with
// reconnects (reference rpc_client.h:46).
class RpcChannel : public std::enable_shared_from_this<RpcChannel> {
 public:
  static constexpr int kRetryCount = 10;
  // Release() keeps at most this many idle pooled sockets; extras are
  // closed on release so a concurrency burst cannot pin fds forever.
  static constexpr int kMaxPooledFds = 8;

  explicit RpcChannel(std::string host, int port);
  ~RpcChannel();

  // max_retries <= 0 → kRetryCount. Registry traffic passes 1-2 so
  // heartbeat/shutdown paths can't stall behind an unreachable host.
  // With set_mux(true) the call rides a shared v2 connection (many
  // in-flight calls per fd, replies demuxed by request_id); against a
  // v1 server the channel falls back to the classic path for life.
  // deadline_abs_us > 0 (steady-clock µs) stamps each v2 kExecute
  // request frame with the REMAINING budget at write time (hello-
  // negotiated; v1 peers byte-unchanged) so the server can shed
  // already-dead work; it does not bound the call locally.
  // map_epoch > 0 stamps the ownership-map epoch the caller ROUTED
  // with (captured at query-run start, not read live — see
  // QueryEnv.map_epoch) so a flipped shard refuses stale-map reads.
  // trace.id != 0 stamps the caller's trace context (hello-negotiated
  // kFeatTrace) so the shard's timing breakdown stitches under the
  // client span; untraced calls are byte-unchanged.
  Status Call(uint32_t msg_type, const std::vector<char>& body,
              std::vector<char>* reply_body, int max_retries = 0,
              int64_t deadline_abs_us = 0, uint64_t map_epoch = 0,
              WireTrace trace = {});

  // Prepared-plan kExecute (RpcConfig::prepared, hello kFeatPrepared):
  // ensures `plan` (keyed by plan_id, its content hash) is registered
  // on the mux connection the call rides, then ships ONLY `feeds`,
  // stamped with the plan id. A server miss (evicted / invalidated /
  // unknown id — always an explicit counted status) forgets the local
  // registration and re-prepares on the next attempt; a peer without
  // the feature, a v1 fallback, or retry exhaustion reassembles the
  // classic full-plan frame ('ETEY' bytes identical to Call) — counted
  // prepared_fallbacks, never a silent wrong or dropped plan. Hedged
  // legs (hedge_delay_us) carry the SAME plan id, each leg's
  // connection registered before it fires.
  Status CallExecutePrepared(const std::vector<char>& plan,
                             uint64_t plan_id,
                             const std::vector<char>& feeds,
                             std::vector<char>* reply_body,
                             int max_retries = 0,
                             int64_t deadline_abs_us = 0,
                             uint64_t map_epoch = 0, WireTrace trace = {});

  // Async mux submission: invokes done(status, reply) when the reply
  // frame arrives (or the connection dies). Requires mux mode; without
  // it the call is executed inline (blocking) before done fires.
  void CallAsync(uint32_t msg_type, std::vector<char> body,
                 std::function<void(Status, std::vector<char>)> done);

  // > 0: bound connect() AND each recv/send to this budget (poll-based
  // connect + SO_RCVTIMEO/SO_SNDTIMEO). 0 (default) = blocking sockets
  // — the graph-query path keeps them (long merges may stream for a
  // while); registry channels set ~3s. Mux connections apply it to
  // connect() only (the demux reader legitimately idles in recv).
  void set_timeout_ms(int ms) { timeout_ms_ = ms; }

  // Enable multiplexed v2 transport (call before the first Call()).
  void set_mux(bool on) { mux_ = on; }
  bool mux_active() const { return mux_ && !v1_fallback_.load(); }

  // Epoch sink: v2 reply frames carry the serving graph's epoch (flag
  // bit, hello-negotiated); the demux reader max-updates *sink with it
  // so the owner (ClientManager) observes bumps passively on every
  // reply. The sink must outlive the channel. nullptr disables.
  void set_epoch_sink(std::atomic<uint64_t>* sink) { epoch_sink_ = sink; }


  const std::string& host() const { return host_; }
  int port() const { return port_; }

 private:
  class MuxConn;

  int Acquire();           // pooled or fresh connected socket, -1 on fail
  void Release(int fd);
  int Connect();
  Status MuxCall(uint32_t msg_type, const std::vector<char>& body,
                 std::vector<char>* reply_body, int max_retries,
                 int64_t deadline_abs_us, uint64_t map_epoch,
                 WireTrace trace);
  // One hedged sync mux call: primary leg on `conn`; past hedge_us
  // without a reply, the same request fires on a second connection and
  // the first reply wins (the loser is abandoned by request_id).
  // plan_id != 0: both legs are prepared executes stamped with the
  // SAME plan id (`plan` is registered on the hedge connection before
  // its leg fires, so a fresh conn can never miss by construction).
  Status HedgedMuxCall(const std::shared_ptr<MuxConn>& conn, int slot,
                       int slots, uint32_t msg_type,
                       const std::vector<char>& body,
                       std::vector<char>* reply_body, int64_t hedge_us,
                       int64_t deadline_abs_us, uint64_t map_epoch,
                       WireTrace trace, uint64_t plan_id = 0,
                       const std::vector<char>* plan = nullptr);
  // Mux slot for the next call: p2c over (inflight, EWMA latency) when
  // configured, else round-robin. `avoid` >= 0 excludes that slot (the
  // hedge leg must take a different wire path).
  int PickSlot(int slots, int avoid = -1);
  // Slot's live mux connection, dialing if absent/broken; nullptr on
  // connect failure. Sets v1_fallback_ when the server refuses hello.
  std::shared_ptr<MuxConn> MuxGet(int slot);

  std::string host_;
  int port_;
  int timeout_ms_ = 0;
  std::atomic<uint64_t>* epoch_sink_ = nullptr;
  std::mutex mu_;
  std::vector<int> free_fds_;
  bool mux_ = false;
  std::atomic<bool> v1_fallback_{false};
  std::atomic<uint64_t> mux_rr_{0};  // round-robin over mux slots
  std::mutex mux_mu_;
  std::vector<std::shared_ptr<MuxConn>> mux_conns_;
};

// ---------------------------------------------------------------------------
// TCP registry server — the ZooKeeper role WITHOUT a shared filesystem
// (reference euler/common/zk_server_monitor.h). Servers heartbeat named
// entries over the framed protocol (kRegPut); clients list entries with
// server-computed ages (kRegList) — ephemeral-node semantics from the
// server's own clock, so machines need no NFS and no clock agreement.
// All registry access below accepts either a directory path (optionally
// "dir:"-prefixed) or "tcp:<host>:<port>" pointing at one of these.
// ---------------------------------------------------------------------------
class RegistryServer {
 public:
  ~RegistryServer();
  Status Start(int port);  // 0 → ephemeral
  void Stop();
  int port() const { return port_; }

 private:
  void AcceptLoop();
  void HandleConnection(int fd);

  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> stopping_{false};
  std::thread acceptor_;
  std::mutex mu_;  // guards entries_ and conns_
  // name → (last-put steady time ms, put sequence). The sequence breaks
  // same-millisecond ties: clients pick the entry with the HIGHEST seq
  // per shard (exact insertion recency), while age drives staleness.
  std::map<std::string, std::pair<int64_t, uint64_t>> entries_;
  uint64_t put_seq_ = 0;
  // parallel vectors: connection thread, its fd, and a finished flag
  // (reaped opportunistically in AcceptLoop; finished conns' fds are
  // already closed and must not be shutdown() again)
  std::vector<std::thread> conns_;
  std::vector<int> conn_fds_;
  std::vector<std::shared_ptr<std::atomic<bool>>> done_;
};

// Push an ownership-map spec to one graph server (kSetOwnership over a
// short-lived v1 channel — the admin path the elastic driver uses to
// flip a fleet's routing). *epoch_out (optional) gets the installed
// map epoch on success.
Status PushOwnership(const std::string& host, int port,
                     const std::string& spec, uint64_t* epoch_out = nullptr);

// Write/refresh one named entry in a registry (file touch or tcp put).
Status RegistryPutEntry(const std::string& spec, const std::string& name);
// Drop one named entry (file unlink or tcp remove) — clean shutdown.
Status RegistryRemoveEntry(const std::string& spec, const std::string& name);
// List a registry's shard entries: shard idx → (host, port) + entry age
// in ms (time since last heartbeat).
Status ScanRegistrySpec(const std::string& spec,
                        std::map<int, std::pair<std::string, int>>* found,
                        std::map<int, int64_t>* ages_ms);

// Discovery: resolve shard → endpoints. Sources, like the reference's
// ZK monitor + static config:
//   - registry: dir path or tcp: spec with "shard_<i>__<host>_<port>" entries
//   - static spec: "host:port,host:port,..." (index in list = shard)
struct ShardEndpoints {
  std::vector<std::pair<std::string, int>> endpoints;  // per shard
};
Status DiscoverFromRegistry(const std::string& registry, int shard_num,
                            ShardEndpoints* out);
// Single scan; shard count derived from the max index found (all indices
// 0..max must be present).
Status DiscoverFromRegistryAuto(const std::string& registry,
                                ShardEndpoints* out);
Status DiscoverFromSpec(const std::string& spec, ShardEndpoints* out);

// Live registry watcher — the role of the reference's ZK server monitor
// (zk_server_monitor.cc, ShardCallback server_monitor.h:33-40): rescans
// the registry every interval_ms and fires the callback when a shard
// endpoint appears, changes, or goes stale (file mtime older than
// stale_ms — the heartbeat stopped) / disappears.
class ServerMonitor {
 public:
  // up=true: shard registered (or re-registered at a new endpoint).
  // up=false: shard's registration vanished or went stale.
  using Callback = std::function<void(int shard, const std::string& host,
                                      int port, bool up)>;

  ServerMonitor(std::string registry_dir, int interval_ms = 1000,
                int stale_ms = 6000);
  ~ServerMonitor();

  void Start(Callback cb);
  void Stop();

 private:
  void Loop();

  std::string dir_;
  int interval_ms_, stale_ms_;
  Callback cb_;
  std::map<int, std::pair<std::string, int>> live_;
  std::thread thread_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stopping_ = false;
};

// Per-shard channel table + aggregated shard weights. Parity: reference
// ClientManager (client_manager.h:31) + QueryProxy's weight matrices.
class ClientManager {
 public:
  ~ClientManager();

  // Connects to every shard, fetches ShardMeta from each, aggregates.
  Status Init(const ShardEndpoints& eps);

  // Live membership: watch the registry; when a shard re-registers at a
  // new endpoint (server restart), swap its channel so subsequent calls
  // reach the new server — the reference's ZK add/remove callback path
  // re-resolving RpcManager channels. Safe to call after Init.
  void WatchRegistry(const std::string& dir, int interval_ms = 1000,
                     int stale_ms = 6000);

  int shard_num() const { return static_cast<int>(channels_.size()); }
  int partition_num() const { return partition_num_; }
  const GraphMeta& graph_meta() const { return graph_meta_; }

  // ---- elastic fleet: epoch-versioned ownership routing ----
  // Install/replace the routing map (client-cached view of the
  // registry-published map). Every channel starts stamping the new
  // epoch into its kExecute frames immediately. Refused when the map
  // references shards this manager has no channel for (the caller must
  // rebuild against the grown fleet first) or when it is older than
  // the installed one.
  Status SetOwnership(std::shared_ptr<const OwnershipMap> m);
  std::shared_ptr<const OwnershipMap> ownership() const {
    std::lock_guard<std::mutex> lk(omap_mu_);
    return omap_;
  }
  uint64_t map_epoch() const { return map_epoch_.load(); }
  // One owner choice per partition for THIS batch: single-owner
  // partitions route to their owner; replicated partitions pick by
  // power-of-two-choices over the per-shard (inflight, EWMA latency)
  // score, so a hot owner stops attracting reads. False → no map
  // installed (callers fall back to the ShardOf hash convention).
  bool PickOwners(std::vector<int>* out) const;
  // Per-shard traffic since Init (the hot-shard detection signal,
  // mirrored into obs by the Python layer): kExecute REQUEST counts
  // and split-routed ROW counts. Requests alone cannot see skew — the
  // distribute rewrite fires a (possibly empty) REMOTE at every shard
  // per query, so rows are the load signal. Fills min(cap, shard_num)
  // entries of each; returns the count filled. Either pointer may be
  // null.
  int ShardTraffic(uint64_t* reqs, uint64_t* rows, int cap) const;
  // Split kernels report the ids they routed to each shard.
  void CountRoutedRows(int shard, uint64_t n) {
    if (shard >= 0 && shard < stats_shards_)
      shard_rows_[shard].fetch_add(n);
  }
  // Hedge alternative for `shard`: a shard whose owned partitions
  // cover shard's (OwnershipMap::Covers) — the replica-hedging target.
  // -1 when none exists or no map is installed.
  int HedgeAltFor(int shard) const;

  // Per-shard weight sums; type < 0 → total over types.
  float NodeWeight(int shard, int type) const;
  float EdgeWeight(int shard, int type) const;
  // Whole-graph label count (graph_partition proportional sampling).
  // owned=true → hash-ownership count (hash-distribute sampleGL split).
  float GraphLabelWeight(int shard, bool owned = false) const;

  // Blocking execute on one shard. deadline_abs_us > 0 propagates the
  // caller's remaining budget inside the v2 request frame (see
  // RpcChannel::Call); map_epoch > 0 stamps the run-start ownership-
  // map epoch; trace stamps the caller's wire trace context — the
  // QueryEnv plumbs all three from the query's entry point down to
  // every REMOTE sub-call.
  Status Execute(int shard, const ExecuteRequest& req, ExecuteReply* rep,
                 int64_t deadline_abs_us = 0, uint64_t map_epoch = 0,
                 WireTrace trace = {});
  // Async: schedules on the global pool, invokes done on completion.
  void ExecuteAsync(int shard, ExecuteRequest req,
                    std::function<void(Status, ExecuteReply)> done,
                    int64_t deadline_abs_us = 0, uint64_t map_epoch = 0,
                    WireTrace trace = {});

  // ---- streaming deltas ----
  // Highest graph epoch observed on any reply from any shard (passive:
  // v2 frames piggyback it; DeltaSince/ApplyDelta refresh it actively).
  uint64_t ObservedEpoch() const { return observed_epoch_.load(); }
  // Broadcast one batched delta to every shard (each applies its hash-
  // owned rows and bumps its epoch). Idempotent per shard — a retry
  // after a partial failure re-applies the same rows (last-write-wins)
  // and only advances the epoch again. *new_epoch gets the max epoch.
  Status ApplyDelta(const NodeId* node_ids, const int32_t* node_types,
                    const float* node_weights, size_t n_nodes,
                    const NodeId* edge_src, const NodeId* edge_dst,
                    const int32_t* edge_types, const float* edge_weights,
                    size_t n_edges, uint64_t* new_epoch);
  // Union of the shards' dirty sets for epochs > from. *covered is
  // false when ANY shard's history no longer reaches `from` (caller
  // must treat everything as dirty). *epoch gets the max current epoch.
  Status DeltaSince(uint64_t from, uint64_t* epoch, bool* covered,
                    std::vector<NodeId>* ids);

 private:
  std::shared_ptr<RpcChannel> Channel(int shard) const;
  // Encoded wire forms of one kExecute: the classic full frame
  // (prepared off — today's byte-identical path) OR the split
  // plan/feeds pair + content-hash plan id (RpcConfig::prepared; the
  // channel reassembles the full frame itself on fallback). Shared so
  // replica-hedge legs race the same logical request — both legs stamp
  // the SAME plan id.
  struct ExecWire {
    std::shared_ptr<ByteWriter> full;
    std::shared_ptr<ByteWriter> plan;
    std::shared_ptr<ByteWriter> feeds;
    uint64_t plan_id = 0;
  };
  static Status CallExecWire(const std::shared_ptr<RpcChannel>& chan,
                             const ExecWire& wire, std::vector<char>* reply,
                             int64_t deadline_abs_us, uint64_t map_epoch,
                             WireTrace trace);
  // Two-leg replica race (RpcConfig::hedge_replicas): primary on
  // `shard`, and past hedge_us without a reply the same bytes fire at
  // `alt` (a covering owner). First reply wins; the loser's blocking
  // leg drains on its own thread and is discarded (counted).
  Status ReplicaHedgedExecute(int shard, int alt, ExecWire wire,
                              std::vector<char>* reply, int64_t hedge_us,
                              int64_t deadline_abs_us, uint64_t map_epoch,
                              WireTrace trace);
  // Decode + install a shard's re-fetched ShardMeta after a failover
  // channel swap, so proportional SAMPLE_SPLIT routing doesn't keep the
  // dead server's weight sums if the restarted shard serves changed
  // data. Caller holds the life_ lock (see below).
  void RefreshMeta(int shard, const Status& call_status,
                   const std::vector<char>& reply);

  mutable std::mutex chan_mu_;  // guards channels_ swaps from the monitor
  std::vector<std::shared_ptr<RpcChannel>> channels_;
  mutable std::mutex meta_mu_;  // guards metas_ refresh vs weight reads
  std::vector<ShardMeta> metas_;
  // Lifetime gate for pool-scheduled RefreshMeta tasks: they capture
  // this shared state, take the lock, and bail if `second` (destroyed)
  // is set — the destructor flips it under the same lock, so no task
  // touches a dead ClientManager.
  std::shared_ptr<std::pair<std::mutex, bool>> life_ =
      std::make_shared<std::pair<std::mutex, bool>>();
  GraphMeta graph_meta_;
  int partition_num_ = 1;
  std::unique_ptr<ServerMonitor> monitor_;
  // max graph epoch seen on any shard reply (channels' epoch sink)
  std::atomic<uint64_t> observed_epoch_{0};
  // elastic fleet: the client-cached ownership map + its epoch mirror
  // (the channels' map_epoch_src_ points at map_epoch_), per-shard
  // routing-load signals (PickOwners p2c), per-shard request counters
  // (hot-shard detection), and the precomputed hedge alternatives.
  mutable std::mutex omap_mu_;
  std::shared_ptr<const OwnershipMap> omap_;
  std::vector<int> hedge_alt_;  // under omap_mu_
  std::atomic<uint64_t> map_epoch_{0};
  std::unique_ptr<std::atomic<uint64_t>[]> shard_reqs_;
  std::unique_ptr<std::atomic<uint64_t>[]> shard_rows_;
  std::unique_ptr<std::atomic<int64_t>[]> shard_inflight_;
  std::unique_ptr<std::atomic<int64_t>[]> shard_ewma_us_;
  int stats_shards_ = 0;  // size of the arrays above
};

}  // namespace et

#endif  // EULER_TPU_RPC_H_
