#include "serde.h"

namespace et {

namespace {
// Wire-format tag. v2 ('ETEY') added NodeDef::also_produces mid-record;
// a mixed-version client/server pair fails fast on the magic check
// instead of misreading the record.
constexpr uint32_t kExecMagic = 0x59455445;  // 'ETEY'
// Prepared-plan split pieces (see serde.h): the plan half and the
// feeds half of one ExecuteRequest, each self-tagged so a frame that
// lands on the wrong decoder fails fast instead of misreading.
constexpr uint32_t kPlanMagic = 0x4e505445;   // 'ETPN'
constexpr uint32_t kFeedsMagic = 0x46455445;  // 'ETEF'

void PutStrList(const std::vector<std::string>& v, ByteWriter* w) {
  w->Put<uint32_t>(static_cast<uint32_t>(v.size()));
  for (const auto& s : v) w->PutStr(s);
}

Status GetStrList(ByteReader* r, std::vector<std::string>* out) {
  uint32_t n;
  if (!r->Get(&n)) return Status::IOError("truncated string list");
  out->resize(n);
  for (uint32_t i = 0; i < n; ++i)
    if (!r->GetStr(&(*out)[i])) return Status::IOError("truncated string");
  return Status::OK();
}
}  // namespace

size_t EncodedTensorSize(const Tensor& t) {
  return 4 + 4 + 8 * static_cast<size_t>(t.rank()) + t.ByteSize();
}

void EncodeTensor(const Tensor& t, ByteWriter* w) {
  // sizing pass: one reserve instead of doubling-reallocs while a
  // large gather payload appends (encoded bytes unchanged)
  w->Reserve(EncodedTensorSize(t));
  w->Put<int32_t>(static_cast<int32_t>(t.dtype()));
  w->Put<uint32_t>(static_cast<uint32_t>(t.rank()));
  for (int64_t d : t.dims()) w->Put<int64_t>(d);
  w->PutRaw(t.raw(), t.ByteSize());
}

Status DecodeTensor(ByteReader* r, Tensor* out) {
  int32_t dt;
  uint32_t rank;
  if (!r->Get(&dt) || !r->Get(&rank))
    return Status::IOError("truncated tensor header");
  if (dt < 0 || dt > 4) return Status::IOError("bad dtype");
  if (rank > 16) return Status::IOError("bad tensor rank");
  std::vector<int64_t> dims(rank);
  uint64_t elems = 1;
  for (uint32_t i = 0; i < rank; ++i) {
    if (!r->Get(&dims[i])) return Status::IOError("truncated dims");
    if (dims[i] < 0) return Status::IOError("negative tensor dim");
    if (dims[i] > 0 && elems > (1ull << 40) / static_cast<uint64_t>(dims[i]))
      return Status::IOError("tensor dims overflow");
    elems *= static_cast<uint64_t>(dims[i]);
  }
  // payload must fit in what's left of the frame — rejects corrupt or
  // malicious headers before the allocation can throw on a pool thread
  if (elems * DTypeSize(static_cast<DType>(dt)) > r->remaining())
    return Status::IOError("tensor payload exceeds frame");
  Tensor t(static_cast<DType>(dt), dims);
  if (!r->GetRaw(t.raw(), t.ByteSize()))
    return Status::IOError("truncated tensor data");
  *out = std::move(t);
  return Status::OK();
}

void EncodeNodeDef(const NodeDef& n, ByteWriter* w) {
  w->PutStr(n.name);
  w->PutStr(n.op);
  PutStrList(n.inputs, w);
  PutStrList(n.attrs, w);
  PutStrList(n.post_process, w);
  w->Put<uint32_t>(static_cast<uint32_t>(n.dnf.size()));
  for (const auto& conj : n.dnf) PutStrList(conj, w);
  w->Put<int32_t>(n.shard_idx);
  PutStrList(n.also_produces, w);
  w->Put<uint32_t>(static_cast<uint32_t>(n.inner.size()));
  for (const auto& in : n.inner) EncodeNodeDef(in, w);
}

Status DecodeNodeDef(ByteReader* r, NodeDef* out) {
  if (!r->GetStr(&out->name) || !r->GetStr(&out->op))
    return Status::IOError("truncated node header");
  ET_RETURN_IF_ERROR(GetStrList(r, &out->inputs));
  ET_RETURN_IF_ERROR(GetStrList(r, &out->attrs));
  ET_RETURN_IF_ERROR(GetStrList(r, &out->post_process));
  uint32_t n_dnf;
  if (!r->Get(&n_dnf)) return Status::IOError("truncated dnf");
  out->dnf.resize(n_dnf);
  for (uint32_t i = 0; i < n_dnf; ++i)
    ET_RETURN_IF_ERROR(GetStrList(r, &out->dnf[i]));
  uint32_t n_inner;
  if (!r->Get(&out->shard_idx)) return Status::IOError("truncated node tail");
  ET_RETURN_IF_ERROR(GetStrList(r, &out->also_produces));
  if (!r->Get(&n_inner)) return Status::IOError("truncated node tail");
  out->inner.resize(n_inner);
  for (uint32_t i = 0; i < n_inner; ++i)
    ET_RETURN_IF_ERROR(DecodeNodeDef(r, &out->inner[i]));
  return Status::OK();
}

void EncodeDag(const std::vector<NodeDef>& nodes, ByteWriter* w) {
  w->Put<uint32_t>(static_cast<uint32_t>(nodes.size()));
  for (const auto& n : nodes) EncodeNodeDef(n, w);
}

Status DecodeDag(ByteReader* r, std::vector<NodeDef>* out) {
  uint32_t n;
  if (!r->Get(&n)) return Status::IOError("truncated dag");
  out->resize(n);
  for (uint32_t i = 0; i < n; ++i)
    ET_RETURN_IF_ERROR(DecodeNodeDef(r, &(*out)[i]));
  return Status::OK();
}

void EncodeExecuteRequest(const ExecuteRequest& req, ByteWriter* w) {
  w->Put<uint32_t>(kExecMagic);
  w->Put<uint32_t>(static_cast<uint32_t>(req.inputs.size()));
  for (const auto& kv : req.inputs) {
    w->PutStr(kv.first);
    EncodeTensor(kv.second, w);
  }
  EncodeDag(req.nodes, w);
  PutStrList(req.outputs, w);
}

Status DecodeExecuteRequest(ByteReader* r, ExecuteRequest* out) {
  uint32_t magic, n_in;
  if (!r->Get(&magic) || magic != kExecMagic)
    return Status::IOError("bad execute request magic");
  if (!r->Get(&n_in)) return Status::IOError("truncated request");
  out->inputs.resize(n_in);
  for (uint32_t i = 0; i < n_in; ++i) {
    if (!r->GetStr(&out->inputs[i].first))
      return Status::IOError("truncated input name");
    ET_RETURN_IF_ERROR(DecodeTensor(r, &out->inputs[i].second));
  }
  ET_RETURN_IF_ERROR(DecodeDag(r, &out->nodes));
  return GetStrList(r, &out->outputs);
}

void EncodeExecuteReply(const ExecuteReply& rep, ByteWriter* w) {
  // sizing pass: total reply size is cheap to compute up front (names
  // + tensor headers + payload bytes), so one reserve kills the
  // realloc churn a multi-megabyte gather reply used to pay
  size_t total = 4 + 4 + rep.status.message().size();
  if (rep.status.ok())
    for (const auto& kv : rep.outputs)
      total += 4 + kv.first.size() + EncodedTensorSize(kv.second);
  w->Reserve(total);
  w->Put<uint32_t>(static_cast<uint32_t>(rep.status.code()));
  w->PutStr(rep.status.message());
  if (!rep.status.ok()) return;
  w->Put<uint32_t>(static_cast<uint32_t>(rep.outputs.size()));
  for (const auto& kv : rep.outputs) {
    w->PutStr(kv.first);
    EncodeTensor(kv.second, w);
  }
}

void EncodeExecutePlan(const ExecuteRequest& req, ByteWriter* w) {
  w->Put<uint32_t>(kPlanMagic);
  EncodeDag(req.nodes, w);
  PutStrList(req.outputs, w);
}

Status DecodeExecutePlan(ByteReader* r, ExecuteRequest* out) {
  uint32_t magic;
  if (!r->Get(&magic) || magic != kPlanMagic)
    return Status::IOError("bad execute plan magic");
  ET_RETURN_IF_ERROR(DecodeDag(r, &out->nodes));
  return GetStrList(r, &out->outputs);
}

void EncodeExecuteFeeds(const ExecuteRequest& req, ByteWriter* w) {
  size_t total = 8;
  for (const auto& kv : req.inputs)
    total += 4 + kv.first.size() + EncodedTensorSize(kv.second);
  w->Reserve(total);
  w->Put<uint32_t>(kFeedsMagic);
  w->Put<uint32_t>(static_cast<uint32_t>(req.inputs.size()));
  for (const auto& kv : req.inputs) {
    w->PutStr(kv.first);
    EncodeTensor(kv.second, w);
  }
}

Status DecodeExecuteFeeds(ByteReader* r, ExecuteRequest* out) {
  uint32_t magic, n_in;
  if (!r->Get(&magic) || magic != kFeedsMagic)
    return Status::IOError("bad execute feeds magic");
  if (!r->Get(&n_in)) return Status::IOError("truncated feeds");
  out->inputs.resize(n_in);
  for (uint32_t i = 0; i < n_in; ++i) {
    if (!r->GetStr(&out->inputs[i].first))
      return Status::IOError("truncated feed name");
    ET_RETURN_IF_ERROR(DecodeTensor(r, &out->inputs[i].second));
  }
  return Status::OK();
}

Status AssembleFullExecuteRequest(const std::vector<char>& feeds,
                                  const std::vector<char>& plan,
                                  std::vector<char>* out) {
  // 'ETEY' | feeds minus its magic | plan minus its magic — exactly the
  // EncodeExecuteRequest layout (magic | n_inputs | inputs | dag |
  // outputs). Magic-checked so a swapped-argument caller fails fast.
  uint32_t fm = 0, pm = 0;
  if (feeds.size() < 4 || plan.size() < 4) return Status::IOError("short");
  std::memcpy(&fm, feeds.data(), 4);
  std::memcpy(&pm, plan.data(), 4);
  if (fm != kFeedsMagic || pm != kPlanMagic)
    return Status::IOError("assemble: bad feeds/plan magic");
  out->clear();
  out->reserve(feeds.size() + plan.size() - 4);
  out->insert(out->end(), reinterpret_cast<const char*>(&kExecMagic),
              reinterpret_cast<const char*>(&kExecMagic) + 4);
  out->insert(out->end(), feeds.begin() + 4, feeds.end());
  out->insert(out->end(), plan.begin() + 4, plan.end());
  return Status::OK();
}

uint64_t PlanContentHash(const char* p, size_t n) {
  uint64_t h = 1469598103934665603ULL;  // FNV-1a 64 offset basis
  for (size_t i = 0; i < n; ++i) {
    h ^= static_cast<unsigned char>(p[i]);
    h *= 1099511628211ULL;
  }
  return h != 0 ? h : 1;  // 0 is the "no plan" sentinel on the wire
}

void EncodeExecuteReplySegments(ExecuteReply&& rep, ReplySegments* out) {
  out->runs.clear();
  out->tensors.clear();
  out->total = 0;
  ByteWriter& m = out->meta;
  size_t meta_total = 4 + 4 + rep.status.message().size();
  if (rep.status.ok())
    for (const auto& kv : rep.outputs)
      meta_total += 4 + kv.first.size() + 16 + 8 * kv.second.rank();
  m.Reserve(meta_total);
  size_t run_start = 0;
  auto close_meta_run = [&] {
    if (m.buffer().size() > run_start)
      out->runs.push_back({run_start, m.buffer().size() - run_start, -1});
    run_start = m.buffer().size();
  };
  m.Put<uint32_t>(static_cast<uint32_t>(rep.status.code()));
  m.PutStr(rep.status.message());
  if (rep.status.ok()) {
    m.Put<uint32_t>(static_cast<uint32_t>(rep.outputs.size()));
    for (auto& kv : rep.outputs) {
      m.PutStr(kv.first);
      // the EncodeTensor header, inline in the meta stream; the payload
      // becomes a view into the pinned tensor instead of a copy
      m.Put<int32_t>(static_cast<int32_t>(kv.second.dtype()));
      m.Put<uint32_t>(static_cast<uint32_t>(kv.second.rank()));
      for (int64_t d : kv.second.dims()) m.Put<int64_t>(d);
      if (kv.second.ByteSize() > 0) {
        close_meta_run();
        out->runs.push_back({0, kv.second.ByteSize(),
                             static_cast<int>(out->tensors.size())});
        out->tensors.push_back(std::move(kv.second));
      }
    }
  }
  close_meta_run();
  for (const auto& r : out->runs) out->total += r.len;
}

Status DecodeExecuteReply(ByteReader* r, ExecuteReply* out) {
  uint32_t code;
  std::string msg;
  if (!r->Get(&code) || !r->GetStr(&msg))
    return Status::IOError("truncated reply header");
  out->status = Status(static_cast<Code>(code), msg);
  if (!out->status.ok()) return Status::OK();
  uint32_t n;
  if (!r->Get(&n)) return Status::IOError("truncated reply");
  out->outputs.resize(n);
  for (uint32_t i = 0; i < n; ++i) {
    if (!r->GetStr(&out->outputs[i].first))
      return Status::IOError("truncated output name");
    ET_RETURN_IF_ERROR(DecodeTensor(r, &out->outputs[i].second));
  }
  return Status::OK();
}

}  // namespace et
