#include "serde.h"

namespace et {

namespace {
// Wire-format tag. v2 ('ETEY') added NodeDef::also_produces mid-record;
// a mixed-version client/server pair fails fast on the magic check
// instead of misreading the record.
constexpr uint32_t kExecMagic = 0x59455445;  // 'ETEY'

void PutStrList(const std::vector<std::string>& v, ByteWriter* w) {
  w->Put<uint32_t>(static_cast<uint32_t>(v.size()));
  for (const auto& s : v) w->PutStr(s);
}

Status GetStrList(ByteReader* r, std::vector<std::string>* out) {
  uint32_t n;
  if (!r->Get(&n)) return Status::IOError("truncated string list");
  out->resize(n);
  for (uint32_t i = 0; i < n; ++i)
    if (!r->GetStr(&(*out)[i])) return Status::IOError("truncated string");
  return Status::OK();
}
}  // namespace

void EncodeTensor(const Tensor& t, ByteWriter* w) {
  w->Put<int32_t>(static_cast<int32_t>(t.dtype()));
  w->Put<uint32_t>(static_cast<uint32_t>(t.rank()));
  for (int64_t d : t.dims()) w->Put<int64_t>(d);
  w->PutRaw(t.raw(), t.ByteSize());
}

Status DecodeTensor(ByteReader* r, Tensor* out) {
  int32_t dt;
  uint32_t rank;
  if (!r->Get(&dt) || !r->Get(&rank))
    return Status::IOError("truncated tensor header");
  if (dt < 0 || dt > 4) return Status::IOError("bad dtype");
  if (rank > 16) return Status::IOError("bad tensor rank");
  std::vector<int64_t> dims(rank);
  uint64_t elems = 1;
  for (uint32_t i = 0; i < rank; ++i) {
    if (!r->Get(&dims[i])) return Status::IOError("truncated dims");
    if (dims[i] < 0) return Status::IOError("negative tensor dim");
    if (dims[i] > 0 && elems > (1ull << 40) / static_cast<uint64_t>(dims[i]))
      return Status::IOError("tensor dims overflow");
    elems *= static_cast<uint64_t>(dims[i]);
  }
  // payload must fit in what's left of the frame — rejects corrupt or
  // malicious headers before the allocation can throw on a pool thread
  if (elems * DTypeSize(static_cast<DType>(dt)) > r->remaining())
    return Status::IOError("tensor payload exceeds frame");
  Tensor t(static_cast<DType>(dt), dims);
  if (!r->GetRaw(t.raw(), t.ByteSize()))
    return Status::IOError("truncated tensor data");
  *out = std::move(t);
  return Status::OK();
}

void EncodeNodeDef(const NodeDef& n, ByteWriter* w) {
  w->PutStr(n.name);
  w->PutStr(n.op);
  PutStrList(n.inputs, w);
  PutStrList(n.attrs, w);
  PutStrList(n.post_process, w);
  w->Put<uint32_t>(static_cast<uint32_t>(n.dnf.size()));
  for (const auto& conj : n.dnf) PutStrList(conj, w);
  w->Put<int32_t>(n.shard_idx);
  PutStrList(n.also_produces, w);
  w->Put<uint32_t>(static_cast<uint32_t>(n.inner.size()));
  for (const auto& in : n.inner) EncodeNodeDef(in, w);
}

Status DecodeNodeDef(ByteReader* r, NodeDef* out) {
  if (!r->GetStr(&out->name) || !r->GetStr(&out->op))
    return Status::IOError("truncated node header");
  ET_RETURN_IF_ERROR(GetStrList(r, &out->inputs));
  ET_RETURN_IF_ERROR(GetStrList(r, &out->attrs));
  ET_RETURN_IF_ERROR(GetStrList(r, &out->post_process));
  uint32_t n_dnf;
  if (!r->Get(&n_dnf)) return Status::IOError("truncated dnf");
  out->dnf.resize(n_dnf);
  for (uint32_t i = 0; i < n_dnf; ++i)
    ET_RETURN_IF_ERROR(GetStrList(r, &out->dnf[i]));
  uint32_t n_inner;
  if (!r->Get(&out->shard_idx)) return Status::IOError("truncated node tail");
  ET_RETURN_IF_ERROR(GetStrList(r, &out->also_produces));
  if (!r->Get(&n_inner)) return Status::IOError("truncated node tail");
  out->inner.resize(n_inner);
  for (uint32_t i = 0; i < n_inner; ++i)
    ET_RETURN_IF_ERROR(DecodeNodeDef(r, &out->inner[i]));
  return Status::OK();
}

void EncodeDag(const std::vector<NodeDef>& nodes, ByteWriter* w) {
  w->Put<uint32_t>(static_cast<uint32_t>(nodes.size()));
  for (const auto& n : nodes) EncodeNodeDef(n, w);
}

Status DecodeDag(ByteReader* r, std::vector<NodeDef>* out) {
  uint32_t n;
  if (!r->Get(&n)) return Status::IOError("truncated dag");
  out->resize(n);
  for (uint32_t i = 0; i < n; ++i)
    ET_RETURN_IF_ERROR(DecodeNodeDef(r, &(*out)[i]));
  return Status::OK();
}

void EncodeExecuteRequest(const ExecuteRequest& req, ByteWriter* w) {
  w->Put<uint32_t>(kExecMagic);
  w->Put<uint32_t>(static_cast<uint32_t>(req.inputs.size()));
  for (const auto& kv : req.inputs) {
    w->PutStr(kv.first);
    EncodeTensor(kv.second, w);
  }
  EncodeDag(req.nodes, w);
  PutStrList(req.outputs, w);
}

Status DecodeExecuteRequest(ByteReader* r, ExecuteRequest* out) {
  uint32_t magic, n_in;
  if (!r->Get(&magic) || magic != kExecMagic)
    return Status::IOError("bad execute request magic");
  if (!r->Get(&n_in)) return Status::IOError("truncated request");
  out->inputs.resize(n_in);
  for (uint32_t i = 0; i < n_in; ++i) {
    if (!r->GetStr(&out->inputs[i].first))
      return Status::IOError("truncated input name");
    ET_RETURN_IF_ERROR(DecodeTensor(r, &out->inputs[i].second));
  }
  ET_RETURN_IF_ERROR(DecodeDag(r, &out->nodes));
  return GetStrList(r, &out->outputs);
}

void EncodeExecuteReply(const ExecuteReply& rep, ByteWriter* w) {
  w->Put<uint32_t>(static_cast<uint32_t>(rep.status.code()));
  w->PutStr(rep.status.message());
  if (!rep.status.ok()) return;
  w->Put<uint32_t>(static_cast<uint32_t>(rep.outputs.size()));
  for (const auto& kv : rep.outputs) {
    w->PutStr(kv.first);
    EncodeTensor(kv.second, w);
  }
}

Status DecodeExecuteReply(ByteReader* r, ExecuteReply* out) {
  uint32_t code;
  std::string msg;
  if (!r->Get(&code) || !r->GetStr(&msg))
    return Status::IOError("truncated reply header");
  out->status = Status(static_cast<Code>(code), msg);
  if (!out->status.ok()) return Status::OK();
  uint32_t n;
  if (!r->Get(&n)) return Status::IOError("truncated reply");
  out->outputs.resize(n);
  for (uint32_t i = 0; i < n; ++i) {
    if (!r->GetStr(&out->outputs[i].first))
      return Status::IOError("truncated output name");
    ET_RETURN_IF_ERROR(DecodeTensor(r, &out->outputs[i].second));
  }
  return Status::OK();
}

}  // namespace et
