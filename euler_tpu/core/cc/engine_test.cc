// Native engine self-test binary — assert-style unit tests over the C++
// core, runnable standalone and under sanitizers:
//
//   make test        # build + run (O2)
//   make asan        # AddressSanitizer build + run
//   make tsan        # ThreadSanitizer build + run (race detection — the
//                    # CI the reference lacked, SURVEY.md §5)
//
// Mirrors the reference's gtest tiers (SURVEY.md §4): common (samplers,
// threadpool, rng), graph store, serde, executor, index, compiler.
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <thread>
#include <csignal>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#include "common.h"
#include "dag.h"
#include "gql.h"
#include "graph.h"
#include "index.h"
#include "io.h"
#include "kernels_common.h"
#include "rpc.h"
#include "sampling.h"
#include "serde.h"
#include "store.h"
#include "tensor.h"
#include "threadpool.h"
#include "udf.h"
#include "wal.h"

namespace et {
namespace {

int g_failures = 0;

#define CHECK_TRUE(cond)                                              \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,    \
                   #cond);                                            \
      ++g_failures;                                                   \
    }                                                                 \
  } while (0)

#define CHECK_OK(expr)                                                \
  do {                                                                \
    ::et::Status _s = (expr);                                         \
    if (!_s.ok()) {                                                   \
      std::fprintf(stderr, "FAIL %s:%d: %s -> %s\n", __FILE__,        \
                   __LINE__, #expr, _s.message().c_str());            \
      ++g_failures;                                                   \
    }                                                                 \
  } while (0)

// ---- common: rng, samplers, threadpool ----
void TestPcg32Determinism() {
  Pcg32 a(42, 1), b(42, 1), c(43, 1);
  bool same = true, diff = false;
  for (int i = 0; i < 100; ++i) {
    uint32_t x = a.NextU32(), y = b.NextU32(), z = c.NextU32();
    same &= (x == y);
    diff |= (x != z);
  }
  CHECK_TRUE(same);
  CHECK_TRUE(diff);
}

void TestAliasSamplerStatistics() {
  // weights 1,2,3,4 → frequencies ∝ weight (statistical test like the
  // reference's fast_weighted_collection_test.cc)
  std::vector<float> w{1, 2, 3, 4};
  AliasSampler s;
  s.Init(w);
  Pcg32 rng(7);
  std::vector<int> counts(4, 0);
  const int N = 200000;
  for (int i = 0; i < N; ++i) counts[s.Sample(&rng)]++;
  for (int i = 0; i < 4; ++i) {
    double expect = N * w[i] / 10.0;
    CHECK_TRUE(std::fabs(counts[i] - expect) < 5 * std::sqrt(expect));
  }
}

void TestParallelForCoversAll() {
  std::vector<std::atomic<int>> hits(10000);
  ParallelFor(GlobalThreadPool(), 10000, 64,
              [&](int64_t b, int64_t e, int) {
                for (int64_t i = b; i < e; ++i) hits[i].fetch_add(1);
              });
  for (auto& h : hits) CHECK_TRUE(h.load() == 1);
}

void TestThreadPoolStress() {
  // many tiny tasks racing on an atomic — trips TSAN if the queue or
  // latch were racy
  std::atomic<int64_t> sum{0};
  ThreadPool pool(8);
  std::atomic<int> remaining{10000};
  std::mutex mu;
  std::condition_variable cv;
  for (int i = 0; i < 10000; ++i) {
    pool.Schedule([&, i] {
      sum.fetch_add(i);
      // decrement under mu: if the decrement were outside, the main
      // thread could observe 0 and destroy mu/cv while this worker is
      // about to lock them (UB caught by review r4)
      std::lock_guard<std::mutex> lk(mu);
      if (remaining.fetch_sub(1) == 1) cv.notify_one();
    });
  }
  std::unique_lock<std::mutex> lk(mu);
  cv.wait(lk, [&] { return remaining.load() == 0; });
  CHECK_TRUE(sum.load() == 10000LL * 9999 / 2);
}

void TestThreadPoolPriorityLanes() {
  // Both workers of a 2-thread pool get parked on long LOW tasks, six
  // more LOW tasks queue behind them, then one HIGH task arrives. The
  // high-preferring worker (idx 1) must take the HIGH task as soon as
  // it frees — ahead of the whole queued LOW backlog — while worker 0
  // keeps draining LOW (the anti-starvation guarantee).
  ThreadPool pool(2);
  std::mutex mu;
  std::condition_variable cv;
  int low_done = 0;
  bool high_done = false;
  int low_done_at_high = -1;
  std::atomic<bool> gate{false};
  for (int i = 0; i < 8; ++i) {
    pool.Schedule(
        [&] {
          // first two occupy the workers until the HIGH task is queued
          while (!gate.load()) ::usleep(500);
          ::usleep(5000);
          std::lock_guard<std::mutex> lk(mu);
          ++low_done;
          cv.notify_all();
        },
        ThreadPool::kLow);
  }
  pool.Schedule([&] {
    std::lock_guard<std::mutex> lk(mu);
    high_done = true;
    low_done_at_high = low_done;
    cv.notify_all();
  });  // default lane: kHigh
  gate.store(true);
  std::unique_lock<std::mutex> lk(mu);
  cv.wait(lk, [&] { return high_done; });
  // the high task may wait for ONE in-flight low per worker, never for
  // the queued backlog (6 lows were still queued when it arrived)
  CHECK_TRUE(low_done_at_high <= 4);
  cv.wait(lk, [&] { return low_done == 8; });  // lanes both drain
}

// ---- graph store ----
std::unique_ptr<Graph> RingGraph() {
  GraphBuilder b;
  for (uint64_t i = 1; i <= 10; ++i)
    b.AddNode(i, static_cast<int32_t>(i % 2), static_cast<float>(i));
  for (uint64_t i = 1; i <= 10; ++i)
    b.AddEdge(i, i % 10 + 1, 0, 1.0f);
  b.mutable_meta()->node_features.push_back(
      {"f", FeatureKind::kDense, 2});
  for (uint64_t i = 1; i <= 10; ++i) {
    float v[2] = {static_cast<float>(i), -static_cast<float>(i)};
    b.SetNodeDense(i, 0, v, 2);
  }
  return b.Finalize();
}

void TestGraphStore() {
  auto g = RingGraph();
  CHECK_TRUE(g->node_count() == 10);
  CHECK_TRUE(g->edge_count() == 10);
  Pcg32 rng(1);
  NodeId nb;
  float w;
  int32_t t;
  g->SampleNeighbor(4, nullptr, 0, 1, 0, &rng, &nb, &w, &t);
  CHECK_TRUE(nb == 5);
  float f[2];
  NodeId id = 7;
  g->GetDenseFeature(&id, 1, 0, 2, f);
  CHECK_TRUE(f[0] == 7.0f && f[1] == -7.0f);
  // unknown id zero-fills
  id = 999;
  g->GetDenseFeature(&id, 1, 0, 2, f);
  CHECK_TRUE(f[0] == 0.0f && f[1] == 0.0f);
}

void TestConcurrentSampling() {
  // immutable graph + per-thread rngs: concurrent readers must be clean
  // under TSAN
  auto g = RingGraph();
  ThreadPool pool(8);
  std::atomic<int> remaining{64};
  std::atomic<bool> ok{true};
  std::mutex mu;
  std::condition_variable cv;
  for (int t0 = 0; t0 < 64; ++t0) {
    pool.Schedule([&, t0] {
      Pcg32 rng(t0);
      NodeId out[8];
      g->SampleNode(-1, 8, &rng, out);
      for (NodeId id : out)
        if (id < 1 || id > 10) ok.store(false);
      std::lock_guard<std::mutex> lk(mu);  // see TestThreadPoolStress
      if (remaining.fetch_sub(1) == 1) cv.notify_one();
    });
  }
  std::unique_lock<std::mutex> lk(mu);
  cv.wait(lk, [&] { return remaining.load() == 0; });
  CHECK_TRUE(ok.load());
}

void TestUdfResultCacheConcurrent() {
  // the UDF result cache is hit from the executor's thread pool: hammer
  // Get/Put/Clear/SetCapacity from many threads under TSAN; then check
  // the single-threaded contract (hit returns the stored column,
  // collision-by-construction verifies as a miss).
  auto& c = UdfResultCache::Instance();
  c.SetCapacityBytes(1u << 20);
  c.Clear();
  ThreadPool pool(8);
  std::atomic<int> remaining{64};
  std::mutex mu;
  std::condition_variable cv;
  for (int t0 = 0; t0 < 64; ++t0) {
    pool.Schedule([&, t0] {
      std::vector<uint64_t> ids = {static_cast<uint64_t>(t0 % 8)};
      uint64_t key = UdfCacheKey(1, 0, "udf:mean", 0, ids.data(), 1);
      auto hit = c.Get(key, 1, 0, "udf:mean", 0, ids.data(), 1);
      if (!hit) {
        auto col = std::make_shared<CachedColumn>();
        col->graph_uid = 1;
        col->generation = 0;
        col->spec = "udf:mean";
        col->fid = 0;
        col->ids = ids;
        col->offs = {0, 1};
        col->vals = {static_cast<float>(t0 % 8)};
        c.Put(key, std::move(col));
      }
      if (t0 % 16 == 3) c.Clear();
      if (t0 % 16 == 7) c.SetCapacityBytes(1u << 19);
      std::lock_guard<std::mutex> lk(mu);  // see TestThreadPoolStress
      if (remaining.fetch_sub(1) == 1) cv.notify_one();
    });
  }
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return remaining.load() == 0; });
  }
  // single-threaded contract
  c.SetCapacityBytes(1u << 20);
  c.Clear();
  std::vector<uint64_t> ids = {42};
  uint64_t key = UdfCacheKey(9, 3, "udf:scale:2", 1, ids.data(), 1);
  CHECK_TRUE(c.Get(key, 9, 3, "udf:scale:2", 1, ids.data(), 1) == nullptr);
  auto col = std::make_shared<CachedColumn>();
  col->graph_uid = 9;
  col->generation = 3;
  col->spec = "udf:scale:2";
  col->fid = 1;
  col->ids = ids;
  col->offs = {0, 2};
  col->vals = {1.f, 2.f};
  c.Put(key, col);
  auto hit = c.Get(key, 9, 3, "udf:scale:2", 1, ids.data(), 1);
  CHECK_TRUE(hit != nullptr && hit->vals.size() == 2);
  // same bucket, different full key (simulated collision) → miss
  CHECK_TRUE(c.Get(key, 9, 4, "udf:scale:2", 1, ids.data(), 1) == nullptr);
  uint64_t h, m, e, b;
  c.Stats(&h, &m, &e, &b);
  CHECK_TRUE(e >= 1 && b > 0);
  // restore the production default: the cache is a process singleton
  // and later tests must not inherit this test's tiny capacity
  c.SetCapacityBytes(64u << 20);
  c.Clear();
}

// ---- serde ----
void TestTensorSerde() {
  Tensor t(DType::kF32, {2, 3});
  for (int i = 0; i < 6; ++i) t.Flat<float>()[i] = i * 1.5f;
  ByteWriter w;
  EncodeTensor(t, &w);
  ByteReader r(w.buffer().data(), w.buffer().size());
  Tensor back;
  CHECK_OK(DecodeTensor(&r, &back));
  CHECK_TRUE(back.dims() == t.dims());
  CHECK_TRUE(std::memcmp(back.raw(), t.raw(), t.ByteSize()) == 0);

  // corrupt header must be rejected, not crash
  std::vector<char> evil(w.buffer());
  int64_t huge = 1LL << 50;
  std::memcpy(evil.data() + 8, &huge, 8);
  ByteReader r2(evil.data(), evil.size());
  Tensor bad;
  CHECK_TRUE(!DecodeTensor(&r2, &bad).ok());
}

// ---- executor ----
void TestExecutorRunsDag() {
  // the fusion assertions below require FuseLocalPass active; restore
  // the caller's knob afterwards so a NO_FUSE suite run stays NO_FUSE
  const char* saved_ptr = getenv("EULER_TPU_NO_FUSE");
  // copy before unsetenv: POSIX allows unsetenv to invalidate the pointer
  std::string saved_no_fuse = saved_ptr != nullptr ? saved_ptr : "";
  bool had_no_fuse = saved_ptr != nullptr;
  unsetenv("EULER_TPU_NO_FUSE");
  struct RestoreEnv {
    std::string saved;
    bool had;
    ~RestoreEnv() {
      if (had) setenv("EULER_TPU_NO_FUSE", saved.c_str(), 1);
    }
  } restore{saved_no_fuse, had_no_fuse};
  // AS chain through the executor against a real graph
  auto g = RingGraph();
  CompileOptions opts;
  opts.mode = "local";
  GqlCompiler compiler(opts);
  std::shared_ptr<const TranslateResult> plan;
  CHECK_OK(compiler.Compile("v(roots).getNB(*).as(nb)", &plan));
  OpKernelContext ctx;
  Tensor roots(DType::kU64, {2});
  roots.Flat<uint64_t>()[0] = 3;
  roots.Flat<uint64_t>()[1] = 9;
  ctx.Put("roots", std::move(roots));
  QueryEnv env;
  env.graph = g.get();
  Executor exec(&plan->dag, env, &ctx);
  CHECK_OK(exec.RunSync());
  Tensor out;
  CHECK_TRUE(ctx.Get("nb:1", &out));
  CHECK_TRUE(out.NumElements() == 2);
  CHECK_TRUE(out.Flat<uint64_t>()[0] == 4);
  CHECK_TRUE(out.Flat<uint64_t>()[1] == 10);

  // local mode fuses the whole plan into one FUSED node (FuseLocalPass);
  // assert that so the sanitizer runs exercise FusedOp intentionally
  CHECK_TRUE(plan->dag.nodes.size() == 1);
  CHECK_TRUE(plan->dag.nodes[0].op == "FUSED");
  CHECK_TRUE(plan->dag.nodes[0].inner.size() >= 2);

  // a multi-hop sampling chain through the fused path
  std::shared_ptr<const TranslateResult> plan2;
  CHECK_OK(compiler.Compile(
      "v(roots).sampleNB(*, 3, 0).as(h0).sampleNB(*, 2, 0).as(h1)", &plan2));
  OpKernelContext ctx2;
  Tensor roots2(DType::kU64, {2});
  roots2.Flat<uint64_t>()[0] = 1;
  roots2.Flat<uint64_t>()[1] = 5;
  ctx2.Put("roots", std::move(roots2));
  Executor exec2(&plan2->dag, env, &ctx2);
  CHECK_OK(exec2.RunSync());
  Tensor h1;
  CHECK_TRUE(ctx2.Get("h1:1", &h1));
  CHECK_TRUE(h1.NumElements() == 2 * 3 * 2);
}

// ---- index ----
void TestIndexDnf() {
  auto g = RingGraph();
  IndexManager idx;
  CHECK_OK(idx.BuildFromSpec(*g, "f:range_index"));
  IndexResult res;
  CHECK_OK(idx.EvalDnf(g.get(), {{"f gt 8"}}, &res));
  CHECK_TRUE(res.rows.size() == 2);  // f = 9, 10
  // id membership keeps (row, weight) pairing even out of order
  IndexResult r2;
  CHECK_OK(idx.EvalDnf(g.get(), {{"id in 9:2"}}, &r2));
  CHECK_TRUE(r2.rows.size() == 2);
  std::map<uint32_t, float> got;
  for (size_t i = 0; i < r2.rows.size(); ++i) got[r2.rows[i]] = r2.weights[i];
  CHECK_TRUE(got[g->NodeIndex(9)] == 9.0f);
  CHECK_TRUE(got[g->NodeIndex(2)] == 2.0f);
}

// ---- dump/load ----
void TestDumpLoadRoundtrip() {
  auto g = RingGraph();
  std::string dir = "/tmp/et_engine_test_dump";
  std::string cmd = "mkdir -p " + dir;
  CHECK_TRUE(std::system(cmd.c_str()) == 0);
  CHECK_OK(DumpGraphPartitioned(*g, dir, 2));
  std::unique_ptr<Graph> back;
  CHECK_OK(LoadShard(dir, 0, 1, 0, true, &back));
  CHECK_TRUE(back->node_count() == 10);
  CHECK_TRUE(back->edge_count() == 10);
}

// ---- out-of-core columnar store ----
// One hub (node 1, degree 63) plus a sparse tail, two node/edge types,
// every feature kind — exercises each column family the store
// serializes and gives the hub-first hot-set chooser a clear winner.
std::unique_ptr<Graph> OutcoreGraph() {
  GraphBuilder b;
  for (uint64_t i = 1; i <= 64; ++i)
    b.AddNode(i, static_cast<int32_t>(i % 2), static_cast<float>(i));
  for (uint64_t i = 2; i <= 64; ++i)
    b.AddEdge(1, i, 0, static_cast<float>(i));
  for (uint64_t i = 2; i <= 64; ++i) b.AddEdge(i, i % 64 + 1, 1, 1.0f);
  b.mutable_meta()->node_features.push_back({"d", FeatureKind::kDense, 4});
  b.mutable_meta()->node_features.push_back({"s", FeatureKind::kSparse, 0});
  b.mutable_meta()->node_features.push_back({"b", FeatureKind::kBinary, 0});
  b.mutable_meta()->edge_features.push_back({"ed", FeatureKind::kDense, 2});
  for (uint64_t i = 1; i <= 64; ++i) {
    float v[4];
    for (int k = 0; k < 4; ++k) v[k] = static_cast<float>(i * 10 + k);
    b.SetNodeDense(i, 0, v, 4);
    uint64_t sp[2] = {i, i * 7};
    b.SetNodeSparse(i, 1, sp, 2);
    std::string bytes = "blob_" + std::to_string(i);
    b.SetNodeBinary(i, 2, bytes.data(), static_cast<int64_t>(bytes.size()));
  }
  for (uint64_t i = 2; i <= 64; ++i) {
    float ev[2] = {static_cast<float>(i), static_cast<float>(-2.0 * i)};
    b.SetEdgeDense(1, i, 0, 0, ev, 2);
  }
  return b.Finalize();
}

// Full-read parity between two graphs: adjacency (both directions),
// every feature kind, and seeded sampler draws. The store's contract is
// byte-identity with its heap twin, so equality here is exact.
void CheckGraphParity(const Graph& a, const Graph& b) {
  CHECK_TRUE(a.node_count() == b.node_count());
  CHECK_TRUE(a.edge_count() == b.edge_count());
  CHECK_TRUE(a.epoch() == b.epoch());
  for (uint64_t id = 1; id <= a.node_count() + 1; ++id) {
    std::vector<NodeId> ia, ib;
    std::vector<float> wa, wb;
    std::vector<int32_t> ta, tb;
    a.GetFullNeighbor(id, nullptr, 0, &ia, &wa, &ta);
    b.GetFullNeighbor(id, nullptr, 0, &ib, &wb, &tb);
    CHECK_TRUE(ia == ib && wa == wb && ta == tb);
    ia.clear(); ib.clear(); wa.clear(); wb.clear(); ta.clear(); tb.clear();
    a.GetFullInNeighbor(id, nullptr, 0, &ia, &wa, &ta);
    b.GetFullInNeighbor(id, nullptr, 0, &ib, &wb, &tb);
    CHECK_TRUE(ia == ib && wa == wb && ta == tb);
    NodeId nid = id;
    float da[4] = {0}, db[4] = {0};
    a.GetDenseFeature(&nid, 1, 0, 4, da);
    b.GetDenseFeature(&nid, 1, 0, 4, db);
    CHECK_TRUE(std::memcmp(da, db, sizeof(da)) == 0);
    std::vector<uint64_t> oa, ob, va, vb;
    a.GetSparseFeature(&nid, 1, 1, &oa, &va);
    b.GetSparseFeature(&nid, 1, 1, &ob, &vb);
    CHECK_TRUE(oa == ob && va == vb);
    std::vector<uint64_t> boa, bob;
    std::vector<char> bva, bvb;
    a.GetBinaryFeature(&nid, 1, 2, &boa, &bva);
    b.GetBinaryFeature(&nid, 1, 2, &bob, &bvb);
    CHECK_TRUE(boa == bob && bva == bvb);
  }
  {
    NodeId s = 1, d = 5;
    int32_t t = 0;
    float ea[2] = {0}, eb[2] = {0};
    a.GetEdgeDenseFeature(&s, &d, &t, 1, 0, 2, ea);
    b.GetEdgeDenseFeature(&s, &d, &t, 1, 0, 2, eb);
    CHECK_TRUE(std::memcmp(ea, eb, sizeof(ea)) == 0);
  }
  // Seeded draws must match stream-for-stream: the alias tables and the
  // row order serialized verbatim (never hub-sorted).
  Pcg32 ra(99), rb(99);
  NodeId sa[16], sb[16];
  a.SampleNode(-1, 16, &ra, sa);
  b.SampleNode(-1, 16, &rb, sb);
  CHECK_TRUE(std::memcmp(sa, sb, sizeof(sa)) == 0);
  float wsa[8], wsb[8];
  int32_t tsa[8], tsb[8];
  a.SampleNeighbor(1, nullptr, 0, 8, 0, &ra, sa, wsa, tsa);
  b.SampleNeighbor(1, nullptr, 0, 8, 0, &rb, sb, wsb, tsb);
  CHECK_TRUE(std::memcmp(sa, sb, 8 * sizeof(NodeId)) == 0);
  CHECK_TRUE(std::memcmp(wsa, wsb, sizeof(wsa)) == 0);
}

void TestColumnarStoreRoundtrip() {
  auto g = OutcoreGraph();
  CHECK_TRUE(std::system("mkdir -p /tmp/et_engine_test_store") == 0);
  std::string path = "/tmp/et_engine_test_store/columnar.etc";
  CHECK_OK(WriteColumnarStore(*g, path));

  auto& c = GlobalStoreCounters();
  uint64_t hits0 = c.hot_hits.load(), cold0 = c.cold_reads.load();
  // All-hot attach: every read classifies hot, none cold.
  std::unique_ptr<Graph> hot;
  CHECK_OK(LoadGraphFromStore(path, 1LL << 30, &hot));
  CHECK_TRUE(hot->attached());
  CHECK_TRUE(hot->tier() != nullptr);
  CHECK_TRUE(hot->tier()->hot_rows() == hot->node_count());
  CheckGraphParity(*g, *hot);
  CHECK_TRUE(c.hot_hits.load() > hits0);
  CHECK_TRUE(c.cold_reads.load() == cold0);

  // Zero-budget attach: parity still exact, reads classify cold and the
  // cold-read histogram moves.
  uint64_t hist_n0 = c.cold_hist.n.load();
  std::unique_ptr<Graph> cold;
  CHECK_OK(LoadGraphFromStore(path, 0, &cold));
  CHECK_TRUE(cold->tier()->hot_rows() == 0);
  CheckGraphParity(*g, *cold);
  CHECK_TRUE(c.cold_reads.load() > cold0);
  CHECK_TRUE(c.cold_hist.n.load() > hist_n0);

  // The stats snapshot surfaces the mapping gauges.
  uint64_t st[kStoreStatSlots];
  StoreStatsSnapshot(st);
  CHECK_TRUE(st[5] > 0);   // mapped_bytes
  CHECK_TRUE(st[7] >= 2);  // attaches
}

// The RAM overlay above the mmap base: applying the same delta to the
// heap twin and the attached graph must yield byte-identical snapshots
// (ISSUE gate: post-delta reads byte-identical to the RAM engine).
void TestColumnarStorePostDelta() {
  auto base = OutcoreGraph();
  CHECK_TRUE(std::system("mkdir -p /tmp/et_engine_test_store") == 0);
  std::string path = "/tmp/et_engine_test_store/delta.etc";
  CHECK_OK(WriteColumnarStore(*base, path));
  std::unique_ptr<Graph> mm;
  CHECK_OK(LoadGraphFromStore(path, 1 << 20, &mm));

  // update node 5's weight, add node 100, re-weight hub edge (1,2,0),
  // add a fresh edge (3,7,1)
  NodeId nids[2] = {5, 100};
  int32_t ntypes[2] = {1, 0};
  float nws[2] = {50.0f, 1.0f};
  NodeId esrc[2] = {1, 3}, edst[2] = {2, 7};
  int32_t etypes[2] = {0, 1};
  float ews[2] = {9.0f, 2.5f};
  std::unique_ptr<Graph> next_heap, next_mm;
  std::vector<NodeId> dirty_h, dirty_m;
  CHECK_OK(ApplyGraphDelta(*base, nids, ntypes, nws, 2, esrc, edst, etypes,
                           ews, 2, 0, 1, &next_heap, &dirty_h));
  CHECK_OK(ApplyGraphDelta(*mm, nids, ntypes, nws, 2, esrc, edst, etypes,
                           ews, 2, 0, 1, &next_mm, &dirty_m));
  CHECK_TRUE(dirty_h == dirty_m);
  CheckGraphParity(*next_heap, *next_mm);
  // the delta snapshot itself is a heap overlay until the next spill
  CHECK_TRUE(!next_mm->attached());
}

// WAL compaction emits the columnar sidecar; recovery with storage=mmap
// attaches it and replays the tail to the same graph the heap path
// rebuilds.
void TestWalColumnarSidecarRecovery() {
  std::string root = "/tmp/et_engine_test_walcol";
  CHECK_TRUE(std::system(("rm -rf " + root + " && mkdir -p " + root +
                          "/data " + root + "/wal").c_str()) == 0);
  auto g = OutcoreGraph();
  CHECK_OK(DumpGraphPartitioned(*g, root + "/data", 1));

  std::unique_ptr<DeltaWal> wal;
  CHECK_OK(DeltaWal::Open(root + "/wal", FsyncPolicy::kNever, 1, &wal));
  wal->set_columnar_sidecar(true);
  // one delta record (kApplyDelta wire body), epoch 0 -> 1
  ByteWriter body;
  NodeId nid = 200;
  int32_t ntype = 1;
  float nw = 3.0f;
  NodeId esrc = 200, edst = 1;
  int32_t etype = 0;
  float ew = 4.0f;
  body.Put<uint64_t>(1);
  body.PutRaw(&nid, sizeof(nid));
  body.PutRaw(&ntype, sizeof(ntype));
  body.PutRaw(&nw, sizeof(nw));
  body.Put<uint64_t>(1);
  body.PutRaw(&esrc, sizeof(esrc));
  body.PutRaw(&edst, sizeof(edst));
  body.PutRaw(&etype, sizeof(etype));
  body.PutRaw(&ew, sizeof(ew));
  CHECK_OK(wal->Append(1, body.buffer().data(), body.buffer().size()));

  // heap-path recovery replays the record…
  std::unique_ptr<Graph> heap_g;
  uint64_t replayed = 0;
  CHECK_OK(RecoverShard(root + "/wal", root + "/data", 0, 1, true, &heap_g,
                        &replayed));
  CHECK_TRUE(replayed == 1);
  CHECK_TRUE(heap_g->epoch() == 1);

  // …compaction snapshots it WITH the sidecar…
  CHECK_OK(wal->Compact(*heap_g));
  CHECK_TRUE(!wal->last_snapshot_dir().empty());
  std::string sidecar = wal->last_snapshot_dir() + "/" + kColumnarFileName;
  std::unique_ptr<Graph> side_g;
  CHECK_OK(LoadGraphFromStore(sidecar, 0, &side_g));
  CheckGraphParity(*heap_g, *side_g);

  // …and a fresh mmap-mode recovery attaches it (no pending tail).
  std::unique_ptr<Graph> mm_g;
  CHECK_OK(RecoverShard(root + "/wal", root + "/data", 0, 1, true, &mm_g,
                        nullptr, nullptr, nullptr, nullptr, 1, 1 << 20));
  CHECK_TRUE(mm_g->attached());
  CheckGraphParity(*heap_g, *mm_g);
}

// Hardening (review r18): shard-qualified sidecar names, freshness
// gating against re-dumped partition files, overflow-safe header
// bounds, typed-column size verification, and residency-gauge walks
// racing tier teardown.
void TestColumnarStoreHardening() {
  CHECK_TRUE(ColumnarSidecarName(0, 1) == std::string(kColumnarFileName));
  CHECK_TRUE(ColumnarSidecarName(2, 4) == "columnar.2of4.etc");

  std::string root = "/tmp/et_engine_test_fresh";
  CHECK_TRUE(
      std::system(("rm -rf " + root + " && mkdir -p " + root).c_str()) == 0);
  auto g = OutcoreGraph();
  CHECK_OK(DumpGraphPartitioned(*g, root, 1));
  std::string sidecar = root + "/" + kColumnarFileName;
  CHECK_TRUE(!SidecarIsFresh(root, sidecar));  // nothing spilled yet
  CHECK_OK(WriteColumnarStore(*g, sidecar));
  CHECK_TRUE(SidecarIsFresh(root, sidecar));  // spill postdates the parts
  // simulate an in-place re-dump (partition files newer than the
  // spill) by backdating the sidecar — deterministic even on coarse
  // mtime clocks, where touching a part file "now" can tie the spill
  struct timespec back[2];
  back[0].tv_sec = 0;
  back[0].tv_nsec = UTIME_OMIT;
  back[1].tv_sec = 1;  // epoch+1s: long before the partition files
  back[1].tv_nsec = 0;
  CHECK_TRUE(utimensat(AT_FDCWD, sidecar.c_str(), back, 0) == 0);
  CHECK_TRUE(!SidecarIsFresh(root, sidecar));
  // a sibling shard's spill is NOT a source file: it must not re-stale
  // this shard's fresh sidecar
  CHECK_OK(WriteColumnarStore(*g, sidecar));  // re-spill -> fresh again
  CHECK_TRUE(SidecarIsFresh(root, sidecar));
  CHECK_OK(WriteColumnarStore(*g, root + "/" + ColumnarSidecarName(1, 2)));
  CHECK_TRUE(SidecarIsFresh(root, sidecar));

  // typed Find rejects a size-mismatched column instead of
  // reinterpreting it (reads past the mapping otherwise)
  std::shared_ptr<ColumnarStore> store;
  CHECK_OK(ColumnarStore::Open(sidecar, &store));
  const uint64_t* p64 = nullptr;
  const float* p32 = nullptr;
  size_t n = 0;
  CHECK_TRUE(store->Find("node_ids", &p64, &n) && n > 0);  // u64: matches
  CHECK_TRUE(!store->Find("node_ids", &p32, &n));          // f32: rejected

  // corrupt header: a count whose byte size wraps uint64 must be
  // rejected, not accepted by an overflowed bounds check. The first
  // column entry ("aux", elem_size 1) puts count at byte 31.
  {
    std::FILE* f = std::fopen(sidecar.c_str(), "rb");
    CHECK_TRUE(f != nullptr);
    std::fseek(f, 0, SEEK_END);
    std::vector<char> bytes(std::ftell(f));
    std::fseek(f, 0, SEEK_SET);
    CHECK_TRUE(std::fread(bytes.data(), 1, bytes.size(), f) == bytes.size());
    std::fclose(f);
    uint64_t huge = ~0ULL;
    std::memcpy(bytes.data() + 31, &huge, sizeof(huge));
    std::string bad = root + "/bad.etc";
    f = std::fopen(bad.c_str(), "wb");
    CHECK_TRUE(f != nullptr &&
               std::fwrite(bytes.data(), 1, bytes.size(), f) == bytes.size());
    std::fclose(f);
    std::shared_ptr<ColumnarStore> rejected;
    CHECK_TRUE(!ColumnarStore::Open(bad, &rejected).ok());
  }

  // residency gauges vs. tier teardown: StoreStatsSnapshot walks the
  // tier registry while attach/destroy churns it (the reattach swap) —
  // the sanitizer targets fail here if the walk reads a dead tier
  std::atomic<bool> stop{false};
  std::thread scraper([&] {
    uint64_t st[kStoreStatSlots];
    while (!stop.load()) StoreStatsSnapshot(st);
  });
  for (int i = 0; i < 50; ++i) {
    std::unique_ptr<Graph> att;
    CHECK_OK(LoadGraphFromStore(sidecar, 1 << 16, &att));
  }
  stop.store(true);
  scraper.join();
}

// Ragged offsets travel as i32 [n,2]; every merge producer range-checks
// its final cursor (advisor r1: >2^31-element merges would silently
// wrap). Exercise the guard on both sides of the boundary — allocating
// a real >2GB payload in a unit test is not viable, and every producer
// funnels through this one check.
void TestI32OffsetGuard() {
  NodeDef node;
  node.name = "GP_RAGGED_MERGE_test";
  CHECK_OK(CheckI32Offsets(node, 0));
  CHECK_OK(CheckI32Offsets(node, (1LL << 31) - 1));
  Status s = CheckI32Offsets(node, 1LL << 31);
  CHECK_TRUE(!s.ok());
  CHECK_TRUE(s.message().find("int32 offset range") != std::string::npos);
  CHECK_TRUE(s.message().find(node.name) != std::string::npos);
  CHECK_TRUE(!CheckI32Offsets(node, (1LL << 40)).ok());
}


// TCP registry server: concurrent put/list/remove through the real
// socket path (ZK-role discovery without a shared FS) — TSAN covers the
// entries_/conns_ locking and the reap-on-accept path.
void TestRegistryServer() {
  RegistryServer reg;
  CHECK_OK(reg.Start(0));
  std::string spec = "tcp:127.0.0.1:" + std::to_string(reg.port());
  // concurrent heartbeats from several "shards"
  ThreadPool pool(4);
  std::atomic<int> remaining{12};
  std::mutex mu;
  std::condition_variable cv;
  for (int i = 0; i < 12; ++i) {
    pool.Schedule([&, i] {
      std::string name = "shard_" + std::to_string(i % 3) +
                         "__127.0.0.1_" + std::to_string(9000 + i % 3);
      CHECK_OK(RegistryPutEntry(spec, name));
      if (remaining.fetch_sub(1) == 1) {
        std::lock_guard<std::mutex> lk(mu);
        cv.notify_one();
      }
    });
  }
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return remaining.load() == 0; });
  }
  std::map<int, std::pair<std::string, int>> found;
  std::map<int, int64_t> ages;
  CHECK_OK(ScanRegistrySpec(spec, &found, &ages));
  CHECK_TRUE(found.size() == 3);
  CHECK_TRUE(found[1].second == 9001);
  CHECK_TRUE(ages[0] >= 0 && ages[0] < 60000);
  // youngest-entry-wins: a NEW registration for shard 0 supersedes
  CHECK_OK(RegistryPutEntry(spec, "shard_0__127.0.0.1_9100"));
  found.clear();
  ages.clear();
  CHECK_OK(ScanRegistrySpec(spec, &found, &ages));
  CHECK_TRUE(found[0].second == 9100);
  // remove drops the entry
  CHECK_OK(RegistryRemoveEntry(spec, "shard_2__127.0.0.1_9002"));
  found.clear();
  CHECK_OK(ScanRegistrySpec(spec, &found, nullptr));
  CHECK_TRUE(found.find(2) == found.end());
  reg.Stop();
  // a scan against the stopped server fails cleanly (bounded)
  found.clear();
  CHECK_TRUE(!ScanRegistrySpec(spec, &found, nullptr).ok());
}

// ---- rpc: protocol v2 mux transport ----
void TestRpcMuxTransport() {
  std::shared_ptr<const Graph> g(RingGraph());
  // heap-held: a stack-placed server's mutexes land on addresses a
  // prior test's destroyed locals used, which TSAN misreads
  auto server = std::make_unique<GraphServer>(g, nullptr, 0, 1, 1);
  CHECK_OK(server->Start(0));

  RpcConfig saved = GlobalRpcConfig();
  GlobalRpcConfig().mux = true;
  GlobalRpcConfig().mux_connections = 1;
  GlobalRpcConfig().compress_threshold = 64;
  auto& ctr = GlobalRpcCounters();

  // v1 reference bytes (classic channel, no mux)
  RpcChannel v1ch("127.0.0.1", server->port());
  std::vector<char> v1_meta;
  CHECK_OK(v1ch.Call(1 /*kMeta*/, {}, &v1_meta));
  CHECK_TRUE(!v1_meta.empty());

  // many concurrent in-flight calls over ONE mux connection; replies
  // come back out-of-order and must route to the right caller
  uint64_t conns0 = ctr.connections_opened.load();
  RpcChannel ch("127.0.0.1", server->port());
  ch.set_mux(true);
  {
    ThreadPool pool(8);
    std::atomic<int> remaining{32};
    std::atomic<bool> all_ok{true};
    std::mutex mu;
    std::condition_variable cv;
    for (int i = 0; i < 32; ++i) {
      pool.Schedule([&, i] {
        std::vector<char> reply;
        uint32_t mt = (i % 2 == 0) ? 1u /*kMeta*/ : 2u /*kPing*/;
        Status s = ch.Call(mt, {}, &reply);
        if (!s.ok() || (mt == 1 && reply != v1_meta)) all_ok.store(false);
        if (remaining.fetch_sub(1) == 1) {
          std::lock_guard<std::mutex> lk(mu);
          cv.notify_one();
        }
      });
    }
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return remaining.load() == 0; });
    CHECK_TRUE(all_ok.load());
  }
  CHECK_TRUE(ch.mux_active());
  // 32 calls rode exactly one new connection
  CHECK_TRUE(ctr.connections_opened.load() - conns0 == 1);

  // async surface: reply delivered via callback on the client pool
  {
    std::mutex mu;
    std::condition_variable cv;
    bool fired = false;
    Status got = Status::IOError("not fired");
    ch.CallAsync(2 /*kPing*/, {}, [&](Status s, std::vector<char>) {
      std::lock_guard<std::mutex> lk(mu);
      got = s;
      fired = true;
      cv.notify_one();
    });
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return fired; });
    CHECK_OK(got);
  }

  // kill the server while callers hammer the channel: every parked
  // waiter must come back with a STATUS (the joins below are the
  // no-hang assertion)
  {
    std::atomic<bool> saw_failure{false};
    std::vector<std::thread> threads;
    for (int t = 0; t < 4; ++t) {
      threads.emplace_back([&] {
        for (int i = 0; i < 200; ++i) {
          std::vector<char> reply;
          if (!ch.Call(2 /*kPing*/, {}, &reply, /*max_retries=*/2).ok()) {
            saw_failure.store(true);
            return;
          }
        }
      });
    }
    ::usleep(5000);
    server->Stop();
    for (auto& th : threads) th.join();
    CHECK_TRUE(saw_failure.load());
  }
  GlobalRpcConfig() = saved;
}

// ---- rpc: v2 client against a v1-only server falls back cleanly ----
void TestRpcHelloFallback() {
  std::shared_ptr<const Graph> g(RingGraph());
  ::setenv("EULER_TPU_RPC_SERVER_V1", "1", 1);
  auto server = std::make_unique<GraphServer>(g, nullptr, 0, 1, 1);
  CHECK_OK(server->Start(0));
  ::unsetenv("EULER_TPU_RPC_SERVER_V1");

  RpcConfig saved = GlobalRpcConfig();
  GlobalRpcConfig().mux = true;
  auto& ctr = GlobalRpcCounters();
  uint64_t fb0 = ctr.hello_fallbacks.load();

  RpcChannel v1ch("127.0.0.1", server->port());
  std::vector<char> v1_meta;
  CHECK_OK(v1ch.Call(1 /*kMeta*/, {}, &v1_meta));

  RpcChannel ch("127.0.0.1", server->port());
  ch.set_mux(true);
  std::vector<char> meta;
  CHECK_OK(ch.Call(1 /*kMeta*/, {}, &meta));  // hello refused → v1 path
  CHECK_TRUE(meta == v1_meta);
  CHECK_TRUE(!ch.mux_active());
  CHECK_TRUE(ctr.hello_fallbacks.load() == fb0 + 1);
  server->Stop();
  GlobalRpcConfig() = saved;
}

// ---- rpc: wire trace context → server-side timing breakdown ----
void TestServerTraceBreakdown() {
  std::shared_ptr<const Graph> g(RingGraph());
  auto server = std::make_unique<GraphServer>(g, nullptr, 0, 1, 1);
  CHECK_OK(server->Start(0));
  RpcConfig saved = GlobalRpcConfig();
  GlobalRpcConfig().mux = true;
  GlobalRpcConfig().mux_connections = 1;
  auto& ctr = GlobalRpcCounters();

  // drain whatever earlier tests' traffic left in the ring
  std::vector<ServerTraceRecord> recs;
  GlobalServerTraceStats().Drain(&recs);

  ExecuteRequest req;  // empty DAG: decode/execute/serialize still run
  ByteWriter w;
  EncodeExecuteRequest(req, &w);

  RpcChannel ch("127.0.0.1", server->port());
  ch.set_mux(true);
  std::vector<char> reply;
  uint64_t t0 = ctr.trace_propagated.load();

  // untraced call: nothing stamped, nothing ringed (wire identity is
  // pinned at the byte level by the Python interop tests)
  CHECK_OK(ch.Call(0 /*kExecute*/, w.buffer(), &reply, /*max_retries=*/2));
  CHECK_TRUE(ctr.trace_propagated.load() == t0);
  GlobalServerTraceStats().Drain(&recs);
  CHECK_TRUE(recs.empty());

  // traced call: stamped, and the server records the breakdown under
  // the caller's trace/parent with a freshly minted span id
  CHECK_OK(ch.Call(0, w.buffer(), &reply, 2, /*deadline=*/0,
                   /*map_epoch=*/0, WireTrace{77, 5}));
  CHECK_TRUE(ctr.trace_propagated.load() == t0 + 1);
  GlobalServerTraceStats().Drain(&recs);
  CHECK_TRUE(recs.size() == 1);
  CHECK_TRUE(recs[0].trace_id == 77 && recs[0].parent_span == 5);
  CHECK_TRUE(recs[0].span_id != 0);
  CHECK_TRUE(recs[0].verb == 0 && recs[0].flags == 0);
  CHECK_TRUE(recs[0].start_unix_us > 0);

  // the always-on phase histograms saw both calls (queue + execute)
  uint64_t n = 0, sum = 0;
  uint64_t counts[ServerTraceStats::kTraceBuckets + 1];
  CHECK_TRUE(GlobalServerTraceStats().HistSnapshot(0, 0, &n, &sum, counts));
  CHECK_TRUE(n >= 2);
  CHECK_TRUE(GlobalServerTraceStats().HistSnapshot(0, 2, &n, &sum, counts));
  CHECK_TRUE(n >= 2);

  server->Stop();
  GlobalRpcConfig() = saved;
}

// ---- serde: sizing-reserved encodes + split-plan + reply segments ----
void TestSerdeSizingSplitSegments() {
  // request with a payload-bearing feed and a small multi-node plan
  ExecuteRequest req;
  Tensor roots(DType::kU64, {4});
  for (int i = 0; i < 4; ++i) roots.Flat<uint64_t>()[i] = 100 + i;
  req.inputs.emplace_back("roots", roots);
  NodeDef nd;
  nd.name = "SAMPLE_NB_0";
  nd.op = "SAMPLE_NB";
  nd.inputs = {"roots"};
  nd.attrs = {"*", "3", "0"};
  req.nodes.push_back(nd);
  req.outputs = {"SAMPLE_NB_0:0", "SAMPLE_NB_0:1"};

  // the documented invariant: 'ETEY' + feeds[4:] + plan[4:] is byte-
  // identical to the classic full encoding (the fallback reassembly)
  ByteWriter full, pw, fw;
  EncodeExecuteRequest(req, &full);
  EncodeExecutePlan(req, &pw);
  EncodeExecuteFeeds(req, &fw);
  std::vector<char> assembled;
  CHECK_OK(AssembleFullExecuteRequest(fw.buffer(), pw.buffer(), &assembled));
  CHECK_TRUE(assembled == full.buffer());
  // swapped arguments must fail fast, not misread
  CHECK_TRUE(
      !AssembleFullExecuteRequest(pw.buffer(), fw.buffer(), &assembled)
           .ok());

  // split halves decode back to the original request
  ExecuteRequest back;
  {
    ByteReader r(pw.buffer().data(), pw.buffer().size());
    CHECK_OK(DecodeExecutePlan(&r, &back));
    CHECK_TRUE(r.remaining() == 0);
    ByteReader r2(fw.buffer().data(), fw.buffer().size());
    CHECK_OK(DecodeExecuteFeeds(&r2, &back));
    CHECK_TRUE(r2.remaining() == 0);
  }
  CHECK_TRUE(back.nodes.size() == 1 && back.nodes[0].op == "SAMPLE_NB");
  CHECK_TRUE(back.outputs == req.outputs);
  CHECK_TRUE(back.inputs.size() == 1 &&
             std::memcmp(back.inputs[0].second.raw(), roots.raw(),
                         roots.ByteSize()) == 0);

  // content hash: stable, non-zero, and sensitive to any plan byte
  uint64_t h1 = PlanContentHash(pw.buffer().data(), pw.buffer().size());
  uint64_t h2 = PlanContentHash(pw.buffer().data(), pw.buffer().size());
  CHECK_TRUE(h1 == h2 && h1 != 0);
  std::vector<char> tweaked(pw.buffer());
  tweaked.back() ^= 1;
  CHECK_TRUE(PlanContentHash(tweaked.data(), tweaked.size()) != h1);

  // reply segments: runs concatenated in order == EncodeExecuteReply
  ExecuteReply rep;
  rep.status = Status::OK();
  Tensor t1(DType::kF32, {3, 5});
  for (int i = 0; i < 15; ++i) t1.Flat<float>()[i] = i * 0.5f;
  Tensor t2(DType::kU64, {0});  // empty payload: meta-only run
  Tensor t3(DType::kI32, {7});
  for (int i = 0; i < 7; ++i) t3.Flat<int32_t>()[i] = -i;
  rep.outputs.emplace_back("a:0", t1);
  rep.outputs.emplace_back("b:0", t2);
  rep.outputs.emplace_back("c:0", t3);
  ByteWriter contiguous;
  EncodeExecuteReply(rep, &contiguous);
  ReplySegments segs;
  EncodeExecuteReplySegments(std::move(rep), &segs);
  std::vector<char> glued;
  for (const auto& run : segs.runs) {
    const char* p = run.tensor >= 0
                        ? reinterpret_cast<const char*>(
                              segs.tensors[run.tensor].raw())
                        : segs.meta.buffer().data() + run.off;
    glued.insert(glued.end(), p, p + run.len);
  }
  CHECK_TRUE(glued == contiguous.buffer());
  CHECK_TRUE(segs.total == contiguous.buffer().size());
  // tensor payloads are VIEWS (two payload-bearing tensors pinned)
  CHECK_TRUE(segs.tensors.size() == 2);

  // error replies segment too (no outputs encoded)
  ExecuteReply bad;
  bad.status = Status::Internal("boom");
  ByteWriter bad_c;
  EncodeExecuteReply(bad, &bad_c);
  ReplySegments bad_s;
  EncodeExecuteReplySegments(std::move(bad), &bad_s);
  CHECK_TRUE(bad_s.runs.size() == 1 && bad_s.total == bad_c.buffer().size());
}

// ---- rpc: prepared plans (kPrepare + flagged kExecute) end to end ----
void TestPreparedPlanExecution() {
  std::shared_ptr<const Graph> g(RingGraph());
  auto server = std::make_unique<GraphServer>(g, nullptr, 0, 1, 1);
  CHECK_OK(server->Start(0));
  RpcConfig saved = GlobalRpcConfig();
  GlobalRpcConfig().mux = true;
  GlobalRpcConfig().mux_connections = 1;
  GlobalRpcConfig().prepared = true;
  auto& ctr = GlobalRpcCounters();

  CompileOptions opts;
  opts.mode = "local";
  GqlCompiler compiler(opts);
  std::shared_ptr<const TranslateResult> plan;
  CHECK_OK(compiler.Compile("v(roots).getNB(*).as(nb)", &plan));
  ExecuteRequest req;
  Tensor roots(DType::kU64, {2});
  roots.Flat<uint64_t>()[0] = 3;
  roots.Flat<uint64_t>()[1] = 9;
  req.inputs.emplace_back("roots", roots);
  req.nodes = plan->dag.nodes;
  req.outputs = {"nb:1"};

  ByteWriter full, pw, fw;
  EncodeExecuteRequest(req, &full);
  EncodeExecutePlan(req, &pw);
  EncodeExecuteFeeds(req, &fw);
  const uint64_t pid =
      PlanContentHash(pw.buffer().data(), pw.buffer().size());

  RpcChannel ch("127.0.0.1", server->port());
  ch.set_mux(true);
  // classic full-frame reference reply (same v2 connection family)
  std::vector<char> ref;
  CHECK_OK(ch.Call(0 /*kExecute*/, full.buffer(), &ref, 2));

  // prepared: first call registers once, later calls hit; replies are
  // byte-identical to the classic path (the zero-copy writer included)
  const uint64_t reg0 = ctr.prepared_registered.load();
  const uint64_t hit0 = ctr.prepared_hits.load();
  std::vector<char> rep1, rep2;
  CHECK_OK(ch.CallExecutePrepared(pw.buffer(), pid, fw.buffer(), &rep1, 2));
  CHECK_OK(ch.CallExecutePrepared(pw.buffer(), pid, fw.buffer(), &rep2, 2));
  CHECK_TRUE(rep1 == ref && rep2 == ref);
  CHECK_TRUE(ctr.prepared_registered.load() == reg0 + 1);
  CHECK_TRUE(ctr.prepared_hits.load() == hit0 + 2);

  // a prepared frame ships FEWER bytes than the full frame: the saved
  // wire is the plan bytes minus the 8-byte id prefix
  CHECK_TRUE(fw.buffer().size() + 8 < full.buffer().size());

  // LRU eviction → explicit miss → client re-prepares and converges
  GlobalRpcConfig().plan_cache = 1;
  ExecuteRequest req2 = req;
  req2.outputs = {"nb:0"};  // different plan content → different id
  ByteWriter pw2, fw2;
  EncodeExecutePlan(req2, &pw2);
  EncodeExecuteFeeds(req2, &fw2);
  const uint64_t pid2 =
      PlanContentHash(pw2.buffer().data(), pw2.buffer().size());
  std::vector<char> repB;
  CHECK_OK(
      ch.CallExecutePrepared(pw2.buffer(), pid2, fw2.buffer(), &repB, 2));
  const uint64_t miss0 = ctr.prepared_misses.load();
  std::vector<char> rep3;
  CHECK_OK(ch.CallExecutePrepared(pw.buffer(), pid, fw.buffer(), &rep3, 3));
  CHECK_TRUE(rep3 == ref);
  CHECK_TRUE(ctr.prepared_misses.load() >= miss0 + 1);
  GlobalRpcConfig().plan_cache = 64;

  // ownership-map flip strands every cached plan: the next prepared
  // execute answers the counted invalidation miss, the client
  // re-prepares, and the result is still byte-identical — a stale plan
  // never executes silently
  const uint64_t inv0 = ctr.prepared_invalidated.load();
  auto om = std::make_shared<OwnershipMap>();
  CHECK_OK(OwnershipMap::Decode("e1-P1-0", om.get()));
  CHECK_OK(server->SetOwnership(om));
  std::vector<char> rep4;
  CHECK_OK(ch.CallExecutePrepared(pw.buffer(), pid, fw.buffer(), &rep4, 3));
  CHECK_TRUE(ctr.prepared_invalidated.load() == inv0 + 1);

  // prepared request against a v1-only server: counted fallback, same
  // answer through the classic framing
  ::setenv("EULER_TPU_RPC_SERVER_V1", "1", 1);
  auto v1srv = std::make_unique<GraphServer>(g, nullptr, 0, 1, 1);
  CHECK_OK(v1srv->Start(0));
  ::unsetenv("EULER_TPU_RPC_SERVER_V1");
  RpcChannel chv1("127.0.0.1", v1srv->port());
  chv1.set_mux(true);
  const uint64_t fb0 = ctr.prepared_fallbacks.load();
  std::vector<char> repv1;
  CHECK_OK(
      chv1.CallExecutePrepared(pw.buffer(), pid, fw.buffer(), &repv1, 3));
  CHECK_TRUE(ctr.prepared_fallbacks.load() >= fb0 + 1);
  v1srv->Stop();

  server->Stop();
  GlobalRpcConfig() = saved;
}

// ---- gql: prepare-time plan optimizer passes (golden rewrites) ----
void TestPlanOptimizerPasses() {
  // dedup: two identical deterministic gathers; one is a requested
  // output (protected), the duplicate folds into it
  {
    DAGDef dag;
    NodeDef a;
    a.name = "API_GET_P_0";
    a.op = "API_GET_P";
    a.inputs = {"roots"};
    a.attrs = {"price"};
    NodeDef b = a;
    b.name = "API_GET_P_1";
    NodeDef c;
    c.name = "SUM_2";
    c.op = "POST_PROCESS";
    c.inputs = {"API_GET_P_0:0", "API_GET_P_1:0"};
    dag.nodes = {a, b, c};
    dag.next_id = 100;
    PlanOptStats st;
    CHECK_OK(OptimizePreparedPlan(&dag, {"SUM_2:0"}, &st));
    CHECK_TRUE(st.dedup == 1);
    // the duplicate's consumers were rewired onto the survivor
    const NodeDef* kept = dag.Find("SUM_2");
    if (kept == nullptr) {  // whole plan may have fused
      CHECK_TRUE(dag.nodes.size() == 1 && dag.nodes[0].op == "FUSED");
      for (const auto& n : dag.nodes[0].inner)
        if (n.name == "SUM_2") kept = &n;
    }
    CHECK_TRUE(kept != nullptr &&
               kept->inputs == std::vector<std::string>(
                                   {"API_GET_P_0:0", "API_GET_P_0:0"}));
  }
  // filter pushdown: GET_NODE(dnf2) ∘ GET_NODE(dnf1) → one node with
  // dnf1 ∧ dnf2 — but ONLY while the child's :1 positions are unread
  {
    DAGDef dag;
    NodeDef f1;
    f1.name = "API_GET_NODE_0";
    f1.op = "API_GET_NODE";
    f1.inputs = {"roots"};
    f1.dnf = {{"price gt 1"}};
    NodeDef f2;
    f2.name = "API_GET_NODE_1";
    f2.op = "API_GET_NODE";
    f2.inputs = {"API_GET_NODE_0:0"};
    f2.dnf = {{"price lt 9"}};
    dag.nodes = {f1, f2};
    dag.next_id = 100;
    PlanOptStats st;
    CHECK_OK(OptimizePreparedPlan(&dag, {"API_GET_NODE_1:0"}, &st));
    CHECK_TRUE(st.pushdown == 1);
    std::string text = DagToString(dag);
    CHECK_TRUE(text.find("price gt 1 & price lt 9") != std::string::npos);
    // same chain, but the child's :1 (positions) is fetched → no merge
    DAGDef dag2;
    dag2.nodes = {f1, f2};
    dag2.next_id = 100;
    PlanOptStats st2;
    CHECK_OK(OptimizePreparedPlan(
        &dag2, {"API_GET_NODE_1:0", "API_GET_NODE_1:1"}, &st2));
    CHECK_TRUE(st2.pushdown == 0);
  }
  // fusion: a sync multi-node plan collapses into one FUSED group and
  // the executed form stays deterministic
  {
    DAGDef dag;
    NodeDef own;
    own.name = "API_GET_NODE_0";
    own.op = "API_GET_NODE";
    own.inputs = {"roots"};
    NodeDef gp;
    gp.name = "API_GET_P_1";
    gp.op = "API_GET_P";
    gp.inputs = {"API_GET_NODE_0:0"};
    gp.attrs = {"price"};
    dag.nodes = {own, gp};
    dag.next_id = 100;
    PlanOptStats st;
    CHECK_OK(OptimizePreparedPlan(&dag, {"API_GET_P_1:0"}, &st));
    CHECK_TRUE(st.fuse == 2);
    CHECK_TRUE(dag.nodes.size() == 1 && dag.nodes[0].op == "FUSED");
    CHECK_TRUE(DagIsDeterministic(dag));
  }
  // determinism gate: sampling verbs disqualify a plan, FUSED recurses
  {
    DAGDef dag;
    NodeDef s;
    s.name = "API_SAMPLE_NB_0";
    s.op = "API_SAMPLE_NB";
    s.inputs = {"roots"};
    s.attrs = {"*", "3", "0"};
    dag.nodes = {s};
    CHECK_TRUE(!DagIsDeterministic(dag));
    DAGDef fused;
    NodeDef f;
    f.name = "FUSED_1";
    f.op = "FUSED";
    f.inputs = {"roots"};
    f.inner = {s};
    fused.nodes = {f};
    CHECK_TRUE(!DagIsDeterministic(fused));
    CHECK_TRUE(IsDeterministicOp("API_GET_NB_NODE"));
    CHECK_TRUE(!IsDeterministicOp("API_SAMPLE_NB"));
  }
  // compile cache: bounded LRU — a distinct-query flood stays capped
  {
    CompileOptions opts;
    opts.mode = "local";
    GqlCompiler compiler(opts);
    for (int i = 0; i < 300; ++i) {
      std::shared_ptr<const TranslateResult> plan;
      CHECK_OK(compiler.Compile(
          "v(roots).getNB(" + std::to_string(i % 2) + ").as(nb" +
              std::to_string(i) + ")",
          &plan));
    }
    CHECK_TRUE(compiler.cache_size() == GqlCompiler::kCacheCap);
    // an entry still resident answers from cache (same pointer)
    std::shared_ptr<const TranslateResult> p1, p2;
    CHECK_OK(compiler.Compile("v(roots).getNB(0).as(nb299)", &p1));
    CHECK_OK(compiler.Compile("v(roots).getNB(0).as(nb299)", &p2));
    CHECK_TRUE(p1.get() == p2.get());
  }
}

// ---- rpc: deterministic result reuse + cross-request coalescing ----
void TestExecuteReuseAndCoalesce() {
  std::shared_ptr<const Graph> g(RingGraph());
  auto server = std::make_unique<GraphServer>(g, nullptr, 0, 1, 1);
  CHECK_OK(server->Start(0));
  RpcConfig saved = GlobalRpcConfig();
  GlobalRpcConfig().mux = true;
  GlobalRpcConfig().mux_connections = 1;
  GlobalRpcConfig().prepared = true;
  GlobalRpcConfig().reuse_window = 8;
  auto& ctr = GlobalRpcCounters();

  CompileOptions opts;
  opts.mode = "local";
  opts.fuse_local = false;  // keep the plan multi-node for the optimizer
  GqlCompiler compiler(opts);
  std::shared_ptr<const TranslateResult> plan;
  CHECK_OK(compiler.Compile("v(roots).getNB(*).as(nb)", &plan));
  ExecuteRequest req;
  Tensor roots(DType::kU64, {2});
  roots.Flat<uint64_t>()[0] = 3;
  roots.Flat<uint64_t>()[1] = 9;
  req.inputs.emplace_back("roots", roots);
  req.nodes = plan->dag.nodes;
  req.outputs = {"nb:1"};
  ByteWriter pw, fw;
  EncodeExecutePlan(req, &pw);
  EncodeExecuteFeeds(req, &fw);
  const uint64_t pid =
      PlanContentHash(pw.buffer().data(), pw.buffer().size());

  RpcChannel ch("127.0.0.1", server->port());
  ch.set_mux(true);
  // cold call: registers + executes + installs the reuse entry
  const uint64_t hit0 = ctr.reuse_hits.load();
  const uint64_t miss0 = ctr.reuse_misses.load();
  std::vector<char> rep1, rep2;
  CHECK_OK(ch.CallExecutePrepared(pw.buffer(), pid, fw.buffer(), &rep1, 2));
  CHECK_TRUE(ctr.reuse_misses.load() == miss0 + 1);
  // warm call: byte-identical reply straight from the window
  CHECK_OK(ch.CallExecutePrepared(pw.buffer(), pid, fw.buffer(), &rep2, 2));
  CHECK_TRUE(ctr.reuse_hits.load() == hit0 + 1);
  CHECK_TRUE(rep1 == rep2);
  // different feeds: never served from the window (exact-byte compare)
  ExecuteRequest reqB = req;
  reqB.inputs[0].second.Flat<uint64_t>()[1] = 11;
  ByteWriter fwB;
  EncodeExecuteFeeds(reqB, &fwB);
  std::vector<char> repB;
  CHECK_OK(
      ch.CallExecutePrepared(pw.buffer(), pid, fwB.buffer(), &repB, 2));
  CHECK_TRUE(repB != rep1);

  // ownership flip purges the window (counted) — a post-flip call can
  // never be answered with a pre-flip result
  const uint64_t inv0 = ctr.reuse_invalidated.load();
  auto om = std::make_shared<OwnershipMap>();
  CHECK_OK(OwnershipMap::Decode("e1-P1-0", om.get()));
  CHECK_OK(server->SetOwnership(om));
  CHECK_TRUE(ctr.reuse_invalidated.load() >= inv0 + 2);

  // nondeterministic plan: the fast path must not engage at all
  std::shared_ptr<const TranslateResult> splan;
  CHECK_OK(compiler.Compile("v(roots).sampleNB(0, 3, -1).as(snb)", &splan));
  ExecuteRequest sreq;
  sreq.inputs.emplace_back("roots", roots);
  sreq.nodes = splan->dag.nodes;
  sreq.outputs = {"snb:1"};
  ByteWriter spw, sfw;
  EncodeExecutePlan(sreq, &spw);
  EncodeExecuteFeeds(sreq, &sfw);
  const uint64_t spid =
      PlanContentHash(spw.buffer().data(), spw.buffer().size());
  const uint64_t h1 = ctr.reuse_hits.load();
  const uint64_t m1 = ctr.reuse_misses.load();
  std::vector<char> sr1, sr2;
  CHECK_OK(
      ch.CallExecutePrepared(spw.buffer(), spid, sfw.buffer(), &sr1, 2));
  CHECK_OK(
      ch.CallExecutePrepared(spw.buffer(), spid, sfw.buffer(), &sr2, 2));
  CHECK_TRUE(ctr.reuse_hits.load() == h1);
  CHECK_TRUE(ctr.reuse_misses.load() == m1);

  // coalescing: two identical deterministic executes inside one window
  // → one shared run answers both, byte-identically
  GlobalRpcConfig().reuse_window = 0;  // isolate the coalescer
  GlobalRpcConfig().coalesce_window_us = 60000;
  const uint64_t co0 = ctr.coalesced_requests.load();
  const uint64_t cb0 = ctr.coalesce_batches.load();
  std::vector<char> ra, rb;
  std::thread t1([&] {
    CHECK_OK(ch.CallExecutePrepared(pw.buffer(), pid, fw.buffer(), &ra, 2));
  });
  ::usleep(5000);  // let the leader open its bucket
  std::thread t2([&] {
    CHECK_OK(ch.CallExecutePrepared(pw.buffer(), pid, fw.buffer(), &rb, 2));
  });
  t1.join();
  t2.join();
  CHECK_TRUE(ra == rb && ra == rep1);
  CHECK_TRUE(ctr.coalesced_requests.load() >= co0 + 1);
  CHECK_TRUE(ctr.coalesce_batches.load() >= cb0 + 1);

  server->Stop();
  GlobalRpcConfig() = saved;
}

}  // namespace
}  // namespace et


int main() {
  // server/client teardown races write to closing sockets on purpose
  // (hedge losers, coalesce fan-out) — EPIPE is handled, SIGPIPE kills
  ::signal(SIGPIPE, SIG_IGN);
  et::MinLogLevel() = 2;  // quiet
  et::TestPcg32Determinism();
  et::TestAliasSamplerStatistics();
  et::TestParallelForCoversAll();
  et::TestThreadPoolStress();
  et::TestThreadPoolPriorityLanes();
  et::TestRegistryServer();
  et::TestRpcMuxTransport();
  et::TestRpcHelloFallback();
  et::TestServerTraceBreakdown();
  et::TestSerdeSizingSplitSegments();
  et::TestPreparedPlanExecution();
  et::TestPlanOptimizerPasses();
  et::TestExecuteReuseAndCoalesce();
  et::TestI32OffsetGuard();
  et::TestGraphStore();
  et::TestConcurrentSampling();
  et::TestUdfResultCacheConcurrent();
  et::TestTensorSerde();
  et::TestExecutorRunsDag();
  et::TestIndexDnf();
  et::TestDumpLoadRoundtrip();
  et::TestColumnarStoreRoundtrip();
  et::TestColumnarStorePostDelta();
  et::TestWalColumnarSidecarRecovery();
  et::TestColumnarStoreHardening();
  if (et::g_failures == 0) {
    std::printf("engine_test: ALL OK\n");
    return 0;
  }
  std::fprintf(stderr, "engine_test: %d failures\n", et::g_failures);
  return 1;
}
