// extern "C" surface for the query layer: QueryProxy, gremlin execution,
// and graph service lifecycle.
//
// Capability parity with the reference's ctypes entries
// tf_euler/utils/init_query_proxy.cc (InitQueryProxy) and
// euler/service/python_api.cc (StartService) — restructured as
// handle-based objects so one process can host several proxies/servers
// (e.g. fork-free multi-shard tests).
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>

#include "capi_internal.h"
#include "common.h"
#include "gql.h"
#include "graph.h"
#include "index.h"
#include "io.h"
#include "query_proxy.h"
#include "rpc.h"
#include "store.h"
#include "tensor.h"

namespace {

using et::capi::FailWith;

struct QueryRegistry {
  std::mutex mu;
  int64_t next = 1;
  std::unordered_map<int64_t, std::shared_ptr<et::QueryProxy>> proxies;
  std::unordered_map<int64_t, std::shared_ptr<et::GraphServer>> servers;
  // servers keep their (swappable) graph holder alive
  std::unordered_map<int64_t, std::shared_ptr<et::GraphRef>> server_graphs;
  std::unordered_map<int64_t, std::shared_ptr<et::RegistryServer>> registries;
};

QueryRegistry& QReg() {
  static QueryRegistry* r = new QueryRegistry();
  return *r;
}

// One in-flight query execution: staged inputs → run → held outputs.
struct Exec {
  std::shared_ptr<et::QueryProxy> proxy;
  std::map<std::string, et::Tensor> inputs;
  std::vector<std::pair<std::string, et::Tensor>> outputs;
};

struct ExecRegistry {
  std::mutex mu;
  int64_t next = 1;
  std::unordered_map<int64_t, std::shared_ptr<Exec>> execs;
};

ExecRegistry& EReg() {
  static ExecRegistry* r = new ExecRegistry();
  return *r;
}

std::shared_ptr<Exec> GetExec(int64_t h) {
  auto& r = EReg();
  std::lock_guard<std::mutex> lk(r.mu);
  auto it = r.execs.find(h);
  return it == r.execs.end() ? nullptr : it->second;
}

et::Tensor MakeTensor(int dtype, int rank, const int64_t* dims,
                      const void* data) {
  std::vector<int64_t> d(dims, dims + rank);
  et::Tensor t(static_cast<et::DType>(dtype), d);
  std::memcpy(t.raw(), data, t.ByteSize());
  return t;
}

}  // namespace

extern "C" {

// ---- QueryProxy ----
int64_t etq_new_local(int64_t graph_handle, const char* index_spec,
                      uint64_t seed) {
  // bind to the handle's swappable GraphRef (not one snapshot): an
  // etg_apply_delta on the graph handle is visible to this proxy
  auto ref = et::capi::GraphRefFromHandle(graph_handle);
  if (!ref) {
    FailWith("bad graph handle");
    return 0;
  }
  std::unique_ptr<et::QueryProxy> qp;
  et::Status s = et::QueryProxy::NewLocal(ref, index_spec ? index_spec : "",
                                          seed, &qp);
  if (!s.ok()) {
    FailWith(s.message());
    return 0;
  }
  auto& r = QReg();
  std::lock_guard<std::mutex> lk(r.mu);
  int64_t h = r.next++;
  r.proxies[h] = std::move(qp);
  return h;
}

int64_t etq_new_remote(const char* endpoints, uint64_t seed,
                       const char* mode) {
  std::unique_ptr<et::QueryProxy> qp;
  et::Status s = et::QueryProxy::NewRemote(
      endpoints, seed, mode && mode[0] ? mode : "distribute", &qp);
  if (!s.ok()) {
    FailWith(s.message());
    return 0;
  }
  auto& r = QReg();
  std::lock_guard<std::mutex> lk(r.mu);
  int64_t h = r.next++;
  r.proxies[h] = std::move(qp);
  return h;
}

int etq_index_dump(int64_t h, const char* dir) {
  auto& r = QReg();
  std::shared_ptr<et::QueryProxy> qp;
  {
    std::lock_guard<std::mutex> lk(r.mu);
    auto it = r.proxies.find(h);
    if (it == r.proxies.end()) return FailWith("bad proxy handle");
    qp = it->second;
  }
  et::Status s = qp->DumpIndex(dir ? dir : "");
  if (!s.ok()) return FailWith(s.message());
  return 0;
}

// out: [queries, errors, total_us, last_us]
int etq_stats(int64_t h, uint64_t* out) {
  auto& r = QReg();
  std::shared_ptr<et::QueryProxy> qp;
  {
    std::lock_guard<std::mutex> lk(r.mu);
    auto it = r.proxies.find(h);
    if (it == r.proxies.end()) return FailWith("bad proxy handle");
    qp = it->second;
  }
  auto st = qp->stats();
  out[0] = st.queries;
  out[1] = st.errors;
  out[2] = st.total_us;
  out[3] = st.last_us;
  return 0;
}

int etq_free(int64_t h) {
  auto& r = QReg();
  std::lock_guard<std::mutex> lk(r.mu);
  r.proxies.erase(h);
  return 0;
}

// ---- streaming deltas (proxy surface) ----
static std::shared_ptr<et::QueryProxy> GetProxy(int64_t h) {
  auto& r = QReg();
  std::lock_guard<std::mutex> lk(r.mu);
  auto it = r.proxies.find(h);
  return it == r.proxies.end() ? nullptr : it->second;
}

// Observed graph epoch: exact for local proxies; for distribute-mode
// proxies the max epoch seen on any shard reply (v2 frames piggyback
// it — poll etq_delta_since for an active refresh over v1).
int64_t etq_epoch(int64_t h) {
  auto qp = GetProxy(h);
  if (!qp) {
    FailWith("bad proxy handle");
    return -1;
  }
  return static_cast<int64_t>(qp->ObservedEpoch());
}

// Batched delta through the proxy: local → swap this handle's graph;
// distribute → broadcast kApplyDelta to every shard (each applies its
// hash-owned rows). out_epoch gets the new (max) epoch.
int etq_apply_delta(int64_t h, int64_t n_nodes, const uint64_t* node_ids,
                    const int32_t* node_types, const float* node_weights,
                    int64_t n_edges, const uint64_t* edge_src,
                    const uint64_t* edge_dst, const int32_t* edge_types,
                    const float* edge_weights, int64_t* out_epoch) {
  auto qp = GetProxy(h);
  if (!qp) return FailWith("bad proxy handle");
  uint64_t epoch = 0;
  et::Status s = qp->ApplyDelta(
      node_ids, node_types, node_weights, static_cast<size_t>(n_nodes),
      edge_src, edge_dst, edge_types, edge_weights,
      static_cast<size_t>(n_edges), &epoch);
  if (!s.ok()) return FailWith(s.message());
  if (out_epoch != nullptr) *out_epoch = static_cast<int64_t>(epoch);
  return 0;
}

// ---- elastic fleet (ownership maps; distribute-mode proxies) ----
// Install the ownership map this client routes with (spec from the
// registry). Fails on local proxies, on maps older than the installed
// one, and on maps referencing shards beyond this client's channels
// (rebuild the proxy against the grown fleet first).
int etq_set_ownership(int64_t h, const char* spec) {
  auto qp = GetProxy(h);
  if (!qp) return FailWith("bad proxy handle");
  et::Status s = qp->SetOwnership(spec ? spec : "");
  if (!s.ok()) return FailWith(s.message());
  return 0;
}

// Installed ownership-map epoch (0 = none / local proxy); -1 bad handle.
int64_t etq_ownership_epoch(int64_t h) {
  auto qp = GetProxy(h);
  if (!qp) {
    FailWith("bad proxy handle");
    return -1;
  }
  return static_cast<int64_t>(qp->OwnershipEpoch());
}

// Shard count this proxy was built against (1 for local proxies);
// -1 bad handle. The elastic layer compares it with the published
// map's fleet width to decide when a proxy rebuild is due.
int etq_shard_num(int64_t h) {
  auto qp = GetProxy(h);
  if (!qp) {
    FailWith("bad proxy handle");
    return -1;
  }
  return qp->shard_num();
}

// Per-shard traffic since proxy init (hot-shard detection): fills
// out_reqs with kExecute request counts and out_rows with split-routed
// id counts (min(cap, shard_num) entries each; either may be null).
// Returns the count filled (0 for local proxies), -1 bad handle.
int etq_shard_stats(int64_t h, uint64_t* out_reqs, uint64_t* out_rows,
                    int cap) {
  auto qp = GetProxy(h);
  if (!qp) {
    // FailWith returns the generic error code 1, which here would read
    // as "1 shard filled" — the contract (and the Python caller's
    // `got < 0` check) needs an explicit -1
    FailWith("bad proxy handle");
    return -1;
  }
  return qp->ShardStats(out_reqs, out_rows, cap);
}

// Dirty-node union for epochs > from_epoch (res->u64, sorted unique);
// *out_covered 0 → some shard's bounded history no longer reaches
// from_epoch (the caller must treat everything as dirty).
int etq_delta_since(int64_t h, int64_t from_epoch, EtResult* res,
                    int64_t* out_epoch, int32_t* out_covered) {
  auto qp = GetProxy(h);
  if (!qp) return FailWith("bad proxy handle");
  uint64_t epoch = 0;
  bool covered = false;
  std::vector<et::NodeId> ids;
  et::Status s = qp->DeltaSince(static_cast<uint64_t>(from_epoch), &epoch,
                                &covered, &ids);
  if (!s.ok()) return FailWith(s.message());
  res->u64.assign(ids.begin(), ids.end());
  res->offsets.clear();
  res->f32.clear();
  res->i32.clear();
  res->bytes.clear();
  if (out_epoch != nullptr) *out_epoch = static_cast<int64_t>(epoch);
  if (out_covered != nullptr) *out_covered = covered ? 1 : 0;
  return 0;
}

// ---- query execution ----
int64_t etq_exec_new(int64_t proxy_handle) {
  std::shared_ptr<et::QueryProxy> proxy;
  {
    auto& r = QReg();
    std::lock_guard<std::mutex> lk(r.mu);
    auto it = r.proxies.find(proxy_handle);
    if (it == r.proxies.end()) {
      FailWith("bad proxy handle");
      return 0;
    }
    proxy = it->second;
  }
  auto e = std::make_shared<Exec>();
  e->proxy = std::move(proxy);
  auto& r = EReg();
  std::lock_guard<std::mutex> lk(r.mu);
  int64_t h = r.next++;
  r.execs[h] = std::move(e);
  return h;
}

int etq_exec_add_input(int64_t h, const char* name, int dtype, int rank,
                       const int64_t* dims, const void* data) {
  auto e = GetExec(h);
  if (!e) return FailWith("bad exec handle");
  e->inputs[name] = MakeTensor(dtype, rank, dims, data);
  return 0;
}

int etq_exec_run(int64_t h, const char* gremlin) {
  auto e = GetExec(h);
  if (!e) return FailWith("bad exec handle");
  std::map<std::string, et::Tensor> outputs;
  et::Status s = e->proxy->RunGremlin(gremlin, e->inputs, &outputs);
  if (!s.ok()) return FailWith(s.message());
  e->outputs.assign(outputs.begin(), outputs.end());
  return 0;
}

int64_t etq_exec_output_count(int64_t h) {
  auto e = GetExec(h);
  return e ? static_cast<int64_t>(e->outputs.size()) : -1;
}

const char* etq_exec_output_name(int64_t h, int64_t i) {
  auto e = GetExec(h);
  if (!e || i < 0 || i >= static_cast<int64_t>(e->outputs.size()))
    return "";
  return e->outputs[i].first.c_str();
}

int etq_exec_output_info(int64_t h, int64_t i, int32_t* dtype,
                         int32_t* rank, int64_t* num_elements) {
  auto e = GetExec(h);
  if (!e || i < 0 || i >= static_cast<int64_t>(e->outputs.size()))
    return FailWith("bad output index");
  const et::Tensor& t = e->outputs[i].second;
  *dtype = static_cast<int32_t>(t.dtype());
  *rank = static_cast<int32_t>(t.rank());
  *num_elements = t.NumElements();
  return 0;
}

int etq_exec_output_dims(int64_t h, int64_t i, int64_t* dims) {
  auto e = GetExec(h);
  if (!e || i < 0 || i >= static_cast<int64_t>(e->outputs.size()))
    return FailWith("bad output index");
  const et::Tensor& t = e->outputs[i].second;
  for (size_t k = 0; k < t.rank(); ++k) dims[k] = t.dims()[k];
  return 0;
}

const void* etq_exec_output_data(int64_t h, int64_t i) {
  auto e = GetExec(h);
  if (!e || i < 0 || i >= static_cast<int64_t>(e->outputs.size()))
    return nullptr;
  return e->outputs[i].second.raw();
}

int etq_exec_free(int64_t h) {
  auto& r = EReg();
  std::lock_guard<std::mutex> lk(r.mu);
  r.execs.erase(h);
  return 0;
}

// ---- graph service ----
// Start serving a shard loaded from a data directory. Returns a server
// handle; port 0 picks an ephemeral port (query with ets_port).
// Durable form (ets_start2): wal_dir non-empty attaches a write-ahead
// delta log — restart recovers snapshot+WAL to the pre-crash epoch,
// then (catchup != 0 and a registry given) closes any remaining gap via
// peer kGetDeltaLog anti-entropy BEFORE registering for traffic.
// Shared implementation behind ets_start2/ets_start3. storage: 0 = heap
// (unchanged), 1 = mmap out-of-core tier (store.h) with `hot_bytes` of
// hub-pinned hot set — the graph serves from a mapped columnar store
// and WAL compactions re-attach fresh generations.
static int64_t StartShardService(const char* data_dir, int shard_idx,
                                 int shard_num, int port,
                                 const char* registry_dir, const char* host,
                                 const char* index_spec, const char* wal_dir,
                                 int fsync_policy, int64_t compact_bytes,
                                 int catchup, int storage,
                                 int64_t hot_bytes) {
  const bool durable = wal_dir != nullptr && wal_dir[0] != '\0';
  std::unique_ptr<et::Graph> g;
  std::unique_ptr<et::DeltaWal> wal;
  std::vector<et::WalRecord> wal_records;
  bool wal_degraded = false;
  et::Status s;
  bool wal_gap = false;
  et::OwnershipMap recovered_map;
  if (durable) {
    uint64_t replayed = 0;
    s = et::RecoverShard(wal_dir, data_dir, shard_idx, shard_num,
                         /*build_in_adjacency=*/true, &g, &replayed,
                         &wal_records, &wal_gap, &recovered_map, storage,
                         hot_bytes);
    if (!s.ok()) {
      FailWith(s.message());
      return 0;
    }
    et::Status ws = et::DeltaWal::Open(
        wal_dir,
        fsync_policy != 0 ? et::FsyncPolicy::kAlways
                          : et::FsyncPolicy::kNever,
        compact_bytes, &wal);
    if (!ws.ok()) {
      // unusable log dir: serve reads, refuse deltas (counted) — the
      // graceful-degradation contract, never silent divergence. The
      // degraded-instance gauge is bumped by set_wal below.
      wal_degraded = true;
      ET_LOG_WARNING << "shard " << shard_idx << " wal degraded ("
                     << ws.message() << "): deltas will be refused";
    }
  } else {
    // Non-durable + mmap: attach the data dir's shard-qualified columnar
    // sidecar when one exists AND is at least as new as the partition
    // files it was spilled from (a re-dumped dataset must never be
    // shadowed by a stale spill); otherwise load once on the heap, spill
    // the sidecar beside the partition files (so the NEXT start attaches
    // directly), and re-attach. Any failure degrades to the heap path.
    if (storage == 1 && data_dir != nullptr && data_dir[0] != '\0') {
      const std::string sidecar =
          std::string(data_dir) + "/" +
          et::ColumnarSidecarName(shard_idx, shard_num);
      et::Status as =
          et::SidecarIsFresh(data_dir, sidecar)
              ? et::LoadGraphFromStore(sidecar, hot_bytes, &g)
              : et::Status::IOError("no fresh sidecar at " + sidecar);
      if (!as.ok()) {
        g.reset();
        s = et::LoadShard(data_dir, shard_idx, shard_num,
                          /*data_type=*/0,
                          /*build_in_adjacency=*/true, &g);
        if (!s.ok()) {
          FailWith(s.message());
          return 0;
        }
        as = et::WriteColumnarStore(*g, sidecar);
        if (as.ok()) {
          std::unique_ptr<et::Graph> attached;
          as = et::LoadGraphFromStore(sidecar, hot_bytes, &attached);
          if (as.ok()) g = std::move(attached);
        }
        if (!as.ok())
          ET_LOG_WARNING << "shard " << shard_idx
                         << " columnar attach failed (" << as.message()
                         << "): serving from heap";
      }
    } else {
      s = et::LoadShard(data_dir, shard_idx, shard_num,
                        /*data_type=*/0,
                        /*build_in_adjacency=*/true, &g);
      if (!s.ok()) {
        FailWith(s.message());
        return 0;
      }
    }
  }
  std::shared_ptr<const et::Graph> graph(std::move(g));
  std::shared_ptr<et::IndexManager> index;
  if (index_spec != nullptr && index_spec[0] != '\0') {
    index = std::make_shared<et::IndexManager>();
    s = index->BuildFromSpec(*graph, index_spec);
    if (!s.ok()) {
      FailWith(s.message());
      return 0;
    }
  }
  int partition_num = graph->meta().partition_num;
  auto graph_ref = std::make_shared<et::GraphRef>(std::move(graph));
  auto server = std::make_shared<et::GraphServer>(
      graph_ref, index, shard_idx, shard_num, partition_num);
  // spec retained so kApplyDelta can rebuild the index on the new
  // snapshot (a server with an index but no spec refuses deltas)
  server->set_index_spec(index_spec != nullptr ? index_spec : "");
  if (storage != 0) server->set_storage(storage, hot_bytes);
  if (durable) {
    server->set_wal(std::shared_ptr<et::DeltaWal>(std::move(wal)),
                    wal_degraded);
    // seed the anti-entropy log from our own WAL (the records recovery
    // already parsed — no second pass over the log) so a peer
    // recovering after us can catch up THROUGH us
    if (!wal_records.empty()) server->SeedDeltaLog(wal_records);
    // a replay that stopped on a gap/failed record leaves the shard's
    // epoch numbering untrusted: never claim anti-entropy coverage
    if (wal_gap) server->MarkDeltaLogGap();
    // re-install the persisted ownership map so the recovered shard
    // keeps refusing stale-map reads and filtering deltas under the
    // map its WAL replay used
    if (recovered_map.map_epoch != 0) {
      et::Status os = server->SetOwnership(
          std::make_shared<et::OwnershipMap>(recovered_map));
      if (!os.ok())
        ET_LOG_WARNING << "shard " << shard_idx
                       << " could not re-install recovered ownership map: "
                       << os.message();
    }
  }
  s = server->Start(port);
  if (!s.ok()) {
    FailWith(s.message());
    return 0;
  }
  if (registry_dir != nullptr && registry_dir[0] != '\0') {
    // rejoin at the fleet epoch BEFORE registering: discovery routes
    // traffic only after Register, so clients of a recovered shard see
    // no epoch regression on the happy path. A FAILED catch-up is
    // non-fatal (the client epoch-regression flush is the fallback)
    // but marks the delta log non-authoritative: this shard's future
    // live epochs may alias fleet deltas it never saw, and serving
    // them to a catching-up peer would silently diverge it.
    if (durable && catchup != 0 &&
        !server->CatchUpFromRegistry(registry_dir).ok())
      server->MarkDeltaLogGap();
    s = server->Register(registry_dir, host && host[0] ? host : "127.0.0.1");
    if (!s.ok()) {
      FailWith(s.message());
      return 0;
    }
  }
  auto& r = QReg();
  std::lock_guard<std::mutex> lk(r.mu);
  int64_t h = r.next++;
  r.servers[h] = server;
  r.server_graphs[h] = graph_ref;
  return h;
}

int64_t ets_start2(const char* data_dir, int shard_idx, int shard_num,
                   int port, const char* registry_dir, const char* host,
                   const char* index_spec, const char* wal_dir,
                   int fsync_policy, int64_t compact_bytes, int catchup) {
  return StartShardService(data_dir, shard_idx, shard_num, port,
                           registry_dir, host, index_spec, wal_dir,
                           fsync_policy, compact_bytes, catchup,
                           /*storage=*/0, /*hot_bytes=*/0);
}

// ets_start2 + out-of-core storage selection: storage 0 = heap,
// 1 = mmap columnar tier with a `hot_bytes` hub-pinned hot set.
int64_t ets_start3(const char* data_dir, int shard_idx, int shard_num,
                   int port, const char* registry_dir, const char* host,
                   const char* index_spec, const char* wal_dir,
                   int fsync_policy, int64_t compact_bytes, int catchup,
                   int storage, int64_t hot_bytes) {
  return StartShardService(data_dir, shard_idx, shard_num, port,
                           registry_dir, host, index_spec, wal_dir,
                           fsync_policy, compact_bytes, catchup, storage,
                           hot_bytes);
}

int64_t ets_start(const char* data_dir, int shard_idx, int shard_num,
                  int port, const char* registry_dir, const char* host,
                  const char* index_spec) {
  return ets_start2(data_dir, shard_idx, shard_num, port, registry_dir,
                    host, index_spec, /*wal_dir=*/"", /*fsync_policy=*/1,
                    /*compact_bytes=*/0, /*catchup=*/0);
}

// Install an ownership map on an in-process serving shard (the elastic
// driver's flip for servers it owns; remote servers take the
// kSetOwnership wire verb via etg_push_ownership).
int ets_set_ownership(int64_t h, const char* spec) {
  std::shared_ptr<et::GraphServer> server;
  {
    auto& r = QReg();
    std::lock_guard<std::mutex> lk(r.mu);
    auto it = r.servers.find(h);
    if (it == r.servers.end()) return FailWith("bad server handle");
    server = it->second;
  }
  auto m = std::make_shared<et::OwnershipMap>();
  et::Status s = et::OwnershipMap::Decode(spec ? spec : "", m.get());
  if (s.ok()) s = server->SetOwnership(std::move(m));
  if (!s.ok()) return FailWith(s.message());
  return 0;
}

// Serving shard's installed ownership-map epoch (0 = none / bad handle).
int64_t ets_map_epoch(int64_t h) {
  auto& r = QReg();
  std::lock_guard<std::mutex> lk(r.mu);
  auto it = r.servers.find(h);
  return it == r.servers.end()
             ? 0
             : static_cast<int64_t>(it->second->map_epoch());
}

// Current graph epoch of a serving shard (post-recovery rejoin checks).
int64_t ets_epoch(int64_t h) {
  auto& r = QReg();
  std::lock_guard<std::mutex> lk(r.mu);
  auto it = r.servers.find(h);
  return it == r.servers.end() ? -1
                               : static_cast<int64_t>(it->second->epoch());
}

int ets_port(int64_t h) {
  auto& r = QReg();
  std::lock_guard<std::mutex> lk(r.mu);
  auto it = r.servers.find(h);
  return it == r.servers.end() ? -1 : it->second->port();
}

int ets_stop(int64_t h) {
  std::shared_ptr<et::GraphServer> server;
  {
    auto& r = QReg();
    std::lock_guard<std::mutex> lk(r.mu);
    auto it = r.servers.find(h);
    if (it != r.servers.end()) {
      server = it->second;
      r.servers.erase(it);
      r.server_graphs.erase(h);
    }
  }
  if (server) server->Stop();
  return 0;
}

// ---- registry server (ZK-role discovery without a shared FS) ----
int64_t etr_start(int port) {
  auto reg = std::make_shared<et::RegistryServer>();
  et::Status s = reg->Start(port);
  if (!s.ok()) {
    FailWith(s.message());
    return 0;
  }
  auto& r = QReg();
  std::lock_guard<std::mutex> lk(r.mu);
  int64_t h = r.next++;
  r.registries[h] = reg;
  return h;
}

int etr_port(int64_t h) {
  auto& r = QReg();
  std::lock_guard<std::mutex> lk(r.mu);
  auto it = r.registries.find(h);
  return it == r.registries.end() ? -1 : it->second->port();
}

int etr_stop(int64_t h) {
  std::shared_ptr<et::RegistryServer> reg;
  {
    auto& r = QReg();
    std::lock_guard<std::mutex> lk(r.mu);
    auto it = r.registries.find(h);
    if (it != r.registries.end()) {
      reg = it->second;
      r.registries.erase(it);
    }
  }
  if (reg) reg->Stop();
  return 0;
}

// List a registry's shard entries as "idx,host,port,age_ms\n" lines
// (spec = dir path, "dir:...", or "tcp:host:port"). Returns the needed
// byte length (truncates to buf_len), or -1 on scan failure — lets
// launchers poll until every expected shard has registered.
int64_t etr_scan(const char* spec, char* buf, int64_t buf_len) {
  std::map<int, std::pair<std::string, int>> found;
  std::map<int, int64_t> ages;
  et::Status s = et::ScanRegistrySpec(spec ? spec : "", &found, &ages);
  if (!s.ok()) {
    FailWith(s.message());
    return -1;
  }
  std::string out;
  for (const auto& kv : found) {
    out += std::to_string(kv.first) + "," + kv.second.first + "," +
           std::to_string(kv.second.second) + "," +
           std::to_string(ages[kv.first]) + "\n";
  }
  if (buf != nullptr && buf_len > 0) {
    int64_t n = std::min<int64_t>(buf_len - 1, out.size());
    std::memcpy(buf, out.data(), n);
    buf[n] = '\0';
  }
  return static_cast<int64_t>(out.size());
}

// ---- compiler debug (golden structure tests) ----
// Compile a gremlin under the given sharding options; writes the DAG dump
// into buf (truncated to buf_len), returns needed length or -1 on error.
int64_t etq_compile_debug(const char* gremlin, int shard_num,
                          int partition_num, const char* mode, char* buf,
                          int64_t buf_len) {
  et::CompileOptions opts;
  opts.shard_num = shard_num;
  opts.partition_num = partition_num;
  opts.mode = mode;
  et::GqlCompiler compiler(opts);
  std::shared_ptr<const et::TranslateResult> plan;
  et::Status s = compiler.Compile(gremlin, &plan);
  if (!s.ok()) {
    FailWith(s.message());
    return -1;
  }
  std::string text = et::DagToString(plan->dag);
  int64_t n = static_cast<int64_t>(text.size());
  if (buf != nullptr && buf_len > 0) {
    int64_t c = std::min(buf_len - 1, n);
    std::memcpy(buf, text.data(), c);
    buf[c] = '\0';
  }
  return n;
}

// Query.explain(): compile a gremlin and report either the form the
// client registers (stage 0) or what the server's prepare-time
// optimizer turns it into (stage 1, header line = rewrite counts +
// determinism verdict). Same probe-then-fill contract as
// etq_compile_debug.
int64_t etq_compile_debug2(const char* gremlin, int shard_num,
                           int partition_num, const char* mode, int stage,
                           char* buf, int64_t buf_len) {
  et::CompileOptions opts;
  opts.shard_num = shard_num;
  opts.partition_num = partition_num;
  opts.mode = mode;
  et::GqlCompiler compiler(opts);
  std::shared_ptr<const et::TranslateResult> plan;
  et::Status s = compiler.Compile(gremlin, &plan);
  if (!s.ok()) {
    FailWith(s.message());
    return -1;
  }
  std::string text;
  if (stage <= 0) {
    text = et::DagToString(plan->dag);
  } else {
    et::DAGDef opt;
    opt.nodes = plan->dag.nodes;
    // decoded-plan convention (rpc.cc kPrepare): fresh ids start past
    // every registered name so FUSED group names cannot collide
    opt.next_id = static_cast<int>(opt.nodes.size()) + 1000;
    std::vector<std::string> outs = plan->last_outputs;
    for (const auto& a : plan->aliases) outs.push_back(a);
    et::PlanOptStats st;
    s = et::OptimizePreparedPlan(&opt, outs, &st);
    if (!s.ok()) {
      FailWith(s.message());
      return -1;
    }
    text = "optimized rewrites[fuse=" + std::to_string(st.fuse) +
           " pushdown=" + std::to_string(st.pushdown) +
           " dedup=" + std::to_string(st.dedup) + "] deterministic=" +
           (et::DagIsDeterministic(opt) ? "1" : "0") + "\n" +
           et::DagToString(opt);
  }
  int64_t n = static_cast<int64_t>(text.size());
  if (buf != nullptr && buf_len > 0) {
    int64_t c = std::min(buf_len - 1, n);
    std::memcpy(buf, text.data(), c);
    buf[c] = '\0';
  }
  return n;
}

// Server-side explain: dump every plan registered in server h's shared
// store (generation, determinism, rewrite counts, executing DAG, and
// the verbatim registered form when the optimizer rewrote it).
int64_t ets_plan_debug(int64_t h, char* buf, int64_t buf_len) {
  std::shared_ptr<et::GraphServer> server;
  {
    auto& r = QReg();
    std::lock_guard<std::mutex> lk(r.mu);
    auto it = r.servers.find(h);
    if (it != r.servers.end()) server = it->second;
  }
  if (!server) {
    FailWith("bad server handle");
    return -1;
  }
  std::string text = server->DebugPlans();
  int64_t n = static_cast<int64_t>(text.size());
  if (buf != nullptr && buf_len > 0) {
    int64_t c = std::min(buf_len - 1, n);
    std::memcpy(buf, text.data(), c);
    buf[c] = '\0';
  }
  return n;
}

}  // extern "C"
