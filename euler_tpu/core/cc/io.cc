#include "io.h"

#include "hdfs_io.h"

#include <cstdio>

namespace et {

namespace {
constexpr char kMetaMagic[4] = {'E', 'T', 'M', '1'};
constexpr char kPartMagic[4] = {'E', 'T', 'P', '1'};
// v2 adds an optional trailing graph-label section to partition files
// (whole-graph classification support); v1 files load fine (no labels).
constexpr uint32_t kVersion = 2;
}  // namespace

Status ReadFileToString(const std::string& path, std::string* out) {
  if (IsHdfsPath(path)) return HdfsReadFile(path, out);
  std::FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) return Status::IOError("cannot open " + path);
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  out->resize(static_cast<size_t>(size));
  size_t got = size ? std::fread(&(*out)[0], 1, size, f) : 0;
  std::fclose(f);
  if (got != static_cast<size_t>(size)) {
    return Status::IOError("short read on " + path);
  }
  return Status::OK();
}

Status WriteStringToFile(const std::string& path, const char* data,
                         size_t size) {
  if (IsHdfsPath(path)) return HdfsWriteFile(path, data, size);
  std::FILE* f = std::fopen(path.c_str(), "wb");
  if (!f) return Status::IOError("cannot open " + path + " for write");
  size_t put = size ? std::fwrite(data, 1, size, f) : 0;
  std::fclose(f);
  if (put != size) return Status::IOError("short write on " + path);
  return Status::OK();
}

Status SaveMeta(const GraphMeta& meta, const std::string& path) {
  ByteWriter w;
  EncodeMeta(meta, &w);
  return WriteStringToFile(path, w.buffer().data(), w.buffer().size());
}

void EncodeMeta(const GraphMeta& meta, ByteWriter* wp) {
  ByteWriter& w = *wp;
  w.PutRaw(kMetaMagic, 4);
  w.Put<uint32_t>(kVersion);
  w.Put<uint32_t>(meta.num_node_types);
  w.Put<uint32_t>(meta.num_edge_types);
  w.Put<uint32_t>(meta.partition_num);
  w.Put<uint64_t>(meta.node_count);
  w.Put<uint64_t>(meta.edge_count);
  w.PutStr(meta.name);
  w.Put<uint32_t>(static_cast<uint32_t>(meta.node_type_names.size()));
  for (const auto& s : meta.node_type_names) w.PutStr(s);
  w.Put<uint32_t>(static_cast<uint32_t>(meta.edge_type_names.size()));
  for (const auto& s : meta.edge_type_names) w.PutStr(s);
  auto put_feats = [&](const std::vector<FeatureInfo>& fs) {
    w.Put<uint32_t>(static_cast<uint32_t>(fs.size()));
    for (const auto& f : fs) {
      w.PutStr(f.name);
      w.Put<int32_t>(static_cast<int32_t>(f.kind));
      w.Put<int64_t>(f.dim);
    }
  };
  put_feats(meta.node_features);
  put_feats(meta.edge_features);
}

Status LoadMeta(const std::string& path, GraphMeta* meta) {
  std::string blob;
  ET_RETURN_IF_ERROR(ReadFileToString(path, &blob));
  ByteReader r(blob.data(), blob.size());
  Status s = DecodeMeta(&r, meta);
  if (!s.ok()) return Status::IOError(s.message() + " in " + path);
  return Status::OK();
}

Status DecodeMeta(ByteReader* rp, GraphMeta* meta) {
  ByteReader& r = *rp;
  char magic[4];
  uint32_t ver, nt, et, pn;
  if (!r.GetRaw(magic, 4) || std::memcmp(magic, kMetaMagic, 4) != 0) {
    return Status::IOError("bad meta magic");
  }
  if (!r.Get(&ver) || ver < 1 || ver > kVersion) {
    return Status::IOError("unsupported meta version");
  }
  if (!r.Get(&nt) || !r.Get(&et) || !r.Get(&pn)) {
    return Status::IOError("truncated meta");
  }
  meta->num_node_types = nt;
  meta->num_edge_types = et;
  meta->partition_num = pn;
  uint64_t nc, ec;
  if (!r.Get(&nc) || !r.Get(&ec)) return Status::IOError("truncated meta");
  meta->node_count = nc;
  meta->edge_count = ec;
  if (!r.GetStr(&meta->name)) return Status::IOError("truncated meta");
  auto get_strs = [&](std::vector<std::string>* out) {
    uint32_t n;
    if (!r.Get(&n)) return false;
    out->resize(n);
    for (uint32_t i = 0; i < n; ++i) {
      if (!r.GetStr(&(*out)[i])) return false;
    }
    return true;
  };
  if (!get_strs(&meta->node_type_names) || !get_strs(&meta->edge_type_names)) {
    return Status::IOError("truncated meta");
  }
  auto get_feats = [&](std::vector<FeatureInfo>* out) {
    uint32_t n;
    if (!r.Get(&n)) return false;
    out->resize(n);
    for (uint32_t i = 0; i < n; ++i) {
      int32_t kind;
      if (!r.GetStr(&(*out)[i].name) || !r.Get(&kind) ||
          !r.Get(&(*out)[i].dim)) {
        return false;
      }
      (*out)[i].kind = static_cast<FeatureKind>(kind);
    }
    return true;
  };
  if (!get_feats(&meta->node_features) || !get_feats(&meta->edge_features)) {
    return Status::IOError("truncated meta");
  }
  return Status::OK();
}

namespace {

struct FeatBlock {
  std::vector<std::pair<uint16_t, std::vector<float>>> dense;
  std::vector<std::pair<uint16_t, std::vector<uint64_t>>> sparse;
  std::vector<std::pair<uint16_t, std::vector<char>>> binary;
};

bool ReadFeats(ByteReader* r, FeatBlock* fb) {
  uint16_t nd, ns, nb;
  if (!r->Get(&nd)) return false;
  fb->dense.resize(nd);
  for (uint16_t i = 0; i < nd; ++i) {
    uint32_t dim;
    if (!r->Get(&fb->dense[i].first) || !r->Get(&dim)) return false;
    fb->dense[i].second.resize(dim);
    if (!r->GetRaw(fb->dense[i].second.data(), dim * sizeof(float))) {
      return false;
    }
  }
  if (!r->Get(&ns)) return false;
  fb->sparse.resize(ns);
  for (uint16_t i = 0; i < ns; ++i) {
    uint32_t len;
    if (!r->Get(&fb->sparse[i].first) || !r->Get(&len)) return false;
    fb->sparse[i].second.resize(len);
    if (!r->GetRaw(fb->sparse[i].second.data(), len * sizeof(uint64_t))) {
      return false;
    }
  }
  if (!r->Get(&nb)) return false;
  fb->binary.resize(nb);
  for (uint16_t i = 0; i < nb; ++i) {
    uint32_t len;
    if (!r->Get(&fb->binary[i].first) || !r->Get(&len)) return false;
    fb->binary[i].second.resize(len);
    if (!r->GetRaw(fb->binary[i].second.data(), len)) return false;
  }
  return true;
}

}  // namespace

Status LoadPartitionFile(const std::string& path, int data_type,
                         GraphBuilder* builder) {
  std::string blob;
  ET_RETURN_IF_ERROR(ReadFileToString(path, &blob));
  ByteReader r(blob.data(), blob.size());
  char magic[4];
  uint32_t ver;
  if (!r.GetRaw(magic, 4) || std::memcmp(magic, kPartMagic, 4) != 0) {
    return Status::IOError("bad partition magic in " + path);
  }
  if (!r.Get(&ver) || ver < 1 || ver > kVersion) {
    return Status::IOError("unsupported partition version");
  }
  uint64_t n_nodes;
  if (!r.Get(&n_nodes)) return Status::IOError("truncated partition");
  bool want_nodes = data_type == 0 || data_type == 1;
  bool want_edges = data_type == 0 || data_type == 2;
  for (uint64_t i = 0; i < n_nodes; ++i) {
    uint64_t id;
    int32_t type;
    float w;
    FeatBlock fb;
    if (!r.Get(&id) || !r.Get(&type) || !r.Get(&w) || !ReadFeats(&r, &fb)) {
      return Status::IOError("truncated node record in " + path);
    }
    if (!want_nodes) continue;
    builder->AddNode(id, type, w);
    for (auto& d : fb.dense) {
      builder->SetNodeDense(id, d.first, d.second.data(),
                            static_cast<int64_t>(d.second.size()));
    }
    for (auto& s : fb.sparse) {
      builder->SetNodeSparse(id, s.first, s.second.data(),
                             static_cast<int64_t>(s.second.size()));
    }
    for (auto& b : fb.binary) {
      builder->SetNodeBinary(id, b.first, b.second.data(),
                             static_cast<int64_t>(b.second.size()));
    }
  }
  uint64_t n_edges;
  if (!r.Get(&n_edges)) return Status::IOError("truncated partition");
  for (uint64_t i = 0; i < n_edges; ++i) {
    uint64_t src, dst;
    int32_t type;
    float w;
    FeatBlock fb;
    if (!r.Get(&src) || !r.Get(&dst) || !r.Get(&type) || !r.Get(&w) ||
        !ReadFeats(&r, &fb)) {
      return Status::IOError("truncated edge record in " + path);
    }
    if (!want_edges) continue;
    builder->AddEdge(src, dst, type, w);
    for (auto& d : fb.dense) {
      builder->SetEdgeDense(src, dst, type, d.first, d.second.data(),
                            static_cast<int64_t>(d.second.size()));
    }
    for (auto& s : fb.sparse) {
      builder->SetEdgeSparse(src, dst, type, s.first, s.second.data(),
                             static_cast<int64_t>(s.second.size()));
    }
    for (auto& b : fb.binary) {
      builder->SetEdgeBinary(src, dst, type, b.first, b.second.data(),
                             static_cast<int64_t>(b.second.size()));
    }
  }
  if (ver >= 2 && r.remaining() >= sizeof(uint64_t)) {
    uint64_t n_labeled;
    if (!r.Get(&n_labeled)) return Status::IOError("truncated label section");
    for (uint64_t i = 0; i < n_labeled; ++i) {
      uint64_t id, gl;
      if (!r.Get(&id) || !r.Get(&gl))
        return Status::IOError("truncated label record in " + path);
      if (want_nodes) builder->SetGraphLabels(&id, &gl, 1);
    }
  }
  return Status::OK();
}

Status LoadShard(const std::string& dir, int shard_idx, int shard_num,
                 int data_type, bool build_in_adjacency,
                 std::unique_ptr<Graph>* out) {
  if (shard_num <= 0) shard_num = 1;
  GraphMeta meta;
  ET_RETURN_IF_ERROR(LoadMeta(dir + "/meta.bin", &meta));
  GraphBuilder builder;
  *builder.mutable_meta() = meta;
  int loaded = 0;
  for (int p = 0; p < meta.partition_num; ++p) {
    if (p % shard_num != shard_idx) continue;
    std::string path = dir + "/part_" + std::to_string(p) + ".dat";
    ET_RETURN_IF_ERROR(LoadPartitionFile(path, data_type, &builder));
    ++loaded;
  }
  ET_LOG(INFO) << "loaded shard " << shard_idx << "/" << shard_num << " ("
               << loaded << " partitions) from " << dir;
  *out = builder.Finalize(build_in_adjacency);
  return Status::OK();
}

// Writes the records of partition p of P (nodes and source-owned edges
// with id % P == p) — the same assignment the Python prep tool uses
// (tools/generate_data.py) so dumped and generated data interoperate.
// by_graph: partition ownership by graph label (graph_partition mode —
// whole graphs stay on one shard) instead of node-id hash.
static uint64_t OwnerOf(const Graph& g, uint32_t row, uint64_t P,
                        bool by_graph) {
  if (by_graph) {
    uint64_t gl = g.node_graph_label(row);
    if (gl != 0) return gl % P;
  }
  return g.node_id(row) % P;
}

static Status DumpOnePartition(const Graph& g, const GraphMeta& meta,
                               const std::string& path, uint64_t p,
                               uint64_t P, bool by_graph) {
  ByteWriter w;
  w.PutRaw(kPartMagic, 4);
  w.Put<uint32_t>(kVersion);
  const size_t N = g.node_count();
  size_t n_mine = 0;
  for (size_t i = 0; i < N; ++i)
    if (OwnerOf(g, static_cast<uint32_t>(i), P, by_graph) == p) ++n_mine;
  w.Put<uint64_t>(n_mine);
  std::vector<float> dense_buf;
  std::vector<uint64_t> sp_off, sp_val;
  std::vector<char> bin_val;
  for (size_t i = 0; i < N; ++i) {
    NodeId id = g.node_id(static_cast<uint32_t>(i));
    if (OwnerOf(g, static_cast<uint32_t>(i), P, by_graph) != p) continue;
    w.Put<uint64_t>(id);
    w.Put<int32_t>(g.node_type(static_cast<uint32_t>(i)));
    w.Put<float>(g.node_weight(static_cast<uint32_t>(i)));
    // Collect this node's features by querying the public accessors.
    std::vector<std::pair<uint16_t, std::vector<float>>> dense;
    std::vector<std::pair<uint16_t, std::vector<uint64_t>>> sparse;
    std::vector<std::pair<uint16_t, std::vector<char>>> binary;
    for (size_t fid = 0; fid < meta.node_features.size(); ++fid) {
      const auto& info = meta.node_features[fid];
      if (info.kind == FeatureKind::kDense && info.dim > 0) {
        dense_buf.assign(info.dim, 0.f);
        g.GetDenseFeature(&id, 1, static_cast<int>(fid), info.dim,
                          dense_buf.data());
        dense.push_back({static_cast<uint16_t>(fid), dense_buf});
      } else if (info.kind == FeatureKind::kSparse) {
        sp_off.clear();
        sp_val.clear();
        g.GetSparseFeature(&id, 1, static_cast<int>(fid), &sp_off, &sp_val);
        if (!sp_val.empty()) {
          sparse.push_back({static_cast<uint16_t>(fid), sp_val});
        }
      } else if (info.kind == FeatureKind::kBinary) {
        sp_off.clear();
        bin_val.clear();
        g.GetBinaryFeature(&id, 1, static_cast<int>(fid), &sp_off, &bin_val);
        if (!bin_val.empty()) {
          binary.push_back({static_cast<uint16_t>(fid), bin_val});
        }
      }
    }
    w.Put<uint16_t>(static_cast<uint16_t>(dense.size()));
    for (auto& d : dense) {
      w.Put<uint16_t>(d.first);
      w.Put<uint32_t>(static_cast<uint32_t>(d.second.size()));
      w.PutRaw(d.second.data(), d.second.size() * sizeof(float));
    }
    w.Put<uint16_t>(static_cast<uint16_t>(sparse.size()));
    for (auto& s : sparse) {
      w.Put<uint16_t>(s.first);
      w.Put<uint32_t>(static_cast<uint32_t>(s.second.size()));
      w.PutRaw(s.second.data(), s.second.size() * sizeof(uint64_t));
    }
    w.Put<uint16_t>(static_cast<uint16_t>(binary.size()));
    for (auto& b : binary) {
      w.Put<uint16_t>(b.first);
      w.Put<uint32_t>(static_cast<uint32_t>(b.second.size()));
      w.PutRaw(b.second.data(), b.second.size());
    }
  }

  // Edges: walk every node's full out-neighborhood.
  std::vector<NodeId> nbr;
  std::vector<float> ws;
  std::vector<int32_t> ts;
  uint64_t edge_total = 0;
  for (size_t i = 0; i < N; ++i) {
    if (OwnerOf(g, static_cast<uint32_t>(i), P, by_graph) != p) continue;
    nbr.clear();
    ws.clear();
    ts.clear();
    g.GetFullNeighbor(g.node_id(static_cast<uint32_t>(i)), nullptr, 0, &nbr,
                      &ws, &ts);
    edge_total += nbr.size();
  }
  w.Put<uint64_t>(edge_total);
  for (size_t i = 0; i < N; ++i) {
    NodeId src = g.node_id(static_cast<uint32_t>(i));
    if (OwnerOf(g, static_cast<uint32_t>(i), P, by_graph) != p) continue;
    nbr.clear();
    ws.clear();
    ts.clear();
    g.GetFullNeighbor(src, nullptr, 0, &nbr, &ws, &ts);
    for (size_t e = 0; e < nbr.size(); ++e) {
      w.Put<uint64_t>(src);
      w.Put<uint64_t>(nbr[e]);
      w.Put<int32_t>(ts[e]);
      w.Put<float>(ws[e]);
      std::vector<std::pair<uint16_t, std::vector<float>>> dense;
      std::vector<std::pair<uint16_t, std::vector<uint64_t>>> sparse;
      std::vector<std::pair<uint16_t, std::vector<char>>> binary;
      for (size_t fid = 0; fid < meta.edge_features.size(); ++fid) {
        const auto& info = meta.edge_features[fid];
        if (info.kind == FeatureKind::kDense && info.dim > 0) {
          dense_buf.assign(info.dim, 0.f);
          g.GetEdgeDenseFeature(&src, &nbr[e], &ts[e], 1,
                                static_cast<int>(fid), info.dim,
                                dense_buf.data());
          bool nonzero = false;
          for (float v : dense_buf) nonzero |= (v != 0.f);
          if (nonzero) dense.push_back({static_cast<uint16_t>(fid), dense_buf});
        } else if (info.kind == FeatureKind::kSparse) {
          sp_off.clear();
          sp_val.clear();
          g.GetEdgeSparseFeature(&src, &nbr[e], &ts[e], 1,
                                 static_cast<int>(fid), &sp_off, &sp_val);
          if (!sp_val.empty()) {
            sparse.push_back({static_cast<uint16_t>(fid), sp_val});
          }
        } else if (info.kind == FeatureKind::kBinary) {
          sp_off.clear();
          bin_val.clear();
          g.GetEdgeBinaryFeature(&src, &nbr[e], &ts[e], 1,
                                 static_cast<int>(fid), &sp_off, &bin_val);
          if (!bin_val.empty()) {
            binary.push_back({static_cast<uint16_t>(fid), bin_val});
          }
        }
      }
      w.Put<uint16_t>(static_cast<uint16_t>(dense.size()));
      for (auto& d : dense) {
        w.Put<uint16_t>(d.first);
        w.Put<uint32_t>(static_cast<uint32_t>(d.second.size()));
        w.PutRaw(d.second.data(), d.second.size() * sizeof(float));
      }
      w.Put<uint16_t>(static_cast<uint16_t>(sparse.size()));
      for (auto& s : sparse) {
        w.Put<uint16_t>(s.first);
        w.Put<uint32_t>(static_cast<uint32_t>(s.second.size()));
        w.PutRaw(s.second.data(), s.second.size() * sizeof(uint64_t));
      }
      w.Put<uint16_t>(static_cast<uint16_t>(binary.size()));
      for (auto& b : binary) {
        w.Put<uint16_t>(b.first);
        w.Put<uint32_t>(static_cast<uint32_t>(b.second.size()));
        w.PutRaw(b.second.data(), b.second.size());
      }
    }
  }

  // v2 trailing section: graph labels of this partition's nodes
  uint64_t n_labeled = 0;
  for (size_t i = 0; i < N; ++i) {
    if (OwnerOf(g, static_cast<uint32_t>(i), P, by_graph) != p) continue;
    if (g.node_graph_label(static_cast<uint32_t>(i)) != 0) ++n_labeled;
  }
  w.Put<uint64_t>(n_labeled);
  for (size_t i = 0; i < N; ++i) {
    NodeId id = g.node_id(static_cast<uint32_t>(i));
    if (OwnerOf(g, static_cast<uint32_t>(i), P, by_graph) != p) continue;
    uint64_t gl = g.node_graph_label(static_cast<uint32_t>(i));
    if (gl == 0) continue;
    w.Put<uint64_t>(id);
    w.Put<uint64_t>(gl);
  }
  return WriteStringToFile(path, w.buffer().data(), w.buffer().size());
}

Status DumpGraphPartitioned(const Graph& g, const std::string& dir,
                            int num_partitions, bool by_graph) {
  if (num_partitions < 1) num_partitions = 1;
  GraphMeta meta = g.meta();
  meta.partition_num = num_partitions;
  ET_RETURN_IF_ERROR(SaveMeta(meta, dir + "/meta.bin"));
  for (int p = 0; p < num_partitions; ++p) {
    ET_RETURN_IF_ERROR(
        DumpOnePartition(g, meta, dir + "/part_" + std::to_string(p) + ".dat",
                         p, num_partitions, by_graph));
  }
  return Status::OK();
}

Status DumpGraph(const Graph& g, const std::string& dir) {
  return DumpGraphPartitioned(g, dir, 1, false);
}

Status Graph::Dump(const std::string& path) const {
  return DumpGraph(*this, path);
}

}  // namespace et
