#include "index.h"

#include <sys/stat.h>

#include <algorithm>
#include <cstdlib>
#include <sstream>

#include "graph.h"
#include "io.h"

namespace et {

// ---------------------------------------------------------------------------
// IndexResult algebra
// ---------------------------------------------------------------------------
IndexResult IndexResult::Union(const IndexResult& a, const IndexResult& b) {
  IndexResult out;
  out.rows.reserve(a.rows.size() + b.rows.size());
  size_t i = 0, j = 0;
  while (i < a.rows.size() || j < b.rows.size()) {
    if (j >= b.rows.size() || (i < a.rows.size() && a.rows[i] < b.rows[j])) {
      out.rows.push_back(a.rows[i]);
      out.weights.push_back(a.weights[i]);
      ++i;
    } else if (i >= a.rows.size() || b.rows[j] < a.rows[i]) {
      out.rows.push_back(b.rows[j]);
      out.weights.push_back(b.weights[j]);
      ++j;
    } else {  // equal row — keep one copy
      out.rows.push_back(a.rows[i]);
      out.weights.push_back(a.weights[i]);
      ++i;
      ++j;
    }
  }
  return out;
}

IndexResult IndexResult::Intersect(const IndexResult& a,
                                   const IndexResult& b) {
  IndexResult out;
  size_t i = 0, j = 0;
  while (i < a.rows.size() && j < b.rows.size()) {
    if (a.rows[i] < b.rows[j]) {
      ++i;
    } else if (b.rows[j] < a.rows[i]) {
      ++j;
    } else {
      out.rows.push_back(a.rows[i]);
      out.weights.push_back(a.weights[i]);
      ++i;
      ++j;
    }
  }
  return out;
}

bool IndexResult::Contains(uint32_t row) const {
  return std::binary_search(rows.begin(), rows.end(), row);
}

float IndexResult::TotalWeight() const {
  float s = 0;
  for (float w : weights) s += w;
  return s;
}

void IndexResult::Sample(size_t count, Pcg32* rng, uint32_t* out) const {
  if (rows.empty()) {
    for (size_t i = 0; i < count; ++i) out[i] = kInvalidRow;
    return;
  }
  std::vector<float> cum(weights.size());
  float s = 0;
  for (size_t i = 0; i < weights.size(); ++i) {
    s += weights[i];
    cum[i] = s;
  }
  if (s <= 0) {  // all-zero weights → uniform
    for (size_t i = 0; i < count; ++i)
      out[i] = rows[rng->NextUInt(rows.size())];
    return;
  }
  for (size_t i = 0; i < count; ++i) {
    float r = rng->NextFloat() * s;
    size_t idx = std::lower_bound(cum.begin(), cum.end(), r) - cum.begin();
    if (idx >= rows.size()) idx = rows.size() - 1;
    out[i] = rows[idx];
  }
}

// ---------------------------------------------------------------------------
// HashSampleIndex
// ---------------------------------------------------------------------------
CmpOp ParseCmpOp(const std::string& s) {
  if (s == "eq") return CmpOp::kEq;
  if (s == "ne") return CmpOp::kNe;
  if (s == "lt") return CmpOp::kLt;
  if (s == "le") return CmpOp::kLe;
  if (s == "gt") return CmpOp::kGt;
  if (s == "ge") return CmpOp::kGe;
  if (s == "in") return CmpOp::kIn;
  if (s == "hk") return CmpOp::kHasKey;
  ET_LOG(WARNING) << "unknown cmp op '" << s << "', treating as eq";
  return CmpOp::kEq;
}

void HashSampleIndex::Add(const std::string& term, uint32_t row,
                          float weight) {
  auto& p = postings_[term];
  p.rows.push_back(row);
  p.weights.push_back(weight);
  all_.rows.push_back(row);
  all_.weights.push_back(weight);
}

static void SortResult(IndexResult* r) {
  std::vector<size_t> order(r->rows.size());
  for (size_t i = 0; i < order.size(); ++i) order[i] = i;
  std::sort(order.begin(), order.end(),
            [&](size_t a, size_t b) { return r->rows[a] < r->rows[b]; });
  IndexResult sorted;
  sorted.rows.reserve(order.size());
  sorted.weights.reserve(order.size());
  for (size_t i : order) {
    // drop duplicate rows (a sparse feature can repeat a token)
    if (!sorted.rows.empty() && sorted.rows.back() == r->rows[i]) continue;
    sorted.rows.push_back(r->rows[i]);
    sorted.weights.push_back(r->weights[i]);
  }
  *r = std::move(sorted);
}

void HashSampleIndex::Seal() {
  for (auto& kv : postings_) SortResult(&kv.second);
  SortResult(&all_);
}

static IndexResult Difference(const IndexResult& all, const IndexResult& b) {
  IndexResult out;
  size_t j = 0;
  for (size_t i = 0; i < all.rows.size(); ++i) {
    while (j < b.rows.size() && b.rows[j] < all.rows[i]) ++j;
    if (j < b.rows.size() && b.rows[j] == all.rows[i]) continue;
    out.rows.push_back(all.rows[i]);
    out.weights.push_back(all.weights[i]);
  }
  return out;
}

IndexResult HashSampleIndex::Lookup(CmpOp op, const std::string& value) const {
  switch (op) {
    case CmpOp::kHasKey:
      return all_;
    case CmpOp::kEq: {
      auto it = postings_.find(value);
      return it == postings_.end() ? IndexResult() : it->second;
    }
    case CmpOp::kNe: {
      auto it = postings_.find(value);
      return it == postings_.end() ? all_ : Difference(all_, it->second);
    }
    case CmpOp::kIn: {
      IndexResult acc;
      std::stringstream ss(value);
      std::string term;
      while (std::getline(ss, term, ':')) {
        auto it = postings_.find(term);
        if (it != postings_.end()) acc = IndexResult::Union(acc, it->second);
      }
      return acc;
    }
    default:
      ET_LOG(WARNING) << "hash index does not support range ops";
      return IndexResult();
  }
}

// ---------------------------------------------------------------------------
// RangeSampleIndex
// ---------------------------------------------------------------------------
void RangeSampleIndex::Add(double value, uint32_t row, float weight) {
  entries_.push_back({value, row, weight});
}

void RangeSampleIndex::Seal() {
  std::sort(entries_.begin(), entries_.end(),
            [](const Entry& a, const Entry& b) {
              return a.value < b.value ||
                     (a.value == b.value && a.row < b.row);
            });
}

IndexResult RangeSampleIndex::RangeToResult(size_t begin, size_t end) const {
  IndexResult out;
  out.rows.reserve(end - begin);
  for (size_t i = begin; i < end; ++i) {
    out.rows.push_back(entries_[i].row);
    out.weights.push_back(entries_[i].weight);
  }
  SortResult(&out);
  return out;
}

IndexResult RangeSampleIndex::Lookup(CmpOp op,
                                     const std::string& value) const {
  auto lb = [this](double v) {
    return std::lower_bound(entries_.begin(), entries_.end(), v,
                            [](const Entry& e, double x) {
                              return e.value < x;
                            }) -
           entries_.begin();
  };
  auto ub = [this](double v) {
    return std::upper_bound(entries_.begin(), entries_.end(), v,
                            [](double x, const Entry& e) {
                              return x < e.value;
                            }) -
           entries_.begin();
  };
  if (op == CmpOp::kHasKey) return RangeToResult(0, entries_.size());
  if (op == CmpOp::kIn) {
    IndexResult acc;
    std::stringstream ss(value);
    std::string term;
    while (std::getline(ss, term, ':')) {
      double v = std::atof(term.c_str());
      acc = IndexResult::Union(acc, RangeToResult(lb(v), ub(v)));
    }
    return acc;
  }
  double v = std::atof(value.c_str());
  switch (op) {
    case CmpOp::kEq: return RangeToResult(lb(v), ub(v));
    case CmpOp::kLt: return RangeToResult(0, lb(v));
    case CmpOp::kLe: return RangeToResult(0, ub(v));
    case CmpOp::kGt: return RangeToResult(ub(v), entries_.size());
    case CmpOp::kGe: return RangeToResult(lb(v), entries_.size());
    case CmpOp::kNe: {
      IndexResult lo = RangeToResult(0, lb(v));
      IndexResult hi = RangeToResult(ub(v), entries_.size());
      return IndexResult::Union(lo, hi);
    }
    default: return IndexResult();
  }
}

// ---------------------------------------------------------------------------
// HashRangeSampleIndex (reference hash_range_sample_index.h)
// ---------------------------------------------------------------------------
void HashRangeSampleIndex::Add(const std::string& term, double value,
                               uint32_t row, float weight) {
  sub_[term].Add(value, row, weight);
}

void HashRangeSampleIndex::Seal() {
  for (auto& kv : sub_) kv.second.Seal();
}

IndexResult HashRangeSampleIndex::Lookup(CmpOp op,
                                         const std::string& value) const {
  auto p = value.find("::");
  if (p == std::string::npos) return IndexResult();
  auto it = sub_.find(value.substr(0, p));
  if (it == sub_.end()) return IndexResult();
  return it->second.Lookup(op, value.substr(p + 2));
}

// ---------------------------------------------------------------------------
// IndexManager
// ---------------------------------------------------------------------------
Status IndexManager::BuildFromSpec(const Graph& g, const std::string& spec) {
  std::stringstream ss(spec);
  std::string item;
  while (std::getline(ss, item, ',')) {
    if (item.empty()) continue;
    if (item.rfind("load:", 0) == 0) {
      ET_RETURN_IF_ERROR(Load(item.substr(5)));
      continue;
    }
    auto pos = item.find(':');
    if (pos == std::string::npos)
      return Status::InvalidArgument("bad index spec item: " + item);
    std::string attr = item.substr(0, pos);
    std::string kind_s = item.substr(pos + 1);
    IndexKind kind;
    if (kind_s.find("hash_range") != std::string::npos) {
      kind = IndexKind::kHashRange;
    } else if (kind_s.find("range") != std::string::npos) {
      kind = IndexKind::kRange;
    } else {
      kind = IndexKind::kHash;
    }
    ET_RETURN_IF_ERROR(Build(g, attr, kind));
  }
  return Status::OK();
}

namespace {

// Per-row attribute accessors shared by the composite build: the hash
// terms (stringified values) and numeric values of one attribute.
Status RowHashTerms(const Graph& g, const std::string& attr, uint32_t row,
                    std::vector<std::string>* out) {
  out->clear();
  const GraphMeta& meta = g.meta();
  if (attr == "node_type" || attr == "label") {
    int32_t t = g.node_type(row);
    std::string name = (t >= 0 && t < (int)meta.node_type_names.size())
                           ? meta.node_type_names[t]
                           : std::to_string(t);
    out->push_back(name);
    if (name != std::to_string(t)) out->push_back(std::to_string(t));
    return Status::OK();
  }
  int fid = -1;
  for (size_t i = 0; i < meta.node_features.size(); ++i)
    if (meta.node_features[i].name == attr) fid = static_cast<int>(i);
  if (fid < 0) return Status::NotFound("no node feature named " + attr);
  NodeId id = g.node_id(row);
  const FeatureInfo& fi = meta.node_features[fid];
  if (fi.kind == FeatureKind::kDense) {
    float v;
    g.GetDenseFeature(&id, 1, fid, 1, &v);
    std::ostringstream os;
    os << v;
    out->push_back(os.str());
  } else if (fi.kind == FeatureKind::kSparse) {
    std::vector<uint64_t> offs, vals;
    g.GetSparseFeature(&id, 1, fid, &offs, &vals);
    for (uint64_t v : vals) out->push_back(std::to_string(v));
  } else {
    std::vector<uint64_t> offs;
    std::vector<char> bytes;
    g.GetBinaryFeature(&id, 1, fid, &offs, &bytes);
    out->push_back(std::string(bytes.begin(), bytes.end()));
  }
  return Status::OK();
}

Status RowRangeValues(const Graph& g, const std::string& attr, uint32_t row,
                      std::vector<double>* out) {
  out->clear();
  const GraphMeta& meta = g.meta();
  if (attr == "node_type" || attr == "label") {
    out->push_back(g.node_type(row));
    return Status::OK();
  }
  int fid = -1;
  for (size_t i = 0; i < meta.node_features.size(); ++i)
    if (meta.node_features[i].name == attr) fid = static_cast<int>(i);
  if (fid < 0) return Status::NotFound("no node feature named " + attr);
  const FeatureInfo& fi = meta.node_features[fid];
  NodeId id = g.node_id(row);
  if (fi.kind == FeatureKind::kDense) {
    float v;
    g.GetDenseFeature(&id, 1, fid, 1, &v);
    out->push_back(v);
  } else if (fi.kind == FeatureKind::kSparse) {
    std::vector<uint64_t> offs, vals;
    g.GetSparseFeature(&id, 1, fid, &offs, &vals);
    for (uint64_t v : vals) out->push_back(static_cast<double>(v));
  } else {
    return Status::InvalidArgument(
        "binary feature cannot be the range half of a composite index: " +
        attr);
  }
  return Status::OK();
}

}  // namespace

Status IndexManager::Build(const Graph& g, const std::string& attr,
                           IndexKind kind) {
  const GraphMeta& meta = g.meta();
  size_t n = g.node_count();

  if (kind == IndexKind::kHashRange) {
    // composite "A+B": per-term sub-range-index (reference
    // HashRangeSampleIndex — one lookup serves "A eq x and B cmp v")
    auto plus = attr.find('+');
    if (plus == std::string::npos)
      return Status::InvalidArgument(
          "hash_range_index needs 'attrA+attrB', got: " + attr);
    std::string ha = attr.substr(0, plus), ra = attr.substr(plus + 1);
    auto idx = std::make_unique<HashRangeSampleIndex>();
    std::vector<std::string> terms;
    std::vector<double> vals;
    for (uint32_t row = 0; row < n; ++row) {
      ET_RETURN_IF_ERROR(RowHashTerms(g, ha, row, &terms));
      ET_RETURN_IF_ERROR(RowRangeValues(g, ra, row, &vals));
      float w = g.node_weight(row);
      for (const auto& t : terms)
        for (double v : vals) idx->Add(t, v, row, w);
    }
    idx->Seal();
    indexes_[attr] = std::move(idx);
    return Status::OK();
  }

  auto add_all = [&](auto* idx, auto&& value_of) {
    for (uint32_t row = 0; row < n; ++row) value_of(idx, row);
    idx->Seal();
  };

  if (attr == "node_type" || attr == "label") {
    if (kind == IndexKind::kHash) {
      auto idx = std::make_unique<HashSampleIndex>();
      add_all(idx.get(), [&](HashSampleIndex* ix, uint32_t row) {
        int32_t t = g.node_type(row);
        std::string name = (t >= 0 && t < (int)meta.node_type_names.size())
                               ? meta.node_type_names[t]
                               : std::to_string(t);
        ix->Add(name, row, g.node_weight(row));
        if (name != std::to_string(t))  // allow numeric form too
          ix->Add(std::to_string(t), row, g.node_weight(row));
      });
      indexes_[attr] = std::move(idx);
    } else {
      auto idx = std::make_unique<RangeSampleIndex>();
      add_all(idx.get(), [&](RangeSampleIndex* ix, uint32_t row) {
        ix->Add(g.node_type(row), row, g.node_weight(row));
      });
      indexes_[attr] = std::move(idx);
    }
    return Status::OK();
  }

  // Feature-backed attribute.
  int fid = -1;
  for (size_t i = 0; i < meta.node_features.size(); ++i)
    if (meta.node_features[i].name == attr) fid = static_cast<int>(i);
  if (fid < 0) return Status::NotFound("no node feature named " + attr);
  const FeatureInfo& fi = meta.node_features[fid];

  if (fi.kind == FeatureKind::kDense) {
    // scalar at dim 0
    std::vector<float> buf(1);
    if (kind == IndexKind::kRange) {
      auto idx = std::make_unique<RangeSampleIndex>();
      for (uint32_t row = 0; row < n; ++row) {
        NodeId id = g.node_id(row);
        g.GetDenseFeature(&id, 1, fid, 1, buf.data());
        idx->Add(buf[0], row, g.node_weight(row));
      }
      idx->Seal();
      indexes_[attr] = std::move(idx);
    } else {
      auto idx = std::make_unique<HashSampleIndex>();
      for (uint32_t row = 0; row < n; ++row) {
        NodeId id = g.node_id(row);
        g.GetDenseFeature(&id, 1, fid, 1, buf.data());
        std::ostringstream os;
        os << buf[0];
        idx->Add(os.str(), row, g.node_weight(row));
      }
      idx->Seal();
      indexes_[attr] = std::move(idx);
    }
    return Status::OK();
  }

  if (fi.kind == FeatureKind::kSparse) {
    std::vector<uint64_t> offs, vals;
    if (kind == IndexKind::kRange) {
      auto idx = std::make_unique<RangeSampleIndex>();
      for (uint32_t row = 0; row < n; ++row) {
        NodeId id = g.node_id(row);
        offs.clear();
        vals.clear();
        g.GetSparseFeature(&id, 1, fid, &offs, &vals);
        for (uint64_t v : vals)
          idx->Add(static_cast<double>(v), row, g.node_weight(row));
      }
      idx->Seal();
      indexes_[attr] = std::move(idx);
    } else {
      auto idx = std::make_unique<HashSampleIndex>();
      for (uint32_t row = 0; row < n; ++row) {
        NodeId id = g.node_id(row);
        offs.clear();
        vals.clear();
        g.GetSparseFeature(&id, 1, fid, &offs, &vals);
        for (uint64_t v : vals)
          idx->Add(std::to_string(v), row, g.node_weight(row));
      }
      idx->Seal();
      indexes_[attr] = std::move(idx);
    }
    return Status::OK();
  }

  // binary feature → hash of the byte string
  auto idx = std::make_unique<HashSampleIndex>();
  std::vector<uint64_t> offs;
  std::vector<char> bytes;
  for (uint32_t row = 0; row < n; ++row) {
    NodeId id = g.node_id(row);
    offs.clear();
    bytes.clear();
    g.GetBinaryFeature(&id, 1, fid, &offs, &bytes);
    idx->Add(std::string(bytes.begin(), bytes.end()), row,
             g.node_weight(row));
  }
  idx->Seal();
  indexes_[attr] = std::move(idx);
  return Status::OK();
}

const SampleIndex* IndexManager::Find(const std::string& attr) const {
  auto it = indexes_.find(attr);
  return it == indexes_.end() ? nullptr : it->second.get();
}

std::vector<std::string> IndexManager::attrs() const {
  std::vector<std::string> out;
  for (auto& kv : indexes_) out.push_back(kv.first);
  return out;
}

Status IndexManager::EvalDnf(
    const Graph* g, const std::vector<std::vector<std::string>>& dnf,
    IndexResult* out) const {
  IndexResult acc;
  bool first_disj = true;
  for (const auto& conj : dnf) {
    // Parse all terms up front so compound predicates can be paired onto
    // a composite hash_range index: "A eq X and B cmp V" with an "A+B"
    // index becomes ONE sub-index lookup (reference
    // HashRangeSampleIndex) instead of intersecting two posting lists.
    struct PTerm {
      std::string attr, op_s, value;
      bool consumed = false;
    };
    std::vector<PTerm> terms;
    for (const auto& term : conj) {
      std::stringstream ss(term);
      PTerm t;
      ss >> t.attr >> t.op_s;
      std::getline(ss, t.value);
      if (!t.value.empty() && t.value[0] == ' ') t.value.erase(0, 1);
      terms.push_back(std::move(t));
    }
    IndexResult conj_res;
    bool first_term = true;
    auto fold = [&](IndexResult r) {
      conj_res = first_term ? std::move(r)
                            : IndexResult::Intersect(conj_res, r);
      first_term = false;
    };
    for (size_t i = 0; i < terms.size(); ++i) {
      if (terms[i].consumed || terms[i].op_s != "eq") continue;
      for (size_t j = 0; j < terms.size(); ++j) {
        if (i == j || terms[j].consumed) continue;
        const std::string& jo = terms[j].op_s;
        if (jo != "lt" && jo != "le" && jo != "gt" && jo != "ge" &&
            jo != "eq")
          continue;
        const SampleIndex* ci = Find(terms[i].attr + "+" + terms[j].attr);
        if (ci == nullptr || ci->kind() != IndexKind::kHashRange) continue;
        fold(ci->Lookup(ParseCmpOp(jo),
                        terms[i].value + "::" + terms[j].value));
        terms[i].consumed = terms[j].consumed = true;
        break;
      }
    }
    for (const auto& pt : terms) {
      if (pt.consumed) continue;
      const std::string& attr = pt.attr;
      const std::string& op_s = pt.op_s;
      const std::string& value = pt.value;
      IndexResult r;
      if (attr == "id") {
        // direct id membership against the graph — no index required
        if (g == nullptr)
          return Status::InvalidArgument("id condition needs a graph");
        std::stringstream vs(value);
        std::string one;
        std::vector<std::pair<uint32_t, float>> pairs;
        while (std::getline(vs, one, ':')) {
          uint64_t id = std::strtoull(one.c_str(), nullptr, 10);
          uint32_t row = g->NodeIndex(id);
          if (row != kInvalidIndex)
            pairs.emplace_back(row, g->node_weight(row));
        }
        // Intersect/Union assume row-sorted postings; sort keeps each
        // weight paired with its row
        std::sort(pairs.begin(), pairs.end());
        for (const auto& p : pairs) {
          r.rows.push_back(p.first);
          r.weights.push_back(p.second);
        }
      } else {
        const SampleIndex* idx = Find(attr);
        if (idx == nullptr)
          return Status::NotFound("no index for attribute " + attr);
        r = idx->Lookup(ParseCmpOp(op_s), value);
      }
      fold(std::move(r));
    }
    acc = first_disj ? std::move(conj_res) : IndexResult::Union(acc, conj_res);
    first_disj = false;
  }
  *out = std::move(acc);
  return Status::OK();
}

// ---------------------------------------------------------------------------
// Persistence (reference index_manager.h:34,54: servers load a serialized
// Index/ dir instead of rebuilding from columns at every start)
// ---------------------------------------------------------------------------
namespace {

void PutResult(const IndexResult& r, ByteWriter* w) {
  w->Put<uint64_t>(r.rows.size());
  w->PutRaw(r.rows.data(), r.rows.size() * sizeof(uint32_t));
  w->PutRaw(r.weights.data(), r.weights.size() * sizeof(float));
}

Status GetResult(ByteReader* r, IndexResult* out) {
  uint64_t n;
  if (!r->Get(&n)) return Status::Internal("index: truncated result");
  // validate against the remaining payload BEFORE resizing — a corrupt
  // count must surface as a Status, not a std::length_error abort
  if (n > r->remaining() / (sizeof(uint32_t) + sizeof(float)))
    return Status::Internal("index: corrupt result count");
  out->rows.resize(n);
  out->weights.resize(n);
  if (!r->GetRaw(out->rows.data(), n * sizeof(uint32_t)) ||
      !r->GetRaw(out->weights.data(), n * sizeof(float)))
    return Status::Internal("index: truncated result payload");
  return Status::OK();
}

}  // namespace

void HashSampleIndex::Serialize(ByteWriter* w) const {
  w->Put<uint64_t>(postings_.size());
  for (const auto& kv : postings_) {
    w->PutStr(kv.first);
    PutResult(kv.second, w);
  }
  PutResult(all_, w);
}

Status HashSampleIndex::Deserialize(ByteReader* r) {
  uint64_t n;
  if (!r->Get(&n)) return Status::Internal("hash index: truncated");
  for (uint64_t i = 0; i < n; ++i) {
    std::string term;
    if (!r->GetStr(&term)) return Status::Internal("hash index: bad term");
    ET_RETURN_IF_ERROR(GetResult(r, &postings_[term]));
  }
  return GetResult(r, &all_);
}

void RangeSampleIndex::Serialize(ByteWriter* w) const {
  w->Put<uint64_t>(entries_.size());
  for (const auto& e : entries_) {
    w->Put<double>(e.value);
    w->Put<uint32_t>(e.row);
    w->Put<float>(e.weight);
  }
}

Status RangeSampleIndex::Deserialize(ByteReader* r) {
  uint64_t n;
  if (!r->Get(&n)) return Status::Internal("range index: truncated");
  if (n > r->remaining() / (sizeof(double) + sizeof(uint32_t) +
                            sizeof(float)))
    return Status::Internal("range index: corrupt entry count");
  entries_.resize(n);
  for (uint64_t i = 0; i < n; ++i) {
    if (!r->Get(&entries_[i].value) || !r->Get(&entries_[i].row) ||
        !r->Get(&entries_[i].weight))
      return Status::Internal("range index: truncated entry");
  }
  return Status::OK();  // entries were dumped sealed (sorted)
}

void HashRangeSampleIndex::Serialize(ByteWriter* w) const {
  w->Put<uint64_t>(sub_.size());
  for (const auto& kv : sub_) {
    w->PutStr(kv.first);
    kv.second.Serialize(w);
  }
}

Status HashRangeSampleIndex::Deserialize(ByteReader* r) {
  uint64_t n;
  if (!r->Get(&n)) return Status::Internal("hash_range index: truncated");
  for (uint64_t i = 0; i < n; ++i) {
    std::string term;
    if (!r->GetStr(&term))
      return Status::Internal("hash_range index: bad term");
    ET_RETURN_IF_ERROR(sub_[term].Deserialize(r));
  }
  return Status::OK();
}

Status IndexManager::Dump(const std::string& dir) const {
  ::mkdir(dir.c_str(), 0755);  // best-effort; write below reports failure
  ByteWriter w;
  w.Put<uint32_t>(0x45544958u);  // 'ETIX'
  w.Put<uint32_t>(1u);           // version
  w.Put<uint32_t>(static_cast<uint32_t>(indexes_.size()));
  for (const auto& kv : indexes_) {
    w.PutStr(kv.first);
    w.Put<int32_t>(static_cast<int32_t>(kv.second->kind()));
    kv.second->Serialize(&w);
  }
  return WriteStringToFile(dir + "/index.bin", w.buffer().data(),
                           w.buffer().size());
}

Status IndexManager::Load(const std::string& dir) {
  std::string blob;
  ET_RETURN_IF_ERROR(ReadFileToString(dir + "/index.bin", &blob));
  ByteReader r(blob.data(), blob.size());
  uint32_t magic, ver, count;
  if (!r.Get(&magic) || magic != 0x45544958u)
    return Status::InvalidArgument(dir + ": not an index dump");
  if (!r.Get(&ver) || ver != 1)
    return Status::InvalidArgument(dir + ": unsupported index version");
  if (!r.Get(&count)) return Status::Internal("index dump truncated");
  for (uint32_t i = 0; i < count; ++i) {
    std::string name;
    int32_t kind;
    if (!r.GetStr(&name) || !r.Get(&kind))
      return Status::Internal("index dump: bad header");
    std::unique_ptr<SampleIndex> idx;
    switch (static_cast<IndexKind>(kind)) {
      case IndexKind::kHash: idx = std::make_unique<HashSampleIndex>(); break;
      case IndexKind::kRange:
        idx = std::make_unique<RangeSampleIndex>();
        break;
      case IndexKind::kHashRange:
        idx = std::make_unique<HashRangeSampleIndex>();
        break;
      default:
        return Status::InvalidArgument("index dump: unknown kind");
    }
    ET_RETURN_IF_ERROR(idx->Deserialize(&r));
    indexes_[name] = std::move(idx);
  }
  return Status::OK();
}

}  // namespace et
