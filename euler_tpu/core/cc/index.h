// Attribute index subsystem: equality/range postings with weighted sampling.
//
// Capability parity with the reference's euler/core/index/ (SURVEY.md §2.1):
// HashSampleIndex (equality, hash_sample_index.h:41), RangeSampleIndex
// (lt/le/gt/ge ranges, range_sample_index.h:36), the IndexResult union/
// intersection algebra with weighted sampling over postings
// (common_index_result.h), and the IndexManager singleton. Redesigned for
// the columnar store: postings are sorted node-row u32 arrays (not id
// vectors), built directly from the graph's feature columns rather than a
// separate on-disk Index/ directory — `IndexManager::Build` scans the
// finalized graph once per indexed attribute.
#ifndef EULER_TPU_INDEX_H_
#define EULER_TPU_INDEX_H_

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "sampling.h"

namespace et {

class Graph;

// Sorted set of matching node rows + their sampling weights.
// Union/Intersect keep rows sorted; Sample is weighted (cumulative-sum +
// binary search, like the reference's CompactWeightedCollection-backed
// results).
struct IndexResult {
  std::vector<uint32_t> rows;   // strictly increasing
  std::vector<float> weights;   // parallel to rows

  static IndexResult Union(const IndexResult& a, const IndexResult& b);
  static IndexResult Intersect(const IndexResult& a, const IndexResult& b);

  bool Contains(uint32_t row) const;
  float TotalWeight() const;
  // Weighted sample with replacement; writes `count` row indices.
  // Empty result → writes kInvalidRow.
  static constexpr uint32_t kInvalidRow = 0xffffffffu;
  void Sample(size_t count, Pcg32* rng, uint32_t* out) const;
};

enum class IndexKind : int { kHash = 0, kRange = 1, kHashRange = 2 };
enum class CmpOp : int { kEq, kNe, kLt, kLe, kGt, kGe, kIn, kHasKey };

// "eq","ne","lt","le","gt","ge","in","hk" (hasKey)
CmpOp ParseCmpOp(const std::string& s);

class ByteWriter;
class ByteReader;

// One indexed attribute over all local nodes.
class SampleIndex {
 public:
  virtual ~SampleIndex() = default;
  virtual IndexKind kind() const = 0;
  // `value` is the RHS literal; for kIn it is a ::-separated list.
  virtual IndexResult Lookup(CmpOp op, const std::string& value) const = 0;
  // binary persistence (reference index_manager.h:34,54 loads a
  // serialized Index/ dir instead of rebuilding from columns)
  virtual void Serialize(ByteWriter* w) const = 0;
  virtual Status Deserialize(ByteReader* r) = 0;
};

// Equality index: term → postings. Terms are stringified attribute values.
// ne/in supported (ne = all \ postings, computed against the full list).
class HashSampleIndex : public SampleIndex {
 public:
  IndexKind kind() const override { return IndexKind::kHash; }
  IndexResult Lookup(CmpOp op, const std::string& value) const override;
  void Serialize(ByteWriter* w) const override;
  Status Deserialize(ByteReader* r) override;

  void Add(const std::string& term, uint32_t row, float weight);
  void Seal();  // sort postings, build the all-rows list

 private:
  std::unordered_map<std::string, IndexResult> postings_;
  IndexResult all_;
};

// Ordered index over a numeric attribute: supports the full cmp set via
// binary search on the sorted (value, row) array.
class RangeSampleIndex : public SampleIndex {
 public:
  IndexKind kind() const override { return IndexKind::kRange; }
  IndexResult Lookup(CmpOp op, const std::string& value) const override;
  void Serialize(ByteWriter* w) const override;
  Status Deserialize(ByteReader* r) override;

  void Add(double value, uint32_t row, float weight);
  void Seal();

 private:
  struct Entry {
    double value;
    uint32_t row;
    float weight;
  };
  std::vector<Entry> entries_;  // sorted by (value, row) after Seal
  IndexResult RangeToResult(size_t begin, size_t end) const;
};

// Composite equality+range index (reference HashRangeSampleIndex,
// hash_range_sample_index.h): one RangeSampleIndex per hash term, so a
// compound predicate "A eq X and B < v" is served by ONE O(log) lookup
// on the per-term sub-index instead of intersecting two posting lists.
// Lookup value format mirrors the reference: "<hash term>::<range rhs>",
// with `op` applying to the range part.
class HashRangeSampleIndex : public SampleIndex {
 public:
  IndexKind kind() const override { return IndexKind::kHashRange; }
  IndexResult Lookup(CmpOp op, const std::string& value) const override;
  void Serialize(ByteWriter* w) const override;
  Status Deserialize(ByteReader* r) override;

  void Add(const std::string& term, double value, uint32_t row, float weight);
  void Seal();

 private:
  std::map<std::string, RangeSampleIndex> sub_;
};

// Owns all indexes for one graph. Attribute sources:
//   "node_type"          — the node's type id (hash or range)
//   dense feature name   — scalar value at dim 0 (range) or stringified (hash)
//   sparse feature name  — every u64 token becomes a hash term
//   binary feature name  — the byte string as one hash term
// Parity: reference IndexManager (index_manager.h:34) + the data-prep
// json2partindex pipeline, collapsed into post-load Build calls.
class IndexManager {
 public:
  // spec: comma-separated "attr:hash_index" / "attr:range_index" /
  // "attrA+attrB:hash_range_index" items, e.g.
  // "price:range_index,att+price:hash_range_index" (reference index_info
  // format, parser/compiler_test.cc:169, incl. the composite). The
  // special item "load:<dir>" loads a previously dumped index directory
  // instead of rebuilding from columns.
  Status BuildFromSpec(const Graph& g, const std::string& spec);
  Status Build(const Graph& g, const std::string& attr, IndexKind kind);

  // Persist/restore all built indexes (reference IndexManager loads a
  // serialized Index/ dir, index_manager.h:34,54).
  Status Dump(const std::string& dir) const;
  Status Load(const std::string& dir);

  const SampleIndex* Find(const std::string& attr) const;
  bool has(const std::string& attr) const { return Find(attr) != nullptr; }
  std::vector<std::string> attrs() const;

  // Evaluate one DNF condition (dnf[i] = conjunction of "attr op value"
  // terms) to a posting set. The special attribute "id" matches node ids
  // directly against the graph (no index needed); other unknown attributes
  // → error. `g` may be null if no term uses "id".
  Status EvalDnf(const Graph* g,
                 const std::vector<std::vector<std::string>>& dnf,
                 IndexResult* out) const;

 private:
  std::map<std::string, std::unique_ptr<SampleIndex>> indexes_;
};

}  // namespace et

#endif  // EULER_TPU_INDEX_H_
