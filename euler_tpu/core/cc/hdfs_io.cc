// HDFS file IO via a dlopen'd libhdfs — no compile-time Hadoop
// dependency.
//
// Capability parity with the reference's euler/common/hdfs_file_io.cc:43-71
// (LibHDFS struct of dlsym'd function pointers; hdfs:// URLs accepted
// anywhere a path is). The library is resolved at first use from
// $EULER_TPU_LIBHDFS, then libhdfs.so / libhdfs.so.0.0.0; absence yields a
// clear IOError instead of a link failure.
#include "hdfs_io.h"

#include <dlfcn.h>

#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>

namespace et {
namespace {

// minimal slice of hadoop's hdfs.h ABI
using hdfsFS = void*;
using hdfsFile = void*;
struct hdfsFileInfo {
  int mKind;
  char* mName;
  long mLastMod;
  long long mSize;
  short mReplication;
  long long mBlockSize;
  char* mOwner;
  char* mGroup;
  short mPermissions;
  long mLastAccess;
};

constexpr int kORdonly = 0;  // O_RDONLY
constexpr int kOWronly = 1;  // O_WRONLY

struct LibHDFS {
  void* handle = nullptr;
  hdfsFS (*Connect)(const char* host, uint16_t port) = nullptr;
  int (*Disconnect)(hdfsFS) = nullptr;
  hdfsFile (*OpenFile)(hdfsFS, const char* path, int flags, int bufferSize,
                       short replication, int32_t blocksize) = nullptr;
  int (*CloseFile)(hdfsFS, hdfsFile) = nullptr;
  int32_t (*Read)(hdfsFS, hdfsFile, void* buffer, int32_t length) = nullptr;
  int32_t (*Write)(hdfsFS, hdfsFile, const void* buffer,
                   int32_t length) = nullptr;
  hdfsFileInfo* (*GetPathInfo)(hdfsFS, const char* path) = nullptr;
  void (*FreeFileInfo)(hdfsFileInfo*, int numEntries) = nullptr;

  Status Load() {
    if (handle != nullptr) return Status::OK();
    const char* override_path = std::getenv("EULER_TPU_LIBHDFS");
    if (override_path != nullptr && override_path[0] != '\0') {
      // an explicit override must not silently fall back to a system
      // libhdfs — a typo'd path would connect to a different library
      // than the operator asked for
      handle = ::dlopen(override_path, RTLD_NOW | RTLD_GLOBAL);
      if (handle == nullptr)
        return Status::IOError(
            std::string("libhdfs not found at EULER_TPU_LIBHDFS=") +
            override_path);
    } else {
      for (const char* c : {"libhdfs.so", "libhdfs.so.0.0.0"}) {
        handle = ::dlopen(c, RTLD_NOW | RTLD_GLOBAL);
        if (handle != nullptr) break;
      }
    }
    if (handle == nullptr)
      return Status::IOError(
          "libhdfs not found (set EULER_TPU_LIBHDFS or install Hadoop "
          "native libs)");
#define ET_HDFS_SYM(field, name)                                     \
  do {                                                               \
    *reinterpret_cast<void**>(&field) = ::dlsym(handle, name);       \
    if (field == nullptr)                                            \
      return Status::IOError("libhdfs missing symbol " name);        \
  } while (0)
    ET_HDFS_SYM(Connect, "hdfsConnect");
    ET_HDFS_SYM(Disconnect, "hdfsDisconnect");
    ET_HDFS_SYM(OpenFile, "hdfsOpenFile");
    ET_HDFS_SYM(CloseFile, "hdfsCloseFile");
    ET_HDFS_SYM(Read, "hdfsRead");
    ET_HDFS_SYM(Write, "hdfsWrite");
    ET_HDFS_SYM(GetPathInfo, "hdfsGetPathInfo");
    ET_HDFS_SYM(FreeFileInfo, "hdfsFreeFileInfo");
#undef ET_HDFS_SYM
    return Status::OK();
  }
};

LibHDFS& Lib() {
  static LibHDFS* lib = new LibHDFS();
  return *lib;
}

std::mutex g_fs_mu;
std::map<std::pair<std::string, int>, hdfsFS>& FsCache() {
  static auto* m = new std::map<std::pair<std::string, int>, hdfsFS>();
  return *m;
}

// hdfs://host:port/path | hdfs:///path (default fs) → (host, port, path)
Status ParseUrl(const std::string& url, std::string* host, int* port,
                std::string* path) {
  if (url.rfind("hdfs://", 0) != 0)
    return Status::InvalidArgument("not an hdfs:// url: " + url);
  std::string rest = url.substr(7);
  auto slash = rest.find('/');
  if (slash == std::string::npos)
    return Status::InvalidArgument("hdfs url has no path: " + url);
  std::string authority = rest.substr(0, slash);
  *path = rest.substr(slash);
  *host = "default";
  *port = 0;
  if (!authority.empty()) {
    auto colon = authority.rfind(':');
    if (colon != std::string::npos) {
      *host = authority.substr(0, colon);
      *port = std::atoi(authority.substr(colon + 1).c_str());
    } else {
      *host = authority;
    }
  }
  return Status::OK();
}

Status GetFs(const std::string& host, int port, hdfsFS* fs) {
  std::lock_guard<std::mutex> lk(g_fs_mu);
  ET_RETURN_IF_ERROR(Lib().Load());
  auto key = std::make_pair(host, port);
  auto it = FsCache().find(key);
  if (it != FsCache().end()) {
    *fs = it->second;
    return Status::OK();
  }
  hdfsFS f = Lib().Connect(host.c_str(), static_cast<uint16_t>(port));
  if (f == nullptr)
    return Status::IOError("hdfsConnect failed for " + host + ":" +
                           std::to_string(port));
  FsCache()[key] = f;
  *fs = f;
  return Status::OK();
}

}  // namespace

bool IsHdfsPath(const std::string& path) {
  return path.rfind("hdfs://", 0) == 0;
}

Status HdfsReadFile(const std::string& url, std::string* out) {
  std::string host, path;
  int port;
  ET_RETURN_IF_ERROR(ParseUrl(url, &host, &port, &path));
  hdfsFS fs;
  ET_RETURN_IF_ERROR(GetFs(host, port, &fs));
  hdfsFileInfo* info = Lib().GetPathInfo(fs, path.c_str());
  if (info == nullptr) return Status::IOError("hdfs path not found: " + url);
  long long size = info->mSize;
  Lib().FreeFileInfo(info, 1);
  hdfsFile f = Lib().OpenFile(fs, path.c_str(), kORdonly, 0, 0, 0);
  if (f == nullptr) return Status::IOError("cannot open " + url);
  out->resize(static_cast<size_t>(size));
  long long got = 0;
  while (got < size) {
    int32_t chunk = static_cast<int32_t>(
        std::min<long long>(size - got, 64 << 20));
    int32_t r = Lib().Read(fs, f, &(*out)[got], chunk);
    if (r <= 0) break;
    got += r;
  }
  Lib().CloseFile(fs, f);
  if (got != size) return Status::IOError("short hdfs read on " + url);
  return Status::OK();
}

Status HdfsWriteFile(const std::string& url, const char* data, size_t size) {
  std::string host, path;
  int port;
  ET_RETURN_IF_ERROR(ParseUrl(url, &host, &port, &path));
  hdfsFS fs;
  ET_RETURN_IF_ERROR(GetFs(host, port, &fs));
  hdfsFile f = Lib().OpenFile(fs, path.c_str(), kOWronly, 0, 0, 0);
  if (f == nullptr) return Status::IOError("cannot open " + url + " for write");
  size_t put = 0;
  while (put < size) {
    int32_t chunk = static_cast<int32_t>(
        std::min<size_t>(size - put, 64 << 20));
    int32_t w = Lib().Write(fs, f, data + put, chunk);
    if (w <= 0) break;
    put += static_cast<size_t>(w);
  }
  int rc = Lib().CloseFile(fs, f);
  if (put != size || rc != 0)
    return Status::IOError("short hdfs write on " + url);
  return Status::OK();
}

}  // namespace et
