// Col<T> — an owning-or-external column view, the storage seam under the
// SoA graph store.
//
// The graph's big arrays (CSR adjacency, feature matrices, alias tables)
// historically were std::vectors: always heap-resident, so a shard could
// never serve a graph bigger than RAM. Col<T> keeps the exact vector
// surface the build path uses (resize/assign/push_back/operator[]) while
// adding ONE new mode: AttachExternal(ptr, n) points the column at
// read-only memory owned by someone else — in practice an mmap'd
// columnar store file (store.h) — and frees the heap copy. Reads are
// identical in both modes (ptr_/n_ are kept in sync by every mutator),
// so the sampling/feature accessors run byte-for-byte the same whether
// the bytes live on the heap or in the page cache.
//
// Contract:
//   * const access (operator[], data(), begin()/end(), back()) works in
//     both modes and is branch-free — one pointer indirection, same as
//     std::vector.
//   * mutators (resize/assign/push_back/clear/non-const operator[]/
//     non-const data()) are OWNING-mode only; calling one on an attached
//     column silently detaches it into an empty owning column first
//     (mutating an mmap'd base is a logic error the build path never
//     performs; Finalize always starts from fresh owning columns).
//   * copying an owning column copies the heap vector; copying an
//     attached column copies the (ptr, n) view — both keep reads valid
//     as long as the backing store outlives the copy (Graph holds a
//     shared_ptr to its ColumnarStore for exactly this reason).
#ifndef EULER_TPU_COL_H_
#define EULER_TPU_COL_H_

#include <cstddef>
#include <vector>

namespace et {

template <typename T>
class Col {
 public:
  using value_type = T;

  Col() = default;
  Col(const Col& o) { *this = o; }
  Col(Col&& o) noexcept { *this = static_cast<Col&&>(o); }
  Col& operator=(const Col& o) {
    if (this == &o) return *this;
    if (o.external_) {
      own_.clear();
      own_.shrink_to_fit();
      ptr_ = o.ptr_;
      n_ = o.n_;
      external_ = true;
    } else {
      own_ = o.own_;
      Refresh();
    }
    return *this;
  }
  Col& operator=(Col&& o) noexcept {
    if (this == &o) return *this;
    if (o.external_) {
      own_.clear();
      ptr_ = o.ptr_;
      n_ = o.n_;
      external_ = true;
    } else {
      own_ = std::move(o.own_);
      Refresh();
    }
    return *this;
  }

  // ---- reads (both modes) ----
  const T& operator[](size_t i) const { return ptr_[i]; }
  const T* data() const { return ptr_; }
  const T* begin() const { return ptr_; }
  const T* end() const { return ptr_ + n_; }
  size_t size() const { return n_; }
  bool empty() const { return n_ == 0; }
  const T& back() const { return ptr_[n_ - 1]; }
  bool external() const { return external_; }

  // ---- owning-mode mutators (vector-compatible surface) ----
  T& operator[](size_t i) { return Own()[i]; }
  T* data() { return Own().data(); }
  T* begin() { return Own().data(); }
  T* end() { T* p = Own().data(); return p + own_.size(); }
  void resize(size_t n) { Own().resize(n); Refresh(); }
  void resize(size_t n, const T& v) { Own().resize(n, v); Refresh(); }
  void assign(size_t n, const T& v) { Own().assign(n, v); Refresh(); }
  template <typename It>
  void assign(It first, It last) { Own().assign(first, last); Refresh(); }
  void push_back(const T& v) { Own().push_back(v); Refresh(); }
  void reserve(size_t n) { Own().reserve(n); Refresh(); }
  void clear() { Own().clear(); Refresh(); }
  void shrink_to_fit() { Own().shrink_to_fit(); Refresh(); }
  // Move a prepared vector in without copying.
  void adopt(std::vector<T>&& v) { own_ = std::move(v); Refresh(); }

  // ---- external mode ----
  // Point the column at `n` elements of externally owned, read-only
  // memory (an mmap'd store column) and free the heap copy. The backing
  // memory must outlive every read.
  void AttachExternal(const T* p, size_t n) {
    own_.clear();
    own_.shrink_to_fit();
    ptr_ = p;
    n_ = n;
    external_ = true;
  }

 private:
  std::vector<T>& Own() {
    if (external_) {  // mutating an attached column detaches it (empty)
      ptr_ = nullptr;
      n_ = 0;
      external_ = false;
    }
    return own_;
  }
  void Refresh() {
    ptr_ = own_.data();
    n_ = own_.size();
    external_ = false;
  }

  std::vector<T> own_;
  const T* ptr_ = nullptr;
  size_t n_ = 0;
  bool external_ = false;
};

}  // namespace et

#endif  // EULER_TPU_COL_H_
