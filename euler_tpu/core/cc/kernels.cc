// Local graph op kernels — the query "ISA" executed by the DAG executor.
//
// Capability parity with euler/core/kernels/ (SURVEY.md §2.1 "Graph op
// kernels"): root sampling (API_SAMPLE_NODE with index-conditioned DNF,
// sample_node_op.cc:66-96; API_SAMPLE_EDGE; API_SAMPLE_N_WITH_TYPES),
// traversal (API_SAMPLE_NB, API_GET_NB_NODE, API_GET_RNB_NODE, API_GET_TOPK,
// get_nb_filter), features (API_GET_P / API_GET_EDGE_P with UDF hook,
// get_feature_op.cc), node filtering (API_GET_NODE), layerwise
// (API_SAMPLE_L), aliasing (AS), post-process (order_by/limit,
// post_process_op.cc:325), and ID_UNIQUE dedup.
//
// Tensor conventions (all batch, row-aligned with the input id tensor):
//   ragged quad  = idx i32 [n,2] (start,end) | ids u64 | w f32 | t i32
//   feature pair = idx i32 [n,2] | values (f32 dense / u64 sparse / u8 bin)
// Fixed-count sampling still emits idx so downstream merge/gather logic is
// shape-agnostic.
#include <algorithm>
#include <cstdlib>
#include <memory>
#include <mutex>
#include <numeric>
#include <sstream>
#include <unordered_map>

#include "dag.h"
#include "graph.h"
#include "index.h"
#include "kernels_common.h"
#include "ops.h"
#include "tensor.h"
#include "udf.h"

namespace et {
namespace {

// ---------------------------------------------------------------------------
// helpers
// ---------------------------------------------------------------------------
std::vector<std::string> SplitStr(const std::string& s, char sep) {
  std::vector<std::string> out;
  std::stringstream ss(s);
  std::string item;
  while (std::getline(ss, item, sep)) out.push_back(item);
  return out;
}

// "0:2:5" → {0,2,5}; "*", "" or any negative entry → empty (= all types,
// matching sampleN's type=-1 convention).
std::vector<int32_t> ParseEdgeTypes(const std::string& s) {
  std::vector<int32_t> out;
  if (s.empty() || s == "*") return out;
  for (auto& t : SplitStr(s, ':')) {
    int32_t v = std::atoi(t.c_str());
    if (v < 0) return {};
    out.push_back(v);
  }
  return out;
}

// dnf evaluation without a configured index still supports the pure-id
// branch (hasId) — an empty manager resolves ids against the graph and
// returns NotFound for real attribute conditions.
const IndexManager& IndexOrEmpty(const QueryEnv& env) {
  static IndexManager* empty = new IndexManager();
  return env.index != nullptr ? *env.index : *empty;
}

// Resolve a feature name (or "f<id>") to (kind, fid, dim) from graph meta.
Status ResolveFeature(const Graph& g, const std::string& name, bool edge,
                      FeatureKind* kind, int* fid, int64_t* dim) {
  const auto& feats =
      edge ? g.meta().edge_features : g.meta().node_features;
  for (size_t i = 0; i < feats.size(); ++i) {
    if (feats[i].name == name) {
      *kind = feats[i].kind;
      *fid = static_cast<int>(i);
      *dim = feats[i].dim;
      return Status::OK();
    }
  }
  // "sparse_f1"-style prefixed or bare integer id: kind from prefix,
  // default dense.
  std::string base = name;
  FeatureKind k = FeatureKind::kDense;
  if (name.rfind("sparse_", 0) == 0) {
    k = FeatureKind::kSparse;
    base = name.substr(7);
  } else if (name.rfind("binary_", 0) == 0) {
    k = FeatureKind::kBinary;
    base = name.substr(7);
  } else if (name.rfind("dense_", 0) == 0) {
    base = name.substr(6);
  }
  if (!base.empty() && base[0] == 'f') base = base.substr(1);
  char* end = nullptr;
  long v = std::strtol(base.c_str(), &end, 10);
  if (end != base.c_str() && *end == '\0' && v >= 0 &&
      static_cast<size_t>(v) < feats.size()) {
    *fid = static_cast<int>(v);
    *kind = feats[v].kind;
    (void)k;
    *dim = feats[v].dim;
    return Status::OK();
  }
  return Status::NotFound("unknown feature: " + name);
}

Tensor MakeIdx(const std::vector<uint64_t>& offsets) {
  size_t n = offsets.size() - 1;
  Tensor idx(DType::kI32, {static_cast<int64_t>(n), 2});
  int32_t* p = idx.Flat<int32_t>();
  for (size_t i = 0; i < n; ++i) {
    p[2 * i] = static_cast<int32_t>(offsets[i]);
    p[2 * i + 1] = static_cast<int32_t>(offsets[i + 1]);
  }
  return idx;
}

// ---------------------------------------------------------------------------
// API_SAMPLE_NODE — attrs: [count, node_type]; optional input 0 overrides
// count. dnf present → index-conditioned sampling (reference
// sample_node_op.cc:66-96).
// out :0 = ids u64 [count]
// ---------------------------------------------------------------------------
class SampleNodeOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    int64_t count = node.attrs.size() > 0 ? std::atoll(node.attrs[0].c_str()) : 0;
    int type = node.attrs.size() > 1 ? std::atoi(node.attrs[1].c_str()) : -1;
    if (!node.inputs.empty()) {
      Tensor t;
      if (ctx->Get(node.inputs[0], &t) && t.NumElements() > 0)
        count = t.AsI64(0);
    }
    if (count < 0) {
      done(Status::InvalidArgument("sampleN count must be >= 0"));
      return;
    }
    Pcg32 rng = NodeRng(node, env);
    Tensor out(DType::kU64, {count});
    if (!node.dnf.empty()) {
      IndexResult res;
      ET_K_RETURN_IF_ERROR(
          IndexOrEmpty(env).EvalDnf(env.graph, node.dnf, &res));
      if (type >= 0) {
        // intersect with type postings via direct filter
        IndexResult typed;
        for (size_t i = 0; i < res.rows.size(); ++i) {
          if (env.graph->node_type(res.rows[i]) == type) {
            typed.rows.push_back(res.rows[i]);
            typed.weights.push_back(res.weights[i]);
          }
        }
        res = std::move(typed);
      }
      std::vector<uint32_t> rows(count);
      res.Sample(count, &rng, rows.data());
      uint64_t* ids = out.Flat<uint64_t>();
      for (int64_t i = 0; i < count; ++i)
        ids[i] = rows[i] == IndexResult::kInvalidRow
                     ? 0
                     : env.graph->node_id(rows[i]);
    } else {
      env.graph->SampleNode(type, count, &rng, out.Flat<uint64_t>());
    }
    ctx->Put(node.OutName(0), std::move(out));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("API_SAMPLE_NODE", SampleNodeOp);

// API_SAMPLE_N_WITH_TYPES — input 0: i32 types per row → :0 ids u64.
class SampleNWithTypesOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor types;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &types));
    int64_t n = types.NumElements();
    Pcg32 rng = NodeRng(node, env);
    Tensor out(DType::kU64, {n});
    env.graph->SampleNodeWithTypes(types.Flat<int32_t>(), n, &rng,
                                   out.Flat<uint64_t>());
    ctx->Put(node.OutName(0), std::move(out));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("API_SAMPLE_N_WITH_TYPES", SampleNWithTypesOp);

// API_SAMPLE_EDGE — attrs [count, edge_type] → :0 src, :1 dst, :2 type.
class SampleEdgeOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    int64_t count = node.attrs.size() > 0 ? std::atoll(node.attrs[0].c_str()) : 0;
    int type = node.attrs.size() > 1 ? std::atoi(node.attrs[1].c_str()) : -1;
    if (!node.inputs.empty()) {
      Tensor t;
      if (ctx->Get(node.inputs[0], &t) && t.NumElements() > 0)
        count = t.AsI64(0);
    }
    if (count < 0) {
      done(Status::InvalidArgument("sampleE count must be >= 0"));
      return;
    }
    Pcg32 rng = NodeRng(node, env);
    Tensor src(DType::kU64, {count}), dst(DType::kU64, {count}),
        et_(DType::kI32, {count});
    env.graph->SampleEdge(type, count, &rng, src.Flat<uint64_t>(),
                          dst.Flat<uint64_t>(), et_.Flat<int32_t>());
    ctx->Put(node.OutName(0), std::move(src));
    ctx->Put(node.OutName(1), std::move(dst));
    ctx->Put(node.OutName(2), std::move(et_));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("API_SAMPLE_EDGE", SampleEdgeOp);

// API_GET_NODE — input 0: candidate ids; keeps ids that exist locally and
// match the dnf (index-backed). Missing/filtered → dropped. Outputs
// :0 surviving ids, :1 i32 original positions.
class GetNodeOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor ids_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &ids_t));
    const uint64_t* ids = ids_t.Flat<uint64_t>();
    int64_t n = ids_t.NumElements();
    IndexResult res;
    bool has_dnf = !node.dnf.empty();
    if (has_dnf) {
      ET_K_RETURN_IF_ERROR(
          IndexOrEmpty(env).EvalDnf(env.graph, node.dnf, &res));
    }
    std::vector<uint64_t> keep;
    std::vector<int32_t> pos;
    for (int64_t i = 0; i < n; ++i) {
      uint32_t row = env.graph->NodeIndex(ids[i]);
      if (row == kInvalidIndex) continue;
      if (has_dnf && !res.Contains(row)) continue;
      keep.push_back(ids[i]);
      pos.push_back(static_cast<int32_t>(i));
    }
    ctx->Put(node.OutName(0), Tensor::FromVector(keep));
    ctx->Put(node.OutName(1), Tensor::FromVector(pos));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("API_GET_NODE", GetNodeOp);

// API_SAMPLE_NB — input 0: ids; attrs [edge_types, count, default_id]
// → ragged quad (fixed row length = count).
class SampleNeighborOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor ids_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &ids_t));
    auto ets = ParseEdgeTypes(node.attrs.size() > 0 ? node.attrs[0] : "");
    int64_t count = node.attrs.size() > 1 ? std::atoll(node.attrs[1].c_str()) : 1;
    if (count < 0) {
      done(Status::InvalidArgument("sampleNB count must be >= 0"));
      return;
    }
    uint64_t def = node.attrs.size() > 2 ? std::strtoull(node.attrs[2].c_str(), nullptr, 10) : 0;
    const uint64_t* ids = ids_t.Flat<uint64_t>();
    int64_t n = ids_t.NumElements();
    Pcg32 rng = NodeRng(node, env);
    Tensor idx(DType::kI32, {n, 2});
    Tensor nb(DType::kU64, {n * count});
    Tensor w(DType::kF32, {n * count});
    Tensor t(DType::kI32, {n * count});
    int32_t* pidx = idx.Flat<int32_t>();
    for (int64_t i = 0; i < n; ++i) {
      env.graph->SampleNeighbor(ids[i], ets.empty() ? nullptr : ets.data(),
                                ets.size(), count, def, &rng,
                                nb.Flat<uint64_t>() + i * count,
                                w.Flat<float>() + i * count,
                                t.Flat<int32_t>() + i * count);
      pidx[2 * i] = static_cast<int32_t>(i * count);
      pidx[2 * i + 1] = static_cast<int32_t>((i + 1) * count);
    }
    ctx->Put(node.OutName(0), std::move(idx));
    ctx->Put(node.OutName(1), std::move(nb));
    ctx->Put(node.OutName(2), std::move(w));
    ctx->Put(node.OutName(3), std::move(t));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("API_SAMPLE_NB", SampleNeighborOp);

// Shared impl for full/in/topk neighbor ops → ragged quad.
void FullNeighborImpl(const NodeDef& node, const QueryEnv& env,
                      OpKernelContext* ctx, bool in_edges, bool sorted,
                      int64_t topk, std::function<void(Status)> done) {
  Tensor ids_t;
  Status s = GetInput(ctx, node, 0, &ids_t);
  if (!s.ok()) {
    done(s);
    return;
  }
  auto ets = ParseEdgeTypes(node.attrs.size() > 0 ? node.attrs[0] : "");
  const uint64_t* ids = ids_t.Flat<uint64_t>();
  int64_t n = ids_t.NumElements();
  std::vector<uint64_t> offsets{0};
  std::vector<NodeId> nb;
  std::vector<float> w;
  std::vector<int32_t> t;
  for (int64_t i = 0; i < n; ++i) {
    if (topk > 0) {
      size_t before = nb.size();
      nb.resize(before + topk);
      w.resize(before + topk);
      t.resize(before + topk);
      env.graph->GetTopKNeighbor(ids[i], ets.empty() ? nullptr : ets.data(),
                                 ets.size(), topk, 0, nb.data() + before,
                                 w.data() + before, t.data() + before);
    } else if (in_edges) {
      env.graph->GetFullInNeighbor(ids[i], ets.empty() ? nullptr : ets.data(),
                                   ets.size(), &nb, &w, &t);
    } else {
      env.graph->GetFullNeighbor(ids[i], ets.empty() ? nullptr : ets.data(),
                                 ets.size(), &nb, &w, &t, sorted);
    }
    offsets.push_back(nb.size());
  }
  ctx->Put(node.OutName(0), MakeIdx(offsets));
  ctx->Put(node.OutName(1), Tensor::FromVector(nb));
  ctx->Put(node.OutName(2), Tensor::FromVector(w));
  ctx->Put(node.OutName(3), Tensor::FromVector(t));
  done(Status::OK());
}

class GetNbNodeOp : public OpKernel {
 public:
  void Compute(const NodeDef& n, const QueryEnv& e, OpKernelContext* c,
               std::function<void(Status)> d) override {
    FullNeighborImpl(n, e, c, false, false, 0, std::move(d));
  }
};
ET_REGISTER_KERNEL("API_GET_NB_NODE", GetNbNodeOp);

class GetSortedNbNodeOp : public OpKernel {
 public:
  void Compute(const NodeDef& n, const QueryEnv& e, OpKernelContext* c,
               std::function<void(Status)> d) override {
    FullNeighborImpl(n, e, c, false, true, 0, std::move(d));
  }
};
ET_REGISTER_KERNEL("API_GET_SORTED_NB_NODE", GetSortedNbNodeOp);

class GetRNbNodeOp : public OpKernel {
 public:
  void Compute(const NodeDef& n, const QueryEnv& e, OpKernelContext* c,
               std::function<void(Status)> d) override {
    FullNeighborImpl(n, e, c, true, false, 0, std::move(d));
  }
};
ET_REGISTER_KERNEL("API_GET_RNB_NODE", GetRNbNodeOp);

class GetTopKNbOp : public OpKernel {
 public:
  void Compute(const NodeDef& n, const QueryEnv& e, OpKernelContext* c,
               std::function<void(Status)> d) override {
    int64_t k = n.attrs.size() > 1 ? std::atoll(n.attrs[1].c_str()) : 1;
    if (k < 0) {
      d(Status::InvalidArgument("getTopKNB k must be >= 0"));
      return;
    }
    FullNeighborImpl(n, e, c, false, false, k, std::move(d));
  }
};
ET_REGISTER_KERNEL("API_GET_TOPK_NB", GetTopKNbOp);

// API_GET_NB_FILTER — ragged quad filtered by a dnf over the *neighbor*
// nodes (reference get_nb_filter_op.cc:127). Inputs: idx, ids, w, t.
class GetNbFilterOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor idx_t, ids_t, w_t, t_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &idx_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 1, &ids_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 2, &w_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 3, &t_t));
    IndexResult res;
    if (!node.dnf.empty()) {
      if (env.index == nullptr) {
        done(Status::Internal("nb filter requires an index"));
        return;
      }
      ET_K_RETURN_IF_ERROR(env.index->EvalDnf(env.graph, node.dnf, &res));
    }
    int64_t n = idx_t.dim(0);
    const int32_t* pidx = idx_t.Flat<int32_t>();
    const uint64_t* ids = ids_t.Flat<uint64_t>();
    const float* w = w_t.Flat<float>();
    const int32_t* t = t_t.Flat<int32_t>();
    std::vector<uint64_t> offsets{0};
    std::vector<uint64_t> out_ids;
    std::vector<float> out_w;
    std::vector<int32_t> out_t;
    for (int64_t i = 0; i < n; ++i) {
      for (int32_t j = pidx[2 * i]; j < pidx[2 * i + 1]; ++j) {
        uint32_t row = env.graph->NodeIndex(ids[j]);
        if (row == kInvalidIndex) continue;
        if (!node.dnf.empty() && !res.Contains(row)) continue;
        out_ids.push_back(ids[j]);
        out_w.push_back(w[j]);
        out_t.push_back(t[j]);
      }
      offsets.push_back(out_ids.size());
    }
    ctx->Put(node.OutName(0), MakeIdx(offsets));
    ctx->Put(node.OutName(1), Tensor::FromVector(out_ids));
    ctx->Put(node.OutName(2), Tensor::FromVector(out_w));
    ctx->Put(node.OutName(3), Tensor::FromVector(out_t));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("API_GET_NB_FILTER", GetNbFilterOp);

// API_GET_NB_EDGE — input 0: node ids; attr0: edge_types. Returns the
// *edges* to each root's out-neighbors (reference
// get_neighbor_edge_op.cc, GQL `outE` at gremlin.l:21), for
// edge-feature chains: outputs feed API_GET_EDGE_P as an edge triple.
// Conditions (dnf) are evaluated inline per edge — supported terms:
// weight <cmp> v, edge_type <cmp> t, id in a:b:c (neighbor membership).
// post_process: "order_by id|weight [asc|desc]" and "limit k", applied
// per root row (reference applies them inside the op too).
// out :0 idx i32 [n,2] | :1 src u64 | :2 dst u64 | :3 type i32 | :4 w f32
class GetNbEdgeOp : public OpKernel {
 public:
  static bool Cmp(double a, const std::string& op, double b) {
    if (op == "eq") return a == b;
    if (op == "ne") return a != b;
    if (op == "lt") return a < b;
    if (op == "le") return a <= b;
    if (op == "gt") return a > b;
    if (op == "ge") return a >= b;
    return false;
  }

  // pre-parsed dnf term: field ∈ {weight, edge_type, id}; id/edge_type
  // "in"/"eq"/"ne" use the id set, numeric cmps use num.
  struct Term {
    enum Field { kWeight, kEdgeType, kId } field;
    std::string op;
    double num = 0;
    std::vector<uint64_t> ids;
  };

  static Status ParseDnf(const std::vector<std::vector<std::string>>& dnf,
                         std::vector<std::vector<Term>>* out) {
    for (const auto& conj : dnf) {
      std::vector<Term> terms;
      for (const auto& term : conj) {
        std::stringstream ss(term);
        std::string attr, op_s, value;
        ss >> attr >> op_s;
        std::getline(ss, value);
        if (!value.empty() && value[0] == ' ') value.erase(0, 1);
        Term t;
        t.op = op_s;
        bool cmp_op = op_s == "eq" || op_s == "ne" || op_s == "lt" ||
                      op_s == "le" || op_s == "gt" || op_s == "ge";
        if (attr == "weight") {
          if (!cmp_op)
            return Status::InvalidArgument(
                "outE weight condition supports eq/ne/lt/le/gt/ge, got: " +
                op_s);
          t.field = Term::kWeight;
          t.num = std::atof(value.c_str());
        } else if (attr == "edge_type" || attr == "id") {
          if (!cmp_op && op_s != "in")
            return Status::InvalidArgument(
                "outE " + attr + " condition got unknown op: " + op_s);
          t.field = attr == "id" ? Term::kId : Term::kEdgeType;
          t.num = std::atof(value.c_str());
          for (auto& v : SplitStr(value, ':'))
            t.ids.push_back(std::strtoull(v.c_str(), nullptr, 10));
          if (attr == "id" && op_s != "in" && op_s != "eq" && op_s != "ne")
            return Status::InvalidArgument(
                "outE id condition supports in/eq/ne, got: " + op_s);
        } else {
          return Status::InvalidArgument(
              "outE condition supports weight/edge_type/id, got: " + attr);
        }
        terms.push_back(std::move(t));
      }
      out->push_back(std::move(terms));
    }
    return Status::OK();
  }

  static bool EdgeMatch(const std::vector<std::vector<Term>>& dnf,
                        uint64_t dst, float w, int32_t ty) {
    if (dnf.empty()) return true;
    for (const auto& conj : dnf) {
      bool all = true;
      for (const auto& t : conj) {
        bool ok;
        if (t.field == Term::kWeight) {
          ok = Cmp(w, t.op, t.num);
        } else if (t.field == Term::kEdgeType) {
          if (t.op == "in") {
            ok = std::find(t.ids.begin(), t.ids.end(),
                           static_cast<uint64_t>(ty)) != t.ids.end();
          } else {
            ok = Cmp(ty, t.op, t.num);
          }
        } else {  // kId: membership in the listed neighbor ids
          bool member = std::find(t.ids.begin(), t.ids.end(), dst) !=
                        t.ids.end();
          ok = t.op == "ne" ? !member : member;
        }
        if (!ok) { all = false; break; }
      }
      if (all) return true;
    }
    return false;
  }

  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor ids_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &ids_t));
    auto ets = ParseEdgeTypes(node.attrs.size() > 0 ? node.attrs[0] : "");
    const uint64_t* ids = ids_t.Flat<uint64_t>();
    int64_t n = ids_t.NumElements();
    std::vector<uint64_t> offsets{0};
    std::vector<uint64_t> src, dst;
    std::vector<float> w;
    std::vector<int32_t> t;
    std::vector<NodeId> nb_row;
    std::vector<float> w_row;
    std::vector<int32_t> t_row;
    RowPostProcess pp = RowPostProcess::Parse(node.post_process);
    if (!pp.order_field.empty() && pp.order_field != "id" &&
        pp.order_field != "weight") {
      done(Status::InvalidArgument("outE order_by supports id|weight, got: " +
                                   pp.order_field));
      return;
    }
    std::vector<std::vector<Term>> dnf;
    ET_K_RETURN_IF_ERROR(ParseDnf(node.dnf, &dnf));
    for (int64_t i = 0; i < n; ++i) {
      nb_row.clear();
      w_row.clear();
      t_row.clear();
      env.graph->GetFullNeighbor(ids[i], ets.empty() ? nullptr : ets.data(),
                                 ets.size(), &nb_row, &w_row, &t_row, false);
      std::vector<size_t> keep;
      keep.reserve(nb_row.size());
      for (size_t j = 0; j < nb_row.size(); ++j)
        if (EdgeMatch(dnf, nb_row[j], w_row[j], t_row[j])) keep.push_back(j);
      pp.Apply(&keep, [&](size_t j) { return nb_row[j]; },
               [&](size_t j) { return w_row[j]; });
      for (size_t j : keep) {
        src.push_back(ids[i]);
        dst.push_back(nb_row[j]);
        w.push_back(w_row[j]);
        t.push_back(t_row[j]);
      }
      offsets.push_back(src.size());
    }
    ctx->Put(node.OutName(0), MakeIdx(offsets));
    ctx->Put(node.OutName(1), Tensor::FromVector(src));
    ctx->Put(node.OutName(2), Tensor::FromVector(dst));
    ctx->Put(node.OutName(3), Tensor::FromVector(t));
    ctx->Put(node.OutName(4), Tensor::FromVector(w));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("API_GET_NB_EDGE", GetNbEdgeOp);

// ---------------------------------------------------------------------------
// API_GET_P — input 0: ids; attrs: feature names; optional
// "udf:<name>[:p1:p2...]" first attr applies a registered value-UDF with
// numeric params (reference udf.h:33-68, applied in API_GET_P; registry
// + built-ins live in udf.cc). Per feature f: out :2f = idx, :2f+1 =
// values.
// ---------------------------------------------------------------------------
class GetFeatureOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor ids_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &ids_t));
    const uint64_t* ids = ids_t.Flat<uint64_t>();
    int64_t n = ids_t.NumElements();
    ValueUdf udf;
    std::vector<double> udf_params;
    uint64_t udf_gen = 0;  // captured atomically with the lookup: a
    // later Generation() read could cache an old function's result
    // under a newer generation if Register() raced in between
    size_t a0 = 0;
    if (!node.attrs.empty() && node.attrs[0].rfind("udf:", 0) == 0) {
      std::string uname;
      ET_K_RETURN_IF_ERROR(
          ParseUdfSpec(node.attrs[0].substr(4), &uname, &udf_params));
      udf = UdfRegistry::Instance().Find(uname, &udf_gen);
      if (!udf) {
        done(Status::NotFound("no registered udf named " + uname));
        return;
      }
      a0 = 1;
    }
    int out_i = 0;
    for (size_t a = a0; a < node.attrs.size(); ++a, out_i += 2) {
      FeatureKind kind;
      int fid;
      int64_t dim;
      ET_K_RETURN_IF_ERROR(
          ResolveFeature(*env.graph, node.attrs[a], false, &kind, &fid, &dim));
      if (kind == FeatureKind::kDense) {
        // UDF result cache (reference UdfCache, udf.h:33-68): the
        // transformed column is keyed on (immutable graph uid, registry
        // generation, full udf spec, fid, ids) — repeated queries skip
        // both the feature read and the transform. The hash only
        // buckets; the stored full key decides a true hit.
        uint64_t ck = 0;
        std::shared_ptr<const CachedColumn> hit;
        if (udf) {
          ck = UdfCacheKey(env.graph->uid(), udf_gen, node.attrs[0], fid,
                           ids, static_cast<size_t>(n));
          hit = UdfResultCache::Instance().Get(
              ck, env.graph->uid(), udf_gen, node.attrs[0], fid, ids,
              static_cast<size_t>(n));
        }
        if (hit) {
          ctx->Put(node.OutName(out_i), MakeIdx(hit->offs));
          ctx->Put(node.OutName(out_i + 1), Tensor::FromVector(hit->vals));
        } else {
          std::vector<float> vals(n * dim);
          env.graph->GetDenseFeature(ids, n, fid, dim, vals.data());
          std::vector<uint64_t> offs(n + 1);
          for (int64_t i = 0; i <= n; ++i) offs[i] = i * dim;
          if (udf) {
            ET_K_RETURN_IF_ERROR(udf(udf_params, &offs, &vals));
            auto col = std::make_shared<CachedColumn>();
            col->graph_uid = env.graph->uid();
            col->generation = udf_gen;
            col->spec = node.attrs[0];
            col->fid = fid;
            col->ids.assign(ids, ids + n);
            col->offs = offs;
            col->vals = vals;
            UdfResultCache::Instance().Put(ck, std::move(col));
          }
          ctx->Put(node.OutName(out_i), MakeIdx(offs));
          ctx->Put(node.OutName(out_i + 1), Tensor::FromVector(vals));
        }
      } else if (kind == FeatureKind::kSparse) {
        std::vector<uint64_t> offs, vals;
        env.graph->GetSparseFeature(ids, n, fid, &offs, &vals);
        ctx->Put(node.OutName(out_i), MakeIdx(offs));
        ctx->Put(node.OutName(out_i + 1), Tensor::FromVector(vals));
      } else {
        std::vector<uint64_t> offs;
        std::vector<char> vals;
        env.graph->GetBinaryFeature(ids, n, fid, &offs, &vals);
        ctx->Put(node.OutName(out_i), MakeIdx(offs));
        ctx->Put(node.OutName(out_i + 1), Tensor::FromVector(vals));
      }
    }
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("API_GET_P", GetFeatureOp);

// API_GET_EDGE_P — inputs: src, dst, type tensors; attrs: feature names.
class GetEdgeFeatureOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor src_t, dst_t, type_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &src_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 1, &dst_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 2, &type_t));
    int64_t n = src_t.NumElements();
    int out_i = 0;
    for (size_t a = 0; a < node.attrs.size(); ++a, out_i += 2) {
      FeatureKind kind;
      int fid;
      int64_t dim;
      ET_K_RETURN_IF_ERROR(
          ResolveFeature(*env.graph, node.attrs[a], true, &kind, &fid, &dim));
      if (kind == FeatureKind::kDense) {
        std::vector<float> vals(n * dim);
        env.graph->GetEdgeDenseFeature(src_t.Flat<uint64_t>(),
                                       dst_t.Flat<uint64_t>(),
                                       type_t.Flat<int32_t>(), n, fid, dim,
                                       vals.data());
        std::vector<uint64_t> offs(n + 1);
        for (int64_t i = 0; i <= n; ++i) offs[i] = i * dim;
        ctx->Put(node.OutName(out_i), MakeIdx(offs));
        ctx->Put(node.OutName(out_i + 1), Tensor::FromVector(vals));
      } else if (kind == FeatureKind::kSparse) {
        std::vector<uint64_t> offs, vals;
        env.graph->GetEdgeSparseFeature(src_t.Flat<uint64_t>(),
                                        dst_t.Flat<uint64_t>(),
                                        type_t.Flat<int32_t>(), n, fid, &offs,
                                        &vals);
        ctx->Put(node.OutName(out_i), MakeIdx(offs));
        ctx->Put(node.OutName(out_i + 1), Tensor::FromVector(vals));
      } else {
        std::vector<uint64_t> offs;
        std::vector<char> vals;
        env.graph->GetEdgeBinaryFeature(src_t.Flat<uint64_t>(),
                                        dst_t.Flat<uint64_t>(),
                                        type_t.Flat<int32_t>(), n, fid, &offs,
                                        &vals);
        ctx->Put(node.OutName(out_i), MakeIdx(offs));
        ctx->Put(node.OutName(out_i + 1), Tensor::FromVector(vals));
      }
    }
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("API_GET_EDGE_P", GetEdgeFeatureOp);

// API_GET_NODE_T — input 0: ids → :0 i32 types (-1 for missing).
class GetNodeTypeOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor ids_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &ids_t));
    const uint64_t* ids = ids_t.Flat<uint64_t>();
    int64_t n = ids_t.NumElements();
    Tensor out(DType::kI32, {n});
    int32_t* p = out.Flat<int32_t>();
    for (int64_t i = 0; i < n; ++i) {
      uint32_t row = env.graph->NodeIndex(ids[i]);
      p[i] = row == kInvalidIndex ? -1 : env.graph->node_type(row);
    }
    ctx->Put(node.OutName(0), std::move(out));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("API_GET_NODE_T", GetNodeTypeOp);

// API_SAMPLE_L — layerwise sampling (reference sample_layer_op.cc:74).
// input 0: root ids; attrs [edge_types, layer_sizes "m0:m1", default_id,
// optional weight_func "sqrt", optional "emit_wsum"]. out :l = pool ids
// for layer l; with emit_wsum (set by the distribute rewrite on the
// per-shard single-layer clones) out :n_layers+l = that layer's total
// candidate mass, which POOL_MERGE uses to weigh shards.
class SampleLayerOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor ids_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &ids_t));
    auto ets = ParseEdgeTypes(node.attrs.size() > 0 ? node.attrs[0] : "");
    std::vector<int32_t> sizes;
    for (auto& s : SplitStr(node.attrs.size() > 1 ? node.attrs[1] : "1", ':')) {
      int32_t m = std::atoi(s.c_str());
      if (m < 0) {
        done(Status::InvalidArgument("sampleLNB layer size must be >= 0"));
        return;
      }
      sizes.push_back(m);
    }
    uint64_t def = node.attrs.size() > 2 ? std::strtoull(node.attrs[2].c_str(), nullptr, 10) : 0;
    LayerWeightFunc wf = LayerWeightFunc::kIdentity;
    if (node.attrs.size() > 3 && !node.attrs[3].empty()) {
      if (node.attrs[3] != "sqrt") {
        done(Status::InvalidArgument(
            "sampleLNB weight_func must be 'sqrt', got " + node.attrs[3]));
        return;
      }
      wf = LayerWeightFunc::kSqrt;
    }
    bool emit_wsum = node.attrs.size() > 4 && node.attrs[4] == "emit_wsum";
    Pcg32 rng = NodeRng(node, env);
    std::vector<Tensor> layers;
    std::vector<NodeId*> ptrs;
    for (int32_t m : sizes) {
      layers.emplace_back(DType::kU64, std::vector<int64_t>{m});
      ptrs.push_back(layers.back().Flat<uint64_t>());
    }
    std::vector<float> wsums;
    SampleLayerwise(*env.graph, ids_t.Flat<uint64_t>(), ids_t.NumElements(),
                    sizes.data(), sizes.size(),
                    ets.empty() ? nullptr : ets.data(), ets.size(), def, &rng,
                    ptrs, wf, emit_wsum ? &wsums : nullptr);
    size_t n_layers_out = layers.size();
    for (size_t l = 0; l < n_layers_out; ++l)
      ctx->Put(node.OutName(l), std::move(layers[l]));
    if (emit_wsum) {
      // SampleLayerwise records one wsum per layer unconditionally
      ET_K_RETURN_IF_ERROR(
          wsums.size() == n_layers_out
              ? Status::OK()
              : Status::Internal("layer wsum count mismatch"));
      for (size_t l = 0; l < n_layers_out; ++l) {
        Tensor w(DType::kF32, {1});
        w.Flat<float>()[0] = wsums[l];
        ctx->Put(node.OutName(n_layers_out + l), std::move(w));
      }
    }
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("API_SAMPLE_L", SampleLayerOp);

// ---------------------------------------------------------------------------
// AS — alias all inputs under a new name for final fetch
// (reference as_op.cc). attrs[0] = alias.
// ---------------------------------------------------------------------------
class AsOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    if (node.attrs.empty()) {
      done(Status::InvalidArgument("AS needs an alias attr"));
      return;
    }
    const std::string& alias = node.attrs[0];
    for (size_t i = 0; i < node.inputs.size(); ++i) {
      ctx->AddAlias(alias + ":" + std::to_string(i), node.inputs[i]);
      ctx->AddAlias(node.OutName(i), node.inputs[i]);
    }
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("AS", AsOp);

// ---------------------------------------------------------------------------
// FUSED — a collapsed local plan (gql.cc FuseLocalPass): runs `inner`
// nodes inline in the already-topological order, sharing this query's
// context, so an entire sampling chain costs one executor dispatch.
// Inner kernels put tensors under their ORIGINAL names; consumers outside
// the fusion group resolve through NodeDef::also_produces.
// ---------------------------------------------------------------------------
class FusedOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    for (const auto& sub : node.inner) {
      OpKernel* k = LookupKernel(sub.op);
      if (k == nullptr) {
        done(Status::NotFound("FUSED: no kernel for op " + sub.op));
        return;
      }
      // Contract: fusion groups hold synchronous kernels only (FuseLocal-
      // Pass excludes REMOTE, the sole async op). Waiting here for a
      // stray async kernel would deadlock the shared pool (the inner
      // completion needs a pool thread this one is blocking), so fail
      // loudly instead. State lives in a shared_ptr so a late completion
      // writes into live memory instead of a dead stack frame.
      struct CallState {
        std::mutex mu;
        bool fired = false;
        Status st;
      };
      auto cs = std::make_shared<CallState>();
      k->Compute(sub, env, ctx, [cs](Status s) {
        std::lock_guard<std::mutex> lk(cs->mu);
        cs->st = std::move(s);
        cs->fired = true;
      });
      Status st;
      {
        std::lock_guard<std::mutex> lk(cs->mu);
        if (!cs->fired) {
          done(Status::Internal(
              "FUSED: op " + sub.op +
              " completed asynchronously; fusion requires sync kernels"));
          return;
        }
        st = cs->st;
      }
      if (!st.ok()) {
        done(st);
        return;
      }
    }
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("FUSED", FusedOp);

// ---------------------------------------------------------------------------
// POST_PROCESS — order_by/limit over a ragged quad (reference
// post_process_op.cc:325). Inputs: idx, ids, w, t. post_process entries:
// "order_by <id|weight> <asc|desc>", "limit <k>".
// ---------------------------------------------------------------------------
class PostProcessOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor idx_t, ids_t, w_t, t_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &idx_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 1, &ids_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 2, &w_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 3, &t_t));
    RowPostProcess pp = RowPostProcess::Parse(node.post_process);
    int64_t n = idx_t.dim(0);
    const int32_t* pidx = idx_t.Flat<int32_t>();
    const uint64_t* ids = ids_t.Flat<uint64_t>();
    const float* w = w_t.Flat<float>();
    const int32_t* t = t_t.Flat<int32_t>();
    std::vector<uint64_t> offsets{0};
    std::vector<uint64_t> out_ids;
    std::vector<float> out_w;
    std::vector<int32_t> out_t;
    for (int64_t i = 0; i < n; ++i) {
      std::vector<int32_t> order;
      for (int32_t j = pidx[2 * i]; j < pidx[2 * i + 1]; ++j)
        order.push_back(j);
      pp.Apply(&order, [&](int32_t j) { return ids[j]; },
               [&](int32_t j) { return w[j]; });
      for (int32_t j : order) {
        out_ids.push_back(ids[j]);
        out_w.push_back(w[j]);
        out_t.push_back(t[j]);
      }
      offsets.push_back(out_ids.size());
    }
    ctx->Put(node.OutName(0), MakeIdx(offsets));
    ctx->Put(node.OutName(1), Tensor::FromVector(out_ids));
    ctx->Put(node.OutName(2), Tensor::FromVector(out_w));
    ctx->Put(node.OutName(3), Tensor::FromVector(out_t));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("POST_PROCESS", PostProcessOp);

// ---------------------------------------------------------------------------
// ID_UNIQUE — input ids → :0 unique ids (first-seen order), :1 i32 inverse
// positions. Used by the distribute rewrite to dedup before REMOTE.
// ---------------------------------------------------------------------------
class IdUniqueOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor ids_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &ids_t));
    const uint64_t* ids = ids_t.Flat<uint64_t>();
    int64_t n = ids_t.NumElements();
    std::vector<uint64_t> uniq;
    std::vector<int32_t> inv(n);
    std::unordered_map<uint64_t, int32_t> seen;
    for (int64_t i = 0; i < n; ++i) {
      auto it = seen.find(ids[i]);
      if (it == seen.end()) {
        it = seen.emplace(ids[i], static_cast<int32_t>(uniq.size())).first;
        uniq.push_back(ids[i]);
      }
      inv[i] = it->second;
    }
    ctx->Put(node.OutName(0), Tensor::FromVector(uniq));
    ctx->Put(node.OutName(1), Tensor::FromVector(inv));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("ID_UNIQUE", IdUniqueOp);


// ---------------------------------------------------------------------------
// Whole-graph (graph classification) ops — reference
// sample_graph_label_op.cc / get_graph_by_label_op.cc.
// ---------------------------------------------------------------------------
// API_SAMPLE_GRAPH_LABEL — attrs [count]; optional input overrides count.
// out :0 = labels u64 [count].
class SampleGraphLabelOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    int64_t count =
        node.attrs.size() > 0 ? std::atoll(node.attrs[0].c_str()) : 0;
    if (!node.inputs.empty()) {
      Tensor t;
      if (ctx->Get(node.inputs[0], &t) && t.NumElements() > 0)
        count = t.AsI64(0);
    }
    if (count < 0) {
      done(Status::InvalidArgument("sampleGL count must be >= 0"));
      return;
    }
    Pcg32 rng = NodeRng(node, env);
    Tensor out(DType::kU64, {count});
    // attrs [count, "owned", shard_idx, shard_num]: hash-distribute inner
    // form — draw only labels this shard owns (see SampleSplitOp).
    if (node.attrs.size() > 3 && node.attrs[1] == "owned") {
      env.graph->SampleGraphLabelOwned(
          static_cast<size_t>(count), std::atoi(node.attrs[2].c_str()),
          std::atoi(node.attrs[3].c_str()), &rng, out.Flat<uint64_t>());
    } else {
      env.graph->SampleGraphLabel(static_cast<size_t>(count), &rng,
                                  out.Flat<uint64_t>());
    }
    ctx->Put(node.OutName(0), std::move(out));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("API_SAMPLE_GRAPH_LABEL", SampleGraphLabelOp);

// API_GET_GRAPH_BY_LABEL — input 0: labels u64. attrs[0] "all" (default):
// one row per input label, empty when unknown; "owned": only labels this
// graph holds (the graph_partition inner form — positions select the
// owner's rows at the client merge).
// out :0 = pos i32 [m], :1 = idx i32 [m,2], :2 = node ids u64.
class GetGraphByLabelOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor labels_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &labels_t));
    bool owned_only = !node.attrs.empty() && node.attrs[0] == "owned";
    const uint64_t* labels = labels_t.Flat<uint64_t>();
    int64_t n = labels_t.NumElements();
    std::vector<int32_t> pos;
    std::vector<uint64_t> offs{0};
    std::vector<uint64_t> out_ids;
    for (int64_t i = 0; i < n; ++i) {
      const std::vector<uint32_t>* rows = env.graph->GraphNodes(labels[i]);
      if (rows == nullptr && owned_only) continue;
      if (rows != nullptr)
        for (uint32_t r : *rows) out_ids.push_back(env.graph->node_id(r));
      pos.push_back(static_cast<int32_t>(i));
      offs.push_back(out_ids.size());
    }
    int64_t m = static_cast<int64_t>(pos.size());
    ET_K_RETURN_IF_ERROR(
        CheckI32Offsets(node, static_cast<int64_t>(offs.back())));
    Tensor idx(DType::kI32, {m, 2});
    int32_t* pi = idx.Flat<int32_t>();
    for (int64_t i = 0; i < m; ++i) {
      pi[2 * i] = static_cast<int32_t>(offs[i]);
      pi[2 * i + 1] = static_cast<int32_t>(offs[i + 1]);
    }
    ctx->Put(node.OutName(0), Tensor::FromVector(pos));
    ctx->Put(node.OutName(1), std::move(idx));
    ctx->Put(node.OutName(2), Tensor::FromVector(out_ids));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("API_GET_GRAPH_BY_LABEL", GetGraphByLabelOp);

}  // namespace
}  // namespace et
