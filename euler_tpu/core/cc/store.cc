// Out-of-core columnar store: writer, mmap attach, hot-set accounting.
// See store.h for the design contract.
#include "store.h"

#include <dirent.h>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <set>

#include "io.h"

namespace et {

const char kColumnarFileName[] = "columnar.etc";

std::string ColumnarSidecarName(int shard_idx, int shard_num) {
  if (shard_num <= 1) return kColumnarFileName;
  return "columnar." + std::to_string(shard_idx) + "of" +
         std::to_string(shard_num) + ".etc";
}

bool SidecarIsFresh(const std::string& dir, const std::string& sidecar_path) {
  struct stat sc;
  if (::stat(sidecar_path.c_str(), &sc) != 0) return false;
  DIR* d = ::opendir(dir.c_str());
  if (d == nullptr) return false;
  bool fresh = true;
  while (struct dirent* e = ::readdir(d)) {
    const std::string name = e->d_name;
    if (name == "." || name == "..") continue;
    // other shards' sidecars and in-flight spills are not source files
    if (name.find(".etc") != std::string::npos) continue;
    struct stat st;
    if (::stat((dir + "/" + name).c_str(), &st) != 0 ||
        !S_ISREG(st.st_mode))
      continue;
    if (st.st_mtim.tv_sec > sc.st_mtim.tv_sec ||
        (st.st_mtim.tv_sec == sc.st_mtim.tv_sec &&
         st.st_mtim.tv_nsec > sc.st_mtim.tv_nsec)) {
      fresh = false;  // a partition file is newer than the spill
      break;
    }
  }
  ::closedir(d);
  return fresh;
}

StoreCounters& GlobalStoreCounters() {
  static StoreCounters* c = new StoreCounters();
  return *c;
}

namespace {

constexpr char kStoreMagic[4] = {'E', 'T', 'S', '1'};
constexpr uint32_t kStoreVersion = 1;
constexpr size_t kAlign = 64;
constexpr size_t kPage = 4096;

inline int64_t MonoNowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

inline size_t AlignUp(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

// Live-tier registry for the process-wide residency gauges.
std::mutex& TierRegMu() {
  static std::mutex* m = new std::mutex();
  return *m;
}
std::set<StorageTier*>& TierReg() {
  static std::set<StorageTier*>* s = new std::set<StorageTier*>();
  return *s;
}

// One serialized column: name + element geometry + a pointer to the
// source bytes (writer side).
struct ColSpec {
  std::string name;
  uint32_t elem_size = 1;
  uint64_t count = 0;
  const void* data = nullptr;
};

template <typename T>
void AddCol(std::vector<ColSpec>* specs, const std::string& name,
            const Col<T>& c) {
  specs->push_back({name, static_cast<uint32_t>(sizeof(T)), c.size(),
                    static_cast<const void*>(c.data())});
}

}  // namespace

// ---------------------------------------------------------------------------
// StoreAccess — the single friend through which store.cc reads/wires
// Graph internals. Writer and attacher walk the SAME column list so the
// two directions can never diverge silently.
// ---------------------------------------------------------------------------
struct StoreAccess {
  // Serialize the aux section: meta + every scalar an attached Graph
  // needs that is not itself a column.
  static void EncodeAux(const Graph& g, ByteWriter* w) {
    EncodeMeta(g.meta_, w);
    w->Put<uint64_t>(g.dense_base_);
    w->Put<uint32_t>(static_cast<uint32_t>(g.node_type_wsum_.size()));
    for (float f : g.node_type_wsum_) w->Put<float>(f);
    w->Put<uint32_t>(static_cast<uint32_t>(g.edge_type_wsum_.size()));
    for (float f : g.edge_type_wsum_) w->Put<float>(f);
    w->Put<float>(g.node_sampler_all_.total_weight());
    w->Put<float>(g.edge_sampler_all_.total_weight());
    w->Put<uint32_t>(static_cast<uint32_t>(g.node_sampler_by_type_.size()));
    for (const auto& s : g.node_sampler_by_type_)
      w->Put<float>(s.total_weight());
    w->Put<uint32_t>(static_cast<uint32_t>(g.edge_sampler_by_type_.size()));
    for (const auto& s : g.edge_sampler_by_type_)
      w->Put<float>(s.total_weight());
  }

  static void CollectColumns(const Graph& g, std::vector<ColSpec>* specs) {
    AddCol(specs, "node_ids", g.node_ids_);
    AddCol(specs, "node_types", g.node_types_);
    AddCol(specs, "node_weights", g.node_weights_);
    AddCol(specs, "dense_idx", g.dense_idx_);
    AddCol(specs, "graph_labels", g.graph_labels_);
    AddCol(specs, "adj_offsets", g.adj_offsets_);
    AddCol(specs, "adj_nbr", g.adj_nbr_);
    AddCol(specs, "adj_w", g.adj_w_);
    AddCol(specs, "adj_cumw", g.adj_cumw_);
    AddCol(specs, "in_adj_offsets", g.in_adj_offsets_);
    AddCol(specs, "in_adj_nbr", g.in_adj_nbr_);
    AddCol(specs, "in_adj_w", g.in_adj_w_);
    AddCol(specs, "in_adj_cumw", g.in_adj_cumw_);
    for (size_t t = 0; t < g.nodes_by_type_.size(); ++t)
      AddCol(specs, "nbt_" + std::to_string(t), g.nodes_by_type_[t]);
    for (size_t t = 0; t < g.edges_by_type_.size(); ++t)
      AddCol(specs, "ebt_" + std::to_string(t), g.edges_by_type_[t]);
    AddCol(specs, "nsp_all", g.node_sampler_all_.prob_col());
    AddCol(specs, "nsa_all", g.node_sampler_all_.alias_col());
    AddCol(specs, "esp_all", g.edge_sampler_all_.prob_col());
    AddCol(specs, "esa_all", g.edge_sampler_all_.alias_col());
    for (size_t t = 0; t < g.node_sampler_by_type_.size(); ++t) {
      AddCol(specs, "nsp_" + std::to_string(t),
             g.node_sampler_by_type_[t].prob_col());
      AddCol(specs, "nsa_" + std::to_string(t),
             g.node_sampler_by_type_[t].alias_col());
    }
    for (size_t t = 0; t < g.edge_sampler_by_type_.size(); ++t) {
      AddCol(specs, "esp_" + std::to_string(t),
             g.edge_sampler_by_type_[t].prob_col());
      AddCol(specs, "esa_" + std::to_string(t),
             g.edge_sampler_by_type_[t].alias_col());
    }
    for (size_t f = 0; f < g.node_dense_.size(); ++f)
      AddCol(specs, "nd_" + std::to_string(f), g.node_dense_[f]);
    for (size_t f = 0; f < g.node_var_.size(); ++f) {
      AddCol(specs, "nvo_" + std::to_string(f), g.node_var_[f].offsets);
      AddCol(specs, "nvu_" + std::to_string(f), g.node_var_[f].values_u64);
      AddCol(specs, "nvb_" + std::to_string(f), g.node_var_[f].values_bytes);
    }
    for (size_t f = 0; f < g.edge_dense_.size(); ++f)
      AddCol(specs, "ed_" + std::to_string(f), g.edge_dense_[f]);
    for (size_t f = 0; f < g.edge_var_.size(); ++f) {
      AddCol(specs, "evo_" + std::to_string(f), g.edge_var_[f].offsets);
      AddCol(specs, "evu_" + std::to_string(f), g.edge_var_[f].values_u64);
      AddCol(specs, "evb_" + std::to_string(f), g.edge_var_[f].values_bytes);
    }
  }

  static Status Attach(std::shared_ptr<ColumnarStore> store,
                       int64_t hot_bytes, std::unique_ptr<Graph>* out);
};

// ---------------------------------------------------------------------------
// Writer
// ---------------------------------------------------------------------------
Status WriteColumnarStore(const Graph& g, const std::string& path) {
  ByteWriter aux;
  StoreAccess::EncodeAux(g, &aux);
  std::vector<ColSpec> specs;
  specs.push_back({"aux", 1, aux.buffer().size(),
                   static_cast<const void*>(aux.buffer().data())});
  StoreAccess::CollectColumns(g, &specs);

  // Header: magic | version | epoch | n_cols, then the column table with
  // absolute 64-aligned payload offsets. Two passes: size the header,
  // then lay out payloads after it.
  size_t header_size = 4 + 4 + 8 + 4;
  for (const auto& s : specs) header_size += 4 + s.name.size() + 4 + 8 + 8;
  std::vector<uint64_t> offsets(specs.size());
  size_t cur = AlignUp(header_size);
  for (size_t i = 0; i < specs.size(); ++i) {
    offsets[i] = cur;
    cur = AlignUp(cur + specs[i].count * specs[i].elem_size);
  }

  ByteWriter h;
  h.PutRaw(kStoreMagic, 4);
  h.Put<uint32_t>(kStoreVersion);
  h.Put<uint64_t>(g.epoch());
  h.Put<uint32_t>(static_cast<uint32_t>(specs.size()));
  for (size_t i = 0; i < specs.size(); ++i) {
    h.PutStr(specs[i].name);
    h.Put<uint32_t>(specs[i].elem_size);
    h.Put<uint64_t>(specs[i].count);
    h.Put<uint64_t>(offsets[i]);
  }

  // Atomic tmp+rename (the ModelBundle convention): a crashed writer
  // never leaves a half-written store under the canonical name. The tmp
  // is pid-qualified so concurrent first-starts spilling the same path
  // never interleave writes; both renames land identical bytes.
  std::string tmp = path + ".tmp." + std::to_string(::getpid());
  std::FILE* f = std::fopen(tmp.c_str(), "wb");
  if (!f) return Status::IOError("cannot open " + tmp + " for write");
  auto write_all = [&](const void* p, size_t n) {
    return n == 0 || std::fwrite(p, 1, n, f) == n;
  };
  static const char zeros[kAlign] = {};
  bool ok = write_all(h.buffer().data(), h.buffer().size());
  size_t written = h.buffer().size();
  for (size_t i = 0; ok && i < specs.size(); ++i) {
    if (offsets[i] > written) {
      ok = write_all(zeros, offsets[i] - written);
      written = offsets[i];
    }
    size_t n = specs[i].count * specs[i].elem_size;
    ok = ok && write_all(specs[i].data, n);
    written += n;
  }
  ok = ok && std::fflush(f) == 0;
  int fd = fileno(f);
  ok = ok && fd >= 0 && fsync(fd) == 0;
  std::fclose(f);
  if (!ok) {
    std::remove(tmp.c_str());
    return Status::IOError("short write on " + tmp);
  }
  if (std::rename(tmp.c_str(), path.c_str()) != 0) {
    std::remove(tmp.c_str());
    return Status::IOError("rename " + tmp + " -> " + path + " failed");
  }
  return Status::OK();
}

// ---------------------------------------------------------------------------
// ColumnarStore
// ---------------------------------------------------------------------------
ColumnarStore::~ColumnarStore() {
  if (base_ != nullptr) munmap(const_cast<char*>(base_), mapped_bytes_);
  if (fd_ >= 0) close(fd_);
}

Status ColumnarStore::Open(const std::string& path,
                           std::shared_ptr<ColumnarStore>* out) {
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) return Status::IOError("cannot open " + path);
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 16) {
    close(fd);
    return Status::IOError("bad columnar store " + path);
  }
  size_t size = static_cast<size_t>(st.st_size);
  void* base = mmap(nullptr, size, PROT_READ, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return Status::IOError("mmap failed on " + path);
  }
  auto store = std::shared_ptr<ColumnarStore>(new ColumnarStore());
  store->path_ = path;
  store->fd_ = fd;
  store->base_ = static_cast<const char*>(base);
  store->mapped_bytes_ = size;

  ByteReader r(store->base_, size);
  char magic[4];
  uint32_t ver = 0, n_cols = 0;
  if (!r.GetRaw(magic, 4) || std::memcmp(magic, kStoreMagic, 4) != 0)
    return Status::IOError("bad store magic in " + path);
  if (!r.Get(&ver) || ver != kStoreVersion)
    return Status::IOError("unsupported store version in " + path);
  if (!r.Get(&store->epoch_) || !r.Get(&n_cols))
    return Status::IOError("truncated store header in " + path);
  for (uint32_t i = 0; i < n_cols; ++i) {
    std::string name;
    uint32_t elem_size = 0;
    uint64_t count = 0, off = 0;
    if (!r.GetStr(&name) || !r.Get(&elem_size) || !r.Get(&count) ||
        !r.Get(&off))
      return Status::IOError("truncated store column table in " + path);
    // overflow-safe: off + count*elem_size can wrap on a corrupt header
    if (off > size ||
        (count > 0 &&
         (elem_size == 0 || count > (size - off) / elem_size)))
      return Status::IOError("column " + name + " exceeds file in " + path);
    Column c;
    c.data = store->base_ + off;
    c.count = count;
    c.elem_size = elem_size;
    store->cols_[name] = c;
  }
  *out = std::move(store);
  return Status::OK();
}

const ColumnarStore::Column* ColumnarStore::aux() const {
  auto it = cols_.find("aux");
  return it == cols_.end() ? nullptr : &it->second;
}

// ---------------------------------------------------------------------------
// StorageTier
// ---------------------------------------------------------------------------
StorageTier::StorageTier(std::shared_ptr<ColumnarStore> store)
    : store_(std::move(store)) {}

// Registration is deferred until Attach has fully built the tier: the
// ctor registering itself would expose half-initialized fields to a
// concurrent GlobalResidency walk. The mutex hand-off publishes every
// field written before Register() to any walk that locks after it.
void StorageTier::Register() {
  std::lock_guard<std::mutex> lk(TierRegMu());
  TierReg().insert(this);
}

StorageTier::~StorageTier() {
  std::lock_guard<std::mutex> lk(TierRegMu());
  TierReg().erase(this);  // no-op for a tier that never registered
}

void StorageTier::OnRowAccess(uint32_t row) {
  StoreCounters& c = GlobalStoreCounters();
  if (IsHot(row)) {
    c.hot_hits.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  c.cold_reads.fetch_add(1, std::memory_order_relaxed);
  if (adj_offsets_ == nullptr || row >= n_rows_) return;
  // Pre-fault the row's adjacency pages under the cold-read timer: the
  // gather that follows would take these faults anyway; fronting them
  // here makes the penalty a measured, bucketed quantity instead of
  // noise smeared over the request.
  uint64_t b = adj_offsets_[static_cast<uint64_t>(row) * num_edge_types_];
  uint64_t e = adj_offsets_[static_cast<uint64_t>(row + 1) * num_edge_types_];
  int64_t t0 = MonoNowUs();
  if (e > b) {
    // cap the touch at 1024 pages per array — a pathological hub read
    // must not stall the timer for seconds
    size_t nbytes8 = std::min<size_t>((e - b) * 8, 1024 * kPage);
    size_t nbytes4 = std::min<size_t>((e - b) * 4, 1024 * kPage);
    volatile const char* p;
    unsigned sink = 0;
    if (adj_nbr_ != nullptr) {
      p = adj_nbr_ + b * 8;
      for (size_t o = 0; o < nbytes8; o += kPage) sink += p[o];
    }
    if (adj_w_ != nullptr) {
      p = adj_w_ + b * 4;
      for (size_t o = 0; o < nbytes4; o += kPage) sink += p[o];
    }
    if (adj_cumw_ != nullptr) {
      p = adj_cumw_ + b * 4;
      for (size_t o = 0; o < nbytes4; o += kPage) sink += p[o];
    }
    (void)sink;
  }
  c.cold_hist.Observe(static_cast<uint64_t>(MonoNowUs() - t0));
}

int64_t StorageTier::PollResidentBytes() {
  std::lock_guard<std::mutex> lk(resid_mu_);
  size_t pages = (store_->mapped_bytes() + kPage - 1) / kPage;
  std::vector<unsigned char> now(pages, 0);
  if (mincore(const_cast<char*>(store_->base()), store_->mapped_bytes(),
              now.data()) != 0)
    return -1;
  int64_t resident = 0;
  uint64_t in = 0, out = 0;
  bool have_prev = prev_resident_.size() == pages;
  for (size_t i = 0; i < pages; ++i) {
    bool r = (now[i] & 1) != 0;
    if (r) ++resident;
    if (have_prev) {
      bool was = (prev_resident_[i] & 1) != 0;
      if (r && !was) ++in;
      if (!r && was) ++out;
    } else if (r) {
      ++in;  // first poll: everything resident was paged in since attach
    }
  }
  prev_resident_ = std::move(now);
  StoreCounters& c = GlobalStoreCounters();
  c.page_in.fetch_add(in, std::memory_order_relaxed);
  c.page_out.fetch_add(out, std::memory_order_relaxed);
  return resident * static_cast<int64_t>(kPage);
}

void StorageTier::GlobalResidency(int64_t* resident, int64_t* mapped,
                                  int64_t* hot_pinned) {
  *resident = 0;
  *mapped = 0;
  *hot_pinned = 0;
  // Hold the registry lock for the whole walk: ~StorageTier serializes
  // on TierRegMu before erasing itself, so every pointer in the set
  // stays alive while we poll it. Snapshotting the set and polling
  // unlocked raced a reattach's tier teardown (use-after-free on a
  // /metrics scrape concurrent with compaction).
  std::lock_guard<std::mutex> lk(TierRegMu());
  for (StorageTier* t : TierReg()) {
    int64_t r = t->PollResidentBytes();
    if (r > 0) *resident += r;
    *mapped += static_cast<int64_t>(t->mapped_bytes());
    *hot_pinned += t->hot_pinned_bytes();
  }
}

void StoreStatsSnapshot(uint64_t out[kStoreStatSlots]) {
  StoreCounters& c = GlobalStoreCounters();
  int64_t resident = 0, mapped = 0, pinned = 0;
  StorageTier::GlobalResidency(&resident, &mapped, &pinned);
  out[0] = c.hot_hits.load();
  out[1] = c.cold_reads.load();
  out[2] = c.page_in.load();
  out[3] = c.page_out.load();
  out[4] = static_cast<uint64_t>(resident);
  out[5] = static_cast<uint64_t>(mapped);
  out[6] = static_cast<uint64_t>(pinned);
  out[7] = c.attaches.load();
  c.cold_hist.Snapshot(&out[8], &out[9], &out[10]);
}

// Graph-side hook (declared in graph.h; lives here so graph.cc does not
// need store.h).
void Graph::TierTouchRow(uint32_t idx) const { tier_raw_->OnRowAccess(idx); }

// ---------------------------------------------------------------------------
// Attach
// ---------------------------------------------------------------------------
namespace {

template <typename T>
Status AttachCol(const ColumnarStore& s, const std::string& name,
                 Col<T>* col) {
  const T* p = nullptr;
  size_t n = 0;
  if (!s.Find(name, &p, &n))
    return Status::IOError("store missing column " + name);
  col->AttachExternal(p, n);
  return Status::OK();
}

}  // namespace

Status StoreAccess::Attach(std::shared_ptr<ColumnarStore> store,
                           int64_t hot_bytes, std::unique_ptr<Graph>* out) {
  auto g = std::unique_ptr<Graph>(new Graph());
  const ColumnarStore& s = *store;

  // aux: meta + scalars
  const ColumnarStore::Column* aux = s.aux();
  if (aux == nullptr) return Status::IOError("store has no aux section");
  ByteReader r(static_cast<const char*>(aux->data), aux->count);
  ET_RETURN_IF_ERROR(DecodeMeta(&r, &g->meta_));
  uint64_t dense_base = 0;
  if (!r.Get(&dense_base)) return Status::IOError("truncated store aux");
  g->dense_base_ = dense_base;
  auto get_floats = [&r](std::vector<float>* v) {
    uint32_t n;
    if (!r.Get(&n)) return false;
    v->resize(n);
    for (uint32_t i = 0; i < n; ++i)
      if (!r.Get(&(*v)[i])) return false;
    return true;
  };
  if (!get_floats(&g->node_type_wsum_) || !get_floats(&g->edge_type_wsum_))
    return Status::IOError("truncated store aux (wsums)");
  float ns_total = 0.f, es_total = 0.f;
  std::vector<float> ns_tot_t, es_tot_t;
  if (!r.Get(&ns_total) || !r.Get(&es_total) || !get_floats(&ns_tot_t) ||
      !get_floats(&es_tot_t))
    return Status::IOError("truncated store aux (sampler totals)");

  // columns
  ET_RETURN_IF_ERROR(AttachCol(s, "node_ids", &g->node_ids_));
  ET_RETURN_IF_ERROR(AttachCol(s, "node_types", &g->node_types_));
  ET_RETURN_IF_ERROR(AttachCol(s, "node_weights", &g->node_weights_));
  ET_RETURN_IF_ERROR(AttachCol(s, "dense_idx", &g->dense_idx_));
  ET_RETURN_IF_ERROR(AttachCol(s, "graph_labels", &g->graph_labels_));
  ET_RETURN_IF_ERROR(AttachCol(s, "adj_offsets", &g->adj_offsets_));
  ET_RETURN_IF_ERROR(AttachCol(s, "adj_nbr", &g->adj_nbr_));
  ET_RETURN_IF_ERROR(AttachCol(s, "adj_w", &g->adj_w_));
  ET_RETURN_IF_ERROR(AttachCol(s, "adj_cumw", &g->adj_cumw_));
  ET_RETURN_IF_ERROR(AttachCol(s, "in_adj_offsets", &g->in_adj_offsets_));
  ET_RETURN_IF_ERROR(AttachCol(s, "in_adj_nbr", &g->in_adj_nbr_));
  ET_RETURN_IF_ERROR(AttachCol(s, "in_adj_w", &g->in_adj_w_));
  ET_RETURN_IF_ERROR(AttachCol(s, "in_adj_cumw", &g->in_adj_cumw_));

  const int NT = std::max(1, g->meta_.num_node_types);
  const int ET = std::max(1, g->meta_.num_edge_types);
  g->nodes_by_type_.resize(NT);
  for (int t = 0; t < NT; ++t)
    ET_RETURN_IF_ERROR(
        AttachCol(s, "nbt_" + std::to_string(t), &g->nodes_by_type_[t]));
  g->edges_by_type_.resize(ET);
  for (int t = 0; t < ET; ++t)
    ET_RETURN_IF_ERROR(
        AttachCol(s, "ebt_" + std::to_string(t), &g->edges_by_type_[t]));

  auto attach_sampler = [&s](const std::string& p_name,
                             const std::string& a_name, float total,
                             AliasSampler* samp) -> Status {
    const float* prob = nullptr;
    const uint32_t* alias = nullptr;
    size_t np = 0, na = 0;
    if (!s.Find(p_name, &prob, &np) || !s.Find(a_name, &alias, &na))
      return Status::IOError("store missing sampler " + p_name);
    if (np != na) return Status::IOError("sampler size mismatch " + p_name);
    samp->Attach(prob, alias, np, total);
    return Status::OK();
  };
  ET_RETURN_IF_ERROR(
      attach_sampler("nsp_all", "nsa_all", ns_total, &g->node_sampler_all_));
  ET_RETURN_IF_ERROR(
      attach_sampler("esp_all", "esa_all", es_total, &g->edge_sampler_all_));
  if (ns_tot_t.size() != static_cast<size_t>(NT) ||
      es_tot_t.size() != static_cast<size_t>(ET))
    return Status::IOError("store sampler totals do not match type counts");
  g->node_sampler_by_type_.resize(NT);
  for (int t = 0; t < NT; ++t)
    ET_RETURN_IF_ERROR(attach_sampler("nsp_" + std::to_string(t),
                                      "nsa_" + std::to_string(t), ns_tot_t[t],
                                      &g->node_sampler_by_type_[t]));
  g->edge_sampler_by_type_.resize(ET);
  for (int t = 0; t < ET; ++t)
    ET_RETURN_IF_ERROR(attach_sampler("esp_" + std::to_string(t),
                                      "esa_" + std::to_string(t), es_tot_t[t],
                                      &g->edge_sampler_by_type_[t]));

  size_t nnf = g->meta_.node_features.size();
  size_t nef = g->meta_.edge_features.size();
  g->node_dense_.resize(nnf);
  g->node_var_.resize(nnf);
  for (size_t f = 0; f < nnf; ++f) {
    ET_RETURN_IF_ERROR(
        AttachCol(s, "nd_" + std::to_string(f), &g->node_dense_[f]));
    ET_RETURN_IF_ERROR(
        AttachCol(s, "nvo_" + std::to_string(f), &g->node_var_[f].offsets));
    ET_RETURN_IF_ERROR(
        AttachCol(s, "nvu_" + std::to_string(f), &g->node_var_[f].values_u64));
    ET_RETURN_IF_ERROR(AttachCol(s, "nvb_" + std::to_string(f),
                                 &g->node_var_[f].values_bytes));
  }
  g->edge_dense_.resize(nef);
  g->edge_var_.resize(nef);
  for (size_t f = 0; f < nef; ++f) {
    ET_RETURN_IF_ERROR(
        AttachCol(s, "ed_" + std::to_string(f), &g->edge_dense_[f]));
    ET_RETURN_IF_ERROR(
        AttachCol(s, "evo_" + std::to_string(f), &g->edge_var_[f].offsets));
    ET_RETURN_IF_ERROR(
        AttachCol(s, "evu_" + std::to_string(f), &g->edge_var_[f].values_u64));
    ET_RETURN_IF_ERROR(AttachCol(s, "evb_" + std::to_string(f),
                                 &g->edge_var_[f].values_bytes));
  }

  // small derived state the store does not carry. CRITICAL: all reads
  // below go through `cg` — a non-const Col access (operator[]/data())
  // resolves to the OWNING-mode mutator overload, which silently
  // detaches the just-attached column back to an empty heap vector.
  const Graph& cg = *g;
  const size_t N = cg.node_ids_.size();
  if (cg.dense_idx_.empty()) {
    // no compact-id table: rebuild the hash fallback (O(N) heap — the
    // one index the out-of-core tier keeps in RAM for sparse id spaces)
    g->id2idx_.reserve(N);
    for (size_t i = 0; i < N; ++i)
      g->id2idx_[cg.node_ids_[i]] = static_cast<uint32_t>(i);
  }
  if (!cg.graph_labels_.empty()) {
    for (size_t i = 0; i < N && i < cg.graph_labels_.size(); ++i) {
      uint64_t gl = cg.graph_labels_[i];
      if (gl != 0) g->label_rows_[gl].push_back(static_cast<uint32_t>(i));
    }
    g->label_ids_.reserve(g->label_rows_.size());
    for (const auto& kv : g->label_rows_) g->label_ids_.push_back(kv.first);
    std::sort(g->label_ids_.begin(), g->label_ids_.end());
  }
  g->epoch_ = store->epoch();

  // storage tier: hub-first hot set + accounting
  auto tier = std::make_shared<StorageTier>(store);
  tier->n_rows_ = N;
  tier->num_edge_types_ = ET;
  tier->adj_offsets_ = cg.adj_offsets_.data();
  tier->adj_nbr_ = reinterpret_cast<const char*>(cg.adj_nbr_.data());
  tier->adj_w_ = reinterpret_cast<const char*>(cg.adj_w_.data());
  tier->adj_cumw_ = reinterpret_cast<const char*>(cg.adj_cumw_.data());
  for (size_t f = 0; f < cg.node_dense_.size(); ++f) {
    if (cg.node_dense_[f].empty() || N == 0) continue;
    tier->dense_rows_.push_back(
        {reinterpret_cast<const char*>(cg.node_dense_[f].data()),
         cg.node_dense_[f].size() / N * sizeof(float)});
  }
  tier->hot_bytes_ = hot_bytes;
  tier->hot_.assign((N + 63) / 64, 0);
  if (hot_bytes > 0 && N > 0 && !cg.adj_offsets_.empty()) {
    // hub-first: order rows by out-degree (the degree statistics the
    // device hub tables use) and pin until the byte budget is spent
    std::vector<std::pair<uint64_t, uint32_t>> by_deg(N);
    for (size_t i = 0; i < N; ++i) {
      uint64_t deg =
          cg.adj_offsets_[(i + 1) * ET] - cg.adj_offsets_[i * ET];
      by_deg[i] = {deg, static_cast<uint32_t>(i)};
    }
    std::sort(by_deg.begin(), by_deg.end(),
              [](const auto& a, const auto& b) {
                return a.first != b.first ? a.first > b.first
                                          : a.second < b.second;
              });
    size_t dense_row_bytes = 0;
    for (const auto& dr : tier->dense_rows_) dense_row_bytes += dr.second;
    int64_t spent = 0;
    bool try_mlock = true;
    for (const auto& dv : by_deg) {
      uint32_t row = dv.second;
      int64_t row_bytes =
          static_cast<int64_t>(dv.first) * (8 + 4 + 4) +
          static_cast<int64_t>(dense_row_bytes) + 8 /* node arrays */;
      if (spent + row_bytes > hot_bytes && tier->hot_rows_ > 0) break;
      tier->hot_[row >> 6] |= 1ULL << (row & 63);
      ++tier->hot_rows_;
      spent += row_bytes;
      // pre-fault + advise + best-effort mlock of the row's adjacency
      uint64_t b = cg.adj_offsets_[static_cast<uint64_t>(row) * ET];
      uint64_t e = cg.adj_offsets_[static_cast<uint64_t>(row + 1) * ET];
      auto pin = [&](const char* base, size_t lo, size_t hi) {
        if (base == nullptr || hi <= lo) return;
        uintptr_t start = reinterpret_cast<uintptr_t>(base + lo) & ~(kPage - 1);
        uintptr_t end = reinterpret_cast<uintptr_t>(base + hi);
        madvise(reinterpret_cast<void*>(start), end - start, MADV_WILLNEED);
        volatile const char* p = base + lo;
        for (size_t o = 0; o < hi - lo; o += kPage) (void)p[o];
        (void)p[hi - lo - 1];
        if (try_mlock &&
            mlock(reinterpret_cast<void*>(start), end - start) == 0) {
          tier->mlocked_bytes_ += static_cast<int64_t>(end - start);
        } else if (try_mlock) {
          try_mlock = false;  // RLIMIT_MEMLOCK exhausted: touch-only
        }
      };
      pin(tier->adj_nbr_, b * 8, e * 8);
      pin(tier->adj_w_, b * 4, e * 4);
      pin(tier->adj_cumw_, b * 4, e * 4);
      for (const auto& dr : tier->dense_rows_)
        pin(dr.first, static_cast<size_t>(row) * dr.second,
            static_cast<size_t>(row + 1) * dr.second);
      if (spent >= hot_bytes) break;
    }
    tier->hot_pinned_bytes_ = spent;
  }
  tier->Register();  // tier fully built: publish to the gauge registry
  g->store_ = std::move(store);
  g->tier_ = tier;
  g->tier_raw_ = tier.get();
  GlobalStoreCounters().attaches.fetch_add(1);
  *out = std::move(g);
  return Status::OK();
}

Status LoadGraphFromStore(const std::string& path, int64_t hot_bytes,
                          std::unique_ptr<Graph>* out) {
  std::shared_ptr<ColumnarStore> store;
  ET_RETURN_IF_ERROR(ColumnarStore::Open(path, &store));
  ET_RETURN_IF_ERROR(StoreAccess::Attach(std::move(store), hot_bytes, out));
  ET_LOG(INFO) << "attached graph from columnar store " << path << " ("
               << (*out)->node_count() << " nodes, " << (*out)->edge_count()
               << " edges, hot_rows=" << (*out)->tier()->hot_rows() << ")";
  return Status::OK();
}

}  // namespace et
