// QueryProxy: one object that turns gremlin strings into result tensors.
//
// Capability parity with the reference's euler/client/query_proxy.*
// (SURVEY.md §2.1): Init picks local vs distribute mode from config
// (query_proxy.cc:34-41), boots the graph + index locally or a
// ClientManager remotely, owns the compiler, and RunGremlin compiles
// (cached) then executes on the shared thread pool (query_proxy.cc:213-233).
#ifndef EULER_TPU_QUERY_PROXY_H_
#define EULER_TPU_QUERY_PROXY_H_

#include <atomic>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "common.h"
#include "gql.h"
#include "graph.h"
#include "index.h"
#include "rpc.h"

namespace et {

class QueryProxy {
 public:
  // Local (embedded) mode over an existing in-memory graph.
  // index_spec: "" or "attr:hash_index,attr2:range_index".
  static Status NewLocal(std::shared_ptr<const Graph> graph,
                         const std::string& index_spec, uint64_t seed,
                         std::unique_ptr<QueryProxy>* out);
  // Streaming form: queries run against whatever snapshot the shared
  // ref currently holds, so an etg_apply_delta on the owning graph
  // handle is visible to every proxy bound to it (each execution pins
  // its snapshot; the index lazily rebuilds on an epoch bump).
  static Status NewLocal(std::shared_ptr<GraphRef> graph_ref,
                         const std::string& index_spec, uint64_t seed,
                         std::unique_ptr<QueryProxy>* out);

  // Distribute mode: endpoints either from a registry dir ("dir:<path>")
  // or a static spec ("hosts:<h:p,h:p,...>"). shard_num inferred from the
  // endpoint list.
  // mode: "distribute" (hash-sharded) or "graph_partition" (shards own
  // whole graphs; ops broadcast + ownership-filtered).
  static Status NewRemote(const std::string& endpoints, uint64_t seed,
                          const std::string& mode,
                          std::unique_ptr<QueryProxy>* out);

  // Compile + execute. Returns every alias tensor ("<as>:i") plus the
  // terminal outputs of the chain.
  Status RunGremlin(const std::string& query,
                    const std::map<std::string, Tensor>& inputs,
                    std::map<std::string, Tensor>* outputs);

  const GraphMeta& graph_meta() const;
  int shard_num() const {
    return client_ ? client_->shard_num() : 1;
  }

  // Persist the local-mode index (reference: serialized Index/ dir,
  // index_manager.h:34,54); load back via index_spec "load:<dir>".
  Status DumpIndex(const std::string& dir) const {
    if (!index_) return Status::InvalidArgument("no local index to dump");
    return index_->Dump(dir);
  }

  // Per-proxy query timing (aux parity: the reference's ad-hoc
  // TimmerBegin/GetTimmerInterval, euler/common/timmer.h — surfaced as
  // counters instead of log lines). All monotonically increasing.
  struct Stats {
    uint64_t queries = 0;     // RunGremlin calls completed
    uint64_t errors = 0;      // ... that returned a non-OK status
    uint64_t total_us = 0;    // wall time summed over calls
    uint64_t last_us = 0;     // wall time of the most recent call
  };
  Stats stats() const {
    return {queries_.load(), errors_.load(), total_us_.load(),
            last_us_.load()};
  }

  // ---- streaming deltas ----
  // Local mode: the ref's current epoch (exact). Distribute mode: the
  // highest epoch observed on any shard reply (passive piggyback;
  // DeltaSince refreshes it actively).
  uint64_t ObservedEpoch() const;
  // Apply a batched delta: local → rebuild + swap this ref (and orphan
  // the old snapshot's UDF cache entries); distribute → broadcast
  // kApplyDelta to every shard.
  Status ApplyDelta(const NodeId* node_ids, const int32_t* node_types,
                    const float* node_weights, size_t n_nodes,
                    const NodeId* edge_src, const NodeId* edge_dst,
                    const int32_t* edge_types, const float* edge_weights,
                    size_t n_edges, uint64_t* new_epoch);
  // Dirty-node union for epochs > from; *covered false → history gap,
  // treat everything as dirty.
  Status DeltaSince(uint64_t from, uint64_t* epoch, bool* covered,
                    std::vector<NodeId>* ids);

  // ---- elastic fleet (distribute mode only) ----
  // Install the epoch-versioned ownership map this client routes with
  // (registry-published spec; see OwnershipMap::Decode). Splits then
  // place ids by the map's owner lists (p2c over replicated
  // partitions) and every kExecute frame is stamped with the map epoch
  // so a server on a newer map refuses it ("stale ownership map").
  Status SetOwnership(const std::string& spec);
  uint64_t OwnershipEpoch() const {
    return client_ ? client_->map_epoch() : 0;
  }
  // Per-shard traffic: request + split-routed row counts (hot-shard
  // detection; rows carry the skew — every shard sees one REMOTE per
  // query). Fills min(cap, shard_num) entries of each, returns the
  // count filled (0 in local mode).
  int ShardStats(uint64_t* reqs, uint64_t* rows, int cap) const {
    return client_ ? client_->ShardTraffic(reqs, rows, cap) : 0;
  }

 private:
  QueryProxy() = default;

  Status RunGremlinTimed(const std::string& query,
                         const std::map<std::string, Tensor>& inputs,
                         std::map<std::string, Tensor>* outputs);

  std::shared_ptr<GraphRef> graph_ref_;         // local mode
  std::shared_ptr<IndexManager> index_;         // local mode
  std::string index_spec_;                      // local mode (rebuilds)
  uint64_t index_epoch_ = 0;   // epoch index_ was built against
  std::mutex index_mu_;        // guards index_/index_epoch_ lazy rebuild
  std::unique_ptr<ClientManager> client_;       // distribute mode
  std::unique_ptr<GqlCompiler> compiler_;
  uint64_t seed_ = 0;
  std::atomic<uint64_t> run_counter_{0};  // per-run RNG nonce
  std::atomic<uint64_t> queries_{0}, errors_{0}, total_us_{0}, last_us_{0};
};

}  // namespace et

#endif  // EULER_TPU_QUERY_PROXY_H_
