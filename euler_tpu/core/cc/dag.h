// Query dataflow IR + dependency-counting parallel executor.
//
// Capability parity with the reference's euler/core/dag/ (runtime DAG),
// euler/core/dag_def/ (mutable rewrite IR) and euler/core/framework/
// executor.cc (SURVEY.md §2.1). Redesigned: one NodeDef struct serves as
// both the rewrite IR and the runtime node (the reference's DAGProto round
// trip is replaced by direct construction); dependencies are resolved from
// tensor names ("producer:idx"), so inserting split/REMOTE/merge nodes is
// just renaming inputs. The executor is the same design as the reference's
// (executor.cc:37-95): atomic remaining-dep counters, ready nodes scheduled
// onto a thread pool, async kernels chain through a done callback.
#ifndef EULER_TPU_DAG_H_
#define EULER_TPU_DAG_H_

#include <atomic>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "common.h"
#include "tensor.h"
#include "threadpool.h"

namespace et {

class Graph;
class IndexManager;
class ClientManager;

// One operator instance in a query plan. `inputs` are tensor names — either
// another node's output ("SAMPLE_NODE_1:0") or an externally provided query
// input. Outputs are implicitly named name+":i". Parity: reference
// DAGNodeProto {name, op, inputs, dnf, post_process, shard_idx, inner_nodes}
// (euler/proto/dag_node.proto:11-28).
struct NodeDef {
  std::string name;
  std::string op;
  std::vector<std::string> inputs;
  // Positional op-specific string attributes (edge types, counts, feature
  // names...). Parsed by each kernel.
  std::vector<std::string> attrs;
  // Filter condition in disjunctive normal form: dnf[i] is a conjunction of
  // "attr cmp value" terms, e.g. {"price gt 3", "label eq A"}.
  std::vector<std::vector<std::string>> dnf;
  // Post-process directives: "order_by <field> <asc|desc>", "limit <k>",
  // "as <alias>".
  std::vector<std::string> post_process;
  // REMOTE only: target shard and the sub-plan to run there.
  int shard_idx = -1;
  std::vector<NodeDef> inner;
  // FUSED only: names of the subsumed inner nodes. The fused kernel puts
  // their tensors under the original "<name>:idx" names, and dependency
  // resolution treats this node as the producer of those names — so
  // consumers outside the fusion group need no rewriting. (Reference
  // analog: the subgraph-iso fusion pass, optimizer.h:96; here fusion is
  // a direct linear-chain collapse.)
  std::vector<std::string> also_produces;

  std::string OutName(int i) const { return name + ":" + std::to_string(i); }
};

// A mutable query plan: ordered list of NodeDefs with unique names.
// The GQL translator emits one, optimizer passes rewrite it in place.
//
// Concurrency contract (load-bearing for the server-side prepared-plan
// cache, rpc.h kFeatPrepared): once construction/rewrites finish, a
// DAGDef is READ-ONLY to execution — any number of Executors may run
// over one shared const DAGDef concurrently (each builds its own
// runtime node table; kernels receive const NodeDef&). A cached
// decoded plan is therefore executed in place, never copied per
// request.
struct DAGDef {
  std::vector<NodeDef> nodes;
  int next_id = 0;

  std::string UniqueName(const std::string& op) {
    return op + "_" + std::to_string(next_id++);
  }
  NodeDef* Find(const std::string& name) {
    for (auto& n : nodes)
      if (n.name == name) return &n;
    return nullptr;
  }
  const NodeDef* Find(const std::string& name) const {
    for (auto& n : nodes)
      if (n.name == name) return &n;
    return nullptr;
  }
};

// Everything a kernel may touch besides the context. Null members are
// simply unavailable in that mode (e.g. no ClientManager in local mode).
struct QueryEnv {
  const Graph* graph = nullptr;
  IndexManager* index = nullptr;
  ClientManager* client = nullptr;
  ThreadPool* pool = nullptr;
  uint64_t seed = 0;  // 0 → thread-local RNG; nonzero → deterministic
  // Per-execution counter mixed into kernel RNG streams so a seeded proxy
  // still draws fresh samples on every run (only the sequence across runs
  // is reproducible, not each run identical).
  uint64_t nonce = 0;
  // Absolute steady-clock deadline (µs) for this run; 0 = none. REMOTE
  // sub-calls propagate the remaining budget inside their v2 request
  // frames (rpc.h kFeatDeadline) so shards shed already-dead work.
  int64_t deadline_us = 0;
  // Ownership-map epoch captured at RUN START (0 = no map). REMOTE
  // sub-calls stamp it into their v2 request frames (kFeatMapEpoch).
  // Captured-then-stamped (not read live at write time) so a map flip
  // mid-run can only make the stamp OLDER than the map the split used
  // — a spurious, retried refusal — never newer (which would slip a
  // stale-routed read past the server's one-sided check).
  uint64_t map_epoch = 0;
  // Wire trace context for this run (0 = untraced). REMOTE sub-calls
  // stamp it into their v2 request frames (kFeatTrace) so the shard's
  // timing breakdown carries the client's trace/span ids — every wire
  // attempt of one run (retries, hedge legs) shares the same context
  // and the server mints a distinct span per request.
  uint64_t trace_id = 0;
  uint64_t trace_parent = 0;
};

// Stateless kernel; one singleton per op name serves all queries
// concurrently. Parity: reference OpKernel/AsyncOpKernel
// (framework/op_kernel.h:38,59) — collapsed into one async signature; sync
// kernels just call done inline.
class OpKernel {
 public:
  virtual ~OpKernel() = default;
  virtual void Compute(const NodeDef& node, const QueryEnv& env,
                       OpKernelContext* ctx,
                       std::function<void(Status)> done) = 0;
};

// Global op registry. Parity: REGISTER_OP_KERNEL (op_kernel.h:106).
OpKernel* LookupKernel(const std::string& op);
void RegisterKernel(const std::string& op, std::unique_ptr<OpKernel> k);

template <typename K>
struct KernelRegistrar {
  explicit KernelRegistrar(const char* op) {
    RegisterKernel(op, std::unique_ptr<OpKernel>(new K()));
  }
};
#define ET_REGISTER_KERNEL(op, K) \
  static ::et::KernelRegistrar<K> et_reg_##K(op)

// Executes a DAGDef against a context: resolves tensor-name dependencies,
// schedules ready nodes on the pool, calls done(status) once all nodes
// finish (or the first error aborts). One Executor per query; safe to
// delete after done fires.
class Executor {
 public:
  Executor(const DAGDef* dag, const QueryEnv& env, OpKernelContext* ctx);

  // Asynchronous; done is invoked exactly once, possibly on a pool thread.
  void Run(std::function<void(Status)> done);

  // Convenience: block until completion.
  Status RunSync();

 private:
  struct RtNode {
    const NodeDef* def;
    std::atomic<int> remaining;
    std::vector<int> successors;
    RtNode() : def(nullptr), remaining(0) {}
    RtNode(RtNode&& o) noexcept
        : def(o.def),
          remaining(o.remaining.load()),
          successors(std::move(o.successors)) {}
    RtNode& operator=(RtNode&& o) noexcept {
      def = o.def;
      remaining.store(o.remaining.load());
      successors = std::move(o.successors);
      return *this;
    }
  };

  void Dispatch(int idx);
  void OnNodeDone(int idx, const Status& s);

  const DAGDef* dag_;
  QueryEnv env_;
  OpKernelContext* ctx_;
  std::vector<RtNode> nodes_;
  std::atomic<int> remaining_nodes_;
  std::atomic<bool> failed_;
  std::mutex err_mu_;
  Status first_error_;
  std::function<void(Status)> done_;
};

// Topological order of node indices; returns false on a cycle.
bool TopologicSort(const DAGDef& dag, std::vector<int>* order);

}  // namespace et

#endif  // EULER_TPU_DAG_H_
