// Distributed plumbing kernels: split / REMOTE / merge / gather.
//
// Capability parity with the reference's distributed kernel set
// (SURVEY.md §2.1 "Graph op kernels", distributed plumbing:
// BROAD_CAST_SPLIT, ID_SPLIT hash-mod placement, SAMPLE_NODE_SPLIT
// weight-proportional count split, ID_UNIQUE, IDX_GATHER/DATA_GATHER,
// APPEND_MERGE/IDX_MERGE/DATA_MERGE/REGULAR_DATA_MERGE, and the async
// REMOTE op remote_op.cc:31,60-120). Redesigned around the row-aligned
// tensor conventions of kernels.cc: every merge is "reassemble rows in
// original input order from per-shard (positions, data) pairs", every
// gather is "expand unique-row results through an inverse index".
#include <algorithm>
#include <cstdlib>
#include <cstring>
#include <unordered_set>

#include "dag.h"
#include "gql.h"
#include "graph.h"
#include "index.h"
#include "kernels_common.h"
#include "rpc.h"
#include "tensor.h"

namespace et {
namespace {


// ---------------------------------------------------------------------------
// COLLECT — rebind inputs as this node's outputs (the rewrite's seam: the
// merge pipeline ends in a COLLECT named like the original op, so all
// downstream references keep working).
// ---------------------------------------------------------------------------
class CollectOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    for (size_t i = 0; i < node.inputs.size(); ++i)
      ctx->AddAlias(node.OutName(i), node.inputs[i]);
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("COLLECT", CollectOp);

// ---------------------------------------------------------------------------
// ID_SPLIT — attrs [partition_num, shard_num]; input ids → per shard s:
// ids (:2s) and original positions (:2s+1).
// ---------------------------------------------------------------------------
class IdSplitOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor ids_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &ids_t));
    int pn = std::atoi(node.attrs[0].c_str());
    int sn = std::atoi(node.attrs[1].c_str());
    const uint64_t* ids = ids_t.Flat<uint64_t>();
    int64_t n = ids_t.NumElements();
    // elastic fleet: an installed ownership map replaces the hash
    // placement — one owner pick per partition for this batch (p2c over
    // replicated partitions' owners). Empty picks → hash convention.
    std::vector<int> picks;
    if (env.client != nullptr && !env.client->PickOwners(&picks))
      picks.clear();
    const uint64_t mp = picks.size();
    std::vector<std::vector<uint64_t>> sids(sn);
    std::vector<std::vector<int32_t>> spos(sn);
    for (int64_t i = 0; i < n; ++i) {
      int s = mp ? picks[ids[i] % mp] : ShardOf(ids[i], pn, sn);
      if (s < 0 || s >= sn) s = ShardOf(ids[i], pn, sn);  // defensive
      sids[s].push_back(ids[i]);
      spos[s].push_back(static_cast<int32_t>(i));
    }
    for (int s = 0; s < sn; ++s) {
      // routed-row accounting: the hot-shard detection signal (every
      // shard sees one REMOTE per query regardless; rows carry skew)
      if (env.client != nullptr && !sids[s].empty())
        env.client->CountRoutedRows(s, sids[s].size());
      ctx->Put(node.OutName(2 * s), Tensor::FromVector(sids[s]));
      ctx->Put(node.OutName(2 * s + 1), Tensor::FromVector(spos[s]));
    }
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("ID_SPLIT", IdSplitOp);

// TRIPLE_SPLIT — attrs [pn, sn]; inputs src,dst,type → per shard:
// src(:4s) dst(:4s+1) type(:4s+2) pos(:4s+3). Placement by src owner.
class TripleSplitOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor src_t, dst_t, tt;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &src_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 1, &dst_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 2, &tt));
    int pn = std::atoi(node.attrs[0].c_str());
    int sn = std::atoi(node.attrs[1].c_str());
    const uint64_t* src = src_t.Flat<uint64_t>();
    const uint64_t* dst = dst_t.Flat<uint64_t>();
    const int32_t* typ = tt.Flat<int32_t>();
    int64_t n = src_t.NumElements();
    // ownership-map routing by the edge's SOURCE owner (the placement
    // convention); hash fallback without a map — see IdSplitOp
    std::vector<int> picks;
    if (env.client != nullptr && !env.client->PickOwners(&picks))
      picks.clear();
    const uint64_t mp = picks.size();
    std::vector<std::vector<uint64_t>> ss(sn), sd(sn);
    std::vector<std::vector<int32_t>> st(sn), sp(sn);
    for (int64_t i = 0; i < n; ++i) {
      int s = mp ? picks[src[i] % mp] : ShardOf(src[i], pn, sn);
      if (s < 0 || s >= sn) s = ShardOf(src[i], pn, sn);  // defensive
      ss[s].push_back(src[i]);
      sd[s].push_back(dst[i]);
      st[s].push_back(typ[i]);
      sp[s].push_back(static_cast<int32_t>(i));
    }
    for (int s = 0; s < sn; ++s) {
      if (env.client != nullptr && !ss[s].empty())
        env.client->CountRoutedRows(s, ss[s].size());
      ctx->Put(node.OutName(4 * s), Tensor::FromVector(ss[s]));
      ctx->Put(node.OutName(4 * s + 1), Tensor::FromVector(sd[s]));
      ctx->Put(node.OutName(4 * s + 2), Tensor::FromVector(st[s]));
      ctx->Put(node.OutName(4 * s + 3), Tensor::FromVector(sp[s]));
    }
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("TRIPLE_SPLIT", TripleSplitOp);

// TYPES_SPLIT — attrs [sn]; input per-row node types; each row is assigned
// a shard ∝ that shard's weight for the row's type (reference
// weight-proportional sampling, query_proxy.cc:77-105).
class TypesSplitOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor types_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &types_t));
    int sn = std::atoi(node.attrs[0].c_str());
    const int32_t* types = types_t.Flat<int32_t>();
    int64_t n = types_t.NumElements();
    Pcg32 rng = NodeRng(node, env);
    std::vector<std::vector<int32_t>> st(sn);
    std::vector<std::vector<int32_t>> sp(sn);
    std::vector<float> cum(sn);
    for (int64_t i = 0; i < n; ++i) {
      float total = 0;
      for (int s = 0; s < sn; ++s) {
        float w = env.client != nullptr ? env.client->NodeWeight(s, types[i])
                                        : 1.f;
        total += w;
        cum[s] = total;
      }
      int pick = sn - 1;
      if (total > 0) {
        float r = rng.NextFloat() * total;
        pick = static_cast<int>(
            std::lower_bound(cum.begin(), cum.end(), r) - cum.begin());
        if (pick >= sn) pick = sn - 1;
      }
      st[pick].push_back(types[i]);
      sp[pick].push_back(static_cast<int32_t>(i));
    }
    for (int s = 0; s < sn; ++s) {
      ctx->Put(node.OutName(2 * s), Tensor::FromVector(st[s]));
      ctx->Put(node.OutName(2 * s + 1), Tensor::FromVector(sp[s]));
    }
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("TYPES_SPLIT", TypesSplitOp);

// SAMPLE_SPLIT — attrs [node|edge, count, type]; optional input count
// scalar. Outputs per shard :s = i64 count, multinomial ∝ shard weight
// (reference SAMPLE_NODE_SPLIT, sample_node_split_op.cc).
class SampleSplitOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    bool edge = node.attrs[0] == "edge";
    bool glabel = node.attrs[0] == "glabel";
    int64_t count = std::atoll(node.attrs[1].c_str());
    int type = std::atoi(node.attrs[2].c_str());
    // 4th attr "owned": hash-distribute sampleGL — split by labels each
    // shard OWNS (label % shard_num), not labels present, so a label
    // spanning shards isn't drawn multiple times its fair share.
    bool owned = node.attrs.size() > 3 && node.attrs[3] == "owned";
    if (!node.inputs.empty()) {
      Tensor t;
      if (ctx->Get(node.inputs[0], &t) && t.NumElements() > 0)
        count = t.AsI64(0);
    }
    int sn = env.client != nullptr ? env.client->shard_num() : 1;
    std::vector<float> cum(sn);
    float total = 0;
    for (int s = 0; s < sn; ++s) {
      float w = 1.f;
      if (env.client != nullptr)
        w = glabel ? env.client->GraphLabelWeight(s, owned)
                   : (edge ? env.client->EdgeWeight(s, type)
                           : env.client->NodeWeight(s, type));
      total += w;
      cum[s] = total;
    }
    std::vector<int64_t> counts(sn, 0);
    Pcg32 rng = NodeRng(node, env);
    for (int64_t i = 0; i < count; ++i) {
      int pick = sn - 1;
      if (total > 0) {
        float r = rng.NextFloat() * total;
        pick = static_cast<int>(
            std::lower_bound(cum.begin(), cum.end(), r) - cum.begin());
        if (pick >= sn) pick = sn - 1;
      }
      counts[pick]++;
    }
    for (int s = 0; s < sn; ++s)
      ctx->Put(node.OutName(s), Tensor::Scalar<int64_t>(counts[s]));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("SAMPLE_SPLIT", SampleSplitOp);

// ---------------------------------------------------------------------------
// merges
// ---------------------------------------------------------------------------
// APPEND_MERGE — concat inputs along dim 0.
class AppendMergeOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    std::vector<Tensor> ins(node.inputs.size());
    int64_t total = 0;
    for (size_t i = 0; i < node.inputs.size(); ++i) {
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, i, &ins[i]));
      total += ins[i].NumElements();
    }
    Tensor out(ins[0].dtype(), {total});
    uint8_t* p = out.raw();
    for (auto& t : ins) {
      std::memcpy(p, t.raw(), t.ByteSize());
      p += t.ByteSize();
    }
    ctx->Put(node.OutName(0), std::move(out));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("APPEND_MERGE", AppendMergeOp);

// REGULAR_MERGE — attrs [row_elems]; inputs per shard (pos, data).
// Scatter fixed-size rows back to original positions.
class RegularMergeOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    int64_t row = std::atoll(node.attrs[0].c_str());
    size_t ns = node.inputs.size() / 2;
    int64_t n = 0;
    std::vector<Tensor> pos(ns), data(ns);
    for (size_t s = 0; s < ns; ++s) {
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 2 * s, &pos[s]));
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 2 * s + 1, &data[s]));
      n += pos[s].NumElements();
    }
    DType dt = data[0].dtype();
    size_t esz = DTypeSize(dt) * row;
    Tensor out(dt, {n * row});
    for (size_t s = 0; s < ns; ++s) {
      const int32_t* p = pos[s].Flat<int32_t>();
      for (int64_t j = 0; j < pos[s].NumElements(); ++j)
        std::memcpy(out.raw() + p[j] * esz, data[s].raw() + j * esz, esz);
    }
    ctx->Put(node.OutName(0), std::move(out));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("REGULAR_MERGE", RegularMergeOp);

// RAGGED_MERGE — attrs [P]; inputs per shard: pos, idx, P payloads.
// Rebuild ragged rows in original order → idx (:0) + payloads (:1..P).
class RaggedMergeOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    int P = std::atoi(node.attrs[0].c_str());
    size_t stride = 2 + P;
    size_t ns = node.inputs.size() / stride;
    std::vector<Tensor> pos(ns), idx(ns);
    std::vector<std::vector<Tensor>> pay(ns, std::vector<Tensor>(P));
    int64_t n = 0;
    for (size_t s = 0; s < ns; ++s) {
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, stride * s, &pos[s]));
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, stride * s + 1, &idx[s]));
      for (int p = 0; p < P; ++p)
        ET_K_RETURN_IF_ERROR(GetInput(ctx, node, stride * s + 2 + p,
                                   &pay[s][p]));
      n += pos[s].NumElements();
    }
    // global row → (shard, local row)
    std::vector<std::pair<int32_t, int32_t>> where(n);
    for (size_t s = 0; s < ns; ++s) {
      const int32_t* p = pos[s].Flat<int32_t>();
      for (int64_t j = 0; j < pos[s].NumElements(); ++j)
        where[p[j]] = {static_cast<int32_t>(s), static_cast<int32_t>(j)};
    }
    Tensor out_idx(DType::kI32, {n, 2});
    int32_t* oi = out_idx.Flat<int32_t>();
    int64_t cursor = 0;
    for (int64_t i = 0; i < n; ++i) {
      auto [s, j] = where[i];
      const int32_t* si = idx[s].Flat<int32_t>();
      int64_t len = si[2 * j + 1] - si[2 * j];
      oi[2 * i] = static_cast<int32_t>(cursor);
      oi[2 * i + 1] = static_cast<int32_t>(cursor + len);
      cursor += len;
    }
    ET_K_RETURN_IF_ERROR(CheckI32Offsets(node, cursor));
    std::vector<Tensor> out_pay;
    for (int p = 0; p < P; ++p) {
      DType dt = pay[0][p].dtype();
      size_t esz = DTypeSize(dt);
      Tensor out(dt, {cursor});
      for (int64_t i = 0; i < n; ++i) {
        auto [s, j] = where[i];
        const int32_t* si = idx[s].Flat<int32_t>();
        int64_t b = si[2 * j], e = si[2 * j + 1];
        std::memcpy(out.raw() + oi[2 * i] * esz, pay[s][p].raw() + b * esz,
                    (e - b) * esz);
      }
      out_pay.push_back(std::move(out));
    }
    ctx->Put(node.OutName(0), std::move(out_idx));
    for (int p = 0; p < P; ++p)
      ctx->Put(node.OutName(1 + p), std::move(out_pay[p]));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("RAGGED_MERGE", RaggedMergeOp);

// REGULAR_GATHER — attrs [row_elems]; inputs inv i32[n], data → out row i =
// data row inv[i].
class RegularGatherOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor inv_t, data;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &inv_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 1, &data));
    int64_t row = std::atoll(node.attrs[0].c_str());
    const int32_t* inv = inv_t.Flat<int32_t>();
    int64_t n = inv_t.NumElements();
    size_t esz = DTypeSize(data.dtype()) * row;
    Tensor out(data.dtype(), {n * row});
    for (int64_t i = 0; i < n; ++i)
      std::memcpy(out.raw() + i * esz, data.raw() + inv[i] * esz, esz);
    ctx->Put(node.OutName(0), std::move(out));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("REGULAR_GATHER", RegularGatherOp);

// RAGGED_GATHER — attrs [P]; inputs inv, idx_u, P payloads (unique-aligned)
// → expanded idx + payloads for the original rows.
class RaggedGatherOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    int P = std::atoi(node.attrs[0].c_str());
    Tensor inv_t, idx_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &inv_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 1, &idx_t));
    std::vector<Tensor> pay(P);
    for (int p = 0; p < P; ++p)
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 2 + p, &pay[p]));
    const int32_t* inv = inv_t.Flat<int32_t>();
    const int32_t* ui = idx_t.Flat<int32_t>();
    int64_t n = inv_t.NumElements();
    Tensor out_idx(DType::kI32, {n, 2});
    int32_t* oi = out_idx.Flat<int32_t>();
    int64_t cursor = 0;
    for (int64_t i = 0; i < n; ++i) {
      int64_t len = ui[2 * inv[i] + 1] - ui[2 * inv[i]];
      oi[2 * i] = static_cast<int32_t>(cursor);
      oi[2 * i + 1] = static_cast<int32_t>(cursor + len);
      cursor += len;
    }
    ET_K_RETURN_IF_ERROR(CheckI32Offsets(node, cursor));
    for (int p = 0; p < P; ++p) {
      size_t esz = DTypeSize(pay[p].dtype());
      Tensor out(pay[p].dtype(), {cursor});
      for (int64_t i = 0; i < n; ++i) {
        int64_t b = ui[2 * inv[i]], e = ui[2 * inv[i] + 1];
        std::memcpy(out.raw() + oi[2 * i] * esz, pay[p].raw() + b * esz,
                    (e - b) * esz);
      }
      ctx->Put(node.OutName(1 + p), std::move(out));
    }
    ctx->Put(node.OutName(0), std::move(out_idx));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("RAGGED_GATHER", RaggedGatherOp);

// POOL_MERGE — attrs [m, default_id]; inputs are per-shard
// (pool ids [m_s], candidate mass [1]) pairs from single-layer
// API_SAMPLE_L(emit_wsum) clones. Each output slot draws a shard
// ∝ its candidate mass, then a uniform entry from that shard's pool —
// shard pools are already weighted-with-replacement draws over the
// shard-local candidates, so for the identity weight_func the merge
// reproduces the GLOBAL weighted-with-replacement distribution exactly
// (the embedded engine's semantics). With weight_func=sqrt the
// transform is applied to each shard's PARTIAL accumulation, so a
// candidate whose frontier predecessors span shards gets
// sqrt(w0)+sqrt(w1) rather than sqrt(w0+w1) — the same semantics as
// the reference's distributed lowering (local_sample_layer_op.cc runs
// per shard over shard-local edges), documented rather than hidden.
// Zero-mass shards (no local candidates — their pools are all
// default_id pads) are never drawn unless every shard is empty.
class PoolMergeOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    int64_t m = std::atoll(node.attrs[0].c_str());
    uint64_t default_id =
        node.attrs.size() > 1
            ? std::strtoull(node.attrs[1].c_str(), nullptr, 10)
            : 0;
    size_t ns = node.inputs.size() / 2;
    std::vector<Tensor> pools(ns);
    std::vector<float> mass(ns);
    std::vector<float> cum(ns);
    float total = 0.f;
    for (size_t s = 0; s < ns; ++s) {
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 2 * s, &pools[s]));
      Tensor w;
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 2 * s + 1, &w));
      mass[s] = w.NumElements() ? w.Flat<float>()[0] : 0.f;
      if (mass[s] < 0 || pools[s].NumElements() == 0) mass[s] = 0.f;
      total += mass[s];
      cum[s] = total;
    }
    Pcg32 rng = NodeRng(node, env);
    Tensor out(DType::kU64, {m});
    uint64_t* o = out.Flat<uint64_t>();
    if (total <= 0.f) {
      for (int64_t i = 0; i < m; ++i) o[i] = default_id;
    } else {
      for (int64_t i = 0; i < m; ++i) {
        float r = rng.NextFloat() * total;
        // upper_bound (first cum > r): r == 0 with leading zero-mass
        // shards must still land on the first POSITIVE-mass shard
        size_t s = std::upper_bound(cum.begin(), cum.end(), r) - cum.begin();
        if (s >= ns) s = ns - 1;
        const uint64_t* p = pools[s].Flat<uint64_t>();
        o[i] = p[rng.NextUInt(pools[s].NumElements())];
      }
    }
    ctx->Put(node.OutName(0), std::move(out));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("POOL_MERGE", PoolMergeOp);

// FILTER_MERGE — inputs per shard (pos, surviving ids, local survivor
// positions) → (ids, positions) ordered by original position.
class FilterMergeOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    size_t ns = node.inputs.size() / 3;
    std::vector<std::pair<int32_t, uint64_t>> rows;
    for (size_t s = 0; s < ns; ++s) {
      Tensor pos, ids, lpos;
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 3 * s, &pos));
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 3 * s + 1, &ids));
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 3 * s + 2, &lpos));
      const int32_t* p = pos.Flat<int32_t>();
      const uint64_t* id = ids.Flat<uint64_t>();
      const int32_t* lp = lpos.Flat<int32_t>();
      for (int64_t j = 0; j < ids.NumElements(); ++j)
        rows.emplace_back(p[lp[j]], id[j]);
    }
    std::sort(rows.begin(), rows.end());
    std::vector<uint64_t> out_ids;
    std::vector<int32_t> out_pos;
    for (auto& r : rows) {
      out_pos.push_back(r.first);
      out_ids.push_back(r.second);
    }
    ctx->Put(node.OutName(0), Tensor::FromVector(out_ids));
    ctx->Put(node.OutName(1), Tensor::FromVector(out_pos));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("FILTER_MERGE", FilterMergeOp);

// QUAD_FILTER_APPLY — inputs idx, ids, w, t, keep_ids → quad restricted to
// the membership set.
class QuadFilterApplyOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor idx_t, ids_t, w_t, t_t, keep_t;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &idx_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 1, &ids_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 2, &w_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 3, &t_t));
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 4, &keep_t));
    std::unordered_set<uint64_t> keep;
    const uint64_t* kp = keep_t.Flat<uint64_t>();
    for (int64_t i = 0; i < keep_t.NumElements(); ++i) keep.insert(kp[i]);
    int64_t n = idx_t.dim(0);
    const int32_t* pidx = idx_t.Flat<int32_t>();
    const uint64_t* ids = ids_t.Flat<uint64_t>();
    const float* w = w_t.Flat<float>();
    const int32_t* t = t_t.Flat<int32_t>();
    std::vector<uint64_t> offs{0};
    std::vector<uint64_t> oid;
    std::vector<float> ow;
    std::vector<int32_t> ot;
    for (int64_t i = 0; i < n; ++i) {
      for (int32_t j = pidx[2 * i]; j < pidx[2 * i + 1]; ++j) {
        if (keep.count(ids[j]) == 0) continue;
        oid.push_back(ids[j]);
        ow.push_back(w[j]);
        ot.push_back(t[j]);
      }
      offs.push_back(oid.size());
    }
    ET_K_RETURN_IF_ERROR(
        CheckI32Offsets(node, static_cast<int64_t>(offs.back())));
    Tensor out_idx(DType::kI32, {n, 2});
    int32_t* oi = out_idx.Flat<int32_t>();
    for (int64_t i = 0; i < n; ++i) {
      oi[2 * i] = static_cast<int32_t>(offs[i]);
      oi[2 * i + 1] = static_cast<int32_t>(offs[i + 1]);
    }
    ctx->Put(node.OutName(0), std::move(out_idx));
    ctx->Put(node.OutName(1), Tensor::FromVector(oid));
    ctx->Put(node.OutName(2), Tensor::FromVector(ow));
    ctx->Put(node.OutName(3), Tensor::FromVector(ot));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("QUAD_FILTER_APPLY", QuadFilterApplyOp);

// ---------------------------------------------------------------------------
// REMOTE — ship inputs + inner sub-DAG to shard_idx, decode replies
// (reference remote_op.cc:60-120). Async: the RPC runs on the pool via
// ClientManager::ExecuteAsync; with no ClientManager (single-process
// tests) the inner plan runs loopback against the local graph.
//
// Prepared plans (RpcConfig::prepared, rpc.h kFeatPrepared): the inner
// sub-DAG + output names a training loop re-ships every step are the
// content-stable PLAN half of this request — ClientManager::Execute
// splits it from the feed tensors, registers it once per connection,
// and stamps its content-hash id on EVERY wire attempt of this call
// (transport retries, mux-hedge legs, replica-hedge legs all carry the
// same id), so steady-state kExecute frames ship feeds only.
// ---------------------------------------------------------------------------
class RemoteOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    ExecuteRequest req;
    for (const auto& in : node.inputs) {
      Tensor t;
      if (!ctx->Get(in, &t)) {
        done(Status::NotFound("REMOTE input not produced: " + in));
        return;
      }
      req.inputs.emplace_back(in, std::move(t));
    }
    req.nodes = node.inner;
    req.outputs = node.attrs;

    if (env.client == nullptr) {
      // loopback: execute the inner plan against the local graph. Fully
      // async — blocking here would park an executor thread while the
      // inner nodes wait for the same pool (deadlock once every thread
      // holds a blocked REMOTE).
      auto inner_ctx = std::make_shared<OpKernelContext>();
      for (auto& kv : req.inputs) inner_ctx->Put(kv.first, kv.second);
      auto dag = std::make_shared<DAGDef>();
      dag->nodes = req.nodes;
      QueryEnv inner_env = env;
      auto exec = std::make_shared<Executor>(dag.get(), inner_env,
                                             inner_ctx.get());
      auto outputs = req.outputs;
      std::string out_name = node.name;
      // exec/dag/inner_ctx stay alive via the callback capture
      exec->Run([exec, dag, inner_ctx, outputs, out_name, ctx,
                 done = std::move(done)](Status s) {
        if (s.ok()) {
          for (size_t i = 0; i < outputs.size(); ++i) {
            Tensor t;
            if (!inner_ctx->Get(outputs[i], &t)) {
              s = Status::NotFound("REMOTE output missing: " + outputs[i]);
              break;
            }
            ctx->Put(out_name + ":" + std::to_string(i), std::move(t));
          }
        }
        done(s);
      });
      return;
    }

    std::string name = node.name;
    std::vector<std::string> outs = req.outputs;
    env.client->ExecuteAsync(
        node.shard_idx, std::move(req),
        [ctx, name, outs, done](Status s, ExecuteReply rep) {
          if (s.ok()) {
            for (size_t i = 0; i < rep.outputs.size() && i < outs.size();
                 ++i)
              ctx->Put(name + ":" + std::to_string(i),
                       std::move(rep.outputs[i].second));
          }
          done(s);
        },
        // propagate the run's remaining deadline, the run-start map
        // epoch, and the wire trace context inside the v2 frame: the
        // shard sheds already-dead work, refuses reads routed on a
        // superseded ownership map, and records its timing breakdown
        // under the caller's trace/span ids
        env.deadline_us, env.map_epoch,
        WireTrace{env.trace_id, env.trace_parent});
  }
};
ET_REGISTER_KERNEL("REMOTE", RemoteOp);


// ---------------------------------------------------------------------------
// GP_* merges — graph_partition mode (reference gp_unique_merge_op.cc and
// friends). Shards return (positions-into-the-broadcast-input, outputs);
// these kernels reassemble full-size results. Uncovered positions (ids no
// shard owns) become empty rows, or fixed pads with attr "pad:<k>:<def>".
// ---------------------------------------------------------------------------
// GP_RAGGED_MERGE — attrs [P, ("pad:k:def" | "concat")?]; inputs: base
// (defines n) + per shard (pos, idx, P payloads). out :0 iota pos,
// :1 idx [n,2], :2..1+P payloads. Default: one owner per position (gp
// mode). "concat": a position's row is the concatenation of every
// shard's row (hash-distribute mode, where one graph label's members
// scatter across shards).
class GpRaggedMergeOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    int P = std::atoi(node.attrs[0].c_str());
    int64_t pad_k = 0;
    uint64_t pad_def = 0;
    // concat_sort additionally sorts each merged row's u64 payload, so
    // shard-spanning rows come out in the same id order local mode emits
    // (only meaningful for P == 1: a per-payload sort would break
    // cross-payload row alignment).
    bool concat = node.attrs.size() > 1 &&
                  node.attrs[1].rfind("concat", 0) == 0;
    bool sort_rows = node.attrs.size() > 1 && node.attrs[1] == "concat_sort";
    if (node.attrs.size() > 1 && node.attrs[1].rfind("pad:", 0) == 0) {
      auto rest = node.attrs[1].substr(4);
      auto colon = rest.find(':');
      pad_k = std::atoll(rest.substr(0, colon).c_str());
      if (colon != std::string::npos)
        pad_def = std::strtoull(rest.substr(colon + 1).c_str(), nullptr, 10);
    }
    Tensor base;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &base));
    int64_t n = base.dims().empty() ? base.NumElements() : base.dim(0);
    size_t stride = 2 + P;
    size_t ns = (node.inputs.size() - 1) / stride;
    std::vector<Tensor> pos(ns), idx(ns);
    std::vector<std::vector<Tensor>> pay(ns, std::vector<Tensor>(P));
    for (size_t s = 0; s < ns; ++s) {
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 1 + stride * s, &pos[s]));
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 1 + stride * s + 1, &idx[s]));
      for (int p = 0; p < P; ++p)
        ET_K_RETURN_IF_ERROR(
            GetInput(ctx, node, 1 + stride * s + 2 + p, &pay[s][p]));
    }
    // global row → contributing (shard, local row) pairs; empty =
    // uncovered. Default mode keeps only the last owner; concat keeps all.
    std::vector<std::vector<std::pair<int32_t, int32_t>>> where(n);
    for (size_t s = 0; s < ns; ++s) {
      const int32_t* p = pos[s].Flat<int32_t>();
      for (int64_t j = 0; j < pos[s].NumElements(); ++j) {
        if (p[j] < 0 || p[j] >= n) continue;
        if (!concat) where[p[j]].clear();
        where[p[j]].emplace_back(static_cast<int32_t>(s),
                                 static_cast<int32_t>(j));
      }
    }
    Tensor out_pos(DType::kI32, {n});
    Tensor out_idx(DType::kI32, {n, 2});
    int32_t* op_ = out_pos.Flat<int32_t>();
    int32_t* oi = out_idx.Flat<int32_t>();
    int64_t cursor = 0;
    for (int64_t i = 0; i < n; ++i) {
      op_[i] = static_cast<int32_t>(i);
      int64_t len = where[i].empty() ? pad_k : 0;
      for (auto [s, j] : where[i]) {
        const int32_t* si = idx[s].Flat<int32_t>();
        len += si[2 * j + 1] - si[2 * j];
      }
      oi[2 * i] = static_cast<int32_t>(cursor);
      oi[2 * i + 1] = static_cast<int32_t>(cursor + len);
      cursor += len;
    }
    ET_K_RETURN_IF_ERROR(CheckI32Offsets(node, cursor));
    for (int p = 0; p < P; ++p) {
      DType dt = DType::kU64;
      for (size_t s = 0; s < ns; ++s)
        if (pay[s][p].NumElements() > 0 || s + 1 == ns) {
          dt = pay[s][p].dtype();
          break;
        }
      size_t esz = DTypeSize(dt);
      Tensor out(dt, {cursor});
      for (int64_t i = 0; i < n; ++i) {
        uint8_t* dst = out.raw() + oi[2 * i] * esz;
        if (where[i].empty() && pad_k > 0) {
          // uncovered + fixed-count: pad like the local kernel would
          for (int64_t t = 0; t < pad_k; ++t) {
            if (dt == DType::kU64) {
              reinterpret_cast<uint64_t*>(dst)[t] = pad_def;
            } else if (dt == DType::kF32) {
              reinterpret_cast<float*>(dst)[t] = 0.f;
            } else {
              reinterpret_cast<int32_t*>(dst)[t] = -1;
            }
          }
          continue;
        }
        for (auto [s, j] : where[i]) {
          const int32_t* si = idx[s].Flat<int32_t>();
          int64_t b = si[2 * j], e = si[2 * j + 1];
          std::memcpy(dst, pay[s][p].raw() + b * esz, (e - b) * esz);
          dst += (e - b) * esz;
        }
        if (sort_rows && P == 1 && dt == DType::kU64) {
          uint64_t* row = reinterpret_cast<uint64_t*>(
              out.raw() + oi[2 * i] * esz);
          std::sort(row, row + (oi[2 * i + 1] - oi[2 * i]));
        }
      }
      ctx->Put(node.OutName(2 + p), std::move(out));
    }
    ctx->Put(node.OutName(0), std::move(out_pos));
    ctx->Put(node.OutName(1), std::move(out_idx));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("GP_RAGGED_MERGE", GpRaggedMergeOp);

// GP_FILTER_MERGE — inputs per shard (ids, pos); positions are already
// global (broadcast input). Union ordered by position.
class GpFilterMergeOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    size_t ns = node.inputs.size() / 2;
    std::vector<std::pair<int32_t, uint64_t>> rows;
    for (size_t s = 0; s < ns; ++s) {
      Tensor ids, pos;
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 2 * s, &ids));
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 2 * s + 1, &pos));
      const uint64_t* id = ids.Flat<uint64_t>();
      const int32_t* p = pos.Flat<int32_t>();
      for (int64_t j = 0; j < ids.NumElements(); ++j)
        rows.emplace_back(p[j], id[j]);
    }
    std::sort(rows.begin(), rows.end());
    std::vector<uint64_t> out_ids;
    std::vector<int32_t> out_pos;
    for (auto& r : rows) {
      out_pos.push_back(r.first);
      out_ids.push_back(r.second);
    }
    ctx->Put(node.OutName(0), Tensor::FromVector(out_ids));
    ctx->Put(node.OutName(1), Tensor::FromVector(out_pos));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("GP_FILTER_MERGE", GpFilterMergeOp);

// GP_SCATTER_MERGE — inputs: base + per shard (pos, vals i32). out :0 =
// i32 [n], -1 where uncovered.
class GpScatterMergeOp : public OpKernel {
 public:
  void Compute(const NodeDef& node, const QueryEnv& env, OpKernelContext* ctx,
               std::function<void(Status)> done) override {
    Tensor base;
    ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 0, &base));
    int64_t n = base.NumElements();
    Tensor out(DType::kI32, {n});
    int32_t* o = out.Flat<int32_t>();
    for (int64_t i = 0; i < n; ++i) o[i] = -1;
    size_t ns = (node.inputs.size() - 1) / 2;
    for (size_t s = 0; s < ns; ++s) {
      Tensor pos, vals;
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 1 + 2 * s, &pos));
      ET_K_RETURN_IF_ERROR(GetInput(ctx, node, 1 + 2 * s + 1, &vals));
      const int32_t* p = pos.Flat<int32_t>();
      const int32_t* v = vals.Flat<int32_t>();
      for (int64_t j = 0; j < pos.NumElements(); ++j)
        if (p[j] >= 0 && p[j] < n) o[p[j]] = v[j];
    }
    ctx->Put(node.OutName(0), std::move(out));
    done(Status::OK());
  }
};
ET_REGISTER_KERNEL("GP_SCATTER_MERGE", GpScatterMergeOp);

}  // namespace
}  // namespace et
