// Out-of-core graph tier: mmap'd columnar store + hub-pinned hot set.
//
// The storage hierarchy beneath the immutable-snapshot GraphRef. A
// finalized Graph's big columns (CSR adjacency, feature matrices, alias
// tables — everything O(N) or O(E)) serialize verbatim into one
// columnar file; LoadGraphFromStore maps that file PROT_READ/MAP_SHARED
// and attaches every Col<T> (col.h) to it, producing a Graph that is
// byte-identical to its heap twin — same arrays, same row order, same
// alias tables, so every sampler draw and feature read matches the
// in-RAM engine exactly — while the page cache, not the heap, owns the
// bytes. RAM then holds only an explicit HOT SET, chosen hub-first by
// out-degree (the same degree statistics the device tables use): hub
// rows' adjacency + dense-feature pages are pre-faulted, advised
// MADV_WILLNEED, and mlock'd as far as RLIMIT_MEMLOCK allows.
//
// Who writes the file: WAL compaction (wal.cc DeltaWal::Compact) emits
// `columnar.etc` beside each snapshot generation when the sidecar is
// enabled — the on-disk tier's writer for free — and recovery/start
// paths write a boot store when attaching a graph that has none yet.
// A delta apply still builds its new snapshot on the heap (the RAM
// overlay above the mmap base); the next compaction re-spills it to a
// new columnar generation and the server re-attaches.
//
// Accounting (the observable half of the 10×-RAM claim):
//   * hot_hits / cold_reads — every row-addressed accessor classifies
//     the row against the hot bitmask (Graph::TouchRow); hub reads
//     never count as cold.
//   * cold-read latency — a cold row's adjacency pages are touched
//     (pre-faulted) under a timer; the log2-µs histogram rides the
//     ServerTraceStats bucket convention (rpc.h LatencyHist).
//   * page_in / page_out / resident_bytes — mincore() polling over the
//     mapping, diffed page-by-page between polls.
// All counters are process-global (StoreCounters, the WalCounters
// pattern) and exported through etg_store_stats / gql.store_stats().
#ifndef EULER_TPU_STORE_H_
#define EULER_TPU_STORE_H_

#include <atomic>
#include <cstdint>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "graph.h"
#include "rpc.h"  // LatencyHist — the shared log2-µs bucket convention

namespace et {

// Default sidecar file name, written beside meta.bin / part_*.dat in a
// data or snapshot directory.
extern const char kColumnarFileName[];  // "columnar.etc"

// Sidecar name for a shard's slice of a SHARED data directory:
// "columnar.etc" for the 1-shard case, otherwise
// "columnar.<idx>of<num>.etc" — co-located shards each spill/attach
// their own partition and never serve a sibling's. Snapshot dirs are
// per-shard already and keep the plain name.
std::string ColumnarSidecarName(int shard_idx, int shard_num);

// True when `sidecar_path` exists and is at least as new (mtime, ns
// precision) as every other regular file in `dir` — the partition
// files it was spilled from. Other sidecars / in-flight spills
// (*.etc*) are not source files and are ignored. Missing or stale →
// false: callers fall back to heap load + re-spill, so a re-dumped
// dataset can never be shadowed by an old sidecar.
bool SidecarIsFresh(const std::string& dir, const std::string& sidecar_path);

// Process-global out-of-core counters (obs mirrors them via
// etg_store_stats — same pattern as WalCounters/RpcCounters).
struct StoreCounters {
  std::atomic<uint64_t> hot_hits{0};    // row reads that hit the hot set
  std::atomic<uint64_t> cold_reads{0};  // row reads outside it
  std::atomic<uint64_t> page_in{0};     // pages that became resident
  std::atomic<uint64_t> page_out{0};    // pages the kernel evicted
  std::atomic<uint64_t> attaches{0};    // graphs attached over process life
  LatencyHist cold_hist;                // cold-read page-in latency (µs)
};
StoreCounters& GlobalStoreCounters();

// One mmap'd columnar store file. Owns the fd + mapping; Graphs attach
// their Col<T> members to the mapped columns and hold a shared_ptr so
// the mapping outlives every reader.
class ColumnarStore {
 public:
  ~ColumnarStore();

  static Status Open(const std::string& path,
                     std::shared_ptr<ColumnarStore>* out);

  struct Column {
    const void* data = nullptr;
    uint64_t count = 0;
    uint32_t elem_size = 0;
  };
  // Typed lookup; returns (nullptr, 0) for an absent or empty column —
  // attaching that yields an empty Col, which is exactly what an empty
  // vector serialized to.
  template <typename T>
  bool Find(const std::string& name, const T** ptr, size_t* n) const {
    auto it = cols_.find(name);
    if (it == cols_.end() || it->second.count == 0) {
      *ptr = nullptr;
      *n = 0;
      return it != cols_.end();
    }
    if (it->second.elem_size != sizeof(T)) {
      // size-mismatched column (corrupt or foreign store): reinterpreting
      // would index past the mapping — report absent so attach fails loudly
      *ptr = nullptr;
      *n = 0;
      return false;
    }
    *ptr = static_cast<const T*>(it->second.data);
    *n = static_cast<size_t>(it->second.count);
    return true;
  }
  bool Has(const std::string& name) const { return cols_.count(name) != 0; }
  // Raw aux blob (meta + scalars section).
  const Column* aux() const;

  const std::string& path() const { return path_; }
  uint64_t epoch() const { return epoch_; }
  const char* base() const { return base_; }
  size_t mapped_bytes() const { return mapped_bytes_; }

 private:
  ColumnarStore() = default;
  std::string path_;
  int fd_ = -1;
  const char* base_ = nullptr;
  size_t mapped_bytes_ = 0;
  uint64_t epoch_ = 0;
  std::unordered_map<std::string, Column> cols_;
};

// Hot-set accounting + residency tracking for one attached Graph.
// Immutable after Build (the hot bitmask never changes for a given
// snapshot); counters go to GlobalStoreCounters.
class StorageTier {
 public:
  explicit StorageTier(std::shared_ptr<ColumnarStore> store);
  ~StorageTier();

  // Row-access classification (Graph::TouchRow hook). Hot rows count a
  // hit and return immediately; cold rows count a read and pre-fault
  // the row's adjacency pages under the cold-read timer.
  void OnRowAccess(uint32_t row);

  bool IsHot(uint32_t row) const {
    return row < n_rows_ && ((hot_[row >> 6] >> (row & 63)) & 1) != 0;
  }
  size_t hot_rows() const { return hot_rows_; }
  int64_t hot_bytes_budget() const { return hot_bytes_; }
  int64_t hot_pinned_bytes() const { return hot_pinned_bytes_; }
  int64_t mlocked_bytes() const { return mlocked_bytes_; }
  size_t mapped_bytes() const { return store_->mapped_bytes(); }

  // mincore() poll over the whole mapping: returns resident bytes and
  // accumulates page_in/page_out deltas into the global counters.
  int64_t PollResidentBytes();

  // Sum of PollResidentBytes / mapped bytes / pinned bytes over every
  // live tier in the process (the etg_store_stats gauges).
  static void GlobalResidency(int64_t* resident, int64_t* mapped,
                              int64_t* hot_pinned);

 private:
  friend struct StoreAccess;  // Build() wiring (store.cc)

  // Publish to the residency-gauge registry; called by Attach only
  // after every field is built (a ctor-time insert would expose a
  // half-initialized tier to a concurrent GlobalResidency walk).
  void Register();

  std::shared_ptr<ColumnarStore> store_;
  size_t n_rows_ = 0;
  int num_edge_types_ = 1;
  const uint64_t* adj_offsets_ = nullptr;  // n_rows*ET + 1
  const char* adj_nbr_ = nullptr;   // spans touched on cold reads
  const char* adj_w_ = nullptr;
  const char* adj_cumw_ = nullptr;
  // per-row dense feature ranges: (base, bytes_per_row)
  std::vector<std::pair<const char*, size_t>> dense_rows_;
  std::vector<uint64_t> hot_;  // bitmask over rows
  size_t hot_rows_ = 0;
  int64_t hot_bytes_ = 0;
  int64_t hot_pinned_bytes_ = 0;
  int64_t mlocked_bytes_ = 0;
  std::mutex resid_mu_;
  std::vector<unsigned char> prev_resident_;  // mincore bitmap, last poll
};

// Serialize a finalized graph's columns into `path` (atomic tmp+rename).
// The written arrays are the graph's in-memory arrays verbatim — the
// byte-parity invariant the sampling tests pin.
Status WriteColumnarStore(const Graph& g, const std::string& path);

// Open `path` and build an attached Graph over it: every big column
// mmap'd, hot set of `hot_bytes` chosen hub-first, heap holding only
// small derived state (id hash when the dense id table is absent,
// label maps). The result is byte-identical to the graph that wrote
// the store.
Status LoadGraphFromStore(const std::string& path, int64_t hot_bytes,
                          std::unique_ptr<Graph>* out);

// Flat stats export (capi etg_store_stats). Slot order:
//   0 hot_hits | 1 cold_reads | 2 page_in | 3 page_out
//   4 resident_bytes | 5 mapped_bytes | 6 hot_pinned_bytes | 7 attaches
//   8 cold_n | 9 cold_sum_us | 10..34 cold log2-µs bucket counts
// (buckets follow the ServerTraceStats convention: 24 bounds 1µs..2^23µs
// + overflow). Polls residency on every call.
constexpr int kStoreStatSlots = 35;
void StoreStatsSnapshot(uint64_t out[kStoreStatSlots]);

}  // namespace et

#endif  // EULER_TPU_STORE_H_
