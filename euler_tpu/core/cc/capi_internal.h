// Internal seam between the two extern-"C" translation units: capi.cc owns
// the graph handle registry and thread-local error message; capi_query.cc
// resolves graph handles and reports errors through it.
#ifndef EULER_TPU_CAPI_INTERNAL_H_
#define EULER_TPU_CAPI_INTERNAL_H_

#include <cstdint>
#include <memory>
#include <string>

namespace et {
class Graph;
namespace capi {

// Resolve a Python-held graph handle (nullptr if unknown).
std::shared_ptr<Graph> GraphFromHandle(int64_t h);

// Record msg as the thread-local last error; returns the nonzero C error
// code callers propagate.
int FailWith(const std::string& msg);

}  // namespace capi
}  // namespace et

#endif  // EULER_TPU_CAPI_INTERNAL_H_
