// Internal seam between the two extern-"C" translation units: capi.cc owns
// the graph handle registry and thread-local error message; capi_query.cc
// resolves graph handles and reports errors through it.
#ifndef EULER_TPU_CAPI_INTERNAL_H_
#define EULER_TPU_CAPI_INTERNAL_H_

#include <cstdint>
#include <memory>
#include <string>
#include <vector>

// Variable-size result carrier handed across the ctypes boundary as an
// opaque pointer (etres_* accessors in capi.cc). Shared here so both
// extern-"C" translation units can fill one.
struct EtResult {
  std::vector<uint64_t> offsets;
  std::vector<uint64_t> u64;
  std::vector<float> f32;
  std::vector<int32_t> i32;
  std::vector<char> bytes;
};

namespace et {
class Graph;
class GraphRef;
namespace capi {

// Resolve a Python-held graph handle (nullptr if unknown). Returns the
// handle's CURRENT snapshot — a delta apply swaps the snapshot behind
// the same handle (the snapshot itself stays immutable).
std::shared_ptr<Graph> GraphFromHandle(int64_t h);

// The handle's swappable holder (streaming deltas): proxies bound to
// it observe etg_apply_delta swaps.
std::shared_ptr<GraphRef> GraphRefFromHandle(int64_t h);

// Record msg as the thread-local last error; returns the nonzero C error
// code callers propagate.
int FailWith(const std::string& msg);

}  // namespace capi
}  // namespace et

#endif  // EULER_TPU_CAPI_INTERNAL_H_
