// Value-UDF registry + built-ins (reference udf.h:33-68, mean_udf.cc,
// min_udf.cc, max_udf.cc — plus parameterized built-ins `scale` and
// `clip` demonstrating the reference's param-node mechanism as plain
// numeric params).
#include "udf.h"

#include <algorithm>
#include <cstdlib>
#include <limits>
#include <sstream>

namespace et {

UdfRegistry& UdfRegistry::Instance() {
  static UdfRegistry* r = new UdfRegistry();
  return *r;
}

void UdfRegistry::Register(const std::string& name, ValueUdf fn) {
  std::lock_guard<std::mutex> lk(mu_);
  fns_[name] = std::move(fn);
}

ValueUdf UdfRegistry::Find(const std::string& name) const {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = fns_.find(name);
  return it == fns_.end() ? ValueUdf() : it->second;
}

std::vector<std::string> UdfRegistry::Names() const {
  std::lock_guard<std::mutex> lk(mu_);
  std::vector<std::string> out;
  for (auto& kv : fns_) out.push_back(kv.first);
  std::sort(out.begin(), out.end());
  return out;
}

Status ParseUdfSpec(const std::string& spec, std::string* name,
                    std::vector<double>* params) {
  params->clear();
  std::stringstream ss(spec);
  std::string part;
  if (!std::getline(ss, part, ':') || part.empty())
    return Status::InvalidArgument("empty udf name in spec: " + spec);
  *name = part;
  while (std::getline(ss, part, ':')) {
    char* end = nullptr;
    double v = std::strtod(part.c_str(), &end);
    if (end == part.c_str() || *end != '\0')
      return Status::InvalidArgument("bad udf param '" + part + "' in " +
                                     spec);
    params->push_back(v);
  }
  return Status::OK();
}

namespace {

// Per-row reduction helper: out row i is one value.
template <typename Fold>
Status Reduce(std::vector<uint64_t>* offs, std::vector<float>* vals,
              float init, Fold fold, bool mean) {
  std::vector<float> out;
  size_t n = offs->size() - 1;
  out.reserve(n);
  for (size_t i = 0; i < n; ++i) {
    float acc = init;
    uint64_t len = (*offs)[i + 1] - (*offs)[i];
    for (uint64_t j = (*offs)[i]; j < (*offs)[i + 1]; ++j)
      acc = fold(acc, (*vals)[j]);
    if (mean) acc = len ? acc / len : 0.f;
    out.push_back(len ? acc : 0.f);
  }
  *vals = std::move(out);
  for (size_t i = 0; i <= n; ++i) (*offs)[i] = i;
  return Status::OK();
}

struct BuiltinsInstaller {
  BuiltinsInstaller() {
    auto& r = UdfRegistry::Instance();
    r.Register("mean", [](const std::vector<double>&,
                          std::vector<uint64_t>* o, std::vector<float>* v) {
      return Reduce(o, v, 0.f, [](float a, float b) { return a + b; }, true);
    });
    r.Register("max", [](const std::vector<double>&,
                         std::vector<uint64_t>* o, std::vector<float>* v) {
      return Reduce(o, v, -std::numeric_limits<float>::infinity(),
                    [](float a, float b) { return std::max(a, b); }, false);
    });
    r.Register("min", [](const std::vector<double>&,
                         std::vector<uint64_t>* o, std::vector<float>* v) {
      return Reduce(o, v, std::numeric_limits<float>::infinity(),
                    [](float a, float b) { return std::min(a, b); }, false);
    });
    // parameterized built-ins (reference param-node parity)
    r.Register("scale", [](const std::vector<double>& p,
                           std::vector<uint64_t>*, std::vector<float>* v) {
      if (p.size() != 1)
        return Status::InvalidArgument("udf scale needs 1 param (factor)");
      for (auto& x : *v) x = static_cast<float>(x * p[0]);
      return Status::OK();
    });
    r.Register("clip", [](const std::vector<double>& p,
                          std::vector<uint64_t>*, std::vector<float>* v) {
      if (p.size() != 2)
        return Status::InvalidArgument("udf clip needs 2 params (lo, hi)");
      for (auto& x : *v)
        x = std::min(std::max(x, static_cast<float>(p[0])),
                     static_cast<float>(p[1]));
      return Status::OK();
    });
  }
};
BuiltinsInstaller installer;

}  // namespace
}  // namespace et

// ---------------------------------------------------------------------------
// C ABI: Python registers custom UDFs through ctypes (the TPU build's
// version of the reference's compiled-in UDF subclasses).
// The callback fills the output through et_udf_emit on the handed-out
// builder pointer; returning nonzero signals failure.
// ---------------------------------------------------------------------------
extern "C" {

typedef int (*et_udf_cb)(const double* params, int64_t n_params,
                         const uint64_t* offs, int64_t n_rows,
                         const float* vals, int64_t n_vals, void* out);

struct EtUdfOut {
  std::vector<uint64_t>* offs;
  std::vector<float>* vals;
};

void et_udf_emit(void* out, const uint64_t* offs, int64_t n_offs,
                 const float* vals, int64_t n_vals) {
  auto* o = static_cast<EtUdfOut*>(out);
  o->offs->assign(offs, offs + n_offs);
  o->vals->assign(vals, vals + n_vals);
}

void etg_register_udf(const char* name, et_udf_cb cb) {
  std::string n = name;
  et::UdfRegistry::Instance().Register(
      n, [cb, n](const std::vector<double>& params,
                 std::vector<uint64_t>* offs, std::vector<float>* vals) {
        std::vector<uint64_t> out_offs;
        std::vector<float> out_vals;
        EtUdfOut out{&out_offs, &out_vals};
        int rc = cb(params.data(), static_cast<int64_t>(params.size()),
                    offs->data(), static_cast<int64_t>(offs->size()) - 1,
                    vals->data(), static_cast<int64_t>(vals->size()), &out);
        if (rc != 0)
          return et::Status::Internal("python udf '" + n + "' failed rc=" +
                                      std::to_string(rc));
        if (out_offs.empty())
          return et::Status::Internal("python udf '" + n +
                                      "' emitted no output");
        if (out_offs.front() != 0 ||
            out_offs.back() != out_vals.size())
          return et::Status::Internal(
              "python udf '" + n + "' emitted inconsistent ragged output: "
              "offsets[-1]=" + std::to_string(out_offs.back()) +
              " but " + std::to_string(out_vals.size()) + " values");
        *offs = std::move(out_offs);
        *vals = std::move(out_vals);
        return et::Status::OK();
      });
}

}  // extern "C"
