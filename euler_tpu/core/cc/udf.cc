// Value-UDF registry + built-ins (reference udf.h:33-68, mean_udf.cc,
// min_udf.cc, max_udf.cc — plus parameterized built-ins `scale` and
// `clip` demonstrating the reference's param-node mechanism as plain
// numeric params).
#include "udf.h"

#include <algorithm>
#include <cstdlib>
#include <limits>
#include <sstream>

namespace et {

UdfRegistry& UdfRegistry::Instance() {
  static UdfRegistry* r = new UdfRegistry();
  return *r;
}

void UdfRegistry::Register(const std::string& name, ValueUdf fn) {
  std::lock_guard<std::mutex> lk(mu_);
  ++generation_;
  fns_[name] = std::move(fn);
}

uint64_t UdfRegistry::Generation() const {
  std::lock_guard<std::mutex> lk(mu_);
  return generation_;
}

ValueUdf UdfRegistry::Find(const std::string& name,
                           uint64_t* generation) const {
  std::lock_guard<std::mutex> lk(mu_);
  if (generation) *generation = generation_;
  auto it = fns_.find(name);
  return it == fns_.end() ? ValueUdf() : it->second;
}

std::vector<std::string> UdfRegistry::Names() const {
  std::lock_guard<std::mutex> lk(mu_);
  std::vector<std::string> out;
  for (auto& kv : fns_) out.push_back(kv.first);
  std::sort(out.begin(), out.end());
  return out;
}

UdfResultCache& UdfResultCache::Instance() {
  static UdfResultCache* c = new UdfResultCache();
  return *c;
}

std::shared_ptr<const CachedColumn> UdfResultCache::Get(
    uint64_t key, uint64_t graph_uid, uint64_t generation,
    const std::string& spec, int fid, const uint64_t* ids, size_t n) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = map_.find(key);
  if (it == map_.end() ||
      !it->second.col->KeyEquals(graph_uid, generation, spec, fid, ids, n)) {
    // a 64-bit hash collision verifies as a miss, never as wrong data
    ++misses_;
    return nullptr;
  }
  ++hits_;
  lru_.splice(lru_.begin(), lru_, it->second.lru_it);  // touch
  return it->second.col;  // pointer copy only; no payload copy in-lock
}

void UdfResultCache::Put(uint64_t key, std::shared_ptr<const CachedColumn> col) {
  std::lock_guard<std::mutex> lk(mu_);
  if (cap_bytes_ == 0) return;  // caching disabled
  auto it = map_.find(key);
  if (it != map_.end()) return;  // immutable inputs → same value; keep
  Entry e;
  e.col = std::move(col);
  size_t sz = EntryBytes(e);
  if (sz > cap_bytes_) return;  // larger than the whole cache
  while (bytes_ + sz > cap_bytes_ && !lru_.empty()) {
    uint64_t victim = lru_.back();
    lru_.pop_back();
    auto vit = map_.find(victim);
    bytes_ -= EntryBytes(vit->second);
    map_.erase(vit);
  }
  lru_.push_front(key);
  e.lru_it = lru_.begin();
  bytes_ += sz;
  map_.emplace(key, std::move(e));
}

void UdfResultCache::Clear() {
  std::lock_guard<std::mutex> lk(mu_);
  map_.clear();
  lru_.clear();
  bytes_ = 0;
}

size_t UdfResultCache::EvictGraph(uint64_t graph_uid) {
  std::lock_guard<std::mutex> lk(mu_);
  size_t dropped = 0;
  for (auto it = map_.begin(); it != map_.end();) {
    if (it->second.col->graph_uid == graph_uid) {
      bytes_ -= EntryBytes(it->second);
      lru_.erase(it->second.lru_it);
      it = map_.erase(it);
      ++dropped;
    } else {
      ++it;
    }
  }
  epoch_evictions_ += dropped;
  return dropped;
}

uint64_t UdfResultCache::EpochEvictions() const {
  std::lock_guard<std::mutex> lk(mu_);
  return epoch_evictions_;
}

void UdfResultCache::Stats(uint64_t* hits, uint64_t* misses,
                           uint64_t* entries, uint64_t* bytes) const {
  std::lock_guard<std::mutex> lk(mu_);
  *hits = hits_;
  *misses = misses_;
  *entries = map_.size();
  *bytes = bytes_;
}

void UdfResultCache::SetCapacityBytes(size_t cap) {
  std::lock_guard<std::mutex> lk(mu_);
  cap_bytes_ = cap;
  while (bytes_ > cap_bytes_ && !lru_.empty()) {
    uint64_t victim = lru_.back();
    lru_.pop_back();
    auto vit = map_.find(victim);
    bytes_ -= EntryBytes(vit->second);
    map_.erase(vit);
  }
}

uint64_t UdfCacheKey(uint64_t graph_uid, uint64_t generation,
                     const std::string& spec, int fid, const uint64_t* ids,
                     size_t n) {
  uint64_t h = 1469598103934665603ULL;
  auto mix = [&h](const void* p, size_t len) {
    const unsigned char* b = static_cast<const unsigned char*>(p);
    for (size_t i = 0; i < len; ++i) h = (h ^ b[i]) * 1099511628211ULL;
  };
  auto mix_sized = [&](const void* p, uint64_t len) {
    mix(&len, sizeof(len));  // length prefix: concatenations can't alias
    mix(p, static_cast<size_t>(len));
  };
  mix(&graph_uid, sizeof(graph_uid));
  mix(&generation, sizeof(generation));
  mix_sized(spec.data(), spec.size());
  mix(&fid, sizeof(fid));
  mix_sized(ids, n * sizeof(uint64_t));
  return h;
}

Status ParseUdfSpec(const std::string& spec, std::string* name,
                    std::vector<double>* params) {
  params->clear();
  std::stringstream ss(spec);
  std::string part;
  if (!std::getline(ss, part, ':') || part.empty())
    return Status::InvalidArgument("empty udf name in spec: " + spec);
  *name = part;
  while (std::getline(ss, part, ':')) {
    char* end = nullptr;
    double v = std::strtod(part.c_str(), &end);
    if (end == part.c_str() || *end != '\0')
      return Status::InvalidArgument("bad udf param '" + part + "' in " +
                                     spec);
    params->push_back(v);
  }
  return Status::OK();
}

namespace {

// Per-row reduction helper: out row i is one value.
template <typename Fold>
Status Reduce(std::vector<uint64_t>* offs, std::vector<float>* vals,
              float init, Fold fold, bool mean) {
  std::vector<float> out;
  size_t n = offs->size() - 1;
  out.reserve(n);
  for (size_t i = 0; i < n; ++i) {
    float acc = init;
    uint64_t len = (*offs)[i + 1] - (*offs)[i];
    for (uint64_t j = (*offs)[i]; j < (*offs)[i + 1]; ++j)
      acc = fold(acc, (*vals)[j]);
    if (mean) acc = len ? acc / len : 0.f;
    out.push_back(len ? acc : 0.f);
  }
  *vals = std::move(out);
  for (size_t i = 0; i <= n; ++i) (*offs)[i] = i;
  return Status::OK();
}

struct BuiltinsInstaller {
  BuiltinsInstaller() {
    auto& r = UdfRegistry::Instance();
    r.Register("mean", [](const std::vector<double>&,
                          std::vector<uint64_t>* o, std::vector<float>* v) {
      return Reduce(o, v, 0.f, [](float a, float b) { return a + b; }, true);
    });
    r.Register("max", [](const std::vector<double>&,
                         std::vector<uint64_t>* o, std::vector<float>* v) {
      return Reduce(o, v, -std::numeric_limits<float>::infinity(),
                    [](float a, float b) { return std::max(a, b); }, false);
    });
    r.Register("min", [](const std::vector<double>&,
                         std::vector<uint64_t>* o, std::vector<float>* v) {
      return Reduce(o, v, std::numeric_limits<float>::infinity(),
                    [](float a, float b) { return std::min(a, b); }, false);
    });
    // parameterized built-ins (reference param-node parity)
    r.Register("scale", [](const std::vector<double>& p,
                           std::vector<uint64_t>*, std::vector<float>* v) {
      if (p.size() != 1)
        return Status::InvalidArgument("udf scale needs 1 param (factor)");
      for (auto& x : *v) x = static_cast<float>(x * p[0]);
      return Status::OK();
    });
    r.Register("clip", [](const std::vector<double>& p,
                          std::vector<uint64_t>*, std::vector<float>* v) {
      if (p.size() != 2)
        return Status::InvalidArgument("udf clip needs 2 params (lo, hi)");
      for (auto& x : *v)
        x = std::min(std::max(x, static_cast<float>(p[0])),
                     static_cast<float>(p[1]));
      return Status::OK();
    });
  }
};
BuiltinsInstaller installer;

}  // namespace
}  // namespace et

// ---------------------------------------------------------------------------
// C ABI: Python registers custom UDFs through ctypes (the TPU build's
// version of the reference's compiled-in UDF subclasses).
// The callback fills the output through et_udf_emit on the handed-out
// builder pointer; returning nonzero signals failure.
// ---------------------------------------------------------------------------
extern "C" {

typedef int (*et_udf_cb)(const double* params, int64_t n_params,
                         const uint64_t* offs, int64_t n_rows,
                         const float* vals, int64_t n_vals, void* out);

struct EtUdfOut {
  std::vector<uint64_t>* offs;
  std::vector<float>* vals;
};

void et_udf_emit(void* out, const uint64_t* offs, int64_t n_offs,
                 const float* vals, int64_t n_vals) {
  auto* o = static_cast<EtUdfOut*>(out);
  o->offs->assign(offs, offs + n_offs);
  o->vals->assign(vals, vals + n_vals);
}

// UDF result-cache introspection/control (hit-count tests, memory
// pressure, disabling via capacity 0).
void etg_udf_cache_stats(uint64_t* hits, uint64_t* misses,
                         uint64_t* entries, uint64_t* bytes) {
  et::UdfResultCache::Instance().Stats(hits, misses, entries, bytes);
}

void etg_udf_cache_clear() { et::UdfResultCache::Instance().Clear(); }

void etg_udf_cache_set_capacity(uint64_t bytes) {
  et::UdfResultCache::Instance().SetCapacityBytes(
      static_cast<size_t>(bytes));
}

void etg_register_udf(const char* name, et_udf_cb cb) {
  std::string n = name;
  et::UdfRegistry::Instance().Register(
      n, [cb, n](const std::vector<double>& params,
                 std::vector<uint64_t>* offs, std::vector<float>* vals) {
        std::vector<uint64_t> out_offs;
        std::vector<float> out_vals;
        EtUdfOut out{&out_offs, &out_vals};
        int rc = cb(params.data(), static_cast<int64_t>(params.size()),
                    offs->data(), static_cast<int64_t>(offs->size()) - 1,
                    vals->data(), static_cast<int64_t>(vals->size()), &out);
        if (rc != 0)
          return et::Status::Internal("python udf '" + n + "' failed rc=" +
                                      std::to_string(rc));
        if (out_offs.empty())
          return et::Status::Internal("python udf '" + n +
                                      "' emitted no output");
        if (out_offs.front() != 0 ||
            out_offs.back() != out_vals.size())
          return et::Status::Internal(
              "python udf '" + n + "' emitted inconsistent ragged output: "
              "offsets[-1]=" + std::to_string(out_offs.back()) +
              " but " + std::to_string(out_vals.size()) + " values");
        *offs = std::move(out_offs);
        *vals = std::move(out_vals);
        return et::Status::OK();
      });
}

}  // extern "C"
