// Durable streaming deltas: per-shard write-ahead delta log + snapshot
// compaction + crash recovery.
//
// The reference engine survives restarts because graph state lives in
// dumped partition blocks behind FileIO/HDFS; our streaming-delta layer
// (graph.h ApplyGraphDelta / GraphRef) deliberately kept mutations
// memory-only, so a crashed shard restarted at epoch 0 with its accepted
// deltas gone. This module closes that hole with the classic database
// shape, sized for the delta-apply cost model (an apply is already an
// O(graph) snapshot rebuild, so the log can afford one record per apply):
//
//   * DeltaWal — an append-only log of the RAW broadcast delta bodies
//     (the kApplyDelta wire payload, unfiltered: replay re-filters by
//     hash ownership exactly like the live path). Records are
//     length-prefixed, crc32-checksummed, and epoch-stamped; appends
//     happen BEFORE the GraphRef swap so an acked delta is always on
//     disk. Configurable fsync policy (kFsyncNever rides the page cache
//     — survives SIGKILL, not power loss; kFsyncAlways survives both).
//   * Snapshot compaction — past compact_bytes of log, the current
//     snapshot is re-dumped through DumpGraphPartitioned into an atomic
//     temp+rename directory (the ModelBundle convention), CURRENT flips
//     to it, and older logs/snapshots are deleted. The dump keeps the
//     graph's ORIGINAL partition_num so hash-ownership filtering is
//     unchanged after a recovery reload.
//   * Recovery — RecoverShard loads CURRENT's snapshot (or the original
//     data_dir when none), restamps its epoch, then replays log records
//     with epoch > current through ApplyGraphDelta. A torn tail (crash
//     mid-append, disk-full partial write) truncates the log at the
//     first bad checksum instead of refusing to start.
//
// Log file layout (little-endian), one file per generation
// (wal_<start_epoch>.log; a compaction at epoch E starts wal_<E>.log):
//   record: u32 'ETWR' | u64 epoch | u64 body_len | u32 crc32(body) | body
//
// Thread-safety: Append/MaybeCompact are called under the owning
// GraphRef's apply_mutex (applies are serialized anyway), so DeltaWal
// itself only guards its counters.
#ifndef EULER_TPU_WAL_H_
#define EULER_TPU_WAL_H_

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>
#include <vector>

#include "common.h"
#include "graph.h"

namespace et {

// Process-global durability counters (the obs registry mirrors them via
// etg_wal_stats, the same pattern as RpcCounters).
struct WalCounters {
  std::atomic<uint64_t> appends{0};          // records appended
  std::atomic<uint64_t> fsyncs{0};           // fsync() calls issued
  std::atomic<uint64_t> replayed_records{0};  // records applied at recovery
  std::atomic<uint64_t> compactions{0};      // snapshot compactions
  std::atomic<uint64_t> catchup_deltas{0};   // records applied via peer
                                             // anti-entropy catch-up
  std::atomic<uint64_t> refused{0};          // deltas refused (wal degraded)
  std::atomic<uint64_t> torn_records{0};     // records dropped at replay
                                             // (bad checksum / torn tail)
  // gauge: NUMBER of degraded wal instances in this process (an
  // unwritable wal refuses deltas). A count, not a boolean — one
  // healthy shard's append must not mask another shard's degrade.
  std::atomic<int64_t> degraded{0};
};
WalCounters& GlobalWalCounters();

enum class FsyncPolicy : int {
  kNever = 0,   // write(2) only: survives process death (SIGKILL), the
                // page cache owns power-loss durability
  kAlways = 1,  // fsync after every append: survives power loss too
};

// One decoded log record: the epoch the delta produced + the raw
// broadcast body (kApplyDelta wire payload).
struct WalRecord {
  uint64_t epoch = 0;
  std::vector<char> body;
};

class DeltaWal {
 public:
  ~DeltaWal();

  // Opens (creating the directory and an initial generation if needed)
  // the log under `dir`. compact_bytes <= 0 disables compaction.
  // Failure leaves *out null — callers serve reads and refuse deltas
  // (degraded), they do not crash.
  static Status Open(const std::string& dir, FsyncPolicy fsync,
                     int64_t compact_bytes, std::unique_ptr<DeltaWal>* out);

  // Appends one record (raw broadcast delta body) stamped with the
  // epoch the apply will produce. Called BEFORE the GraphRef swap: a
  // failure here must refuse the delta (counted, degraded gauge set) so
  // the in-memory graph never runs ahead of its log. A later success
  // clears the degraded gauge (disk-full recovers when space frees).
  Status Append(uint64_t epoch, const char* body, size_t len);

  // Re-dump `g` (post-swap snapshot) as the new recovery base when the
  // live log has outgrown compact_bytes: atomic temp+rename snapshot
  // dir, CURRENT flip, fresh log generation, old generations deleted.
  // no-op (OK) when under threshold or compaction is disabled.
  Status MaybeCompact(const Graph& g);
  // Unconditional compaction (tests / explicit admin).
  Status Compact(const Graph& g);

  int64_t log_bytes() const { return log_bytes_; }
  const std::string& dir() const { return dir_; }

  // Reads every generation's records in order, validating checksums.
  // Stops at the first bad/torn record, physically truncating that file
  // to its valid prefix (so future appends never land after garbage),
  // and ignores any later generations. Static: recovery runs before a
  // DeltaWal is open for writing.
  static Status ReadAll(const std::string& dir,
                        std::vector<WalRecord>* out);

  // Snapshot bookkeeping (shared with RecoverShard): the CURRENT
  // snapshot subdirectory name ("" when none) and its stamped epoch.
  static Status ReadCurrentSnapshot(const std::string& dir,
                                    std::string* snap_dir,
                                    uint64_t* epoch);

  // Whether the live log has crossed compact_bytes — the caller's cue
  // to schedule a (possibly off-path) MaybeCompact.
  bool wants_compaction() const {
    return compact_bytes_ > 0 && log_bytes_ >= compact_bytes_;
  }

  // Out-of-core sidecar: when enabled, Compact also writes the graph as
  // a columnar store (store.h, "columnar.etc") inside the snapshot dir —
  // compaction doubles as the on-disk tier's writer, and the server can
  // re-attach the fresh generation mmap'd instead of keeping the heap
  // copy. Defaults from ETG_WAL_COLUMNAR at Open ("1" enables); servers
  // started with storage="mmap" force it on. Sidecar write failure
  // degrades to a plain snapshot (warning) — recovery and reattach
  // simply skip the missing file.
  void set_columnar_sidecar(bool on) { columnar_sidecar_ = on; }
  bool columnar_sidecar() const { return columnar_sidecar_; }
  // Directory of the most recent snapshot THIS instance published (""
  // until the first Compact) — where the reattach path looks for the
  // sidecar without re-reading CURRENT.
  const std::string& last_snapshot_dir() const { return last_snapshot_dir_; }

 private:
  DeltaWal() = default;
  Status OpenActiveLog();
  // Degraded-gauge transitions (the gauge counts degraded INSTANCES):
  // called under the owning apply_mutex, so no internal lock.
  void MarkDegraded();
  void ClearDegraded();

  std::string dir_;
  FsyncPolicy fsync_ = FsyncPolicy::kAlways;
  int64_t compact_bytes_ = 0;
  int fd_ = -1;             // active generation, O_APPEND
  std::string active_path_;
  int64_t log_bytes_ = 0;   // bytes in the active generation
  bool degraded_ = false;   // this instance's contribution to the gauge
  bool columnar_sidecar_ = false;
  std::string last_snapshot_dir_;
};

// Decode a kApplyDelta wire body (the WAL record payload) into its
// columnar delta arrays, validating wire-supplied counts against the
// bytes actually present. Shared by the RPC path and WAL replay so both
// reject the same malformed bodies.
Status DecodeDeltaBody(const char* data, size_t size,
                       std::vector<NodeId>* ids, std::vector<int32_t>* ntypes,
                       std::vector<float>* nw, std::vector<NodeId>* src,
                       std::vector<NodeId>* dst, std::vector<int32_t>* etypes,
                       std::vector<float>* ew);

// Crash recovery: rebuild this shard's graph from snapshot + log.
//   1. CURRENT snapshot under wal_dir if present (epoch restamped),
//      else the original data_dir at epoch 0;
//   2. replay log records with epoch == current+1 through
//      ApplyGraphDelta (same hash-ownership filter as the live path).
// `replayed` (optional) reports how many records applied; `records_out`
// (optional) receives every VALID log record read — callers that also
// need the raw records (GraphServer::SeedDeltaLog) reuse them instead
// of parsing the whole log a second time. Torn tails truncate (the
// shard is merely BEHIND, with a consistent epoch prefix); a record
// that fails to apply or an epoch gap stops replay with a warning and
// sets *gap_out — the shard's later epoch numbering may alias
// different fleet deltas, so its anti-entropy log must not claim
// coverage (GraphServer::MarkDeltaLogGap). Anti-entropy catch-up and
// the client epoch-regression flush are the fallbacks either way.
// `omap_out` (optional) receives the persisted ownership map when one
// is found beside the log (see PersistOwnership) — replay re-filters
// deltas under it, and the caller should re-install it on the server so
// the recovered shard keeps refusing stale-map reads.
// `storage` selects the recovered graph's storage tier: 0 = heap (the
// default, unchanged behavior); 1 = mmap out-of-core (store.h). With
// storage=1 and nothing to replay, a snapshot that carries a columnar
// sidecar is attached directly (no heap materialization — the fast
// restart path); otherwise recovery builds on the heap as usual, spills
// a boot store ("boot_columnar.etc" beside the log), and re-attaches.
// `hot_bytes` is the attached tier's hub hot-set budget. Attach
// failures degrade to serving the heap graph with a warning.
Status RecoverShard(const std::string& wal_dir, const std::string& data_dir,
                    int shard_idx, int shard_num, bool build_in_adjacency,
                    std::unique_ptr<Graph>* out, uint64_t* replayed,
                    std::vector<WalRecord>* records_out = nullptr,
                    bool* gap_out = nullptr,
                    OwnershipMap* omap_out = nullptr,
                    int storage = 0, int64_t hot_bytes = 0);

// Elastic fleet: persist/read the shard's installed ownership-map spec
// beside its WAL ("OWNERSHIP", atomic temp+rename) so crash-recovery
// replay filters deltas under the same map the live path applied them
// with. ReadOwnershipSpec returns "" when absent.
Status PersistOwnership(const std::string& wal_dir,
                        const std::string& spec);
std::string ReadOwnershipSpec(const std::string& wal_dir);

}  // namespace et

#endif  // EULER_TPU_WAL_H_
