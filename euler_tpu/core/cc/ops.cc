#include "ops.h"

#include <algorithm>
#include <cmath>
#include <unordered_map>

#include "threadpool.h"

namespace et {

bool EdgeExistsAnyType(const Graph& g, NodeId src, NodeId dst,
                       const int32_t* edge_types, size_t n_types);

void SampleFanout(const Graph& g, const NodeId* roots, size_t n_roots,
                  const int32_t* counts, size_t n_hops,
                  const int32_t* edge_types, const int64_t* et_offsets,
                  NodeId default_id, Pcg32* rng,
                  const std::vector<NodeId*>& out_ids,
                  const std::vector<float*>& out_w,
                  const std::vector<int32_t*>& out_t) {
  const NodeId* cur = roots;
  size_t cur_n = n_roots;
  for (size_t hop = 0; hop < n_hops; ++hop) {
    const int32_t* et = nullptr;
    size_t n_et = 0;
    if (edge_types != nullptr && et_offsets != nullptr) {
      et = edge_types + et_offsets[hop];
      n_et = static_cast<size_t>(et_offsets[hop + 1] - et_offsets[hop]);
    }
    size_t k = static_cast<size_t>(counts[hop]);
    NodeId* ids = out_ids[hop];
    float* ws = out_w.empty() ? nullptr : out_w[hop];
    int32_t* ts = out_t.empty() ? nullptr : out_t[hop];
    if (cur_n >= 4096) {
      // deep hops dominate fanout cost; fan the rows across the pool.
      // Per-chunk rngs derive from one draw, and ParallelFor's chunk
      // layout depends only on (n, grain), so results are reproducible
      // under a fixed seed on any machine
      uint64_t hop_seed =
          (static_cast<uint64_t>(rng->NextU32()) << 32) | rng->NextU32();
      ParallelFor(GlobalThreadPool(), static_cast<int64_t>(cur_n), 2048,
                  [&](int64_t b, int64_t e, int c) {
                    Pcg32 local(hop_seed, static_cast<uint64_t>(c) * 2 + 1);
                    g.SampleNeighborBatch(cur + b, static_cast<size_t>(e - b),
                                          et, n_et, k, default_id, &local,
                                          ids + b * k,
                                          ws ? ws + b * k : nullptr,
                                          ts ? ts + b * k : nullptr);
                  });
    } else {
      g.SampleNeighborBatch(cur, cur_n, et, n_et, k, default_id, rng, ids,
                            ws, ts);
    }
    cur = ids;
    cur_n = cur_n * k;
  }
}

void RandomWalk(const Graph& g, const NodeId* roots, size_t n_roots,
                size_t walk_len, float p, float q, NodeId default_id,
                const int32_t* edge_types, size_t n_types, Pcg32* rng,
                NodeId* out) {
  const bool biased = (p != 1.f || q != 1.f);
  std::vector<NodeId> nbr;
  std::vector<float> ws;
  std::vector<int32_t> ts;
  std::vector<float> biased_w;
  const size_t W = walk_len + 1;
  for (size_t i = 0; i < n_roots; ++i) {
    NodeId* row = out + i * W;
    row[0] = roots[i];
    NodeId prev = default_id;
    NodeId cur = roots[i];
    for (size_t step = 1; step <= walk_len; ++step) {
      if (cur == default_id) {
        row[step] = default_id;
        continue;
      }
      if (!biased || step == 1) {
        NodeId nxt;
        g.SampleNeighbor(cur, edge_types, n_types, 1, default_id, rng, &nxt,
                         nullptr, nullptr);
        prev = cur;
        cur = nxt;
      } else {
        nbr.clear();
        ws.clear();
        ts.clear();
        g.GetFullNeighbor(cur, edge_types, n_types, &nbr, &ws, &ts);
        if (nbr.empty()) {
          prev = cur;
          cur = default_id;
          row[step] = default_id;
          continue;
        }
        // node2vec bias: 1/p back to prev, 1 to common neighbors of prev,
        // 1/q to the rest. Edge existence checked against the store.
        biased_w.resize(nbr.size());
        bool prev_has_out = g.OutDegree(prev, edge_types, n_types) > 0;
        for (size_t j = 0; j < nbr.size(); ++j) {
          float bias;
          if (nbr[j] == prev) {
            bias = 1.f / p;
          } else if (prev_has_out &&
                     EdgeExistsAnyType(g, prev, nbr[j], edge_types, n_types)) {
            bias = 1.f;
          } else {
            bias = 1.f / q;
          }
          biased_w[j] = ws[j] * bias;
        }
        float total = 0.f;
        for (float v : biased_w) total += v;
        NodeId nxt = default_id;
        if (total > 0.f) {
          float r = rng->NextFloat() * total;
          float run = 0.f;
          size_t sel = nbr.size() - 1;
          for (size_t j = 0; j < nbr.size(); ++j) {
            run += biased_w[j];
            if (r < run) {
              sel = j;
              break;
            }
          }
          nxt = nbr[sel];
        }
        prev = cur;
        cur = nxt;
      }
      row[step] = cur;
    }
  }
}

bool EdgeExistsAnyType(const Graph& g, NodeId src, NodeId dst,
                       const int32_t* edge_types, size_t n_types) {
  if (edge_types == nullptr || n_types == 0) {
    for (int et = 0; et < g.num_edge_types(); ++et) {
      if (g.EdgeSlot(src, dst, et) != Graph::kNoSlot) return true;
    }
    return false;
  }
  for (size_t i = 0; i < n_types; ++i) {
    if (g.EdgeSlot(src, dst, edge_types[i]) != Graph::kNoSlot) return true;
  }
  return false;
}

void SampleLayerwise(const Graph& g, const NodeId* roots, size_t n_roots,
                     const int32_t* layer_sizes, size_t n_layers,
                     const int32_t* edge_types, size_t n_types,
                     NodeId default_id, Pcg32* rng,
                     const std::vector<NodeId*>& out_layers,
                     LayerWeightFunc weight_func,
                     std::vector<float>* layer_wsums) {
  // Frontier = current set of nodes; each layer samples `m` nodes from the
  // union of the frontier's neighborhoods, ∝ accumulated edge weight.
  std::vector<NodeId> frontier(roots, roots + n_roots);
  std::vector<NodeId> cand_ids;
  std::vector<float> cand_w;
  std::vector<NodeId> nbr;
  std::vector<float> ws;
  std::vector<int32_t> ts;
  std::unordered_map<NodeId, float> acc;
  for (size_t layer = 0; layer < n_layers; ++layer) {
    size_t m = static_cast<size_t>(layer_sizes[layer]);
    acc.clear();
    for (NodeId u : frontier) {
      if (u == default_id) continue;
      nbr.clear();
      ws.clear();
      ts.clear();
      g.GetFullNeighbor(u, edge_types, n_types, &nbr, &ws, &ts);
      for (size_t j = 0; j < nbr.size(); ++j) acc[nbr[j]] += ws[j];
    }
    cand_ids.clear();
    cand_w.clear();
    float wsum = 0.f;
    for (const auto& kv : acc) {
      cand_ids.push_back(kv.first);
      float w = weight_func == LayerWeightFunc::kSqrt
                    ? std::sqrt(kv.second)
                    : kv.second;
      cand_w.push_back(w);
      wsum += w;
    }
    if (layer_wsums) layer_wsums->push_back(wsum);
    NodeId* out = out_layers[layer];
    if (cand_ids.empty()) {
      for (size_t j = 0; j < m; ++j) out[j] = default_id;
      frontier.assign(m, default_id);
      continue;
    }
    AliasSampler sampler;
    sampler.Init(cand_w);
    for (size_t j = 0; j < m; ++j) out[j] = cand_ids[sampler.Sample(rng)];
    frontier.assign(out, out + m);
  }
}

}  // namespace et
