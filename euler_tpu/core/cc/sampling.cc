#include "sampling.h"

#include <atomic>
#include <mutex>

namespace et {

namespace {
std::atomic<uint64_t> g_rng_base_seed{0x9e3779b97f4a7c15ULL};
std::atomic<uint64_t> g_rng_thread_counter{0};
}  // namespace

Pcg32& ThreadLocalRng() {
  thread_local Pcg32 rng(
      g_rng_base_seed.load(std::memory_order_relaxed) +
      0x632be59bd9b4e019ULL *
          (1 + g_rng_thread_counter.fetch_add(1, std::memory_order_relaxed)));
  return rng;
}

void SeedGlobalRng(uint64_t seed) {
  g_rng_base_seed.store(seed, std::memory_order_relaxed);
  g_rng_thread_counter.store(0, std::memory_order_relaxed);
  ThreadLocalRng().Seed(seed);
}

void AliasSampler::Init(const float* weights, size_t n) {
  prob_.assign(n, 0.f);
  alias_.assign(n, 0);
  total_weight_ = 0.f;
  if (n == 0) return;

  double sum = 0.0;
  for (size_t i = 0; i < n; ++i) sum += weights[i];
  total_weight_ = static_cast<float>(sum);
  if (sum <= 0.0) {
    // Degenerate: uniform.
    for (size_t i = 0; i < n; ++i) {
      prob_[i] = 1.f;
      alias_[i] = static_cast<uint32_t>(i);
    }
    return;
  }

  // Vose's algorithm: scaled probabilities partitioned into small/large
  // worklists, pairing each under-full column with an over-full donor.
  std::vector<double> scaled(n);
  for (size_t i = 0; i < n; ++i) scaled[i] = weights[i] * n / sum;

  std::vector<uint32_t> small, large;
  small.reserve(n);
  large.reserve(n);
  for (size_t i = 0; i < n; ++i) {
    (scaled[i] < 1.0 ? small : large).push_back(static_cast<uint32_t>(i));
  }
  while (!small.empty() && !large.empty()) {
    uint32_t s = small.back();
    small.pop_back();
    uint32_t l = large.back();
    large.pop_back();
    prob_[s] = static_cast<float>(scaled[s]);
    alias_[s] = l;
    scaled[l] = scaled[l] + scaled[s] - 1.0;
    (scaled[l] < 1.0 ? small : large).push_back(l);
  }
  for (uint32_t i : large) prob_[i] = 1.f;
  for (uint32_t i : small) prob_[i] = 1.f;  // numerical leftovers
}

}  // namespace et
