// Helpers shared by the kernel translation units (kernels.cc,
// kernels_dist.cc): input fetch with uniform error text, the per-node
// deterministic RNG, and the early-return macro for Status-returning
// expressions inside async Compute bodies.
#ifndef EULER_TPU_KERNELS_COMMON_H_
#define EULER_TPU_KERNELS_COMMON_H_

#include <algorithm>
#include <cstdint>
#include <limits>
#include <sstream>
#include <string>
#include <vector>

#include "common.h"
#include "dag.h"
#include "tensor.h"

namespace et {

// Per-row post-process spec ("order_by <field> [asc|desc]", "limit k") —
// one parser shared by POST_PROCESS and API_GET_NB_EDGE so the two
// kernels cannot drift on the wire format.
struct RowPostProcess {
  std::string order_field;
  bool desc = false;
  int64_t limit = -1;

  static RowPostProcess Parse(const std::vector<std::string>& entries) {
    RowPostProcess pp;
    for (const auto& e : entries) {
      std::stringstream ss(e);
      std::string kind, a, b;
      ss >> kind >> a >> b;
      if (kind == "order_by" && !a.empty()) {
        pp.order_field = a;
        pp.desc = b == "desc";
      } else if (kind == "limit" && !a.empty()) {
        pp.limit = std::atoll(a.c_str());
      }
    }
    return pp;
  }

  // Sort + truncate one row's element indices. id_at/w_at map an index to
  // its sort keys; unknown fields sort by weight (the historical
  // POST_PROCESS behavior — callers wanting strictness validate first).
  template <typename Idx, typename IdAt, typename WAt>
  void Apply(std::vector<Idx>* order, IdAt id_at, WAt w_at) const {
    if (!order_field.empty()) {
      bool by_id = order_field == "id";
      std::stable_sort(order->begin(), order->end(), [&](Idx x, Idx y) {
        if (by_id) return desc ? id_at(y) < id_at(x) : id_at(x) < id_at(y);
        return desc ? w_at(y) < w_at(x) : w_at(x) < w_at(y);
      });
    }
    if (limit >= 0 && static_cast<int64_t>(order->size()) > limit)
      order->resize(limit);
  }
};

// Ragged row offsets travel as i32 [n,2] tensors; a merged payload past
// 2^31 elements would silently wrap, so every producer range-checks the
// final cursor before casting.
inline Status CheckI32Offsets(const NodeDef& node, int64_t total) {
  if (total > std::numeric_limits<int32_t>::max())
    return Status::InvalidArgument(
        node.name + ": ragged payload of " + std::to_string(total) +
        " elements exceeds int32 offset range");
  return Status::OK();
}

inline Status GetInput(OpKernelContext* ctx, const NodeDef& node, size_t i,
                       Tensor* out) {
  if (i >= node.inputs.size())
    return Status::InvalidArgument(node.name + ": missing input " +
                                   std::to_string(i));
  if (!ctx->Get(node.inputs[i], out))
    return Status::NotFound(node.name + ": input tensor '" + node.inputs[i] +
                            "' not produced");
  return Status::OK();
}

inline Pcg32 NodeRng(const NodeDef& node, const QueryEnv& env) {
  if (env.seed == 0) return Pcg32(ThreadLocalRng().NextU32());
  uint64_t h = 1469598103934665603ULL;
  for (char c : node.name)
    h = (h ^ static_cast<uint64_t>(c)) * 1099511628211ULL;
  // seq = per-execution nonce: repeated run()s draw fresh (but replayable)
  // samples instead of the same batch every time.
  return Pcg32(env.seed ^ h, env.nonce * 2 + 1);
}

#define ET_K_RETURN_IF_ERROR(expr)   \
  do {                               \
    ::et::Status _s = (expr);        \
    if (!_s.ok()) {                  \
      done(_s);                      \
      return;                        \
    }                                \
  } while (0)

}  // namespace et

#endif  // EULER_TPU_KERNELS_COMMON_H_
