// Helpers shared by the kernel translation units (kernels.cc,
// kernels_dist.cc): input fetch with uniform error text, the per-node
// deterministic RNG, and the early-return macro for Status-returning
// expressions inside async Compute bodies.
#ifndef EULER_TPU_KERNELS_COMMON_H_
#define EULER_TPU_KERNELS_COMMON_H_

#include <cstdint>
#include <limits>
#include <string>

#include "common.h"
#include "dag.h"
#include "tensor.h"

namespace et {

// Ragged row offsets travel as i32 [n,2] tensors; a merged payload past
// 2^31 elements would silently wrap, so every producer range-checks the
// final cursor before casting.
inline Status CheckI32Offsets(const NodeDef& node, int64_t total) {
  if (total > std::numeric_limits<int32_t>::max())
    return Status::InvalidArgument(
        node.name + ": ragged payload of " + std::to_string(total) +
        " elements exceeds int32 offset range");
  return Status::OK();
}

inline Status GetInput(OpKernelContext* ctx, const NodeDef& node, size_t i,
                       Tensor* out) {
  if (i >= node.inputs.size())
    return Status::InvalidArgument(node.name + ": missing input " +
                                   std::to_string(i));
  if (!ctx->Get(node.inputs[i], out))
    return Status::NotFound(node.name + ": input tensor '" + node.inputs[i] +
                            "' not produced");
  return Status::OK();
}

inline Pcg32 NodeRng(const NodeDef& node, const QueryEnv& env) {
  if (env.seed == 0) return Pcg32(ThreadLocalRng().NextU32());
  uint64_t h = 1469598103934665603ULL;
  for (char c : node.name)
    h = (h ^ static_cast<uint64_t>(c)) * 1099511628211ULL;
  // seq = per-execution nonce: repeated run()s draw fresh (but replayable)
  // samples instead of the same batch every time.
  return Pcg32(env.seed ^ h, env.nonce * 2 + 1);
}

#define ET_K_RETURN_IF_ERROR(expr)   \
  do {                               \
    ::et::Status _s = (expr);        \
    if (!_s.ok()) {                  \
      done(_s);                      \
      return;                        \
    }                                \
  } while (0)

}  // namespace et

#endif  // EULER_TPU_KERNELS_COMMON_H_
