#include "dag.h"

#include <condition_variable>
#include <unordered_map>

namespace et {

// ---------------------------------------------------------------------------
// Kernel registry
// ---------------------------------------------------------------------------
namespace {
std::unordered_map<std::string, std::unique_ptr<OpKernel>>& Registry() {
  static auto* m =
      new std::unordered_map<std::string, std::unique_ptr<OpKernel>>();
  return *m;
}
std::mutex& RegistryMu() {
  static std::mutex mu;
  return mu;
}
}  // namespace

OpKernel* LookupKernel(const std::string& op) {
  std::lock_guard<std::mutex> lk(RegistryMu());
  auto it = Registry().find(op);
  return it == Registry().end() ? nullptr : it->second.get();
}

void RegisterKernel(const std::string& op, std::unique_ptr<OpKernel> k) {
  std::lock_guard<std::mutex> lk(RegistryMu());
  Registry()[op] = std::move(k);
}

// ---------------------------------------------------------------------------
// Dependency resolution
// ---------------------------------------------------------------------------
// "SAMPLE_NODE_1:0" → producer node name "SAMPLE_NODE_1". Names without a
// ":idx" suffix (external inputs) or with an unknown producer resolve to -1.
static std::string ProducerOf(const std::string& tensor_name) {
  auto pos = tensor_name.rfind(':');
  if (pos == std::string::npos) return tensor_name;
  return tensor_name.substr(0, pos);
}

bool TopologicSort(const DAGDef& dag, std::vector<int>* order) {
  std::unordered_map<std::string, int> by_name;
  for (size_t i = 0; i < dag.nodes.size(); ++i) {
    by_name[dag.nodes[i].name] = static_cast<int>(i);
    for (const auto& extra : dag.nodes[i].also_produces)
      by_name[extra] = static_cast<int>(i);
  }
  std::vector<int> indeg(dag.nodes.size(), 0);
  std::vector<std::vector<int>> succ(dag.nodes.size());
  for (size_t i = 0; i < dag.nodes.size(); ++i) {
    for (const auto& in : dag.nodes[i].inputs) {
      auto it = by_name.find(ProducerOf(in));
      if (it != by_name.end() && it->second != static_cast<int>(i)) {
        succ[it->second].push_back(static_cast<int>(i));
        indeg[i]++;
      }
    }
  }
  order->clear();
  std::vector<int> stack;
  for (size_t i = 0; i < indeg.size(); ++i)
    if (indeg[i] == 0) stack.push_back(static_cast<int>(i));
  while (!stack.empty()) {
    int n = stack.back();
    stack.pop_back();
    order->push_back(n);
    for (int s : succ[n])
      if (--indeg[s] == 0) stack.push_back(s);
  }
  return order->size() == dag.nodes.size();
}

// ---------------------------------------------------------------------------
// Executor
// ---------------------------------------------------------------------------
Executor::Executor(const DAGDef* dag, const QueryEnv& env,
                   OpKernelContext* ctx)
    : dag_(dag), env_(env), ctx_(ctx), remaining_nodes_(0), failed_(false) {
  if (env_.pool == nullptr) env_.pool = GlobalThreadPool();
  std::unordered_map<std::string, int> by_name;
  for (size_t i = 0; i < dag->nodes.size(); ++i) {
    by_name[dag->nodes[i].name] = static_cast<int>(i);
    for (const auto& extra : dag->nodes[i].also_produces)
      by_name[extra] = static_cast<int>(i);
  }
  nodes_.resize(dag->nodes.size());
  for (size_t i = 0; i < dag->nodes.size(); ++i) {
    nodes_[i].def = &dag->nodes[i];
    int deps = 0;
    for (const auto& in : dag->nodes[i].inputs) {
      auto it = by_name.find(ProducerOf(in));
      if (it != by_name.end() && it->second != static_cast<int>(i)) {
        nodes_[it->second].successors.push_back(static_cast<int>(i));
        deps++;
      }
    }
    nodes_[i].remaining.store(deps);
  }
  remaining_nodes_.store(static_cast<int>(nodes_.size()));
}

void Executor::Run(std::function<void(Status)> done) {
  done_ = std::move(done);
  if (nodes_.empty()) {
    auto d = std::move(done_);
    d(Status::OK());
    return;
  }
  std::vector<int> ready;
  for (size_t i = 0; i < nodes_.size(); ++i)
    if (nodes_[i].remaining.load() == 0) ready.push_back(static_cast<int>(i));
  if (ready.empty()) {
    auto d = std::move(done_);
    d(Status::Internal("query DAG has a cycle"));
    return;
  }
  for (int idx : ready) {
    env_.pool->Schedule([this, idx] { Dispatch(idx); });
  }
}

void Executor::Dispatch(int idx) {
  const NodeDef& def = *nodes_[idx].def;
  if (failed_.load()) {  // fail fast: skip work, still retire the node
    OnNodeDone(idx, Status::OK());
    return;
  }
  OpKernel* k = LookupKernel(def.op);
  if (k == nullptr) {
    OnNodeDone(idx, Status::NotFound("no kernel for op: " + def.op));
    return;
  }
  k->Compute(def, env_, ctx_, [this, idx](Status s) { OnNodeDone(idx, s); });
}

void Executor::OnNodeDone(int idx, const Status& s) {
  if (!s.ok()) {
    std::lock_guard<std::mutex> lk(err_mu_);
    if (!failed_.exchange(true)) first_error_ = s;
  }
  for (int succ : nodes_[idx].successors) {
    if (nodes_[succ].remaining.fetch_sub(1) == 1) {
      env_.pool->Schedule([this, succ] { Dispatch(succ); });
    }
  }
  if (remaining_nodes_.fetch_sub(1) == 1) {
    Status final = Status::OK();
    {
      std::lock_guard<std::mutex> lk(err_mu_);
      if (failed_.load()) final = first_error_;
    }
    // release the stored callback before invoking: callers capture the
    // Executor's own shared_ptr in `done` (loopback REMOTE), and a held
    // copy would cycle exec -> done_ -> exec and leak every inner tensor
    auto d = std::move(done_);
    d(final);
  }
}

Status Executor::RunSync() {
  std::mutex mu;
  std::condition_variable cv;
  bool finished = false;
  Status result;
  Run([&](Status s) {
    std::lock_guard<std::mutex> lk(mu);
    result = s;
    finished = true;
    cv.notify_one();
  });
  std::unique_lock<std::mutex> lk(mu);
  cv.wait(lk, [&] { return finished; });
  return result;
}

}  // namespace et
