#include "threadpool.h"

#include <algorithm>

namespace et {

ThreadPool::ThreadPool(size_t num_threads) {
  num_threads = std::max<size_t>(1, num_threads);
  threads_.reserve(num_threads);
  for (size_t i = 0; i < num_threads; ++i) {
    threads_.emplace_back([this, i] { WorkerLoop(i); });
  }
}

ThreadPool::~ThreadPool() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    shutdown_ = true;
  }
  cv_.notify_all();
  for (auto& t : threads_) t.join();
}

void ThreadPool::Schedule(std::function<void()> fn, Lane lane) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    (lane == kLow ? low_queue_ : queue_).push_back(std::move(fn));
  }
  cv_.notify_one();
}

void ThreadPool::WorkerLoop(size_t worker_idx) {
  // Lane preference (see Lane in the header): worker 0 drains LOW
  // first, everyone else drains HIGH first — weak priority with a
  // progress guarantee for both lanes. A single-thread pool's lone
  // worker is worker 0 and still serves both lanes.
  std::deque<std::function<void()>>* pref =
      worker_idx == 0 ? &low_queue_ : &queue_;
  std::deque<std::function<void()>>* other =
      worker_idx == 0 ? &queue_ : &low_queue_;
  for (;;) {
    std::function<void()> fn;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] {
        return shutdown_ || !queue_.empty() || !low_queue_.empty();
      });
      std::deque<std::function<void()>>* q =
          !pref->empty() ? pref : (!other->empty() ? other : nullptr);
      if (q == nullptr) return;  // shutdown and both lanes drained
      fn = std::move(q->front());
      q->pop_front();
    }
    fn();
  }
}

ThreadPool* GlobalThreadPool() {
  static ThreadPool* pool =
      new ThreadPool(std::max(4u, std::thread::hardware_concurrency()));
  return pool;
}

void ParallelFor(ThreadPool* pool, int64_t n, int64_t grain,
                 const std::function<void(int64_t, int64_t, int)>& fn) {
  if (n <= 0) return;
  // chunk layout depends only on (n, grain) — never on the machine's
  // core count — so callers seeding per-chunk rngs get identical results
  // everywhere; 64 caps task overhead while keeping any pool busy
  int64_t chunks = std::min<int64_t>(64, (n + grain - 1) / grain);
  if (chunks <= 1) {
    fn(0, n, 0);
    return;
  }
  int64_t per = (n + chunks - 1) / chunks;
  std::mutex mu;
  std::condition_variable cv;
  int remaining = static_cast<int>(chunks);
  for (int64_t c = 0; c < chunks; ++c) {
    int64_t b = c * per, e = std::min(n, (c + 1) * per);
    pool->Schedule([&, b, e, c] {
      fn(b, e, static_cast<int>(c));
      std::lock_guard<std::mutex> lk(mu);
      if (--remaining == 0) cv.notify_one();
    });
  }
  std::unique_lock<std::mutex> lk(mu);
  cv.wait(lk, [&] { return remaining == 0; });
}

ThreadPool* ClientThreadPool() {
  // 8 threads: parity with the reference's fixed client pool
  // (query_proxy.cc:209); these threads only do blocking socket I/O, so
  // sizing by host cores buys nothing
  static ThreadPool* pool = new ThreadPool(8);
  return pool;
}

}  // namespace et
