#include "threadpool.h"

#include <algorithm>

namespace et {

ThreadPool::ThreadPool(size_t num_threads) {
  num_threads = std::max<size_t>(1, num_threads);
  threads_.reserve(num_threads);
  for (size_t i = 0; i < num_threads; ++i) {
    threads_.emplace_back([this] { WorkerLoop(); });
  }
}

ThreadPool::~ThreadPool() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    shutdown_ = true;
  }
  cv_.notify_all();
  for (auto& t : threads_) t.join();
}

void ThreadPool::Schedule(std::function<void()> fn) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.push_back(std::move(fn));
  }
  cv_.notify_one();
}

void ThreadPool::WorkerLoop() {
  for (;;) {
    std::function<void()> fn;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] { return shutdown_ || !queue_.empty(); });
      if (queue_.empty()) return;  // shutdown and drained
      fn = std::move(queue_.front());
      queue_.pop_front();
    }
    fn();
  }
}

ThreadPool* GlobalThreadPool() {
  static ThreadPool* pool =
      new ThreadPool(std::max(4u, std::thread::hardware_concurrency()));
  return pool;
}

ThreadPool* ClientThreadPool() {
  // 8 threads: parity with the reference's fixed client pool
  // (query_proxy.cc:209); these threads only do blocking socket I/O, so
  // sizing by host cores buys nothing
  static ThreadPool* pool = new ThreadPool(8);
  return pool;
}

}  // namespace et
