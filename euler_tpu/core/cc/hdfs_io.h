// hdfs:// file IO via dlopen'd libhdfs (reference hdfs_file_io.cc:43-71).
#ifndef EULER_TPU_HDFS_IO_H_
#define EULER_TPU_HDFS_IO_H_

#include <string>

#include "common.h"

namespace et {

bool IsHdfsPath(const std::string& path);
Status HdfsReadFile(const std::string& url, std::string* out);
Status HdfsWriteFile(const std::string& url, const char* data, size_t size);

}  // namespace et

#endif  // EULER_TPU_HDFS_IO_H_
