// Binary wire format for tensors and query DAGs.
//
// Capability parity with the reference's protobuf schemas
// (euler/proto/{service,worker}.proto, framework/{tensor,dag,dag_node}
// .proto — SURVEY.md §2.1 "Protos") — replaced by a hand-rolled
// little-endian format over the same ByteWriter/ByteReader the graph
// store uses (io.h), removing the protobuf dependency and the
// encode/decode copies of TensorProto repeated fields.
//
// ExecuteRequest  : u32 'ETEX' | u32 n_inputs | n×(str name, tensor)
//                 | dag | u32 n_outputs | n×str
// ExecuteReply    : u32 code | str error  (code!=0 → no payload)
//                 | u32 n_outputs | n×(str name, tensor)
// tensor          : i32 dtype | u32 rank | rank×i64 dims | bytes
// dag             : u32 n_nodes | n×node
// node            : str name | str op | u32×(inputs, attrs, pp) lists
//                 | u32 n_dnf | per conj: u32 n_terms | terms
//                 | i32 shard_idx | u32 n_inner | inner nodes
#ifndef EULER_TPU_SERDE_H_
#define EULER_TPU_SERDE_H_

#include <string>
#include <vector>

#include "common.h"
#include "dag.h"
#include "io.h"
#include "tensor.h"

namespace et {

void EncodeTensor(const Tensor& t, ByteWriter* w);
Status DecodeTensor(ByteReader* r, Tensor* out);

void EncodeNodeDef(const NodeDef& n, ByteWriter* w);
Status DecodeNodeDef(ByteReader* r, NodeDef* out);

void EncodeDag(const std::vector<NodeDef>& nodes, ByteWriter* w);
Status DecodeDag(ByteReader* r, std::vector<NodeDef>* out);

struct ExecuteRequest {
  std::vector<std::pair<std::string, Tensor>> inputs;
  std::vector<NodeDef> nodes;
  std::vector<std::string> outputs;  // tensor names to return
};

struct ExecuteReply {
  Status status;
  std::vector<std::pair<std::string, Tensor>> outputs;
};

void EncodeExecuteRequest(const ExecuteRequest& req, ByteWriter* w);
Status DecodeExecuteRequest(ByteReader* r, ExecuteRequest* out);
void EncodeExecuteReply(const ExecuteReply& rep, ByteWriter* w);
Status DecodeExecuteReply(ByteReader* r, ExecuteReply* out);

}  // namespace et

#endif  // EULER_TPU_SERDE_H_
