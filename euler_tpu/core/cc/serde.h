// Binary wire format for tensors and query DAGs.
//
// Capability parity with the reference's protobuf schemas
// (euler/proto/{service,worker}.proto, framework/{tensor,dag,dag_node}
// .proto — SURVEY.md §2.1 "Protos") — replaced by a hand-rolled
// little-endian format over the same ByteWriter/ByteReader the graph
// store uses (io.h), removing the protobuf dependency and the
// encode/decode copies of TensorProto repeated fields.
//
// ExecuteRequest  : u32 'ETEX' | u32 n_inputs | n×(str name, tensor)
//                 | dag | u32 n_outputs | n×str
// ExecuteReply    : u32 code | str error  (code!=0 → no payload)
//                 | u32 n_outputs | n×(str name, tensor)
// tensor          : i32 dtype | u32 rank | rank×i64 dims | bytes
// dag             : u32 n_nodes | n×node
// node            : str name | str op | u32×(inputs, attrs, pp) lists
//                 | u32 n_dnf | per conj: u32 n_terms | terms
//                 | i32 shard_idx | u32 n_inner | inner nodes
#ifndef EULER_TPU_SERDE_H_
#define EULER_TPU_SERDE_H_

#include <string>
#include <vector>

#include "common.h"
#include "dag.h"
#include "io.h"
#include "tensor.h"

namespace et {

void EncodeTensor(const Tensor& t, ByteWriter* w);
Status DecodeTensor(ByteReader* r, Tensor* out);

void EncodeNodeDef(const NodeDef& n, ByteWriter* w);
Status DecodeNodeDef(ByteReader* r, NodeDef* out);

void EncodeDag(const std::vector<NodeDef>& nodes, ByteWriter* w);
Status DecodeDag(ByteReader* r, std::vector<NodeDef>* out);

struct ExecuteRequest {
  std::vector<std::pair<std::string, Tensor>> inputs;
  std::vector<NodeDef> nodes;
  std::vector<std::string> outputs;  // tensor names to return
};

struct ExecuteReply {
  Status status;
  std::vector<std::pair<std::string, Tensor>> outputs;
};

// Exact encoded size of one tensor (header + dims + payload) — the
// sizing pass EncodeTensor / EncodeExecuteReply reserve from.
size_t EncodedTensorSize(const Tensor& t);

void EncodeExecuteRequest(const ExecuteRequest& req, ByteWriter* w);
Status DecodeExecuteRequest(ByteReader* r, ExecuteRequest* out);
void EncodeExecuteReply(const ExecuteReply& rep, ByteWriter* w);
Status DecodeExecuteReply(ByteReader* r, ExecuteReply* out);

// ---------------------------------------------------------------------------
// Prepared-plan split (rpc.h kFeatPrepared): one ExecuteRequest is the
// concatenation of a content-stable PLAN (the inner DAG + requested
// output names — identical across the thousands of steps of a training
// loop) and the per-request FEEDS (the named input tensors). The client
// registers the plan once per connection (kPrepare, keyed by its
// content hash) and then ships only the feeds.
//
//   plan  : u32 'ETPN' | dag | u32 n_outputs | n×str
//   feeds : u32 'ETEF' | u32 n_inputs | n×(str name, tensor)
//
// Invariant (pinned by native test): 'ETEY' + feeds[4:] + plan[4:] is
// byte-identical to EncodeExecuteRequest of the same request — the
// transport can always reassemble the classic full frame for fallback.
// ---------------------------------------------------------------------------
void EncodeExecutePlan(const ExecuteRequest& req, ByteWriter* w);
Status DecodeExecutePlan(ByteReader* r, ExecuteRequest* out);
void EncodeExecuteFeeds(const ExecuteRequest& req, ByteWriter* w);
Status DecodeExecuteFeeds(ByteReader* r, ExecuteRequest* out);
// Reassemble the classic EncodeExecuteRequest bytes from the split
// pieces (full-plan fallback when a peer lacks kFeatPrepared or a
// prepared execute keeps missing).
Status AssembleFullExecuteRequest(const std::vector<char>& feeds,
                                  const std::vector<char>& plan,
                                  std::vector<char>* out);
// FNV-1a 64 over the encoded plan bytes — the prepared-plan id. Both
// sides compute it from the same bytes, so a cache hit can never
// execute a different plan than the client encoded (an unknown or
// stale id is an explicit miss status, never a silent wrong plan).
uint64_t PlanContentHash(const char* p, size_t n);

// ---------------------------------------------------------------------------
// Zero-copy reply segments: EncodeExecuteReply's bytes, split into the
// metadata stream (status / names / tensor headers, owned by `meta`)
// and views into the reply's tensor payloads (pinned by `tensors`), so
// an uncompressed reply can be writev'd header+prefix+bodies without
// ever copying the tensor bytes into one contiguous buffer. The runs
// concatenated in order are byte-identical to EncodeExecuteReply
// (pinned by native test).
// ---------------------------------------------------------------------------
struct ReplySegments {
  struct Run {
    size_t off = 0;  // meta-run: offset into meta.buffer()
    size_t len = 0;
    int tensor = -1;  // >= 0: this run is tensors[tensor].raw() bytes
  };
  ByteWriter meta;
  std::vector<Run> runs;
  std::vector<Tensor> tensors;  // payload owners (moved from the reply)
  size_t total = 0;             // sum of run lengths
};
void EncodeExecuteReplySegments(ExecuteReply&& rep, ReplySegments* out);

}  // namespace et

#endif  // EULER_TPU_SERDE_H_
