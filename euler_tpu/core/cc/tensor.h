// Tensor + OpKernelContext for the query execution framework.
//
// Capability parity with the reference's euler/core/framework/{tensor.h,
// tensor_shape.h,allocator.h,op_kernel.h OpKernelContext} (SURVEY.md §2.1).
// Redesigned: a Tensor is a shared flat byte buffer + dtype + dims (no
// ref-counted Buffer class hierarchy — shared_ptr does that job), and the
// context is a name→Tensor map guarded by one mutex. Kernels are coarse
// batch ops, so per-access locking is off the hot path.
#ifndef EULER_TPU_TENSOR_H_
#define EULER_TPU_TENSOR_H_

#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"

namespace et {

enum class DType : int32_t {
  kU64 = 0,  // node ids
  kI64 = 1,
  kI32 = 2,
  kF32 = 3,
  kU8 = 4,  // raw bytes / strings
};

inline size_t DTypeSize(DType t) {
  switch (t) {
    case DType::kU64:
    case DType::kI64:
      return 8;
    case DType::kI32:
    case DType::kF32:
      return 4;
    case DType::kU8:
      return 1;
  }
  return 1;
}

template <typename T>
struct DTypeOf;
template <> struct DTypeOf<uint64_t> { static constexpr DType v = DType::kU64; };
template <> struct DTypeOf<int64_t> { static constexpr DType v = DType::kI64; };
template <> struct DTypeOf<int32_t> { static constexpr DType v = DType::kI32; };
template <> struct DTypeOf<float> { static constexpr DType v = DType::kF32; };
template <> struct DTypeOf<uint8_t> { static constexpr DType v = DType::kU8; };
template <> struct DTypeOf<char> { static constexpr DType v = DType::kU8; };

class Tensor {
 public:
  Tensor() : dtype_(DType::kU8) {}
  Tensor(DType dtype, std::vector<int64_t> dims)
      : dtype_(dtype), dims_(std::move(dims)) {
    data_ = std::make_shared<std::vector<uint8_t>>(ByteSize());
  }

  template <typename T>
  static Tensor FromVector(const std::vector<T>& v,
                           std::vector<int64_t> dims = {}) {
    if (dims.empty()) dims = {static_cast<int64_t>(v.size())};
    Tensor t(DTypeOf<T>::v, std::move(dims));
    std::memcpy(t.raw(), v.data(), v.size() * sizeof(T));
    return t;
  }

  template <typename T>
  static Tensor Scalar(T v) {
    Tensor t(DTypeOf<T>::v, {1});
    t.Flat<T>()[0] = v;
    return t;
  }

  DType dtype() const { return dtype_; }
  const std::vector<int64_t>& dims() const { return dims_; }
  int64_t dim(size_t i) const { return dims_[i]; }
  size_t rank() const { return dims_.size(); }

  int64_t NumElements() const {
    int64_t n = 1;
    for (int64_t d : dims_) n *= d;
    return n;
  }
  size_t ByteSize() const { return NumElements() * DTypeSize(dtype_); }

  template <typename T>
  T* Flat() {
    ET_CHECK(DTypeOf<T>::v == dtype_) << "dtype mismatch";
    return reinterpret_cast<T*>(data_->data());
  }
  template <typename T>
  const T* Flat() const {
    ET_CHECK(DTypeOf<T>::v == dtype_) << "dtype mismatch";
    return reinterpret_cast<const T*>(data_->data());
  }
  uint8_t* raw() { return data_->data(); }
  const uint8_t* raw() const { return data_ ? data_->data() : nullptr; }

  bool valid() const { return data_ != nullptr; }

  // Values as int64 regardless of integral dtype (query args convenience).
  int64_t AsI64(int64_t i) const {
    switch (dtype_) {
      case DType::kU64: return static_cast<int64_t>(Flat<uint64_t>()[i]);
      case DType::kI64: return Flat<int64_t>()[i];
      case DType::kI32: return Flat<int32_t>()[i];
      default: ET_LOG(FATAL) << "AsI64 on non-integral tensor"; return 0;
    }
  }

 private:
  DType dtype_;
  std::vector<int64_t> dims_;
  std::shared_ptr<std::vector<uint8_t>> data_;
};

// Carries all named intermediate results across one query execution.
// Parity: reference OpKernelContext (framework/op_kernel.h:73) — a
// name→Tensor map with Allocate/AddAlias, here thread-safe for the
// parallel executor.
class OpKernelContext {
 public:
  void Put(const std::string& name, Tensor t) {
    std::lock_guard<std::mutex> lk(mu_);
    tensors_[name] = std::move(t);
  }

  bool Get(const std::string& name, Tensor* out) const {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = tensors_.find(name);
    if (it == tensors_.end()) return false;
    *out = it->second;
    return true;
  }

  Tensor GetOrDie(const std::string& name) const {
    Tensor t;
    ET_CHECK(Get(name, &t)) << "missing tensor: " << name;
    return t;
  }

  bool Has(const std::string& name) const {
    std::lock_guard<std::mutex> lk(mu_);
    return tensors_.count(name) > 0;
  }

  // Alias: `alias` resolves to the tensor currently stored under `name`.
  void AddAlias(const std::string& alias, const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = tensors_.find(name);
    if (it != tensors_.end()) tensors_[alias] = it->second;
  }

  std::vector<std::string> Names() const {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<std::string> out;
    out.reserve(tensors_.size());
    for (auto& kv : tensors_) out.push_back(kv.first);
    return out;
  }

 private:
  mutable std::mutex mu_;
  std::unordered_map<std::string, Tensor> tensors_;
};

}  // namespace et

#endif  // EULER_TPU_TENSOR_H_
