// euler_tpu common runtime: status, logging, RNG.
//
// Capability parity with the reference's euler/common/{status.h,logging.h,
// random.cc} (see SURVEY.md §2.1), redesigned: header-only where possible,
// no singletons beyond the logger level, thread-local PCG32 RNG instead of
// rand_r (faster, better statistical quality, reproducible via explicit
// seeding for tests).
#ifndef EULER_TPU_COMMON_H_
#define EULER_TPU_COMMON_H_

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <sstream>
#include <string>

namespace et {

// ---------------------------------------------------------------------------
// Status
// ---------------------------------------------------------------------------
enum class Code : int {
  kOk = 0,
  kInvalidArgument = 1,
  kNotFound = 2,
  kAlreadyExists = 3,
  kOutOfRange = 4,
  kInternal = 5,
  kIOError = 6,
  kUnimplemented = 7,
};

class Status {
 public:
  Status() : code_(Code::kOk) {}
  Status(Code code, std::string msg) : code_(code), msg_(std::move(msg)) {}

  static Status OK() { return Status(); }
  static Status InvalidArgument(const std::string& m) {
    return Status(Code::kInvalidArgument, m);
  }
  static Status NotFound(const std::string& m) {
    return Status(Code::kNotFound, m);
  }
  static Status Internal(const std::string& m) {
    return Status(Code::kInternal, m);
  }
  static Status IOError(const std::string& m) {
    return Status(Code::kIOError, m);
  }

  bool ok() const { return code_ == Code::kOk; }
  Code code() const { return code_; }
  const std::string& message() const { return msg_; }

 private:
  Code code_;
  std::string msg_;
};

#define ET_RETURN_IF_ERROR(expr)              \
  do {                                        \
    ::et::Status _s = (expr);                 \
    if (!_s.ok()) return _s;                  \
  } while (0)

// ---------------------------------------------------------------------------
// Logging: ET_LOG(INFO) << "..."; levels DEBUG/INFO/WARNING/ERROR/FATAL.
// ---------------------------------------------------------------------------
enum class LogLevel : int { kDebug = 0, kInfo = 1, kWarning = 2, kError = 3, kFatal = 4 };

inline int& MinLogLevel() {
  static int level = 1;  // INFO by default; override with EULER_TPU_LOG_LEVEL.
  return level;
}

class LogMessage {
 public:
  LogMessage(const char* file, int line, LogLevel level)
      : level_(level) {
    const char* names[] = {"D", "I", "W", "E", "F"};
    stream_ << "[" << names[static_cast<int>(level)] << " " << file << ":"
            << line << "] ";
  }
  ~LogMessage() {
    if (static_cast<int>(level_) >= MinLogLevel()) {
      stream_ << "\n";
      std::fputs(stream_.str().c_str(), stderr);
    }
    if (level_ == LogLevel::kFatal) std::abort();
  }
  std::ostringstream& stream() { return stream_; }

 private:
  LogLevel level_;
  std::ostringstream stream_;
};

#define ET_LOG_DEBUG ::et::LogMessage(__FILE__, __LINE__, ::et::LogLevel::kDebug).stream()
#define ET_LOG_INFO ::et::LogMessage(__FILE__, __LINE__, ::et::LogLevel::kInfo).stream()
#define ET_LOG_WARNING ::et::LogMessage(__FILE__, __LINE__, ::et::LogLevel::kWarning).stream()
#define ET_LOG_ERROR ::et::LogMessage(__FILE__, __LINE__, ::et::LogLevel::kError).stream()
#define ET_LOG_FATAL ::et::LogMessage(__FILE__, __LINE__, ::et::LogLevel::kFatal).stream()
#define ET_LOG(severity) ET_LOG_##severity

#define ET_CHECK(cond)                                              \
  if (!(cond)) ET_LOG(FATAL) << "Check failed: " #cond " "

// ---------------------------------------------------------------------------
// RNG: PCG32 — small, fast, statistically solid. Thread-local instance for
// sampling hot paths; explicit instances for reproducible tests.
// ---------------------------------------------------------------------------
class Pcg32 {
 public:
  explicit Pcg32(uint64_t seed = 0x853c49e6748fea9bULL,
                 uint64_t seq = 0xda3e39cb94b95bdbULL) {
    state_ = 0u;
    inc_ = (seq << 1u) | 1u;
    NextU32();
    state_ += seed;
    NextU32();
  }

  uint32_t NextU32() {
    uint64_t old = state_;
    state_ = old * 6364136223846793005ULL + inc_;
    uint32_t xorshifted = static_cast<uint32_t>(((old >> 18u) ^ old) >> 27u);
    uint32_t rot = static_cast<uint32_t>(old >> 59u);
    return (xorshifted >> rot) | (xorshifted << ((-rot) & 31));
  }

  // Uniform in [0, 1).
  float NextFloat() { return (NextU32() >> 8) * (1.0f / 16777216.0f); }

  // Uniform integer in [0, n).
  uint64_t NextUInt(uint64_t n) {
    if (n == 0) return 0;
    uint64_t hi = (static_cast<uint64_t>(NextU32()) << 32) | NextU32();
    return hi % n;
  }

  void Seed(uint64_t seed) { *this = Pcg32(seed); }

 private:
  uint64_t state_;
  uint64_t inc_;
};

Pcg32& ThreadLocalRng();
// Seed every thread-local RNG deterministically (current thread only; new
// threads derive from this base). Used for reproducible tests and bench runs.
void SeedGlobalRng(uint64_t seed);

}  // namespace et

#endif  // EULER_TPU_COMMON_H_
