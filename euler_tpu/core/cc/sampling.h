// Weighted sampling primitives.
//
// Capability parity with the reference's euler/common/{alias_method.h,
// fast_weighted_collection.h, compact_weighted_collection.h} (SURVEY.md
// §2.1): O(1) alias-method sampling for global node/edge samplers, and a
// memory-compact prefix-sum + binary-search sampler for per-group neighbor
// sampling. Redesigned around index-based columnar storage: collections
// sample *indices* into external id arrays rather than owning (id, weight)
// pairs, which matches the SoA graph store and avoids duplicating ids.
#ifndef EULER_TPU_SAMPLING_H_
#define EULER_TPU_SAMPLING_H_

#include <cstdint>
#include <vector>

#include "col.h"
#include "common.h"

namespace et {

// O(1) weighted sampling via Vose's alias method. Built once over a weight
// array; Sample() returns an index in [0, size) with probability
// weight[i] / sum(weight). The prob/alias tables are Col<T> so a
// finalized sampler can be serialized into (and re-attached from) the
// mmap'd columnar store — the O(E) global edge sampler must not force
// the whole edge set back onto the heap (store.h).
class AliasSampler {
 public:
  AliasSampler() = default;

  void Init(const float* weights, size_t n);
  void Init(const std::vector<float>& weights) {
    Init(weights.data(), weights.size());
  }
  void Init(const Col<float>& weights) {
    Init(weights.data(), weights.size());
  }

  size_t size() const { return prob_.size(); }
  float total_weight() const { return total_weight_; }

  size_t Sample(Pcg32* rng) const {
    if (prob_.empty()) return 0;
    size_t col = static_cast<size_t>(rng->NextUInt(prob_.size()));
    return rng->NextFloat() < prob_[col] ? col : alias_[col];
  }

  // Serialization seam (store.cc): read the finalized tables, or attach
  // them to externally owned memory (total_weight rides the store's aux
  // section — it is not derivable from prob/alias alone).
  const Col<float>& prob_col() const { return prob_; }
  const Col<uint32_t>& alias_col() const { return alias_; }
  void Attach(const float* prob, const uint32_t* alias, size_t n,
              float total_weight) {
    prob_.AttachExternal(prob, n);
    alias_.AttachExternal(alias, n);
    total_weight_ = total_weight;
  }

 private:
  Col<float> prob_;
  Col<uint32_t> alias_;
  float total_weight_ = 0.f;
};

// Prefix-sum sampler over a *slice* of a shared cumulative-weight array —
// the per-neighbor-group sampler. The graph store keeps one global cumw
// array aligned with the adjacency array; each (node, edge_type) group is a
// [begin, end) range. O(log k) per sample, zero extra memory per group.
//
// cumw[i] holds the inclusive prefix sum of weights *within the group*,
// i.e. cumw[begin] = w0, cumw[end-1] = total.
inline size_t SampleFromCumulative(const float* cumw, size_t begin, size_t end,
                                   Pcg32* rng) {
  size_t n = end - begin;
  if (n == 0) return begin;  // caller must guard empty groups
  float total = cumw[end - 1];
  if (total <= 0.f) {
    return begin + static_cast<size_t>(rng->NextUInt(n));
  }
  float r = rng->NextFloat() * total;
  // Branchless-ish binary search for first cumw[j] > r.
  size_t lo = begin, hi = end;
  while (lo < hi) {
    size_t mid = lo + ((hi - lo) >> 1);
    if (cumw[mid] <= r) {
      lo = mid + 1;
    } else {
      hi = mid;
    }
  }
  return lo < end ? lo : end - 1;
}

}  // namespace et

#endif  // EULER_TPU_SAMPLING_H_
