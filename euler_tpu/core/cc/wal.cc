#include "wal.h"

#include <dirent.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>
#include <zlib.h>

#include <algorithm>
#include <cerrno>
#include <cstring>

#include <cstdlib>

#include "io.h"
#include "store.h"

namespace et {

WalCounters& GlobalWalCounters() {
  static WalCounters* c = new WalCounters();
  return *c;
}

namespace {

constexpr uint32_t kWalMagic = 0x52575445;  // 'ETWR'
constexpr size_t kWalHdrLen = 4 + 8 + 8 + 4;  // magic|epoch|len|crc
constexpr uint64_t kMaxRecordLen = 1ULL << 30;  // 1 GiB sanity cap

uint32_t Crc32(const char* p, size_t n) {
  return static_cast<uint32_t>(
      crc32(0L, reinterpret_cast<const Bytef*>(p), static_cast<uInt>(n)));
}

std::string GenName(uint64_t start_epoch) {
  return "wal_" + std::to_string(start_epoch) + ".log";
}

// wal_<epoch>.log → epoch; false for anything else.
bool ParseGenName(const std::string& name, uint64_t* epoch) {
  if (name.rfind("wal_", 0) != 0) return false;
  if (name.size() < 9 || name.substr(name.size() - 4) != ".log") return false;
  const std::string digits = name.substr(4, name.size() - 8);
  if (digits.empty()) return false;
  uint64_t e = 0;
  for (char c : digits) {
    if (c < '0' || c > '9') return false;
    e = e * 10 + static_cast<uint64_t>(c - '0');
  }
  *epoch = e;
  return true;
}

Status ListDir(const std::string& dir, std::vector<std::string>* names) {
  names->clear();
  DIR* d = ::opendir(dir.c_str());
  if (d == nullptr)
    return Status::IOError("cannot open wal dir " + dir + ": " +
                           std::strerror(errno));
  while (dirent* e = ::readdir(d)) {
    std::string n = e->d_name;
    if (n != "." && n != "..") names->push_back(std::move(n));
  }
  ::closedir(d);
  std::sort(names->begin(), names->end());
  return Status::OK();
}

// Generation start epochs present under dir, ascending.
std::vector<uint64_t> ListGenerations(const std::string& dir) {
  std::vector<std::string> names;
  std::vector<uint64_t> gens;
  if (!ListDir(dir, &names).ok()) return gens;
  for (const auto& n : names) {
    uint64_t e;
    if (ParseGenName(n, &e)) gens.push_back(e);
  }
  std::sort(gens.begin(), gens.end());
  return gens;
}

void FsyncDir(const std::string& dir) {
  int fd = ::open(dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (fd >= 0) {
    ::fsync(fd);
    ::close(fd);
  }
}

Status RemoveTreeBestEffort(const std::string& path) {
  struct stat st;
  if (::lstat(path.c_str(), &st) != 0) return Status::OK();  // gone
  if (!S_ISDIR(st.st_mode)) {
    ::unlink(path.c_str());
    return Status::OK();
  }
  std::vector<std::string> names;
  ET_RETURN_IF_ERROR(ListDir(path, &names));
  for (const auto& n : names) RemoveTreeBestEffort(path + "/" + n);
  ::rmdir(path.c_str());
  return Status::OK();
}

// Parse one generation file's records; on a bad/torn record, truncate
// the FILE to the valid prefix and stop. Returns the valid byte length.
int64_t ParseGeneration(const std::string& path,
                        std::vector<WalRecord>* out) {
  std::string blob;
  if (!ReadFileToString(path, &blob).ok()) return 0;
  size_t off = 0;
  auto& c = GlobalWalCounters();
  while (off + kWalHdrLen <= blob.size()) {
    uint32_t magic, crc;
    uint64_t epoch, len;
    std::memcpy(&magic, blob.data() + off, 4);
    std::memcpy(&epoch, blob.data() + off + 4, 8);
    std::memcpy(&len, blob.data() + off + 12, 8);
    std::memcpy(&crc, blob.data() + off + 20, 4);
    if (magic != kWalMagic || len > kMaxRecordLen ||
        off + kWalHdrLen + len > blob.size() ||
        Crc32(blob.data() + off + kWalHdrLen, len) != crc) {
      break;  // torn tail / corruption: keep the valid prefix only
    }
    WalRecord rec;
    rec.epoch = epoch;
    rec.body.assign(blob.data() + off + kWalHdrLen,
                    blob.data() + off + kWalHdrLen + len);
    out->push_back(std::move(rec));
    off += kWalHdrLen + len;
  }
  if (off < blob.size()) {
    c.torn_records.fetch_add(1);
    ET_LOG(WARNING) << "wal " << path << ": truncating "
                    << (blob.size() - off)
                    << " trailing bytes at a torn/corrupt record (replay "
                    << "keeps the " << out->size() << "-record prefix)";
    ::truncate(path.c_str(), static_cast<off_t>(off));
  }
  return static_cast<int64_t>(off);
}

}  // namespace

// ---------------------------------------------------------------------------
// DeltaWal
// ---------------------------------------------------------------------------

DeltaWal::~DeltaWal() {
  ClearDegraded();  // this instance's gauge contribution dies with it
  if (fd_ >= 0) ::close(fd_);
}

void DeltaWal::MarkDegraded() {
  if (!degraded_) {
    degraded_ = true;
    GlobalWalCounters().degraded.fetch_add(1);
  }
}

void DeltaWal::ClearDegraded() {
  if (degraded_) {
    degraded_ = false;
    GlobalWalCounters().degraded.fetch_sub(1);
  }
}

Status DeltaWal::Open(const std::string& dir, FsyncPolicy fsync,
                      int64_t compact_bytes,
                      std::unique_ptr<DeltaWal>* out) {
  out->reset();
  if (::mkdir(dir.c_str(), 0755) != 0 && errno != EEXIST)
    return Status::IOError("cannot create wal dir " + dir + ": " +
                           std::strerror(errno));
  struct stat st;
  if (::stat(dir.c_str(), &st) != 0 || !S_ISDIR(st.st_mode))
    return Status::IOError("wal dir " + dir + " is not a directory");
  auto wal = std::unique_ptr<DeltaWal>(new DeltaWal());
  wal->dir_ = dir;
  wal->fsync_ = fsync;
  wal->compact_bytes_ = compact_bytes;
  const char* col = std::getenv("ETG_WAL_COLUMNAR");
  if (col != nullptr && col[0] == '1') wal->columnar_sidecar_ = true;
  ET_RETURN_IF_ERROR(wal->OpenActiveLog());
  *out = std::move(wal);
  return Status::OK();
}

Status DeltaWal::OpenActiveLog() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
  std::vector<uint64_t> gens = ListGenerations(dir_);
  uint64_t gen = gens.empty() ? 0 : gens.back();
  active_path_ = dir_ + "/" + GenName(gen);
  fd_ = ::open(active_path_.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (fd_ < 0)
    return Status::IOError("cannot open wal log " + active_path_ + ": " +
                           std::strerror(errno));
  struct stat st;
  log_bytes_ = ::fstat(fd_, &st) == 0 ? static_cast<int64_t>(st.st_size) : 0;
  if (gens.empty()) FsyncDir(dir_);  // first generation file creation
  return Status::OK();
}

Status DeltaWal::Append(uint64_t epoch, const char* body, size_t len) {
  auto& c = GlobalWalCounters();
  if (len > kMaxRecordLen) {
    // mirror the replay-side cap: appending a record replay would
    // classify as corrupt (and truncate — destroying every later
    // acked record in the generation) must refuse the DELTA instead.
    // Per-delta, not an instance degrade.
    return Status::InvalidArgument(
        "delta body of " + std::to_string(len) +
        " bytes exceeds the wal record cap (" +
        std::to_string(kMaxRecordLen) +
        "); split the delta into smaller batches");
  }
  if (fd_ < 0) {
    // a previous failure closed the log; retry the open so a transient
    // condition (disk freed, dir restored) recovers without a restart
    Status s = OpenActiveLog();
    if (!s.ok()) {
      MarkDegraded();
      return s;
    }
  }
  std::vector<char> rec(kWalHdrLen + len);
  uint32_t crc = Crc32(body, len);
  uint64_t l = len;
  std::memcpy(rec.data(), &kWalMagic, 4);
  std::memcpy(rec.data() + 4, &epoch, 8);
  std::memcpy(rec.data() + 12, &l, 8);
  std::memcpy(rec.data() + 20, &crc, 4);
  if (len > 0) std::memcpy(rec.data() + kWalHdrLen, body, len);
  // one write(2) per record: on SIGKILL the page cache keeps whatever
  // the syscall accepted; a partial write (disk full) leaves a torn
  // tail that replay truncates
  size_t done = 0;
  while (done < rec.size()) {
    ssize_t w = ::write(fd_, rec.data() + done, rec.size() - done);
    if (w <= 0) {
      MarkDegraded();
      // roll the partial record back so a post-refusal append does not
      // interleave after garbage; if even that fails, replay's checksum
      // truncation still bounds the damage
      ::ftruncate(fd_, static_cast<off_t>(log_bytes_));
      return Status::IOError("wal append failed on " + active_path_ + ": " +
                             std::strerror(errno));
    }
    done += static_cast<size_t>(w);
  }
  if (fsync_ == FsyncPolicy::kAlways) {
    if (::fsync(fd_) != 0) {
      MarkDegraded();
      ::ftruncate(fd_, static_cast<off_t>(log_bytes_));
      return Status::IOError("wal fsync failed on " + active_path_ + ": " +
                             std::strerror(errno));
    }
    c.fsyncs.fetch_add(1);
  }
  log_bytes_ += static_cast<int64_t>(rec.size());
  c.appends.fetch_add(1);
  ClearDegraded();  // transient condition (e.g. disk-full) healed
  return Status::OK();
}

Status DeltaWal::MaybeCompact(const Graph& g) {
  if (compact_bytes_ <= 0 || log_bytes_ < compact_bytes_)
    return Status::OK();
  return Compact(g);
}

Status DeltaWal::Compact(const Graph& g) {
  const uint64_t epoch = g.epoch();
  const std::string snap_name = "snapshot_" + std::to_string(epoch);
  const std::string snap_dir = dir_ + "/" + snap_name;
  const std::string tmp_dir = snap_dir + ".tmp";
  RemoveTreeBestEffort(tmp_dir);
  RemoveTreeBestEffort(snap_dir);  // stale same-epoch leftover of a crash
  if (::mkdir(tmp_dir.c_str(), 0755) != 0)
    return Status::IOError("cannot create snapshot tmp dir " + tmp_dir +
                           ": " + std::strerror(errno));
  // keep the graph's ORIGINAL partition count: LoadShard's p % shard_num
  // filter (and ApplyGraphDelta's hash-ownership filter, which divides
  // by partition_num) must see the same layout after a recovery reload
  ET_RETURN_IF_ERROR(
      DumpGraphPartitioned(g, tmp_dir, g.meta().partition_num));
  const std::string epoch_str = std::to_string(epoch);
  ET_RETURN_IF_ERROR(WriteStringToFile(tmp_dir + "/EPOCH", epoch_str.data(),
                                       epoch_str.size()));
  if (columnar_sidecar_) {
    // out-of-core tier writer: the same snapshot generation doubles as
    // the mmap base the server can re-attach (store.h)
    Status cs = WriteColumnarStore(
        g, tmp_dir + "/" + std::string(kColumnarFileName));
    if (!cs.ok())
      ET_LOG(WARNING) << "wal " << dir_ << ": columnar sidecar failed ("
                      << cs.message() << ") — snapshot published without it";
  }
  if (::rename(tmp_dir.c_str(), snap_dir.c_str()) != 0)
    return Status::IOError("cannot publish snapshot " + snap_dir + ": " +
                           std::strerror(errno));
  last_snapshot_dir_ = snap_dir;
  // CURRENT flip is itself temp+rename — a crash leaves either the old
  // or the new pointer, never a torn file
  const std::string cur_tmp = dir_ + "/CURRENT.tmp";
  ET_RETURN_IF_ERROR(
      WriteStringToFile(cur_tmp, snap_name.data(), snap_name.size()));
  if (::rename(cur_tmp.c_str(), (dir_ + "/CURRENT").c_str()) != 0)
    return Status::IOError("cannot flip CURRENT in " + dir_ + ": " +
                           std::strerror(errno));
  FsyncDir(dir_);
  // new log generation; everything before it is covered by the snapshot
  const std::string new_log = dir_ + "/" + GenName(epoch);
  int fd = ::open(new_log.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (fd < 0)
    return Status::IOError("cannot open post-compaction log " + new_log +
                           ": " + std::strerror(errno));
  if (fd_ >= 0) ::close(fd_);
  fd_ = fd;
  active_path_ = new_log;
  log_bytes_ = 0;
  // garbage-collect superseded generations and snapshots
  std::vector<std::string> names;
  if (ListDir(dir_, &names).ok()) {
    for (const auto& n : names) {
      uint64_t e;
      if (ParseGenName(n, &e) && e < epoch)
        ::unlink((dir_ + "/" + n).c_str());
      else if (n.rfind("snapshot_", 0) == 0 && n != snap_name)
        RemoveTreeBestEffort(dir_ + "/" + n);
    }
  }
  FsyncDir(dir_);
  GlobalWalCounters().compactions.fetch_add(1);
  ET_LOG(INFO) << "wal " << dir_ << ": compacted to " << snap_name
               << " (log truncated)";
  return Status::OK();
}

Status DeltaWal::ReadAll(const std::string& dir,
                         std::vector<WalRecord>* out) {
  out->clear();
  std::vector<uint64_t> gens = ListGenerations(dir);
  for (size_t i = 0; i < gens.size(); ++i) {
    const std::string path = dir + "/" + GenName(gens[i]);
    struct stat st;
    if (::stat(path.c_str(), &st) != 0) continue;
    int64_t valid = ParseGeneration(path, out);
    // a torn record invalidates everything after it (epoch order):
    // ignore later generations too (they should not exist — the torn
    // file is by construction the newest — but be defensive)
    if (valid < st.st_size) {
      if (i + 1 < gens.size())
        ET_LOG(WARNING) << "wal " << dir << ": ignoring "
                        << (gens.size() - i - 1)
                        << " generation(s) after a torn record";
      break;
    }
  }
  return Status::OK();
}

Status DeltaWal::ReadCurrentSnapshot(const std::string& dir,
                                     std::string* snap_dir,
                                     uint64_t* epoch) {
  snap_dir->clear();
  *epoch = 0;
  std::string name;
  if (!ReadFileToString(dir + "/CURRENT", &name).ok())
    return Status::OK();  // no snapshot yet
  // trim whitespace defensively (hand-edited CURRENT files)
  while (!name.empty() && (name.back() == '\n' || name.back() == ' '))
    name.pop_back();
  if (name.empty()) return Status::OK();
  std::string epoch_blob;
  const std::string full = dir + "/" + name;
  if (!ReadFileToString(full + "/EPOCH", &epoch_blob).ok())
    return Status::IOError("snapshot " + full + " has no EPOCH stamp");
  uint64_t e = 0;
  for (char c : epoch_blob) {
    if (c < '0' || c > '9') break;
    e = e * 10 + static_cast<uint64_t>(c - '0');
  }
  *snap_dir = name;
  *epoch = e;
  return Status::OK();
}

// ---------------------------------------------------------------------------
// Recovery
// ---------------------------------------------------------------------------

Status DecodeDeltaBody(const char* data, size_t size,
                       std::vector<NodeId>* ids, std::vector<int32_t>* ntypes,
                       std::vector<float>* nw, std::vector<NodeId>* src,
                       std::vector<NodeId>* dst, std::vector<int32_t>* etypes,
                       std::vector<float>* ew) {
  ByteReader r(data, size);
  uint64_t n_nodes = 0, n_edges = 0;
  // validate counts against the bytes actually present BEFORE any
  // resize (same rule as the wire path: a record declaring 2^62 rows
  // fails cheaply instead of bad_alloc'ing)
  bool ok = r.Get(&n_nodes) &&
            n_nodes <= r.remaining() /
                (sizeof(NodeId) + sizeof(int32_t) + sizeof(float));
  if (ok && n_nodes > 0) {
    ids->resize(n_nodes);
    ntypes->resize(n_nodes);
    nw->resize(n_nodes);
    ok = r.GetRaw(ids->data(), n_nodes * sizeof(NodeId)) &&
         r.GetRaw(ntypes->data(), n_nodes * sizeof(int32_t)) &&
         r.GetRaw(nw->data(), n_nodes * sizeof(float));
  }
  ok = ok && r.Get(&n_edges) &&
       n_edges <= r.remaining() /
           (2 * sizeof(NodeId) + sizeof(int32_t) + sizeof(float));
  if (ok && n_edges > 0) {
    src->resize(n_edges);
    dst->resize(n_edges);
    etypes->resize(n_edges);
    ew->resize(n_edges);
    ok = r.GetRaw(src->data(), n_edges * sizeof(NodeId)) &&
         r.GetRaw(dst->data(), n_edges * sizeof(NodeId)) &&
         r.GetRaw(etypes->data(), n_edges * sizeof(int32_t)) &&
         r.GetRaw(ew->data(), n_edges * sizeof(float));
  }
  if (!ok) return Status::IOError("truncated delta body");
  return Status::OK();
}

Status PersistOwnership(const std::string& wal_dir,
                        const std::string& spec) {
  // atomic temp+fsync+rename: a crash leaves either the old map or the
  // new one, never a torn spec. The DATA fsync before the rename is
  // load-bearing — a durable directory entry naming an undurable file
  // could surface as an empty OWNERSHIP after power loss, and recovery
  // would silently replay deltas under the hash convention instead of
  // the map the live path filtered with.
  const std::string tmp = wal_dir + "/OWNERSHIP.tmp";
  int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0)
    return Status::IOError("cannot create " + tmp + ": " +
                           std::string(std::strerror(errno)));
  const char* p = spec.data();
  size_t n = spec.size();
  while (n > 0) {
    ssize_t w = ::write(fd, p, n);
    if (w <= 0) {
      ::close(fd);
      return Status::IOError("cannot write " + tmp + ": " +
                             std::string(std::strerror(errno)));
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  if (::fsync(fd) != 0) {
    ::close(fd);
    return Status::IOError("cannot fsync " + tmp + ": " +
                           std::string(std::strerror(errno)));
  }
  ::close(fd);
  if (::rename(tmp.c_str(), (wal_dir + "/OWNERSHIP").c_str()) != 0)
    return Status::IOError("cannot rename OWNERSHIP into place: " +
                           std::string(std::strerror(errno)));
  FsyncDir(wal_dir);
  return Status::OK();
}

std::string ReadOwnershipSpec(const std::string& wal_dir) {
  std::string spec;
  if (!ReadFileToString(wal_dir + "/OWNERSHIP", &spec).ok()) return "";
  // trim trailing whitespace/newline an operator-edited file may carry
  while (!spec.empty() &&
         (spec.back() == '\n' || spec.back() == '\r' || spec.back() == ' '))
    spec.pop_back();
  return spec;
}

Status RecoverShard(const std::string& wal_dir, const std::string& data_dir,
                    int shard_idx, int shard_num, bool build_in_adjacency,
                    std::unique_ptr<Graph>* out, uint64_t* replayed,
                    std::vector<WalRecord>* records_out, bool* gap_out,
                    OwnershipMap* omap_out, int storage, int64_t hot_bytes) {
  if (replayed != nullptr) *replayed = 0;
  if (gap_out != nullptr) *gap_out = false;
  // persisted ownership map (kSetOwnership wrote it beside the log):
  // replay must re-filter deltas under the SAME map the live path
  // applied them with — a replicated partition's rows would otherwise
  // vanish from a restarted extra owner whose hash placement disowns
  // them. Absent/bad spec → hash convention, the pre-elastic behavior.
  OwnershipMap omap;
  const std::string ospec = ReadOwnershipSpec(wal_dir);
  const OwnershipMap* omap_p = nullptr;
  if (!ospec.empty()) {
    Status os = OwnershipMap::Decode(ospec, &omap);
    if (os.ok()) {
      omap_p = &omap;
      ET_LOG(INFO) << "wal recovery: shard " << shard_idx
                   << " replaying under persisted ownership map " << ospec;
    } else {
      ET_LOG(WARNING) << "wal recovery: ignoring bad OWNERSHIP spec ("
                      << os.message() << ")";
    }
  }
  if (omap_out != nullptr && omap_p != nullptr) *omap_out = omap;
  std::string snap_name;
  uint64_t snap_epoch = 0;
  ET_RETURN_IF_ERROR(
      DeltaWal::ReadCurrentSnapshot(wal_dir, &snap_name, &snap_epoch));
  // Records are read BEFORE loading the base: with nothing to replay
  // and a columnar sidecar beside the base, the out-of-core path can
  // attach the mmap directly and never materialize the graph on heap —
  // the fast restart the 10×-RAM tier exists for.
  std::vector<WalRecord> recs;
  ET_RETURN_IF_ERROR(DeltaWal::ReadAll(wal_dir, &recs));
  const std::string base_dir =
      snap_name.empty() ? data_dir : wal_dir + "/" + snap_name;
  std::unique_ptr<Graph> g;
  if (storage == 1) {
    bool pending = false;
    for (const auto& rec : recs)
      if (rec.epoch > snap_epoch) pending = true;
    // Snapshot dirs are per-shard and written atomically with their
    // sidecar; a shared data_dir base needs the shard-qualified name
    // plus a freshness check against the partition files (a stale or
    // sibling-shard spill must never shadow this shard's data).
    const std::string sidecar =
        base_dir + "/" + (snap_name.empty()
                              ? ColumnarSidecarName(shard_idx, shard_num)
                              : std::string(kColumnarFileName));
    struct stat sst;
    const bool usable =
        !pending && (snap_name.empty()
                         ? SidecarIsFresh(base_dir, sidecar)
                         : ::stat(sidecar.c_str(), &sst) == 0);
    if (usable) {
      std::unique_ptr<Graph> attached;
      Status as = LoadGraphFromStore(sidecar, hot_bytes, &attached);
      if (as.ok() && build_in_adjacency && !attached->has_in_adjacency() &&
          attached->edge_count() > 0) {
        // sidecar written without in-adjacency but the server wants it:
        // fall back to the heap build below
        as = Status::IOError("sidecar lacks in-adjacency");
        attached.reset();
      }
      if (as.ok()) {
        attached->set_epoch(snap_epoch);
        ET_LOG(INFO) << "wal recovery: shard " << shard_idx
                     << " attached columnar sidecar " << sidecar
                     << " (epoch " << snap_epoch << ", no replay)";
        g = std::move(attached);
      } else {
        ET_LOG(WARNING) << "wal recovery: columnar sidecar " << sidecar
                        << " unusable (" << as.message()
                        << ") — recovering on heap";
      }
    }
  }
  if (g == nullptr) {
    ET_RETURN_IF_ERROR(LoadShard(base_dir, shard_idx, shard_num,
                                 /*data_type=*/0, build_in_adjacency, &g));
    if (!snap_name.empty()) {
      g->set_epoch(snap_epoch);
      ET_LOG(INFO) << "wal recovery: shard " << shard_idx << " loaded "
                   << snap_name << " (epoch " << snap_epoch << ")";
    }
  }
  uint64_t applied = 0;
  for (const auto& rec : recs) {
    uint64_t cur = g->epoch();
    if (rec.epoch <= cur) continue;  // covered by the snapshot
    if (rec.epoch != cur + 1) {
      ET_LOG(WARNING) << "wal recovery: epoch gap (have " << cur
                      << ", next record " << rec.epoch
                      << ") — stopping replay; anti-entropy catch-up "
                      << "or client epoch-regression flush covers the rest";
      if (gap_out != nullptr) *gap_out = true;
      break;
    }
    std::vector<NodeId> ids, src, dst;
    std::vector<int32_t> ntypes, etypes;
    std::vector<float> nw, ew;
    Status s = DecodeDeltaBody(rec.body.data(), rec.body.size(), &ids,
                               &ntypes, &nw, &src, &dst, &etypes, &ew);
    std::unique_ptr<Graph> next;
    std::vector<NodeId> dirty;
    if (s.ok()) {
      s = ApplyGraphDelta(*g, ids.data(), ntypes.data(), nw.data(),
                          ids.size(), src.data(), dst.data(), etypes.data(),
                          ew.data(), src.size(), shard_idx, shard_num, &next,
                          &dirty, omap_p);
    }
    if (!s.ok()) {
      ET_LOG(WARNING) << "wal recovery: record for epoch " << rec.epoch
                      << " failed to apply (" << s.message()
                      << ") — serving at epoch " << cur;
      if (gap_out != nullptr) *gap_out = true;
      break;
    }
    g = std::move(next);
    ++applied;
  }
  GlobalWalCounters().replayed_records.fetch_add(applied);
  if (replayed != nullptr) *replayed = applied;
  if (applied > 0)
    ET_LOG(INFO) << "wal recovery: shard " << shard_idx << " replayed "
                 << applied << " record(s) -> epoch " << g->epoch();
  if (records_out != nullptr) *records_out = std::move(recs);
  if (storage == 1 && !g->attached()) {
    // heap recovery under the out-of-core mode: spill a boot store
    // beside the log and re-attach so serving starts mmap'd even when
    // replay was needed. Failure degrades to serving the heap graph.
    const std::string boot = wal_dir + "/boot_columnar.etc";
    // first-ever start: RecoverShard runs before DeltaWal::Open creates
    // the log directory, so the spill must create it itself
    ::mkdir(wal_dir.c_str(), 0755);
    Status ws = WriteColumnarStore(*g, boot);
    if (ws.ok()) {
      std::unique_ptr<Graph> attached;
      uint64_t ep = g->epoch();
      ws = LoadGraphFromStore(boot, hot_bytes, &attached);
      if (ws.ok()) {
        attached->set_epoch(ep);
        g = std::move(attached);
      }
    }
    if (!ws.ok())
      ET_LOG(WARNING) << "wal recovery: boot columnar store failed ("
                      << ws.message() << ") — serving from heap";
  }
  *out = std::move(g);
  return Status::OK();
}

}  // namespace et
