#include "graph.h"

#include <sys/mman.h>

#include <algorithm>
#include <atomic>
#include <cstring>
#include <numeric>

namespace et {

Graph::Graph() {
  static std::atomic<uint64_t> next{1};
  uid_ = next.fetch_add(1, std::memory_order_relaxed);
}

namespace {
// Giant-store arrays (adjacency, cumw, dense features) are hit with
// pure random access on the sampling path; 4KB pages make every miss a
// TLB miss too. Advise transparent hugepages for multi-MB arrays (the
// kernel honors it under THP=madvise, a no-op elsewhere).
void AdviseHuge(const void* p, size_t bytes) {
  constexpr uintptr_t kHuge = 2u << 20;
  if (bytes < 2 * kHuge) return;
  uintptr_t a = reinterpret_cast<uintptr_t>(p);
  uintptr_t lo = (a + kHuge - 1) & ~(kHuge - 1);
  uintptr_t hi = (a + bytes) & ~(kHuge - 1);
  if (hi > lo)
    ::madvise(reinterpret_cast<void*>(lo), hi - lo, MADV_HUGEPAGE);
}

template <typename V>
void AdviseHugeVec(const V& v) {
  AdviseHuge(v.data(), v.size() * sizeof(typename V::value_type));
}
}  // namespace

// ---------------------------------------------------------------------------
// GraphBuilder
// ---------------------------------------------------------------------------

uint32_t GraphBuilder::EnsureNode(NodeId id, int32_t type, float weight,
                                  bool overwrite) {
  auto it = node_row_.find(id);
  if (it != node_row_.end()) {
    if (overwrite) {
      nodes_[it->second].type = type;
      nodes_[it->second].weight = weight;
    }
    return it->second;
  }
  uint32_t row = static_cast<uint32_t>(nodes_.size());
  nodes_.push_back({id, type, weight});
  node_row_.emplace(id, row);
  return row;
}

void GraphBuilder::AddNode(NodeId id, int32_t type, float weight) {
  EnsureNode(id, type, weight, /*overwrite=*/true);
  if (type >= meta_.num_node_types) meta_.num_node_types = type + 1;
}

void GraphBuilder::AddEdge(NodeId src, NodeId dst, int32_t type,
                           float weight) {
  if (type < 0) {
    ET_LOG(WARNING) << "AddEdge: negative edge type " << type << " ignored";
    return;
  }
  EnsureNode(src, 0, 1.0f, /*overwrite=*/false);
  if (type >= meta_.num_edge_types) meta_.num_edge_types = type + 1;
  // duplicates are allowed here and deduped at Finalize (last added
  // wins) — per-edge map maintenance would dominate bulk ingestion
  edges_.push_back({src, dst, type, weight});
}

void GraphBuilder::AddNodes(const NodeId* ids, const int32_t* types,
                            const float* weights, size_t n) {
  nodes_.reserve(nodes_.size() + n);
  for (size_t i = 0; i < n; ++i) {
    AddNode(ids[i], types ? types[i] : 0, weights ? weights[i] : 1.0f);
  }
}

void GraphBuilder::SetGraphLabels(const NodeId* ids, const uint64_t* labels,
                                  size_t n) {
  for (size_t i = 0; i < n; ++i) graph_label_of_[ids[i]] = labels[i];
}

void GraphBuilder::AddEdges(const NodeId* src, const NodeId* dst,
                            const int32_t* types, const float* weights,
                            size_t n) {
  edges_.reserve(edges_.size() + n);
  for (size_t i = 0; i < n; ++i) {
    AddEdge(src[i], dst[i], types ? types[i] : 0,
            weights ? weights[i] : 1.0f);
  }
}

// Feature setters silently ignore negative ids (logged once per call
// site would be noise; the Python layer validates names → ids).
std::vector<GraphBuilder::FeatCell>* GraphBuilder::NodeCells(int fid) {
  if (fid < 0) return nullptr;
  if (static_cast<size_t>(fid) >= node_feat_cells_.size()) {
    node_feat_cells_.resize(fid + 1);
  }
  if (static_cast<size_t>(fid) >= meta_.node_features.size()) {
    meta_.node_features.resize(fid + 1);
  }
  return &node_feat_cells_[fid];
}

std::vector<GraphBuilder::FeatCell>* GraphBuilder::EdgeCells(int fid) {
  if (fid < 0) return nullptr;
  if (static_cast<size_t>(fid) >= edge_feat_cells_.size()) {
    edge_feat_cells_.resize(fid + 1);
  }
  if (static_cast<size_t>(fid) >= meta_.edge_features.size()) {
    meta_.edge_features.resize(fid + 1);
  }
  return &edge_feat_cells_[fid];
}

void GraphBuilder::SetNodeDense(NodeId id, int fid, const float* v,
                                int64_t dim) {
  uint32_t row = EnsureNode(id, 0, 1.0f, false);
  auto* cells = NodeCells(fid);
  if (!cells) return;
  FeatCell c;
  c.row = row;
  c.f32.assign(v, v + dim);
  cells->push_back(std::move(c));
  auto& info = meta_.node_features[fid];
  info.kind = FeatureKind::kDense;
  if (dim > info.dim) info.dim = dim;
}

void GraphBuilder::SetNodeSparse(NodeId id, int fid, const uint64_t* v,
                                 int64_t len) {
  uint32_t row = EnsureNode(id, 0, 1.0f, false);
  auto* cells = NodeCells(fid);
  if (!cells) return;
  FeatCell c;
  c.row = row;
  c.u64.assign(v, v + len);
  cells->push_back(std::move(c));
  auto& info = meta_.node_features[fid];
  info.kind = FeatureKind::kSparse;
  if (len > info.dim) info.dim = len;
}

void GraphBuilder::SetNodeBinary(NodeId id, int fid, const char* v,
                                 int64_t len) {
  uint32_t row = EnsureNode(id, 0, 1.0f, false);
  auto* cells = NodeCells(fid);
  if (!cells) return;
  FeatCell c;
  c.row = row;
  c.bytes.assign(v, v + len);
  cells->push_back(std::move(c));
  meta_.node_features[fid].kind = FeatureKind::kBinary;
}

int64_t GraphBuilder::FindEdgeRow(NodeId src, NodeId dst,
                                  int32_t type) const {
  // extend the lazy index over edges added since the last lookup; later
  // rows overwrite earlier ones, matching Finalize's last-added-wins
  // dedup
  for (; edge_indexed_upto_ < edges_.size(); ++edge_indexed_upto_) {
    size_t e = edge_indexed_upto_;
    auto nit = node_row_.find(edges_[e].src);
    if (nit == node_row_.end()) continue;
    edge_row_[std::make_tuple(nit->second, edges_[e].dst,
                              edges_[e].type)] = e;
  }
  auto nit = node_row_.find(src);
  if (nit == node_row_.end()) return -1;
  auto it = edge_row_.find(std::make_tuple(nit->second, dst, type));
  return it == edge_row_.end() ? -1 : static_cast<int64_t>(it->second);
}

void GraphBuilder::SetEdgeDense(NodeId src, NodeId dst, int32_t type, int fid,
                                const float* v, int64_t dim) {
  int64_t row = FindEdgeRow(src, dst, type);
  if (row < 0) return;
  auto* cells = EdgeCells(fid);
  if (!cells) return;
  FeatCell c;
  c.row = static_cast<uint64_t>(row);
  c.f32.assign(v, v + dim);
  cells->push_back(std::move(c));
  auto& info = meta_.edge_features[fid];
  info.kind = FeatureKind::kDense;
  if (dim > info.dim) info.dim = dim;
}

void GraphBuilder::SetEdgeSparse(NodeId src, NodeId dst, int32_t type,
                                 int fid, const uint64_t* v, int64_t len) {
  int64_t row = FindEdgeRow(src, dst, type);
  if (row < 0) return;
  auto* cells = EdgeCells(fid);
  if (!cells) return;
  FeatCell c;
  c.row = static_cast<uint64_t>(row);
  c.u64.assign(v, v + len);
  cells->push_back(std::move(c));
  auto& info = meta_.edge_features[fid];
  info.kind = FeatureKind::kSparse;
  if (len > info.dim) info.dim = len;
}

void GraphBuilder::SetEdgeBinary(NodeId src, NodeId dst, int32_t type,
                                 int fid, const char* v, int64_t len) {
  int64_t row = FindEdgeRow(src, dst, type);
  if (row < 0) return;
  auto* cells = EdgeCells(fid);
  if (!cells) return;
  FeatCell c;
  c.row = static_cast<uint64_t>(row);
  c.bytes.assign(v, v + len);
  cells->push_back(std::move(c));
  meta_.edge_features[fid].kind = FeatureKind::kBinary;
}

void GraphBuilder::SetNodeDenseBulk(const NodeId* ids, size_t n, int fid,
                                    int64_t dim, const float* values) {
  for (size_t i = 0; i < n; ++i) {
    SetNodeDense(ids[i], fid, values + i * dim, dim);
  }
}

void GraphBuilder::SetEdgeDenseBulk(const NodeId* src, const NodeId* dst,
                                    const int32_t* types, size_t n, int fid,
                                    int64_t dim, const float* values) {
  for (size_t i = 0; i < n; ++i) {
    SetEdgeDense(src[i], dst[i], types ? types[i] : 0, fid, values + i * dim,
                 dim);
  }
}

void GraphBuilder::SetNodeSparseBulk(const NodeId* ids, size_t n, int fid,
                                     const uint64_t* offsets,
                                     const uint64_t* values) {
  for (size_t i = 0; i < n; ++i) {
    SetNodeSparse(ids[i], fid, values + offsets[i],
                  static_cast<int64_t>(offsets[i + 1] - offsets[i]));
  }
}

std::unique_ptr<Graph> GraphBuilder::Finalize(bool build_in_adjacency) {
  auto g = std::unique_ptr<Graph>(new Graph());
  const size_t N = nodes_.size();
  const size_t E = edges_.size();
  // Derive type counts from observed data too: meta may have been shrunk
  // by set_num_types after rows were added, and trusting it would index
  // group buffers out of bounds.
  int max_et = 0, max_nt = 0;
  for (const EdgeRow& er : edges_) max_et = std::max(max_et, er.type + 1);
  for (const NodeRow& nr : nodes_) max_nt = std::max(max_nt, nr.type + 1);
  const int ET = std::max({meta_.num_edge_types, max_et, 1});
  const int NT = std::max({meta_.num_node_types, max_nt, 1});
  meta_.num_edge_types = ET;
  meta_.num_node_types = NT;
  meta_.node_count = N;
  meta_.edge_count = E;
  if (meta_.node_type_names.size() < static_cast<size_t>(NT)) {
    meta_.node_type_names.resize(NT);
    for (int t = 0; t < NT; ++t) {
      if (meta_.node_type_names[t].empty()) {
        meta_.node_type_names[t] = std::to_string(t);
      }
    }
  }
  if (meta_.edge_type_names.size() < static_cast<size_t>(ET)) {
    meta_.edge_type_names.resize(ET);
    for (int t = 0; t < ET; ++t) {
      if (meta_.edge_type_names[t].empty()) {
        meta_.edge_type_names[t] = std::to_string(t);
      }
    }
  }
  g->meta_ = meta_;

  // ---- nodes ----
  g->node_ids_.resize(N);
  g->node_types_.resize(N);
  g->node_weights_.resize(N);
  for (size_t i = 0; i < N; ++i) {
    g->node_ids_[i] = nodes_[i].id;
    g->node_types_[i] = nodes_[i].type;
    g->node_weights_[i] = nodes_[i].weight;
  }
  if (N > 0) {
    NodeId lo = g->node_ids_[0], hi = g->node_ids_[0];
    for (NodeId id : g->node_ids_) {
      lo = std::min(lo, id);
      hi = std::max(hi, id);
    }
    uint64_t span = hi - lo + 1;  // wraps to 0 for the full u64 range
    if (span != 0 && span <= 4 * static_cast<uint64_t>(N)) {
      g->dense_base_ = lo;
      g->dense_idx_.assign(span, kInvalidIndex);
      for (size_t i = 0; i < N; ++i)
        g->dense_idx_[g->node_ids_[i] - lo] = static_cast<uint32_t>(i);
    }
  }
  // the hash map is only the NodeIndex fallback — keeping both on a
  // 100M-edge store would waste ~100MB RSS for nothing
  if (g->dense_idx_.empty()) g->id2idx_ = node_row_;

  // ---- whole-graph labels ----
  if (!graph_label_of_.empty()) {
    g->graph_labels_.assign(N, 0);
    for (const auto& kv : graph_label_of_) {
      if (kv.second == 0) continue;  // 0 = unlabeled by convention
      auto it = node_row_.find(kv.first);
      if (it == node_row_.end()) continue;
      g->graph_labels_[it->second] = kv.second;
      g->label_rows_[kv.second].push_back(it->second);
    }
    for (auto& kv : g->label_rows_) {
      std::sort(kv.second.begin(), kv.second.end());
      g->label_ids_.push_back(kv.first);
    }
    std::sort(g->label_ids_.begin(), g->label_ids_.end());
  }

  // ---- out-adjacency CSR, grouped by (src row, edge type) ----
  // Order edges within a group by dst id → deterministic layout, free
  // sorted-full-neighbor, AND O(log d) EdgeSlot binary search (no edge
  // map). Duplicate (src,dst,type) rows dedupe here, last added wins
  // (ties break by builder row DESC so the survivor sorts first).
  std::vector<uint32_t> esrc_row(E);
  for (size_t e = 0; e < E; ++e) esrc_row[e] = node_row_.at(edges_[e].src);
  // Sort packed (group, dst, ~row) keys instead of indices: an indirect
  // comparator dereferences edges_[] at random, and on 100M+ edges the
  // cache misses made the sort dominate finalize.
  struct SortKey {
    uint64_t group;
    NodeId dst;
    uint64_t row;
  };
  std::vector<SortKey> keys(E);
  for (size_t e = 0; e < E; ++e) {
    keys[e] = {static_cast<uint64_t>(esrc_row[e]) * ET + edges_[e].type,
               edges_[e].dst, e};
  }
  std::sort(keys.begin(), keys.end(), [](const SortKey& a, const SortKey& b) {
    if (a.group != b.group) return a.group < b.group;
    if (a.dst != b.dst) return a.dst < b.dst;
    return a.row > b.row;  // latest added first among duplicates
  });
  std::vector<uint64_t> order(E);
  for (size_t s = 0; s < E; ++s) order[s] = keys[s].row;
  keys.clear();
  keys.shrink_to_fit();
  std::vector<uint64_t> kept;  // slot → builder edge row
  kept.reserve(E);
  std::vector<uint64_t> row2slot(E);  // builder edge row → adjacency slot
  std::vector<uint64_t> group_count(N * ET + 1, 0);
  {
    size_t prev_g = static_cast<size_t>(-1);
    NodeId prev_dst = 0;
    for (uint64_t idx : order) {
      size_t gi = static_cast<size_t>(esrc_row[idx]) * ET + edges_[idx].type;
      NodeId dd = edges_[idx].dst;
      if (!kept.empty() && gi == prev_g && dd == prev_dst) {
        row2slot[idx] = kept.size() - 1;  // duplicate → survivor's slot
        continue;
      }
      row2slot[idx] = kept.size();
      kept.push_back(idx);
      group_count[gi + 1]++;
      prev_g = gi;
      prev_dst = dd;
    }
  }
  const size_t E2 = kept.size();
  g->meta_.edge_count = E2;
  g->adj_offsets_.assign(N * ET + 1, 0);
  for (size_t i = 1; i <= N * ET; ++i) {
    g->adj_offsets_[i] = g->adj_offsets_[i - 1] + group_count[i];
  }
  g->adj_nbr_.resize(E2);
  g->adj_w_.resize(E2);
  g->adj_cumw_.resize(E2);
  for (size_t s = 0; s < E2; ++s) {
    const EdgeRow& er = edges_[kept[s]];
    g->adj_nbr_[s] = er.dst;
    g->adj_w_[s] = er.weight;
  }
  for (size_t gi = 0; gi < N * ET; ++gi) {
    float run = 0.f;
    for (uint64_t s = g->adj_offsets_[gi]; s < g->adj_offsets_[gi + 1]; ++s) {
      run += g->adj_w_[s];
      g->adj_cumw_[s] = run;
    }
  }

  // ---- in-adjacency (only deduped edges whose dst is a local node) ----
  if (build_in_adjacency) {
    std::vector<uint64_t> in_count(N * ET + 1, 0);
    for (uint64_t e : kept) {
      auto it = node_row_.find(edges_[e].dst);
      if (it == node_row_.end()) continue;
      in_count[static_cast<size_t>(it->second) * ET + edges_[e].type + 1]++;
    }
    g->in_adj_offsets_.assign(N * ET + 1, 0);
    for (size_t i = 1; i <= N * ET; ++i) {
      g->in_adj_offsets_[i] = g->in_adj_offsets_[i - 1] + in_count[i];
    }
    size_t in_total = g->in_adj_offsets_[N * ET];
    g->in_adj_nbr_.resize(in_total);
    g->in_adj_w_.resize(in_total);
    g->in_adj_cumw_.resize(in_total);
    std::vector<uint64_t> cursor(g->in_adj_offsets_.begin(),
                                 g->in_adj_offsets_.end() - 1);
    // Respect the same by-src-id order inside each group for determinism.
    for (size_t s = 0; s < E2; ++s) {
      const EdgeRow& er = edges_[kept[s]];
      auto it = node_row_.find(er.dst);
      if (it == node_row_.end()) continue;
      size_t gi = static_cast<size_t>(it->second) * ET + er.type;
      uint64_t pos = cursor[gi]++;
      g->in_adj_nbr_[pos] = er.src;
      g->in_adj_w_[pos] = er.weight;
    }
    for (size_t gi = 0; gi < N * ET; ++gi) {
      float run = 0.f;
      for (uint64_t s = g->in_adj_offsets_[gi]; s < g->in_adj_offsets_[gi + 1];
           ++s) {
        run += g->in_adj_w_[s];
        g->in_adj_cumw_[s] = run;
      }
    }
  }

  // ---- global samplers & weight sums ----
  g->nodes_by_type_.assign(NT, {});
  g->node_type_wsum_.assign(NT, 0.f);
  for (size_t i = 0; i < N; ++i) {
    int32_t t = g->node_types_[i];
    if (t >= 0 && t < NT) {
      g->nodes_by_type_[t].push_back(static_cast<uint32_t>(i));
      g->node_type_wsum_[t] += g->node_weights_[i];
    }
  }
  g->node_sampler_by_type_.resize(NT);
  std::vector<float> wbuf;
  for (int t = 0; t < NT; ++t) {
    wbuf.clear();
    for (uint32_t i : g->nodes_by_type_[t]) wbuf.push_back(g->node_weights_[i]);
    g->node_sampler_by_type_[t].Init(wbuf);
  }
  g->node_sampler_all_.Init(g->node_weights_);

  g->edges_by_type_.assign(ET, {});
  g->edge_type_wsum_.assign(ET, 0.f);
  {
    // slot → type from group index
    for (size_t gi = 0; gi < N * ET; ++gi) {
      int32_t t = static_cast<int32_t>(gi % ET);
      for (uint64_t s = g->adj_offsets_[gi]; s < g->adj_offsets_[gi + 1];
           ++s) {
        g->edges_by_type_[t].push_back(s);
        g->edge_type_wsum_[t] += g->adj_w_[s];
      }
    }
  }
  g->edge_sampler_by_type_.resize(ET);
  for (int t = 0; t < ET; ++t) {
    wbuf.clear();
    for (uint64_t s : g->edges_by_type_[t]) wbuf.push_back(g->adj_w_[s]);
    g->edge_sampler_by_type_[t].Init(wbuf);
  }
  g->edge_sampler_all_.Init(g->adj_w_);

  // ---- features ----
  auto pack_node = [&](int nfids, bool is_node) {
    auto& cells_by_fid = is_node ? node_feat_cells_ : edge_feat_cells_;
    auto& infos = is_node ? g->meta_.node_features : g->meta_.edge_features;
    auto& dense = is_node ? g->node_dense_ : g->edge_dense_;
    auto& var = is_node ? g->node_var_ : g->edge_var_;
    size_t rows = is_node ? N : E2;
    dense.resize(infos.size());
    var.resize(infos.size());
    for (size_t fid = 0; fid < cells_by_fid.size(); ++fid) {
      auto& cells = cells_by_fid[fid];
      const FeatureInfo& info = infos[fid];
      if (info.kind == FeatureKind::kDense) {
        int64_t dim = std::max<int64_t>(info.dim, 1);
        dense[fid].assign(rows * dim, 0.f);
        for (const auto& c : cells) {
          uint64_t r = is_node ? c.row : row2slot[c.row];
          int64_t n = std::min<int64_t>(dim, c.f32.size());
          std::memcpy(dense[fid].data() + r * dim, c.f32.data(),
                      n * sizeof(float));
        }
      } else {
        // CSR over rows. A row may have been set twice (last wins) — dedupe
        // to one cell per row before sizing, or the copy pass would write
        // a stale longer payload past the row's region.
        std::unordered_map<uint64_t, const FeatCell*> last_cell;
        for (const auto& c : cells) {
          last_cell[is_node ? c.row : row2slot[c.row]] = &c;
        }
        auto& vf = var[fid];
        vf.offsets.assign(rows + 1, 0);
        bool sparse = info.kind == FeatureKind::kSparse;
        for (const auto& kv : last_cell) {
          vf.offsets[kv.first + 1] =
              sparse ? kv.second->u64.size() : kv.second->bytes.size();
        }
        for (size_t r = 0; r < rows; ++r) vf.offsets[r + 1] += vf.offsets[r];
        if (sparse) {
          vf.values_u64.resize(vf.offsets[rows]);
        } else {
          vf.values_bytes.resize(vf.offsets[rows]);
        }
        for (const auto& kv : last_cell) {
          uint64_t r = kv.first;
          if (sparse) {
            std::copy(kv.second->u64.begin(), kv.second->u64.end(),
                      vf.values_u64.begin() + vf.offsets[r]);
          } else {
            std::copy(kv.second->bytes.begin(), kv.second->bytes.end(),
                      vf.values_bytes.begin() + vf.offsets[r]);
          }
        }
      }
    }
    (void)nfids;
  };
  pack_node(0, true);
  pack_node(0, false);

  // TLB relief for the random-access sampling path on giant stores
  AdviseHugeVec(g->adj_nbr_);
  AdviseHugeVec(g->adj_w_);
  AdviseHugeVec(g->adj_cumw_);
  AdviseHugeVec(g->adj_offsets_);
  AdviseHugeVec(g->dense_idx_);
  AdviseHugeVec(g->in_adj_nbr_);
  AdviseHugeVec(g->in_adj_cumw_);
  for (auto& d : g->node_dense_) AdviseHugeVec(d);

  return g;
}

// ---------------------------------------------------------------------------
// Graph: sampling
// ---------------------------------------------------------------------------

void Graph::SampleNode(int type, size_t count, Pcg32* rng,
                       NodeId* out_ids) const {
  if (node_ids_.empty()) {
    for (size_t i = 0; i < count; ++i) out_ids[i] = 0;
    return;
  }
  if (type < 0) {
    for (size_t i = 0; i < count; ++i) {
      out_ids[i] = node_ids_[node_sampler_all_.Sample(rng)];
    }
    return;
  }
  if (type >= meta_.num_node_types || nodes_by_type_[type].empty()) {
    for (size_t i = 0; i < count; ++i) out_ids[i] = 0;
    return;
  }
  const auto& pool = nodes_by_type_[type];
  const auto& sampler = node_sampler_by_type_[type];
  for (size_t i = 0; i < count; ++i) {
    out_ids[i] = node_ids_[pool[sampler.Sample(rng)]];
  }
}

void Graph::SampleNodeWithTypes(const int32_t* types, size_t count,
                                Pcg32* rng, NodeId* out_ids) const {
  for (size_t i = 0; i < count; ++i) {
    SampleNode(types[i], 1, rng, out_ids + i);
  }
}

void Graph::SampleEdge(int type, size_t count, Pcg32* rng, NodeId* out_src,
                       NodeId* out_dst, int32_t* out_type) const {
  const int ET = meta_.num_edge_types;
  auto emit = [&](uint64_t slot, size_t i) {
    // slot → group via binary search on offsets; src = group / ET.
    auto it = std::upper_bound(adj_offsets_.begin(), adj_offsets_.end(), slot);
    size_t gi = static_cast<size_t>(it - adj_offsets_.begin()) - 1;
    out_src[i] = node_ids_[gi / ET];
    out_dst[i] = adj_nbr_[slot];
    out_type[i] = static_cast<int32_t>(gi % ET);
  };
  if (adj_nbr_.empty()) {
    for (size_t i = 0; i < count; ++i) {
      out_src[i] = out_dst[i] = 0;
      out_type[i] = -1;
    }
    return;
  }
  if (type < 0) {
    for (size_t i = 0; i < count; ++i) emit(edge_sampler_all_.Sample(rng), i);
    return;
  }
  if (type >= ET || edges_by_type_[type].empty()) {
    for (size_t i = 0; i < count; ++i) {
      out_src[i] = out_dst[i] = 0;
      out_type[i] = -1;
    }
    return;
  }
  const auto& pool = edges_by_type_[type];
  const auto& sampler = edge_sampler_by_type_[type];
  for (size_t i = 0; i < count; ++i) {
    emit(pool[sampler.Sample(rng)], i);
  }
}

namespace {
// Scratch for candidate-group gathering on the sampling hot path:
// thread-local to avoid per-call allocation, unbounded so graphs with any
// number of edge types sample correctly.
struct GroupScratch {
  std::vector<float> totals;
  std::vector<size_t> begins, ends;
  std::vector<int32_t> types;
  void clear() {
    totals.clear();
    begins.clear();
    ends.clear();
    types.clear();
  }
};
GroupScratch& TlsGroupScratch() {
  thread_local GroupScratch s;
  return s;
}
}  // namespace

uint64_t Graph::SampleAdjSlot(uint32_t idx, const int32_t* edge_types,
                              size_t n_types, Pcg32* rng) const {
  const int ET = meta_.num_edge_types;
  // Gather candidate group totals; ET is small so a linear pass beats any
  // fancier structure.
  GroupScratch& s = TlsGroupScratch();
  s.clear();
  float grand = 0.f;
  auto consider = [&](int et) {
    if (et < 0 || et >= ET) return;
    size_t b, e;
    GroupRange(idx, et, &b, &e);
    if (e <= b) return;
    float t = adj_cumw_[e - 1];
    if (t <= 0.f) return;
    s.totals.push_back(t);
    s.begins.push_back(b);
    s.ends.push_back(e);
    grand += t;
  };
  if (edge_types == nullptr || n_types == 0) {
    for (int et = 0; et < ET; ++et) consider(et);
  } else {
    for (size_t i = 0; i < n_types; ++i) consider(edge_types[i]);
  }
  size_t ng = s.totals.size();
  if (ng == 0 || grand <= 0.f) return kNoSlot;
  float r = rng->NextFloat() * grand;
  size_t gsel = 0;
  float run = 0.f;
  for (; gsel < ng; ++gsel) {
    run += s.totals[gsel];
    if (r < run) break;
  }
  if (gsel >= ng) gsel = ng - 1;
  return SampleFromCumulative(adj_cumw_.data(), s.begins[gsel], s.ends[gsel],
                              rng);
}

void Graph::SampleNeighbor(NodeId id, const int32_t* edge_types,
                           size_t n_types, size_t count, NodeId default_id,
                           Pcg32* rng, NodeId* out_ids, float* out_w,
                           int32_t* out_t) const {
  // Hot path (every fanout hop): gather the candidate groups ONCE per
  // node, then draw `count` samples — O(ET + count·log(deg)) instead of
  // re-walking groups and upper_bound'ing the global offsets per sample.
  uint32_t idx = NodeIndex(id);
  const int ET = meta_.num_edge_types;
  GroupScratch& s = TlsGroupScratch();
  s.clear();
  float grand = 0.f;
  if (idx != kInvalidIndex) {
    TouchRow(idx);
    auto consider = [&](int et) {
      if (et < 0 || et >= ET) return;
      size_t b, e;
      GroupRange(idx, et, &b, &e);
      if (e <= b) return;
      float t = adj_cumw_[e - 1];
      if (t <= 0.f) return;
      s.totals.push_back(t);
      s.begins.push_back(b);
      s.ends.push_back(e);
      s.types.push_back(et);
      grand += t;
    };
    if (edge_types == nullptr || n_types == 0) {
      for (int et = 0; et < ET; ++et) consider(et);
    } else {
      for (size_t i = 0; i < n_types; ++i) consider(edge_types[i]);
    }
  }
  size_t ng = s.totals.size();
  for (size_t i = 0; i < count; ++i) {
    if (ng == 0 || grand <= 0.f) {
      out_ids[i] = default_id;
      if (out_w) out_w[i] = 0.f;
      if (out_t) out_t[i] = -1;
      continue;
    }
    size_t gsel = 0;
    if (ng > 1) {
      float r = rng->NextFloat() * grand;
      float run = 0.f;
      for (; gsel < ng; ++gsel) {
        run += s.totals[gsel];
        if (r < run) break;
      }
      if (gsel >= ng) gsel = ng - 1;
    }
    size_t slot = SampleFromCumulative(adj_cumw_.data(), s.begins[gsel],
                                       s.ends[gsel], rng);
    out_ids[i] = adj_nbr_[slot];
    if (out_w) out_w[i] = adj_w_[slot];
    if (out_t) out_t[i] = s.types[gsel];
  }
}

void Graph::SampleNeighborBatch(const NodeId* ids, size_t n,
                                const int32_t* edge_types, size_t n_types,
                                size_t count, NodeId default_id, Pcg32* rng,
                                NodeId* out_ids, float* out_w,
                                int32_t* out_t) const {
  const int ET = meta_.num_edge_types;
  constexpr size_t D = 16;  // prefetch distance: ~enough in-flight
                            // misses to cover DRAM latency
  // candidate edge types for every node (same for all — hoisted)
  thread_local std::vector<int32_t> all_et;
  const int32_t* ets = edge_types;
  size_t n_et = n_types;
  if (ets == nullptr || n_et == 0) {
    all_et.resize(ET);
    for (int t = 0; t < ET; ++t) all_et[t] = t;
    ets = all_et.data();
    n_et = static_cast<size_t>(ET);
  }
  // staged scratch, reused across calls on this thread
  struct Scratch {
    std::vector<uint32_t> idx;
    std::vector<size_t> gb, ge;     // [n * n_et] group ranges
    std::vector<float> gtot;        // [n * n_et] group totals
  };
  thread_local Scratch s;
  s.idx.resize(n);
  s.gb.assign(n * n_et, 0);
  s.ge.assign(n * n_et, 0);
  s.gtot.assign(n * n_et, 0.f);

  // pass 1: id → row index (prefetch the dense-id table ahead)
  for (size_t i = 0; i < n; ++i) {
    if (i + D < n && !dense_idx_.empty()) {
      uint64_t off = ids[i + D] - dense_base_;
      if (off < dense_idx_.size()) __builtin_prefetch(&dense_idx_[off]);
    }
    s.idx[i] = NodeIndex(ids[i]);
  }
  // pass 2: group ranges (prefetch adj_offsets_ rows ahead)
  for (size_t i = 0; i < n; ++i) {
    if (i + D < n && s.idx[i + D] != kInvalidIndex) {
      __builtin_prefetch(
          &adj_offsets_[static_cast<size_t>(s.idx[i + D]) * ET]);
    }
    if (s.idx[i] == kInvalidIndex) continue;
    TouchRow(s.idx[i]);
    for (size_t t = 0; t < n_et; ++t) {
      int et = ets[t];
      if (et < 0 || et >= ET) continue;
      GroupRange(s.idx[i], et, &s.gb[i * n_et + t], &s.ge[i * n_et + t]);
    }
  }
  // pass 3: group totals (prefetch each group's last cumw ahead)
  for (size_t i = 0; i < n; ++i) {
    if (i + D < n) {
      for (size_t t = 0; t < n_et; ++t) {
        size_t e = s.ge[(i + D) * n_et + t];
        if (e > s.gb[(i + D) * n_et + t])
          __builtin_prefetch(&adj_cumw_[e - 1]);
      }
    }
    for (size_t t = 0; t < n_et; ++t) {
      size_t b = s.gb[i * n_et + t], e = s.ge[i * n_et + t];
      if (e > b) s.gtot[i * n_et + t] = adj_cumw_[e - 1];
    }
  }
  // pass 4: draws (prefetch the next nodes' cumw/nbr segments)
  for (size_t i = 0; i < n; ++i) {
    if (i + D < n) {
      for (size_t t = 0; t < n_et; ++t) {
        size_t b = s.gb[(i + D) * n_et + t], e = s.ge[(i + D) * n_et + t];
        if (e > b) {
          __builtin_prefetch(&adj_cumw_[b]);
          __builtin_prefetch(&adj_cumw_[(b + e) / 2]);
          __builtin_prefetch(&adj_nbr_[b]);
          __builtin_prefetch(&adj_nbr_[(b + e) / 2]);
        }
      }
    }
    float grand = 0.f;
    for (size_t t = 0; t < n_et; ++t) {
      float tt = s.gtot[i * n_et + t];
      if (tt > 0.f) grand += tt;
    }
    NodeId* oi = out_ids + i * count;
    float* ow = out_w ? out_w + i * count : nullptr;
    int32_t* ot = out_t ? out_t + i * count : nullptr;
    if (grand <= 0.f) {
      for (size_t c = 0; c < count; ++c) {
        oi[c] = default_id;
        if (ow) ow[c] = 0.f;
        if (ot) ot[c] = -1;
      }
      continue;
    }
    for (size_t c = 0; c < count; ++c) {
      size_t gsel = 0;
      float run = 0.f;
      float r = rng->NextFloat() * grand;
      for (size_t t = 0; t < n_et; ++t) {
        float tt = s.gtot[i * n_et + t];
        if (tt <= 0.f) continue;
        run += tt;
        gsel = t;
        if (r < run) break;
      }
      size_t slot = SampleFromCumulative(adj_cumw_.data(),
                                        s.gb[i * n_et + gsel],
                                        s.ge[i * n_et + gsel], rng);
      oi[c] = adj_nbr_[slot];
      if (ow) ow[c] = adj_w_[slot];
      if (ot) ot[c] = static_cast<int32_t>(ets[gsel]);
    }
  }
}

void Graph::GetFullNeighbor(NodeId id, const int32_t* edge_types,
                            size_t n_types, std::vector<NodeId>* ids,
                            std::vector<float>* ws, std::vector<int32_t>* ts,
                            bool sorted_by_id) const {
  uint32_t idx = NodeIndex(id);
  if (idx == kInvalidIndex) return;
  TouchRow(idx);
  const int ET = meta_.num_edge_types;
  auto grab = [&](int et) {
    if (et < 0 || et >= ET) return;
    size_t b, e;
    GroupRange(idx, et, &b, &e);
    for (size_t s = b; s < e; ++s) {
      ids->push_back(adj_nbr_[s]);
      ws->push_back(adj_w_[s]);
      ts->push_back(et);
    }
  };
  size_t base = ids->size();
  if (edge_types == nullptr || n_types == 0) {
    for (int et = 0; et < ET; ++et) grab(et);
  } else {
    for (size_t i = 0; i < n_types; ++i) grab(edge_types[i]);
  }
  if (sorted_by_id && ids->size() > base) {
    // Groups are each id-sorted; across groups a merge is needed. Simple
    // index sort over the appended range keeps the parallel arrays aligned.
    size_t n = ids->size() - base;
    std::vector<uint32_t> ord(n);
    std::iota(ord.begin(), ord.end(), 0);
    std::sort(ord.begin(), ord.end(), [&](uint32_t a, uint32_t b) {
      return (*ids)[base + a] < (*ids)[base + b];
    });
    std::vector<NodeId> tid(n);
    std::vector<float> tw(n);
    std::vector<int32_t> tt(n);
    for (size_t i = 0; i < n; ++i) {
      tid[i] = (*ids)[base + ord[i]];
      tw[i] = (*ws)[base + ord[i]];
      tt[i] = (*ts)[base + ord[i]];
    }
    std::copy(tid.begin(), tid.end(), ids->begin() + base);
    std::copy(tw.begin(), tw.end(), ws->begin() + base);
    std::copy(tt.begin(), tt.end(), ts->begin() + base);
  }
}

void Graph::GetTopKNeighbor(NodeId id, const int32_t* edge_types,
                            size_t n_types, size_t k, NodeId default_id,
                            NodeId* out_ids, float* out_w,
                            int32_t* out_t) const {
  std::vector<NodeId> ids;
  std::vector<float> ws;
  std::vector<int32_t> ts;
  GetFullNeighbor(id, edge_types, n_types, &ids, &ws, &ts);
  std::vector<uint32_t> ord(ids.size());
  std::iota(ord.begin(), ord.end(), 0);
  size_t take = std::min(k, ids.size());
  std::partial_sort(ord.begin(), ord.begin() + take, ord.end(),
                    [&](uint32_t a, uint32_t b) { return ws[a] > ws[b]; });
  for (size_t i = 0; i < k; ++i) {
    if (i < take) {
      out_ids[i] = ids[ord[i]];
      out_w[i] = ws[ord[i]];
      out_t[i] = ts[ord[i]];
    } else {
      out_ids[i] = default_id;
      out_w[i] = 0.f;
      out_t[i] = -1;
    }
  }
}

void Graph::GetFullInNeighbor(NodeId id, const int32_t* edge_types,
                              size_t n_types, std::vector<NodeId>* ids,
                              std::vector<float>* ws,
                              std::vector<int32_t>* ts) const {
  uint32_t idx = NodeIndex(id);
  if (idx == kInvalidIndex || in_adj_offsets_.empty()) return;
  TouchRow(idx);
  const int ET = meta_.num_edge_types;
  auto grab = [&](int et) {
    if (et < 0 || et >= ET) return;
    size_t gi = static_cast<size_t>(idx) * ET + et;
    for (uint64_t s = in_adj_offsets_[gi]; s < in_adj_offsets_[gi + 1]; ++s) {
      ids->push_back(in_adj_nbr_[s]);
      ws->push_back(in_adj_w_[s]);
      ts->push_back(et);
    }
  };
  if (edge_types == nullptr || n_types == 0) {
    for (int et = 0; et < ET; ++et) grab(et);
  } else {
    for (size_t i = 0; i < n_types; ++i) grab(edge_types[i]);
  }
}

void Graph::SampleInNeighbor(NodeId id, const int32_t* edge_types,
                             size_t n_types, size_t count, NodeId default_id,
                             Pcg32* rng, NodeId* out_ids, float* out_w,
                             int32_t* out_t) const {
  // In-adjacency groups share the cumw trick; reuse via a local gather.
  uint32_t idx = NodeIndex(id);
  const int ET = meta_.num_edge_types;
  if (idx == kInvalidIndex || in_adj_offsets_.empty()) {
    for (size_t i = 0; i < count; ++i) {
      out_ids[i] = default_id;
      if (out_w) out_w[i] = 0.f;
      if (out_t) out_t[i] = -1;
    }
    return;
  }
  TouchRow(idx);
  GroupScratch& s = TlsGroupScratch();
  s.clear();
  float grand = 0.f;
  auto consider = [&](int et) {
    if (et < 0 || et >= ET) return;
    size_t gi = static_cast<size_t>(idx) * ET + et;
    uint64_t b = in_adj_offsets_[gi], e = in_adj_offsets_[gi + 1];
    if (e <= b) return;
    float t = in_adj_cumw_[e - 1];
    if (t <= 0.f) return;
    s.totals.push_back(t);
    s.begins.push_back(b);
    s.ends.push_back(e);
    s.types.push_back(et);
    grand += t;
  };
  if (edge_types == nullptr || n_types == 0) {
    for (int et = 0; et < ET; ++et) consider(et);
  } else {
    for (size_t i = 0; i < n_types; ++i) consider(edge_types[i]);
  }
  size_t ng = s.totals.size();
  for (size_t i = 0; i < count; ++i) {
    if (ng == 0 || grand <= 0.f) {
      out_ids[i] = default_id;
      if (out_w) out_w[i] = 0.f;
      if (out_t) out_t[i] = -1;
      continue;
    }
    float r = rng->NextFloat() * grand;
    size_t gsel = 0;
    float run = 0.f;
    for (; gsel < ng; ++gsel) {
      run += s.totals[gsel];
      if (r < run) break;
    }
    if (gsel >= ng) gsel = ng - 1;
    size_t slot = SampleFromCumulative(in_adj_cumw_.data(), s.begins[gsel],
                                       s.ends[gsel], rng);
    out_ids[i] = in_adj_nbr_[slot];
    if (out_w) out_w[i] = in_adj_w_[slot];
    if (out_t) out_t[i] = s.types[gsel];
  }
}

size_t Graph::OutDegree(NodeId id, const int32_t* edge_types,
                        size_t n_types) const {
  uint32_t idx = NodeIndex(id);
  if (idx == kInvalidIndex) return 0;
  TouchRow(idx);
  const int ET = meta_.num_edge_types;
  size_t total = 0;
  auto add = [&](int et) {
    if (et < 0 || et >= ET) return;
    size_t b, e;
    GroupRange(idx, et, &b, &e);
    total += e - b;
  };
  if (edge_types == nullptr || n_types == 0) {
    for (int et = 0; et < ET; ++et) add(et);
  } else {
    for (size_t i = 0; i < n_types; ++i) add(edge_types[i]);
  }
  return total;
}

// ---------------------------------------------------------------------------
// Graph: features
// ---------------------------------------------------------------------------

void Graph::GetDenseFeature(const NodeId* ids, size_t count, int fid,
                            int64_t dim, float* out) const {
  bool have = fid >= 0 && static_cast<size_t>(fid) < node_dense_.size() &&
              !node_dense_[fid].empty();
  int64_t stored_dim =
      have ? std::max<int64_t>(meta_.node_features[fid].dim, 1) : 0;
  for (size_t i = 0; i < count; ++i) {
    float* dst = out + i * dim;
    uint32_t idx = NodeIndex(ids[i]);
    if (!have || idx == kInvalidIndex) {
      std::memset(dst, 0, dim * sizeof(float));
      continue;
    }
    TouchRow(idx);
    int64_t n = std::min(dim, stored_dim);
    std::memcpy(dst, node_dense_[fid].data() + idx * stored_dim,
                n * sizeof(float));
    if (n < dim) std::memset(dst + n, 0, (dim - n) * sizeof(float));
  }
}

void Graph::GetSparseFeature(const NodeId* ids, size_t count, int fid,
                             std::vector<uint64_t>* offsets,
                             std::vector<uint64_t>* values) const {
  offsets->resize(count + 1);
  (*offsets)[0] = 0;
  bool have = fid >= 0 && static_cast<size_t>(fid) < node_var_.size() &&
              !node_var_[fid].offsets.empty();
  for (size_t i = 0; i < count; ++i) {
    uint32_t idx = have ? NodeIndex(ids[i]) : kInvalidIndex;
    if (idx == kInvalidIndex) {
      (*offsets)[i + 1] = (*offsets)[i];
      continue;
    }
    TouchRow(idx);
    const auto& vf = node_var_[fid];
    uint64_t b = vf.offsets[idx], e = vf.offsets[idx + 1];
    values->insert(values->end(), vf.values_u64.begin() + b,
                   vf.values_u64.begin() + e);
    (*offsets)[i + 1] = (*offsets)[i] + (e - b);
  }
}

void Graph::GetBinaryFeature(const NodeId* ids, size_t count, int fid,
                             std::vector<uint64_t>* offsets,
                             std::vector<char>* values) const {
  offsets->resize(count + 1);
  (*offsets)[0] = 0;
  bool have = fid >= 0 && static_cast<size_t>(fid) < node_var_.size() &&
              !node_var_[fid].offsets.empty();
  for (size_t i = 0; i < count; ++i) {
    uint32_t idx = have ? NodeIndex(ids[i]) : kInvalidIndex;
    if (idx == kInvalidIndex) {
      (*offsets)[i + 1] = (*offsets)[i];
      continue;
    }
    TouchRow(idx);
    const auto& vf = node_var_[fid];
    uint64_t b = vf.offsets[idx], e = vf.offsets[idx + 1];
    values->insert(values->end(), vf.values_bytes.begin() + b,
                   vf.values_bytes.begin() + e);
    (*offsets)[i + 1] = (*offsets)[i] + (e - b);
  }
}

uint64_t Graph::EdgeSlot(NodeId src, NodeId dst, int32_t type) const {
  uint32_t idx = NodeIndex(src);
  if (idx == kInvalidIndex) return kNoSlot;
  TouchRow(idx);
  int32_t et = meta_.num_edge_types;
  if (type < 0 || type >= et) return kNoSlot;
  // each (src row, type) group is sorted by dst — binary search beats a
  // 100M+-entry edge map on both memory and build time
  size_t gi = static_cast<size_t>(idx) * et + type;
  uint64_t b = adj_offsets_[gi], e = adj_offsets_[gi + 1];
  auto first = adj_nbr_.begin() + b, last = adj_nbr_.begin() + e;
  auto it = std::lower_bound(first, last, dst);
  if (it == last || *it != dst) return kNoSlot;
  return b + static_cast<uint64_t>(it - first);
}

float Graph::GetEdgeWeight(NodeId src, NodeId dst, int32_t type) const {
  uint64_t slot = EdgeSlot(src, dst, type);
  return slot == kNoSlot ? 0.f : adj_w_[slot];
}

void Graph::GetEdgeDenseFeature(const NodeId* src, const NodeId* dst,
                                const int32_t* type, size_t count, int fid,
                                int64_t dim, float* out) const {
  bool have = fid >= 0 && static_cast<size_t>(fid) < edge_dense_.size() &&
              !edge_dense_[fid].empty();
  int64_t stored_dim =
      have ? std::max<int64_t>(meta_.edge_features[fid].dim, 1) : 0;
  for (size_t i = 0; i < count; ++i) {
    float* dstp = out + i * dim;
    uint64_t slot = have ? EdgeSlot(src[i], dst[i], type[i]) : kNoSlot;
    if (slot == kNoSlot) {
      std::memset(dstp, 0, dim * sizeof(float));
      continue;
    }
    int64_t n = std::min(dim, stored_dim);
    std::memcpy(dstp, edge_dense_[fid].data() + slot * stored_dim,
                n * sizeof(float));
    if (n < dim) std::memset(dstp + n, 0, (dim - n) * sizeof(float));
  }
}

void Graph::GetEdgeSparseFeature(const NodeId* src, const NodeId* dst,
                                 const int32_t* type, size_t count, int fid,
                                 std::vector<uint64_t>* offsets,
                                 std::vector<uint64_t>* values) const {
  offsets->resize(count + 1);
  (*offsets)[0] = 0;
  bool have = fid >= 0 && static_cast<size_t>(fid) < edge_var_.size() &&
              !edge_var_[fid].offsets.empty();
  for (size_t i = 0; i < count; ++i) {
    uint64_t slot = have ? EdgeSlot(src[i], dst[i], type[i]) : kNoSlot;
    if (slot == kNoSlot) {
      (*offsets)[i + 1] = (*offsets)[i];
      continue;
    }
    const auto& vf = edge_var_[fid];
    uint64_t b = vf.offsets[slot], e = vf.offsets[slot + 1];
    values->insert(values->end(), vf.values_u64.begin() + b,
                   vf.values_u64.begin() + e);
    (*offsets)[i + 1] = (*offsets)[i] + (e - b);
  }
}

void Graph::GetEdgeBinaryFeature(const NodeId* src, const NodeId* dst,
                                 const int32_t* type, size_t count, int fid,
                                 std::vector<uint64_t>* offsets,
                                 std::vector<char>* values) const {
  offsets->resize(count + 1);
  (*offsets)[0] = 0;
  bool have = fid >= 0 && static_cast<size_t>(fid) < edge_var_.size() &&
              !edge_var_[fid].offsets.empty();
  for (size_t i = 0; i < count; ++i) {
    uint64_t slot = have ? EdgeSlot(src[i], dst[i], type[i]) : kNoSlot;
    if (slot == kNoSlot) {
      (*offsets)[i + 1] = (*offsets)[i];
      continue;
    }
    const auto& vf = edge_var_[fid];
    uint64_t b = vf.offsets[slot], e = vf.offsets[slot + 1];
    values->insert(values->end(), vf.values_bytes.begin() + b,
                   vf.values_bytes.begin() + e);
    (*offsets)[i + 1] = (*offsets)[i] + (e - b);
  }
}

void Graph::SampleGraphLabel(size_t count, Pcg32* rng, uint64_t* out) const {
  if (label_ids_.empty()) {
    for (size_t i = 0; i < count; ++i) out[i] = 0;
    return;
  }
  for (size_t i = 0; i < count; ++i)
    out[i] = label_ids_[rng->NextUInt(label_ids_.size())];
}

std::shared_ptr<const std::vector<uint64_t>> Graph::OwnedLabels(
    int shard_idx, int shard_num) const {
  // single-entry cache: a server's (shard_idx, shard_num) never changes,
  // so the filter scan runs once, not per sampleGL call. Shared-ptr
  // snapshot keeps a concurrent rebuild (different identity — only
  // possible in tests) from invalidating a sampler mid-draw.
  std::lock_guard<std::mutex> lk(owned_mu_);
  if (owned_ids_ == nullptr || owned_sidx_ != shard_idx ||
      owned_snum_ != shard_num) {
    auto ids = std::make_shared<std::vector<uint64_t>>();
    for (uint64_t id : label_ids_)
      if (static_cast<int>(id % shard_num) == shard_idx)
        ids->push_back(id);
    owned_ids_ = std::move(ids);
    owned_sidx_ = shard_idx;
    owned_snum_ = shard_num;
  }
  return owned_ids_;
}

size_t Graph::OwnedGraphLabelCount(int shard_idx, int shard_num) const {
  if (shard_num <= 1) return label_ids_.size();
  return OwnedLabels(shard_idx, shard_num)->size();
}

void Graph::SampleGraphLabelOwned(size_t count, int shard_idx, int shard_num,
                                  Pcg32* rng, uint64_t* out) const {
  if (shard_num <= 1) {
    SampleGraphLabel(count, rng, out);
    return;
  }
  auto owned = OwnedLabels(shard_idx, shard_num);
  if (owned->empty()) {
    for (size_t i = 0; i < count; ++i) out[i] = 0;
    return;
  }
  for (size_t i = 0; i < count; ++i)
    out[i] = (*owned)[rng->NextUInt(owned->size())];
}

const std::vector<uint32_t>* Graph::GraphNodes(uint64_t label) const {
  auto it = label_rows_.find(label);
  return it == label_rows_.end() ? nullptr : &it->second;
}

// ---------------------------------------------------------------------------
// Streaming deltas: builder reconstruction + delta apply.
// ---------------------------------------------------------------------------

std::unique_ptr<GraphBuilder> BuilderFromGraph(const Graph& g) {
  auto b = std::make_unique<GraphBuilder>();
  *b->mutable_meta() = g.meta_;  // types, names, feature infos, partitions
  const size_t N = g.node_ids_.size();
  const int ET = g.meta_.num_edge_types;
  // Nodes in engine-row order: EnsureNode appends, so row i stays row i
  // through Finalize — the append-only row-identity invariant every
  // derived table (features, alias rows) relies on across deltas.
  for (size_t i = 0; i < N; ++i) {
    b->AddNode(g.node_ids_[i], g.node_types_[i], g.node_weights_[i]);
  }
  // Edges from the adjacency slots. Insertion order does not affect the
  // finalized layout (Finalize sorts by (group, dst)); what matters is
  // that the deduped edge SET and weights round-trip exactly.
  std::vector<NodeId> esrc, edst;
  std::vector<int32_t> etype;
  const size_t E = g.adj_nbr_.size();
  esrc.reserve(E);
  edst.reserve(E);
  etype.reserve(E);
  for (size_t gi = 0; gi < N * static_cast<size_t>(ET); ++gi) {
    NodeId src = g.node_ids_[gi / ET];
    int32_t et = static_cast<int32_t>(gi % ET);
    for (uint64_t s = g.adj_offsets_[gi]; s < g.adj_offsets_[gi + 1]; ++s) {
      b->AddEdge(src, g.adj_nbr_[s], et, g.adj_w_[s]);
      esrc.push_back(src);
      edst.push_back(g.adj_nbr_[s]);
      etype.push_back(et);
    }
  }
  // Whole-graph labels (0 = unlabeled by convention — skip zeros).
  for (size_t i = 0; i < g.graph_labels_.size(); ++i) {
    if (g.graph_labels_[i] != 0) {
      uint64_t gl = g.graph_labels_[i];
      b->SetGraphLabels(&g.node_ids_[i], &gl, 1);
    }
  }
  // Node features. Dense: one bulk call per fid (node_dense_ is exactly
  // N*dim in row order). Sparse/binary: per non-empty row.
  for (size_t fid = 0; fid < g.node_dense_.size(); ++fid) {
    const auto& col = g.node_dense_[fid];
    if (col.empty()) continue;
    int64_t dim = std::max<int64_t>(g.meta_.node_features[fid].dim, 1);
    b->SetNodeDenseBulk(g.node_ids_.data(), N, static_cast<int>(fid), dim,
                        col.data());
  }
  for (size_t fid = 0; fid < g.node_var_.size(); ++fid) {
    const auto& vf = g.node_var_[fid];
    if (vf.offsets.empty()) continue;
    bool sparse = g.meta_.node_features[fid].kind == FeatureKind::kSparse;
    for (size_t r = 0; r < N; ++r) {
      uint64_t lo = vf.offsets[r], hi = vf.offsets[r + 1];
      if (hi <= lo) continue;
      if (sparse) {
        b->SetNodeSparse(g.node_ids_[r], static_cast<int>(fid),
                         vf.values_u64.data() + lo,
                         static_cast<int64_t>(hi - lo));
      } else {
        b->SetNodeBinary(g.node_ids_[r], static_cast<int>(fid),
                         vf.values_bytes.data() + lo,
                         static_cast<int64_t>(hi - lo));
      }
    }
  }
  // Edge features, keyed by the slot-order (src, dst, type) triples.
  for (size_t fid = 0; fid < g.edge_dense_.size(); ++fid) {
    const auto& col = g.edge_dense_[fid];
    if (col.empty()) continue;
    int64_t dim = std::max<int64_t>(g.meta_.edge_features[fid].dim, 1);
    b->SetEdgeDenseBulk(esrc.data(), edst.data(), etype.data(), esrc.size(),
                        static_cast<int>(fid), dim, col.data());
  }
  for (size_t fid = 0; fid < g.edge_var_.size(); ++fid) {
    const auto& vf = g.edge_var_[fid];
    if (vf.offsets.empty()) continue;
    bool sparse = g.meta_.edge_features[fid].kind == FeatureKind::kSparse;
    for (size_t s = 0; s < esrc.size(); ++s) {
      uint64_t lo = vf.offsets[s], hi = vf.offsets[s + 1];
      if (hi <= lo) continue;
      if (sparse) {
        b->SetEdgeSparse(esrc[s], edst[s], etype[s], static_cast<int>(fid),
                         vf.values_u64.data() + lo,
                         static_cast<int64_t>(hi - lo));
      } else {
        b->SetEdgeBinary(esrc[s], edst[s], etype[s], static_cast<int>(fid),
                         vf.values_bytes.data() + lo,
                         static_cast<int64_t>(hi - lo));
      }
    }
  }
  return b;
}

// ---------------------------------------------------------------------------
// OwnershipMap
// ---------------------------------------------------------------------------
OwnershipMap OwnershipMap::Default(int partition_num, int shard_num,
                                   uint64_t epoch) {
  OwnershipMap m;
  m.map_epoch = epoch;
  m.partition_num = std::max(partition_num, 1);
  if (m.partition_num < shard_num) m.partition_num = shard_num;
  m.shard_num = std::max(shard_num, 1);
  m.owners.resize(m.partition_num);
  for (int p = 0; p < m.partition_num; ++p)
    m.owners[p] = {p % m.shard_num};
  return m;
}

std::string OwnershipMap::Encode() const {
  std::string out = "e" + std::to_string(map_epoch) + "-P" +
                    std::to_string(partition_num) + "-";
  for (int p = 0; p < partition_num; ++p) {
    if (p) out += '.';
    const auto& os = owners[p];
    for (size_t i = 0; i < os.size(); ++i) {
      if (i) out += '+';
      out += std::to_string(os[i]);
    }
  }
  return out;
}

Status OwnershipMap::Decode(const std::string& spec, OwnershipMap* out) {
  OwnershipMap m;
  auto bad = [&](const char* why) {
    return Status::InvalidArgument(std::string("bad ownership spec '") +
                                   spec + "': " + why);
  };
  if (spec.size() < 6 || spec[0] != 'e') return bad("want e<E>-P<pn>-...");
  size_t d1 = spec.find("-P", 1);
  if (d1 == std::string::npos) return bad("missing -P");
  size_t d2 = spec.find('-', d1 + 2);
  if (d2 == std::string::npos) return bad("missing owner list");
  m.map_epoch = std::strtoull(spec.substr(1, d1 - 1).c_str(), nullptr, 10);
  m.partition_num =
      std::atoi(spec.substr(d1 + 2, d2 - d1 - 2).c_str());
  if (m.map_epoch == 0) return bad("map_epoch must be > 0");
  if (m.partition_num < 1) return bad("partition_num must be >= 1");
  std::string rest = spec.substr(d2 + 1);
  size_t pos = 0;
  while (pos <= rest.size()) {
    size_t dot = rest.find('.', pos);
    std::string part = rest.substr(
        pos, dot == std::string::npos ? std::string::npos : dot - pos);
    if (part.empty()) return bad("empty partition owner list");
    std::vector<int> os;
    size_t q = 0;
    while (q <= part.size()) {
      size_t plus = part.find('+', q);
      std::string tok = part.substr(
          q, plus == std::string::npos ? std::string::npos : plus - q);
      if (tok.empty() ||
          tok.find_first_not_of("0123456789") != std::string::npos)
        return bad("non-numeric owner");
      int s = std::atoi(tok.c_str());
      // primary stays first; duplicates collapse
      if (std::find(os.begin(), os.end(), s) == os.end()) os.push_back(s);
      m.shard_num = std::max(m.shard_num, s + 1);
      if (plus == std::string::npos) break;
      q = plus + 1;
    }
    m.owners.push_back(std::move(os));
    if (dot == std::string::npos) break;
    pos = dot + 1;
  }
  if (static_cast<int>(m.owners.size()) != m.partition_num)
    return bad("owner-list count != partition_num");
  *out = std::move(m);
  return Status::OK();
}

bool OwnershipMap::Covers(int sup, int shard) const {
  if (sup == shard) return false;
  bool any = false;
  for (int p = 0; p < partition_num; ++p) {
    bool mine = false, theirs = false;
    for (int s : owners[p]) {
      if (s == shard) mine = true;
      if (s == sup) theirs = true;
    }
    if (mine) {
      if (!theirs) return false;
      any = true;
    }
  }
  return any;
}

Status ApplyGraphDelta(const Graph& base, const NodeId* node_ids,
                       const int32_t* node_types, const float* node_weights,
                       size_t n_nodes, const NodeId* edge_src,
                       const NodeId* edge_dst, const int32_t* edge_types,
                       const float* edge_weights, size_t n_edges,
                       int shard_idx, int shard_num,
                       std::unique_ptr<Graph>* out,
                       std::vector<NodeId>* dirty_out,
                       const OwnershipMap* omap) {
  if (shard_num < 1) shard_num = 1;
  if (shard_idx < 0 || (omap == nullptr && shard_idx >= shard_num))
    return Status::InvalidArgument("bad shard index for delta apply");
  const uint64_t P =
      static_cast<uint64_t>(std::max(base.meta().partition_num, 1));
  const bool mapped = omap != nullptr && omap->map_epoch != 0;
  auto owns = [&](NodeId id) {
    // map routing first: ownership is the map's say (a replicated
    // partition lands on every owner), hash only the no-map fallback
    if (mapped) return omap->owns(shard_idx, id);
    if (shard_num <= 1) return true;
    return static_cast<int>((id % P) % shard_num) == shard_idx;
  };
  auto b = BuilderFromGraph(base);
  for (size_t i = 0; i < n_nodes; ++i) {
    if (!owns(node_ids[i])) continue;
    b->AddNode(node_ids[i], node_types ? node_types[i] : 0,
               node_weights ? node_weights[i] : 1.0f);
  }
  for (size_t i = 0; i < n_edges; ++i) {
    // source-owned, matching DumpOnePartition — an edge lands on (and
    // samples from) exactly one shard of a broadcast delta
    if (!owns(edge_src[i])) continue;
    b->AddEdge(edge_src[i], edge_dst[i], edge_types ? edge_types[i] : 0,
               edge_weights ? edge_weights[i] : 1.0f);
  }
  auto g = b->Finalize(base.has_in_adjacency());
  g->set_epoch(base.epoch() + 1);
  if (dirty_out != nullptr) {
    // FULL delta ids (unfiltered): clients invalidate by id, and a node
    // another shard owns may still sit in their caches
    dirty_out->clear();
    dirty_out->reserve(n_nodes + 2 * n_edges);
    dirty_out->insert(dirty_out->end(), node_ids, node_ids + n_nodes);
    dirty_out->insert(dirty_out->end(), edge_src, edge_src + n_edges);
    dirty_out->insert(dirty_out->end(), edge_dst, edge_dst + n_edges);
    std::sort(dirty_out->begin(), dirty_out->end());
    dirty_out->erase(std::unique(dirty_out->begin(), dirty_out->end()),
                     dirty_out->end());
  }
  *out = std::move(g);
  return Status::OK();
}

}  // namespace et

