// Binary graph serialization: partitioned data files + binary meta.
//
// Capability parity with the reference's euler/common/bytes_io.* +
// euler/core/graph/graph_builder.cc partition loading + euler/tools data
// prep (SURVEY.md §2.1/§2.3). Redesigned with a single self-describing
// little-endian record format written by either the Python prep tool
// (euler_tpu/tools/generate_data.py) or Graph::Dump, and loaded
// shard-aware: shard k of n loads partition files p with p % n == k.
//
// Layout (all little-endian):
//   meta.bin   : "ETM1" u32 ver | u32 NT | u32 ET | u32 P | u64 N | u64 E
//                | str name | NT×str | ET×str
//                | u32 nf  | nf×(str name, i32 kind, i64 dim)   [node feats]
//                | u32 nef | nef×(...)                          [edge feats]
//   part_p.dat : "ETP1" u32 ver | u64 n_nodes | node records
//                | u64 n_edges | edge records
//   node rec   : u64 id | i32 type | f32 w | feats
//   edge rec   : u64 src | u64 dst | i32 type | f32 w | feats
//   feats      : u16 nd | nd×(u16 fid, u32 dim, f32×dim)
//                | u16 ns | ns×(u16 fid, u32 len, u64×len)
//                | u16 nb | nb×(u16 fid, u32 len, bytes)
#ifndef EULER_TPU_IO_H_
#define EULER_TPU_IO_H_

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "common.h"
#include "graph.h"

namespace et {

class ByteWriter {
 public:
  // Pre-reserve capacity for `extra` MORE bytes beyond what is already
  // buffered. Encoders with a cheap sizing pass (EncodeTensor,
  // EncodeExecuteReply) call this so large payloads append without
  // vector doubling-reallocs; encoded bytes are unchanged.
  void Reserve(size_t extra) { buf_.reserve(buf_.size() + extra); }
  void PutRaw(const void* p, size_t n) {
    const char* c = static_cast<const char*>(p);
    buf_.insert(buf_.end(), c, c + n);
  }
  template <typename T>
  void Put(T v) {
    PutRaw(&v, sizeof(T));
  }
  void PutStr(const std::string& s) {
    Put<uint32_t>(static_cast<uint32_t>(s.size()));
    PutRaw(s.data(), s.size());
  }
  const std::vector<char>& buffer() const { return buf_; }

 private:
  std::vector<char> buf_;
};

class ByteReader {
 public:
  ByteReader(const char* data, size_t size) : p_(data), end_(data + size) {}
  bool GetRaw(void* out, size_t n) {
    if (p_ + n > end_) return false;
    std::memcpy(out, p_, n);
    p_ += n;
    return true;
  }
  template <typename T>
  bool Get(T* out) {
    return GetRaw(out, sizeof(T));
  }
  bool GetStr(std::string* out) {
    uint32_t n;
    if (!Get(&n) || p_ + n > end_) return false;
    out->assign(p_, n);
    p_ += n;
    return true;
  }
  bool Skip(size_t n) {
    if (p_ + n > end_) return false;
    p_ += n;
    return true;
  }
  const char* cursor() const { return p_; }
  size_t remaining() const { return static_cast<size_t>(end_ - p_); }

 private:
  const char* p_;
  const char* end_;
};

Status ReadFileToString(const std::string& path, std::string* out);
Status WriteStringToFile(const std::string& path, const char* data,
                         size_t size);

Status SaveMeta(const GraphMeta& meta, const std::string& path);
Status LoadMeta(const std::string& path, GraphMeta* meta);

// In-memory forms of the meta.bin encoding (shared with the columnar
// store's embedded meta section — store.cc): identical bytes to
// SaveMeta/LoadMeta, minus the file I/O.
void EncodeMeta(const GraphMeta& meta, ByteWriter* w);
Status DecodeMeta(ByteReader* r, GraphMeta* meta);

// Appends one partition's records into the builder. data_type: 0=all,
// 1=node-only, 2=edge-only (mirrors reference GraphDataType,
// graph_builder.h:42-47).
Status LoadPartitionFile(const std::string& path, int data_type,
                         GraphBuilder* builder);

// Loads meta + the partitions belonging to (shard_idx, shard_num) from a
// directory laid out by the data-prep tool: meta.bin + part_*.dat.
Status LoadShard(const std::string& dir, int shard_idx, int shard_num,
                 int data_type, bool build_in_adjacency,
                 std::unique_ptr<Graph>* out);

// Serializes the whole (local) graph as one partition + meta into dir.
Status DumpGraph(const Graph& g, const std::string& dir);

// Serializes the graph into `num_partitions` partition files (partition of
// id = id % num_partitions, matching the data-prep tool) so a dumped graph
// can be re-served sharded.
Status DumpGraphPartitioned(const Graph& g, const std::string& dir,
                            int num_partitions, bool by_graph = false);

}  // namespace et

#endif  // EULER_TPU_IO_H_
