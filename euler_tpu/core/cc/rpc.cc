#include "rpc.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <poll.h>
#include <dirent.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <sys/stat.h>

#include <chrono>
#include <cstdio>
#include <cstring>
#include <map>
#include <sstream>

#include "threadpool.h"

namespace et {

namespace {
constexpr uint32_t kFrameMagic = 0x52465445;  // 'ETFR'

enum MsgType : uint32_t {
  kExecute = 0,
  kMeta = 1,
  kPing = 2,
  kRegPut = 3,     // body: entry name → registry stores/refreshes it
  kRegList = 4,    // body: empty → u32 version | u32 count | per entry:
                   // str name, i64 age_ms, u64 put-sequence
  kRegRemove = 5,  // body: entry name → dropped (clean shutdown)
};

// kRegList reply schema version: mixed-binary registry pairs must fail
// loudly, not misparse (the reply has no other self-description).
constexpr uint32_t kRegListVersion = 2;

bool WriteAll(int fd, const char* p, size_t n) {
  while (n > 0) {
    ssize_t w = ::write(fd, p, n);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool ReadAll(int fd, char* p, size_t n) {
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool WriteFrame(int fd, uint32_t msg_type, const char* body, size_t len) {
  char hdr[16];
  std::memcpy(hdr, &kFrameMagic, 4);
  std::memcpy(hdr + 4, &msg_type, 4);
  uint64_t l = len;
  std::memcpy(hdr + 8, &l, 8);
  return WriteAll(fd, hdr, 16) && WriteAll(fd, body, len);
}

bool ReadFrame(int fd, uint32_t* msg_type, std::vector<char>* body) {
  char hdr[16];
  if (!ReadAll(fd, hdr, 16)) return false;
  uint32_t magic;
  std::memcpy(&magic, hdr, 4);
  if (magic != kFrameMagic) return false;
  std::memcpy(msg_type, hdr + 4, 4);
  uint64_t len;
  std::memcpy(&len, hdr + 8, 8);
  if (len > (1ULL << 33)) return false;  // 8 GiB sanity cap
  body->resize(len);
  return len == 0 || ReadAll(fd, body->data(), len);
}
}  // namespace

// ---------------------------------------------------------------------------
// ShardMeta serde
// ---------------------------------------------------------------------------
void EncodeShardMeta(const ShardMeta& m, ByteWriter* w) {
  w->Put<int32_t>(m.shard_idx);
  w->Put<int32_t>(m.shard_num);
  w->Put<int32_t>(m.partition_num);
  w->Put<uint32_t>(static_cast<uint32_t>(m.node_type_wsum.size()));
  for (float f : m.node_type_wsum) w->Put<float>(f);
  w->Put<uint32_t>(static_cast<uint32_t>(m.edge_type_wsum.size()));
  for (float f : m.edge_type_wsum) w->Put<float>(f);
  w->Put<uint64_t>(m.graph_label_count);
  w->Put<uint64_t>(m.owned_graph_label_count);
  const GraphMeta& gm = m.graph_meta;
  w->PutStr(gm.name);
  w->Put<int32_t>(gm.num_node_types);
  w->Put<int32_t>(gm.num_edge_types);
  w->Put<uint64_t>(gm.node_count);
  w->Put<uint64_t>(gm.edge_count);
  auto put_feats = [&](const std::vector<FeatureInfo>& fs) {
    w->Put<uint32_t>(static_cast<uint32_t>(fs.size()));
    for (const auto& f : fs) {
      w->PutStr(f.name);
      w->Put<int32_t>(static_cast<int32_t>(f.kind));
      w->Put<int64_t>(f.dim);
    }
  };
  put_feats(gm.node_features);
  put_feats(gm.edge_features);
  auto put_names = [&](const std::vector<std::string>& ns) {
    w->Put<uint32_t>(static_cast<uint32_t>(ns.size()));
    for (const auto& s : ns) w->PutStr(s);
  };
  put_names(gm.node_type_names);
  put_names(gm.edge_type_names);
}

Status DecodeShardMeta(ByteReader* r, ShardMeta* m) {
  uint32_t n;
  if (!r->Get(&m->shard_idx) || !r->Get(&m->shard_num) ||
      !r->Get(&m->partition_num) || !r->Get(&n))
    return Status::IOError("truncated shard meta");
  m->node_type_wsum.resize(n);
  for (uint32_t i = 0; i < n; ++i)
    if (!r->Get(&m->node_type_wsum[i]))
      return Status::IOError("truncated weights");
  if (!r->Get(&n)) return Status::IOError("truncated shard meta");
  m->edge_type_wsum.resize(n);
  for (uint32_t i = 0; i < n; ++i)
    if (!r->Get(&m->edge_type_wsum[i]))
      return Status::IOError("truncated weights");
  if (!r->Get(&m->graph_label_count) ||
      !r->Get(&m->owned_graph_label_count))
    return Status::IOError("truncated shard meta");
  GraphMeta& gm = m->graph_meta;
  if (!r->GetStr(&gm.name) || !r->Get(&gm.num_node_types) ||
      !r->Get(&gm.num_edge_types) || !r->Get(&gm.node_count) ||
      !r->Get(&gm.edge_count))
    return Status::IOError("truncated graph meta");
  auto get_feats = [&](std::vector<FeatureInfo>* fs) -> bool {
    uint32_t k;
    if (!r->Get(&k)) return false;
    fs->resize(k);
    for (uint32_t i = 0; i < k; ++i) {
      int32_t kind;
      if (!r->GetStr(&(*fs)[i].name) || !r->Get(&kind) ||
          !r->Get(&(*fs)[i].dim))
        return false;
      (*fs)[i].kind = static_cast<FeatureKind>(kind);
    }
    return true;
  };
  auto get_names = [&](std::vector<std::string>* ns) -> bool {
    uint32_t k;
    if (!r->Get(&k)) return false;
    ns->resize(k);
    for (uint32_t i = 0; i < k; ++i)
      if (!r->GetStr(&(*ns)[i])) return false;
    return true;
  };
  if (!get_feats(&gm.node_features) || !get_feats(&gm.edge_features) ||
      !get_names(&gm.node_type_names) || !get_names(&gm.edge_type_names))
    return Status::IOError("truncated graph meta tail");
  return Status::OK();
}

// ---------------------------------------------------------------------------
// GraphServer
// ---------------------------------------------------------------------------
GraphServer::GraphServer(std::shared_ptr<const Graph> graph,
                         std::shared_ptr<IndexManager> index, int shard_idx,
                         int shard_num, int partition_num)
    : graph_(std::move(graph)),
      index_(std::move(index)),
      shard_idx_(shard_idx),
      shard_num_(shard_num),
      partition_num_(partition_num) {}

GraphServer::~GraphServer() { Stop(); }

Status GraphServer::Start(int port) {
  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd_ < 0) return Status::IOError("socket() failed");
  int one = 1;
  ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
      0)
    return Status::IOError("bind() failed on port " + std::to_string(port));
  if (::listen(listen_fd_, 128) != 0)
    return Status::IOError("listen() failed");
  socklen_t len = sizeof(addr);
  ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
  port_ = ntohs(addr.sin_port);
  acceptor_ = std::thread([this] { AcceptLoop(); });
  ET_LOG(INFO) << "graph shard " << shard_idx_ << "/" << shard_num_
               << " serving on port " << port_;
  return Status::OK();
}

void GraphServer::Stop() {
  if (stopping_.exchange(true)) return;
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
  }
  if (acceptor_.joinable()) acceptor_.join();
  // Shut down open sockets so reader threads unblock, then join outside the
  // lock (the threads deregister their fds under conn_mu_ on exit).
  std::vector<Conn> to_join;
  {
    std::lock_guard<std::mutex> lk(conn_mu_);
    for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    to_join = std::move(conns_);
    conns_.clear();
  }
  for (auto& c : to_join)
    if (c.thread.joinable()) c.thread.join();
  {
    std::lock_guard<std::mutex> lk(conn_mu_);
    conn_fds_.clear();
  }
  {
    // pair the stopping_ store with hb_mu_ so the notify can't land in
    // the heartbeat thread's predicate-check window (missed wakeup =
    // Stop stalls a full heartbeat period)
    std::lock_guard<std::mutex> lk(hb_mu_);
  }
  hb_cv_.notify_all();
  if (heartbeat_.joinable()) heartbeat_.join();
  // clean shutdown unregisters (file unlink or tcp kRegRemove); a crash
  // skips this and the entry goes stale instead
  if (!reg_spec_.empty()) RegistryRemoveEntry(reg_spec_, reg_name_);
}

void GraphServer::ReapFinishedLocked() {
  size_t kept = 0;
  for (size_t i = 0; i < conns_.size(); ++i) {
    if (conns_[i].finished->load()) {
      conns_[i].thread.join();
    } else {
      if (kept != i) conns_[kept] = std::move(conns_[i]);
      ++kept;
    }
  }
  conns_.resize(kept);
}

Status GraphServer::Register(const std::string& registry,
                             const std::string& host, int heartbeat_ms) {
  std::ostringstream os;
  os << "shard_" << shard_idx_ << "__" << host << "_" << port_;
  reg_spec_ = registry;
  reg_name_ = os.str();
  ET_RETURN_IF_ERROR(RegistryPutEntry(reg_spec_, reg_name_));
  if (heartbeat_ms > 0 && !heartbeat_.joinable()) {
    heartbeat_ = std::thread([this, heartbeat_ms] {
      std::unique_lock<std::mutex> lk(hb_mu_);
      while (!hb_cv_.wait_for(lk, std::chrono::milliseconds(heartbeat_ms),
                              [this] { return stopping_.load(); })) {
        // re-put: monitors treat a fresh entry as "alive" (ephemeral
        // ZK-node semantics — file mtime or registry-server timestamp)
        RegistryPutEntry(reg_spec_, reg_name_);
      }
    });
  }
  return Status::OK();
}

void GraphServer::AcceptLoop() {
  for (;;) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) {
      if (stopping_.load()) return;
      continue;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::lock_guard<std::mutex> lk(conn_mu_);
    ReapFinishedLocked();
    Conn c;
    c.finished = std::make_shared<std::atomic<bool>>(false);
    auto flag = c.finished;
    conn_fds_.push_back(fd);
    c.thread = std::thread([this, fd, flag] {
      HandleConnection(fd);
      flag->store(true);
    });
    conns_.push_back(std::move(c));
  }
}

void GraphServer::HandleConnection(int fd) {
  std::vector<char> body;
  uint32_t msg_type;
  while (!stopping_.load() && ReadFrame(fd, &msg_type, &body)) {
    ByteWriter w;
    if (msg_type == kExecute) {
      ByteReader r(body.data(), body.size());
      HandleExecute(&r, &w);
    } else if (msg_type == kMeta) {
      ShardMeta m;
      m.shard_idx = shard_idx_;
      m.shard_num = shard_num_;
      m.partition_num = partition_num_;
      m.node_type_wsum = graph_->node_type_weight_sums();
      m.graph_label_count = graph_->graph_label_count();
      m.owned_graph_label_count =
          graph_->OwnedGraphLabelCount(shard_idx_, shard_num_);
      m.edge_type_wsum = graph_->edge_type_weight_sums();
      m.graph_meta = graph_->meta();
      EncodeShardMeta(m, &w);
    } else {  // ping
      w.Put<uint32_t>(0);
    }
    if (!WriteFrame(fd, msg_type, w.buffer().data(), w.buffer().size()))
      break;
  }
  ::close(fd);
  std::lock_guard<std::mutex> lk(conn_mu_);
  for (size_t i = 0; i < conn_fds_.size(); ++i) {
    if (conn_fds_[i] == fd) {
      conn_fds_[i] = conn_fds_.back();
      conn_fds_.pop_back();
      break;
    }
  }
}

void GraphServer::HandleExecute(ByteReader* r, ByteWriter* w) {
  ExecuteRequest req;
  ExecuteReply rep;
  Status s = DecodeExecuteRequest(r, &req);
  if (s.ok()) {
    // Parity: GrpcWorker::ExecuteAsync (grpc_worker.cc:40-96): ctx from
    // request inputs → run the DAG on the shared pool → encode outputs.
    OpKernelContext ctx;
    for (auto& kv : req.inputs) ctx.Put(kv.first, std::move(kv.second));
    DAGDef dag;
    dag.nodes = std::move(req.nodes);
    QueryEnv env;
    env.graph = graph_.get();
    env.index = index_.get();
    env.pool = GlobalThreadPool();
    Executor exec(&dag, env, &ctx);
    s = exec.RunSync();
    if (s.ok()) {
      for (const auto& name : req.outputs) {
        Tensor t;
        if (!ctx.Get(name, &t)) {
          s = Status::NotFound("requested output not produced: " + name);
          break;
        }
        rep.outputs.emplace_back(name, std::move(t));
      }
    }
  }
  rep.status = s;
  if (!s.ok()) rep.outputs.clear();
  EncodeExecuteReply(rep, w);
}

// ---------------------------------------------------------------------------
// RpcChannel
// ---------------------------------------------------------------------------
RpcChannel::RpcChannel(std::string host, int port)
    : host_(std::move(host)), port_(port) {}

RpcChannel::~RpcChannel() {
  std::lock_guard<std::mutex> lk(mu_);
  for (int fd : free_fds_) ::close(fd);
}

int RpcChannel::Connect() {
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  std::string port_s = std::to_string(port_);
  if (::getaddrinfo(host_.c_str(), port_s.c_str(), &hints, &res) != 0)
    return -1;
  int fd = -1;
  for (addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (timeout_ms_ > 0) {
      // bounded connect: a black-holed host would otherwise block the
      // kernel SYN-retry timeout (~2 min) — registry heartbeat/shutdown
      // paths cap this (see set_timeout_ms callers)
      int flags = ::fcntl(fd, F_GETFL, 0);
      ::fcntl(fd, F_SETFL, flags | O_NONBLOCK);
      int rc = ::connect(fd, ai->ai_addr, ai->ai_addrlen);
      if (rc != 0 && errno == EINPROGRESS) {
        pollfd pf{fd, POLLOUT, 0};
        rc = ::poll(&pf, 1, timeout_ms_) == 1 ? 0 : -1;
        if (rc == 0) {
          int err = 0;
          socklen_t len = sizeof(err);
          ::getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
          if (err != 0) rc = -1;
        }
      }
      ::fcntl(fd, F_SETFL, flags);
      if (rc == 0) {
        timeval tv{timeout_ms_ / 1000, (timeout_ms_ % 1000) * 1000};
        ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
        ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
        break;
      }
    } else if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
      break;
    }
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  if (fd >= 0) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }
  return fd;
}

int RpcChannel::Acquire() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!free_fds_.empty()) {
      int fd = free_fds_.back();
      free_fds_.pop_back();
      return fd;
    }
  }
  return Connect();
}

void RpcChannel::Release(int fd) {
  std::lock_guard<std::mutex> lk(mu_);
  free_fds_.push_back(fd);
}

Status RpcChannel::Call(uint32_t msg_type, const std::vector<char>& body,
                        std::vector<char>* reply_body, int max_retries) {
  if (max_retries <= 0) max_retries = kRetryCount;
  for (int attempt = 0; attempt < max_retries; ++attempt) {
    int fd = Acquire();
    if (fd < 0) {
      ::usleep(1000 * (1 << std::min(attempt, 6)));
      continue;
    }
    uint32_t reply_type;
    if (WriteFrame(fd, msg_type, body.data(), body.size()) &&
        ReadFrame(fd, &reply_type, reply_body) && reply_type == msg_type) {
      Release(fd);
      return Status::OK();
    }
    ::close(fd);  // broken connection — retry on a fresh one
  }
  return Status::IOError("rpc to " + host_ + ":" + std::to_string(port_) +
                         " failed after retries");
}

// ---------------------------------------------------------------------------
// Registry server (TCP) + spec-aware registry access
// ---------------------------------------------------------------------------
RegistryServer::~RegistryServer() { Stop(); }

Status RegistryServer::Start(int port) {
  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd_ < 0) return Status::IOError("socket() failed");
  int one = 1;
  ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
      0)
    return Status::IOError("registry bind() failed on port " +
                           std::to_string(port));
  if (::listen(listen_fd_, 64) != 0)
    return Status::IOError("registry listen() failed");
  socklen_t len = sizeof(addr);
  ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
  port_ = ntohs(addr.sin_port);
  acceptor_ = std::thread([this] { AcceptLoop(); });
  ET_LOG(INFO) << "registry server on port " << port_;
  return Status::OK();
}

void RegistryServer::Stop() {
  if (stopping_.exchange(true)) return;
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
  }
  if (acceptor_.joinable()) acceptor_.join();
  std::vector<std::thread> to_join;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (size_t i = 0; i < conn_fds_.size(); ++i) {
      // finished conns already closed their fd — the number may have
      // been recycled by an unrelated descriptor
      if (!done_[i]->load()) ::shutdown(conn_fds_[i], SHUT_RDWR);
    }
    to_join = std::move(conns_);
    conns_.clear();
    done_.clear();
  }
  for (auto& t : to_join)
    if (t.joinable()) t.join();
  std::lock_guard<std::mutex> lk(mu_);
  conn_fds_.clear();
}

void RegistryServer::AcceptLoop() {
  for (;;) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) {
      if (stopping_.load()) return;
      // EMFILE/ECONNABORTED etc: back off instead of pinning a core
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
      continue;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::lock_guard<std::mutex> lk(mu_);
    // reap exited connections — heartbeats/polls open one short-lived
    // connection each, so without this the thread/fd lists grow without
    // bound and Stop() would shutdown() long-recycled fd numbers
    for (size_t i = 0; i < conns_.size();) {
      if (done_[i]->load()) {
        conns_[i].join();
        conns_.erase(conns_.begin() + i);
        done_.erase(done_.begin() + i);
        conn_fds_.erase(conn_fds_.begin() + i);
      } else {
        ++i;
      }
    }
    conn_fds_.push_back(fd);
    done_.push_back(std::make_shared<std::atomic<bool>>(false));
    auto flag = done_.back();
    conns_.emplace_back([this, fd, flag] {
      HandleConnection(fd);
      flag->store(true);  // before close: Stop() skips done fds, so a
      ::close(fd);        // recycled fd number can't be shutdown() here
    });
  }
}

void RegistryServer::HandleConnection(int fd) {
  auto now_ms = [] {
    return std::chrono::duration_cast<std::chrono::milliseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  };
  std::vector<char> body;
  uint32_t msg_type;
  while (!stopping_.load() && ReadFrame(fd, &msg_type, &body)) {
    ByteWriter w;
    if (msg_type == kRegPut) {
      std::string name(body.data(), body.size());
      {
        std::lock_guard<std::mutex> lk(mu_);
        entries_[name] = {now_ms(), ++put_seq_};
      }
      w.Put<int32_t>(0);
    } else if (msg_type == kRegRemove) {
      std::string name(body.data(), body.size());
      {
        std::lock_guard<std::mutex> lk(mu_);
        entries_.erase(name);
      }
      w.Put<int32_t>(0);
    } else if (msg_type == kRegList) {
      std::lock_guard<std::mutex> lk(mu_);
      w.Put<uint32_t>(kRegListVersion);
      w.Put<uint32_t>(static_cast<uint32_t>(entries_.size()));
      int64_t now = now_ms();
      for (const auto& kv : entries_) {
        w.PutStr(kv.first);
        w.Put<int64_t>(now - kv.second.first);
        w.Put<uint64_t>(kv.second.second);
      }
    } else {
      w.Put<int32_t>(-1);
    }
    if (!WriteFrame(fd, msg_type, w.buffer().data(), w.buffer().size()))
      break;
  }
  ::close(fd);
}

// ---------------------------------------------------------------------------
// Discovery (spec-aware: directory registries and tcp: registry servers)
// ---------------------------------------------------------------------------
namespace {
bool SplitTcpSpec(const std::string& spec, std::string* host, int* port) {
  if (spec.rfind("tcp:", 0) != 0) return false;
  auto rest = spec.substr(4);
  auto pos = rest.rfind(':');
  if (pos == std::string::npos) return false;
  *host = rest.substr(0, pos);
  *port = std::atoi(rest.substr(pos + 1).c_str());
  return true;
}

// "shard_<i>__<host>_<port>" -> parts; false for foreign entries.
bool ParseShardEntry(const std::string& name, int* idx, std::string* host,
                     int* port) {
  if (name.rfind("shard_", 0) != 0) return false;
  auto sep = name.find("__");
  if (sep == std::string::npos) return false;
  *idx = std::atoi(name.substr(6, sep - 6).c_str());
  auto last = name.rfind('_');
  if (last == std::string::npos || last <= sep + 1) return false;
  *host = name.substr(sep + 2, last - sep - 2);
  *port = std::atoi(name.substr(last + 1).c_str());
  return *idx >= 0;
}

std::string DirOfSpec(const std::string& spec) {
  return spec.rfind("dir:", 0) == 0 ? spec.substr(4) : spec;
}
}  // namespace

Status RegistryPutEntry(const std::string& spec, const std::string& name) {
  std::string host;
  int port;
  if (SplitTcpSpec(spec, &host, &port)) {
    RpcChannel ch(host, port);
    ch.set_timeout_ms(3000);
    std::vector<char> body(name.begin(), name.end()), reply;
    // 2 bounded attempts: heartbeats repeat anyway; a long retry ladder
    // here would stall the heartbeat thread (and Stop(), which joins
    // it) behind an unreachable registry host
    return ch.Call(kRegPut, body, &reply, /*max_retries=*/2);
  }
  return WriteStringToFile(DirOfSpec(spec) + "/" + name, "", 0);
}

Status RegistryRemoveEntry(const std::string& spec,
                           const std::string& name) {
  std::string host;
  int port;
  if (SplitTcpSpec(spec, &host, &port)) {
    RpcChannel ch(host, port);
    ch.set_timeout_ms(3000);
    std::vector<char> body(name.begin(), name.end()), reply;
    // best-effort single bounded attempt: shutdown must never block on
    // a partitioned registry (the entry just goes stale instead)
    return ch.Call(kRegRemove, body, &reply, /*max_retries=*/1);
  }
  std::remove((DirOfSpec(spec) + "/" + name).c_str());
  return Status::OK();
}

Status ScanRegistrySpec(const std::string& spec,
                        std::map<int, std::pair<std::string, int>>* found,
                        std::map<int, int64_t>* ages_ms) {
  std::string rhost;
  int rport;
  if (SplitTcpSpec(spec, &rhost, &rport)) {
    RpcChannel ch(rhost, rport);
    ch.set_timeout_ms(3000);
    std::vector<char> reply;
    ET_RETURN_IF_ERROR(ch.Call(kRegList, {}, &reply, /*max_retries=*/2));
    ByteReader r(reply.data(), reply.size());
    uint32_t ver, n;
    if (!r.Get(&ver)) return Status::IOError("truncated registry listing");
    if (ver != kRegListVersion)
      return Status::IOError(
          "registry protocol version mismatch: server speaks v" +
          std::to_string(ver) + ", this client v" +
          std::to_string(kRegListVersion) +
          " — upgrade the older binary");
    if (!r.Get(&n)) return Status::IOError("truncated registry listing");
    std::map<int, uint64_t> best_seq;
    for (uint32_t i = 0; i < n; ++i) {
      std::string name;
      int64_t age;
      uint64_t seq;
      if (!r.GetStr(&name) || !r.Get(&age) || !r.Get(&seq))
        return Status::IOError("truncated registry entry");
      int idx, port;
      std::string host;
      if (!ParseShardEntry(name, &idx, &host, &port)) continue;
      // duplicate indices (a crashed server's entry + its replacement):
      // the LATEST registration wins — the server's put sequence is
      // exact insertion recency (ms ages tie within a clock tick)
      auto it = best_seq.find(idx);
      if (it != best_seq.end() && it->second >= seq) continue;
      best_seq[idx] = seq;
      (*found)[idx] = {host, port};
      if (ages_ms != nullptr) (*ages_ms)[idx] = age;
    }
    return Status::OK();
  }
  // File mode: one directory scan; duplicate indices keep the last entry
  // in name order (a stale file left by a crashed server plus its
  // replacement resolves deterministically). Age = wall now - mtime.
  std::string dir = DirOfSpec(spec);
  DIR* d = ::opendir(dir.c_str());
  if (d == nullptr)
    return Status::IOError("cannot open registry dir " + dir);
  dirent* e;
  int64_t now = static_cast<int64_t>(::time(nullptr)) * 1000;
  std::map<int, int64_t> best_age;
  while ((e = ::readdir(d)) != nullptr) {
    int idx, port;
    std::string host;
    if (!ParseShardEntry(e->d_name, &idx, &host, &port)) continue;
    struct stat st;
    std::string path = dir + "/" + e->d_name;
    int64_t age = ::stat(path.c_str(), &st) == 0
                      ? now - static_cast<int64_t>(st.st_mtime) * 1000
                      : (1LL << 60);
    // duplicate indices: youngest mtime wins (see tcp path)
    auto it = best_age.find(idx);
    if (it != best_age.end() && it->second <= age) continue;
    best_age[idx] = age;
    (*found)[idx] = {host, port};
    if (ages_ms != nullptr) (*ages_ms)[idx] = age;
  }
  ::closedir(d);
  return Status::OK();
}

// ---------------------------------------------------------------------------
// ServerMonitor
// ---------------------------------------------------------------------------
ServerMonitor::ServerMonitor(std::string registry_dir, int interval_ms,
                             int stale_ms)
    : dir_(std::move(registry_dir)),
      interval_ms_(interval_ms),
      stale_ms_(stale_ms) {}

ServerMonitor::~ServerMonitor() { Stop(); }

void ServerMonitor::Start(Callback cb) {
  cb_ = std::move(cb);
  thread_ = std::thread([this] { Loop(); });
}

void ServerMonitor::Stop() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (stopping_) return;
    stopping_ = true;
  }
  cv_.notify_all();
  if (thread_.joinable()) thread_.join();
}

void ServerMonitor::Loop() {
  for (;;) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      if (cv_.wait_for(lk, std::chrono::milliseconds(interval_ms_),
                       [this] { return stopping_; }))
        return;
    }
    std::map<int, std::pair<std::string, int>> found;
    std::map<int, int64_t> ages;
    if (!ScanRegistrySpec(dir_, &found, &ages).ok()) continue;
    // stale registrations count as down (heartbeat stopped)
    for (auto it = found.begin(); it != found.end();) {
      if (stale_ms_ > 0 && ages[it->first] > stale_ms_)
        it = found.erase(it);
      else
        ++it;
    }
    // diff against last view → up/down callbacks
    for (const auto& kv : found) {
      auto prev = live_.find(kv.first);
      if (prev == live_.end() || prev->second != kv.second)
        cb_(kv.first, kv.second.first, kv.second.second, true);
    }
    for (const auto& kv : live_) {
      if (found.find(kv.first) == found.end())
        cb_(kv.first, kv.second.first, kv.second.second, false);
    }
    live_ = std::move(found);
  }
}

Status DiscoverFromRegistry(const std::string& registry_dir, int shard_num,
                            ShardEndpoints* out) {
  std::map<int, std::pair<std::string, int>> found;
  ET_RETURN_IF_ERROR(ScanRegistrySpec(registry_dir, &found, nullptr));
  out->endpoints.assign(shard_num, {"", 0});
  int unique = 0;
  for (const auto& kv : found) {
    if (kv.first < shard_num) {
      out->endpoints[kv.first] = kv.second;
      ++unique;
    }
  }
  if (unique < shard_num)
    return Status::NotFound("registry has " + std::to_string(unique) + "/" +
                            std::to_string(shard_num) + " shards");
  return Status::OK();
}

Status DiscoverFromRegistryAuto(const std::string& registry_dir,
                                ShardEndpoints* out) {
  std::map<int, std::pair<std::string, int>> found;
  ET_RETURN_IF_ERROR(ScanRegistrySpec(registry_dir, &found, nullptr));
  if (found.empty())
    return Status::NotFound("no shard files in registry " + registry_dir);
  int shard_num = found.rbegin()->first + 1;
  if (static_cast<int>(found.size()) != shard_num)
    return Status::NotFound("registry " + registry_dir + " has " +
                            std::to_string(found.size()) + " shards but max "
                            "index implies " + std::to_string(shard_num));
  out->endpoints.assign(shard_num, {"", 0});
  for (const auto& kv : found) out->endpoints[kv.first] = kv.second;
  return Status::OK();
}

Status DiscoverFromSpec(const std::string& spec, ShardEndpoints* out) {
  out->endpoints.clear();
  std::stringstream ss(spec);
  std::string item;
  while (std::getline(ss, item, ',')) {
    if (item.empty()) continue;
    auto pos = item.rfind(':');
    if (pos == std::string::npos)
      return Status::InvalidArgument("bad host:port: " + item);
    out->endpoints.emplace_back(item.substr(0, pos),
                                std::atoi(item.substr(pos + 1).c_str()));
  }
  if (out->endpoints.empty())
    return Status::InvalidArgument("empty endpoint spec");
  return Status::OK();
}

// ---------------------------------------------------------------------------
// ClientManager
// ---------------------------------------------------------------------------
ClientManager::~ClientManager() {
  if (monitor_) monitor_->Stop();
  // block until no pool-scheduled RefreshMeta can touch us anymore
  std::lock_guard<std::mutex> lk(life_->first);
  life_->second = true;
}

std::shared_ptr<RpcChannel> ClientManager::Channel(int shard) const {
  std::lock_guard<std::mutex> lk(chan_mu_);
  return channels_[shard];
}

void ClientManager::WatchRegistry(const std::string& dir, int interval_ms,
                                  int stale_ms) {
  monitor_ = std::make_unique<ServerMonitor>(dir, interval_ms, stale_ms);
  monitor_->Start([this](int shard, const std::string& host, int port,
                         bool up) {
    if (shard < 0 || shard >= shard_num()) return;
    if (up) {
      std::shared_ptr<RpcChannel> fresh;
      {
        std::lock_guard<std::mutex> lk(chan_mu_);
        if (channels_[shard]->host() != host ||
            channels_[shard]->port() != port) {
          ET_LOG_INFO << "shard " << shard << " re-resolved to " << host
                      << ":" << port;
          channels_[shard] = std::make_shared<RpcChannel>(host, port);
          fresh = channels_[shard];
        }
      }
      if (fresh) {
        // off the monitor thread: keep the registry poll cadence steady.
        // The RPC runs before taking the life lock so a slow shard can't
        // stall ~ClientManager for a whole call timeout.
        auto life = life_;
        ClientThreadPool()->Schedule([this, life, shard, fresh] {
          std::vector<char> body, reply;
          Status s = fresh->Call(kMeta, body, &reply);
          std::lock_guard<std::mutex> lk(life->first);
          if (life->second) return;  // manager destroyed meanwhile
          RefreshMeta(shard, s, reply);
        });
      }
    } else {
      ET_LOG_INFO << "shard " << shard << " registration lost (" << host
                  << ":" << port << ")";
      // keep the channel: in-flight calls fail+retry and recover when the
      // shard re-registers (the up path swaps in the new endpoint)
    }
  });
}

Status ClientManager::Init(const ShardEndpoints& eps) {
  channels_.clear();
  for (const auto& ep : eps.endpoints)
    channels_.push_back(std::make_shared<RpcChannel>(ep.first, ep.second));
  std::vector<ShardMeta> metas(channels_.size());
  for (size_t s = 0; s < channels_.size(); ++s) {
    std::vector<char> body, reply;
    ET_RETURN_IF_ERROR(channels_[s]->Call(kMeta, body, &reply));
    ByteReader r(reply.data(), reply.size());
    ET_RETURN_IF_ERROR(DecodeShardMeta(&r, &metas[s]));
  }
  if (!metas.empty()) {
    graph_meta_ = metas[0].graph_meta;
    partition_num_ = metas[0].partition_num;
  }
  std::lock_guard<std::mutex> lk(meta_mu_);  // vs in-flight RefreshMeta
  metas_ = std::move(metas);
  return Status::OK();
}

void ClientManager::RefreshMeta(int shard, const Status& call_status,
                                const std::vector<char>& reply) {
  Status s = call_status;
  ShardMeta m;
  if (s.ok()) {
    ByteReader r(reply.data(), reply.size());
    s = DecodeShardMeta(&r, &m);
  }
  if (!s.ok()) {
    ET_LOG_INFO << "shard " << shard
                << " meta refresh after failover failed: " << s.message();
    return;
  }
  std::lock_guard<std::mutex> lk(meta_mu_);
  if (shard < static_cast<int>(metas_.size())) metas_[shard] = std::move(m);
}

float ClientManager::NodeWeight(int shard, int type) const {
  std::lock_guard<std::mutex> lk(meta_mu_);
  const auto& w = metas_[shard].node_type_wsum;
  if (type >= 0)
    return type < static_cast<int>(w.size()) ? w[type] : 0.f;
  float s = 0;
  for (float f : w) s += f;
  return s;
}

float ClientManager::GraphLabelWeight(int shard, bool owned) const {
  std::lock_guard<std::mutex> lk(meta_mu_);
  return static_cast<float>(owned ? metas_[shard].owned_graph_label_count
                                  : metas_[shard].graph_label_count);
}

float ClientManager::EdgeWeight(int shard, int type) const {
  std::lock_guard<std::mutex> lk(meta_mu_);
  const auto& w = metas_[shard].edge_type_wsum;
  if (type >= 0)
    return type < static_cast<int>(w.size()) ? w[type] : 0.f;
  float s = 0;
  for (float f : w) s += f;
  return s;
}

Status ClientManager::Execute(int shard, const ExecuteRequest& req,
                              ExecuteReply* rep) {
  if (shard < 0 || shard >= shard_num())
    return Status::InvalidArgument("bad shard index");
  ByteWriter w;
  EncodeExecuteRequest(req, &w);
  std::vector<char> reply;
  // snapshot: the monitor may swap the channel concurrently
  ET_RETURN_IF_ERROR(Channel(shard)->Call(kExecute, w.buffer(), &reply));
  ByteReader r(reply.data(), reply.size());
  ET_RETURN_IF_ERROR(DecodeExecuteReply(&r, rep));
  return rep->status;
}

void ClientManager::ExecuteAsync(
    int shard, ExecuteRequest req,
    std::function<void(Status, ExecuteReply)> done) {
  // the Call() below blocks until the shard replies — it must not occupy
  // an executor thread (see ClientThreadPool comment in threadpool.h)
  ClientThreadPool()->Schedule(
      [this, shard, req = std::move(req), done = std::move(done)] {
        ExecuteReply rep;
        Status s = Execute(shard, req, &rep);
        done(s, std::move(rep));
      });
}

}  // namespace et
