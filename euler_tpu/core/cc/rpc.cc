#include "rpc.h"

#include <arpa/inet.h>
#include <csignal>
#include <fcntl.h>
#include <poll.h>
#include <dirent.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <sys/stat.h>

#include <zlib.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <list>
#include <map>
#include <sstream>
#include <unordered_map>
#include <unordered_set>

#include "gql.h"
#include "store.h"
#include "threadpool.h"
#include "udf.h"

namespace et {

RpcConfig& GlobalRpcConfig() {
  static RpcConfig* c = new RpcConfig();
  return *c;
}

RpcCounters& GlobalRpcCounters() {
  static RpcCounters* c = new RpcCounters();
  return *c;
}

namespace {
constexpr uint32_t kFrameMagic = 0x52465445;    // 'ETFR'
constexpr uint32_t kFrameMagicV2 = 0x32465445;  // 'ETF2'
constexpr uint32_t kFrameFlagCompressed = 1u;   // body: u64 raw_len | zlib
// Reply body is prefixed with the serving graph's u64 epoch (before
// compression). Hello-negotiated (kFeatEpoch): a server only sets it
// for clients that advertised the feature, so pre-epoch v2 peers — and
// every v1 peer — see unchanged bytes.
constexpr uint32_t kFrameFlagEpoch = 2u;
// REQUEST body is prefixed with the caller's remaining deadline budget
// (u64 µs, before compression). Hello-negotiated (kFeatDeadline): a
// client only stamps it for servers that advertised the feature, so
// pre-deadline v2 peers — and every v1 peer — see unchanged bytes.
constexpr uint32_t kFrameFlagDeadline = 4u;
// REQUEST body is prefixed with the client's ownership-map epoch (u64,
// after the deadline prefix, before compression). Hello-negotiated
// (kFeatMapEpoch): the server refuses a kExecute stamped with an OLDER
// epoch than its installed map ("stale ownership map", counted) — a
// client routing on a superseded map can never silently read a shard
// that stopped receiving that partition's deltas. Clients with no map
// (epoch 0) stamp nothing; pre-map peers see unchanged bytes.
constexpr uint32_t kFrameFlagMapEpoch = 8u;
// REQUEST body is prefixed with the caller's wire trace context (u64
// trace_id | u64 parent_span, after the deadline and map-epoch
// prefixes, before compression). Hello-negotiated (kFeatTrace): only
// stamped for servers that will strip it, and only when the caller set
// a trace context (id != 0) — pre-trace peers and untraced calls see
// byte-identical frames.
constexpr uint32_t kFrameFlagTrace = 16u;
// REQUEST body is a PREPARED kExecute: u64 plan id (after every other
// prefix, before compression) followed by the feed tensors only — the
// DAG + output names were registered earlier via kPrepare, keyed by
// the plan's content hash. Hello-negotiated (kFeatPrepared): only
// stamped for servers that advertised the feature; prepared-off calls
// and pre-prepared peers see byte-identical classic frames. An id the
// server does not have answers an explicit counted miss status, never
// a silent wrong-plan execute (the id IS the content hash).
constexpr uint32_t kFrameFlagPrepared = 32u;
constexpr uint32_t kProtoV2 = 2;
constexpr uint32_t kFeatAcceptCompressed = 1u;  // hello feature bit
constexpr uint32_t kFeatEpoch = 2u;             // hello: send epoch prefixes
constexpr uint32_t kFeatDeadline = 4u;          // hello: deadline prefixes ok
constexpr uint32_t kFeatMapEpoch = 8u;          // hello: map-epoch prefixes ok
constexpr uint32_t kFeatTrace = 16u;            // hello: trace prefixes ok
constexpr uint32_t kFeatPrepared = 32u;         // hello: prepared plans ok

enum MsgType : uint32_t {
  kExecute = 0,
  kMeta = 1,
  kPing = 2,
  kRegPut = 3,     // body: entry name → registry stores/refreshes it
  kRegList = 4,    // body: empty → u32 version | u32 count | per entry:
                   // str name, i64 age_ms, u64 put-sequence
  kRegRemove = 5,  // body: entry name → dropped (clean shutdown)
  kHello = 6,      // v2 only: version | feature bits | compress threshold
  // streaming deltas (graph service; both v1 and v2 framing):
  kApplyDelta = 7,  // body: delta arrays → u32 code | u64 new_epoch / str
  kGetDelta = 8,    // body: u64 from_epoch → u32 code | u64 epoch |
                    // u8 covered | u64 n | n×u64 dirty node ids
  kGetDeltaLog = 9,  // body: u64 from_epoch → u32 code | u64 epoch |
                     // u8 covered | u32 count | count×(u64 epoch,
                     // u64 len, raw kApplyDelta body) — anti-entropy
                     // catch-up for recovering shards
  kSetOwnership = 10,  // body: ownership spec string ("e<E>-P<pn>-...")
                       // → u32 code | u64 map_epoch / u32 1 | str error.
                       // Installs the epoch-versioned ownership map
                       // (elastic fleet: live splits / rebalancing).
  kPrepare = 11,  // v2 only. body: encoded execute plan ('ETPN' dag +
                  // outputs) → u32 code | u64 plan_id (the server-
                  // computed content hash) / u32 1 | str error. Decoded
                  // ONCE into the connection's bounded plan LRU;
                  // subsequent kExecute frames flagged kFrameFlagPrepared
                  // carry the id + feed tensors only.
};

// Bench/chaos-only injected per-row work (env
// EULER_TPU_EXEC_DELAY_US_PER_ROW, read once): models the row-
// proportional scan cost a 2-CPU container cannot exhibit naturally —
// the graph-tier analogue of InferenceServer's inject_scan_ms_per_krow.
// Applied after decode, so the empty split batches the distribute
// rewrite fires at non-owning shards cost nothing and routed ROWS are
// what loads a shard (the signal elastic rebalancing spreads).
int64_t ExecDelayUsPerRow() {
  static const int64_t v = [] {
    const char* e = std::getenv("EULER_TPU_EXEC_DELAY_US_PER_ROW");
    return e != nullptr ? std::atoll(e) : 0;
  }();
  return v;
}

// Max-update an atomic epoch (replies can arrive out of order).
void MaxUpdateEpoch(std::atomic<uint64_t>* a, uint64_t v) {
  if (a == nullptr) return;
  uint64_t cur = a->load();
  while (cur < v && !a->compare_exchange_weak(cur, v)) {
  }
}

// kRegList reply schema version: mixed-binary registry pairs must fail
// loudly, not misparse (the reply has no other self-description).
constexpr uint32_t kRegListVersion = 2;

bool WriteAll(int fd, const char* p, size_t n) {
  while (n > 0) {
    ssize_t w = ::write(fd, p, n);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool ReadAll(int fd, char* p, size_t n) {
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// --- frame headers (one choreography shared by the v1 and v2 paths) ------

// v1 header: magic | msg_type | body_len (16 bytes).
constexpr size_t kV1HdrLen = 16;
// v2 header: magic | msg_type | flags | request_id | body_len (28 bytes).
constexpr size_t kV2HdrLen = 28;

// The single header fill/parse pair every encode/decode path shares —
// the four WriteFrame/ReadFrame/WriteFrameV2/ReadAnyFrame siblings
// used to each hand-roll the same memcpy choreography; behavior is
// pinned by the v1/v2 interop tests. v1 fields occupy the same first
// 16 bytes in both layouts except body_len (offset 8 in v1, 20 in v2).
size_t FillFrameHdr(char* hdr, int ver, uint32_t msg_type, uint32_t flags,
                    uint64_t request_id, uint64_t len) {
  if (ver == 1) {
    std::memcpy(hdr, &kFrameMagic, 4);
    std::memcpy(hdr + 4, &msg_type, 4);
    std::memcpy(hdr + 8, &len, 8);
    return kV1HdrLen;
  }
  std::memcpy(hdr, &kFrameMagicV2, 4);
  std::memcpy(hdr + 4, &msg_type, 4);
  std::memcpy(hdr + 8, &flags, 4);
  std::memcpy(hdr + 12, &request_id, 8);
  std::memcpy(hdr + 20, &len, 8);
  return kV2HdrLen;
}

// Parse a header whose first 16 bytes are in hdr; *ver is set from the
// magic. Returns false on an unknown magic (or v2 when !accept_v2 —
// how EULER_TPU_RPC_SERVER_V1 emulates a pre-v2 binary). When it
// returns true and *ver == 2, the caller must read the remaining
// kV2HdrLen - 16 bytes into hdr before ParseFrameHdrV2Tail.
bool ParseFrameHdr16(const char* hdr, bool accept_v2, int* ver,
                     uint32_t* msg_type, uint32_t* flags,
                     uint64_t* request_id, uint64_t* len) {
  uint32_t magic;
  std::memcpy(&magic, hdr, 4);
  std::memcpy(msg_type, hdr + 4, 4);
  if (magic == kFrameMagic) {
    *ver = 1;
    *flags = 0;
    *request_id = 0;
    std::memcpy(len, hdr + 8, 8);
    return true;
  }
  if (magic == kFrameMagicV2 && accept_v2) {
    *ver = 2;
    std::memcpy(flags, hdr + 8, 4);
    return true;
  }
  return false;
}

void ParseFrameHdrV2Tail(const char* hdr, uint64_t* request_id,
                         uint64_t* len) {
  std::memcpy(request_id, hdr + 12, 8);
  std::memcpy(len, hdr + 20, 8);
}

void FillV2Hdr(char* hdr, uint32_t msg_type, uint32_t flags,
               uint64_t request_id, uint64_t len) {
  FillFrameHdr(hdr, 2, msg_type, flags, request_id, len);
}

bool WriteFrame(int fd, uint32_t msg_type, const char* body, size_t len) {
  char hdr[kV1HdrLen];
  FillFrameHdr(hdr, 1, msg_type, 0, 0, len);
  return WriteAll(fd, hdr, kV1HdrLen) && WriteAll(fd, body, len);
}

bool WriteFrameV2(int fd, uint32_t msg_type, uint32_t flags,
                  uint64_t request_id, const char* body, size_t len) {
  char hdr[kV2HdrLen];
  FillV2Hdr(hdr, msg_type, flags, request_id, len);
  return WriteAll(fd, hdr, kV2HdrLen) && WriteAll(fd, body, len);
}

// Reads a frame of EITHER version (*ver = 1 or 2): the 16-byte v1 header
// first, then — when the magic says v2 — the 12 remaining header bytes.
// accept_v2=false emulates a pre-v2 binary exactly (unknown magic drops
// the connection), which is how EULER_TPU_RPC_SERVER_V1 pins interop.
bool ReadAnyFrame(int fd, int* ver, uint32_t* msg_type, uint32_t* flags,
                  uint64_t* request_id, std::vector<char>* body,
                  bool accept_v2 = true) {
  char hdr[kV2HdrLen];
  if (!ReadAll(fd, hdr, 16)) return false;
  uint64_t len;
  if (!ParseFrameHdr16(hdr, accept_v2, ver, msg_type, flags, request_id,
                       &len))
    return false;
  if (*ver == 2) {
    if (!ReadAll(fd, hdr + 16, kV2HdrLen - 16)) return false;
    ParseFrameHdrV2Tail(hdr, request_id, &len);
  }
  if (len > (1ULL << 33)) return false;  // 8 GiB sanity cap
  body->resize(len);
  return len == 0 || ReadAll(fd, body->data(), len);
}

// v1 frames only (registry protocol + classic clients) — the shared
// parser with v2 refused, byte-for-byte the pre-dedupe behavior.
bool ReadFrame(int fd, uint32_t* msg_type, std::vector<char>* body) {
  int ver = 0;
  uint32_t flags = 0;
  uint64_t rid = 0;
  return ReadAnyFrame(fd, &ver, msg_type, &flags, &rid, body,
                      /*accept_v2=*/false);
}

// Gathered write of header + prefixes + payload views (the zero-copy
// reply path): partial writes advance through the iovec array, counts
// past the kernel's IOV_MAX batch in chunks.
bool WritevAll(int fd, std::vector<iovec>* iov) {
  size_t idx = 0;
  while (idx < iov->size()) {
    int cnt = static_cast<int>(std::min<size_t>(iov->size() - idx, 1024));
    ssize_t w = ::writev(fd, iov->data() + idx, cnt);
    if (w <= 0) return false;
    size_t n = static_cast<size_t>(w);
    while (idx < iov->size() && n >= (*iov)[idx].iov_len) {
      n -= (*iov)[idx].iov_len;
      ++idx;
    }
    if (n > 0) {
      (*iov)[idx].iov_base = static_cast<char*>((*iov)[idx].iov_base) + n;
      (*iov)[idx].iov_len -= n;
    }
  }
  return true;
}

// Compressed body layout: u64 raw_len | zlib stream (level 1 — the
// latency-friendly setting; feature replies are the target, and level 1
// already captures most of the float-row redundancy). Returns false when
// deflate would NOT shrink the frame — the caller then sends raw with no
// flag bit, which is what makes the compression adaptive per frame.
bool DeflateBody(const std::vector<char>& raw, std::vector<char>* out) {
  uLong bound = compressBound(static_cast<uLong>(raw.size()));
  out->resize(8 + bound);
  uint64_t raw_len = raw.size();
  std::memcpy(out->data(), &raw_len, 8);
  uLongf dest_len = bound;
  if (compress2(reinterpret_cast<Bytef*>(out->data() + 8), &dest_len,
                reinterpret_cast<const Bytef*>(raw.data()),
                static_cast<uLong>(raw.size()), /*level=*/1) != Z_OK)
    return false;
  if (8 + dest_len >= raw.size()) return false;
  out->resize(8 + dest_len);
  return true;
}

bool InflateBody(const std::vector<char>& comp, std::vector<char>* out) {
  if (comp.size() < 8) return false;
  uint64_t raw_len;
  std::memcpy(&raw_len, comp.data(), 8);
  if (raw_len > (1ULL << 33)) return false;
  out->resize(raw_len);
  uLongf dest_len = static_cast<uLongf>(raw_len);
  if (raw_len > 0 &&
      uncompress(reinterpret_cast<Bytef*>(out->data()), &dest_len,
                 reinterpret_cast<const Bytef*>(comp.data() + 8),
                 static_cast<uLong>(comp.size() - 8)) != Z_OK)
    return false;
  return dest_len == raw_len;
}

// Per-connection-writer deflate state: one deflateInit for the
// connection's lifetime, deflateReset between frames — compress2 pays
// the full init (window + hash table setup) on EVERY frame. Identical
// output bytes (same level-1 / default window / default strategy), so
// the adaptive shrink check and wire parity are unchanged. Callers
// already serialize frame writes (wmu), which serializes this too.
// RpcConfig::deflate_reuse=false restores the per-frame compress2 path
// (the A/B lever); an init failure falls back the same way.
class DeflateCtx {
 public:
  ~DeflateCtx() {
    if (init_) deflateEnd(&zs_);
  }
  // Same contract as DeflateBody: false when deflate would not shrink.
  bool Deflate(const std::vector<char>& raw, std::vector<char>* out) {
    if (!GlobalRpcConfig().deflate_reuse.load() ||
        raw.size() > (1ULL << 31))  // one-shot avail_in is 32-bit
      return DeflateBody(raw, out);
    if (!init_) {
      std::memset(&zs_, 0, sizeof(zs_));
      if (deflateInit(&zs_, 1) != Z_OK) return DeflateBody(raw, out);
      init_ = true;
    } else {
      deflateReset(&zs_);
    }
    uLong bound = deflateBound(&zs_, static_cast<uLong>(raw.size()));
    out->resize(8 + bound);
    uint64_t raw_len = raw.size();
    std::memcpy(out->data(), &raw_len, 8);
    zs_.next_in = reinterpret_cast<Bytef*>(
        const_cast<char*>(raw.data()));
    zs_.avail_in = static_cast<uInt>(raw.size());
    zs_.next_out = reinterpret_cast<Bytef*>(out->data() + 8);
    zs_.avail_out = static_cast<uInt>(bound);
    if (deflate(&zs_, Z_FINISH) != Z_STREAM_END) {
      deflateEnd(&zs_);
      init_ = false;
      return DeflateBody(raw, out);
    }
    if (8 + zs_.total_out >= raw.size()) return false;
    out->resize(8 + zs_.total_out);
    return true;
  }

 private:
  z_stream zs_;
  bool init_ = false;
};

// Full-jitter retry sleep: U(0, 2^attempt ms), capped at 64ms. The old
// fixed 2^attempt ladder fired synchronized retry stampedes — every
// worker that saw a shard die woke on the same schedule (the Python
// RetryPolicy already jitters; this matches it at the transport layer).
void JitteredBackoffUs(int attempt) {
  uint64_t hi = 1000ULL * (1ULL << std::min(attempt, 6));
  ::usleep(static_cast<useconds_t>(ThreadLocalRng().NextUInt(hi + 1)));
}

// Per-thread deadline handoff (see rpc.h SetCallDeadlineUs): the capi
// sets it on the query's calling thread; QueryProxy consumes it into
// the run's QueryEnv on the same thread.
thread_local int64_t tls_call_deadline_us = 0;
// Per-thread trace handoff (see rpc.h SetCallTrace): same pattern.
thread_local WireTrace tls_call_trace;
}  // namespace

int64_t SteadyNowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void SetCallDeadlineUs(int64_t abs_steady_us) {
  tls_call_deadline_us = abs_steady_us;
}

int64_t TakeCallDeadlineUs() {
  int64_t v = tls_call_deadline_us;
  tls_call_deadline_us = 0;
  return v;
}

void SetCallTrace(uint64_t trace_id, uint64_t parent_span) {
  tls_call_trace.id = trace_id;
  tls_call_trace.parent = parent_span;
}

WireTrace TakeCallTrace() {
  WireTrace t = tls_call_trace;
  tls_call_trace = WireTrace{};
  return t;
}

int64_t WallNowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// ServerTraceStats — per-verb/phase native histograms + traced-span ring
// ---------------------------------------------------------------------------
ServerTraceStats& GlobalServerTraceStats() {
  static ServerTraceStats* s = new ServerTraceStats();
  return *s;
}

int ServerTraceStats::VerbSlot(uint32_t msg_type) {
  switch (msg_type) {
    case kExecute: return 0;
    case kApplyDelta: return 1;
    case kGetDelta: return 2;
    case kGetDeltaLog: return 3;
    case kSetOwnership: return 4;
    case kMeta: return 5;
    default: return -1;  // ping / hello / registry verbs: untracked
  }
}

void LatencyHist::Observe(uint64_t us) {
  // log2 bucket: bound i covers (2^(i-1), 2^i] µs (le-inclusive, the
  // obs Histogram convention); values past the last bound overflow
  int idx = 0;
  while (idx < kBuckets && us > (1ULL << idx)) ++idx;
  counts[idx].fetch_add(1);
  sum_us.fetch_add(us);
  n.fetch_add(1);
}

void LatencyHist::Snapshot(uint64_t* n_out, uint64_t* sum_us_out,
                           uint64_t* counts_out) const {
  *n_out = n.load();
  *sum_us_out = sum_us.load();
  for (int i = 0; i <= kBuckets; ++i) counts_out[i] = counts[i].load();
}

void ServerTraceStats::Observe(int verb_slot, int phase, uint64_t us) {
  if (verb_slot < 0 || verb_slot >= kTraceVerbs || phase < 0 ||
      phase >= kTracePhases)
    return;
  hist_[verb_slot][phase].Observe(us);
}

void ServerTraceStats::Record(const ServerTraceRecord& rec) {
  std::lock_guard<std::mutex> lk(ring_mu_);
  ring_.push_back(rec);
  while (ring_.size() > kRingCap) ring_.pop_front();
}

void ServerTraceStats::Drain(std::vector<ServerTraceRecord>* out) {
  std::lock_guard<std::mutex> lk(ring_mu_);
  out->assign(ring_.begin(), ring_.end());
  ring_.clear();
}

bool ServerTraceStats::HistSnapshot(int verb_slot, int phase, uint64_t* n,
                                    uint64_t* sum_us,
                                    uint64_t* counts) const {
  if (verb_slot < 0 || verb_slot >= kTraceVerbs || phase < 0 ||
      phase >= kTracePhases)
    return false;
  hist_[verb_slot][phase].Snapshot(n, sum_us, counts);
  return true;
}

// ---------------------------------------------------------------------------
// ShardMeta serde
// ---------------------------------------------------------------------------
void EncodeShardMeta(const ShardMeta& m, ByteWriter* w) {
  w->Put<int32_t>(m.shard_idx);
  w->Put<int32_t>(m.shard_num);
  w->Put<int32_t>(m.partition_num);
  w->Put<uint32_t>(static_cast<uint32_t>(m.node_type_wsum.size()));
  for (float f : m.node_type_wsum) w->Put<float>(f);
  w->Put<uint32_t>(static_cast<uint32_t>(m.edge_type_wsum.size()));
  for (float f : m.edge_type_wsum) w->Put<float>(f);
  w->Put<uint64_t>(m.graph_label_count);
  w->Put<uint64_t>(m.owned_graph_label_count);
  const GraphMeta& gm = m.graph_meta;
  w->PutStr(gm.name);
  w->Put<int32_t>(gm.num_node_types);
  w->Put<int32_t>(gm.num_edge_types);
  w->Put<uint64_t>(gm.node_count);
  w->Put<uint64_t>(gm.edge_count);
  auto put_feats = [&](const std::vector<FeatureInfo>& fs) {
    w->Put<uint32_t>(static_cast<uint32_t>(fs.size()));
    for (const auto& f : fs) {
      w->PutStr(f.name);
      w->Put<int32_t>(static_cast<int32_t>(f.kind));
      w->Put<int64_t>(f.dim);
    }
  };
  put_feats(gm.node_features);
  put_feats(gm.edge_features);
  auto put_names = [&](const std::vector<std::string>& ns) {
    w->Put<uint32_t>(static_cast<uint32_t>(ns.size()));
    for (const auto& s : ns) w->PutStr(s);
  };
  put_names(gm.node_type_names);
  put_names(gm.edge_type_names);
}

Status DecodeShardMeta(ByteReader* r, ShardMeta* m) {
  uint32_t n;
  if (!r->Get(&m->shard_idx) || !r->Get(&m->shard_num) ||
      !r->Get(&m->partition_num) || !r->Get(&n))
    return Status::IOError("truncated shard meta");
  m->node_type_wsum.resize(n);
  for (uint32_t i = 0; i < n; ++i)
    if (!r->Get(&m->node_type_wsum[i]))
      return Status::IOError("truncated weights");
  if (!r->Get(&n)) return Status::IOError("truncated shard meta");
  m->edge_type_wsum.resize(n);
  for (uint32_t i = 0; i < n; ++i)
    if (!r->Get(&m->edge_type_wsum[i]))
      return Status::IOError("truncated weights");
  if (!r->Get(&m->graph_label_count) ||
      !r->Get(&m->owned_graph_label_count))
    return Status::IOError("truncated shard meta");
  GraphMeta& gm = m->graph_meta;
  if (!r->GetStr(&gm.name) || !r->Get(&gm.num_node_types) ||
      !r->Get(&gm.num_edge_types) || !r->Get(&gm.node_count) ||
      !r->Get(&gm.edge_count))
    return Status::IOError("truncated graph meta");
  auto get_feats = [&](std::vector<FeatureInfo>* fs) -> bool {
    uint32_t k;
    if (!r->Get(&k)) return false;
    fs->resize(k);
    for (uint32_t i = 0; i < k; ++i) {
      int32_t kind;
      if (!r->GetStr(&(*fs)[i].name) || !r->Get(&kind) ||
          !r->Get(&(*fs)[i].dim))
        return false;
      (*fs)[i].kind = static_cast<FeatureKind>(kind);
    }
    return true;
  };
  auto get_names = [&](std::vector<std::string>* ns) -> bool {
    uint32_t k;
    if (!r->Get(&k)) return false;
    ns->resize(k);
    for (uint32_t i = 0; i < k; ++i)
      if (!r->GetStr(&(*ns)[i])) return false;
    return true;
  };
  if (!get_feats(&gm.node_features) || !get_feats(&gm.edge_features) ||
      !get_names(&gm.node_type_names) || !get_names(&gm.edge_type_names))
    return Status::IOError("truncated graph meta tail");
  return Status::OK();
}

// ---------------------------------------------------------------------------
// GraphServer
// ---------------------------------------------------------------------------
GraphServer::GraphServer(std::shared_ptr<const Graph> graph,
                         std::shared_ptr<IndexManager> index, int shard_idx,
                         int shard_num, int partition_num)
    : GraphServer(std::make_shared<GraphRef>(std::move(graph)),
                  std::move(index), shard_idx, shard_num, partition_num) {}

GraphServer::GraphServer(std::shared_ptr<GraphRef> graph_ref,
                         std::shared_ptr<IndexManager> index, int shard_idx,
                         int shard_num, int partition_num)
    : graph_ref_(std::move(graph_ref)),
      index_(std::move(index)),
      shard_idx_(shard_idx),
      shard_num_(shard_num),
      partition_num_(partition_num) {}

GraphServer::~GraphServer() { Stop(); }

void GraphServer::InvalidateReuse() {
  size_t dropped = 0;
  {
    std::lock_guard<std::mutex> lk(reuse_mu_);
    dropped = reuse_.size();
    reuse_.clear();
    reuse_lru_.clear();
  }
  if (dropped > 0)
    GlobalRpcCounters().reuse_invalidated.fetch_add(
        static_cast<uint64_t>(dropped));
}

void GraphServer::ReattachFromSidecar(DeltaWal* wal) {
  // Caller holds apply_mutex: no delta can race the swap, so the mmap
  // twin is attached from the exact bytes the compaction just dumped.
  // Failure is non-fatal — the shard keeps serving the heap snapshot
  // and the next compaction retries.
  if (wal->last_snapshot_dir().empty()) return;
  const std::string sidecar =
      wal->last_snapshot_dir() + "/" + kColumnarFileName;
  std::shared_ptr<const Graph> base = graph_ref_->get();
  std::unique_ptr<Graph> next;
  Status s = LoadGraphFromStore(sidecar, storage_hot_bytes_, &next);
  if (s.ok() && base->has_in_adjacency() && !next->has_in_adjacency() &&
      next->edge_count() > 0)
    s = Status::IOError("sidecar lacks in-adjacency");
  if (!s.ok()) {
    ET_LOG(WARNING) << "shard " << shard_idx_
                    << " mmap reattach skipped: " << s.message();
    return;
  }
  next->set_epoch(base->epoch());
  std::shared_ptr<const Graph> fresh(std::move(next));
  std::shared_ptr<IndexManager> new_index;
  if (!index_spec_.empty()) {
    new_index = std::make_shared<IndexManager>();
    s = new_index->BuildFromSpec(*fresh, index_spec_);
    if (!s.ok()) {
      ET_LOG(WARNING) << "shard " << shard_idx_
                      << " mmap reattach skipped (index rebuild): "
                      << s.message();
      return;
    }
  }
  uint64_t old_uid = base->uid();
  {
    std::lock_guard<std::mutex> lk(state_mu_);
    // same epoch, empty dirty set: the twin is byte-identical, clients'
    // incremental caches stay valid (DirtySince gains nothing new)
    if (!graph_ref_->SwapFrom(base, std::move(fresh), {})) {
      ET_LOG(WARNING) << "shard " << shard_idx_
                      << " mmap reattach lost a swap race; skipped";
      return;
    }
    index_ = new_index;
  }
  // the snapshot uid changed: anything keyed on the old uid is garbage
  UdfResultCache::Instance().EvictGraph(old_uid);
  InvalidateReuse();
  ET_LOG(INFO) << "shard " << shard_idx_
               << " reattached mmap columnar generation " << sidecar
               << " (epoch " << graph_ref_->epoch() << ")";
}

void GraphServer::SnapshotState(std::shared_ptr<const Graph>* g,
                                std::shared_ptr<IndexManager>* idx) const {
  // one lock for both: a request must never pair a new graph with the
  // old index (HandleApplyDelta swaps them together under state_mu_)
  std::lock_guard<std::mutex> lk(state_mu_);
  *g = graph_ref_->get();
  if (idx != nullptr) *idx = index_;
}

Status GraphServer::Start(int port) {
  // a reply racing a peer close (hedge losers, coalesce fan-out after a
  // client gave up) must surface as an EPIPE write error, not kill the
  // process — CPython embeds already ignore SIGPIPE, standalone
  // binaries (engine_test) get the default terminate without this
  ::signal(SIGPIPE, SIG_IGN);
  // interop test hook: serve exactly like a pre-v2 binary (v2 hellos are
  // an unknown magic → connection dropped, clients fall back to v1)
  const char* v1_env = std::getenv("EULER_TPU_RPC_SERVER_V1");
  v1_only_ = v1_env != nullptr && v1_env[0] != '\0' &&
             std::strcmp(v1_env, "0") != 0;
  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd_ < 0) return Status::IOError("socket() failed");
  int one = 1;
  ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
      0)
    return Status::IOError("bind() failed on port " + std::to_string(port));
  if (::listen(listen_fd_, 128) != 0)
    return Status::IOError("listen() failed");
  socklen_t len = sizeof(addr);
  ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
  port_ = ntohs(addr.sin_port);
  acceptor_ = std::thread([this] { AcceptLoop(); });
  // periodic reap: finished handler threads used to be joined only at
  // the NEXT accept, so an idle server parked joinable threads forever.
  // Plain atomic poll (100ms ticks, reap every 5th): no condvar, so
  // Stop() just flips stopping_ and joins — worst case +100ms.
  reaper_ = std::thread([this] {
    int tick = 0;
    while (!stopping_.load()) {
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
      if (++tick < 5) continue;
      tick = 0;
      std::lock_guard<std::mutex> lk(conn_mu_);
      ReapFinishedLocked();
    }
  });
  ET_LOG(INFO) << "graph shard " << shard_idx_ << "/" << shard_num_
               << " serving on port " << port_;
  return Status::OK();
}

void GraphServer::Stop() {
  if (stopping_.exchange(true)) return;
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
  }
  if (acceptor_.joinable()) acceptor_.join();
  if (reaper_.joinable()) reaper_.join();  // polls stopping_; ≤100ms
  // Shut down open sockets so reader threads unblock, then join outside the
  // lock (the threads deregister their fds under conn_mu_ on exit).
  std::vector<Conn> to_join;
  {
    std::lock_guard<std::mutex> lk(conn_mu_);
    for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    to_join = std::move(conns_);
    conns_.clear();
  }
  for (auto& c : to_join)
    if (c.thread.joinable()) c.thread.join();
  {
    std::lock_guard<std::mutex> lk(conn_mu_);
    conn_fds_.clear();
  }
  {
    // pair the stopping_ store with hb_mu_ so the notify can't land in
    // the heartbeat thread's predicate-check window (missed wakeup =
    // Stop stalls a full heartbeat period)
    std::lock_guard<std::mutex> lk(hb_mu_);
  }
  hb_cv_.notify_all();
  if (heartbeat_.joinable()) heartbeat_.join();
  // clean shutdown unregisters (file unlink or tcp kRegRemove); a crash
  // skips this and the entry goes stale instead
  if (!reg_spec_.empty()) RegistryRemoveEntry(reg_spec_, reg_name_);
  // drain off-path compaction BEFORE releasing the wal: a task that
  // already lock()ed the weak_ptr keeps the DeltaWal alive through its
  // dump, and returning from Stop mid-dump would let a successor open
  // the same wal_dir and have its fresh generation unlinked under it
  {
    std::unique_lock<std::mutex> lk(compact_mu_);
    compact_cv_.wait(lk, [this] { return compact_inflight_ == 0; });
  }
  // release this server's degraded-gauge contribution and drop the wal
  // — every apply and compaction has drained above, and a NOT-yet-
  // started task (weak_ptr capture) turns into a no-op once the wal
  // dies, so a successor on the same wal_dir cannot race a stale dump
  if (wal_degraded_) {
    GlobalWalCounters().degraded.fetch_sub(1);
    wal_degraded_ = false;
  }
  wal_.reset();
}

void GraphServer::ReapFinishedLocked() {
  size_t kept = 0;
  for (size_t i = 0; i < conns_.size(); ++i) {
    if (conns_[i].finished->load()) {
      conns_[i].thread.join();
    } else {
      if (kept != i) conns_[kept] = std::move(conns_[i]);
      ++kept;
    }
  }
  conns_.resize(kept);
}

Status GraphServer::Register(const std::string& registry,
                             const std::string& host, int heartbeat_ms) {
  std::ostringstream os;
  os << "shard_" << shard_idx_ << "__" << host << "_" << port_;
  reg_spec_ = registry;
  reg_name_ = os.str();
  ET_RETURN_IF_ERROR(RegistryPutEntry(reg_spec_, reg_name_));
  if (heartbeat_ms > 0 && !heartbeat_.joinable()) {
    heartbeat_ = std::thread([this, heartbeat_ms] {
      std::unique_lock<std::mutex> lk(hb_mu_);
      while (!hb_cv_.wait_for(lk, std::chrono::milliseconds(heartbeat_ms),
                              [this] { return stopping_.load(); })) {
        // re-put: monitors treat a fresh entry as "alive" (ephemeral
        // ZK-node semantics — file mtime or registry-server timestamp)
        RegistryPutEntry(reg_spec_, reg_name_);
      }
    });
  }
  return Status::OK();
}

void GraphServer::AcceptLoop() {
  for (;;) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) {
      if (stopping_.load()) return;
      continue;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::lock_guard<std::mutex> lk(conn_mu_);
    ReapFinishedLocked();
    Conn c;
    c.finished = std::make_shared<std::atomic<bool>>(false);
    auto flag = c.finished;
    conn_fds_.push_back(fd);
    c.thread = std::thread([this, fd, flag] {
      HandleConnection(fd);
      flag->store(true);
    });
    conns_.push_back(std::move(c));
  }
}

// A decoded, registered execute plan (kPrepare): the DAG + requested
// output names, executed IN PLACE by every prepared request that names
// its id (the DAGDef read-only concurrency contract, dag.h). `gen`
// snapshots the server's plan generation at registration — an
// ownership-map flip bumps it and strands every older entry (client
// plans bake in shard routing; a flip must force a re-prepare).
struct PreparedPlan {
  DAGDef dag;
  std::vector<std::string> outputs;
  uint64_t gen = 0;
  // prepare-time optimizer (RpcConfig::plan_optimize, gql.h
  // OptimizePreparedPlan): the INSTALLED dag above is the optimized
  // form; verbatim_text keeps the registered form's DagToString when
  // any pass rewrote it (introspection probe), empty otherwise.
  bool optimized = false;
  PlanOptStats opt_stats;
  std::string verbatim_text;
  // every op deterministic (gql.h DagIsDeterministic): eligible for the
  // result-reuse window and cross-request coalescing
  bool deterministic = false;
};

// One completed deterministic execution, pinned for the reuse window.
// feeds are the EXACT request body bytes — a key hit still memcmps them
// so a 64-bit collision can never serve foreign results. Outputs hold
// refcounted tensors; serving a hit copies the vector, not the payloads.
struct GraphServer::ReuseEntry {
  uint64_t plan_id = 0;
  uint64_t graph_uid = 0;
  std::vector<char> feeds;
  std::vector<std::pair<std::string, Tensor>> outputs;
};

// An open coalescing batch: the first arrival (leader) holds execution
// for the bounded window; same-key arrivals park their reply
// continuation here and the leader answers everyone from its single
// run. closed flips under coalesce_mu_ when the leader starts
// executing — later arrivals start a fresh bucket.
struct GraphServer::CoalesceBucket {
  uint64_t plan_id = 0;
  uint64_t graph_uid = 0;
  std::vector<char> feeds;  // leader's body bytes (followers must match)
  bool closed = false;
  // each waiter stamps its own timing and writes its own reply frame
  std::vector<std::function<void(const ExecuteReply&)>> waiters;
};

std::string GraphServer::DebugPlans() const {
  // explain() server probe: every registered plan, its generation, its
  // determinism verdict, the per-pass rewrite counts and the form that
  // actually executes (plus the verbatim form when the optimizer rewrote)
  std::string out;
  std::lock_guard<std::mutex> lk(plan_mu_);
  for (uint64_t id : plan_lru_) {
    auto it = plans_.find(id);
    if (it == plans_.end()) continue;
    const PreparedPlan& pl = *it->second.first;
    out += "plan " + std::to_string(id) + " gen=" + std::to_string(pl.gen) +
           " deterministic=" + (pl.deterministic ? "1" : "0") +
           " optimized=" + (pl.optimized ? "1" : "0");
    if (pl.optimized)
      out += " rewrites[fuse=" + std::to_string(pl.opt_stats.fuse) +
             " pushdown=" + std::to_string(pl.opt_stats.pushdown) +
             " dedup=" + std::to_string(pl.opt_stats.dedup) + "]";
    out += "\n";
    out += DagToString(pl.dag);
    if (pl.optimized && !pl.verbatim_text.empty()) {
      out += "-- as registered (pre-optimize):\n";
      out += pl.verbatim_text;
    }
  }
  return out;
}

// Per-connection v2 state: the reply write lock (out-of-order completions
// serialize on it), the hello-negotiated compression caps, and the
// in-flight dispatch bound. shared_ptr-held because executor completions
// outlive the reader loop's stack frame.
struct GraphServer::ConnState {
  explicit ConnState(int fd_in) : fd(fd_in) {}
  const int fd;
  std::mutex wmu;              // serializes reply frames on this fd
  bool write_broken = false;   // under wmu: stop writing after a failure
  bool peer_compress = false;  // hello: client accepts deflated replies
  bool peer_epoch = false;     // hello: client wants epoch reply prefixes
  uint64_t peer_threshold = 0;
  // reused per-connection deflate state (under wmu, like the writes)
  DeflateCtx deflate;
  // registered plans live in the SERVER's shared store (GraphServer::
  // plans_) — one decode per plan per process, shared across
  // connections and surviving reconnects.
  std::mutex imu;
  std::condition_variable icv;
  int inflight = 0;  // dispatched requests whose reply is not yet written
};

void GraphServer::BuildMeta(ByteWriter* w) const {
  std::shared_ptr<const Graph> g;
  SnapshotState(&g, nullptr);
  ShardMeta m;
  m.shard_idx = shard_idx_;
  m.shard_num = shard_num_;
  m.partition_num = partition_num_;
  m.node_type_wsum = g->node_type_weight_sums();
  m.graph_label_count = g->graph_label_count();
  m.owned_graph_label_count = g->OwnedGraphLabelCount(shard_idx_, shard_num_);
  m.edge_type_wsum = g->edge_type_weight_sums();
  m.graph_meta = g->meta();
  EncodeShardMeta(m, w);
}

// kApplyDelta: decode the batched delta, rebuild a new snapshot through
// the builder machinery (readers keep sampling the old one), append the
// raw body to the write-ahead log (durability — BEFORE the swap, so an
// acked delta is always on disk), swap it in with its dirty set,
// rebuild the attribute index, retain the body for peer anti-entropy,
// and orphan the old snapshot's UDF result-cache entries (counted).
// Serialized: concurrent applies would each rebuild from the same base
// and lose one delta.
void GraphServer::HandleApplyDelta(ByteReader* r, ByteWriter* w) {
  // the reader sits at the body start: hand the RAW bytes to the shared
  // apply path (WAL records and the retained delta log store them
  // verbatim so replay/catch-up re-filter exactly like the live path)
  ApplyDeltaBody(r->cursor(), r->remaining(), w);
}

void GraphServer::ApplyDeltaBody(const char* body, size_t len,
                                 ByteWriter* w) {
  // per-ref: also serialized with an embedded-handle apply when the
  // server was constructed over a shared GraphRef
  std::lock_guard<std::mutex> apply_lk(graph_ref_->apply_mutex());
  auto fail = [&](const std::string& msg) {
    w->Put<uint32_t>(1);
    w->PutStr(msg);
  };
  std::vector<NodeId> ids, src, dst;
  std::vector<int32_t> ntypes, etypes;
  std::vector<float> nw, ew;
  Status s = DecodeDeltaBody(body, len, &ids, &ntypes, &nw, &src, &dst,
                             &etypes, &ew);
  if (!s.ok()) {
    fail(s.message());
    return;
  }
  {
    // an index we cannot rebuild must refuse the delta — serving has()
    // filters off a pre-delta index would be silent staleness
    std::lock_guard<std::mutex> lk(state_mu_);
    if (index_ != nullptr && index_spec_.empty()) {
      fail("shard has an attribute index but no index_spec to rebuild "
           "it after a delta; start the server with index_spec");
      return;
    }
  }
  if (wal_degraded_) {
    // wal was requested but its directory is unusable: accepting the
    // delta would diverge the in-memory graph from its (absent) log —
    // refuse with an explicit, counted status instead (the degraded
    // gauge already counts this instance, from set_wal)
    GlobalWalCounters().refused.fetch_add(1);
    fail("wal degraded: shard's write-ahead log is unusable; delta "
         "refused (restart with a writable wal_dir)");
    return;
  }
  std::shared_ptr<const Graph> base = graph_ref_->get();
  std::unique_ptr<Graph> next;
  std::vector<NodeId> dirty;
  // an installed ownership map replaces the hash filter: this shard
  // applies the rows whose partition lists it as an owner — which is
  // also what routes graph_partition-mode deltas (ownership is the
  // map's say, not the modulus convention)
  std::shared_ptr<const OwnershipMap> omap = ownership();
  s = ApplyGraphDelta(
      *base, ids.data(), ntypes.data(), nw.data(), ids.size(), src.data(),
      dst.data(), etypes.data(), ew.data(), src.size(), shard_idx_,
      shard_num_, &next, &dirty, omap.get());
  if (!s.ok()) {
    fail(s.message());
    return;
  }
  std::shared_ptr<const Graph> fresh(std::move(next));
  std::shared_ptr<IndexManager> new_index;
  if (!index_spec_.empty()) {
    new_index = std::make_shared<IndexManager>();
    s = new_index->BuildFromSpec(*fresh, index_spec_);
    if (!s.ok()) {
      fail("index rebuild after delta failed: " + s.message());
      return;
    }
  }
  uint64_t epoch = fresh->epoch();
  uint64_t old_uid = base->uid();
  if (wal_ != nullptr) {
    // append BEFORE the swap: a refused append must leave the served
    // graph exactly where the log says it is (disk-full degrades to
    // "no new deltas", never to divergence). Counted + degraded gauge;
    // a later successful append clears the gauge (space freed).
    Status ws = wal_->Append(epoch, body, len);
    if (!ws.ok()) {
      GlobalWalCounters().refused.fetch_add(1);
      fail("wal append failed; delta refused (shard keeps serving "
           "reads, epoch unchanged): " + ws.message());
      return;
    }
  }
  {
    std::lock_guard<std::mutex> lk(state_mu_);
    // apply_mu_ serializes server applies; SwapFrom additionally guards
    // against an embedded-handle apply racing a SHARED ref (tests)
    if (!graph_ref_->SwapFrom(base, std::move(fresh), std::move(dirty))) {
      fail("concurrent delta apply on this shard's graph; retry");
      return;
    }
    index_ = new_index;  // null when the server has no index
  }
  UdfResultCache::Instance().EvictGraph(old_uid);
  // the reuse window is keyed on the pre-delta snapshot uid — every
  // entry is now stale; purge (counted) before any post-swap execute
  // can look one up
  InvalidateReuse();
  {
    // retained raw body: what kGetDeltaLog serves to a recovering peer
    std::lock_guard<std::mutex> lk(dlog_mu_);
    dlog_.emplace_back(epoch, std::vector<char>(body, body + len));
    dlog_bytes_ += len;
    while (dlog_.size() > kMaxDlogRecords || dlog_bytes_ > kMaxDlogBytes) {
      dlog_bytes_ -= dlog_.front().second.size();
      dlog_.pop_front();
    }
  }
  if (wal_ != nullptr && wal_->wants_compaction()) {
    // Compaction is an O(graph) dump — running it here would hold the
    // delta ack (and apply_mutex) for the whole dump, long enough for
    // the client to time out and re-issue (a spurious epoch bump).
    // Schedule it off-path instead: the task re-takes apply_mutex (so
    // it serializes with later applies exactly like an inline compact)
    // and MaybeCompact re-checks the threshold (a superseding task
    // no-ops). The weak_ptr capture no-ops a task that has not started
    // when the server stops, and Stop() DRAINS started tasks via the
    // inflight count before releasing the wal — either way a successor
    // on the same wal_dir never races a stale dump. Failure is
    // non-fatal: the log keeps growing and the next apply reschedules.
    {
      std::lock_guard<std::mutex> lk(compact_mu_);
      ++compact_inflight_;
    }
    GlobalThreadPool()->Schedule(
        [this, wwal = std::weak_ptr<DeltaWal>(wal_), ref = graph_ref_,
         shard = shard_idx_] {
          // `this` stays valid: Stop() (always run before destruction)
          // waits for compact_inflight_ to reach zero
          auto wal = wwal.lock();
          if (wal != nullptr && !stopping_.load()) {
            std::lock_guard<std::mutex> alk(ref->apply_mutex());
            int64_t before = wal->log_bytes();
            Status cs = wal->MaybeCompact(*ref->get());
            if (!cs.ok())
              ET_LOG(WARNING) << "shard " << shard
                              << " wal compaction failed: "
                              << cs.message();
            // out-of-core mode: a compaction that actually ran (log
            // reset) just wrote the columnar sidecar for the CURRENT
            // snapshot — swap the heap graph for its mmap twin while
            // still under apply_mutex (serialized against applies,
            // exactly like the compact itself)
            if (cs.ok() && storage_mode_ == 1 &&
                wal->log_bytes() < before && wal->columnar_sidecar())
              ReattachFromSidecar(wal.get());
          }
          std::lock_guard<std::mutex> lk(compact_mu_);
          --compact_inflight_;
          compact_cv_.notify_all();
        },
        // maintenance lane: an O(graph) dump never queues ahead of reads
        ThreadPool::kLow);
  }
  ET_LOG(INFO) << "shard " << shard_idx_ << " applied delta (" << ids.size()
               << " nodes, " << src.size() << " edges) -> epoch " << epoch;
  w->Put<uint32_t>(0);
  w->Put<uint64_t>(epoch);
}

Status GraphServer::SetOwnership(std::shared_ptr<const OwnershipMap> m) {
  if (m == nullptr || m->map_epoch == 0)
    return Status::InvalidArgument("ownership map must have epoch > 0");
  // Serialize installs on the ref's apply mutex: a concurrent delta
  // apply must never read a map that has not been PERSISTED yet — it
  // would WAL-append a record whose live filter crash-recovery cannot
  // reproduce (install-then-persist was exactly that hole). Order:
  // check epoch → persist → install; the apply lock also keeps two
  // concurrent installs from landing out of epoch order.
  std::lock_guard<std::mutex> install_lk(graph_ref_->apply_mutex());
  {
    std::lock_guard<std::mutex> lk(omap_mu_);
    if (omap_ != nullptr && m->map_epoch < omap_->map_epoch)
      return Status::InvalidArgument(
          "refusing ownership map epoch " + std::to_string(m->map_epoch) +
          ": shard already at epoch " + std::to_string(omap_->map_epoch));
  }
  if (wal_ != nullptr) {
    Status ps = PersistOwnership(wal_->dir(), m->Encode());
    if (!ps.ok())
      return Status::Internal("ownership persist failed: " + ps.message());
  }
  {
    std::lock_guard<std::mutex> lk(omap_mu_);
    omap_ = m;
  }
  map_epoch_.store(m->map_epoch);
  // strand every cached prepared plan (all connections): the distribute
  // rewrite bakes shard routing into client plans, so a flip makes them
  // stale — the next prepared execute against an old-generation entry
  // answers the counted miss status and the client re-prepares against
  // the new map. Never a silent stale-plan execute.
  plan_gen_.fetch_add(1);
  // routing flipped: cached replies may have been computed for rows this
  // shard no longer owns — drop the whole reuse window (counted)
  InvalidateReuse();
  ET_LOG(INFO) << "shard " << shard_idx_ << " installed ownership map "
               << m->Encode();
  return Status::OK();
}

void GraphServer::HandleSetOwnership(ByteReader* r, ByteWriter* w) {
  std::string spec(r->cursor(), r->remaining());
  auto m = std::make_shared<OwnershipMap>();
  Status s = OwnershipMap::Decode(spec, m.get());
  if (s.ok()) s = SetOwnership(std::move(m));
  if (!s.ok()) {
    w->Put<uint32_t>(1);
    w->PutStr(s.message());
    return;
  }
  w->Put<uint32_t>(0);
  w->Put<uint64_t>(map_epoch_.load());
}

void GraphServer::HandleGetDelta(ByteReader* r, ByteWriter* w) {
  uint64_t from = 0;
  if (!r->Get(&from)) {
    w->Put<uint32_t>(1);
    w->PutStr("truncated get-delta body");
    return;
  }
  std::vector<NodeId> ids;
  uint64_t epoch = 0;
  bool covered = graph_ref_->DirtySince(from, &ids, &epoch);
  w->Put<uint32_t>(0);
  w->Put<uint64_t>(epoch);
  w->Put<uint8_t>(covered ? 1 : 0);
  w->Put<uint64_t>(static_cast<uint64_t>(ids.size()));
  if (!ids.empty()) w->PutRaw(ids.data(), ids.size() * sizeof(NodeId));
}

// kGetDeltaLog: the raw retained delta records with epoch > from —
// what a recovering peer replays to close its gap. covered=0 when the
// bounded retained log no longer reaches from+1 (the peer cannot catch
// up from us; its clients fall back to the epoch-regression flush).
void GraphServer::HandleGetDeltaLog(ByteReader* r, ByteWriter* w) {
  uint64_t from = 0;
  if (!r->Get(&from)) {
    w->Put<uint32_t>(1);
    w->PutStr("truncated get-delta-log body");
    return;
  }
  std::lock_guard<std::mutex> lk(dlog_mu_);
  uint64_t cur = graph_ref_->epoch();
  // covered: nothing newer than `from`, or the retained log's oldest
  // record is <= from+1 (epochs are consecutive, so that means every
  // epoch in (from, cur] is present). A shard whose own recovery left
  // an unclosed gap never claims coverage: its locally-stamped epochs
  // may alias DIFFERENT fleet deltas, and serving them would diverge
  // the peer at matching epoch numbers (no regression flush would
  // ever fire).
  bool covered = dlog_authoritative_.load() &&
                 (from >= cur ||
                  (!dlog_.empty() && dlog_.front().first <= from + 1));
  w->Put<uint32_t>(0);
  w->Put<uint64_t>(cur);
  w->Put<uint8_t>(covered ? 1 : 0);
  // never serve records beyond our own epoch: a WAL-seeded log can hold
  // a record this server failed to (re)apply, and a peer must not be
  // told the fleet reached an epoch this server's graph does not have
  uint32_t count = 0;
  if (covered) {
    for (const auto& rec : dlog_)
      if (rec.first > from && rec.first <= cur) ++count;
  }
  w->Put<uint32_t>(count);
  if (count > 0) {
    for (const auto& rec : dlog_) {
      if (rec.first <= from || rec.first > cur) continue;
      w->Put<uint64_t>(rec.first);
      w->Put<uint64_t>(static_cast<uint64_t>(rec.second.size()));
      w->PutRaw(rec.second.data(), rec.second.size());
    }
  }
}

void GraphServer::SeedDeltaLog(const std::vector<WalRecord>& recs) {
  const uint64_t cur = graph_ref_->epoch();
  std::lock_guard<std::mutex> lk(dlog_mu_);
  for (const auto& rec : recs) {
    // replay may have stopped BEFORE a valid record (failed apply /
    // epoch gap); seeding past the recovered epoch would park a stale
    // body that aliases a future live epoch — a catching-up peer would
    // apply the stale body and skip the real one (silent divergence)
    if (rec.epoch > cur) break;  // records are epoch-ordered
    dlog_.emplace_back(rec.epoch, rec.body);
    dlog_bytes_ += rec.body.size();
  }
  while (dlog_.size() > kMaxDlogRecords || dlog_bytes_ > kMaxDlogBytes) {
    dlog_bytes_ -= dlog_.front().second.size();
    dlog_.pop_front();
  }
}

Status GraphServer::CatchUpFromPeer(const std::string& host, int port) {
  auto chan = std::make_shared<RpcChannel>(host, port);
  chan->set_timeout_ms(5000);
  // bounded rounds: each round either reaches the peer's epoch or makes
  // progress; a peer that keeps advancing faster than we apply would be
  // pathological (applies are serialized fleet-wide in practice)
  for (int round = 0; round < 64; ++round) {
    uint64_t my = graph_ref_->epoch();
    ByteWriter req;
    req.Put<uint64_t>(my);
    std::vector<char> reply;
    ET_RETURN_IF_ERROR(chan->Call(kGetDeltaLog, req.buffer(), &reply, 2));
    ByteReader r(reply.data(), reply.size());
    uint32_t code = 1, count = 0;
    uint64_t peer_epoch = 0;
    uint8_t covered = 0;
    if (!r.Get(&code) || code != 0 || !r.Get(&peer_epoch) ||
        !r.Get(&covered) || !r.Get(&count))
      return Status::IOError("bad get-delta-log reply from " + host + ":" +
                             std::to_string(port));
    if (!covered)
      return Status::Internal(
          "peer " + host + ":" + std::to_string(port) +
          "'s retained delta log no longer reaches epoch " +
          std::to_string(my) + " (peer at " + std::to_string(peer_epoch) +
          ")");
    if (count == 0) {
      // count==0 with peer_epoch > my is the swap/retained-log race:
      // the peer published an epoch whose record is not in its dlog_
      // yet (appended after the snapshot swap). Returning "caught up"
      // here would silently miss that delta forever — back off briefly
      // and retry the round instead (the window is the tail of one
      // apply; the bounded round count still terminates).
      if (peer_epoch <= my) return Status::OK();  // caught up
      ::usleep(50 * 1000);
      continue;
    }
    for (uint32_t i = 0; i < count; ++i) {
      uint64_t e = 0, blen = 0;
      if (!r.Get(&e) || !r.Get(&blen) || blen > r.remaining())
        return Status::IOError("truncated get-delta-log record");
      const char* p = r.cursor();
      r.Skip(blen);
      if (e <= graph_ref_->epoch()) continue;
      ByteWriter w;
      ApplyDeltaBody(p, static_cast<size_t>(blen), &w);
      ByteReader rr(w.buffer().data(), w.buffer().size());
      uint32_t ac = 1;
      rr.Get(&ac);
      if (ac != 0) {
        std::string msg;
        rr.GetStr(&msg);
        return Status::Internal("catch-up apply for epoch " +
                                std::to_string(e) + " failed: " + msg);
      }
      GlobalWalCounters().catchup_deltas.fetch_add(1);
    }
    if (graph_ref_->epoch() >= peer_epoch) return Status::OK();
    if (graph_ref_->epoch() == my)
      return Status::Internal("catch-up made no progress at epoch " +
                              std::to_string(my));
  }
  // rounds exhausted while still behind: report it — the caller's
  // warning path tells the operator the truth instead of an INFO line
  // claiming "catch-up complete" above the fleet's real state
  return Status::Internal(
      "anti-entropy catch-up did not converge (still at epoch " +
      std::to_string(graph_ref_->epoch()) + ")");
}

Status GraphServer::CatchUpFromRegistry(const std::string& registry) {
  std::map<int, std::pair<std::string, int>> found;
  std::map<int, int64_t> ages;
  Status s = ScanRegistrySpec(registry, &found, &ages);
  if (!s.ok()) return Status::OK();  // unreadable registry: nothing to do
  Status last = Status::OK();
  bool tried = false;
  for (const auto& kv : found) {
    if (kv.first == shard_idx_) continue;  // our own (possibly stale) entry
    tried = true;
    last = CatchUpFromPeer(kv.second.first, kv.second.second);
    if (last.ok()) {
      ET_LOG(INFO) << "shard " << shard_idx_
                   << " anti-entropy catch-up complete at epoch "
                   << graph_ref_->epoch() << " (peer shard " << kv.first
                   << ")";
      return Status::OK();
    }
  }
  if (tried) {
    // non-fatal by design: serve at the reached epoch; clients detect
    // the regression and full-flush (the documented fallback). The
    // failure IS returned so the caller can mark this shard's delta
    // log non-authoritative — its upcoming live epochs may alias
    // fleet deltas it never saw.
    ET_LOG(WARNING) << "shard " << shard_idx_
                    << " anti-entropy catch-up failed ("
                    << last.message() << ") — serving at epoch "
                    << graph_ref_->epoch();
    return last;
  }
  return Status::OK();
}

void GraphServer::HandleConnection(int fd) {
  auto conn = std::make_shared<ConnState>(fd);
  std::vector<char> body;
  uint32_t msg_type = 0, flags = 0;
  uint64_t req_id = 0;
  int ver = 0;
  while (!stopping_.load() &&
         ReadAnyFrame(fd, &ver, &msg_type, &flags, &req_id, &body,
                      /*accept_v2=*/!v1_only_)) {
    if (ver == 2) {
      // pipelined path: dispatch and keep reading — replies return
      // out-of-order, correlated by request_id
      if (!HandleV2Frame(conn, msg_type, req_id, flags, std::move(body)))
        break;
      continue;
    }
    // v1: serial request/reply on the reader thread, byte-for-byte the
    // pre-v2 behavior (old 'ETFR' clients see an unchanged server).
    // Handler wall time still lands in the per-verb execute histogram —
    // the breakdown phases (queue/decode/serialize) are a v2 concept.
    const int64_t v1_t0 = SteadyNowUs();
    ByteWriter w;
    if (msg_type == kExecute) {
      ByteReader r(body.data(), body.size());
      HandleExecute(&r, &w);
    } else if (msg_type == kMeta) {
      BuildMeta(&w);
    } else if (msg_type == kApplyDelta) {
      ByteReader r(body.data(), body.size());
      HandleApplyDelta(&r, &w);
    } else if (msg_type == kGetDelta) {
      ByteReader r(body.data(), body.size());
      HandleGetDelta(&r, &w);
    } else if (msg_type == kGetDeltaLog) {
      ByteReader r(body.data(), body.size());
      HandleGetDeltaLog(&r, &w);
    } else if (msg_type == kSetOwnership) {
      ByteReader r(body.data(), body.size());
      HandleSetOwnership(&r, &w);
    } else if (msg_type == kPrepare) {
      // per-connection plan state is a v2 concept; a v1 peer can only
      // have sent this by mistake — refuse explicitly, never a silent
      // ping-shaped 0 that would misparse as a registered plan
      w.Put<uint32_t>(1);
      w.PutStr("prepared plans require the v2 transport");
    } else {  // ping
      w.Put<uint32_t>(0);
    }
    GlobalServerTraceStats().Observe(
        ServerTraceStats::VerbSlot(msg_type), /*phase=execute*/ 2,
        static_cast<uint64_t>(SteadyNowUs() - v1_t0));
    if (!WriteFrame(fd, msg_type, w.buffer().data(), w.buffer().size()))
      break;
  }
  // v2 executions may still be completing on the pool; they write under
  // conn->wmu and MUST finish before the fd closes under them
  {
    std::unique_lock<std::mutex> lk(conn->imu);
    conn->icv.wait(lk, [&] { return conn->inflight == 0; });
  }
  ::close(fd);
  std::lock_guard<std::mutex> lk(conn_mu_);
  for (size_t i = 0; i < conn_fds_.size(); ++i) {
    if (conn_fds_[i] == fd) {
      conn_fds_[i] = conn_fds_.back();
      conn_fds_.pop_back();
      break;
    }
  }
}

bool GraphServer::HandleV2Frame(const std::shared_ptr<ConnState>& conn,
                                uint32_t msg_type, uint64_t request_id,
                                uint32_t flags, std::vector<char> body) {
  // shared reply writer: optional epoch prefix (hello-negotiated — the
  // passive bump-observation channel for mux clients), then adaptive
  // compression (only if the hello offered it, the body clears the
  // client's threshold, AND deflate actually shrinks it), then one
  // frame under the per-connection write lock
  auto write_reply = [this, conn](uint32_t mt, uint64_t rid,
                                  const std::vector<char>& payload) {
    uint32_t out_flags = 0;
    uint64_t epoch = 0;
    const bool stamp = conn->peer_epoch && mt != kHello;
    if (stamp) {
      epoch = graph_ref_->epoch();
      out_flags |= kFrameFlagEpoch;
    }
    const size_t raw_len = payload.size() + (stamp ? 8 : 0);
    std::vector<char> comp;
    bool compressed = false;
    const bool try_compress = conn->peer_compress &&
                              conn->peer_threshold > 0 &&
                              raw_len >= conn->peer_threshold;
    // the epoch prefix lives INSIDE the deflate stream; this branch
    // already pays buffer copies, so stamping-by-copy is free here
    std::vector<char> stamped;
    const std::vector<char>* src = &payload;
    if (try_compress && stamp) {
      stamped.reserve(raw_len);
      stamped.resize(8);
      std::memcpy(stamped.data(), &epoch, 8);
      stamped.insert(stamped.end(), payload.begin(), payload.end());
      src = &stamped;
    }
    std::lock_guard<std::mutex> lk(conn->wmu);
    if (conn->write_broken) return;
    if (try_compress) {
      // deflate under wmu: the reused per-connection context (one
      // deflateInit per connection, reset per frame) is single-writer
      compressed = conn->deflate.Deflate(*src, &comp);
      if (compressed) out_flags |= kFrameFlagCompressed;
    }
    bool ok;
    if (compressed) {
      ok = WriteFrameV2(conn->fd, mt, out_flags, rid, comp.data(),
                        comp.size());
    } else if (stamp) {
      // scatter write (header | epoch | body): prepending 8 bytes must
      // not cost an O(body) copy on every uncompressed reply
      char hdr[kV2HdrLen];
      FillV2Hdr(hdr, mt, out_flags, rid, raw_len);
      ok = WriteAll(conn->fd, hdr, kV2HdrLen) &&
           WriteAll(conn->fd, reinterpret_cast<const char*>(&epoch), 8) &&
           WriteAll(conn->fd, payload.data(), payload.size());
    } else {
      ok = WriteFrameV2(conn->fd, mt, out_flags, rid, payload.data(),
                        payload.size());
    }
    if (!ok) conn->write_broken = true;
  };

  const int64_t arrival_us = SteadyNowUs();
  if ((flags & kFrameFlagCompressed) != 0) {
    std::vector<char> raw;
    if (!InflateBody(body, &raw)) return false;  // protocol error
    body = std::move(raw);
  }
  // propagated deadline: remaining budget at client send time (µs).
  // Measured against time spent HERE (arrival → dispatch pickup) only —
  // wire flight time is invisible without clock agreement.
  int64_t deadline_us = 0;
  if ((flags & kFrameFlagDeadline) != 0) {
    if (body.size() < 8) return false;  // protocol error
    uint64_t rem = 0;
    std::memcpy(&rem, body.data(), 8);
    deadline_us = static_cast<int64_t>(std::min<uint64_t>(rem, 1ULL << 62));
    body.erase(body.begin(), body.begin() + 8);
  }
  // ownership-map epoch the client routed this request with (second
  // prefix, after the deadline — same wire order WriteRequest stamps)
  uint64_t req_map_epoch = 0;
  if ((flags & kFrameFlagMapEpoch) != 0) {
    if (body.size() < 8) return false;  // protocol error
    std::memcpy(&req_map_epoch, body.data(), 8);
    body.erase(body.begin(), body.begin() + 8);
  }
  // wire trace context (third prefix): the client span this request's
  // server-side timing breakdown nests under in a merged trace
  WireTrace req_trace;
  if ((flags & kFrameFlagTrace) != 0) {
    if (body.size() < 16) return false;  // protocol error
    std::memcpy(&req_trace.id, body.data(), 8);
    std::memcpy(&req_trace.parent, body.data() + 8, 8);
    body.erase(body.begin(), body.begin() + 16);
  }
  // prepared-plan id (fourth prefix): the remaining body is feed
  // tensors only; the DAG + outputs come from the connection's plan
  // cache (or the request answers the explicit miss status below)
  uint64_t plan_id = 0;
  if ((flags & kFrameFlagPrepared) != 0) {
    if (body.size() < 8) return false;  // protocol error
    std::memcpy(&plan_id, body.data(), 8);
    body.erase(body.begin(), body.begin() + 8);
  }
  if (msg_type == kHello) {
    ByteReader r(body.data(), body.size());
    uint32_t pver = 0, feats = 0;
    uint64_t thresh = 0;
    if (r.Get(&pver) && r.Get(&feats)) r.Get(&thresh);
    // reader-thread-only writes, and every dispatch happens after the
    // hello on the same thread — no lock needed
    conn->peer_compress = (feats & kFeatAcceptCompressed) != 0;
    conn->peer_epoch = (feats & kFeatEpoch) != 0;
    conn->peer_threshold = thresh;
    ByteWriter w;
    w.Put<uint32_t>(kProtoV2);
    w.Put<uint32_t>(kFeatAcceptCompressed | kFeatEpoch | kFeatDeadline |
                    kFeatMapEpoch | kFeatTrace | kFeatPrepared);
    w.Put<uint64_t>(thresh);
    write_reply(kHello, request_id, w.buffer());
    return true;
  }
  if (msg_type == kPrepare) {
    // register on the reader thread: decode + optimize is O(plan)
    // exactly once per plan per PROCESS (shared store) — the cost every
    // later prepared kExecute from any connection stops paying
    ByteWriter w;
    ExecuteRequest preq;
    ByteReader r(body.data(), body.size());
    Status ps = DecodeExecutePlan(&r, &preq);
    if (ps.ok() && r.remaining() != 0)
      ps = Status::IOError("trailing bytes after execute plan");
    if (!ps.ok()) {
      w.Put<uint32_t>(1);
      w.PutStr(ps.message());
    } else {
      auto& ctr = GlobalRpcCounters();
      const uint64_t id = PlanContentHash(body.data(), body.size());
      auto plan = std::make_shared<PreparedPlan>();
      plan->dag.nodes = std::move(preq.nodes);
      plan->outputs = std::move(preq.outputs);
      plan->gen = plan_gen_.load();
      // prepare-time optimizer: rewrite ONCE here so every execute of
      // this plan runs the optimized form. A pass failure keeps the
      // verbatim plan (registration never fails on optimizer grounds).
      if (GlobalRpcConfig().plan_optimize.load()) {
        std::string before = DagToString(plan->dag);
        DAGDef opt;
        opt.nodes = plan->dag.nodes;  // copy; rewrite the copy
        opt.next_id = static_cast<int>(opt.nodes.size()) + 1000;
        PlanOptStats st;
        if (OptimizePreparedPlan(&opt, plan->outputs, &st).ok()) {
          const bool rewrote = st.fuse + st.pushdown + st.dedup > 0;
          if (rewrote) {
            plan->dag = std::move(opt);
            plan->optimized = true;
            plan->opt_stats = st;
            plan->verbatim_text = std::move(before);
            ctr.plan_optimized.fetch_add(1);
            ctr.plan_rewrites_fuse.fetch_add(st.fuse);
            ctr.plan_rewrites_pushdown.fetch_add(st.pushdown);
            ctr.plan_rewrites_dedup.fetch_add(st.dedup);
          }
        }
      }
      plan->deterministic = DagIsDeterministic(plan->dag);
      const int cap = std::max(GlobalRpcConfig().plan_cache.load(), 1);
      {
        std::lock_guard<std::mutex> lk(plan_mu_);
        auto it = plans_.find(id);
        if (it != plans_.end()) {
          // re-registration after a generation bump = the per-epoch
          // re-derivation of the routing the client plan bakes in
          if (it->second.first->gen != plan->gen)
            ctr.plan_rewrites_epoch.fetch_add(1);
          plan_lru_.erase(it->second.second);
          plans_.erase(it);
        }
        plan_lru_.push_front(id);
        plans_[id] = {std::move(plan), plan_lru_.begin()};
        while (static_cast<int>(plans_.size()) > cap) {
          plans_.erase(plan_lru_.back());
          plan_lru_.pop_back();
        }
      }
      ctr.prepared_registered.fetch_add(1);
      w.Put<uint32_t>(0);
      w.Put<uint64_t>(id);
    }
    write_reply(kPrepare, request_id, w.buffer());
    return true;
  }
  if (msg_type == kApplyDelta || msg_type == kGetDelta ||
      msg_type == kGetDeltaLog) {
    // Off the reader thread: an apply's O(graph) snapshot rebuild on
    // this thread would stall every pipelined request multiplexed on
    // the connection (kExecute dispatches async for the same reason).
    // Counted in conn->inflight so close drains it; apply_mu_ already
    // serializes concurrent applies.
    {
      std::lock_guard<std::mutex> lk(conn->imu);
      ++conn->inflight;
    }
    GlobalThreadPool()->Schedule(
        [this, conn, write_reply, msg_type, request_id, arrival_us,
         body = std::move(body)] {
          auto& trace = GlobalServerTraceStats();
          const int slot = ServerTraceStats::VerbSlot(msg_type);
          const int64_t pickup_us = SteadyNowUs();
          trace.Observe(slot, /*queue*/ 0,
                        static_cast<uint64_t>(pickup_us - arrival_us));
          ByteWriter w;
          ByteReader r(body.data(), body.size());
          if (msg_type == kApplyDelta) {
            HandleApplyDelta(&r, &w);
          } else if (msg_type == kGetDelta) {
            HandleGetDelta(&r, &w);
          } else {
            HandleGetDeltaLog(&r, &w);
          }
          trace.Observe(slot, /*execute*/ 2,
                        static_cast<uint64_t>(SteadyNowUs() - pickup_us));
          write_reply(msg_type, request_id, w.buffer());
          std::lock_guard<std::mutex> lk(conn->imu);
          --conn->inflight;
          conn->icv.notify_all();
        },
        // priority lanes: delta/catch-up maintenance traffic must never
        // queue ahead of user reads on the dispatch pool
        ThreadPool::kLow);
    return true;
  }
  if (msg_type != kExecute) {
    ByteWriter w;
    if (msg_type == kMeta) {
      BuildMeta(&w);
    } else if (msg_type == kSetOwnership) {
      ByteReader r(body.data(), body.size());
      HandleSetOwnership(&r, &w);
    } else {  // ping / unknown
      w.Put<uint32_t>(0);
    }
    GlobalServerTraceStats().Observe(
        ServerTraceStats::VerbSlot(msg_type), /*execute*/ 2,
        static_cast<uint64_t>(SteadyNowUs() - arrival_us));
    write_reply(msg_type, request_id, w.buffer());
    return true;
  }
  // kExecute: bounded out-of-order dispatch — the point of v2. The DAG
  // runs ASYNCHRONOUSLY on the shared executor pool (Executor::Run's
  // completion fires on a pool thread), so one connection can have many
  // requests executing while this reader keeps reading; no server thread
  // is parked per in-flight request.
  //
  // Prepared execute: resolve the plan id against the server's SHARED
  // plan store FIRST. An unknown / evicted / generation-stale id
  // answers an explicit counted miss status right here — the feeds are
  // never guessed against some other plan, and the client re-prepares.
  std::shared_ptr<const PreparedPlan> prep;
  if (plan_id != 0) {
    auto& ctr = GlobalRpcCounters();
    bool invalidated = false;
    const uint64_t cur_gen = plan_gen_.load();
    {
      std::lock_guard<std::mutex> lk(plan_mu_);
      auto it = plans_.find(plan_id);
      if (it != plans_.end()) {
        if (it->second.first->gen != cur_gen) {
          // registered against a superseded ownership map: the client
          // plan bakes in shard routing the flip just moved
          plan_lru_.erase(it->second.second);
          plans_.erase(it);
          invalidated = true;
        } else {
          plan_lru_.splice(plan_lru_.begin(), plan_lru_,
                           it->second.second);
          prep = it->second.first;
        }
      }
    }
    if (prep == nullptr) {
      if (invalidated) {
        ctr.prepared_invalidated.fetch_add(1);
        // the stranded plan's distribute rewrite is about to be
        // re-derived under the new ownership epoch (the client answers
        // this miss with a fresh kPrepare) — the counted per-epoch
        // re-derivation, one per stranded plan
        ctr.plan_rewrites_epoch.fetch_add(1);
      }
      ctr.prepared_misses.fetch_add(1);
      ExecuteReply rep;
      rep.status = Status::Internal(
          "unknown prepared plan " + std::to_string(plan_id) +
          (invalidated
               ? " (invalidated by an ownership-map flip); re-prepare"
               : " on this server; re-prepare"));
      ByteWriter w;
      EncodeExecuteReply(rep, &w);
      write_reply(kExecute, request_id, w.buffer());
      return true;
    }
    ctr.prepared_hits.fetch_add(1);
  }
  int cap = std::max(GlobalRpcConfig().max_inflight.load(), 1);
  {
    std::unique_lock<std::mutex> lk(conn->imu);
    conn->icv.wait(lk, [&] {
      return conn->inflight < cap || stopping_.load();
    });
    if (stopping_.load()) return false;
    ++conn->inflight;
  }
  struct Pending {
    OpKernelContext ctx;
    // full-frame path: the request owns its decoded DAG + output names.
    DAGDef dag;
    std::vector<std::string> outputs;
    // prepared path: the DAG + outputs live in the shared cached plan,
    // executed in place (dag.h concurrency contract) — no per-request
    // decode or copy of the plan half.
    std::shared_ptr<const PreparedPlan> plan;
    std::unique_ptr<Executor> exec;
    // pins the snapshot this request runs against: a concurrent delta
    // apply swaps the ref, and the old graph must outlive the execution
    std::shared_ptr<const Graph> graph;
    std::shared_ptr<IndexManager> index;
    const std::vector<std::string>& out_names() const {
      return plan != nullptr ? plan->outputs : outputs;
    }
  };
  // Per-request timing breakdown (queue-wait / decode / execute /
  // serialize — exactly the quantities the deadline shed measures
  // implicitly): always observed into the native phase histograms;
  // additionally recorded into the bounded server span ring when the
  // request carried a wire trace context (kFeatTrace), so a merged
  // chrome trace stitches this shard's time under the client span.
  struct ReqTiming {
    WireTrace trace;
    int64_t arrival_us = 0;    // steady, at frame read
    int64_t wall_arrival_us = 0;
    int64_t pickup_us = 0;     // steady, at dispatch pickup
    int64_t decoded_us = 0;    // 0 when shed before decode
    int64_t exec_done_us = 0;  // 0 when the DAG never ran
    uint32_t flags = 0;  // bit0 deadline-shed, bit1 stale-map-shed,
                         // bit2 non-OK status
  };
  auto tm = std::make_shared<ReqTiming>();
  tm->trace = req_trace;
  tm->arrival_us = arrival_us;
  tm->wall_arrival_us = WallNowUs();
  // Zero-copy reply writer for kExecute: the reply is encoded as
  // SEGMENTS (metadata stream + views into the pinned output tensors)
  // and gather-written header | epoch | segments in one writev — an
  // uncompressed reply never copies its tensor payloads into one
  // contiguous buffer. Compression still needs contiguous bytes, so
  // that branch materializes them (it pays buffer passes anyway), and
  // the deflate state is the connection's reused context. Wire bytes
  // are identical to the EncodeExecuteReply path on every branch
  // (pinned by the native segments-parity test).
  auto write_exec_reply = [this, conn](uint64_t rid, ExecuteReply rep) {
    ReplySegments segs;
    EncodeExecuteReplySegments(std::move(rep), &segs);
    uint32_t out_flags = 0;
    uint64_t epoch = 0;
    const bool stamp = conn->peer_epoch;
    if (stamp) {
      epoch = graph_ref_->epoch();
      out_flags |= kFrameFlagEpoch;
    }
    const size_t raw_len = segs.total + (stamp ? 8 : 0);
    auto seg_ptr = [&segs](const ReplySegments::Run& r) {
      return r.tensor >= 0 ? reinterpret_cast<const char*>(
                                 segs.tensors[r.tensor].raw())
                           : segs.meta.buffer().data() + r.off;
    };
    const bool try_compress = conn->peer_compress &&
                              conn->peer_threshold > 0 &&
                              raw_len >= conn->peer_threshold;
    std::vector<char> contig;
    if (try_compress) {
      contig.reserve(raw_len);
      if (stamp)
        contig.insert(contig.end(), reinterpret_cast<const char*>(&epoch),
                      reinterpret_cast<const char*>(&epoch) + 8);
      for (const auto& r : segs.runs) {
        const char* p = seg_ptr(r);
        contig.insert(contig.end(), p, p + r.len);
      }
    }
    std::lock_guard<std::mutex> lk(conn->wmu);
    if (conn->write_broken) return;
    bool ok;
    std::vector<char> comp;
    // deflate under wmu: the per-connection context is single-writer
    if (try_compress && conn->deflate.Deflate(contig, &comp)) {
      out_flags |= kFrameFlagCompressed;
      ok = WriteFrameV2(conn->fd, kExecute, out_flags, rid, comp.data(),
                        comp.size());
    } else if (try_compress) {
      // would not shrink: the materialized raw bytes ship as-is
      ok = WriteFrameV2(conn->fd, kExecute, out_flags, rid, contig.data(),
                        contig.size());
    } else {
      char hdr[kV2HdrLen];
      FillV2Hdr(hdr, kExecute, out_flags, rid, raw_len);
      std::vector<iovec> iov;
      iov.reserve(2 + segs.runs.size());
      auto add_iov = [&iov](const void* p, size_t n) {
        iovec v;
        v.iov_base = const_cast<void*>(p);
        v.iov_len = n;
        iov.push_back(v);
      };
      add_iov(hdr, kV2HdrLen);
      if (stamp) add_iov(&epoch, 8);
      for (const auto& r : segs.runs) add_iov(seg_ptr(r), r.len);
      ok = WritevAll(conn->fd, &iov);
    }
    if (!ok) conn->write_broken = true;
  };
  auto finish = [conn, write_exec_reply, request_id,
                 tm](ExecuteReply rep) {
    const int64_t ser0 = SteadyNowUs();
    const bool rep_ok = rep.status.ok();
    write_exec_reply(request_id, std::move(rep));
    const uint64_t ser_us =
        static_cast<uint64_t>(SteadyNowUs() - ser0);
    auto& trace = GlobalServerTraceStats();
    const int64_t pickup = tm->pickup_us > 0 ? tm->pickup_us : ser0;
    const uint64_t queue_us =
        static_cast<uint64_t>(pickup - tm->arrival_us);
    const uint64_t decode_us =
        tm->decoded_us > 0 ? static_cast<uint64_t>(tm->decoded_us - pickup)
                           : 0;
    const uint64_t exec_us =
        tm->exec_done_us > 0 && tm->decoded_us > 0
            ? static_cast<uint64_t>(tm->exec_done_us - tm->decoded_us)
            : 0;
    trace.Observe(0, /*queue*/ 0, queue_us);
    if (tm->decoded_us > 0) trace.Observe(0, /*decode*/ 1, decode_us);
    if (tm->exec_done_us > 0) trace.Observe(0, /*execute*/ 2, exec_us);
    trace.Observe(0, /*serialize*/ 3, ser_us);
    if (tm->trace.id != 0) {
      if (!rep_ok) tm->flags |= 4u;
      auto clamp = [](uint64_t v) {
        return static_cast<uint32_t>(
            std::min<uint64_t>(v, 0xffffffffULL));
      };
      ServerTraceRecord rec;
      rec.trace_id = tm->trace.id;
      rec.parent_span = tm->trace.parent;
      rec.span_id = trace.NextSpanId();
      rec.verb = kExecute;
      rec.flags = tm->flags;
      rec.start_unix_us = tm->wall_arrival_us;
      rec.queue_us = clamp(queue_us);
      rec.decode_us = clamp(decode_us);
      rec.exec_us = clamp(exec_us);
      rec.serialize_us = clamp(ser_us);
      trace.Record(rec);
    }
    std::lock_guard<std::mutex> lk(conn->imu);
    --conn->inflight;
    conn->icv.notify_all();
  };
  // Decode + execute on the HIGH dispatch lane: the pool-queue wait in
  // front of this task is exactly the delay the propagated deadline
  // measures — a request whose budget already expired by pickup is
  // SHED with an explicit status (counted), its DAG never run.
  GlobalThreadPool()->Schedule(
      [this, finish, tm, deadline_us, arrival_us, req_map_epoch, prep,
       plan_id, body = std::move(body)]() mutable {
        tm->pickup_us = SteadyNowUs();
        // stale ownership map: the request was SPLIT with a routing map
        // this shard has since superseded — partitions it stopped
        // owning no longer receive deltas here, so serving the read
        // would be a silent misroute. Refuse with an explicit status;
        // the client refreshes the registry-published map and retries.
        // One-sided (older only): a NEWER client epoch is safe — flips
        // only shrink a surviving shard's owned set, and rows it still
        // gets asked for are rows it still owns under the new map.
        const uint64_t have_map = map_epoch_.load();
        if (req_map_epoch != 0 && have_map != 0 &&
            req_map_epoch < have_map) {
          GlobalRpcCounters().stale_map_shed.fetch_add(1);
          tm->flags |= 2u;
          ExecuteReply rep;
          rep.status = Status::Internal(
              "stale ownership map: request routed on map epoch " +
              std::to_string(req_map_epoch) + ", shard is at " +
              std::to_string(have_map) + "; refresh the map and retry");
          finish(rep);
          return;
        }
        if (deadline_us > 0 && tm->pickup_us - arrival_us > deadline_us) {
          GlobalRpcCounters().deadline_shed.fetch_add(1);
          tm->flags |= 1u;
          ExecuteReply rep;
          rep.status = Status::Internal(
              "deadline shed: request waited " +
              std::to_string(tm->pickup_us - arrival_us) +
              "us in dispatch, past its " + std::to_string(deadline_us) +
              "us remaining budget");
          finish(rep);
          return;
        }
        auto p = std::make_shared<Pending>();
        // snapshot FIRST: the reuse/coalesce key must name the exact
        // graph this request will execute against
        SnapshotState(&p->graph, &p->index);
        // ---- deterministic fast paths (tentpole): result reuse +
        // cross-request coalescing. Gated on a DETERMINISTIC prepared
        // plan — a plan whose feed bytes fully determine its reply —
        // and keyed (plan id, graph snapshot uid, feed-byte hash) with
        // an exact feed compare on every match.
        const int reuse_cap = GlobalRpcConfig().reuse_window.load();
        const int64_t co_win = GlobalRpcConfig().coalesce_window_us.load();
        const bool fast_eligible =
            prep != nullptr && prep->deterministic &&
            (reuse_cap > 0 || co_win > 0);
        uint64_t key = 0;
        if (fast_eligible) {
          auto mix = [](uint64_t a, uint64_t b) {
            return a ^ (b + 0x9e3779b97f4a7c15ULL + (a << 6) + (a >> 2));
          };
          key = mix(mix(plan_id, p->graph->uid()),
                    PlanContentHash(body.data(), body.size()));
        }
        auto feeds_match = [&body](const std::vector<char>& feeds) {
          return feeds.size() == body.size() &&
                 (body.empty() ||
                  std::memcmp(feeds.data(), body.data(), body.size()) == 0);
        };
        if (fast_eligible && reuse_cap > 0) {
          std::shared_ptr<const ReuseEntry> hit;
          {
            std::lock_guard<std::mutex> lk(reuse_mu_);
            auto it = reuse_.find(key);
            if (it != reuse_.end() &&
                it->second.first->plan_id == plan_id &&
                it->second.first->graph_uid == p->graph->uid() &&
                feeds_match(it->second.first->feeds)) {
              reuse_lru_.splice(reuse_lru_.begin(), reuse_lru_,
                                it->second.second);
              hit = it->second.first;
            }
          }
          if (hit != nullptr) {
            // served from the window: no decode, no execute — the
            // phases the histograms see shrink to exactly the saved work
            GlobalRpcCounters().reuse_hits.fetch_add(1);
            tm->decoded_us = SteadyNowUs();
            tm->exec_done_us = tm->decoded_us;
            ExecuteReply rep;
            rep.outputs = hit->outputs;  // refcounted payload shares
            finish(std::move(rep));
            return;
          }
          GlobalRpcCounters().reuse_misses.fetch_add(1);
        }
        std::shared_ptr<CoalesceBucket> bucket;
        if (fast_eligible && co_win > 0) {
          std::unique_lock<std::mutex> lk(coalesce_mu_);
          auto it = coalesce_.find(key);
          if (it != coalesce_.end() && !it->second->closed &&
              it->second->plan_id == plan_id &&
              it->second->graph_uid == p->graph->uid() &&
              feeds_match(it->second->feeds)) {
            // follower: park the reply continuation; the open bucket's
            // leader answers it from the single shared execution. The
            // follower's execute phase is the shared run (MicroBatcher
            // attribution: coalescing makes execute a shared phase).
            tm->decoded_us = SteadyNowUs();
            it->second->waiters.push_back(
                [finish, tm](const ExecuteReply& rep) {
                  tm->exec_done_us = SteadyNowUs();
                  finish(rep);
                });
            GlobalRpcCounters().coalesced_requests.fetch_add(1);
            return;
          }
          bucket = std::make_shared<CoalesceBucket>();
          bucket->plan_id = plan_id;
          bucket->graph_uid = p->graph->uid();
          bucket->feeds.assign(body.begin(), body.end());
          coalesce_[key] = bucket;
          lk.unlock();
          // leader: bounded hold collecting same-key arrivals, then
          // close the bucket and execute once for everyone in it
          ::usleep(static_cast<useconds_t>(
              std::min<int64_t>(co_win, 100000)));
          lk.lock();
          bucket->closed = true;
          coalesce_.erase(key);
        }
        // every exit past this point must answer parked followers too
        auto deliver = [this, finish, bucket](ExecuteReply rep) {
          if (bucket != nullptr) {
            std::vector<std::function<void(const ExecuteReply&)>> ws;
            {
              std::lock_guard<std::mutex> lk(coalesce_mu_);
              ws = std::move(bucket->waiters);
            }
            if (!ws.empty())
              GlobalRpcCounters().coalesce_batches.fetch_add(1);
            for (auto& fn : ws) fn(rep);
          }
          finish(std::move(rep));
        };
        ExecuteRequest req;
        ByteReader r(body.data(), body.size());
        // prepared path: the body is feed tensors only — the decode
        // phase the histogram counts shrinks to exactly that
        Status ds = prep != nullptr ? DecodeExecuteFeeds(&r, &req)
                                    : DecodeExecuteRequest(&r, &req);
        if (!ds.ok()) {
          ExecuteReply rep;
          rep.status = ds;
          deliver(std::move(rep));
          return;
        }
        // decode ends here; the bench-only injected per-row work below
        // models row-proportional scan cost and belongs to EXECUTE
        tm->decoded_us = SteadyNowUs();
        const int64_t per_row_us = ExecDelayUsPerRow();
        if (per_row_us > 0) {
          uint64_t rows = 0;
          for (const auto& kv : req.inputs)
            if (kv.second.dtype() == DType::kU64)
              rows += static_cast<uint64_t>(kv.second.NumElements());
          if (rows > 0)
            ::usleep(static_cast<useconds_t>(
                std::min<int64_t>(per_row_us * rows, 1000000)));
        }
        for (auto& kv : req.inputs)
          p->ctx.Put(kv.first, std::move(kv.second));
        const DAGDef* dag_ptr;
        if (prep != nullptr) {
          p->plan = prep;  // executed in place, pinned for the run
          dag_ptr = &prep->dag;
        } else {
          p->dag.nodes = std::move(req.nodes);
          p->outputs = std::move(req.outputs);
          dag_ptr = &p->dag;
        }
        QueryEnv env;
        env.graph = p->graph.get();
        env.index = p->index.get();
        env.pool = GlobalThreadPool();
        if (deadline_us > 0) env.deadline_us = arrival_us + deadline_us;
        p->exec = std::make_unique<Executor>(dag_ptr, env, &p->ctx);
        const bool store_reuse = fast_eligible && reuse_cap > 0;
        // completion owns the last ref to p: the executor releases its
        // stored callback before invoking (see Executor::OnNodeDone), so
        // destroying the Executor from inside its own done is the
        // sanctioned pattern
        p->exec->Run([this, p, deliver, tm, store_reuse, reuse_cap, key,
                      plan_id, body = std::move(body)](Status rs) {
          tm->exec_done_us = SteadyNowUs();
          ExecuteReply rep;
          rep.status = rs;
          if (rs.ok()) {
            for (const auto& name : p->out_names()) {
              Tensor t;
              if (!p->ctx.Get(name, &t)) {
                rep.status = Status::NotFound(
                    "requested output not produced: " + name);
                rep.outputs.clear();
                break;
              }
              rep.outputs.emplace_back(name, std::move(t));
            }
          }
          if (store_reuse && rep.status.ok()) {
            // install BEFORE replying so a closed loop on this result
            // hits from its next request on
            auto e = std::make_shared<ReuseEntry>();
            e->plan_id = plan_id;
            e->graph_uid = p->graph->uid();
            e->feeds = std::move(body);
            e->outputs = rep.outputs;  // refcounted payload shares
            std::lock_guard<std::mutex> lk(reuse_mu_);
            auto it = reuse_.find(key);
            if (it != reuse_.end()) {
              reuse_lru_.erase(it->second.second);
              reuse_.erase(it);
            }
            reuse_lru_.push_front(key);
            reuse_[key] = {std::move(e), reuse_lru_.begin()};
            while (static_cast<int>(reuse_.size()) > reuse_cap) {
              reuse_.erase(reuse_lru_.back());
              reuse_lru_.pop_back();
            }
          }
          deliver(std::move(rep));
        });
      });
  return true;
}

void GraphServer::HandleExecute(ByteReader* r, ByteWriter* w) {
  ExecuteRequest req;
  ExecuteReply rep;
  Status s = DecodeExecuteRequest(r, &req);
  if (s.ok()) {
    // Parity: GrpcWorker::ExecuteAsync (grpc_worker.cc:40-96): ctx from
    // request inputs → run the DAG on the shared pool → encode outputs.
    OpKernelContext ctx;
    for (auto& kv : req.inputs) ctx.Put(kv.first, std::move(kv.second));
    DAGDef dag;
    dag.nodes = std::move(req.nodes);
    std::shared_ptr<const Graph> g;
    std::shared_ptr<IndexManager> idx;
    SnapshotState(&g, &idx);
    QueryEnv env;
    env.graph = g.get();
    env.index = idx.get();
    env.pool = GlobalThreadPool();
    Executor exec(&dag, env, &ctx);
    s = exec.RunSync();
    if (s.ok()) {
      for (const auto& name : req.outputs) {
        Tensor t;
        if (!ctx.Get(name, &t)) {
          s = Status::NotFound("requested output not produced: " + name);
          break;
        }
        rep.outputs.emplace_back(name, std::move(t));
      }
    }
  }
  rep.status = s;
  if (!s.ok()) rep.outputs.clear();
  EncodeExecuteReply(rep, w);
}

// ---------------------------------------------------------------------------
// RpcChannel::MuxConn — one multiplexed v2 connection. Callers stamp a
// fresh request_id, write their frame under the write lock, and park on a
// waiter slot; a single demux reader thread routes reply frames back by
// id (out-of-order welcome). A dead socket fails EVERY parked waiter with
// a status — an RST mid-stream can never leave a caller hanging.
// ---------------------------------------------------------------------------
class RpcChannel::MuxConn {
 public:
  // Shared completion state for one hedged call: two legs (primary +
  // hedge) on DIFFERENT connections race; the first reply wins and the
  // caller abandons the loser by request_id (CancelHedged — its late
  // reply is discarded by the demux reader). Conn death fails a leg
  // instead of hanging it; the call only fails when every submitted
  // leg failed.
  struct HedgeGroup {
    std::mutex mu;
    std::condition_variable cv;
    bool done = false;  // a winning reply was delivered
    int winner = -1;    // leg index of the winner
    std::vector<char> body;
    int submitted = 0;  // legs put on a wire
    int failed = 0;     // legs that died with a transport status
    Status fail_st = Status::OK();
  };

  MuxConn(int fd, bool peer_compress, int64_t compress_threshold,
          int max_inflight, std::atomic<uint64_t>* epoch_sink,
          bool peer_deadline, bool peer_map, bool peer_trace,
          bool peer_prepared)
      : fd_(fd),
        peer_compress_(peer_compress),
        peer_deadline_(peer_deadline),
        peer_map_(peer_map),
        peer_trace_(peer_trace),
        peer_prepared_(peer_prepared),
        compress_threshold_(compress_threshold),
        max_inflight_(std::max(max_inflight, 1)),
        epoch_sink_(epoch_sink) {
    reader_ = std::thread([this] { ReaderLoop(); });
  }

  ~MuxConn() {
    Shutdown();
    if (reader_.joinable()) reader_.join();
    ::close(fd_);
  }

  // Force-break: the reader unblocks, fails all waiters, and exits.
  void Shutdown() { ::shutdown(fd_, SHUT_RDWR); }

  bool broken() {
    std::lock_guard<std::mutex> lk(mu_);
    return broken_;
  }

  // Connection-selection signals for power-of-two-choices (PickSlot):
  // current in-flight depth + an EWMA of recent reply latency. A
  // stalled connection shows up in both and stops attracting calls.
  int inflight() {
    std::lock_guard<std::mutex> lk(mu_);
    return static_cast<int>(waiters_.size());
  }
  int64_t ewma_us() { return ewma_us_.load(); }

  // ---- prepared plans (client half) ----
  bool peer_prepared() const { return peer_prepared_; }
  bool HasPrepared(uint64_t plan_id) {
    std::lock_guard<std::mutex> lk(prep_mu_);
    return prepared_ids_.count(plan_id) != 0;
  }
  // A server miss means the plan fell out of the connection's LRU (or
  // an ownership flip stranded it): drop the local record so the next
  // attempt re-prepares.
  void ForgetPrepared(uint64_t plan_id) {
    std::lock_guard<std::mutex> lk(prep_mu_);
    prepared_ids_.erase(plan_id);
  }
  // Register `plan` on THIS connection (kPrepare round trip). The
  // server recomputes the id from the same bytes; a mismatch refuses
  // the registration rather than recording an id that would execute a
  // different plan.
  Status Prepare(const std::vector<char>& plan, uint64_t plan_id) {
    std::vector<char> reply;
    Status s = Call(kPrepare, plan, &reply);
    if (!s.ok()) return s;
    ByteReader r(reply.data(), reply.size());
    uint32_t code = 1;
    if (!r.Get(&code)) return Status::IOError("truncated prepare reply");
    if (code != 0) {
      std::string msg;
      r.GetStr(&msg);
      return Status::Internal("prepare refused: " + msg);
    }
    uint64_t id = 0;
    if (!r.Get(&id) || id != plan_id)
      return Status::Internal("prepare id mismatch (client " +
                              std::to_string(plan_id) + " vs server " +
                              std::to_string(id) + ")");
    std::lock_guard<std::mutex> lk(prep_mu_);
    prepared_ids_.insert(plan_id);
    return Status::OK();
  }

  Status Call(uint32_t msg_type, const std::vector<char>& body,
              std::vector<char>* reply_body, int64_t deadline_abs_us = 0,
              uint64_t map_epoch = 0, WireTrace trace = {},
              uint64_t plan_id = 0) {
    auto& ctr = GlobalRpcCounters();
    Waiter w;
    w.start_us = SteadyNowUs();
    uint64_t id = next_id_.fetch_add(1);
    {
      std::unique_lock<std::mutex> lk(mu_);
      // in-flight cap: block before writing request max_inflight+1 so a
      // runaway feeder can't queue unbounded server work on one conn
      cv_.wait(lk, [&] {
        return broken_ ||
               static_cast<int>(waiters_.size()) < max_inflight_;
      });
      if (broken_) return Status::IOError("mux connection is down");
      waiters_[id] = &w;
    }
    ctr.inflight.fetch_add(1);
    if (!WriteRequest(msg_type, id, body, deadline_abs_us, map_epoch,
                      trace, plan_id)) {
      // socket dead: tear the whole conn down so every parked waiter
      // (not just this call) gets a status promptly
      Shutdown();
    }
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return w.done || broken_; });
    ctr.inflight.fetch_sub(1);
    if (!w.done) {
      waiters_.erase(id);
      return Status::IOError("mux connection reset mid-call");
    }
    if (w.st.ok()) {
      *reply_body = std::move(w.body);
      ctr.round_trips.fetch_add(1);
      ctr.mux_calls.fetch_add(1);
    }
    return w.st;
  }

  // Callback waiter: done fires on the client pool once the reply frame
  // arrives (or with a status when the connection dies). No thread is
  // parked while the request is on the wire.
  void CallAsync(uint32_t msg_type, const std::vector<char>& body,
                 std::function<void(Status, std::vector<char>)> done,
                 int64_t deadline_abs_us = 0) {
    auto* w = new Waiter();
    w->cb = std::move(done);
    w->start_us = SteadyNowUs();
    uint64_t id = next_id_.fetch_add(1);
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (broken_) {
        FailAsyncWaiter(w, Status::IOError("mux connection is down"));
        return;
      }
      // async callers skip the blocking cap (they are bounded by their
      // own scheduling); the server still bounds dispatch per conn
      waiters_[id] = w;
    }
    GlobalRpcCounters().inflight.fetch_add(1);
    if (!WriteRequest(msg_type, id, body, deadline_abs_us, 0, {}))
      Shutdown();
  }

  // One leg of a hedged call: heap waiter bound to the shared group.
  // Returns the request_id (the cancellation handle), or 0 when this
  // connection is already down (the leg is recorded failed on the
  // group so the caller's wait predicate stays truthful).
  uint64_t SubmitHedged(uint32_t msg_type, const std::vector<char>& body,
                        const std::shared_ptr<HedgeGroup>& g, int leg,
                        int64_t deadline_abs_us, uint64_t map_epoch,
                        WireTrace trace, uint64_t plan_id = 0) {
    auto* w = new Waiter();
    w->hedge = g;
    w->leg = leg;
    w->start_us = SteadyNowUs();
    uint64_t id = next_id_.fetch_add(1);
    {
      std::unique_lock<std::mutex> lk(mu_);
      // same client-side backpressure as Call: hedging must not let a
      // runaway feeder queue unbounded server work on one conn
      cv_.wait(lk, [&] {
        return broken_ ||
               static_cast<int>(waiters_.size()) < max_inflight_;
      });
      if (broken_) {
        delete w;
        std::lock_guard<std::mutex> glk(g->mu);
        ++g->submitted;
        ++g->failed;
        g->fail_st = Status::IOError("mux connection is down");
        g->cv.notify_all();
        return 0;
      }
      // count the leg as submitted BEFORE the waiter becomes routable:
      // the reader could deliver its reply before we return
      {
        std::lock_guard<std::mutex> glk(g->mu);
        ++g->submitted;
      }
      waiters_[id] = w;
    }
    GlobalRpcCounters().inflight.fetch_add(1);
    if (!WriteRequest(msg_type, id, body, deadline_abs_us, map_epoch,
                      trace, plan_id))
      Shutdown();
    return id;
  }

  // Cancel an abandoned hedge leg by request_id: deregister the waiter
  // so the demux reader drops its late reply on the floor (the
  // "unknown id: discarded" path). Returns false when the reply (or
  // conn teardown) already consumed the waiter.
  bool CancelHedged(uint64_t id) {
    Waiter* w = nullptr;
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = waiters_.find(id);
      if (it == waiters_.end()) return false;
      w = it->second;
      waiters_.erase(it);
      cv_.notify_all();  // a cap slot freed
    }
    delete w;
    GlobalRpcCounters().inflight.fetch_sub(1);
    return true;
  }

 private:
  struct Waiter {
    Status st = Status::OK();
    std::vector<char> body;
    bool done = false;
    std::function<void(Status, std::vector<char>)> cb;  // async only
    std::shared_ptr<HedgeGroup> hedge;  // hedged legs only
    int leg = 0;
    int64_t start_us = 0;  // submit time (EWMA latency signal)
  };

  static void FailAsyncWaiter(Waiter* w, Status s) {
    auto cb = std::move(w->cb);
    delete w;
    ClientThreadPool()->Schedule([cb = std::move(cb), s]() mutable {
      cb(s, {});
    });
  }

  bool WriteRequest(uint32_t msg_type, uint64_t id,
                    const std::vector<char>& body, int64_t deadline_abs_us,
                    uint64_t map_epoch, WireTrace trace,
                    uint64_t plan_id = 0) {
    auto& ctr = GlobalRpcCounters();
    uint32_t flags = 0;
    // request prefixes, in wire order: [deadline u64][map_epoch u64]
    // [trace u64 id | u64 parent][plan_id u64], each hello-negotiated
    // and kExecute-only. Deadline stamps the REMAINING budget at write
    // time (an already-expired budget stamps 1µs so the SERVER sheds
    // it); map_epoch stamps the routing map this request was split
    // with, so a server on a NEWER map refuses it instead of serving a
    // partition whose deltas now land elsewhere; trace carries the
    // client span this request's server-side breakdown nests under;
    // plan_id marks a PREPARED execute whose body is feed tensors only
    // (the DAG was registered via kPrepare — CallExecutePrepared only
    // passes it when the peer advertised kFeatPrepared).
    char prefix[40];
    size_t npfx = 0;
    if (peer_deadline_ && deadline_abs_us > 0 && msg_type == kExecute) {
      uint64_t remaining_us = static_cast<uint64_t>(
          std::max<int64_t>(deadline_abs_us - SteadyNowUs(), 1));
      std::memcpy(prefix + npfx, &remaining_us, 8);
      npfx += 8;
      flags |= kFrameFlagDeadline;
      ctr.deadline_propagated.fetch_add(1);
    }
    if (peer_map_ && map_epoch != 0 && msg_type == kExecute) {
      // the CALLER's run-start epoch, not a live read: stamping a map
      // installed after the split could slip a stale-routed read past
      // the server's one-sided check (see QueryEnv.map_epoch)
      std::memcpy(prefix + npfx, &map_epoch, 8);
      npfx += 8;
      flags |= kFrameFlagMapEpoch;
    }
    if (peer_trace_ && trace.id != 0 && msg_type == kExecute) {
      // same context on every wire attempt of one logical call — the
      // SERVER mints a distinct span id per request, so hedge legs and
      // retries show as siblings under the same client span
      std::memcpy(prefix + npfx, &trace.id, 8);
      std::memcpy(prefix + npfx + 8, &trace.parent, 8);
      npfx += 16;
      flags |= kFrameFlagTrace;
      ctr.trace_propagated.fetch_add(1);
    }
    if (peer_prepared_ && plan_id != 0 && msg_type == kExecute) {
      std::memcpy(prefix + npfx, &plan_id, 8);
      npfx += 8;
      flags |= kFrameFlagPrepared;
    }
    // adaptive request compression (negotiated in the hello); the
    // prefixes ride INSIDE the deflate stream like the reply epoch
    // prefix does
    const size_t raw_len = body.size() + npfx;
    std::vector<char> stamped;
    const std::vector<char>* src = &body;
    const bool try_compress =
        peer_compress_ && compress_threshold_ > 0 &&
        static_cast<int64_t>(raw_len) >= compress_threshold_;
    if (try_compress && npfx > 0) {
      stamped.resize(npfx);
      std::memcpy(stamped.data(), prefix, npfx);
      stamped.insert(stamped.end(), body.begin(), body.end());
      src = &stamped;
    }
    bool wrote;
    size_t wire_len = raw_len;
    {
      std::lock_guard<std::mutex> lk(wmu_);
      const std::vector<char>* out = &body;
      std::vector<char> comp;
      if (try_compress) {
        // deflate under wmu: the reused per-connection deflate state
        // (deflateInit once, reset per frame) is single-writer, like
        // the fd itself
        if (dctx_.Deflate(*src, &comp)) {
          out = &comp;
          flags |= kFrameFlagCompressed;
          ctr.compressed_frames_sent.fetch_add(1);
        }
      }
      if ((flags & kFrameFlagCompressed) != 0) {
        wire_len = out->size();
        wrote = WriteFrameV2(fd_, msg_type, flags, id, out->data(),
                             out->size());
      } else if (npfx > 0) {
        // scatter write (header | prefixes | body): prefixing must not
        // cost an O(body) copy on every uncompressed stamped request
        char hdr[kV2HdrLen];
        FillV2Hdr(hdr, msg_type, flags, id, raw_len);
        wrote = WriteAll(fd_, hdr, kV2HdrLen) &&
                WriteAll(fd_, prefix, npfx) &&
                WriteAll(fd_, body.data(), body.size());
      } else {
        wrote = WriteFrameV2(fd_, msg_type, flags, id, body.data(),
                             body.size());
      }
    }
    ctr.bytes_sent_raw.fetch_add(kV2HdrLen + raw_len);
    if (wrote) ctr.bytes_sent.fetch_add(kV2HdrLen + wire_len);
    return wrote;
  }

  void ReaderLoop() {
    std::vector<char> body;
    uint32_t msg_type = 0, flags = 0;
    uint64_t id = 0;
    int ver = 0;
    auto& ctr = GlobalRpcCounters();
    for (;;) {
      if (!ReadAnyFrame(fd_, &ver, &msg_type, &flags, &id, &body) ||
          ver != 2)
        break;
      uint64_t wire = kV2HdrLen + body.size();
      if ((flags & kFrameFlagCompressed) != 0) {
        std::vector<char> raw;
        if (!InflateBody(body, &raw)) break;  // protocol error: drop conn
        body = std::move(raw);
        ctr.compressed_frames_received.fetch_add(1);
      }
      if ((flags & kFrameFlagEpoch) != 0) {
        // epoch prefix: the serving graph's version stamp rides every
        // reply — strip it and max-update the owner's observed epoch
        if (body.size() < 8) break;  // protocol error
        uint64_t epoch;
        std::memcpy(&epoch, body.data(), 8);
        MaxUpdateEpoch(epoch_sink_, epoch);
        body.erase(body.begin(), body.begin() + 8);
      }
      ctr.bytes_received.fetch_add(wire);
      ctr.bytes_received_raw.fetch_add(kV2HdrLen + body.size());
      Waiter* async_w = nullptr;
      Waiter* hedged_w = nullptr;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto it = waiters_.find(id);
        if (it != waiters_.end()) {
          Waiter* w = it->second;
          waiters_.erase(it);
          // EWMA reply latency (p2c signal): new = (7*old + sample) / 8
          if (w->start_us > 0) {
            int64_t sample = SteadyNowUs() - w->start_us;
            int64_t old = ewma_us_.load();
            ewma_us_.store(old == 0 ? sample : (7 * old + sample) / 8);
          }
          if (w->hedge) {
            w->body = std::move(body);
            hedged_w = w;
          } else if (w->cb) {
            w->body = std::move(body);
            async_w = w;
          } else {
            w->body = std::move(body);
            w->done = true;
          }
          // every branch shrank waiters_: wake completed sync callers
          // AND any sync Call parked on the max_inflight cap (async
          // completions must release cap slots too)
          cv_.notify_all();
        }
        // unknown id: reply for an abandoned (cancelled) waiter — dropped
      }
      if (hedged_w != nullptr) {
        ctr.inflight.fetch_sub(1);
        auto g = hedged_w->hedge;
        int leg = hedged_w->leg;
        std::vector<char> b = std::move(hedged_w->body);
        delete hedged_w;
        bool won;
        {
          std::lock_guard<std::mutex> glk(g->mu);
          won = !g->done;
          if (won) {
            g->done = true;
            g->winner = leg;
            g->body = std::move(b);
          }
          // else: the OTHER leg already won and this reply is
          // discarded (a raced loser the caller did not cancel in
          // time)
          g->cv.notify_all();
        }
        // round_trips/mux_calls stay 1:1 with LOGICAL calls whether
        // hedging is on or off: only the winning leg counts — a
        // discarded loser already shows in hedge_wasted and in the
        // bytes counters (the wire truth)
        if (won) {
          ctr.round_trips.fetch_add(1);
          ctr.mux_calls.fetch_add(1);
        }
      }
      if (async_w != nullptr) {
        ctr.inflight.fetch_sub(1);
        ctr.round_trips.fetch_add(1);
        ctr.mux_calls.fetch_add(1);
        ClientThreadPool()->Schedule([async_w] {
          auto cb = std::move(async_w->cb);
          Status st = async_w->st;
          std::vector<char> b = std::move(async_w->body);
          delete async_w;
          cb(st, std::move(b));
        });
      }
      body.clear();  // moved-from: reset for the next frame
    }
    // teardown: fail every parked waiter with a status — no hangs
    std::vector<Waiter*> async_fail;
    std::vector<Waiter*> hedge_fail;
    {
      std::lock_guard<std::mutex> lk(mu_);
      broken_ = true;
      for (auto& kv : waiters_) {
        if (kv.second->hedge) {
          hedge_fail.push_back(kv.second);
        } else if (kv.second->cb) {
          async_fail.push_back(kv.second);
        } else {
          kv.second->st =
              Status::IOError("mux connection reset with in-flight calls");
          kv.second->done = true;
        }
      }
      waiters_.clear();
      cv_.notify_all();
    }
    for (Waiter* w : hedge_fail) {
      ctr.inflight.fetch_sub(1);
      auto g = w->hedge;
      delete w;
      std::lock_guard<std::mutex> glk(g->mu);
      ++g->failed;
      g->fail_st =
          Status::IOError("mux connection reset with in-flight calls");
      g->cv.notify_all();
    }
    for (Waiter* w : async_fail) {
      ctr.inflight.fetch_sub(1);
      FailAsyncWaiter(
          w, Status::IOError("mux connection reset with in-flight calls"));
    }
  }

  const int fd_;
  const bool peer_compress_;
  const bool peer_deadline_;
  const bool peer_map_;
  const bool peer_trace_;
  const bool peer_prepared_;
  const int64_t compress_threshold_;
  const int max_inflight_;
  std::atomic<uint64_t>* const epoch_sink_;
  // plan ids registered on THIS connection (a reconnect starts empty —
  // server plan caches are per-connection state)
  std::mutex prep_mu_;
  std::unordered_set<uint64_t> prepared_ids_;
  // reused request-deflate state, serialized by wmu_ like the fd
  DeflateCtx dctx_;
  std::atomic<int64_t> ewma_us_{0};  // recent reply latency (p2c signal)
  std::atomic<uint64_t> next_id_{1};
  std::mutex wmu_;  // one writer at a time on the shared fd
  std::mutex mu_;   // waiters_ + broken_
  std::condition_variable cv_;
  bool broken_ = false;
  std::unordered_map<uint64_t, Waiter*> waiters_;
  std::thread reader_;
};

// ---------------------------------------------------------------------------
// RpcChannel
// ---------------------------------------------------------------------------
RpcChannel::RpcChannel(std::string host, int port)
    : host_(std::move(host)), port_(port) {}

RpcChannel::~RpcChannel() {
  {
    std::lock_guard<std::mutex> lk(mux_mu_);
    mux_conns_.clear();  // ~MuxConn: shutdown socket, join reader
  }
  std::lock_guard<std::mutex> lk(mu_);
  for (int fd : free_fds_) ::close(fd);
}

int RpcChannel::Connect() {
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  std::string port_s = std::to_string(port_);
  if (::getaddrinfo(host_.c_str(), port_s.c_str(), &hints, &res) != 0)
    return -1;
  int fd = -1;
  for (addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (timeout_ms_ > 0) {
      // bounded connect: a black-holed host would otherwise block the
      // kernel SYN-retry timeout (~2 min) — registry heartbeat/shutdown
      // paths cap this (see set_timeout_ms callers)
      int flags = ::fcntl(fd, F_GETFL, 0);
      ::fcntl(fd, F_SETFL, flags | O_NONBLOCK);
      int rc = ::connect(fd, ai->ai_addr, ai->ai_addrlen);
      if (rc != 0 && errno == EINPROGRESS) {
        pollfd pf{fd, POLLOUT, 0};
        rc = ::poll(&pf, 1, timeout_ms_) == 1 ? 0 : -1;
        if (rc == 0) {
          int err = 0;
          socklen_t len = sizeof(err);
          ::getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
          if (err != 0) rc = -1;
        }
      }
      ::fcntl(fd, F_SETFL, flags);
      if (rc == 0) {
        timeval tv{timeout_ms_ / 1000, (timeout_ms_ % 1000) * 1000};
        ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
        ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
        break;
      }
    } else if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
      break;
    }
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  if (fd >= 0) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    GlobalRpcCounters().connections_opened.fetch_add(1);
  }
  return fd;
}

int RpcChannel::Acquire() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!free_fds_.empty()) {
      int fd = free_fds_.back();
      free_fds_.pop_back();
      return fd;
    }
  }
  return Connect();
}

void RpcChannel::Release(int fd) {
  std::lock_guard<std::mutex> lk(mu_);
  if (static_cast<int>(free_fds_.size()) >= kMaxPooledFds) {
    // cap the idle pool: a concurrency burst used to grow it without
    // bound and the sockets were kept forever
    ::close(fd);
    return;
  }
  free_fds_.push_back(fd);
}

std::shared_ptr<RpcChannel::MuxConn> RpcChannel::MuxGet(int slot) {
  // the whole dial runs under mux_mu_: a thundering herd of callers
  // hitting an undialed slot must share ONE connection, not each open
  // their own (the fd frugality is the point of the mux)
  std::lock_guard<std::mutex> lk(mux_mu_);
  if (slot < static_cast<int>(mux_conns_.size()) && mux_conns_[slot] &&
      !mux_conns_[slot]->broken())
    return mux_conns_[slot];
  int fd = Connect();
  if (fd < 0) return nullptr;
  // The hello round trip below must be BOUNDED: it runs under mux_mu_,
  // so a peer that accepts the TCP connection but never answers (wedged
  // process, post-handshake black hole) would otherwise park every call
  // on this channel forever — the MuxConn "dead socket fails every
  // waiter" guarantee only starts after the handshake. timeout_ms_ wins
  // when the caller set one; 10s otherwise.
  {
    int hello_ms = timeout_ms_ > 0 ? timeout_ms_ : 10000;
    timeval tv{hello_ms / 1000, (hello_ms % 1000) * 1000};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  }
  const RpcConfig cfg = GlobalRpcConfig();
  ByteWriter hw;
  hw.Put<uint32_t>(kProtoV2);
  hw.Put<uint32_t>(kFeatAcceptCompressed | kFeatEpoch | kFeatDeadline |
                   kFeatMapEpoch | kFeatTrace | kFeatPrepared);
  const int64_t hello_thr = cfg.compress_threshold.load();
  hw.Put<uint64_t>(static_cast<uint64_t>(hello_thr > 0 ? hello_thr : 0));
  std::vector<char> hbody;
  uint32_t msg_type = 0, flags = 0;
  uint64_t rid = 0;
  int ver = 0;
  bool hello_ok = WriteFrameV2(fd, kHello, 0, 0, hw.buffer().data(),
                               hw.buffer().size()) &&
                  ReadAnyFrame(fd, &ver, &msg_type, &flags, &rid, &hbody) &&
                  ver == 2 && msg_type == kHello;
  bool peer_compress = false;
  bool peer_deadline = false;
  bool peer_map = false;
  bool peer_trace = false;
  bool peer_prepared = false;
  if (hello_ok) {
    ByteReader r(hbody.data(), hbody.size());
    uint32_t pver = 0, feats = 0;
    if (!r.Get(&pver) || !r.Get(&feats) || pver < kProtoV2) hello_ok = false;
    peer_compress = (feats & kFeatAcceptCompressed) != 0;
    // only stamp deadline/map-epoch/trace/prepared prefixes for servers
    // that will strip them — older v2 servers keep seeing
    // byte-identical requests
    peer_deadline = (feats & kFeatDeadline) != 0;
    peer_map = (feats & kFeatMapEpoch) != 0;
    peer_trace = (feats & kFeatTrace) != 0;
    peer_prepared = (feats & kFeatPrepared) != 0;
  }
  if (!hello_ok) {
    ::close(fd);
    // connect succeeded but the hello was refused: a pre-v2 server drops
    // the unknown magic. Fall back to v1 for this channel's lifetime (a
    // mid-handshake crash lands here too — still correct, just unmuxed
    // until the endpoint's channel is rebuilt by the registry monitor).
    v1_fallback_.store(true);
    GlobalRpcCounters().hello_fallbacks.fetch_add(1);
    ET_LOG_INFO << "rpc " << host_ << ":" << port_
                << " refused the v2 hello; falling back to v1 framing";
    return nullptr;
  }
  // Handshake bound must NOT leak onto the live mux fd: the demux reader
  // legitimately idles in recv between replies and a long merge may
  // stream past timeout_ms_ (header contract: on mux connections the
  // timeout applies to connect + hello only).
  {
    timeval tv{0, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  }
  auto conn = std::make_shared<MuxConn>(
      fd, peer_compress, cfg.compress_threshold, cfg.max_inflight,
      epoch_sink_, peer_deadline, peer_map, peer_trace, peer_prepared);
  if (slot >= static_cast<int>(mux_conns_.size()))
    mux_conns_.resize(slot + 1);
  mux_conns_[slot] = conn;
  return conn;
}

int RpcChannel::PickSlot(int slots, int avoid) {
  if (slots <= 1) return 0;
  if (avoid >= 0 && slots == 2) return 1 - avoid;
  if (!GlobalRpcConfig().p2c.load()) {
    // blind rotation (the pre-p2c default)
    int slot = static_cast<int>(mux_rr_.fetch_add(1) % slots);
    if (slot == avoid) slot = (slot + 1) % slots;
    return slot;
  }
  // power-of-two-choices: two distinct random slots, take the one with
  // the lower (inflight, EWMA latency) score. An undialed slot scores
  // as idle — it gets explored instead of starved.
  auto& rng = ThreadLocalRng();
  int a = static_cast<int>(rng.NextUInt(slots));
  int b = static_cast<int>(rng.NextUInt(slots - 1));
  if (b >= a) ++b;
  if (a == avoid) a = b;
  if (b == avoid) b = a;
  if (a == b) return a;
  int64_t ia = 0, ea = 0, ib = 0, eb = 0;
  {
    std::lock_guard<std::mutex> lk(mux_mu_);
    auto score = [this](int s, int64_t* infl, int64_t* ewma) {
      if (s < static_cast<int>(mux_conns_.size()) && mux_conns_[s] &&
          !mux_conns_[s]->broken()) {
        *infl = mux_conns_[s]->inflight();
        *ewma = mux_conns_[s]->ewma_us();
      }
    };
    score(a, &ia, &ea);
    score(b, &ib, &eb);
  }
  // load first (a stalled conn accumulates inflight), latency second
  if (ia != ib) return ia < ib ? a : b;
  return ea <= eb ? a : b;
}

Status RpcChannel::MuxCall(uint32_t msg_type, const std::vector<char>& body,
                           std::vector<char>* reply_body, int max_retries,
                           int64_t deadline_abs_us, uint64_t map_epoch,
                           WireTrace trace) {
  Status last = Status::IOError("rpc not attempted");
  for (int attempt = 0; attempt < max_retries; ++attempt) {
    if (v1_fallback_.load()) return last;  // caller switches to v1
    int slots = std::max(GlobalRpcConfig().mux_connections.load(), 1);
    int slot = PickSlot(slots);
    auto conn = MuxGet(slot);
    if (conn == nullptr) {
      if (v1_fallback_.load()) return last;
      JitteredBackoffUs(attempt);  // connect failed — dead endpoint
      continue;
    }
    // adaptive hedging (kExecute only — the idempotent-from-the-
    // client's-view read verb; a hedged mutation would double-apply):
    // needs a SECOND wire path, so mux_connections >= 2
    int64_t hedge_us = GlobalRpcConfig().hedge_delay_us.load();
    if (hedge_us > 0 && slots >= 2 && msg_type == kExecute) {
      last = HedgedMuxCall(conn, slot, slots, msg_type, body, reply_body,
                           hedge_us, deadline_abs_us, map_epoch, trace);
    } else {
      last = conn->Call(msg_type, body, reply_body, deadline_abs_us,
                        map_epoch, trace);
    }
    if (last.ok()) return last;
    // transport failure: the conn marked itself broken; the next attempt
    // re-dials (a dead endpoint fails fast in connect and backs off there)
  }
  return Status::IOError("rpc to " + host_ + ":" + std::to_string(port_) +
                         " failed after retries: " + last.message());
}

namespace {
// Does this decoded-enough reply carry the server's prepared-plan miss
// status? Only the leading code + message are peeked — the marker
// prefix is the contract (like "stale ownership map" / "deadline
// shed"), so a legitimate query error can never trigger a re-prepare
// loop.
bool IsPreparedMissReply(const std::vector<char>& reply) {
  ByteReader r(reply.data(), reply.size());
  uint32_t code = 0;
  std::string msg;
  if (!r.Get(&code) || code == 0 || !r.GetStr(&msg)) return false;
  return msg.rfind("unknown prepared plan", 0) == 0;
}
}  // namespace

Status RpcChannel::CallExecutePrepared(const std::vector<char>& plan,
                                       uint64_t plan_id,
                                       const std::vector<char>& feeds,
                                       std::vector<char>* reply_body,
                                       int max_retries,
                                       int64_t deadline_abs_us,
                                       uint64_t map_epoch,
                                       WireTrace trace) {
  if (max_retries <= 0) max_retries = kRetryCount;
  auto& ctr = GlobalRpcCounters();
  // correctness fallback: the classic full-plan frame, byte-identical
  // to EncodeExecuteRequest (serde invariant) — used whenever the
  // prepared path is unavailable or keeps missing
  auto full_call = [&]() -> Status {
    ctr.prepared_fallbacks.fetch_add(1);
    std::vector<char> full;
    Status as = AssembleFullExecuteRequest(feeds, plan, &full);
    if (!as.ok()) return as;
    return Call(kExecute, full, reply_body, max_retries, deadline_abs_us,
                map_epoch, trace);
  };
  if (!(mux_ && !v1_fallback_.load())) return full_call();
  Status last = Status::IOError("rpc not attempted");
  for (int attempt = 0; attempt < max_retries; ++attempt) {
    if (v1_fallback_.load()) return full_call();
    int slots = std::max(GlobalRpcConfig().mux_connections.load(), 1);
    int slot = PickSlot(slots);
    auto conn = MuxGet(slot);
    if (conn == nullptr) {
      if (v1_fallback_.load()) return full_call();
      JitteredBackoffUs(attempt);  // connect failed — dead endpoint
      continue;
    }
    if (!conn->peer_prepared()) return full_call();  // pre-feature peer
    if (!conn->HasPrepared(plan_id)) {
      last = conn->Prepare(plan, plan_id);
      if (!last.ok()) continue;  // transport died / server refused
    }
    int64_t hedge_us = GlobalRpcConfig().hedge_delay_us.load();
    if (hedge_us > 0 && slots >= 2) {
      last = HedgedMuxCall(conn, slot, slots, kExecute, feeds, reply_body,
                           hedge_us, deadline_abs_us, map_epoch, trace,
                           plan_id, &plan);
    } else {
      last = conn->Call(kExecute, feeds, reply_body, deadline_abs_us,
                        map_epoch, trace, plan_id);
    }
    if (!last.ok()) continue;  // transport failure: re-dial next attempt
    if (IsPreparedMissReply(*reply_body)) {
      // the server evicted or invalidated the plan (both counted on its
      // edge) — drop the local registration and re-prepare next attempt
      conn->ForgetPrepared(plan_id);
      last = Status::Internal("prepared plan missed; re-preparing");
      continue;
    }
    return Status::OK();
  }
  // attempts exhausted on the prepared path (endpoint flapping or a
  // pathological miss loop): the full frame is always correct
  return full_call();
}

// One hedged sync call (see RpcConfig::hedge_delay_us): primary leg on
// `conn`; if no reply lands inside hedge_us, the same request fires on
// a different mux connection and the FIRST reply wins. The loser is
// abandoned by request_id — CancelHedged drops its waiter so the demux
// reader discards the late reply — and counted hedge_wasted exactly
// once per abandoned leg. A leg that dies with its connection counts
// as failed, not wasted; the call only fails when every submitted leg
// failed (the outer MuxCall retry ladder then re-dials).
Status RpcChannel::HedgedMuxCall(const std::shared_ptr<MuxConn>& conn,
                                 int slot, int slots, uint32_t msg_type,
                                 const std::vector<char>& body,
                                 std::vector<char>* reply_body,
                                 int64_t hedge_us, int64_t deadline_abs_us,
                                 uint64_t map_epoch, WireTrace trace,
                                 uint64_t plan_id,
                                 const std::vector<char>* plan) {
  auto& ctr = GlobalRpcCounters();
  auto g = std::make_shared<MuxConn::HedgeGroup>();
  uint64_t id0 = conn->SubmitHedged(msg_type, body, g, 0, deadline_abs_us,
                                    map_epoch, trace, plan_id);
  std::shared_ptr<MuxConn> conn1;
  uint64_t id1 = 0;
  {
    std::unique_lock<std::mutex> lk(g->mu);
    if (id0 == 0)
      return Status::IOError("mux connection is down");
    g->cv.wait_for(lk, std::chrono::microseconds(hedge_us), [&] {
      return g->done || g->failed >= g->submitted;
    });
    if (!g->done && g->failed == 0) {
      // primary leg is straggling: fire the hedge on a different conn
      lk.unlock();
      conn1 = MuxGet(PickSlot(slots, /*avoid=*/slot));
      if (conn1 != nullptr && plan_id != 0 &&
          !conn1->HasPrepared(plan_id)) {
        // the hedge leg carries the SAME plan id as the primary, so
        // its connection must know the plan before the leg fires — a
        // one-time kPrepare round trip on a fresh hedge conn (later
        // hedges hit the registration). A failed prepare skips the
        // hedge rather than firing a leg guaranteed to miss.
        if (plan == nullptr || !conn1->Prepare(*plan, plan_id).ok())
          conn1 = nullptr;
      }
      if (conn1 != nullptr) {
        ctr.hedge_fired.fetch_add(1);
        id1 = conn1->SubmitHedged(msg_type, body, g, 1, deadline_abs_us,
                                  map_epoch, trace, plan_id);
      }
      lk.lock();
    }
    g->cv.wait(lk, [&] { return g->done || g->failed >= g->submitted; });
    if (!g->done) return g->fail_st;
    if (g->winner == 1) ctr.hedge_won.fetch_add(1);
    *reply_body = std::move(g->body);
  }
  // abandon the losing leg OUTSIDE g->mu (CancelHedged takes the conn
  // lock; the reader takes conn lock then g->mu — same order matters).
  // Counted wasted whether the cancel landed (reply still in flight,
  // now discarded by request_id) or the loser's reply raced in first
  // and was discarded at the group — both are abandoned work.
  bool loser_inflight;
  uint64_t loser_id;
  std::shared_ptr<MuxConn> loser_conn;
  {
    std::lock_guard<std::mutex> lk(g->mu);
    if (g->winner == 0) {
      loser_conn = conn1;
      loser_id = id1;
    } else {
      loser_conn = conn;
      loser_id = id0;
    }
    loser_inflight = g->submitted == 2 && g->failed == 0;
  }
  if (loser_inflight && loser_conn != nullptr && loser_id != 0) {
    loser_conn->CancelHedged(loser_id);
    ctr.hedge_wasted.fetch_add(1);
  }
  return Status::OK();
}

void RpcChannel::CallAsync(
    uint32_t msg_type, std::vector<char> body,
    std::function<void(Status, std::vector<char>)> done) {
  if (mux_active()) {
    int slots = std::max(GlobalRpcConfig().mux_connections.load(), 1);
    auto conn = MuxGet(PickSlot(slots));
    if (conn != nullptr) {
      conn->CallAsync(msg_type, body, std::move(done));
      return;
    }
  }
  // no mux connection (v1 server / connect failure): blocking call off
  // the caller's thread, full retry ladder included. The scheduled task
  // must not outlive the channel: when it is shared-owned (ClientManager,
  // which may drop its ref on a failover swap) hold a weak ref and fail
  // the callback with a status if the channel died first; a channel never
  // owned by a shared_ptr (stack-allocated in tests) keeps the old
  // caller-guarantees-lifetime contract.
  std::weak_ptr<RpcChannel> weak = weak_from_this();
  const bool shared_owned = !weak.expired();
  ClientThreadPool()->Schedule(
      [this, weak = std::move(weak), shared_owned, msg_type,
       body = std::move(body), done = std::move(done)] {
        std::shared_ptr<RpcChannel> self;
        if (shared_owned) {
          self = weak.lock();
          if (self == nullptr) {
            done(Status::IOError("rpc channel destroyed with call pending"),
                 {});
            return;
          }
        }
        std::vector<char> reply;
        Status s = Call(msg_type, body, &reply);
        done(s, std::move(reply));
      });
}

Status RpcChannel::Call(uint32_t msg_type, const std::vector<char>& body,
                        std::vector<char>* reply_body, int max_retries,
                        int64_t deadline_abs_us, uint64_t map_epoch,
                        WireTrace trace) {
  if (max_retries <= 0) max_retries = kRetryCount;
  if (mux_ && !v1_fallback_.load()) {
    Status s = MuxCall(msg_type, body, reply_body, max_retries,
                       deadline_abs_us, map_epoch, trace);
    if (s.ok() || !v1_fallback_.load()) return s;
    // the server refused the hello mid-call: finish this call on v1
  }
  auto& ctr = GlobalRpcCounters();
  for (int attempt = 0; attempt < max_retries; ++attempt) {
    int fd = Acquire();
    if (fd < 0) {
      JitteredBackoffUs(attempt);
      continue;
    }
    uint32_t reply_type;
    if (WriteFrame(fd, msg_type, body.data(), body.size()) &&
        ReadFrame(fd, &reply_type, reply_body) && reply_type == msg_type) {
      ctr.round_trips.fetch_add(1);
      ctr.v1_calls.fetch_add(1);
      ctr.bytes_sent.fetch_add(16 + body.size());
      ctr.bytes_sent_raw.fetch_add(16 + body.size());
      ctr.bytes_received.fetch_add(16 + reply_body->size());
      ctr.bytes_received_raw.fetch_add(16 + reply_body->size());
      Release(fd);
      return Status::OK();
    }
    ::close(fd);  // broken connection — retry on a fresh one
  }
  return Status::IOError("rpc to " + host_ + ":" + std::to_string(port_) +
                         " failed after retries");
}

Status PushOwnership(const std::string& host, int port,
                     const std::string& spec, uint64_t* epoch_out) {
  RpcChannel chan(host, port);
  chan.set_timeout_ms(5000);
  std::vector<char> body(spec.begin(), spec.end());
  std::vector<char> reply;
  ET_RETURN_IF_ERROR(chan.Call(kSetOwnership, body, &reply, 2));
  ByteReader r(reply.data(), reply.size());
  uint32_t code = 1;
  if (!r.Get(&code))
    return Status::IOError("truncated set-ownership reply");
  if (code != 0) {
    std::string msg;
    r.GetStr(&msg);
    return Status::Internal("shard " + host + ":" + std::to_string(port) +
                            " refused ownership map: " + msg);
  }
  uint64_t e = 0;
  r.Get(&e);
  if (epoch_out != nullptr) *epoch_out = e;
  return Status::OK();
}

// ---------------------------------------------------------------------------
// Registry server (TCP) + spec-aware registry access
// ---------------------------------------------------------------------------
RegistryServer::~RegistryServer() { Stop(); }

Status RegistryServer::Start(int port) {
  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd_ < 0) return Status::IOError("socket() failed");
  int one = 1;
  ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
      0)
    return Status::IOError("registry bind() failed on port " +
                           std::to_string(port));
  if (::listen(listen_fd_, 64) != 0)
    return Status::IOError("registry listen() failed");
  socklen_t len = sizeof(addr);
  ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
  port_ = ntohs(addr.sin_port);
  acceptor_ = std::thread([this] { AcceptLoop(); });
  ET_LOG(INFO) << "registry server on port " << port_;
  return Status::OK();
}

void RegistryServer::Stop() {
  if (stopping_.exchange(true)) return;
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
  }
  if (acceptor_.joinable()) acceptor_.join();
  std::vector<std::thread> to_join;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (size_t i = 0; i < conn_fds_.size(); ++i) {
      // finished conns already closed their fd — the number may have
      // been recycled by an unrelated descriptor
      if (!done_[i]->load()) ::shutdown(conn_fds_[i], SHUT_RDWR);
    }
    to_join = std::move(conns_);
    conns_.clear();
    done_.clear();
  }
  for (auto& t : to_join)
    if (t.joinable()) t.join();
  std::lock_guard<std::mutex> lk(mu_);
  conn_fds_.clear();
}

void RegistryServer::AcceptLoop() {
  for (;;) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) {
      if (stopping_.load()) return;
      // EMFILE/ECONNABORTED etc: back off instead of pinning a core
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
      continue;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::lock_guard<std::mutex> lk(mu_);
    // reap exited connections — heartbeats/polls open one short-lived
    // connection each, so without this the thread/fd lists grow without
    // bound and Stop() would shutdown() long-recycled fd numbers
    for (size_t i = 0; i < conns_.size();) {
      if (done_[i]->load()) {
        conns_[i].join();
        conns_.erase(conns_.begin() + i);
        done_.erase(done_.begin() + i);
        conn_fds_.erase(conn_fds_.begin() + i);
      } else {
        ++i;
      }
    }
    conn_fds_.push_back(fd);
    done_.push_back(std::make_shared<std::atomic<bool>>(false));
    auto flag = done_.back();
    conns_.emplace_back([this, fd, flag] {
      HandleConnection(fd);
      flag->store(true);  // before close: Stop() skips done fds, so a
      ::close(fd);        // recycled fd number can't be shutdown() here
    });
  }
}

void RegistryServer::HandleConnection(int fd) {
  auto now_ms = [] {
    return std::chrono::duration_cast<std::chrono::milliseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  };
  std::vector<char> body;
  uint32_t msg_type;
  while (!stopping_.load() && ReadFrame(fd, &msg_type, &body)) {
    ByteWriter w;
    if (msg_type == kRegPut) {
      std::string name(body.data(), body.size());
      {
        std::lock_guard<std::mutex> lk(mu_);
        entries_[name] = {now_ms(), ++put_seq_};
      }
      w.Put<int32_t>(0);
    } else if (msg_type == kRegRemove) {
      std::string name(body.data(), body.size());
      {
        std::lock_guard<std::mutex> lk(mu_);
        entries_.erase(name);
      }
      w.Put<int32_t>(0);
    } else if (msg_type == kRegList) {
      std::lock_guard<std::mutex> lk(mu_);
      w.Put<uint32_t>(kRegListVersion);
      w.Put<uint32_t>(static_cast<uint32_t>(entries_.size()));
      int64_t now = now_ms();
      for (const auto& kv : entries_) {
        w.PutStr(kv.first);
        w.Put<int64_t>(now - kv.second.first);
        w.Put<uint64_t>(kv.second.second);
      }
    } else {
      w.Put<int32_t>(-1);
    }
    if (!WriteFrame(fd, msg_type, w.buffer().data(), w.buffer().size()))
      break;
  }
  // NO close here: the connection-thread wrapper in AcceptLoop owns the
  // close (after setting the done flag, so Stop() never shutdown()s a
  // recycled fd number). Closing here too double-closed every registry
  // connection — and when another thread had already reused the fd
  // number, the second close killed an UNRELATED live socket, which is
  // exactly the concurrent-heartbeat flake (ECONNRESET/EBADF/EISCONN on
  // fresh registry channels) the native registry test kept tripping.
}

// ---------------------------------------------------------------------------
// Discovery (spec-aware: directory registries and tcp: registry servers)
// ---------------------------------------------------------------------------
namespace {
bool SplitTcpSpec(const std::string& spec, std::string* host, int* port) {
  if (spec.rfind("tcp:", 0) != 0) return false;
  auto rest = spec.substr(4);
  auto pos = rest.rfind(':');
  if (pos == std::string::npos) return false;
  *host = rest.substr(0, pos);
  *port = std::atoi(rest.substr(pos + 1).c_str());
  return true;
}

// "shard_<i>__<host>_<port>" -> parts; false for foreign entries.
bool ParseShardEntry(const std::string& name, int* idx, std::string* host,
                     int* port) {
  if (name.rfind("shard_", 0) != 0) return false;
  auto sep = name.find("__");
  if (sep == std::string::npos) return false;
  *idx = std::atoi(name.substr(6, sep - 6).c_str());
  auto last = name.rfind('_');
  if (last == std::string::npos || last <= sep + 1) return false;
  *host = name.substr(sep + 2, last - sep - 2);
  *port = std::atoi(name.substr(last + 1).c_str());
  return *idx >= 0;
}

std::string DirOfSpec(const std::string& spec) {
  return spec.rfind("dir:", 0) == 0 ? spec.substr(4) : spec;
}
}  // namespace

Status RegistryPutEntry(const std::string& spec, const std::string& name) {
  std::string host;
  int port;
  if (SplitTcpSpec(spec, &host, &port)) {
    RpcChannel ch(host, port);
    ch.set_timeout_ms(3000);
    std::vector<char> body(name.begin(), name.end()), reply;
    // 2 bounded attempts: heartbeats repeat anyway; a long retry ladder
    // here would stall the heartbeat thread (and Stop(), which joins
    // it) behind an unreachable registry host
    return ch.Call(kRegPut, body, &reply, /*max_retries=*/2);
  }
  return WriteStringToFile(DirOfSpec(spec) + "/" + name, "", 0);
}

Status RegistryRemoveEntry(const std::string& spec,
                           const std::string& name) {
  std::string host;
  int port;
  if (SplitTcpSpec(spec, &host, &port)) {
    RpcChannel ch(host, port);
    ch.set_timeout_ms(3000);
    std::vector<char> body(name.begin(), name.end()), reply;
    // best-effort single bounded attempt: shutdown must never block on
    // a partitioned registry (the entry just goes stale instead)
    return ch.Call(kRegRemove, body, &reply, /*max_retries=*/1);
  }
  std::remove((DirOfSpec(spec) + "/" + name).c_str());
  return Status::OK();
}

Status ScanRegistrySpec(const std::string& spec,
                        std::map<int, std::pair<std::string, int>>* found,
                        std::map<int, int64_t>* ages_ms) {
  std::string rhost;
  int rport;
  if (SplitTcpSpec(spec, &rhost, &rport)) {
    RpcChannel ch(rhost, rport);
    ch.set_timeout_ms(3000);
    std::vector<char> reply;
    ET_RETURN_IF_ERROR(ch.Call(kRegList, {}, &reply, /*max_retries=*/2));
    ByteReader r(reply.data(), reply.size());
    uint32_t ver, n;
    if (!r.Get(&ver)) return Status::IOError("truncated registry listing");
    if (ver != kRegListVersion)
      return Status::IOError(
          "registry protocol version mismatch: server speaks v" +
          std::to_string(ver) + ", this client v" +
          std::to_string(kRegListVersion) +
          " — upgrade the older binary");
    if (!r.Get(&n)) return Status::IOError("truncated registry listing");
    std::map<int, uint64_t> best_seq;
    for (uint32_t i = 0; i < n; ++i) {
      std::string name;
      int64_t age;
      uint64_t seq;
      if (!r.GetStr(&name) || !r.Get(&age) || !r.Get(&seq))
        return Status::IOError("truncated registry entry");
      int idx, port;
      std::string host;
      if (!ParseShardEntry(name, &idx, &host, &port)) continue;
      // duplicate indices (a crashed server's entry + its replacement):
      // the LATEST registration wins — the server's put sequence is
      // exact insertion recency (ms ages tie within a clock tick)
      auto it = best_seq.find(idx);
      if (it != best_seq.end() && it->second >= seq) continue;
      best_seq[idx] = seq;
      (*found)[idx] = {host, port};
      if (ages_ms != nullptr) (*ages_ms)[idx] = age;
    }
    return Status::OK();
  }
  // File mode: one directory scan; duplicate indices keep the last entry
  // in name order (a stale file left by a crashed server plus its
  // replacement resolves deterministically). Age = wall now - mtime.
  std::string dir = DirOfSpec(spec);
  DIR* d = ::opendir(dir.c_str());
  if (d == nullptr)
    return Status::IOError("cannot open registry dir " + dir);
  dirent* e;
  int64_t now = static_cast<int64_t>(::time(nullptr)) * 1000;
  std::map<int, int64_t> best_age;
  while ((e = ::readdir(d)) != nullptr) {
    int idx, port;
    std::string host;
    if (!ParseShardEntry(e->d_name, &idx, &host, &port)) continue;
    struct stat st;
    std::string path = dir + "/" + e->d_name;
    int64_t age = ::stat(path.c_str(), &st) == 0
                      ? now - static_cast<int64_t>(st.st_mtime) * 1000
                      : (1LL << 60);
    // duplicate indices: youngest mtime wins (see tcp path)
    auto it = best_age.find(idx);
    if (it != best_age.end() && it->second <= age) continue;
    best_age[idx] = age;
    (*found)[idx] = {host, port};
    if (ages_ms != nullptr) (*ages_ms)[idx] = age;
  }
  ::closedir(d);
  return Status::OK();
}

// ---------------------------------------------------------------------------
// ServerMonitor
// ---------------------------------------------------------------------------
ServerMonitor::ServerMonitor(std::string registry_dir, int interval_ms,
                             int stale_ms)
    : dir_(std::move(registry_dir)),
      interval_ms_(interval_ms),
      stale_ms_(stale_ms) {}

ServerMonitor::~ServerMonitor() { Stop(); }

void ServerMonitor::Start(Callback cb) {
  cb_ = std::move(cb);
  thread_ = std::thread([this] { Loop(); });
}

void ServerMonitor::Stop() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (stopping_) return;
    stopping_ = true;
  }
  cv_.notify_all();
  if (thread_.joinable()) thread_.join();
}

void ServerMonitor::Loop() {
  for (;;) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      if (cv_.wait_for(lk, std::chrono::milliseconds(interval_ms_),
                       [this] { return stopping_; }))
        return;
    }
    std::map<int, std::pair<std::string, int>> found;
    std::map<int, int64_t> ages;
    if (!ScanRegistrySpec(dir_, &found, &ages).ok()) continue;
    // stale registrations count as down (heartbeat stopped)
    for (auto it = found.begin(); it != found.end();) {
      if (stale_ms_ > 0 && ages[it->first] > stale_ms_)
        it = found.erase(it);
      else
        ++it;
    }
    // diff against last view → up/down callbacks
    for (const auto& kv : found) {
      auto prev = live_.find(kv.first);
      if (prev == live_.end() || prev->second != kv.second)
        cb_(kv.first, kv.second.first, kv.second.second, true);
    }
    for (const auto& kv : live_) {
      if (found.find(kv.first) == found.end())
        cb_(kv.first, kv.second.first, kv.second.second, false);
    }
    live_ = std::move(found);
  }
}

Status DiscoverFromRegistry(const std::string& registry_dir, int shard_num,
                            ShardEndpoints* out) {
  std::map<int, std::pair<std::string, int>> found;
  ET_RETURN_IF_ERROR(ScanRegistrySpec(registry_dir, &found, nullptr));
  out->endpoints.assign(shard_num, {"", 0});
  int unique = 0;
  for (const auto& kv : found) {
    if (kv.first < shard_num) {
      out->endpoints[kv.first] = kv.second;
      ++unique;
    }
  }
  if (unique < shard_num)
    return Status::NotFound("registry has " + std::to_string(unique) + "/" +
                            std::to_string(shard_num) + " shards");
  return Status::OK();
}

Status DiscoverFromRegistryAuto(const std::string& registry_dir,
                                ShardEndpoints* out) {
  std::map<int, std::pair<std::string, int>> found;
  ET_RETURN_IF_ERROR(ScanRegistrySpec(registry_dir, &found, nullptr));
  if (found.empty())
    return Status::NotFound("no shard files in registry " + registry_dir);
  int shard_num = found.rbegin()->first + 1;
  if (static_cast<int>(found.size()) != shard_num)
    return Status::NotFound("registry " + registry_dir + " has " +
                            std::to_string(found.size()) + " shards but max "
                            "index implies " + std::to_string(shard_num));
  out->endpoints.assign(shard_num, {"", 0});
  for (const auto& kv : found) out->endpoints[kv.first] = kv.second;
  return Status::OK();
}

Status DiscoverFromSpec(const std::string& spec, ShardEndpoints* out) {
  out->endpoints.clear();
  std::stringstream ss(spec);
  std::string item;
  while (std::getline(ss, item, ',')) {
    if (item.empty()) continue;
    auto pos = item.rfind(':');
    if (pos == std::string::npos)
      return Status::InvalidArgument("bad host:port: " + item);
    out->endpoints.emplace_back(item.substr(0, pos),
                                std::atoi(item.substr(pos + 1).c_str()));
  }
  if (out->endpoints.empty())
    return Status::InvalidArgument("empty endpoint spec");
  return Status::OK();
}

// ---------------------------------------------------------------------------
// ClientManager
// ---------------------------------------------------------------------------
ClientManager::~ClientManager() {
  if (monitor_) monitor_->Stop();
  // block until no pool-scheduled RefreshMeta can touch us anymore
  std::lock_guard<std::mutex> lk(life_->first);
  life_->second = true;
}

std::shared_ptr<RpcChannel> ClientManager::Channel(int shard) const {
  std::lock_guard<std::mutex> lk(chan_mu_);
  return channels_[shard];
}

void ClientManager::WatchRegistry(const std::string& dir, int interval_ms,
                                  int stale_ms) {
  monitor_ = std::make_unique<ServerMonitor>(dir, interval_ms, stale_ms);
  monitor_->Start([this](int shard, const std::string& host, int port,
                         bool up) {
    if (shard < 0 || shard >= shard_num()) return;
    if (up) {
      std::shared_ptr<RpcChannel> fresh;
      {
        std::lock_guard<std::mutex> lk(chan_mu_);
        if (channels_[shard]->host() != host ||
            channels_[shard]->port() != port) {
          ET_LOG_INFO << "shard " << shard << " re-resolved to " << host
                      << ":" << port;
          channels_[shard] = std::make_shared<RpcChannel>(host, port);
          // a replacement channel re-reads the transport config — this
          // is also how a v1-fallback channel regains mux after the
          // shard restarts on a v2 binary
          if (GlobalRpcConfig().mux) channels_[shard]->set_mux(true);
          channels_[shard]->set_epoch_sink(&observed_epoch_);
          fresh = channels_[shard];
        }
      }
      if (fresh) {
        // off the monitor thread: keep the registry poll cadence steady.
        // The RPC runs before taking the life lock so a slow shard can't
        // stall ~ClientManager for a whole call timeout.
        auto life = life_;
        ClientThreadPool()->Schedule([this, life, shard, fresh] {
          std::vector<char> body, reply;
          Status s = fresh->Call(kMeta, body, &reply);
          std::lock_guard<std::mutex> lk(life->first);
          if (life->second) return;  // manager destroyed meanwhile
          RefreshMeta(shard, s, reply);
        });
      }
    } else {
      ET_LOG_INFO << "shard " << shard << " registration lost (" << host
                  << ":" << port << ")";
      // keep the channel: in-flight calls fail+retry and recover when the
      // shard re-registers (the up path swaps in the new endpoint)
    }
  });
}

Status ClientManager::Init(const ShardEndpoints& eps) {
  channels_.clear();
  for (const auto& ep : eps.endpoints) {
    channels_.push_back(std::make_shared<RpcChannel>(ep.first, ep.second));
    // graph-service channels opt into the multiplexed transport from the
    // process-global config; registry channels (RegistryPutEntry & co.
    // build their own short-lived RpcChannel) always speak v1
    if (GlobalRpcConfig().mux) channels_.back()->set_mux(true);
    channels_.back()->set_epoch_sink(&observed_epoch_);
  }
  // per-shard routing signals: request counters (hot-shard detection),
  // inflight + reply-latency EWMA (PickOwners p2c / hedge steering)
  stats_shards_ = static_cast<int>(channels_.size());
  shard_reqs_ = std::make_unique<std::atomic<uint64_t>[]>(stats_shards_);
  shard_rows_ = std::make_unique<std::atomic<uint64_t>[]>(stats_shards_);
  shard_inflight_ = std::make_unique<std::atomic<int64_t>[]>(stats_shards_);
  shard_ewma_us_ = std::make_unique<std::atomic<int64_t>[]>(stats_shards_);
  for (int s = 0; s < stats_shards_; ++s) {
    shard_reqs_[s].store(0);
    shard_rows_[s].store(0);
    shard_inflight_[s].store(0);
    shard_ewma_us_[s].store(0);
  }
  std::vector<ShardMeta> metas(channels_.size());
  for (size_t s = 0; s < channels_.size(); ++s) {
    std::vector<char> body, reply;
    ET_RETURN_IF_ERROR(channels_[s]->Call(kMeta, body, &reply));
    ByteReader r(reply.data(), reply.size());
    ET_RETURN_IF_ERROR(DecodeShardMeta(&r, &metas[s]));
  }
  if (!metas.empty()) {
    graph_meta_ = metas[0].graph_meta;
    partition_num_ = metas[0].partition_num;
  }
  std::lock_guard<std::mutex> lk(meta_mu_);  // vs in-flight RefreshMeta
  metas_ = std::move(metas);
  return Status::OK();
}

void ClientManager::RefreshMeta(int shard, const Status& call_status,
                                const std::vector<char>& reply) {
  Status s = call_status;
  ShardMeta m;
  if (s.ok()) {
    ByteReader r(reply.data(), reply.size());
    s = DecodeShardMeta(&r, &m);
  }
  if (!s.ok()) {
    ET_LOG_INFO << "shard " << shard
                << " meta refresh after failover failed: " << s.message();
    return;
  }
  std::lock_guard<std::mutex> lk(meta_mu_);
  if (shard < static_cast<int>(metas_.size())) metas_[shard] = std::move(m);
}

float ClientManager::NodeWeight(int shard, int type) const {
  std::lock_guard<std::mutex> lk(meta_mu_);
  const auto& w = metas_[shard].node_type_wsum;
  if (type >= 0)
    return type < static_cast<int>(w.size()) ? w[type] : 0.f;
  float s = 0;
  for (float f : w) s += f;
  return s;
}

float ClientManager::GraphLabelWeight(int shard, bool owned) const {
  std::lock_guard<std::mutex> lk(meta_mu_);
  return static_cast<float>(owned ? metas_[shard].owned_graph_label_count
                                  : metas_[shard].graph_label_count);
}

float ClientManager::EdgeWeight(int shard, int type) const {
  std::lock_guard<std::mutex> lk(meta_mu_);
  const auto& w = metas_[shard].edge_type_wsum;
  if (type >= 0)
    return type < static_cast<int>(w.size()) ? w[type] : 0.f;
  float s = 0;
  for (float f : w) s += f;
  return s;
}

Status ClientManager::SetOwnership(std::shared_ptr<const OwnershipMap> m) {
  if (m == nullptr || m->map_epoch == 0)
    return Status::InvalidArgument("ownership map must have epoch > 0");
  if (m->shard_num > shard_num())
    return Status::InvalidArgument(
        "ownership map references shard " + std::to_string(m->shard_num - 1) +
        " but this client has " + std::to_string(shard_num()) +
        " channel(s); rebuild the client against the grown fleet first");
  std::lock_guard<std::mutex> lk(omap_mu_);
  if (omap_ != nullptr && m->map_epoch < omap_->map_epoch)
    return Status::InvalidArgument(
        "refusing ownership map epoch " + std::to_string(m->map_epoch) +
        ": client already at epoch " + std::to_string(omap_->map_epoch));
  // precompute each shard's hedge alternative (a covering owner) once
  // per map install — Execute reads it per call
  hedge_alt_.assign(shard_num(), -1);
  for (int s = 0; s < shard_num(); ++s)
    for (int a = 0; a < m->shard_num && a < shard_num(); ++a)
      if (m->Covers(a, s)) {
        hedge_alt_[s] = a;
        break;
      }
  omap_ = std::move(m);
  // runs started after this stamp the new epoch (QueryEnv captures it)
  map_epoch_.store(omap_->map_epoch);
  return Status::OK();
}

bool ClientManager::PickOwners(std::vector<int>* out) const {
  std::shared_ptr<const OwnershipMap> m;
  {
    std::lock_guard<std::mutex> lk(omap_mu_);
    m = omap_;
  }
  if (m == nullptr || m->map_epoch == 0) return false;
  out->resize(m->partition_num);
  auto& rng = ThreadLocalRng();
  for (int p = 0; p < m->partition_num; ++p) {
    const auto& os = m->owners[p];
    if (os.size() == 1) {
      (*out)[p] = os[0];
      continue;
    }
    // p2c over the owner list: two random distinct candidates, lower
    // (inflight, EWMA latency) wins — load first (a hot owner
    // accumulates inflight), latency as the tie-breaker
    size_t ia = rng.NextUInt(os.size());
    size_t ib = rng.NextUInt(os.size() - 1);
    if (ib >= ia) ++ib;
    int a = os[ia];
    int b = os[ib];
    auto load = [&](int s, int64_t* infl, int64_t* ewma) {
      if (s >= 0 && s < stats_shards_) {
        *infl = shard_inflight_[s].load();
        *ewma = shard_ewma_us_[s].load();
      } else {
        *infl = 0;
        *ewma = 0;
      }
    };
    int64_t la = 0, ea = 0, lb = 0, eb = 0;
    load(a, &la, &ea);
    load(b, &lb, &eb);
    (*out)[p] = la != lb ? (la < lb ? a : b) : (ea <= eb ? a : b);
  }
  return true;
}

int ClientManager::ShardTraffic(uint64_t* reqs, uint64_t* rows,
                                int cap) const {
  int n = std::min(cap, stats_shards_);
  for (int s = 0; s < n; ++s) {
    if (reqs != nullptr) reqs[s] = shard_reqs_[s].load();
    if (rows != nullptr) rows[s] = shard_rows_[s].load();
  }
  return n;
}

int ClientManager::HedgeAltFor(int shard) const {
  std::lock_guard<std::mutex> lk(omap_mu_);
  if (shard < 0 || shard >= static_cast<int>(hedge_alt_.size())) return -1;
  return hedge_alt_[shard];
}

// Live replica-hedge leg threads (process-global): the race legs are
// dedicated detached threads, and a leg against a stalled shard with
// no deadline can block until its connection dies — a closed-loop
// retry storm must not accumulate threads without bound. At the cap,
// Execute degrades to the plain (pre-hedging) blocking call.
static std::atomic<int> g_replica_hedge_legs{0};
constexpr int kMaxReplicaHedgeLegs = 128;

Status ClientManager::CallExecWire(const std::shared_ptr<RpcChannel>& chan,
                                   const ExecWire& wire,
                                   std::vector<char>* reply,
                                   int64_t deadline_abs_us,
                                   uint64_t map_epoch, WireTrace trace) {
  // prepared mode: the channel owns registration + miss-fallback;
  // every leg of one logical request (retries, replica-hedge legs)
  // stamps the SAME content-hash plan id
  if (wire.plan_id != 0)
    return chan->CallExecutePrepared(wire.plan->buffer(), wire.plan_id,
                                     wire.feeds->buffer(), reply,
                                     /*max_retries=*/0, deadline_abs_us,
                                     map_epoch, trace);
  return chan->Call(kExecute, wire.full->buffer(), reply,
                    /*max_retries=*/0, deadline_abs_us, map_epoch, trace);
}

Status ClientManager::ReplicaHedgedExecute(
    int shard, int alt, ExecWire wire,
    std::vector<char>* reply, int64_t hedge_us, int64_t deadline_abs_us,
    uint64_t map_epoch, WireTrace trace) {
  auto& ctr = GlobalRpcCounters();
  // Two blocking legs race on their own detached threads; this thread
  // coordinates on the shared state. Dedicated threads (not the client
  // pool): a coordinator parked on a fixed-size pool while its legs
  // queue behind other coordinators would deadlock it. The loser's
  // blocking Call cannot be cancelled — it drains on its thread and
  // its reply is discarded at the race (counted replica_hedge_wasted).
  // The channel snapshot keeps the endpoint alive past a concurrent
  // monitor swap; `race` keeps the state alive past this return.
  struct Race {
    std::mutex mu;
    std::condition_variable cv;
    int done = 0;
    int winner = -1;
    Status st[2] = {Status::OK(), Status::OK()};
    std::vector<char> reply[2];
  };
  auto race = std::make_shared<Race>();
  auto fire = [this, wire, race, deadline_abs_us, map_epoch,
               trace](int leg_idx, int target) {
    g_replica_hedge_legs.fetch_add(1);
    auto chan = Channel(target);
    std::thread([chan, wire, race, deadline_abs_us, map_epoch, trace,
                 leg_idx] {
      std::vector<char> rep;
      Status s = CallExecWire(chan, wire, &rep, deadline_abs_us,
                              map_epoch, trace);
      {
        std::lock_guard<std::mutex> lk(race->mu);
        race->st[leg_idx] = s;
        race->reply[leg_idx] = std::move(rep);
        ++race->done;
        if (s.ok() && race->winner < 0) race->winner = leg_idx;
        race->cv.notify_all();
      }
      g_replica_hedge_legs.fetch_sub(1);
    }).detach();
  };
  fire(0, shard);
  int fired = 1;
  {
    std::unique_lock<std::mutex> lk(race->mu);
    race->cv.wait_for(lk, std::chrono::microseconds(hedge_us),
                      [&] { return race->done >= 1; });
    if (race->winner < 0 && race->done == 0) {
      // primary is straggling: race the covering replica
      lk.unlock();
      ctr.replica_hedge_fired.fetch_add(1);
      if (stats_shards_ > alt) shard_reqs_[alt].fetch_add(1);
      fire(1, alt);
      fired = 2;
      lk.lock();
    }
    // first OK reply wins; only fail once EVERY fired leg failed
    race->cv.wait(lk, [&] {
      return race->winner >= 0 || race->done >= fired;
    });
    if (race->winner < 0) return race->st[0];
    if (fired == 2) {
      // the losing leg is wasted work whether it is still in flight
      // (abandoned; drains on its thread, reply discarded) or raced in
      // and was discarded here — a leg that FAILED counts failed, not
      // wasted (the PR-11 hedge accounting convention)
      const int loser = 1 - race->winner;
      if (race->done < fired || race->st[loser].ok())
        ctr.replica_hedge_wasted.fetch_add(1);
    }
    if (race->winner == 1) ctr.replica_hedge_won.fetch_add(1);
    *reply = std::move(race->reply[race->winner]);
  }
  return Status::OK();
}

Status ClientManager::Execute(int shard, const ExecuteRequest& req,
                              ExecuteReply* rep, int64_t deadline_abs_us,
                              uint64_t map_epoch, WireTrace trace) {
  if (shard < 0 || shard >= shard_num())
    return Status::InvalidArgument("bad shard index");
  ExecWire wire;
  if (GlobalRpcConfig().prepared.load()) {
    // split encoding: the plan half (inner DAG + output names — the
    // part a training loop repeats thousands of times) ships at most
    // once per connection, the feeds ship per request. The content
    // hash is computed fresh from the encoded bytes every call, so a
    // cached server plan can never diverge from what this request
    // means.
    wire.plan = std::make_shared<ByteWriter>();
    EncodeExecutePlan(req, wire.plan.get());
    wire.feeds = std::make_shared<ByteWriter>();
    EncodeExecuteFeeds(req, wire.feeds.get());
    wire.plan_id = PlanContentHash(wire.plan->buffer().data(),
                                   wire.plan->buffer().size());
  } else {
    wire.full = std::make_shared<ByteWriter>();
    EncodeExecuteRequest(req, wire.full.get());
  }
  std::vector<char> reply;
  const int64_t t0 = SteadyNowUs();
  if (shard < stats_shards_) {
    shard_reqs_[shard].fetch_add(1);
    shard_inflight_[shard].fetch_add(1);
  }
  Status s;
  const int64_t hedge_us = GlobalRpcConfig().hedge_delay_us.load();
  const int alt = (hedge_us > 0 &&
                   GlobalRpcConfig().hedge_replicas.load())
                      ? HedgeAltFor(shard)
                      : -1;
  if (alt >= 0 &&
      g_replica_hedge_legs.load() + 2 <= kMaxReplicaHedgeLegs) {
    s = ReplicaHedgedExecute(shard, alt, wire, &reply, hedge_us,
                             deadline_abs_us, map_epoch, trace);
  } else if (alt >= 0) {
    // At the leg cap. The cap fills precisely when legs pile up on a
    // STALLED primary (a healthy fleet completes legs as fast as they
    // spawn), so degrading to a plain blocking call on `shard` would
    // park this caller behind the very stall hedging exists to escape
    // — route the whole call at the covering ALTERNATIVE instead (it
    // owns every partition `shard` does, so the answer is identical).
    if (shard_reqs_ != nullptr && alt < stats_shards_)
      shard_reqs_[alt].fetch_add(1);
    s = CallExecWire(Channel(alt), wire, &reply, deadline_abs_us,
                     map_epoch, trace);
  } else {
    // snapshot: the monitor may swap the channel concurrently
    s = CallExecWire(Channel(shard), wire, &reply, deadline_abs_us,
                     map_epoch, trace);
  }
  if (shard < stats_shards_) {
    shard_inflight_[shard].fetch_sub(1);
    if (s.ok()) {
      // per-shard reply-latency EWMA: new = (7*old + sample) / 8 — the
      // PickOwners p2c signal (same smoothing as the mux-slot EWMA)
      int64_t sample = SteadyNowUs() - t0;
      int64_t old = shard_ewma_us_[shard].load();
      shard_ewma_us_[shard].store(old == 0 ? sample
                                           : (7 * old + sample) / 8);
    }
  }
  ET_RETURN_IF_ERROR(s);
  ByteReader r(reply.data(), reply.size());
  ET_RETURN_IF_ERROR(DecodeExecuteReply(&r, rep));
  return rep->status;
}

Status ClientManager::ApplyDelta(
    const NodeId* node_ids, const int32_t* node_types,
    const float* node_weights, size_t n_nodes, const NodeId* edge_src,
    const NodeId* edge_dst, const int32_t* edge_types,
    const float* edge_weights, size_t n_edges, uint64_t* new_epoch) {
  // normalize optional columns once so every shard sees identical bytes
  std::vector<int32_t> nt_buf, et_buf;
  std::vector<float> nw_buf, ew_buf;
  if (node_types == nullptr) nt_buf.assign(n_nodes, 0);
  if (node_weights == nullptr) nw_buf.assign(n_nodes, 1.0f);
  if (edge_types == nullptr) et_buf.assign(n_edges, 0);
  if (edge_weights == nullptr) ew_buf.assign(n_edges, 1.0f);
  ByteWriter w;
  w.Put<uint64_t>(n_nodes);
  if (n_nodes > 0) {
    w.PutRaw(node_ids, n_nodes * sizeof(NodeId));
    w.PutRaw(node_types ? node_types : nt_buf.data(),
             n_nodes * sizeof(int32_t));
    w.PutRaw(node_weights ? node_weights : nw_buf.data(),
             n_nodes * sizeof(float));
  }
  w.Put<uint64_t>(n_edges);
  if (n_edges > 0) {
    w.PutRaw(edge_src, n_edges * sizeof(NodeId));
    w.PutRaw(edge_dst, n_edges * sizeof(NodeId));
    w.PutRaw(edge_types ? edge_types : et_buf.data(),
             n_edges * sizeof(int32_t));
    w.PutRaw(edge_weights ? edge_weights : ew_buf.data(),
             n_edges * sizeof(float));
  }
  // Concurrent per-shard fan-out (pipeline thread-pool pattern): every
  // shard rebuilds its snapshot in parallel, so broadcast wall clock is
  // the SLOWEST shard's rebuild instead of the sum — and the mixed-
  // epoch window (some shards post-delta, some pre) shrinks with it.
  // Per-shard retry semantics unchanged: each Channel::Call keeps its
  // own in-channel retries, a re-issue after any failure is idempotent
  // (last-write-wins rows), and EVERY shard is attempted so a single
  // dead shard cannot leave later shards unapplied (the anti-entropy
  // catch-up on its restart closes its own gap).
  const int n = shard_num();
  uint64_t max_epoch = 0;
  std::mutex mu;
  auto apply_one = [&](int s) -> Status {
    std::vector<char> reply;
    ET_RETURN_IF_ERROR(Channel(s)->Call(kApplyDelta, w.buffer(), &reply));
    ByteReader r(reply.data(), reply.size());
    uint32_t code = 1;
    if (!r.Get(&code)) return Status::IOError("truncated delta reply");
    if (code != 0) {
      std::string msg;
      r.GetStr(&msg);
      return Status::Internal("shard " + std::to_string(s) +
                              " refused delta: " + msg);
    }
    uint64_t epoch = 0;
    if (!r.Get(&epoch)) return Status::IOError("truncated delta reply");
    {
      std::lock_guard<std::mutex> lk(mu);
      max_epoch = std::max(max_epoch, epoch);
    }
    // the shard's weight sums / counts changed — refresh its routing
    // meta so proportional SAMPLE_SPLIT reflects the post-delta graph
    std::vector<char> mreply;
    Status ms = Channel(s)->Call(kMeta, {}, &mreply);
    RefreshMeta(s, ms, mreply);
    return Status::OK();
  };
  std::vector<Status> statuses(n);
  if (n == 1) {
    statuses[0] = apply_one(0);
  } else {
    // blocking calls ride the CLIENT pool (never the shared executor —
    // see ClientThreadPool's comment); the launching thread parks on a
    // plain latch until every shard answered or failed
    std::condition_variable cv;
    int pending = n;
    for (int s = 0; s < n; ++s) {
      ClientThreadPool()->Schedule([&, s] {
        Status st = apply_one(s);
        std::lock_guard<std::mutex> lk(mu);
        statuses[s] = st;
        if (--pending == 0) cv.notify_all();
      });
    }
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return pending == 0; });
  }
  MaxUpdateEpoch(&observed_epoch_, max_epoch);
  if (new_epoch != nullptr) *new_epoch = max_epoch;
  for (int s = 0; s < n; ++s)
    if (!statuses[s].ok()) return statuses[s];
  return Status::OK();
}

Status ClientManager::DeltaSince(uint64_t from, uint64_t* epoch,
                                 bool* covered, std::vector<NodeId>* ids) {
  ByteWriter w;
  w.Put<uint64_t>(from);
  uint64_t max_epoch = 0;
  bool all_covered = true;
  ids->clear();
  for (int s = 0; s < shard_num(); ++s) {
    std::vector<char> reply;
    ET_RETURN_IF_ERROR(Channel(s)->Call(kGetDelta, w.buffer(), &reply));
    ByteReader r(reply.data(), reply.size());
    uint32_t code = 1;
    uint64_t sh_epoch = 0, n = 0;
    uint8_t cov = 0;
    if (!r.Get(&code) || code != 0 || !r.Get(&sh_epoch) || !r.Get(&cov) ||
        !r.Get(&n) || n > r.remaining() / sizeof(NodeId))
      return Status::IOError("bad get-delta reply from shard " +
                             std::to_string(s));
    size_t base = ids->size();
    ids->resize(base + n);
    if (n > 0 && !r.GetRaw(ids->data() + base, n * sizeof(NodeId)))
      return Status::IOError("truncated get-delta ids from shard " +
                             std::to_string(s));
    max_epoch = std::max(max_epoch, sh_epoch);
    all_covered = all_covered && cov != 0;
  }
  std::sort(ids->begin(), ids->end());
  ids->erase(std::unique(ids->begin(), ids->end()), ids->end());
  MaxUpdateEpoch(&observed_epoch_, max_epoch);
  *epoch = max_epoch;
  *covered = all_covered;
  if (!all_covered) ids->clear();
  return Status::OK();
}

void ClientManager::ExecuteAsync(
    int shard, ExecuteRequest req,
    std::function<void(Status, ExecuteReply)> done, int64_t deadline_abs_us,
    uint64_t map_epoch, WireTrace trace) {
  // the Call() below blocks until the shard replies — it must not occupy
  // an executor thread (see ClientThreadPool comment in threadpool.h)
  ClientThreadPool()->Schedule(
      [this, shard, req = std::move(req), done = std::move(done),
       deadline_abs_us, map_epoch, trace] {
        ExecuteReply rep;
        Status s = Execute(shard, req, &rep, deadline_abs_us, map_epoch,
                           trace);
        done(s, std::move(rep));
      });
}

}  // namespace et
