// Composite batch sampling ops over the graph store.
//
// These are the engine-side equivalents of the reference's one-round-trip
// multi-hop ops (tf_euler/kernels/sample_fanout_op.cc:36-48 chained
// .sampleNB GQL, random_walk_op.cc:34-172 node2vec). Instead of compiling a
// query DAG per batch, the rebuild exposes them as direct C++ batch loops
// over the SoA store — the query layer (euler_tpu.gql) lowers to these same
// entry points. All outputs are fixed-shape and default-padded so the
// Python side can hand them to jax without ragged handling.
#ifndef EULER_TPU_OPS_H_
#define EULER_TPU_OPS_H_

#include <cstdint>
#include <vector>

#include "graph.h"

namespace et {

// Multi-hop neighbor expansion. Layer i samples counts[i] neighbors for
// every node of layer i-1 (layer -1 = roots). Edge types may differ per hop:
// hop i uses edge_types[et_offsets[i] : et_offsets[i+1]] (empty → all).
// out_ids/out_w/out_t are per-hop buffers sized n_roots * prod(counts[:i+1]).
void SampleFanout(const Graph& g, const NodeId* roots, size_t n_roots,
                  const int32_t* counts, size_t n_hops,
                  const int32_t* edge_types, const int64_t* et_offsets,
                  NodeId default_id, Pcg32* rng,
                  const std::vector<NodeId*>& out_ids,
                  const std::vector<float*>& out_w,
                  const std::vector<int32_t*>& out_t);

// node2vec-biased random walk. out is [n_roots, walk_len+1] row-major,
// column 0 = roots. p = return parameter, q = in-out parameter
// (p = q = 1 → plain weighted walk). Dead ends pad with default_id.
void RandomWalk(const Graph& g, const NodeId* roots, size_t n_roots,
                size_t walk_len, float p, float q, NodeId default_id,
                const int32_t* edge_types, size_t n_types, Pcg32* rng,
                NodeId* out);

// Layerwise (LADIES-style) sampling: one shared pool of m candidate
// neighbors per layer for the whole batch, sampled ∝ sum of edge weights
// from the current layer (importance sampling over the frontier's union
// neighborhood). Parity: reference API_SAMPLE_L / sampleLNB
// (euler/core/kernels/sample_layer_op.cc:74). Returns the pool (size m,
// padded with default_id) for each layer.
// weight_func transforms the accumulated per-unique-neighbor weight
// before the draw: kIdentity (default) or kSqrt (the reference's
// weight_func="sqrt", local_sample_layer_op.cc:94 — dampens hub mass).
enum class LayerWeightFunc { kIdentity = 0, kSqrt = 1 };

// layer_wsums (optional): receives each layer's total candidate mass
// (sum of per-unique accumulated weights AFTER weight_func) — the
// distributed POOL_MERGE weighs shards by it so the merged pool keeps
// the global weighted-with-replacement distribution.
void SampleLayerwise(const Graph& g, const NodeId* roots, size_t n_roots,
                     const int32_t* layer_sizes, size_t n_layers,
                     const int32_t* edge_types, size_t n_types,
                     NodeId default_id, Pcg32* rng,
                     const std::vector<NodeId*>& out_layers,
                     LayerWeightFunc weight_func = LayerWeightFunc::kIdentity,
                     std::vector<float>* layer_wsums = nullptr);

}  // namespace et

#endif  // EULER_TPU_OPS_H_
