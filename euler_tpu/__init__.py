"""euler_tpu: a TPU-native graph neural network training framework.

Capabilities of Alibaba Euler 2.0 (reference: renyi533/euler), rebuilt
TPU-first: a native C++ columnar graph engine on the host feeding
jit-compiled JAX/XLA SPMD training over a jax.sharding.Mesh.

Layering (bottom → top), mirroring SURVEY.md §1:
  core/        native engine (C++ → libeuler_core.so) + ctypes loader
  graph/       numpy-facing GraphEngine / GraphBuilder (embedded mode)
  ops/         host sampling ops + JAX message-passing (gather/scatter)
  dataflow/    mini-batch subgraph builders (sage/gcn/layerwise/...)
  convolution/ message-passing conv zoo (flax)
  mp_utils/    model assembly (BaseGNNNet, supervised/unsupervised)
  graph_pool/  graph-level readouts
  utils/       layers, encoders, aggregators, metrics, optimizers
  solution/    composable industrial pipeline
  estimator/   training drivers (train/evaluate/infer, orbax checkpoints)
  dataset/     dataset registry (synthetic + on-disk loaders)
  parallel/    Mesh/pjit sharding, sharded embedding tables
  tools/       data prep (json → binary partitions), knn export
  obs/         metrics registry + tracing + /metrics exposition
               (stdlib-only; wired through graph client, input
               pipeline, train loop, and bench)
  serving/     online inference: export bundles (params + embedding
               matrix + IVF index, checksummed manifest), a framed-TCP
               embedding/KNN/score server with dynamic micro-batching
               + load shedding, and a registry-discovered failover
               client
"""

__version__ = "0.1.0"

from euler_tpu.graph import GraphBuilder, GraphEngine  # noqa: F401
