"""LGCN — learnable graph conv with top-k feature ordering
(parity: examples/lgcn)."""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from common import fit_citation  # noqa: E402

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--hidden_dim", type=int, default=32)
    ap.add_argument("--fanout", type=int, default=0,
                    help="0 = auto (60 on pubmed — r3 sweep, 30 "
                         "otherwise)")
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--batch_size", type=int, default=64)
    ap.add_argument("--learning_rate", type=float, default=0.01)
    ap.add_argument("--max_steps", type=int, default=0,
                    help="0 = auto (800 on pubmed, 400 otherwise)")
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--dropout", type=float, default=-1.0,
                    help="-1 = auto (0.3 on pubmed, 0.5 otherwise)")
    ap.add_argument("--weight_decay", type=float, default=0.005)
    ap.add_argument("--model_dir", default="")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)
    is_pubmed = args.dataset == "pubmed"
    args.fanout = args.fanout or (60 if is_pubmed else 30)
    args.max_steps = args.max_steps or (800 if is_pubmed else 400)
    if args.dropout < 0:
        args.dropout = 0.3 if is_pubmed else 0.5

    from euler_tpu.dataflow import FanoutDataFlow
    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import NodeEstimator
    from euler_tpu.mp_utils import SuperviseModel
    from euler_tpu.utils.encoders import LGCEncoder

    data = get_dataset(args.dataset)

    class LGCNModel(SuperviseModel):
        def embed(self, batch):
            x = batch["layers"][0]
            nbr = batch["layers"][1].reshape(x.shape[0], args.fanout, -1)
            return LGCEncoder(dim=args.hidden_dim, k=args.k,
                              name="enc")(x, nbr)

    flow = FanoutDataFlow(data.engine, [args.fanout],
                          feature_ids=["feature"])
    est = NodeEstimator(
        LGCNModel(num_classes=data.num_classes, multilabel=data.multilabel,
                  dropout=args.dropout),
        dict(batch_size=args.batch_size, learning_rate=args.learning_rate,
             weight_decay=args.weight_decay,
             label_dim=data.num_classes),
        data.engine, flow, label_fid="label", label_dim=data.num_classes,
        model_dir=args.model_dir or None)
    res = fit_citation(est, args.max_steps, args.eval_steps)
    print(res)
    return res


if __name__ == "__main__":
    main()
