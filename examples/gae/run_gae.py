"""GAE / VGAE link reconstruction (parity: examples/gae)."""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--variational", action="store_true")
    ap.add_argument("--batch_size", type=int, default=64)
    ap.add_argument("--num_pos", type=int, default=128)
    ap.add_argument("--learning_rate", type=float, default=0.01)
    ap.add_argument("--max_steps", type=int, default=200)
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--model_dir", default="")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)

    from euler_tpu.dataflow import FullBatchDataFlow
    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import GaeEstimator
    from euler_tpu.mp_utils import BaseGraphGAE

    data = get_dataset(args.dataset)
    flow = FullBatchDataFlow(data.engine, feature_ids=["feature"])
    # FullBatch provides nodes/x/edge_index; GaeEstimator adds pos/negs
    flow_call = flow

    class _FlowAdapter:
        def __call__(self, roots):
            b = flow_call(roots)
            b["n_real_nodes"] = b["nodes"].shape[0]
            return b

    model = BaseGraphGAE(dim=args.dim, variational=args.variational)
    est = GaeEstimator(
        model,
        dict(batch_size=args.batch_size, num_pos=args.num_pos,
             learning_rate=args.learning_rate),
        data.engine, _FlowAdapter(), model_dir=args.model_dir or None)
    res = est.train(est.train_input_fn, args.max_steps)
    ev = est.evaluate(est.eval_input_fn, args.eval_steps)
    print({**{f"train_{k}": v for k, v in res.items()},
           **{f"eval_{k}": v for k, v in ev.items()}})
    return ev


if __name__ == "__main__":
    main()
