"""GeniePath — adaptive receptive-field GNN over fanouts
(parity: examples/geniepath)."""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from common import fit_citation  # noqa: E402

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--hidden_dim", type=int, default=64)
    ap.add_argument("--fanouts", default="15,10")
    ap.add_argument("--batch_size", type=int, default=64)
    ap.add_argument("--learning_rate", type=float, default=0.0,
                help="0 = auto per dataset (cora is stable at 0.01; the larger sets need 0.003)")
    ap.add_argument("--max_steps", type=int, default=600)
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--dropout", type=float, default=0.5)
    ap.add_argument("--weight_decay", type=float, default=0.005)
    ap.add_argument("--model_dir", default="")
    ap.add_argument("--device_sampler", action="store_true",
                    help="sample fanouts on the accelerator "
                         "(DeviceSampledGraphSage(encoder='genie'); "
                         "features+labels move to HBM tables)")
    ap.add_argument("--sampler_cap", type=int, default=32)
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)
    if not args.learning_rate:
        args.learning_rate = 0.01 if args.dataset == 'cora' else 0.003

    from euler_tpu.dataflow import FanoutDataFlow
    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import NodeEstimator
    from euler_tpu.mp_utils import SuperviseModel
    from euler_tpu.utils.encoders import GenieEncoder

    fanouts = tuple(int(x) for x in args.fanouts.split(","))
    data = get_dataset(args.dataset)

    class GeniePathModel(SuperviseModel):
        def embed(self, batch):
            return GenieEncoder(dim=args.hidden_dim, fanouts=fanouts,
                                name="enc")(batch["layers"])

    store = sampler = None
    if args.device_sampler:
        from euler_tpu.models import DeviceSampledGraphSage
        from euler_tpu.parallel import DeviceFeatureStore, DeviceNeighborTable

        store = DeviceFeatureStore(data.engine, ["feature"],
                                   label_fid="label",
                                   label_dim=data.num_classes)
        sampler = DeviceNeighborTable(data.engine, cap=args.sampler_cap)
        model = DeviceSampledGraphSage(
            num_classes=data.num_classes, multilabel=data.multilabel,
            dim=args.hidden_dim, fanouts=fanouts, encoder="genie",
            dropout=args.dropout)
        flow = None
    else:
        model = GeniePathModel(num_classes=data.num_classes,
                               multilabel=data.multilabel,
                               dropout=args.dropout)
        flow = FanoutDataFlow(data.engine, list(fanouts),
                              feature_ids=["feature"])
    est = NodeEstimator(
        model,
        dict(batch_size=args.batch_size, learning_rate=args.learning_rate,
             weight_decay=args.weight_decay,
             label_dim=data.num_classes),
        data.engine, flow, label_fid="label", label_dim=data.num_classes,
        model_dir=args.model_dir or None,
        feature_store=store, device_sampler=sampler)
    res = fit_citation(est, args.max_steps, args.eval_steps)
    print(res)
    return res


if __name__ == "__main__":
    main()
