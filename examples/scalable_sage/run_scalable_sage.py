"""Scalable GraphSAGE — 1-hop sampling + historical activation caches
(parity: reference ScalableSageEncoder path, encoders.py:629)."""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from common import fit_citation  # noqa: E402

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--hidden_dim", type=int, default=32)
    ap.add_argument("--num_layers", type=int, default=2)
    ap.add_argument("--fanout", type=int, default=10)
    ap.add_argument("--batch_size", type=int, default=64)
    ap.add_argument("--learning_rate", type=float, default=0.01)
    ap.add_argument("--max_steps", type=int, default=200)
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--model_dir", default="")
    ap.add_argument("--encoder", default="sage", choices=["sage", "gcn"],
                    help="scalable variant: sage (concat) or gcn "
                         "(mean-combine) — the reference's two "
                         "store-backed encoders")
    ap.add_argument("--device_sampler", action="store_true",
                    help="run the TPU-first config: sampling AND the "
                         "activation cache on device "
                         "(DeviceSampledScalableSage + full-coverage "
                         "pre-eval cache refresh — bench --act_cache)")
    ap.add_argument("--sampler_cap", type=int, default=32)
    ap.add_argument("--store_decay", type=float, default=0.9)
    ap.add_argument("--cache_refresh", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="with --device_sampler: full-coverage cache "
                         "refresh before each evaluation (same flag as "
                         "run_graphsage --act_cache)")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)

    from euler_tpu.dataflow import FanoutDataFlow
    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import NodeEstimator
    from euler_tpu.models import ScalableGraphSage

    data = get_dataset(args.dataset)
    flow = FanoutDataFlow(data.engine, [args.fanout],
                          feature_ids=["feature"])
    store = sampler = None
    if args.device_sampler:
        from euler_tpu.models import DeviceSampledScalableSage
        from euler_tpu.parallel import DeviceFeatureStore, DeviceNeighborTable

        store = DeviceFeatureStore(data.engine, ["feature"],
                                   label_fid="label",
                                   label_dim=data.num_classes)
        sampler = DeviceNeighborTable(data.engine, cap=args.sampler_cap)
        model = DeviceSampledScalableSage(
            num_classes=data.num_classes, multilabel=data.multilabel,
            dim=args.hidden_dim, fanout=args.fanout,
            num_layers=args.num_layers, max_id=int(sampler.pad_row),
            store_decay=args.store_decay, encoder=args.encoder)
    elif args.encoder != "sage":
        raise SystemExit("--encoder gcn requires --device_sampler "
                         "(the host example is the sage variant)")
    else:
        model = ScalableGraphSage(
            num_classes=data.num_classes, multilabel=data.multilabel,
            dim=args.hidden_dim, num_layers=args.num_layers,
            max_id=data.max_id)
    est = NodeEstimator(
        model,
        dict(batch_size=args.batch_size, learning_rate=args.learning_rate,
             max_id=data.max_id, label_dim=data.num_classes),
        data.engine, flow, label_fid="label", label_dim=data.num_classes,
        model_dir=args.model_dir or None,
        feature_store=store, device_sampler=sampler)
    if args.device_sampler and args.cache_refresh:
        from euler_tpu.models.graphsage import refresh_act_cache
        est.pre_eval_hook = refresh_act_cache
    res = fit_citation(est, args.max_steps, args.eval_steps)
    print(res)
    return res


if __name__ == "__main__":
    main()
