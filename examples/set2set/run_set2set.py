"""SET2SET graph classification on mutag.

Parity: examples/set2set. Baseline (BASELINE.md): accuracy set2set row.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from graph_common import graph_argparser, run_graph_model  # noqa: E402


def main(argv=None):
    args = graph_argparser().parse_args(argv)
    return run_graph_model("gin", "set2set", args)


if __name__ == "__main__":
    main()
