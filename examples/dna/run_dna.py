"""DNA — dynamic neighborhood aggregation over layer history
(parity: examples/dna)."""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from common import fit_citation  # noqa: E402

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402

import flax.linen as nn  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--hidden_dim", type=int, default=32)
    ap.add_argument("--num_layers", type=int, default=3)
    ap.add_argument("--heads", type=int, default=1)
    ap.add_argument("--batch_size", type=int, default=128)
    ap.add_argument("--learning_rate", type=float, default=0.01)
    ap.add_argument("--max_steps", type=int, default=400)
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--dropout", type=float, default=0.5)
    ap.add_argument("--weight_decay", type=float, default=0.005)
    ap.add_argument("--model_dir", default="")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)

    from euler_tpu.convolution import DNAConv
    from euler_tpu.dataflow import FullBatchDataFlow
    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import NodeEstimator
    from euler_tpu.mp_utils import SuperviseModel

    data = get_dataset(args.dataset)

    class DNAModel(SuperviseModel):
        def embed(self, batch):
            x = batch["x"]
            n = x.shape[0]
            det = not self.has_rng("dropout")
            drop = nn.Dropout(args.dropout)
            h = nn.relu(nn.Dense(args.hidden_dim, name="proj")(
                drop(x, deterministic=det)))
            hist = h[:, None, :]
            for i in range(args.num_layers):
                # between-layer dropout (the reference DNA uses heavy
                # inter-layer dropout on citation sets)
                hist_in = drop(hist, deterministic=det)
                h = DNAConv(out_dim=args.hidden_dim, heads=args.heads,
                            name=f"dna_{i}")(hist_in, batch["edge_index"], n)
                hist = jnp.concatenate([hist, h[:, None, :]], axis=1)
            root = batch.get("root_index")
            return h if root is None else jnp.take(h, root, axis=0)

    flow = FullBatchDataFlow(data.engine, feature_ids=["feature"])
    est = NodeEstimator(
        DNAModel(num_classes=data.num_classes, multilabel=data.multilabel),
        dict(batch_size=args.batch_size, learning_rate=args.learning_rate,
             weight_decay=args.weight_decay,
             label_dim=data.num_classes),
        data.engine, flow, label_fid="label", label_dim=data.num_classes,
        model_dir=args.model_dir or None)
    res = fit_citation(est, args.max_steps, args.eval_steps)
    print(res)
    return res


if __name__ == "__main__":
    main()
