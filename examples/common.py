"""Shared example runner utilities (role of the reference's per-example
flags + estimator wiring, examples/*/run_*.py)."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def citation_argparser(**defaults) -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default=defaults.get("dataset", "cora"))
    ap.add_argument("--hidden_dim", type=int,
                    default=defaults.get("hidden_dim", 32))
    ap.add_argument("--num_layers", type=int,
                    default=defaults.get("num_layers", 2))
    ap.add_argument("--batch_size", type=int,
                    default=defaults.get("batch_size", 128))
    ap.add_argument("--learning_rate", type=float,
                    default=defaults.get("learning_rate", 0.01))
    ap.add_argument("--max_steps", type=int,
                    default=defaults.get("max_steps", 200))
    ap.add_argument("--eval_steps", type=int,
                    default=defaults.get("eval_steps", 20))
    ap.add_argument("--dropout", type=float,
                    default=defaults.get("dropout", 0.0))
    ap.add_argument("--weight_decay", type=float,
                    default=defaults.get("weight_decay", 0.0))
    ap.add_argument("--model_dir", default="")
    ap.add_argument("--run_mode", default="train_and_evaluate")
    from euler_tpu.platform import add_platform_flag

    add_platform_flag(ap)
    return ap


def run_citation(conv_name: str, args, conv_kwargs=None, model_cls=None):
    """Train+evaluate a conv-stack model on a citation dataset."""
    from euler_tpu.platform import init_platform

    init_platform(getattr(args, "platform", "auto"))
    from euler_tpu.dataflow import FullBatchDataFlow
    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import NodeEstimator
    from euler_tpu.mp_utils import BaseGNNNet, SuperviseModel

    data = get_dataset(args.dataset)
    print(f"dataset {args.dataset}: {data.engine.node_count} nodes, "
          f"{data.engine.edge_count} edges [{data.source}]")

    drop = getattr(args, "dropout", 0.0)
    if model_cls is None:
        class ConvModel(SuperviseModel):
            dim: int = args.hidden_dim
            num_layers: int = args.num_layers

            def embed(self, batch):
                return BaseGNNNet(conv_name, self.dim, self.num_layers,
                                  conv_kwargs=conv_kwargs or {},
                                  dropout=drop, name="gnn")(batch)

        model = ConvModel(num_classes=data.num_classes,
                          multilabel=data.multilabel)
    else:
        model = model_cls(num_classes=data.num_classes,
                          multilabel=data.multilabel)

    flow = FullBatchDataFlow(data.engine, feature_ids=["feature"])
    est = NodeEstimator(
        model,
        dict(batch_size=args.batch_size, learning_rate=args.learning_rate,
             weight_decay=getattr(args, "weight_decay", 0.0),
             label_dim=data.num_classes),
        data.engine, flow, label_fid="label", label_dim=data.num_classes,
        model_dir=args.model_dir or None)
    res = fit_citation(est, args.max_steps, args.eval_steps)
    print(res)
    return res


def fit_citation(est, max_steps: int, eval_steps: int):
    """Standard citation protocol: early-stop on the val split (node type
    1), then report the test split (type 2) at the best-val weights — the
    split the reference's published F1 tables quote. Both the model-
    selection metric and the reported test metric come from DETERMINISTIC
    full-split sweeps (each node exactly once, padded tail masked) — the
    old with-replacement sampling put ±1-2 point noise on both."""
    sweep = getattr(est, "eval_sweep_input_fn", None)
    if sweep is None:
        res = est.train_and_evaluate(est.train_input_fn, est.eval_input_fn,
                                     max_steps, eval_steps,
                                     eval_every=max(max_steps // 10, 10),
                                     keep_best=True)
        test_fn, test_steps = est.eval_input_fn, eval_steps
    else:
        res = est.train_and_evaluate(
            est.train_input_fn, est.eval_sweep_input_fn,
            max_steps, est.eval_sweep_steps(),
            eval_every=max(max_steps // 10, 10), keep_best=True)
        test_fn = lambda: est.eval_sweep_input_fn(node_type=2)  # noqa: E731
        test_steps = est.eval_sweep_steps(node_type=2)
    prev = est.eval_node_type
    est.eval_node_type = 2
    try:
        test = est.evaluate(test_fn, test_steps)
    finally:
        est.eval_node_type = prev
    res["test_metric"] = test["metric"]
    res["test_loss"] = test["loss"]
    return res
