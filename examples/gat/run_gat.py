"""GAT on citation datasets.

Parity: examples/gat/run_gat.py. Baseline (BASELINE.md): see gat row.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from common import citation_argparser, run_citation  # noqa: E402


def main(argv=None):
    args = citation_argparser(hidden_dim=16, dropout=0.6, weight_decay=0.005,
                              learning_rate=0.005, max_steps=500).parse_args(argv)
    return run_citation("gat", args, conv_kwargs={'heads': 8})


if __name__ == "__main__":
    main()
