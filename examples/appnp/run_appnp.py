"""APPNP on citation datasets.

Parity: examples/appnp/run_appnp.py. Baseline (BASELINE.md): see appnp row.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from common import citation_argparser, run_citation  # noqa: E402


def main(argv=None):
    args = citation_argparser().parse_args(argv)
    return run_citation("appnp", args, conv_kwargs={'k_hop': 10, 'alpha': 0.1})


if __name__ == "__main__":
    main()
