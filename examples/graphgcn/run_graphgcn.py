"""GRAPHGCN graph classification on mutag.

Parity: examples/graphgcn. Baseline (BASELINE.md): accuracy graphgcn row.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from graph_common import graph_argparser, run_graph_model  # noqa: E402


def main(argv=None):
    # 4 layers / 1200 steps: swept r3 — 0.895 vs 0.868 at 3/800 (the
    # published reference row is 0.891)
    args = graph_argparser(num_layers=4, hidden_dim=64,
                           max_steps=1200).parse_args(argv)
    # the reference pools with 'add' (graphgcn.py:57), not mean
    return run_graph_model("gcn", "sum", args)


if __name__ == "__main__":
    main()
