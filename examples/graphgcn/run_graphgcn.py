"""GRAPHGCN graph classification on mutag.

Parity: examples/graphgcn. Baseline (BASELINE.md): accuracy graphgcn row.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from graph_common import graph_argparser, run_graph_model  # noqa: E402


def main(argv=None):
    args = graph_argparser(num_layers=3, hidden_dim=64,
                           max_steps=800).parse_args(argv)
    # the reference pools with 'add' (graphgcn.py:57), not mean
    return run_graph_model("gcn", "sum", args)


if __name__ == "__main__":
    main()
