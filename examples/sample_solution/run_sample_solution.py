"""Sample-file training: supervised labels come from a line-oriented
sample file ("label,node_id" records), not from the graph store.

Parity: examples/sample_solution (sample.txt + SampleEstimator over
TextLine inputs, euler_estimator/python/sample_estimator.py). The
industrial pattern: labels live in an offline pipeline's output file
while the graph engine serves topology + features.

With --make_samples (default when the sample file is missing) the
script first materializes the file from the dataset's train split —
the role of the reference's checked-in sample.txt.
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402

import numpy as np  # noqa: E402


def write_samples(path, graph, node_type, limit=0):
    """train-split nodes → 'label,node_id' lines (argmax of the one-hot
    label feature)."""
    ids = graph.all_node_ids()
    ids = ids[graph.get_node_type(ids) == node_type]
    if limit:
        ids = ids[:limit]
    labels = graph.get_dense_feature(ids, "label").argmax(-1)
    with open(path, "w") as f:
        for lab, nid in zip(labels, ids):
            f.write(f"{int(lab)},{int(nid)}\n")
    return len(ids)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--sample_file", default="")
    ap.add_argument("--fanouts", default="5,5")
    ap.add_argument("--hidden_dim", type=int, default=32)
    ap.add_argument("--batch_size", type=int, default=64)
    ap.add_argument("--learning_rate", type=float, default=0.003)
    ap.add_argument("--max_steps", type=int, default=300)
    ap.add_argument("--eval_steps", type=int, default=10)
    ap.add_argument("--model_dir", default="")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)

    from euler_tpu.dataflow import FanoutDataFlow
    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import SampleEstimator
    from euler_tpu.models import SupervisedGraphSage

    data = get_dataset(args.dataset)
    g = data.engine
    fanouts = tuple(int(x) for x in args.fanouts.split(","))
    sample_file = args.sample_file
    if not sample_file:
        out_dir = Path(args.model_dir or ".")
        out_dir.mkdir(parents=True, exist_ok=True)
        sample_file = str(out_dir / "sample.txt")
    if not Path(sample_file).exists():
        n = write_samples(sample_file, g, node_type=0)
        print(f"wrote {n} train samples to {sample_file}")

    flow = FanoutDataFlow(g, list(fanouts), feature_ids=["feature"])

    def parse_fn(lines):
        labs, nodes = [], []
        for ln in lines:
            a, b = ln.split(",")
            labs.append(int(a))
            nodes.append(int(b))
        roots = np.asarray(nodes, np.uint64)
        batch = flow(roots)
        batch["labels"] = np.eye(data.num_classes,
                                 dtype=np.float32)[labs]
        batch["infer_ids"] = roots
        return batch

    model = SupervisedGraphSage(num_classes=data.num_classes,
                                multilabel=False, dim=args.hidden_dim,
                                fanouts=fanouts)
    est = SampleEstimator(
        model,
        dict(batch_size=args.batch_size, learning_rate=args.learning_rate,
             label_dim=data.num_classes),
        sample_file, parse_fn, model_dir=args.model_dir or None)
    res = est.train(est.train_input_fn, args.max_steps)
    ev = est.evaluate(est.eval_input_fn, args.eval_steps)
    out = {**{f"train_{k}": v for k, v in res.items()},
           **{f"eval_{k}": v for k, v in ev.items()}}
    print(out)
    return out


if __name__ == "__main__":
    main()
