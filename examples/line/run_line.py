"""LINE (1st/2nd-order proximity embeddings).

Parity: examples/line/run_line.py. Positives are sampled edges; negatives
global weighted node samples.
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--dim", type=int, default=0,
                    help="0 = auto (256 on pubmed — r3 probe lifts MRR "
                         "0.966→0.990, 128 otherwise)")
    ap.add_argument("--order", type=int, default=2, choices=[1, 2])
    ap.add_argument("--num_negs", type=int, default=5)
    ap.add_argument("--batch_size", type=int, default=128)
    ap.add_argument("--learning_rate", type=float, default=0.0,
                    help="0 = auto (0.05 on pubmed, 0.025 otherwise)")
    ap.add_argument("--max_steps", type=int, default=0,
                help="0 = auto: 8000 on pubmed, ~8 epochs otherwise")
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--device_sampler", action="store_true",
                    help="positives (1-hop weighted draw) + negatives "
                         "sampled on device from HBM tables")
    ap.add_argument("--sampler_cap", type=int, default=32)
    ap.add_argument("--model_dir", default="")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)

    import numpy as np

    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import BaseEstimator
    from euler_tpu.models import LINE, DeviceSampledSkipGram

    data = get_dataset(args.dataset)
    g = data.engine
    is_pubmed = args.dataset == "pubmed"
    args.dim = args.dim or (256 if is_pubmed else 128)
    args.learning_rate = args.learning_rate or (0.05 if is_pubmed
                                                else 0.025)
    if not args.max_steps:
        args.max_steps = 8000 if is_pubmed else max(
            500, int(8 * g.edge_count / args.batch_size))
    if args.device_sampler:
        # LINE as a walk_len-1 skip-gram: (src, 1-hop weighted neighbor)
        # pairs ≡ weighted edge sampling given roots ~ node weights;
        # order=1 shares the context table
        from euler_tpu.parallel import DeviceNeighborTable, DeviceNodeSampler

        tab = DeviceNeighborTable(g, cap=args.sampler_cap)
        neg = DeviceNodeSampler(g, node_type=-1)
        model = DeviceSampledSkipGram(
            num_rows=tab.pad_row, dim=args.dim, walk_len=1, left_win=0,
            right_win=1, num_negs=args.num_negs,
            share_context=args.order == 1)
        est = BaseEstimator(model,
                            dict(learning_rate=args.learning_rate),
                            model_dir=args.model_dir or None)
        est.static_batch.update({**tab.tables, **neg.tables})
        seed_box = [0]

        def input_fn():
            while True:
                roots = g.node_rows(g.sample_node(args.batch_size, -1),
                                    missing=tab.pad_row)
                seed_box[0] += 1
                yield {"rows": [roots], "infer_ids": roots,
                       "sample_seed": np.uint32(seed_box[0])}
    else:
        model = LINE(max_id=data.max_id, dim=args.dim, order=args.order)
        est = BaseEstimator(model,
                            dict(learning_rate=args.learning_rate,
                                 max_id=data.max_id),
                            model_dir=args.model_dir or None)

        def input_fn():
            while True:
                src, dst, _ = g.sample_edge(args.batch_size, -1)
                negs = g.sample_node(
                    args.batch_size * args.num_negs, -1).reshape(
                        args.batch_size, args.num_negs)
                yield {"src": src, "pos": dst, "negs": negs,
                       "infer_ids": src}

    res = est.train(input_fn, args.max_steps)
    ev = est.evaluate(input_fn, args.eval_steps)
    print({**{f"train_{k}": v for k, v in res.items()},
           **{f"eval_{k}": v for k, v in ev.items()}})
    return ev


if __name__ == "__main__":
    main()
