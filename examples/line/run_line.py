"""LINE (1st/2nd-order proximity embeddings).

Parity: examples/line/run_line.py. Positives are sampled edges; negatives
global weighted node samples.
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--order", type=int, default=2, choices=[1, 2])
    ap.add_argument("--num_negs", type=int, default=5)
    ap.add_argument("--batch_size", type=int, default=128)
    ap.add_argument("--learning_rate", type=float, default=0.025)
    ap.add_argument("--max_steps", type=int, default=0,
                help="0 = auto: ~8 epochs over the edge set")
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--model_dir", default="")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)

    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import BaseEstimator
    from euler_tpu.models import LINE

    data = get_dataset(args.dataset)
    g = data.engine
    if not args.max_steps:
        args.max_steps = max(500,
                             int(8 * g.edge_count / args.batch_size))
    model = LINE(max_id=data.max_id, dim=args.dim, order=args.order)
    est = BaseEstimator(model,
                        dict(learning_rate=args.learning_rate,
                             max_id=data.max_id),
                        model_dir=args.model_dir or None)

    def input_fn():
        while True:
            src, dst, _ = g.sample_edge(args.batch_size, -1)
            negs = g.sample_node(args.batch_size * args.num_negs, -1).reshape(
                args.batch_size, args.num_negs)
            yield {"src": src, "pos": dst, "negs": negs, "infer_ids": src}

    res = est.train(input_fn, args.max_steps)
    ev = est.evaluate(input_fn, args.eval_steps)
    print({**{f"train_{k}": v for k, v in res.items()},
           **{f"eval_{k}": v for k, v in ev.items()}})
    return ev


if __name__ == "__main__":
    main()
