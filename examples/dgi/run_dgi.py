"""Deep Graph Infomax (parity: examples/dgi)."""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402

import numpy as np  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--batch_size", type=int, default=64)
    ap.add_argument("--learning_rate", type=float, default=0.01)
    ap.add_argument("--max_steps", type=int, default=200)
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--model_dir", default="")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)

    from euler_tpu.dataflow import FullBatchDataFlow
    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import BaseEstimator
    from euler_tpu.models import DGI

    data = get_dataset(args.dataset)
    g = data.engine
    flow = FullBatchDataFlow(g, feature_ids=["feature"])
    model = DGI(dim=args.dim)
    est = BaseEstimator(model, dict(learning_rate=args.learning_rate),
                        model_dir=args.model_dir or None)
    rng = np.random.default_rng(0)

    def input_fn():
        while True:
            roots = g.sample_node(args.batch_size, -1)
            batch = flow(roots)
            perm = rng.permutation(batch["x"].shape[0])
            batch["x_corrupt"] = batch["x"][perm]
            batch["infer_ids"] = roots
            yield batch

    res = est.train(input_fn, args.max_steps)
    ev = est.evaluate(input_fn, args.eval_steps)
    print({**{f"train_{k}": v for k, v in res.items()},
           **{f"eval_{k}": v for k, v in ev.items()}})
    return ev


if __name__ == "__main__":
    main()
