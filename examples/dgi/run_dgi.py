"""Deep Graph Infomax (parity: examples/dgi)."""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402

import numpy as np  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--num_layers", type=int, default=1)
    ap.add_argument("--learning_rate", type=float, default=0.001)
    ap.add_argument("--max_steps", type=int, default=1000)
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--model_dir", default="")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)

    from euler_tpu.dataflow import FullBatchDataFlow
    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import BaseEstimator
    from euler_tpu.models import DGI

    data = get_dataset(args.dataset)
    g = data.engine
    flow = FullBatchDataFlow(g, feature_ids=["feature"])
    model = DGI(dim=args.dim, num_layers=args.num_layers)
    est = BaseEstimator(model, dict(learning_rate=args.learning_rate),
                        model_dir=args.model_dir or None)
    rng = np.random.default_rng(0)

    # the paper trains on the WHOLE graph each step (one corruption per
    # step). The constant graph arrays ride static_batch so only the
    # per-step corruption permutation crosses to the device.
    ids = g.all_node_ids()
    full = flow(ids)
    est.static_batch.update(full)

    def input_fn():
        while True:
            perm = rng.permutation(full["x"].shape[0])
            yield {"x_corrupt": full["x"][perm]}

    res = est.train(input_fn, args.max_steps)
    ev = est.evaluate(input_fn, args.eval_steps)

    # DGI's own metric (real-vs-corrupted discriminator accuracy)
    # saturates by design; the meaningful number is the standard DGI
    # evaluation — a linear probe on the frozen embeddings.
    import jax

    batch = full
    variables = {"params": est.state.params, **(est.state.extra_vars or {})}
    emb = np.asarray(jax.device_get(
        est.model.apply(variables, {**batch, "x_corrupt": batch["x"]}
                        ).embedding))
    labels = g.get_dense_feature(ids, "label").argmax(1)
    types = g.get_node_type(ids)
    tr, te = types == 0, types == 2
    A = emb[tr].T @ emb[tr] + 0.1 * np.eye(emb.shape[1], dtype=np.float32)
    onehot = np.eye(int(labels.max()) + 1, dtype=np.float32)[labels]
    W = np.linalg.solve(A, emb[tr].T @ onehot[tr])
    probe = float(((emb[te] @ W).argmax(1) == labels[te]).mean())
    ev["metric"] = probe

    print({**{f"train_{k}": v for k, v in res.items()},
           **{f"eval_{k}": v for k, v in ev.items()},
           "probe_acc": probe})
    return ev


if __name__ == "__main__":
    main()
