"""DistMult on fb15k-family (parity: examples/distmult) — the TransX
driver with the trilinear scorer."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from TransX.run_transx import main as transx_main  # noqa: E402


def main(argv=None):
    argv = list(argv) if argv is not None else sys.argv[1:]
    if "--model" not in argv:
        argv = ["--model", "DistMult"] + argv
    return transx_main(argv)


if __name__ == "__main__":
    main()
