"""Industrial solution templates (parity: examples/solution +
examples/sample_solution): assemble supervised / unsupervised pipelines
from the solution layer's parts."""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--mode", default="supervise",
                    choices=["supervise", "unsupervise"])
    ap.add_argument("--fanouts", default="10,10")
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--logits", default="dot", choices=["dot", "cosine"])
    ap.add_argument("--batch_size", type=int, default=64)
    ap.add_argument("--learning_rate", type=float, default=0.003)
    ap.add_argument("--weight_decay", type=float, default=0.001)
    ap.add_argument("--max_steps", type=int, default=400)
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--model_dir", default="")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)

    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import BaseEstimator
    from euler_tpu.solution import SuperviseSolution, UnsuperviseSolution

    data = get_dataset(args.dataset)
    fanouts = tuple(int(x) for x in args.fanouts.split(","))
    if args.mode == "supervise":
        sol = SuperviseSolution(
            data.engine, fanouts=fanouts, dim=args.dim,
            num_classes=data.num_classes, multilabel=data.multilabel,
            batch_size=args.batch_size)
    else:
        sol = UnsuperviseSolution(
            data.engine, fanouts=fanouts, dim=args.dim, max_id=data.max_id,
            batch_size=args.batch_size, logits=args.logits)
    est = BaseEstimator(sol.model,
                        dict(learning_rate=args.learning_rate,
                             weight_decay=args.weight_decay,
                             max_id=data.max_id),
                        model_dir=args.model_dir or None)
    if args.mode == "supervise":
        # citation protocol: early-stop on val (type 1), report test
        # (type 2) — solutions sample train nodes by default
        res = est.train_and_evaluate(
            sol.input_fn, lambda: sol.input_fn(1),
            args.max_steps, args.eval_steps,
            eval_every=max(args.max_steps // 10, 10), keep_best=True)
        test = est.evaluate(lambda: sol.input_fn(2), args.eval_steps)
        res["test_metric"] = test["metric"]
        res["test_loss"] = test["loss"]
        print(res)
        return test
    res = est.train(sol.input_fn, args.max_steps)
    ev = est.evaluate(sol.input_fn, args.eval_steps)
    print({**{f"train_{k}": v for k, v in res.items()},
           **{f"eval_{k}": v for k, v in ev.items()}})
    return ev


if __name__ == "__main__":
    main()
