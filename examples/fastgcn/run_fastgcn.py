"""FastGCN — layerwise importance-sampled GCN (parity: examples/fastgcn)."""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from common import fit_citation  # noqa: E402

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--hidden_dim", type=int, default=32)
    ap.add_argument("--layer_sizes", default="",
                help="default: 256,256 on pubmed-sized sets, 128,128 otherwise")
    ap.add_argument("--batch_size", type=int, default=64)
    ap.add_argument("--learning_rate", type=float, default=0.01)
    ap.add_argument("--max_steps", type=int, default=0)
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--dropout", type=float, default=0.5)
    ap.add_argument("--weight_decay", type=float, default=0.005)
    ap.add_argument("--model_dir", default="")
    ap.add_argument("--device_sampler", action="store_true",
                    help="sample the layer pools on the accelerator "
                         "(device_layerwise.sample_layerwise_rows; "
                         "features+labels move to HBM tables; eval "
                         "keeps the standard exact-closure host "
                         "protocol via eval_via_flow)")
    ap.add_argument("--sampler_cap", type=int, default=32)
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)
    if not args.layer_sizes:
        args.layer_sizes = ('256,256' if args.dataset == 'pubmed'
                            else '128,128')
    if not args.max_steps:
        args.max_steps = 1200 if args.dataset == 'pubmed' else 800

    from euler_tpu.dataflow import LayerwiseDataFlow
    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import NodeEstimator
    from euler_tpu.mp_utils import SuperviseModel
    from euler_tpu.utils.encoders import LayerEncoder

    sizes = [int(x) for x in args.layer_sizes.split(",")]
    data = get_dataset(args.dataset)

    class FastGCNModel(SuperviseModel):
        def embed(self, batch):
            return LayerEncoder(dim=args.hidden_dim, dropout=args.dropout,
                                name="enc")(batch["layers"], batch["adjs"])

    store = sampler = None
    if args.device_sampler:
        from euler_tpu.models import DeviceSampledLayerwiseGCN
        from euler_tpu.parallel import DeviceFeatureStore, DeviceNeighborTable

        store = DeviceFeatureStore(data.engine, ["feature"],
                                   label_fid="label",
                                   label_dim=data.num_classes)
        sampler = DeviceNeighborTable(data.engine, cap=args.sampler_cap)
        model = DeviceSampledLayerwiseGCN(
            num_classes=data.num_classes, multilabel=data.multilabel,
            dim=args.hidden_dim, layer_sizes=tuple(sizes),
            layer_dropout=args.dropout)
        # device mode: training short-circuits to root-rows-only batches
        # (in-jit sampled pools); eval_via_flow below keeps eval on the
        # host exact-closure protocol
        flow = None
    else:
        model = FastGCNModel(num_classes=data.num_classes,
                             multilabel=data.multilabel)
        flow = LayerwiseDataFlow(data.engine, sizes, feature_ids=["feature"])
    # standard FastGCN protocol in BOTH modes: importance-sampled pools
    # for training, exact 1-hop closures (full propagation matrix) for
    # evaluation
    eval_flow = LayerwiseDataFlow(data.engine, sizes, sample=False,
                                  feature_ids=["feature"])
    est = NodeEstimator(
        model,
        dict(batch_size=args.batch_size, learning_rate=args.learning_rate,
             weight_decay=args.weight_decay,
             label_dim=data.num_classes),
        data.engine, flow, label_fid="label", label_dim=data.num_classes,
        model_dir=args.model_dir or None, eval_dataflow=eval_flow,
        feature_store=store, device_sampler=sampler,
        eval_via_flow=args.device_sampler)
    res = fit_citation(est, args.max_steps, args.eval_steps)
    print(res)
    return res


if __name__ == "__main__":
    main()
