"""GCN node classification — the reference's flagship quality example.

Parity: examples/gcn/run_gcn.py (flags, dataset, estimator). Regression
bar (BASELINE.md): micro-F1 ≥ 0.82 on cora-shaped data.

Usage: python examples/gcn/run_gcn.py --dataset cora --max_steps 300
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from common import fit_citation  # noqa: E402

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402

import flax.linen as nn  # noqa: E402

from euler_tpu.dataflow import FullBatchDataFlow  # noqa: E402
from euler_tpu.dataset import get_dataset  # noqa: E402
from euler_tpu.estimator import NodeEstimator  # noqa: E402
from euler_tpu.mp_utils import BaseGNNNet, SuperviseModel  # noqa: E402


class GCNModel(SuperviseModel):
    dim: int = 32
    num_layers: int = 2
    conv_name: str = "gcn"

    def embed(self, batch):
        return BaseGNNNet(self.conv_name, self.dim, self.num_layers,
                          name="gnn")(batch)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--conv", default="gcn")
    ap.add_argument("--hidden_dim", type=int, default=32)
    ap.add_argument("--num_layers", type=int, default=2)
    ap.add_argument("--batch_size", type=int, default=128)
    ap.add_argument("--learning_rate", type=float, default=0.01)
    ap.add_argument("--max_steps", type=int, default=300)
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--model_dir", default="")
    ap.add_argument("--run_mode", default="train_and_evaluate",
                    choices=["train", "evaluate", "infer",
                             "train_and_evaluate"])
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)

    data = get_dataset(args.dataset)
    print(f"dataset {args.dataset}: {data.engine.node_count} nodes, "
          f"{data.engine.edge_count} edges, {data.num_classes} classes "
          f"[{data.source}]")
    model = GCNModel(num_classes=data.num_classes, multilabel=data.multilabel,
                     dim=args.hidden_dim, num_layers=args.num_layers,
                     conv_name=args.conv)
    flow = FullBatchDataFlow(data.engine, feature_ids=["feature"])
    est = NodeEstimator(
        model,
        dict(batch_size=args.batch_size, learning_rate=args.learning_rate,
             optimizer="adam", max_id=data.max_id,
             label_dim=data.num_classes),
        data.engine, flow, label_fid="label", label_dim=data.num_classes,
        model_dir=args.model_dir or None,
    )
    if args.run_mode == "train":
        print(est.train(est.train_input_fn, args.max_steps))
    elif args.run_mode == "evaluate":
        print(est.evaluate(est.eval_input_fn, args.eval_steps))
    elif args.run_mode == "infer":
        print(est.infer(est.infer_input_fn))
    else:
        res = fit_citation(est, args.max_steps, args.eval_steps)
        print(res)
        return res
    return None


if __name__ == "__main__":
    main()
