"""Shared runner for whole-graph classification examples (mutag family:
gin / gated_graph / set2set / graphgcn — reference examples)."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def graph_argparser(**defaults) -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="mutag")
    ap.add_argument("--hidden_dim", type=int,
                    default=defaults.get("hidden_dim", 32))
    ap.add_argument("--num_layers", type=int,
                    default=defaults.get("num_layers", 2))
    ap.add_argument("--num_graphs", type=int,
                    default=defaults.get("num_graphs", 16))
    ap.add_argument("--learning_rate", type=float,
                    default=defaults.get("learning_rate", 0.01))
    ap.add_argument("--max_steps", type=int,
                    default=defaults.get("max_steps", 500))
    ap.add_argument("--eval_steps", type=int,
                    default=defaults.get("eval_steps", 20))
    ap.add_argument("--dropout", type=float,
                    default=defaults.get("dropout", 0.5))
    ap.add_argument("--weight_decay", type=float,
                    default=defaults.get("weight_decay", 0.005))
    ap.add_argument("--model_dir", default="")
    from euler_tpu.platform import add_platform_flag

    add_platform_flag(ap)
    return ap


def run_graph_model(conv_name: str, pool_name: str, args):
    from euler_tpu.platform import init_platform

    init_platform(getattr(args, "platform", "auto"))
    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import GraphEstimator
    from euler_tpu.mp_utils import GraphModel

    data = get_dataset(args.dataset)
    model = GraphModel(
        conv_name=conv_name, pool_name=pool_name, dim=args.hidden_dim,
        num_layers=args.num_layers, num_graphs=args.num_graphs,
        num_classes=data.num_classes,
        dropout=getattr(args, "dropout", 0.0))
    est = GraphEstimator(
        model,
        dict(num_graphs=args.num_graphs, learning_rate=args.learning_rate,
             weight_decay=getattr(args, "weight_decay", 0.0),
             train_indices=data.train_indices, eval_indices=data.eval_indices),
        data.graphs, data.labels, model_dir=args.model_dir or None)
    # best-epoch eval accuracy — the GIN-paper protocol the reference's
    # mutag table follows (their 10-fold CV reports the best epoch).
    # eval_steps must cover the whole deterministic sweep (see
    # GraphEstimator.eval_input_fn)
    pool = len(data.eval_indices)
    eval_steps = max(args.eval_steps, -(-pool // args.num_graphs))
    res = est.train_and_evaluate(est.train_input_fn, est.eval_input_fn,
                                 args.max_steps, eval_steps,
                                 eval_every=max(args.max_steps // 10, 10),
                                 keep_best=True)
    print(res)
    return res
