"""TAGCN on citation datasets.

Parity: examples/tagcn/run_tagcn.py. Baseline (BASELINE.md): see tagcn row.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from common import citation_argparser, run_citation  # noqa: E402


def main(argv=None):
    args = citation_argparser(learning_rate=0.0, max_steps=0).parse_args(argv)
    # per-dataset measured best (citeseer prefers the shared defaults)
    if not args.learning_rate:
        args.learning_rate = 0.01 if args.dataset == "citeseer" else 0.005
    if not args.max_steps:
        args.max_steps = 200 if args.dataset == "citeseer" else 500
    return run_citation("tag", args, conv_kwargs={'k_hop': 3})


if __name__ == "__main__":
    main()
