"""GraphSAGE (supervised + unsupervised) over sampled fanouts.

Parity: examples/graphsage/run_graphsage.py:30-46. The fanout/encoder
path — the scalable configuration bench.py measures.
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from common import fit_citation  # noqa: E402

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--mode", default="supervised",
                    choices=["supervised", "unsupervised"])
    ap.add_argument("--fanouts", default="10,10")
    ap.add_argument("--hidden_dim", type=int, default=64)
    ap.add_argument("--aggregator", default="mean")
    ap.add_argument("--device_sampler", action="store_true",
                    help="sample fanouts on the accelerator "
                         "(DeviceNeighborTable; features+labels "
                         "move to HBM tables)")
    ap.add_argument("--sampler_cap", type=int, default=32)
    ap.add_argument("--fused_sampler", action="store_true",
                    help="with --device_sampler (supervised): one fused "
                         "[N+1, 2C] HBM table, one row gather per hop")
    ap.add_argument("--int8_features", action="store_true",
                    help="with --device_sampler: int8-quantized HBM "
                         "feature table (per-column scale, dequant "
                         "after the in-jit gather)")
    ap.add_argument("--act_cache", action="store_true",
                    help="with --device_sampler (supervised): "
                         "DeviceSampledScalableSage — 1-hop sampling + "
                         "in-jit historical-activation cache (the "
                         "structural fix for the products-scale hop-2 "
                         "gather, PERF.md; this flag pins its quality)")
    ap.add_argument("--store_decay", type=float, default=0.9,
                    help="with --act_cache: EMA weight on the old "
                         "cached activation")
    ap.add_argument("--cache_refresh", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="with --act_cache: refresh the cache over ALL "
                         "nodes before each evaluation (plain training "
                         "only writes train-root rows, so eval-time "
                         "neighbor reads on small train splits hit "
                         "zeros); --no-cache_refresh reverts to the "
                         "train-visited-only protocol")
    ap.add_argument("--batch_size", type=int, default=64)
    ap.add_argument("--num_negs", type=int, default=5)
    ap.add_argument("--learning_rate", type=float, default=0.003)
    ap.add_argument("--dropout", type=float, default=0.6)
    ap.add_argument("--weight_decay", type=float, default=0.0)
    ap.add_argument("--max_steps", type=int, default=600)
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--model_dir", default="")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)
    # NOTE: an r3 sweep found wider pubmed windows (25,15 / batch 128)
    # raise TEST F1 to 0.855 but LOWER val F1 — selecting them would be
    # tuning on the reported split, so defaults stay val-chosen
    # (tools/sweep_quality.py records both splits; pick by val).

    from euler_tpu.dataflow import FanoutDataFlow
    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import EdgeEstimator, NodeEstimator
    from euler_tpu.models import SupervisedGraphSage, UnsupervisedGraphSage

    if args.act_cache and not args.device_sampler:
        print("run_graphsage: --act_cache needs --device_sampler "
              "(the cache config is the device path)", file=sys.stderr)
        raise SystemExit(2)
    fanouts = tuple(int(x) for x in args.fanouts.split(","))
    data = get_dataset(args.dataset)
    print(f"dataset {args.dataset}: {data.engine.node_count} nodes "
          f"[{data.source}]")
    flow = FanoutDataFlow(data.engine, list(fanouts),
                          feature_ids=["feature"])
    if args.mode == "supervised":
        store = sampler = None
        if args.device_sampler:
            from euler_tpu.models import DeviceSampledGraphSage
            from euler_tpu.parallel import (
                DeviceFeatureStore, DeviceNeighborTable,
            )

            store = DeviceFeatureStore(
                data.engine, ["feature"], label_fid="label",
                label_dim=data.num_classes,
                quantize="int8" if args.int8_features else None)
            sampler = DeviceNeighborTable(data.engine, cap=args.sampler_cap,
                                          fused=args.fused_sampler)
            if args.act_cache:
                from euler_tpu.models import DeviceSampledScalableSage
                model = DeviceSampledScalableSage(
                    num_classes=data.num_classes,
                    multilabel=data.multilabel, dim=args.hidden_dim,
                    fanout=fanouts[0], num_layers=len(fanouts),
                    max_id=int(sampler.pad_row), dropout=args.dropout,
                    store_decay=args.store_decay)
            else:
                model = DeviceSampledGraphSage(
                    num_classes=data.num_classes,
                    multilabel=data.multilabel,
                    dim=args.hidden_dim, fanouts=fanouts,
                    aggregator=args.aggregator, dropout=args.dropout)
        else:
            model = SupervisedGraphSage(
                num_classes=data.num_classes, multilabel=data.multilabel,
                dim=args.hidden_dim, fanouts=fanouts,
                aggregator=args.aggregator, dropout=args.dropout)
        est = NodeEstimator(
            model,
            dict(batch_size=args.batch_size,
                 learning_rate=args.learning_rate,
                 weight_decay=args.weight_decay,
                 label_dim=data.num_classes),
            data.engine, flow, label_fid="label",
            label_dim=data.num_classes, model_dir=args.model_dir or None,
            feature_store=store, device_sampler=sampler)
        if args.act_cache and args.device_sampler and args.cache_refresh:
            from euler_tpu.models.graphsage import refresh_act_cache
            est.pre_eval_hook = refresh_act_cache
        res = fit_citation(est, args.max_steps, args.eval_steps)
    elif args.device_sampler:
        # fully on-device unsupervised path: fanout embedding, positive
        # 1-hop draw, and weighted negatives all inside the jitted step
        import numpy as np

        from euler_tpu.estimator import BaseEstimator
        from euler_tpu.models import DeviceSampledUnsupervisedSage
        from euler_tpu.parallel import (
            DeviceFeatureStore, DeviceNeighborTable, DeviceNodeSampler,
        )

        g = data.engine
        store = DeviceFeatureStore(
            g, ["feature"],
            quantize="int8" if args.int8_features else None)
        tab = DeviceNeighborTable(g, cap=args.sampler_cap,
                                  fused=args.fused_sampler)
        neg = DeviceNodeSampler(g, node_type=-1)
        model = DeviceSampledUnsupervisedSage(
            num_rows=tab.pad_row, dim=args.hidden_dim, fanouts=fanouts,
            aggregator=args.aggregator, num_negs=args.num_negs)
        est = BaseEstimator(
            model, dict(learning_rate=args.learning_rate),
            model_dir=args.model_dir or None)
        est.static_batch.update({"feature_table": store.features,
                                 **tab.tables, **neg.tables})
        if store.feature_scale is not None:
            est.static_batch["feature_scale"] = store.feature_scale
        seed_box = [0]

        def input_fn():
            while True:
                roots = store.lookup(g.sample_node(args.batch_size, -1))
                seed_box[0] += 1
                yield {"rows": [roots], "infer_ids": roots,
                       "sample_seed": np.uint32(seed_box[0])}

        res = est.train(input_fn, args.max_steps)
        ev = est.evaluate(input_fn, args.eval_steps)
        res = {**{f"train_{k}": v for k, v in res.items()},
               **{f"eval_{k}": v for k, v in ev.items()}}
    else:
        model = UnsupervisedGraphSage(
            dim=args.hidden_dim, max_id=data.max_id, fanouts=fanouts,
            aggregator=args.aggregator, num_negs=args.num_negs)
        est = EdgeEstimator(
            model,
            dict(batch_size=args.batch_size, num_negs=args.num_negs,
                 learning_rate=args.learning_rate, max_id=data.max_id),
            data.engine, dataflow=flow, model_dir=args.model_dir or None)
        res = est.train_and_evaluate(est.train_input_fn, est.eval_input_fn,
                                     args.max_steps, args.eval_steps)
    print(res)
    return res


if __name__ == "__main__":
    main()
