"""ARMA on citation datasets.

Parity: examples/arma/run_arma.py. Baseline (BASELINE.md): see arma row.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from common import citation_argparser, run_citation  # noqa: E402


def main(argv=None):
    args = citation_argparser(dropout=0.5, weight_decay=0.005,
                              max_steps=300).parse_args(argv)
    return run_citation("arma", args, conv_kwargs={'num_stacks': 2, 'arma_layers': 1})


if __name__ == "__main__":
    main()
