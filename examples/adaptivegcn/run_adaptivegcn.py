"""ADAPTIVEGCN on citation datasets.

Parity: examples/adaptivegcn/run_adaptivegcn.py. Baseline (BASELINE.md): see adaptivegcn row.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from common import citation_argparser, run_citation  # noqa: E402


def main(argv=None):
    ap = citation_argparser(dropout=-1.0, weight_decay=0.005,
                            max_steps=300)
    args = ap.parse_args(argv)
    if args.dropout < 0:
        # cora: 0.6 beats 0.5 on VAL (r3 probe, 0.804 vs 0.788 — test
        # 0.817); the other sets keep 0.5
        args.dropout = 0.6 if args.dataset == "cora" else 0.5
    return run_citation("graph", args, conv_kwargs=None)


if __name__ == "__main__":
    main()
