"""TransE/H/R/D knowledge-graph embeddings on fb15k-family datasets.

Parity: examples/TransX. Metrics: MRR / MR / hit@1,3,10 over corrupted
tails.
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="fb15k237")
    ap.add_argument("--model", default="TransE",
                    choices=["TransE", "TransH", "TransR", "TransD",
                             "DistMult"])
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--margin", type=float, default=1.0)
    ap.add_argument("--num_negs", type=int, default=16)
    ap.add_argument("--batch_size", type=int, default=256)
    ap.add_argument("--learning_rate", type=float, default=0.01)
    ap.add_argument("--max_steps", type=int, default=500)
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--model_dir", default="")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)

    import numpy as np

    from euler_tpu import models as zoo
    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import BaseEstimator

    kg = get_dataset(args.dataset)
    g = kg.engine
    print(f"dataset {args.dataset}: {kg.num_entities} entities, "
          f"{kg.num_relations} relations [{kg.source}]")
    model = getattr(zoo, args.model)(
        num_entities=kg.num_entities, num_relations=kg.num_relations,
        dim=args.dim, margin=args.margin)
    est = BaseEstimator(model,
                        dict(learning_rate=args.learning_rate),
                        model_dir=args.model_dir or None)
    rng = np.random.default_rng(0)

    def input_fn():
        while True:
            h, t, r = g.sample_edge(args.batch_size, -1)
            neg_t = rng.integers(0, kg.num_entities,
                                 (args.batch_size, args.num_negs))
            yield {"h": h.astype(np.int64), "r": r.astype(np.int32),
                   "t": t.astype(np.int64),
                   "neg_t": neg_t.astype(np.int64), "infer_ids": h}

    res = est.train(input_fn, args.max_steps)
    ev = est.evaluate(input_fn, args.eval_steps)
    print({**{f"train_{k}": v for k, v in res.items()},
           **{f"eval_{k}": v for k, v in ev.items()}})
    return ev


if __name__ == "__main__":
    main()
