"""AGNN on citation datasets.

Parity: examples/agnn/run_agnn.py. Baseline (BASELINE.md): see agnn row.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from common import citation_argparser, run_citation  # noqa: E402


def main(argv=None):
    args = citation_argparser().parse_args(argv)
    return run_citation("agnn", args, conv_kwargs=None)


if __name__ == "__main__":
    main()
