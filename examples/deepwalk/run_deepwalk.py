"""DeepWalk / node2vec random-walk embeddings.

Parity: examples/deepwalk/run_deepwalk.py. Baseline: MRR row in
BASELINE.md. Walks come from the engine's node2vec sampler; pairs from
gen_pair.
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402

import numpy as np  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--walk_len", type=int, default=5)
    ap.add_argument("--left_win", type=int, default=1)
    ap.add_argument("--right_win", type=int, default=1)
    ap.add_argument("--p", type=float, default=1.0)
    ap.add_argument("--q", type=float, default=1.0)
    ap.add_argument("--num_negs", type=int, default=5)
    ap.add_argument("--batch_size", type=int, default=64)
    ap.add_argument("--learning_rate", type=float, default=0.025)
    ap.add_argument("--max_steps", type=int, default=0,
                help="0 = auto: ~10 root walks per node")
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--device_sampler", action="store_true",
                    help="run walks + pair generation + negative "
                         "sampling ON DEVICE (DeviceNeighborTable + "
                         "DeviceNodeSampler): the host ships only root "
                         "rows per step")
    ap.add_argument("--sampler_cap", type=int, default=32)
    ap.add_argument("--steps_per_loop", type=int, default=1,
                    help=">1 scans K steps per device dispatch "
                         "(device_sampler mode)")
    ap.add_argument("--model_dir", default="")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)

    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import BaseEstimator
    from euler_tpu.models import DeepWalk, DeviceSampledSkipGram
    from euler_tpu.ops.walk_ops import gen_pair

    data = get_dataset(args.dataset)
    g = data.engine
    if not args.max_steps:
        args.max_steps = max(500,
                             int(10 * data.engine.node_count
                                 / args.batch_size))
    print(f"dataset {args.dataset}: {g.node_count} nodes [{data.source}]")

    if args.device_sampler:
        from euler_tpu.parallel import DeviceNeighborTable, DeviceNodeSampler

        tab = DeviceNeighborTable(g, cap=args.sampler_cap)
        neg = DeviceNodeSampler(g, node_type=-1)
        model = DeviceSampledSkipGram(
            num_rows=tab.pad_row, dim=args.dim, walk_len=args.walk_len,
            left_win=args.left_win, right_win=args.right_win,
            num_negs=args.num_negs, p=args.p, q=args.q)
        est = BaseEstimator(
            model,
            dict(learning_rate=args.learning_rate,
                 steps_per_loop=args.steps_per_loop),
            model_dir=args.model_dir or None)
        est.static_batch.update({**tab.tables, **neg.tables})
        seed_box = [0]

        def input_fn():
            while True:
                roots = g.node_rows(g.sample_node(args.batch_size, -1),
                                    missing=tab.pad_row)
                seed_box[0] += 1
                yield {"rows": [roots], "infer_ids": roots,
                       "sample_seed": np.uint32(seed_box[0])}
    else:
        model = DeepWalk(max_id=data.max_id, dim=args.dim)
        est = BaseEstimator(
            model,
            dict(learning_rate=args.learning_rate, max_id=data.max_id),
            model_dir=args.model_dir or None)

        def input_fn():
            while True:
                roots = g.sample_node(args.batch_size, -1)
                walks = g.random_walk(roots, args.walk_len, p=args.p,
                                      q=args.q)
                pairs = gen_pair(walks, args.left_win, args.right_win)
                flat = pairs.reshape(-1, 2)
                negs = g.sample_node(
                    flat.shape[0] * args.num_negs, -1).reshape(
                        flat.shape[0], args.num_negs)
                yield {"src": flat[:, 0], "pos": flat[:, 1], "negs": negs,
                       "infer_ids": flat[:, 0]}

    res = est.train(input_fn, args.max_steps)
    ev = est.evaluate(input_fn, args.eval_steps)
    print({**{f"train_{k}": v for k, v in res.items()},
           **{f"eval_{k}": v for k, v in ev.items()}})
    return ev


if __name__ == "__main__":
    main()
