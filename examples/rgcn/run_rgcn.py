"""R-GCN link prediction on fb15k-family.

Parity: examples/rgcn — relational conv encoder over entity neighborhoods
+ DistMult decoder on triples.
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from euler_tpu.platform import add_platform_flag, init_platform  # noqa: E402

import flax.linen as nn  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="fb15k237")
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--fanout", type=int, default=8)
    ap.add_argument("--num_rel_sample", type=int, default=8,
                    help="relations sampled per batch for aggregation")
    ap.add_argument("--num_negs", type=int, default=16)
    ap.add_argument("--batch_size", type=int, default=128)
    ap.add_argument("--learning_rate", type=float, default=0.01)
    ap.add_argument("--max_steps", type=int, default=300)
    ap.add_argument("--eval_steps", type=int, default=20)
    ap.add_argument("--model_dir", default="")
    add_platform_flag(ap)
    args = ap.parse_args(argv)
    init_platform(args.platform)

    from euler_tpu.dataset import get_dataset
    from euler_tpu.estimator import BaseEstimator
    from euler_tpu.mp_utils.base import ModelOutput
    from euler_tpu.utils import metrics as M
    from euler_tpu.utils.layers import Embedding

    kg = get_dataset(args.dataset)
    g = kg.engine
    R = args.num_rel_sample

    class RGCNLinkModel(nn.Module):
        """Entity embedding refined by per-relation mean of sampled
        neighbor embeddings (RelationConv semantics on fanout batches),
        scored by DistMult."""

        @nn.compact
        def __call__(self, batch):
            ent = Embedding(kg.num_entities, args.dim, name="ent")
            rel = Embedding(kg.num_relations, args.dim, name="rel")
            w_rel = self.param(
                "w_rel", nn.initializers.glorot_uniform(),
                (R, args.dim, args.dim))

            def encode(ids, nbr_ids):
                # nbr_ids: [R, B, K]
                h = ent(ids)
                nbr = ent(nbr_ids).mean(axis=2)          # [R, B, D]
                msg = jnp.einsum("rbd,rde->be", nbr, w_rel) / R
                return nn.relu(h + msg)

            h = encode(batch["h"], batch["h_nbrs"])
            t = ent(batch["t"])
            neg_t = ent(batch["neg_t"])                  # [B, N, D]
            r = rel(batch["r"])
            pos = (h * r * t).sum(-1, keepdims=True)
            neg = jnp.einsum("bd,bnd->bn", h * r, neg_t)
            loss = jnp.maximum(0.0, 1.0 - pos + neg).mean()
            scores = jnp.concatenate([pos, neg], axis=1)
            return ModelOutput(h, loss, "mrr", M.mrr(scores))

    est = BaseEstimator(RGCNLinkModel(),
                        dict(learning_rate=args.learning_rate),
                        model_dir=args.model_dir or None)
    rng = np.random.default_rng(0)
    rel_pool = np.arange(kg.num_relations)

    def input_fn():
        while True:
            h, t, r = g.sample_edge(args.batch_size, -1)
            rels = rng.choice(rel_pool, R, replace=kg.num_relations < R)
            nbrs = []
            for rr in rels:
                nb, _, _ = g.sample_neighbor(h, args.fanout,
                                             edge_types=[int(rr)])
                nbrs.append(nb)
            neg_t = rng.integers(0, kg.num_entities,
                                 (args.batch_size, args.num_negs))
            yield {"h": h.astype(np.int64), "t": t.astype(np.int64),
                   "r": r.astype(np.int32),
                   "h_nbrs": np.stack(nbrs).astype(np.int64),
                   "neg_t": neg_t.astype(np.int64), "infer_ids": h}

    res = est.train(input_fn, args.max_steps)
    ev = est.evaluate(input_fn, args.eval_steps)
    print({**{f"train_{k}": v for k, v in res.items()},
           **{f"eval_{k}": v for k, v in ev.items()}})
    return ev


if __name__ == "__main__":
    main()
